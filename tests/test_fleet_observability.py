"""Fleet-observability tests: collective flight recorder (+ cross-rank
diff verdicts), clock-offset handshake, straggler beacon + skew stats,
cross-rank snapshot aggregation, metrics-dump merging, fleet trace
merging, and the serving lifecycle metric exports.

The real 4-process drills (straggler flagged, desync named by
rank+sequence, flight files per rank) live in
tests/test_multiproc_train.py::test_fleet_observability_drill; this file
covers the in-process contracts those drills ride on.
"""
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fault import inject
from paddle_tpu.observability import REGISTRY, fleet, flight, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fleet_hygiene():
    paddle.set_flags({"FLAGS_enable_metrics": False,
                      "FLAGS_flight_recorder": True,
                      "FLAGS_fleet_beacon": True})
    REGISTRY.reset()
    trace.deactivate()
    trace.clear()
    flight.RECORDER.clear()
    fleet.reset_beacon()
    inject.disarm_all()
    yield
    paddle.set_flags({"FLAGS_enable_metrics": False,
                      "FLAGS_flight_recorder": True,
                      "FLAGS_fleet_beacon": True})
    REGISTRY.reset()
    trace.deactivate()
    trace.clear()
    flight.RECORDER.clear()
    fleet.reset_beacon()
    inject.disarm_all()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_seq_monotonic_per_group(self):
        r = flight.FlightRecorder()
        a = r.begin(0, "all_reduce", (4,), "float32", 16)
        b = r.begin(0, "barrier", (), "float32", 4)
        c = r.begin(7, "all_gather", (2,), "float32", 8)
        assert (a["seq"], b["seq"]) == (0, 1)
        assert c["seq"] == 0          # independent per-group sequence
        assert b["t1"] is None
        r.end(b)
        assert b["t1"] is not None

    def test_ring_bounded(self):
        r = flight.FlightRecorder(capacity=8)
        for i in range(20):
            r.end(r.begin(0, "op", (1,), "f", 1))
        tail = r.tail()
        assert len(tail) == 8
        assert [e["seq"] for e in tail] == list(range(12, 20))

    def test_collectives_stamp_the_ring(self):
        from paddle_tpu.distributed.communication import collective as C
        t = paddle.to_tensor(np.ones(4, np.float32))
        C.all_reduce(t)
        C.barrier()
        tail = flight.RECORDER.tail()
        assert [e["op"] for e in tail] == ["all_reduce", "barrier"]
        assert [e["seq"] for e in tail] == [0, 1]
        assert tail[0]["shape"] == [4] and tail[0]["bytes"] == 16
        assert tail[0]["dtype"] == "float32"
        assert all(e["t1"] is not None for e in tail)

    def test_flag_disables_recording(self):
        from paddle_tpu.distributed.communication import collective as C
        paddle.set_flags({"FLAGS_flight_recorder": False})
        C.all_reduce(paddle.to_tensor(np.ones(2, np.float32)))
        assert flight.RECORDER.tail() == []

    def test_desync_bypass_marks_entry_and_skips_device_op(self):
        from paddle_tpu.distributed.communication import collective as C
        t = paddle.to_tensor(np.asarray([3.0], np.float32))
        with inject.armed("collective.desync", op="all_reduce"):
            C.all_reduce(t)
        e = flight.RECORDER.tail(1)[0]
        assert e["op"] == "all_reduce" and e.get("bypassed") is True
        # armed op filter: a barrier passes through untouched
        with inject.armed("collective.desync", op="all_reduce"):
            C.barrier()
        assert flight.RECORDER.tail(1)[0].get("bypassed") is None

    def test_raised_collective_closes_entry(self):
        # a collective that RAISES must not leave a pending (t1=None)
        # entry — that would poison every later hang diff with a stale
        # 'blocked at seq N' verdict for this rank
        from paddle_tpu.distributed.communication import collective as C
        t = paddle.to_tensor(np.ones(4, np.float32))
        with pytest.raises(ValueError):
            C.all_reduce(t, op="not-a-reduce-op")
        e = flight.RECORDER.tail(1)[0]
        assert e["op"] == "all_reduce"
        assert e["t1"] is not None
        assert e["raised"] == "ValueError"

    def test_dump_and_load_roundtrip(self, tmp_path):
        base = str(tmp_path / "flight.json")
        flight.RECORDER.end(
            flight.RECORDER.begin(0, "all_reduce", (4,), "float32", 16))
        path = flight.dump(path=flight.record_path(base, rank=0),
                           reason="test")
        assert path.endswith(".r0") and os.path.exists(path)
        dumps = flight.load_dumps(base, world=1)
        assert dumps[0]["reason"] == "test"
        assert dumps[0]["entries"][0]["op"] == "all_reduce"

    def test_dump_without_env_is_noop(self):
        os.environ.pop(flight.RECORD_ENV, None)
        assert flight.dump() is None


def _entry(seq, op="barrier", shape=(), dtype="float32", t1=1.0,
           group=0):
    return {"seq": seq, "group": group, "op": op, "shape": list(shape),
            "dtype": dtype, "bytes": 4, "t0": 0.5, "t1": t1}


def _dump(entries, rank=0, world=4):
    return {"rank": rank, "world": world, "entries": entries}


class TestDiffRanks:
    def test_agreeing_tails_are_ok(self):
        dumps = {r: _dump([_entry(0), _entry(1)]) for r in range(4)}
        assert flight.diff_ranks(dumps)["status"] == "ok"

    def test_stall_names_the_rank_that_never_issued(self):
        # ranks 0,1,3 blocked inside seq 1; rank 2 never issued it
        dumps = {r: _dump([_entry(0), _entry(1, t1=None)])
                 for r in (0, 1, 3)}
        dumps[2] = _dump([_entry(0)])
        v = flight.diff_ranks(dumps)
        assert v["status"] == "stall" and v["rank"] == 2 \
            and v["seq"] == 1
        assert "rank 2" in v["detail"]

    def test_desync_names_the_rank_that_raced_ahead(self):
        # rank 2 completed seq 1 (bypass) while peers are blocked in it
        dumps = {r: _dump([_entry(0), _entry(1, t1=None)])
                 for r in (0, 1, 3)}
        dumps[2] = _dump([_entry(0), _entry(1)])
        v = flight.diff_ranks(dumps)
        assert v["status"] == "desync" and v["rank"] == 2 \
            and v["seq"] == 1

    def test_desync_rank_blocked_further_ahead(self):
        # rank 2 bypassed seq 1 and is now blocked inside seq 2: the
        # verdict must still name rank 2, not call its peers absent
        dumps = {r: _dump([_entry(0), _entry(1, t1=None)])
                 for r in (0, 1, 3)}
        dumps[2] = _dump([_entry(0), _entry(1),
                          _entry(2, op="all_reduce", t1=None)])
        v = flight.diff_ranks(dumps)
        assert v["status"] == "desync" and v["rank"] == 2 \
            and v["seq"] == 1

    def test_content_mismatch_named_by_rank_and_seq(self):
        dumps = {r: _dump([_entry(0, op="all_reduce", shape=(8,))])
                 for r in (0, 1, 3)}
        dumps[2] = _dump([_entry(0, op="all_gather", shape=(4,))])
        v = flight.diff_ranks(dumps)
        assert v["status"] == "desync" and v["rank"] == 2 \
            and v["seq"] == 0
        assert "all_gather" in v["detail"]

    def test_all_blocked_is_transport_stall(self):
        dumps = {r: _dump([_entry(0), _entry(1, t1=None)])
                 for r in range(4)}
        v = flight.diff_ranks(dumps)
        assert v["status"] == "stall" and v["rank"] is None

    def test_empty(self):
        assert flight.diff_ranks({})["status"] == "ok"


# ---------------------------------------------------------------------------
# clock sync
# ---------------------------------------------------------------------------
class TestClockSync:
    def test_single_process_offsets(self):
        st = fleet.clock_sync(rounds=3)
        assert st["world"] == 1 and st["offsets"] == {0: 0.0}
        assert st["skew_bound_s"] == 0.0
        assert fleet.clock_state() is st

    def test_offset_gauge_exported(self):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        fleet.clock_sync(rounds=2)
        g = REGISTRY.get("paddle_tpu_fleet_clock_offset_seconds")
        assert g is not None and g.value(rank="0") == 0.0


# ---------------------------------------------------------------------------
# straggler beacon
# ---------------------------------------------------------------------------
class TestSkewStats:
    def test_names_slowest_rank_and_bucket(self):
        m = [[0, 4, 0.010, 0.011, 0.6, 0.2, 0.1, 0.1],
             [1, 4, 0.010, 0.012, 0.6, 0.2, 0.1, 0.1],
             [2, 4, 0.031, 0.033, 0.1, 0.7, 0.1, 0.1],
             [3, 4, 0.010, 0.011, 0.6, 0.2, 0.1, 0.1]]
        s = fleet.skew_stats(m, threshold=0.2)
        assert s["slowest_rank"] == 2 and s["is_straggler"]
        assert s["dominant_bucket"] == "collective"
        assert s["median_step_s"] == pytest.approx(0.010)
        assert s["slowest_score"] == pytest.approx(2.1)
        assert s["scores"][0] == pytest.approx(0.0)

    def test_balanced_fleet_is_not_flagged(self):
        m = [[r, 4, 0.010 + r * 1e-4, 0.011, 0.5, 0.2, 0.2, 0.1]
             for r in range(4)]
        s = fleet.skew_stats(m, threshold=0.2)
        assert not s["is_straggler"]
        assert s["skew"] < 0.05

    def test_accepts_ndarray(self):
        m = np.asarray([[0, 2, 0.01, 0.01, 1, 0, 0, 0]])
        assert fleet.skew_stats(m)["slowest_rank"] == 0


class TestBeacon:
    def test_windows_flush_and_report(self):
        b = fleet.FleetBeacon(window=3)
        for _ in range(7):
            b.step_begin()
            b.step_end()
        assert b.windows == 2
        r = b.last_report
        assert r["slowest_rank"] == 0 and r["window"] == 2
        assert len(r["per_rank"]) == 1
        assert r["per_rank"][0][1] == 3.0      # steps per window

    def test_probe_attribution_covers_collectives(self):
        from paddle_tpu.distributed.communication import collective as C
        b = fleet.FleetBeacon(window=2)
        t = paddle.to_tensor(np.ones(4, np.float32))
        for _ in range(2):
            b.step_begin()
            C.all_reduce(t)
            b.step_end()
        row = b.last_report["per_rank"][0]
        fracs = row[4:8]
        assert sum(fracs) == pytest.approx(1.0, abs=1e-6)
        assert fracs[1] > 0.0                  # collective share seen
        assert not trace.active()              # probe trace released

    def test_tick_style(self):
        b = fleet.FleetBeacon(window=2)
        for _ in range(5):
            b.tick()
            time.sleep(0.001)
        assert b.windows == 2
        assert b.last_report["median_step_s"] > 0

    def test_disabled_flag_short_circuits(self):
        paddle.set_flags({"FLAGS_fleet_beacon": False})
        b = fleet.FleetBeacon(window=2)
        for _ in range(6):
            b.step_begin()
            b.step_end()
        assert b.windows == 0 and b.last_report is None

    def test_slow_step_drill_inflates_step_time(self):
        b = fleet.FleetBeacon(window=2)
        with inject.armed("fleet.slow_step", times=100, seconds=0.02):
            for _ in range(2):
                b.step_begin()
                b.step_end()
        assert b.last_report["median_step_s"] > 0.015

    def test_metrics_exported_per_window(self):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        b = fleet.FleetBeacon(window=2)
        for _ in range(2):
            b.step_begin()
            b.step_end()
        assert REGISTRY.get(
            "paddle_tpu_fleet_beacon_windows_total").total() == 1
        assert REGISTRY.get(
            "paddle_tpu_fleet_straggler_score").value(rank="0") == 0.0
        assert REGISTRY.get(
            "paddle_tpu_fleet_slowest_rank").value() == 0.0

    def test_respects_external_trace_session(self):
        # a profiler owns the buffer: the beacon must read without
        # draining and must not deactivate the session
        trace.clear()
        trace.activate()
        b = fleet.FleetBeacon(window=2)
        for _ in range(2):
            b.step_begin()
            b.step_end()
        assert trace.active()
        trace.deactivate()

    def test_engine_fit_feeds_the_beacon(self):
        from paddle_tpu import nn, optimizer
        from paddle_tpu.distributed.auto_parallel.engine import Engine

        b = fleet.reset_beacon(window=2)
        model = nn.Linear(4, 4)
        eng = Engine(model, loss=lambda o, y: paddle.ops.mean((o - y) ** 2),
                     optimizer=optimizer.AdamW(
                         learning_rate=1e-2,
                         parameters=model.parameters()))
        xs = np.random.randn(32, 4).astype(np.float32)
        data = [(xs[i], xs[i]) for i in range(32)]
        eng.fit(data, epochs=1, batch_size=8)
        assert b.windows >= 2
        assert b.last_report["slowest_rank"] == 0


# ---------------------------------------------------------------------------
# cross-rank snapshot + replica registry
# ---------------------------------------------------------------------------
class TestSnapshot:
    def test_single_process_snapshot_shape(self):
        snap = fleet.snapshot(trace_tail=10)
        assert snap["world"] == 1 and snap["rank"] == 0
        local = snap["ranks"][0]
        for key in ("metrics", "spans", "flight", "beacon", "replicas",
                    "clock", "pid", "host"):
            assert key in local
        json.dumps(snap, default=str)          # JSON-able end to end

    def test_registered_replica_health_rides_snapshot(self):
        class FakeReplica:
            def health(self):
                return {"state": "READY", "ready": True}

        rep = FakeReplica()
        fleet.register_replica(rep)
        try:
            snap = fleet.snapshot(trace_tail=0)
            assert {"state": "READY", "ready": True} \
                in snap["ranks"][0]["replicas"]
        finally:
            fleet._replicas.discard(rep)

    def test_dump_writes_rank0_file(self, tmp_path):
        path = fleet.dump(str(tmp_path / "fleet.json"))
        with open(path) as f:
            snap = json.load(f)
        assert snap["format"] == "paddle_tpu.fleet_snapshot/1"

    def test_paged_engine_registers_itself(self):
        pytest.importorskip("paddle_tpu.inference.serving")
        from paddle_tpu.inference import serving as sv
        if not hasattr(sv, "PagedEngine"):
            pytest.skip("no PagedEngine")
        # registration is exercised end-to-end in test_serving*; here
        # just assert the hook exists on the registry side
        assert callable(fleet.register_replica)


# ---------------------------------------------------------------------------
# metrics-dump merge (tools/metrics_dump.py --merge)
# ---------------------------------------------------------------------------
def _snap(value, labeled=False):
    if labeled:
        return {"m_total": {"kind": "counter", "help": "h",
                            "labelnames": ["op"],
                            "series": [{"labels": ["x"],
                                        "value": value}]}}
    return {"m_total": {"kind": "counter", "help": "h",
                        "labelnames": [],
                        "series": [{"labels": [], "value": value}]}}


class TestMergeSnapshots:
    def test_rank_label_prepended(self):
        merged = fleet.merge_snapshots({"0": _snap(1, labeled=True),
                                        "1": _snap(2, labeled=True)})
        m = merged["m_total"]
        assert m["labelnames"] == ["rank", "op"]
        assert {tuple(s["labels"]) for s in m["series"]} == \
            {("0", "x"), ("1", "x")}

    def test_rank_collision_uses_proc_label(self):
        # a metric that already carries a "rank" label (the fleet
        # gauges) must not render a duplicate label name after merging
        snap = {"s": {"kind": "gauge", "help": "",
                      "labelnames": ["rank"],
                      "series": [{"labels": ["1"], "value": 0.5}]}}
        merged = fleet.merge_snapshots({"0": snap})
        assert merged["s"]["labelnames"] == ["proc", "rank"]
        from paddle_tpu.observability.metrics import render_prometheus
        assert 's{proc="0",rank="1"} 0.5' in render_prometheus(merged)

    def test_merge_files_and_suffix_labels(self, tmp_path):
        base = str(tmp_path / "metrics.json")
        json.dump(_snap(1), open(base, "w"))
        json.dump(_snap(2), open(base + ".rank1", "w"))
        json.dump(_snap(3), open(base + ".pid777", "w"))
        merged = fleet.merge_snapshot_files(base)
        labels = sorted(s["labels"][0]
                        for s in merged["m_total"]["series"])
        assert labels == ["0", "1", "pid777"]
        from paddle_tpu.observability.metrics import render_prometheus
        text = render_prometheus(merged)
        assert 'm_total{rank="1"} 2' in text

    def test_unreadable_sibling_skipped(self, tmp_path, capsys):
        base = str(tmp_path / "metrics.json")
        json.dump(_snap(1), open(base, "w"))
        open(base + ".rank1", "w").write("{truncated")
        merged = fleet.merge_snapshot_files(base)
        assert len(merged["m_total"]["series"]) == 1

    def test_no_files_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            fleet.merge_snapshot_files(str(tmp_path / "absent.json"))

    def test_cli_merge_mode(self, tmp_path):
        from paddle_tpu.observability.__main__ import main
        base = str(tmp_path / "metrics.json")
        json.dump(_snap(1), open(base, "w"))
        json.dump(_snap(2), open(base + ".rank1", "w"))
        out = str(tmp_path / "merged.prom")
        assert main(["--merge", base, "--output", out]) == 0
        text = open(out).read()
        assert 'm_total{rank="0"} 1' in text
        assert 'm_total{rank="1"} 2' in text
        assert main(["--merge", str(tmp_path / "nope.json")]) == 1


# ---------------------------------------------------------------------------
# fleet trace merging (tools/fleet_trace.py)
# ---------------------------------------------------------------------------
def _rank_trace(tmp_path, rank, offset, t0_s):
    evs = [{"name": "clock_sync", "ph": "M", "pid": 0,
            "args": {"rank": rank, "offset_vs_rank0_s": offset}},
           {"name": "process_name", "ph": "M", "pid": 0,
            "args": {"name": "paddle_tpu host"}},
           {"name": "train_step", "cat": "step", "ph": "X", "pid": 0,
            "tid": 0, "ts": int(t0_s * 1e6), "dur": 2000}]
    p = str(tmp_path / f"worker_r{rank}_host_ops.json")
    json.dump({"traceEvents": evs}, open(p, "w"))
    return p


class TestFleetTrace:
    def test_merge_aligns_and_lanes(self, tmp_path):
        sys.path.insert(0, REPO)
        from tools.fleet_trace import main, merge_traces
        # rank 1's clock reads 2.5s ahead: same true instant
        p0 = _rank_trace(tmp_path, 0, 0.0, 50.0)
        p1 = _rank_trace(tmp_path, 1, 2.5, 52.5)
        out = str(tmp_path / "fleet.json")
        assert main([p0, p1, "--out", out]) == 0
        merged = json.load(open(out))
        assert "traceEvents" in merged
        steps = [e for e in merged["traceEvents"]
                 if e.get("name") == "train_step"]
        assert sorted(e["pid"] for e in steps) == [0, 1]
        assert steps[0]["ts"] == steps[1]["ts"] == 50_000_000
        names = {e["pid"]: e["args"]["name"]
                 for e in merged["traceEvents"]
                 if e.get("name") == "process_name"}
        assert names == {0: "rank 0", 1: "rank 1"}
        # a valid chrome trace: every non-meta event carries ph/ts
        for e in merged["traceEvents"]:
            assert "ph" in e
            if e["ph"] != "M":
                assert isinstance(e["ts"], int)
        assert merge_traces([p0, p1])["metadata"][
            "unaligned_ranks"] == []

    def test_offsets_file_overrides(self, tmp_path):
        from tools.fleet_trace import main
        p0 = _rank_trace(tmp_path, 0, 0.0, 50.0)
        p1 = _rank_trace(tmp_path, 1, 0.0, 53.0)
        offs = str(tmp_path / "offsets.json")
        json.dump({"0": 0.0, "1": 3.0}, open(offs, "w"))
        out = str(tmp_path / "fleet.json")
        assert main([p0, p1, "--out", out, "--offsets", offs]) == 0
        merged = json.load(open(out))
        steps = [e for e in merged["traceEvents"]
                 if e.get("name") == "train_step"]
        assert steps[0]["ts"] == steps[1]["ts"]

    def test_missing_file_fails_cleanly(self, tmp_path):
        from tools.fleet_trace import main
        assert main([str(tmp_path / "nope.json"),
                     "--out", str(tmp_path / "o.json")]) == 1

    def test_profiler_export_embeds_clock_metadata(self, tmp_path):
        # drive the export handler directly (a full profiler session
        # would spin up the jax device tracer for ~8s of tier-1 budget;
        # the contract under test is the metadata embedding)
        from paddle_tpu import profiler

        class _FakeProf:
            _events = [("rng", 1.0, 1.001)]
            _spans = [("op", "dispatch", 1.0, 1.002, 0, None)]
            _spans_dropped = 0
            trace_path = None

        fleet.clock_sync(rounds=2)
        prof = _FakeProf()
        profiler.export_chrome_tracing(str(tmp_path))(prof)
        blob = json.load(open(prof.trace_path))
        cs = [e for e in blob["traceEvents"]
              if e.get("name") == "clock_sync"]
        assert cs and cs[0]["args"]["rank"] == 0
        assert cs[0]["args"]["offset_vs_rank0_s"] == 0.0
        assert os.path.basename(prof.trace_path) == \
            "worker_host_ops.json"


# ---------------------------------------------------------------------------
# watchdog flight integration
# ---------------------------------------------------------------------------
class TestWatchdogFlight:
    def test_dump_diagnostics_persists_flight_record(self, tmp_path,
                                                     monkeypatch):
        import io

        from paddle_tpu.distributed.watchdog import Watchdog

        base = str(tmp_path / "flight.json")
        monkeypatch.setenv(flight.RECORD_ENV, base)
        from paddle_tpu.distributed.communication import collective as C
        C.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
        wd = Watchdog(timeout=60.0)
        buf = io.StringIO()
        wd.dump_diagnostics(file=buf)
        out = buf.getvalue()
        assert "collective flight tail" in out
        assert "seq=0" in out and "all_reduce" in out
        assert os.path.exists(flight.record_path(base))
        dumps = flight.load_dumps(base, world=1)
        assert dumps[0]["entries"][0]["op"] == "all_reduce"

    def test_dump_diagnostics_without_env(self):
        import io

        from paddle_tpu.distributed.watchdog import Watchdog

        os.environ.pop(flight.RECORD_ENV, None)
        buf = io.StringIO()
        Watchdog(timeout=60.0).dump_diagnostics(file=buf)
        assert "flight tail" in buf.getvalue()


# ---------------------------------------------------------------------------
# serving lifecycle metric exports
# ---------------------------------------------------------------------------
class TestReplicaLifecycleMetrics:
    def test_transitions_and_probes_exported(self):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        from paddle_tpu.inference.resilience import (ReplicaLifecycle,
                                                     ReplicaState)

        lc = ReplicaLifecycle(name="r0")
        lc.to(ReplicaState.WARMING, "warmup")
        lc.to(ReplicaState.READY, "serving")
        tr = REGISTRY.get("paddle_tpu_serving_replica_transitions_total")
        ready = REGISTRY.get("paddle_tpu_serving_replica_ready")
        live = REGISTRY.get("paddle_tpu_serving_replica_live")
        assert tr.value(from_state="STARTING", to_state="WARMING") == 1
        assert tr.value(from_state="WARMING", to_state="READY") == 1
        assert ready.value(replica="r0") == 1.0
        assert live.value(replica="r0") == 1.0
        lc.degrade("stall")
        assert tr.value(from_state="READY", to_state="DEGRADED") == 1
        assert ready.value(replica="r0") == 0.0
        lc.to(ReplicaState.DRAINING)
        lc.to(ReplicaState.STOPPED)
        assert live.value(replica="r0") == 0.0

    def test_two_replicas_do_not_clobber_probes(self):
        """A second engine's lifecycle (STARTING) must not pull a READY
        replica's probe gauge out of rotation — the gauges are labeled
        per replica."""
        paddle.set_flags({"FLAGS_enable_metrics": True})
        from paddle_tpu.inference.resilience import (ReplicaLifecycle,
                                                     ReplicaState)

        a = ReplicaLifecycle(name="a")
        a.to(ReplicaState.READY, "serving")
        ready = REGISTRY.get("paddle_tpu_serving_replica_ready")
        assert ready.value(replica="a") == 1.0
        b = ReplicaLifecycle(name="b")       # STARTING
        assert ready.value(replica="a") == 1.0
        assert ready.value(replica="b") == 0.0
        b.to(ReplicaState.STOPPED)
        assert REGISTRY.get(
            "paddle_tpu_serving_replica_live").value(replica="a") == 1.0


# ---------------------------------------------------------------------------
# stable metric names (README "Fleet observability" table)
# ---------------------------------------------------------------------------
class TestStableNames:
    def test_fleet_instruments_registered(self):
        for name in (
                "paddle_tpu_fleet_straggler_score",
                "paddle_tpu_fleet_slowest_rank",
                "paddle_tpu_fleet_step_skew",
                "paddle_tpu_fleet_beacon_windows_total",
                "paddle_tpu_fleet_straggler_warnings_total",
                "paddle_tpu_fleet_beacon_gather_seconds",
                "paddle_tpu_fleet_clock_offset_seconds",
                "paddle_tpu_serving_replica_ready",
                "paddle_tpu_serving_replica_live",
                "paddle_tpu_serving_replica_transitions_total"):
            assert REGISTRY.get(name) is not None, name

    def test_fault_points_registered(self):
        assert "fleet.slow_step" in inject.POINTS
        assert "collective.desync" in inject.POINTS
