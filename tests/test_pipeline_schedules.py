"""Interleaved-VPP / ZBH1 / heterogeneous pipeline schedule tests.

Reference contracts: pipeline_parallel.py:1010 (interleave), pp_layers.py:207
(PipelineLayerChunk), pipeline_scheduler_pass/pipeline_zero_bubble.py (ZBH1).
Parity model: the pipelined program must match the sequential model's
outputs and gradients; the VPP schedule must execute strictly fewer
block-unit ticks (smaller bubble) than stage-major 1F1B at fixed m.
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.meta_parallel.pipeline_schedules import (
    schedule_block_ticks, spmd_pipeline_hetero, spmd_pipeline_interleaved,
    spmd_pipeline_zb)

import jax
import jax.numpy as jnp


@pytest.fixture
def pp_mesh():
    old = mesh_mod._global_mesh
    mesh = mesh_mod.build_mesh({"dp": 2, "pp": 4})
    mesh_mod.set_mesh(mesh)
    yield mesh
    mesh_mod._global_mesh = old


def _block_fn(per_block, x):
    (w,) = per_block
    return jnp.tanh(x @ w)


def _seq(Ws, xs):
    h = xs
    for i in range(Ws.shape[0]):
        h = jnp.tanh(h @ Ws[i])
    return h


class TestVPP:
    def test_bubble_ticks_shrink(self):
        # VPP executes strictly fewer block-unit ticks than 1F1B for K>1:
        # (S-1) idle block-ticks instead of (S-1)*K.
        for (m, S, K) in [(8, 4, 2), (8, 4, 4), (16, 8, 2)]:
            vpp = schedule_block_ticks("VPP", m, S, K)
            f1b = schedule_block_ticks("1F1B", m, S, K)
            assert vpp == m * K + S - 1
            assert f1b == (m + S - 1) * K
            assert vpp < f1b

    def test_matches_sequential(self, pp_mesh):
        S, K, m, B, D = 4, 2, 8, 4, 16
        rng = np.random.RandomState(0)
        Ws = jnp.asarray(rng.randn(S * K, D, D).astype(np.float32) * 0.1)
        xs = jnp.asarray(rng.randn(m, B, D).astype(np.float32))

        got = jax.jit(lambda Ws, xs: spmd_pipeline_interleaved(
            _block_fn, [Ws], xs, mesh=pp_mesh, num_stages=S))(Ws, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(_seq(Ws, xs)),
                                   atol=1e-6)

    def test_grads_match_sequential(self, pp_mesh):
        S, K, m, B, D = 4, 2, 8, 2, 8
        rng = np.random.RandomState(1)
        Ws = jnp.asarray(rng.randn(S * K, D, D).astype(np.float32) * 0.1)
        xs = jnp.asarray(rng.randn(m, B, D).astype(np.float32))

        g1 = jax.jit(jax.grad(lambda W: jnp.sum(spmd_pipeline_interleaved(
            _block_fn, [W], xs, mesh=pp_mesh, num_stages=S) ** 2)))(Ws)
        g2 = jax.grad(lambda W: jnp.sum(_seq(W, xs) ** 2))(Ws)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-5)

    def test_m_not_divisible_by_s(self, pp_mesh):
        # partial final injection group still yields exact outputs
        S, K, m, B, D = 4, 2, 6, 2, 8
        rng = np.random.RandomState(2)
        Ws = jnp.asarray(rng.randn(S * K, D, D).astype(np.float32) * 0.1)
        xs = jnp.asarray(rng.randn(m, B, D).astype(np.float32))
        got = jax.jit(lambda Ws, xs: spmd_pipeline_interleaved(
            _block_fn, [Ws], xs, mesh=pp_mesh, num_stages=S))(Ws, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(_seq(Ws, xs)),
                                   atol=1e-6)

    def test_measured_bubble_fraction_shrinks(self, pp_mesh):
        # The compiled VPP program counts its own active block ticks; the
        # measured bubble 1 - active/slots must be K× smaller than the
        # stage-major schedule's (S-1)/(m+S-1).
        S, K, m, B, D = 4, 4, 8, 4, 16
        rng = np.random.RandomState(3)
        Ws = jnp.asarray(rng.randn(S * K, D, D).astype(np.float32) * 0.05)
        xs = jnp.asarray(rng.randn(m, B, D).astype(np.float32))

        out, stats = jax.jit(lambda Ws, xs: spmd_pipeline_interleaved(
            _block_fn, [Ws], xs, mesh=pp_mesh, num_stages=S, remat=False,
            return_stats=True))(Ws, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(_seq(Ws, xs)),
                                   atol=1e-5)
        active = int(stats["active_block_ticks"])
        slots = int(stats["total_block_slots"])
        assert active == m * S * K  # every useful block ran exactly once
        bubble_vpp = 1 - active / slots
        bubble_1f1b = (S - 1) / (m + S - 1)
        assert bubble_vpp == pytest.approx((S - 1) / (m * K + S - 1))
        assert bubble_vpp < bubble_1f1b / (K - 1)

    @pytest.mark.skipif(
        jax.default_backend() != "tpu" or jax.device_count() < 4,
        reason="wall-clock bubble comparison is only meaningful on real "
               "multi-device hardware: on a CPU-emulated mesh the devices "
               "timeshare host cores, so per-tick overheads (finer "
               "ppermutes) dominate the tick-count saving the schedule "
               "exists for. The schedule advantage itself is asserted "
               "deterministically by test_measured_bubble_fraction_shrinks "
               "(the compiled program counts its own idle ticks).")
    def test_vpp_faster_than_stage_major(self, pp_mesh):
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel \
            import spmd_pipeline
        S, K, m, B, D = 4, 4, 8, 64, 512
        rng = np.random.RandomState(3)
        Ws = jnp.asarray(rng.randn(S * K, D, D).astype(np.float32) * 0.05)
        xs = jnp.asarray(rng.randn(m, B, D).astype(np.float32))

        f_vpp = jax.jit(lambda Ws, xs: spmd_pipeline_interleaved(
            _block_fn, [Ws], xs, mesh=pp_mesh, num_stages=S, remat=False))
        f_1f1b = jax.jit(lambda Ws, xs: spmd_pipeline(
            _block_fn, [Ws], xs, mesh=pp_mesh, num_stages=S,
            schedule="FThenB"))

        def best_of(f, n=5):
            jax.block_until_ready(f(Ws, xs))
            ts = []
            for _ in range(n):
                t0 = time.perf_counter()
                jax.block_until_ready(f(Ws, xs))
                ts.append(time.perf_counter() - t0)
            return min(ts)

        t_vpp, t_1f1b = best_of(f_vpp), best_of(f_1f1b)
        # tick ratio is (mK+S-1)/((m+S-1)K) = 35/44 ≈ 0.80; allow noise
        assert t_vpp < t_1f1b * 1.05, (t_vpp, t_1f1b)


class TestZBH1:
    def test_matches_sequential(self, pp_mesh):
        S, K, m, B, D = 4, 2, 8, 4, 16
        rng = np.random.RandomState(4)
        Ws = jnp.asarray(rng.randn(S * K, D, D).astype(np.float32) * 0.1)
        xs = jnp.asarray(rng.randn(m, B, D).astype(np.float32))
        got = jax.jit(lambda Ws, xs: spmd_pipeline_zb(
            _block_fn, [Ws], xs, mesh=pp_mesh, num_stages=S))(Ws, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(_seq(Ws, xs)),
                                   atol=1e-6)

    def test_grads_match_sequential(self, pp_mesh):
        # the dX-ring + dW-filler backward must equal autodiff exactly
        S, K, m, B, D = 4, 2, 8, 2, 8
        rng = np.random.RandomState(5)
        Ws = jnp.asarray(rng.randn(S * K, D, D).astype(np.float32) * 0.1)
        bs = jnp.asarray(rng.randn(S * K, D).astype(np.float32) * 0.1)
        xs = jnp.asarray(rng.randn(m, B, D).astype(np.float32))

        def bf(pb, x):
            return jnp.tanh(x @ pb[0] + pb[1])

        def seq(W, b, xs):
            h = xs
            for i in range(S * K):
                h = jnp.tanh(h @ W[i] + b[i])
            return h

        def loss_zb(W, b, xs):
            return jnp.sum(spmd_pipeline_zb(
                bf, [W, b], xs, mesh=pp_mesh, num_stages=S) ** 2)

        gW, gb, gx = jax.jit(jax.grad(loss_zb, argnums=(0, 1, 2)))(
            Ws, bs, xs)
        gW2, gb2, gx2 = jax.grad(
            lambda W, b, xs: jnp.sum(seq(W, b, xs) ** 2),
            argnums=(0, 1, 2))(Ws, bs, xs)
        # guard against vacuous comparison on vanishing grads: a missing
        # 1/pp scaling must not hide inside atol
        assert float(np.abs(np.asarray(gW2)).max()) > 1e-3
        np.testing.assert_allclose(np.asarray(gW), np.asarray(gW2),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(gb), np.asarray(gb2),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx2),
                                   atol=1e-5)


class TestHetero:
    def test_shape_changing_stages_match_sequential(self, pp_mesh):
        # 4 stages with different params AND different activation shapes:
        # 16 -> 16 -> 12 -> 8 -> 4
        dims = [16, 16, 12, 8, 4]
        rng = np.random.RandomState(6)
        Ws = [jnp.asarray(rng.randn(dims[i], dims[i + 1])
                          .astype(np.float32) * 0.2) for i in range(4)]
        bs = [jnp.asarray(rng.randn(dims[i + 1]).astype(np.float32) * 0.1)
              for i in range(4)]
        m, B = 8, 4
        xs = jnp.asarray(rng.randn(m, B, dims[0]).astype(np.float32))

        def mk_stage(i):
            def f(params, x):
                w, b = params
                return jnp.tanh(x @ w + b)
            return f

        stage_fns = [mk_stage(i) for i in range(4)]
        stage_params = [[Ws[i], bs[i]] for i in range(4)]
        in_avals = [jax.ShapeDtypeStruct((B, dims[i]), jnp.float32)
                    for i in range(4)]
        out_aval = jax.ShapeDtypeStruct((B, dims[4]), jnp.float32)

        got = jax.jit(lambda xs: spmd_pipeline_hetero(
            stage_fns, stage_params, xs, mesh=pp_mesh, num_stages=4,
            out_aval=out_aval, stage_in_avals=in_avals))(xs)

        h = xs
        for w, b in zip(Ws, bs):
            h = jnp.tanh(h @ w + b)
        np.testing.assert_allclose(np.asarray(got), np.asarray(h),
                                   atol=1e-6)

    def test_hetero_grads(self, pp_mesh):
        dims = [8, 6, 10, 4, 4]
        rng = np.random.RandomState(7)
        m, B = 4, 2
        xs = jnp.asarray(rng.randn(m, B, dims[0]).astype(np.float32))
        W0 = [rng.randn(dims[i], dims[i + 1]).astype(np.float32) * 0.2
              for i in range(4)]

        def f(params, x):
            (w,) = params
            return jnp.tanh(x @ w)

        in_avals = [jax.ShapeDtypeStruct((B, dims[i]), jnp.float32)
                    for i in range(4)]
        out_aval = jax.ShapeDtypeStruct((B, dims[4]), jnp.float32)

        def loss_pipe(Ws):
            out = spmd_pipeline_hetero(
                [f] * 4, [[w] for w in Ws], xs, mesh=pp_mesh,
                num_stages=4, out_aval=out_aval, stage_in_avals=in_avals)
            return jnp.sum(out ** 2)

        def loss_seq(Ws):
            h = xs.reshape(-1, dims[0])
            for w in Ws:
                h = jnp.tanh(h @ w)
            return jnp.sum(h ** 2)

        g1 = jax.jit(jax.grad(loss_pipe))([jnp.asarray(w) for w in W0])
        g2 = jax.grad(loss_seq)([jnp.asarray(w) for w in W0])
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)


class TestHeteroBf16Skewed:
    """Round-4: per-stage dtype preservation — an all-bf16 skewed model
    (fat embedding-like stage + thin blocks) rides bf16 buffers (half the
    per-rank param HBM and ring bandwidth of the old forced-fp32 packing)
    and still matches the sequential reference."""

    def test_buffer_dtype_selection(self):
        from paddle_tpu.distributed.fleet.meta_parallel. \
            pipeline_schedules import _buffer_dtype
        assert _buffer_dtype([jnp.bfloat16, jnp.bfloat16]) == jnp.bfloat16
        assert _buffer_dtype([jnp.float16]) == jnp.float16
        assert _buffer_dtype([jnp.bfloat16, jnp.float32]) == jnp.float32
        assert _buffer_dtype([jnp.bfloat16, jnp.int32]) == jnp.float32
        assert _buffer_dtype([jnp.float32]) == jnp.float32

    def test_skewed_bf16_stages_roundtrip(self, pp_mesh):
        # stage 0 is a fat embedding-style stage (64x16), stages 1-3 are
        # thin 16x16 blocks — Pmax tracks the fat stage; all bf16
        rng = np.random.RandomState(8)
        m, B, V, H = 4, 2, 64, 16
        fat = jnp.asarray(rng.randn(V, H).astype(np.float32) * 0.2
                          ).astype(jnp.bfloat16)
        thin = [jnp.asarray(rng.randn(H, H).astype(np.float32) * 0.2
                            ).astype(jnp.bfloat16) for _ in range(3)]
        xs = jnp.asarray(
            rng.randint(0, V, (m, B)).astype(np.int32))

        def embed_stage(params, x):
            (w,) = params
            return jnp.take(w, x.astype(jnp.int32), axis=0)

        def block_stage(params, x):
            (w,) = params
            return jnp.tanh(x @ w)

        stage_fns = [embed_stage] + [block_stage] * 3
        stage_params = [[fat]] + [[w] for w in thin]
        in_avals = [jax.ShapeDtypeStruct((B,), jnp.int32)] + \
            [jax.ShapeDtypeStruct((B, H), jnp.bfloat16)] * 3
        out_aval = jax.ShapeDtypeStruct((B, H), jnp.bfloat16)

        got = jax.jit(lambda xs: spmd_pipeline_hetero(
            stage_fns, stage_params, xs, mesh=pp_mesh, num_stages=4,
            out_aval=out_aval, stage_in_avals=in_avals))(xs)
        assert got.dtype == jnp.bfloat16

        h = jnp.take(fat, xs.reshape(-1), axis=0)
        for w in thin:
            h = jnp.tanh(h @ w)
        np.testing.assert_allclose(
            np.asarray(got, np.float32).reshape(-1, H),
            np.asarray(h, np.float32), atol=1e-2)

    def test_param_buffer_is_bf16_for_bf16_model(self, pp_mesh):
        # the packed per-rank param buffer must cost 2 B/element, not 4
        from paddle_tpu.distributed.fleet.meta_parallel import \
            pipeline_schedules as PS
        dts = [jnp.bfloat16] * 4
        assert PS._buffer_dtype(dts) == jnp.bfloat16
        flat = PS._flatten_pack(
            [jnp.ones((8, 8), jnp.bfloat16)], 100, jnp.bfloat16)
        assert flat.dtype == jnp.bfloat16 and flat.nbytes == 200
