"""Auto-parallel placement planner — search, scoring, emission.

Contracts under test (ISSUE 11 / ROADMAP "Auto-parallel planner"):

* the spec algebra surfaces Partial (reduce-pending) placements with
  the documented meet rule, and the general einsum rule resolves
  arbitrary equations (MoE dispatch/combine included) from the
  recorded ``equation`` attr;
* candidate enumeration is deterministic (same params + mesh -> same
  population, same order);
* the cost model ranks sanely: DP beats TP on a small model; when the
  replicated parameters exceed one chip's HBM the DP candidate is
  REJECTED (hard, with the reason naming the capacity) and a
  sharded-parameter candidate wins;
* ``plan()`` on the GPT emits a placement with ZERO replicate-fallback
  ops, and the emitted (param_specs, in_specs) round-trip through
  ``Engine(mesh=, placement="auto")`` / ``to_static(param_specs=
  "auto")`` with loss parity vs the unsharded path on a virtual
  (data, tp) mesh;
* every op the GPT/llama/MoE workloads emit is scored — named rule,
  category fallback, or an explicit PENALTY_OPS entry
  (tools/planner_audit.py, wired here like fusion_audit).
"""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import mesh as mesh_mod, planner, spmd
from paddle_tpu.distributed.planner import cost as pcost
from paddle_tpu.distributed.spmd import rules as R
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def _mesh(**shape):
    return mesh_mod.build_mesh(dict(shape))


# ==========================================================================
# spec algebra: Partial + meet rule
# ==========================================================================
class TestPartialAlgebra:
    def test_meet_partial_documented_semantics(self):
        # equal keeps
        assert R.meet_partial(("tp",), ("tp",)) == ("tp",)
        # subset -> intersection survives
        assert R.meet_partial(("ep", "tp"), ("tp",)) == ("tp",)
        # disagreement -> only commonly-pending axes survive (an axis
        # one side already reduced cannot be un-reduced)
        assert R.meet_partial(("tp",), ("ep",)) == ()
        assert R.meet_partial(("tp",), ()) == ()
        # normalization: Partial / str / unsorted
        assert R.meet_partial(R.Partial(("b", "a")), ("a", "b")) \
            == ("a", "b")
        assert R.normalize_partial("tp") == ("tp",)
        assert R.normalize_partial(None) == ()

    def test_matmul_contraction_surfaces_partial(self):
        # row-parallel: x(.., H-tp) @ W(H/tp, N) -> out Partial over tp
        res = R.matmul_rule([("data", None, "tp"), ("tp", None)],
                            [(4, 16, 32), (32, 96)], {}, [(4, 16, 96)])
        assert res.out_partial[0] == ("tp",)
        # column-parallel: no pending reduce
        res = R.matmul_rule([("data", None, None), (None, "tp")],
                            [(4, 16, 32), (32, 96)], {}, [(4, 16, 96)])
        assert res.out_partial[0] == ()

    def test_embedding_vocab_shard_is_partial(self):
        res = R.embedding_rule([("data", None), ("tp", None)],
                               [(4, 16), (64, 32)], {}, [(4, 16, 32)])
        assert res.out_partial[0] == ("tp",)


# ==========================================================================
# general einsum rule (equation attr)
# ==========================================================================
class TestEinsumRule:
    def test_moe_dispatch_and_combine(self):
        # dispatch: nec,nh->ech — e sharded over ep propagates; n
        # contracted (unsharded) -> no partial
        res = R.einsum_rule([(None, "ep", None), (None, None)],
                            [(64, 8, 4), (64, 32)],
                            {"equation": "nec,nh->ech"}, [(8, 4, 32)])
        assert res.out_specs[0] == ("ep", None, None)
        assert res.out_partial[0] == ()
        # combine: nec,ech->nh — e contracted AND sharded -> Partial
        res = R.einsum_rule([(None, "ep", None), ("ep", None, None)],
                            [(64, 8, 4), (8, 4, 32)],
                            {"equation": "nec,ech->nh"}, [(64, 32)])
        assert res.out_partial[0] == ("ep",)

    def test_contracted_sharded_dim_partial(self):
        res = R.einsum_rule([("data", "tp"), ("tp", None)],
                            [(8, 32), (32, 16)],
                            {"equation": "bh,hd->bd"}, [(8, 16)])
        assert res.out_specs[0] == ("data", None)
        assert res.out_partial[0] == ("tp",)

    def test_input_constraints_follow_label_map(self):
        res = R.einsum_rule([("data", None), (None, "tp")],
                            [(8, 32), (32, 16)],
                            {"equation": "bh,hd->bd"}, [(8, 16)])
        # h merged replicated, d keeps tp
        assert res.in_specs[1] == (None, "tp")
        assert res.out_specs[0] == ("data", "tp")

    def test_implicit_output_and_fallbacks(self):
        terms = R.parse_einsum_equation("ij,jk", 2)
        assert terms == ([["i", "j"], ["j", "k"]], ["i", "k"])
        assert R.parse_einsum_equation("...ij,jk->...ik", 2) is None
        assert R.parse_einsum_equation("ij,jk->ik", 3) is None
        # no equation -> legacy heuristic, never a crash
        res = R.einsum_rule([("data", None)], [(8, 32)], {}, [(8, 32)])
        assert len(res.out_specs) == 1

    def test_einsum_dispatch_records_equation(self):
        from paddle_tpu import static
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 8], "float32")
            y = paddle.to_tensor(np.ones((8, 2), np.float32))
            paddle.einsum("ij,jk->ik", x, y)
        rec = prog.global_block().ops[-1]
        assert rec.name == "einsum"
        assert rec.attrs.get("equation") == "ij,jk->ik"

    def test_einsum_cost_from_equation(self):
        from paddle_tpu.observability.perf.costmodel import einsum_cost
        c = einsum_cost([(8, 32), (32, 16)], [],
                        {"equation": "bh,hd->bd"}, [(8, 16)])
        assert c.flops == 2.0 * 8 * 32 * 16


# ==========================================================================
# candidate enumeration
# ==========================================================================
PARAMS = [
    ("net.0.fc1.weight", (32, 128)), ("net.0.fc1.bias", (128,)),
    ("net.0.fc2.weight", (128, 32)), ("net.0.fc2.bias", (32,)),
    ("net.ln.weight", (32,)), ("net.wte.weight", (512, 32)),
]


class TestCandidates:
    def test_roles(self):
        assert planner.classify_param("a.qkv_proj.weight", (4, 12)) \
            == "column"
        assert planner.classify_param("a.out_proj.weight", (4, 4)) \
            == "row"
        assert planner.classify_param("gpt.wte.weight", (64, 4)) \
            == "embedding"
        assert planner.classify_param("gpt.wpe.weight", (16, 4)) \
            == "position"
        assert planner.classify_param("blk.ln1.weight", (4,)) == "norm"
        assert planner.classify_param("x.fc1.bias", (8,)) == "bias"

    def test_families_present(self):
        mesh = _mesh(data=2, tp=4)
        cands = planner.enumerate_candidates(PARAMS, mesh)
        names = [c.name for c in cands]
        assert "dp" in names and "tp(tp)" in names \
            and "fsdp(tp)" in names
        dp = next(c for c in cands if c.name == "dp")
        assert all(all(e is None for e in s)
                   for _, s in dp.param_specs)
        tp = next(c for c in cands if c.name == "tp(tp)")
        assert tp.spec_of("net.0.fc1.weight") == (None, "tp")
        assert tp.spec_of("net.0.fc2.weight") == ("tp", None)

    def test_enumeration_deterministic(self):
        mesh = _mesh(data=2, tp=4)
        a = planner.enumerate_candidates(PARAMS, mesh)
        b = planner.enumerate_candidates(PARAMS, mesh)
        assert [c.name for c in a] == [c.name for c in b]
        assert [c.param_specs for c in a] == [c.param_specs for c in b]

    def test_hybrid_family_on_3d_mesh(self):
        mesh = _mesh(data=2, fsdp=2, tp=2)
        names = [c.name for c in
                 planner.enumerate_candidates(PARAMS, mesh)]
        assert any("xfsdp" in n for n in names)


# ==========================================================================
# cost model sanity
# ==========================================================================
class _MLP(nn.Layer):
    """Named fc1/fc2 so the planner's role heuristics see them."""

    def __init__(self, hidden=256):
        super().__init__()
        self.fc1 = nn.Linear(32, hidden)
        self.fc2 = nn.Linear(hidden, 8)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        return self.fc2(F.gelu(self.fc1(x)))


def _mlp_plan(mesh, capacity_bytes=None, hidden=256, batch=1024):
    # small PARAMS, big batch — the data-parallel sweet spot (grad
    # sync is param-sized, activation work batch-sized)
    paddle.seed(7)
    model = _MLP(hidden)
    x = np.random.RandomState(0).randn(batch, 32).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 8, (batch,)) \
        .astype(np.int64)
    loss = nn.CrossEntropyLoss()

    def loss_fn(xt, yt):
        return loss(model(xt), yt)

    return planner.plan(loss_fn, mesh, example_inputs=(x, y),
                        model=model, capacity_bytes=capacity_bytes)


class TestCostSanity:
    def test_dp_beats_tp_on_small_model(self):
        res = _mlp_plan(_mesh(data=2, tp=4))
        by_name = {s.candidate.name: s.score for s in res.ranked}
        assert by_name["dp"].total_s < by_name["tp(tp)"].total_s
        assert not by_name["dp"].rejected

    def test_over_capacity_rejects_dp_and_shards_params(self):
        # param-heavy regime (big weights, small batch): capacity below
        # the replicated footprint must REJECT dp (hard), and the
        # winner must actually shard parameters
        mesh = _mesh(data=2, tp=4)
        probe = _mlp_plan(mesh, hidden=512, batch=64)
        dp = next(s for s in probe.ranked if s.candidate.name == "dp")
        tight = dp.score.hbm_bytes * 0.6
        res = _mlp_plan(mesh, capacity_bytes=tight, hidden=512,
                        batch=64)
        dp2 = next(s for s in res.ranked if s.candidate.name == "dp")
        assert dp2.score.rejected and "HBM" in dp2.score.rejected
        win = res.winner
        assert not win.score.rejected
        assert win.score.hbm_bytes <= tight
        assert any(any(e is not None for e in s)
                   for s in res.param_spec_table.values())

    def test_all_rejected_raises(self):
        with pytest.raises(RuntimeError, match="every candidate"):
            _mlp_plan(_mesh(data=2, tp=4), capacity_bytes=1.0)

    def test_partial_and_grad_sync_are_priced(self):
        res = _mlp_plan(_mesh(data=2, tp=4))
        by_name = {s.candidate.name: s.score for s in res.ranked}
        # DP pays gradient sync; megatron-TP pays pending reduces
        assert by_name["dp"].collective_breakdown["grad_sync"] > 0
        tp = by_name["tp(tp)"]
        assert tp.collective_breakdown["partial"] > 0 \
            or tp.collective_breakdown["backward"] > 0

    def test_penalty_ops_documented(self):
        for op, why in pcost.PENALTY_OPS.items():
            assert isinstance(why, str) and len(why) > 10


# ==========================================================================
# GPT plan: zero fallbacks + deterministic emission
# ==========================================================================
GPT_CFG = dict(vocab_size=128, hidden_size=64, num_layers=1,
               num_heads=4, max_seq_len=32, use_flash_attention=False)


def _gpt_plan(mesh):
    paddle.seed(3)
    model = GPTForCausalLM(GPTConfig(**GPT_CFG))
    ids = np.random.RandomState(0).randint(
        0, GPT_CFG["vocab_size"], (4, 32)).astype(np.int64)

    def loss_fn(x):
        _, loss = model(x, labels=x)
        return loss

    return model, ids, planner.plan(loss_fn, mesh,
                                    example_inputs=(ids,), model=model)


@pytest.fixture(scope="module")
def gpt_plan():
    """One shared plan for the read-only GPT assertions."""
    return _gpt_plan(_mesh(data=2, tp=4))


class TestGptPlan:
    def test_winner_has_zero_fallbacks(self, gpt_plan):
        _, _, res = gpt_plan
        assert res.winner.fallbacks == {}
        assert res.winner.score.fallback_ops == {}
        assert res.winner.score.unscored_ops == {}

    def test_plan_deterministic(self, gpt_plan):
        _, _, a = gpt_plan
        _, _, b = _gpt_plan(_mesh(data=2, tp=4))
        assert [s.candidate.name for s in a.ranked] \
            == [s.candidate.name for s in b.ranked]
        assert a.param_spec_table == b.param_spec_table

    def test_report_renders(self, gpt_plan):
        _, _, res = gpt_plan
        text = res.report()
        assert "Candidate table" in text
        assert res.winner.candidate.name in text
        assert "Emitted placement" in text
        s = res.summary()
        assert s["winner"] == res.winner.candidate.name


# ==========================================================================
# emission round-trips (Engine / to_static)
# ==========================================================================
class TestRoundTrip:
    def test_engine_auto_matches_unsharded(self):
        from paddle_tpu.distributed.auto_parallel.engine import Engine
        from paddle_tpu.io import TensorDataset

        def build():
            paddle.seed(11)
            model = nn.Sequential(nn.Linear(32, 64), nn.GELU(),
                                  nn.Linear(64, 8))
            opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=model.parameters())
            return model, opt

        rng = np.random.RandomState(0)
        xs = rng.randn(32, 32).astype(np.float32)
        ys = rng.randint(0, 8, (32,)).astype(np.int64)
        ds = TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])

        prev = mesh_mod._global_mesh
        try:
            mesh_mod._global_mesh = None
            # single-device reference (no mesh -> plain jit)
            model, opt = build()
            ref = Engine(model, nn.CrossEntropyLoss(), opt)
            ref_hist = ref.fit(ds, epochs=1, batch_size=32)

            mesh_mod._global_mesh = None
            mesh = _mesh(data=2, tp=4)
            model2, opt2 = build()
            eng = Engine(model2, nn.CrossEntropyLoss(), opt2,
                         mesh=mesh, placement="auto")
            hist = eng.fit(ds, epochs=1, batch_size=32)
        finally:
            mesh_mod._global_mesh = prev

        assert eng.placement_plan is not None
        assert eng.spmd_stats["fallback"] == {}
        np.testing.assert_allclose(hist, ref_hist, rtol=1e-4,
                                   atol=1e-5)

    def test_to_static_auto_matches_eager(self):
        from paddle_tpu.jit import to_static

        paddle.seed(5)
        model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(),
                              nn.Linear(32, 4))
        mesh = _mesh(data=2, tp=4)

        def fwd(x):
            return (model(x) ** 2).mean()

        x = paddle.to_tensor(
            np.random.RandomState(2).randn(8, 16).astype(np.float32))
        eager = float(fwd(x).numpy())
        f = to_static(fwd, full_graph=True, mesh=mesh,
                      param_specs="auto")
        got = float(f(x).numpy())
        assert f.placement_plan is not None
        assert f.spmd_stats["fallback"] == {}
        np.testing.assert_allclose(got, eager, rtol=1e-5)

    def test_apply_stamps_and_places(self):
        # constrain capacity so the winner MUST shard parameters, then
        # apply() must place them for real
        mesh = _mesh(data=2, tp=4)
        paddle.seed(3)
        model = GPTForCausalLM(GPTConfig(**GPT_CFG))
        ids = np.random.RandomState(0).randint(
            0, GPT_CFG["vocab_size"], (4, 32)).astype(np.int64)

        def loss_fn(x):
            _, loss = model(x, labels=x)
            return loss

        probe = planner.plan(loss_fn, mesh, example_inputs=(ids,),
                             model=model)
        dp = next(s for s in probe.ranked if s.candidate.name == "dp")
        res = planner.plan(loss_fn, mesh, example_inputs=(ids,),
                           model=model,
                           capacity_bytes=dp.score.hbm_bytes * 0.7)
        placed = res.apply(model)
        assert placed  # the winner shards something
        for name, spec in placed.items():
            p = dict(model.named_parameters())[name]
            assert tuple(p._spmd_spec) == tuple(spec)

    def test_in_specs_shape(self, gpt_plan):
        _, _, res = gpt_plan
        spec = res.in_specs
        assert isinstance(spec, P)


# ==========================================================================
# audit: no silently-unscored ops (tier-1, like fusion_audit)
# ==========================================================================
def test_planner_audit_clean():
    from tools.planner_audit import audit
    rep = audit()
    assert rep["ok"], rep["uncovered"]
    assert set(rep["workloads"]) == {"gpt", "llama", "moe", "dlrm"}
    # the MoE workload's opaque ops go through the penalty table, not
    # silence
    assert rep["workloads"]["moe"].get("moe_layer") == "penalty"


# ==========================================================================
# liveness-at-peak activation pricing (static.liveness -> cost.score_plan)
# ==========================================================================
class TestLivenessActivations:
    """The HBM term prices the liveness PEAK, not the sum of every
    activation: a long elementwise chain holds ~2 values at once, and
    the tighter bound must flip a hard-HBM rejection into an accepted
    candidate — without admitting a genuinely over-capacity plan."""

    def _chain_program(self, depth=24, n=64):
        from paddle_tpu import static
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", (n, n), "float32")
            h = x
            for _ in range(depth):
                h = (h * 1.0009765625) + 0.5
        return prog

    def _score(self, capacity):
        from paddle_tpu.distributed.spmd.propagate import \
            propagate_program
        mesh = _mesh(data=2, tp=4)
        prog = self._chain_program()
        plan = propagate_program(prog, mesh, {"x": None})
        sc = pcost.score_plan(prog, plan, mesh,
                              candidate_name="chain",
                              capacity_bytes=capacity)
        return prog, plan, mesh, sc

    def test_rejection_flips_to_accept(self):
        nb = 64 * 64 * 4
        capacity = 6 * nb
        prog, plan, mesh, sc = self._score(capacity)
        # the OLD all-activations-resident estimate (sum of every op
        # output at its sharded size) is over this capacity...
        old_sum = sum(
            pcost._value_bytes(s)
            * pcost.shard_fraction(spec, mesh, s)
            for op, ann in zip(prog.global_block().ops,
                               plan.annotations)
            for s, spec in zip(op.out_shapes or (), ann.out_specs))
        rest = sc.hbm_bytes - sc.memory_breakdown["activations"]
        assert old_sum + rest > capacity, \
            "fixture too small: old estimate would also fit"
        # ...but the liveness peak of an elementwise chain is ~2
        # buffers, and the candidate is ACCEPTED
        assert sc.rejected is None
        assert sc.hbm_bytes <= capacity
        assert sc.memory_breakdown["activations"] <= 3 * nb
        # attribution names the op at the high-water mark
        assert sc.activation_peak_op in ("multiply", "add", "scale")
        ops = prog.global_block().ops
        assert 0 <= sc.activation_peak_index < len(ops)
        assert "activation_peak_op" in sc.to_dict()

    def test_true_over_capacity_still_rejected(self):
        # the tighter bound must NOT admit a plan whose liveness peak
        # itself busts the device: capacity under the real footprint
        # stays a hard rejection
        _, _, _, probe = self._score(capacity=None or 1e15)
        tight = probe.hbm_bytes * 0.5
        _, _, _, sc = self._score(tight)
        assert sc.rejected is not None and "HBM" in sc.rejected

    def test_gemm_operands_pinned_for_backward(self):
        # a matmul's input is saved for the wgrad: pinning must hold it
        # to program end, so the peak can never be below operand+output
        from paddle_tpu import static
        from paddle_tpu.distributed.spmd.propagate import \
            propagate_program
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", (32, 32), "float32")
            h = x * 2.0              # op-produced GEMM operand
            w = paddle.ones((32, 32), "float32")
            y = paddle.matmul(h, w)
            z = y + 1.0
        mesh = _mesh(data=2, tp=4)
        plan = propagate_program(prog, mesh, {"x": None})
        sc = pcost.score_plan(prog, plan, mesh,
                              candidate_name="pin",
                              capacity_bytes=1e15)
        nb = 32 * 32 * 4
        # h pinned to end + y + z live at the final op -> >= 2 buffers
        assert sc.memory_breakdown["activations"] >= 2 * nb
