"""Native ONNX export tests.

Reference contract: python/paddle/onnx/export.py — paddle.onnx.export
produces a .onnx file whose execution matches the live model's logits.
No onnx/onnxruntime in the image, so verification uses the bundled
protobuf parser + numpy evaluator (paddle_tpu/onnx/runtime.py); an
onnxruntime cross-check runs automatically when that package exists.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.onnx as ponnx
from paddle_tpu import nn


def _export_and_run(model, x, tmp_path, name):
    path = ponnx.export(model, str(tmp_path / name),
                        input_spec=[paddle.to_tensor(x)])
    got = ponnx.run(path, {"x0": x})[0]
    model.eval()
    ref = model(paddle.to_tensor(x))
    np.testing.assert_allclose(got, np.asarray(ref.numpy()),
                               atol=1e-4, rtol=1e-4)
    return path


class TestLeNetExport:
    def test_logits_match(self, tmp_path):
        from paddle_tpu.vision.models import LeNet
        paddle.seed(0)
        m = LeNet()
        x = np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32)
        path = _export_and_run(m, x, tmp_path, "lenet")
        # the file is a real ModelProto our parser round-trips
        from paddle_tpu.onnx import proto
        with open(path, "rb") as f:
            model = proto.parse_model(f.read())
        assert model["opset"] == 13
        ops = [n["op_type"] for n in model["graph"]["nodes"]]
        assert "Conv" in ops and "MaxPool" in ops and "Gemm" in ops

    def test_onnxruntime_if_available(self, tmp_path):
        ort = pytest.importorskip("onnxruntime")
        from paddle_tpu.vision.models import LeNet
        m = LeNet()
        x = np.random.randn(1, 1, 28, 28).astype(np.float32)
        path = ponnx.export(m, str(tmp_path / "lenet_ort"),
                            input_spec=[paddle.to_tensor(x)])
        sess = ort.InferenceSession(path)
        got = sess.run(None, {"x0": x})[0]
        ref = m(paddle.to_tensor(x))
        np.testing.assert_allclose(got, np.asarray(ref.numpy()),
                                   atol=1e-4)


class TestResNetExport:
    def test_resnet18_logits_match(self, tmp_path):
        from paddle_tpu.vision.models import resnet18
        paddle.seed(1)
        m = resnet18(num_classes=10)
        x = np.random.RandomState(1).randn(1, 3, 64, 64).astype(np.float32)
        _export_and_run(m, x, tmp_path, "resnet18")


class TestOpVariants:
    def test_conv_stride_padding_groups(self, tmp_path):
        paddle.seed(2)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.c1 = nn.Conv2D(4, 8, 3, stride=2, padding=1)
                self.c2 = nn.Conv2D(8, 8, 3, padding=2, dilation=2,
                                    groups=2)

            def forward(self, x):
                import paddle_tpu.nn.functional as F
                return F.relu(self.c2(F.relu(self.c1(x))))

        x = np.random.RandomState(2).randn(2, 4, 16, 16).astype(np.float32)
        _export_and_run(Net(), x, tmp_path, "convs")

    def test_pool_and_softmax(self, tmp_path):
        paddle.seed(3)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(16, 4)

            def forward(self, x):
                import paddle_tpu.nn.functional as F
                h = F.avg_pool2d(x, 2, stride=2)
                h = paddle.ops.reshape(h, [h.shape[0], -1])
                return F.softmax(self.fc(h), axis=-1)

        x = np.random.RandomState(3).randn(2, 4, 4, 4).astype(np.float32)
        _export_and_run(Net(), x, tmp_path, "pool_softmax")

    def test_same_padding_roundtrip(self, tmp_path):
        paddle.seed(4)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.c = nn.Conv2D(3, 6, 3, stride=2, padding="SAME")

            def forward(self, x):
                import paddle_tpu.nn.functional as F
                return F.max_pool2d(F.relu(self.c(x)), 2, stride=2,
                                    padding="SAME")

        x = np.random.RandomState(4).randn(2, 3, 9, 9).astype(np.float32)
        _export_and_run(Net(), x, tmp_path, "same_pad")

    def test_flatten_variants(self, tmp_path):
        paddle.seed(5)

        class Net(nn.Layer):
            def forward(self, x):
                a = paddle.ops.flatten(x, start_axis=1)      # Flatten
                b = paddle.ops.flatten(x, start_axis=0)      # full ravel
                return a, paddle.ops.reshape(b, [1, -1])

        x = np.random.RandomState(5).randn(2, 3, 4).astype(np.float32)
        m = Net()
        path = ponnx.export(m, str(tmp_path / "flat"),
                            input_spec=[paddle.to_tensor(x)])
        outs = ponnx.run(path, {"x0": x})
        refs = m(paddle.to_tensor(x))
        for got, ref in zip(outs, refs):
            np.testing.assert_allclose(got, np.asarray(ref.numpy()),
                                       atol=1e-6)

    def test_batch_merging_reshape(self, tmp_path):
        class Net(nn.Layer):
            def forward(self, x):
                return paddle.ops.reshape(x, [x.shape[0] * x.shape[1], -1])

        x = np.random.RandomState(6).randn(2, 3, 4).astype(np.float32)
        m = Net()
        path = ponnx.export(m, str(tmp_path / "merge"),
                            input_spec=[paddle.to_tensor(x)])
        got = ponnx.run(path, {"x0": x})[0]
        np.testing.assert_allclose(got, x.reshape(6, 4), atol=1e-6)

    def test_unsupported_op_raises_clearly(self, tmp_path):
        class Net(nn.Layer):
            def forward(self, x):
                return paddle.ops.cumsum(x, axis=1)

        x = np.random.randn(2, 3).astype(np.float32)
        with pytest.raises(NotImplementedError, match="cumsum"):
            ponnx.export(Net(), str(tmp_path / "bad"),
                         input_spec=[paddle.to_tensor(x)])
