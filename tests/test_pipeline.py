"""Pipeline-parallel tests on the 8-device virtual mesh.

Mirrors the reference PP test strategy (reference:
test/collective/fleet/hybrid_parallel_pp_alexnet.py — pipelined loss must
track the single-process loss) but runs SPMD: the pipelined program and the
sequential model execute in one process and must match numerically.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed.fleet as fleet_pkg
from paddle_tpu import nn
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc, PipelineLayer, PipelineParallel, SegmentLayers,
    SharedLayerDesc, spmd_pipeline)


class Block(nn.Layer):
    def __init__(self, d=16):
        super().__init__()
        self.fc = nn.Linear(d, d)

    def forward(self, x):
        return paddle.ops.tanh(self.fc(x))


class Head(nn.Layer):
    def __init__(self, d=16, out=4):
        super().__init__()
        self.fc = nn.Linear(d, out)

    def forward(self, x):
        return self.fc(x)


def _mse(out, label):
    return paddle.ops.mean((out - label) ** 2)


@pytest.fixture
def pp_mesh():
    old = mesh_mod._global_mesh
    mesh = mesh_mod.build_mesh({"dp": 2, "pp": 4})
    mesh_mod.set_mesh(mesh)
    yield mesh
    mesh_mod._global_mesh = old


class TestSegmentLayers:
    def test_uniform(self):
        assert SegmentLayers.uniform(8, 4) == [0, 2, 4, 6, 8]
        assert SegmentLayers.uniform(10, 4) == [0, 3, 6, 8, 10]

    def test_layer_method(self):
        descs = [LayerDesc(Head), *[LayerDesc(Block) for _ in range(8)],
                 LayerDesc(Head)]
        seg = SegmentLayers(descs, 4, method="layer:Block")
        parts = seg.do_segment()
        assert len(parts) == 5
        assert parts[0] == 0 and parts[-1] == len(descs)


class TestPipelineLayer:
    def test_build_and_stage_index(self, pp_mesh):
        pl = PipelineLayer(layers=[LayerDesc(Block) for _ in range(8)],
                           num_stages=4, loss_fn=_mse)
        assert pl.num_stages == 4
        assert pl.get_stage_from_index(0) == 0
        assert pl.get_stage_from_index(7) == 3
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        out = pl(x)
        assert out.shape == [4, 16]

    def test_shared_layer_desc_ties_weights(self, pp_mesh):
        pl = PipelineLayer(
            layers=[SharedLayerDesc("emb", Block, None, "fc"),
                    LayerDesc(Block),
                    SharedLayerDesc("emb", Block, None, "fc")],
            num_stages=1)
        fns = pl.run_function
        assert fns[0] is fns[2]
        n_unique = len({id(p) for p in pl.parameters()})
        assert n_unique == 4  # shared block (w,b) counted once + middle

    def test_callable_entries(self, pp_mesh):
        pl = PipelineLayer(layers=[LayerDesc(Block),
                                   lambda x: x * 2,
                                   LayerDesc(Block)],
                           num_stages=1)
        x = paddle.to_tensor(np.random.randn(2, 16).astype(np.float32))
        assert pl(x).shape == [2, 16]


class TestSpmdPipeline:
    def test_matches_sequential(self, pp_mesh):
        import jax
        import jax.numpy as jnp
        S, K, m, B, D = 4, 2, 8, 4, 16
        rng = np.random.RandomState(0)
        Ws = jnp.asarray(rng.randn(S * K, D, D).astype(np.float32) * 0.1)
        xs = jnp.asarray(rng.randn(m, B, D).astype(np.float32))

        def block_fn(per_block, x):
            (w,) = per_block
            return jnp.tanh(x @ w)

        def seq(Ws, xs):
            h = xs
            for i in range(S * K):
                h = jnp.tanh(h @ Ws[i])
            return h

        got = jax.jit(lambda Ws, xs: spmd_pipeline(
            block_fn, [Ws], xs, mesh=pp_mesh, num_stages=S,
            schedule="FThenB"))(Ws, xs)
        ref = seq(Ws, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-6)

        # gradients through the pipeline == sequential gradients
        g1 = jax.jit(jax.grad(lambda W: jnp.sum(spmd_pipeline(
            block_fn, [W], xs, mesh=pp_mesh, num_stages=S,
            schedule="1F1B") ** 2)))(Ws)
        g2 = jax.grad(lambda W: jnp.sum(seq(W, xs) ** 2))(Ws)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=1e-5)


class TestPipelineParallel:
    def _make(self, n_blocks=8, stages=4, accumulate=4):
        paddle.seed(42)
        pl = PipelineLayer(
            layers=[LayerDesc(Block) for _ in range(n_blocks)]
            + [LayerDesc(Head)],
            num_stages=stages, loss_fn=_mse)
        strategy = fleet_pkg.DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": accumulate,
                                     "schedule_mode": "1F1B",
                                     "micro_batch_size": 2}
        return pl, strategy

    def test_loss_matches_sequential(self, pp_mesh):
        pl, strategy = self._make()
        pp = PipelineParallel(pl, None, strategy)
        x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
        loss = pp.forward_backward_pipeline((x, y))
        ref = _mse(pl(x), y)
        np.testing.assert_allclose(float(loss.numpy()),
                                   float(ref.numpy()), rtol=1e-5)

    def test_grads_match_sequential(self, pp_mesh):
        pl, strategy = self._make()
        pp = PipelineParallel(pl, None, strategy)
        x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
        pp.forward_backward_pipeline((x, y))
        got = {n: np.asarray(p.grad._data)
               for n, p in pl.named_parameters() if p.grad is not None}

        for p in pl.parameters():
            p.clear_grad()
        loss = _mse(pl(x), y)
        loss.backward()
        for n, p in pl.named_parameters():
            if p.stop_gradient:
                continue
            np.testing.assert_allclose(
                got[n], np.asarray(p.grad._data), atol=2e-5,
                err_msg=f"grad mismatch for {n}")

    def test_train_batch_decreases_loss(self, pp_mesh):
        pl, strategy = self._make(n_blocks=4, stages=4, accumulate=2)
        pp = PipelineParallel(pl, None, strategy)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=pl.parameters())
        x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
        losses = [float(pp.train_batch((x, y), opt).numpy())
                  for _ in range(5)]
        assert losses[-1] < losses[0]

    def test_heterogeneous_stages_pipeline(self, pp_mesh):
        # shape-changing, param-heterogeneous stack now pipelines (switch
        # programs per rank) and must match the sequential model exactly
        paddle.seed(7)
        pl = PipelineLayer(
            layers=[LayerDesc(Block), LayerDesc(Block),
                    LayerDesc(Head), LayerDesc(Head, d=4, out=4)],
            num_stages=4, loss_fn=_mse)
        strategy = fleet_pkg.DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": 4}
        pp = PipelineParallel(pl, None, strategy)
        assert pp._hetero_stages is not None
        x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
        loss = pp.forward_backward_pipeline((x, y))
        ref = _mse(pl(x), y)
        np.testing.assert_allclose(float(loss.numpy()), float(ref.numpy()),
                                   rtol=1e-5)
        got = {n: np.asarray(p.grad._data)
               for n, p in pl.named_parameters() if p.grad is not None}
        for p in pl.parameters():
            p.clear_grad()
        ref.backward()
        for n, p in pl.named_parameters():
            if not p.stop_gradient:
                np.testing.assert_allclose(
                    got[n], np.asarray(p.grad._data), atol=2e-5,
                    err_msg=f"hetero grad mismatch for {n}")

    def test_too_few_layers_rejected(self, pp_mesh):
        # reference contract: PipelineLayer refuses fewer layers than
        # stages at construction (SegmentLayers check)
        with pytest.raises(ValueError, match="should be greater"):
            PipelineLayer(layers=[LayerDesc(Block), LayerDesc(Head)],
                          num_stages=4, loss_fn=_mse)

    @pytest.mark.parametrize("mode", ["VPP", "ZBH1"])
    def test_schedule_modes_match_sequential(self, pp_mesh, mode):
        paddle.seed(11)
        pl = PipelineLayer(
            layers=[LayerDesc(Block) for _ in range(8)] + [LayerDesc(Head)],
            num_stages=4, loss_fn=_mse)
        strategy = fleet_pkg.DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": 8,
                                     "schedule_mode": mode}
        pp = PipelineParallel(pl, None, strategy)
        x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
        loss = pp.forward_backward_pipeline((x, y))
        ref = _mse(pl(x), y)
        np.testing.assert_allclose(float(loss.numpy()), float(ref.numpy()),
                                   rtol=1e-5)
        got = {n: np.asarray(p.grad._data)
               for n, p in pl.named_parameters() if p.grad is not None}
        for p in pl.parameters():
            p.clear_grad()
        ref.backward()
        for n, p in pl.named_parameters():
            if not p.stop_gradient:
                np.testing.assert_allclose(
                    got[n], np.asarray(p.grad._data), atol=2e-5,
                    err_msg=f"{mode} grad mismatch for {n}")

    def test_fleet_distributed_model_pp(self, pp_mesh):
        fleet = fleet_pkg.fleet
        strategy = fleet_pkg.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "pp_degree": 4,
                                   "mp_degree": 1}
        strategy.pipeline_configs = {"accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strategy)
        pl = PipelineLayer(
            layers=[LayerDesc(Block) for _ in range(4)] + [LayerDesc(Head)],
            loss_fn=_mse)
        model = fleet.distributed_model(pl)
        assert isinstance(model, PipelineParallel)
        opt = paddle.optimizer.SGD(learning_rate=1e-2,
                                   parameters=pl.parameters())
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        loss = model.train_batch((x, y), opt)
        assert np.isfinite(float(loss.numpy()))
