"""Vision/detection op tests: NMS family, ROI family, codecs, YOLO,
grid_sample/affine_grid, deform_conv2d.

Reference behaviors: python/paddle/vision/ops.py and the phi kernels; where
torch implements the same op (grid_sample, roi_align via torchvision absent
— use hand checks), we verify against torch CPU or hand-computed values.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops
import paddle_tpu.nn.functional as F


def _np_nms(boxes, scores, thr):
    order = np.argsort(-scores)
    keep = []
    sup = np.zeros(len(boxes), bool)
    for i in order:
        if sup[i]:
            continue
        keep.append(i)
        for j in order:
            if sup[j] or j == i:
                continue
            xx1 = max(boxes[i, 0], boxes[j, 0])
            yy1 = max(boxes[i, 1], boxes[j, 1])
            xx2 = min(boxes[i, 2], boxes[j, 2])
            yy2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(xx2 - xx1, 0) * max(yy2 - yy1, 0)
            a1 = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            a2 = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            iou = inter / (a1 + a2 - inter)
            if iou > thr:
                sup[j] = True
    return keep


class TestNMS:
    def test_matches_reference_greedy(self):
        rng = np.random.RandomState(0)
        xy = rng.uniform(0, 50, (40, 2)).astype(np.float32)
        wh = rng.uniform(5, 30, (40, 2)).astype(np.float32)
        boxes = np.concatenate([xy, xy + wh], axis=1)
        scores = rng.uniform(0, 1, 40).astype(np.float32)
        out = vops.nms(paddle.to_tensor(boxes), 0.4,
                       scores=paddle.to_tensor(scores))
        np.testing.assert_array_equal(out.numpy(),
                                      np.asarray(_np_nms(boxes, scores, 0.4)))

    def test_no_scores_keeps_input_order(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [30, 30, 40, 40]],
                         dtype=np.float32)
        out = vops.nms(paddle.to_tensor(boxes), 0.3)
        np.testing.assert_array_equal(out.numpy(), [0, 2])

    def test_categories_do_not_suppress_each_other(self):
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10]], dtype=np.float32)
        scores = np.array([0.9, 0.8], dtype=np.float32)
        cats = np.array([0, 1])
        out = vops.nms(paddle.to_tensor(boxes), 0.3,
                       scores=paddle.to_tensor(scores),
                       category_idxs=paddle.to_tensor(cats),
                       categories=[0, 1])
        assert len(out.numpy()) == 2

    def test_multiclass_nms(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [30, 30, 40, 40]],
                         dtype=np.float32)
        scores = np.array([[0.9, 0.85, 0.2], [0.1, 0.2, 0.7]],
                          dtype=np.float32)  # (C=2, N=3)
        out, idx, num = vops.multiclass_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.3, nms_threshold=0.3, return_index=True)
        o = out.numpy()
        assert int(num.numpy()[0]) == o.shape[0] == 2
        assert o.shape[1] == 6
        # both detections above threshold survive per-class NMS
        assert set(o[:, 0].astype(int)) == {0, 1}

    def test_matrix_nms_decays_overlaps(self):
        boxes = np.array([[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                          [30, 30, 40, 40]], dtype=np.float32)
        scores = np.array([[0.0, 0.0, 0.0], [0.9, 0.8, 0.7]],
                          dtype=np.float32)
        out, num = vops.matrix_nms(paddle.to_tensor(boxes),
                                   paddle.to_tensor(scores),
                                   score_threshold=0.1, background_label=0)
        o = out.numpy()
        assert int(num.numpy()[0]) == 3
        # the overlapping second box's score is decayed below its raw 0.8
        row = o[np.isclose(o[:, 2], 0.5)][0]
        assert row[1] < 0.8


class TestRoI:
    def test_roi_align_uniform_map(self):
        # constant feature map -> every bin averages to the constant
        x = paddle.to_tensor(np.full((1, 1, 8, 8), 3.0, np.float32))
        boxes = paddle.to_tensor(np.array([[0, 0, 8, 8]], np.float32))
        out = vops.roi_align(x, boxes, [1], output_size=2, aligned=False)
        np.testing.assert_allclose(out.numpy(), np.full((1, 1, 2, 2), 3.0),
                                   rtol=1e-5)

    def test_roi_align_gradient_flows(self):
        x = paddle.to_tensor(
            np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8))
        x.stop_gradient = False
        boxes = paddle.to_tensor(np.array([[1, 1, 6, 6]], np.float32))
        out = vops.roi_align(x, boxes, [1], output_size=2)
        out.sum().backward()
        assert x.grad is not None
        assert float(x.grad.numpy().sum()) == pytest.approx(4.0, rel=1e-4)

    def test_roi_pool_max(self):
        a = np.zeros((1, 1, 8, 8), np.float32)
        a[0, 0, 2, 2] = 7.0
        a[0, 0, 6, 6] = 9.0
        x = paddle.to_tensor(a)
        boxes = paddle.to_tensor(np.array([[0, 0, 7, 7]], np.float32))
        out = vops.roi_pool(x, boxes, [1], output_size=2)
        o = out.numpy()[0, 0]
        assert o[0, 0] == 7.0 and o[1, 1] == 9.0

    def test_psroi_pool_channel_groups(self):
        # C = out_c(2) * 2 * 2; each bin reads its own channel group
        a = np.stack([np.full((8, 8), float(c)) for c in range(8)])[None]
        x = paddle.to_tensor(a.astype(np.float32))
        boxes = paddle.to_tensor(np.array([[0, 0, 7, 7]], np.float32))
        out = vops.psroi_pool(x, boxes, [1], output_size=2)
        o = out.numpy()
        assert o.shape == (1, 2, 2, 2)
        np.testing.assert_allclose(o[0, 0], [[0, 1], [2, 3]], rtol=1e-5)
        np.testing.assert_allclose(o[0, 1], [[4, 5], [6, 7]], rtol=1e-5)


class TestBoxes:
    def test_prior_box_shapes_and_range(self):
        inp = paddle.zeros([1, 3, 4, 4])
        img = paddle.zeros([1, 3, 32, 32])
        boxes, var = vops.prior_box(inp, img, min_sizes=[8.0],
                                    aspect_ratios=[1.0, 2.0], clip=True)
        assert boxes.shape == [4, 4, 2, 4]
        b = boxes.numpy()
        assert (b >= 0).all() and (b <= 1).all()
        assert var.shape == [4, 4, 2, 4]

    def test_box_coder_roundtrip(self):
        priors = np.array([[0, 0, 10, 10], [5, 5, 20, 25]], np.float32)
        gt = np.array([[1, 1, 9, 9]], np.float32)
        var = [0.1, 0.1, 0.2, 0.2]
        enc = vops.box_coder(paddle.to_tensor(priors), var,
                             paddle.to_tensor(gt),
                             code_type="encode_center_size")
        dec = vops.box_coder(paddle.to_tensor(priors), var,
                             paddle.to_tensor(enc.numpy()),
                             code_type="decode_center_size", axis=1)
        d = dec.numpy()  # (M, N, 4) -> each row decodes back to gt
        np.testing.assert_allclose(d[0, 0], gt[0], atol=1e-4)
        np.testing.assert_allclose(d[0, 1], gt[0], atol=1e-4)

    def test_box_clip(self):
        boxes = paddle.to_tensor(
            np.array([[[-5, -5, 50, 50]]], np.float32))
        im_info = paddle.to_tensor(np.array([[40.0, 30.0, 1.0]], np.float32))
        out = vops.box_clip(boxes, im_info)
        np.testing.assert_allclose(out.numpy()[0, 0], [0, 0, 29, 39])

    def test_bipartite_match_greedy(self):
        d = np.array([[0.9, 0.1, 0.3], [0.2, 0.8, 0.4]], np.float32)
        idx, dist = vops.bipartite_match(paddle.to_tensor(d))
        np.testing.assert_array_equal(idx.numpy()[0], [0, 1, -1])
        np.testing.assert_allclose(dist.numpy()[0], [0.9, 0.8, 0.0])

    def test_bipartite_match_per_prediction(self):
        d = np.array([[0.9, 0.6, 0.3]], np.float32)
        idx, _ = vops.bipartite_match(paddle.to_tensor(d),
                                      match_type="per_prediction",
                                      dist_threshold=0.5)
        np.testing.assert_array_equal(idx.numpy()[0], [0, 0, -1])


class TestYolo:
    def test_yolo_box_shapes_and_sigmoid_center(self):
        n, na, c, h, w = 2, 2, 3, 4, 4
        x = paddle.to_tensor(
            np.zeros((n, na * (5 + c), h, w), np.float32))
        img = paddle.to_tensor(np.full((n, 2), 64, np.int32))
        boxes, scores = vops.yolo_box(x, img, anchors=[10, 13, 16, 30],
                                      class_num=c, conf_thresh=0.0,
                                      downsample_ratio=16)
        assert boxes.shape == [n, na * h * w, 4]
        assert scores.shape == [n, na * h * w, c]
        # zero logits -> sigmoid 0.5 center in first cell -> cx=0.5/4*64=8
        b0 = boxes.numpy()[0, 0]
        assert b0[2] > b0[0] and b0[3] > b0[1]

    def test_yolo_loss_decreases_on_fit(self):
        rng = np.random.RandomState(0)
        n, na, c, h, w = 1, 3, 2, 4, 4
        x = paddle.to_tensor(
            rng.randn(n, na * (5 + c), h, w).astype(np.float32))
        x.stop_gradient = False
        gt = paddle.to_tensor(
            np.array([[[0.4, 0.4, 0.3, 0.3]]], np.float32))
        lbl = paddle.to_tensor(np.array([[1]], np.int64))
        loss = vops.yolo_loss(x, gt, lbl, anchors=[10, 13, 16, 30, 33, 23],
                              anchor_mask=[0, 1, 2], class_num=c,
                              ignore_thresh=0.7, downsample_ratio=8)
        assert loss.shape == [n]
        loss.sum().backward()
        g = x.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_generate_proposals(self):
        rng = np.random.RandomState(1)
        scores = rng.uniform(0, 1, (1, 3, 4, 4)).astype(np.float32)
        deltas = rng.randn(1, 12, 4, 4).astype(np.float32) * 0.1
        anchors = np.zeros((4, 4, 3, 4), np.float32)
        for i in range(4):
            for j in range(4):
                for a, sz in enumerate([8, 16, 32]):
                    cx, cy = j * 8 + 4, i * 8 + 4
                    anchors[i, j, a] = [cx - sz / 2, cy - sz / 2,
                                        cx + sz / 2, cy + sz / 2]
        var = np.full_like(anchors, 1.0)
        rois, rscores, num = vops.generate_proposals(
            paddle.to_tensor(scores), paddle.to_tensor(deltas),
            paddle.to_tensor(np.array([[32.0, 32.0]], np.float32)),
            paddle.to_tensor(anchors), paddle.to_tensor(var),
            pre_nms_top_n=50, post_nms_top_n=10, min_size=2.0)
        r = rois.numpy()
        assert r.shape[0] == int(num.numpy()[0]) <= 10
        assert (r[:, 2] >= r[:, 0]).all() and (r[:, 3] >= r[:, 1]).all()
        s = rscores.numpy()
        assert (np.diff(s) <= 1e-6).all()  # score-descending

    def test_distribute_fpn_proposals(self):
        rois = np.array([[0, 0, 10, 10],      # small -> low level
                         [0, 0, 200, 200]], np.float32)  # large -> high
        multi, restore = vops.distribute_fpn_proposals(
            paddle.to_tensor(rois), 2, 5, 4, 224)
        assert len(multi) == 4
        sizes = [m.shape[0] for m in multi]
        assert sum(sizes) == 2
        assert sorted(restore.numpy().ravel().tolist()) == [0, 1]


class TestGridSample:
    def test_identity_grid_bilinear(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 5, 7).astype(np.float32)
        theta = np.tile(np.array([[1, 0, 0], [0, 1, 0]], np.float32),
                        (2, 1, 1))
        grid = F.affine_grid(paddle.to_tensor(theta), [2, 3, 5, 7],
                             align_corners=True)
        out = F.grid_sample(paddle.to_tensor(x), grid, align_corners=True)
        np.testing.assert_allclose(out.numpy(), x, atol=1e-5)

    def test_matches_torch(self):
        torch = pytest.importorskip("torch")
        rng = np.random.RandomState(3)
        x = rng.randn(2, 2, 6, 6).astype(np.float32)
        grid = rng.uniform(-1.3, 1.3, (2, 4, 5, 2)).astype(np.float32)
        for mode in ("bilinear", "nearest"):
            for padding in ("zeros", "border", "reflection"):
                ours = F.grid_sample(paddle.to_tensor(x),
                                     paddle.to_tensor(grid), mode=mode,
                                     padding_mode=padding,
                                     align_corners=True).numpy()
                ref = torch.nn.functional.grid_sample(
                    torch.tensor(x), torch.tensor(grid), mode=mode,
                    padding_mode=padding, align_corners=True).numpy()
                np.testing.assert_allclose(ours, ref, atol=1e-4,
                                           err_msg=f"{mode}/{padding}")

    def test_affine_grid_matches_torch_unaligned(self):
        torch = pytest.importorskip("torch")
        theta = np.array([[[0.8, 0.1, -0.2], [0.0, 1.2, 0.3]]], np.float32)
        ours = F.affine_grid(paddle.to_tensor(theta), [1, 1, 4, 6],
                             align_corners=False).numpy()
        ref = torch.nn.functional.affine_grid(
            torch.tensor(theta), [1, 1, 4, 6], align_corners=False).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_grid_sample_grad_wrt_grid(self):
        x = paddle.to_tensor(
            np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        g = paddle.to_tensor(np.zeros((1, 2, 2, 2), np.float32))
        g.stop_gradient = False
        out = F.grid_sample(x, g, align_corners=True)
        out.sum().backward()
        assert g.grad is not None
        assert np.abs(g.grad.numpy()).sum() > 0


class TestTemporalShift:
    def test_shift_semantics(self):
        # N=1, T=2, C=4, 1x1 spatial; ratio 0.25 -> 1 ch back, 1 ch fwd
        v = np.arange(8, dtype=np.float32).reshape(2, 4, 1, 1)
        out = F.temporal_shift(paddle.to_tensor(v), seg_num=2,
                               shift_ratio=0.25).numpy()
        # ch0: backward shift (t gets t+1): out[t=0]=v[t=1], out[t=1]=0
        assert out[0, 0, 0, 0] == v[1, 0, 0, 0]
        assert out[1, 0, 0, 0] == 0
        # ch1: forward shift: out[t=0]=0, out[t=1]=v[t=0]
        assert out[0, 1, 0, 0] == 0
        assert out[1, 1, 0, 0] == v[0, 1, 0, 0]
        # ch2,3 unchanged
        np.testing.assert_array_equal(out[:, 2:], v[:, 2:])


class TestDeformConv:
    def test_zero_offset_equals_conv2d(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        w = rng.randn(4, 3, 3, 3).astype(np.float32)
        off = np.zeros((2, 2 * 9, 6, 6), np.float32)
        out = vops.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                                 paddle.to_tensor(w))
        ref = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w))
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-4)

    def test_mask_scales_output(self):
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 6, 6).astype(np.float32)
        w = rng.randn(2, 2, 3, 3).astype(np.float32)
        off = np.zeros((1, 18, 4, 4), np.float32)
        mask_half = np.full((1, 9, 4, 4), 0.5, np.float32)
        full = vops.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                                  paddle.to_tensor(w))
        half = vops.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                                  paddle.to_tensor(w),
                                  mask=paddle.to_tensor(mask_half))
        np.testing.assert_allclose(half.numpy(), full.numpy() * 0.5,
                                   atol=1e-4)

    def test_layer_and_grads(self):
        layer = vops.DeformConv2D(2, 3, 3, padding=1)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 2, 5, 5).astype(np.float32))
        x.stop_gradient = False
        off = paddle.to_tensor(np.zeros((1, 18, 5, 5), np.float32))
        out = layer(x, off)
        assert out.shape == [1, 3, 5, 5]
        out.sum().backward()
        assert layer.weight.grad is not None
        assert x.grad is not None


class TestImageIO:
    def test_read_decode_jpeg_roundtrip(self, tmp_path):
        from PIL import Image
        gy, gx = np.mgrid[0:10, 0:12]
        arr = np.stack([gy * 20, gx * 15, gy * 10 + gx * 5],
                       axis=-1).astype(np.uint8)
        p = tmp_path / "img.jpg"
        Image.fromarray(arr).save(p, quality=95)
        raw = vops.read_file(str(p))
        assert raw.numpy().dtype == np.uint8
        img = vops.decode_jpeg(raw, mode="rgb")
        assert img.shape == [3, 10, 12]
        # lossy codec: just sanity-check closeness
        diff = np.abs(img.numpy().transpose(1, 2, 0).astype(int)
                      - arr.astype(int)).mean()
        assert diff < 20
