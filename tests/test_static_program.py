"""static.Program / program_guard / Executor tests.

Reference: python/paddle/static/ (Program, program_guard, data, Executor)
— construct-then-execute parity over the recorded-op replay design.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

static = paddle.static


class TestProgramBuildRun:
    def test_fc_network_batch_polymorphic(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 8], "float32")
            h = static.nn.fc(x, 16, activation="relu")
            out = static.nn.fc(h, 4)
        exe = static.Executor()
        for b in (1, 3, 7):
            xv = np.random.RandomState(b).randn(b, 8).astype(np.float32)
            (o,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
            assert o.shape == (b, 4)

    def test_matches_eager(self):
        paddle.seed(0)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 6], "float32")
            y = (x * 2 + 1).tanh().sum()
        xv = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        (got,) = static.Executor().run(main, feed={"x": xv},
                                       fetch_list=[y])
        expect = np.tanh(xv * 2 + 1).sum()
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_two_feeds(self):
        main = static.Program()
        with static.program_guard(main):
            a = static.data("a", [None, 3], "float32")
            b = static.data("b", [None, 3], "float32")
            c = a @ b.t() + 1
        av = np.ones((2, 3), np.float32)
        bv = np.full((2, 3), 2.0, np.float32)
        (cv,) = static.Executor().run(main, feed={"a": av, "b": bv},
                                      fetch_list=[c])
        np.testing.assert_allclose(cv, np.full((2, 2), 7.0))

    def test_weights_are_live_captures(self):
        # mutating a captured parameter between runs changes the result
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            w = paddle.to_tensor(np.eye(2, dtype=np.float32))
            y = x @ w
        exe = static.Executor()
        xv = np.array([[1, 2], [3, 4]], np.float32)
        (y1,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
        np.testing.assert_allclose(y1, xv)
        w.set_value(paddle.to_tensor(2 * np.eye(2, dtype=np.float32)))
        (y2,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
        np.testing.assert_allclose(y2, 2 * xv)

    def test_embedding(self):
        main = static.Program()
        with static.program_guard(main):
            ids = static.data("ids", [None, 5], "int64")
            emb = static.nn.embedding(ids, size=[10, 4])
        (e,) = static.Executor().run(
            main, feed={"ids": np.zeros((2, 5), np.int64)},
            fetch_list=[emb])
        assert e.shape == (2, 5, 4)


class TestProgramSemantics:
    def test_introspection(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            _ = (x + 1) * 3
        s = main.to_string()
        assert "Program(feeds=[x:" in s
        names = [op.name for op in main.global_block().ops]
        assert "add" in names and "multiply" in names

    def test_data_outside_guard_raises(self):
        with pytest.raises(RuntimeError, match="program_guard"):
            static.data("x", [2, 2])

    def test_missing_feed_raises(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            y = x + 1
        with pytest.raises(KeyError, match="missing feeds"):
            static.Executor().run(main, feed={}, fetch_list=[y])

    def test_recording_stops_after_guard(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            y = x + 1
        n = len(main.global_block().ops)
        _ = paddle.to_tensor(np.ones((2, 2), np.float32)) * 5  # outside
        assert len(main.global_block().ops) == n

    def test_nested_guard_inner_only(self):
        # nested guards record into the INNER program only (reference
        # nested program_guard behavior)
        p1, p2 = static.Program(), static.Program()
        with static.program_guard(p1):
            a = static.data("a", [1], "float32")
            with static.program_guard(p2):
                b = static.data("b", [1], "float32")
                doubled = b * 2
            y = a + 1
        assert [op.name for op in p1.global_block().ops] == ["add"]
        assert [op.name for op in p2.global_block().ops] == ["multiply"]
        (out,) = static.Executor().run(
            p1, feed={"a": np.array([3.0], np.float32)}, fetch_list=[y])
        np.testing.assert_allclose(out, [4.0])
        (out2,) = static.Executor().run(
            p2, feed={"b": np.array([5.0], np.float32)},
            fetch_list=[doubled])
        np.testing.assert_allclose(out2, [10.0])

    def test_default_main_program(self):
        prog = static.default_main_program()
        assert isinstance(prog, static.Program)
        assert isinstance(static.CompiledProgram(prog).program,
                          static.Program)

    def test_jit_cache_reused(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4], "float32")
            y = x.sum()
        exe = static.Executor()
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[y])
        n1 = len(main._jit_cache)
        exe.run(main, feed={"x": np.full((2, 4), 3.0, np.float32)},
                fetch_list=[y])
        assert len(main._jit_cache) == n1  # same signature -> cached
        exe.run(main, feed={"x": np.ones((5, 4), np.float32)},
                fetch_list=[y])
        assert len(main._jit_cache) == n1 + 1  # new batch -> new program


class TestStaticNNAttrs:
    def test_fc_bias_attr_false(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 3], "float32")
            y = static.nn.fc(x, 4, bias_attr=False)
        (out,) = static.Executor().run(
            main, feed={"x": np.zeros((2, 3), np.float32)}, fetch_list=[y])
        np.testing.assert_allclose(out, 0.0)  # no bias -> zero input = zero

    def test_embedding_dtype_selects_weight_dtype(self):
        main = static.Program()
        with static.program_guard(main):
            ids = static.data("ids", [2, 2], "int64")
            out = static.nn.embedding(ids, [4, 3], dtype="bfloat16")
            assert str(out.dtype) == "bfloat16"

    def test_recorder_is_thread_local(self):
        import threading
        main = static.Program()
        done = threading.Event()

        def other_thread():
            # dispatches ops while the main thread's guard is open
            t = paddle.to_tensor(np.ones((2, 2), np.float32))
            _ = t * 3 + 1
            done.set()

        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            th = threading.Thread(target=other_thread)
            th.start()
            th.join()
            _ = x + 1
        assert done.is_set()
        names = [op.name for op in main.global_block().ops]
        assert names == ["add"]  # none of the other thread's ops leaked


    def test_placeholder_id_pinned_under_no_grad(self):
        # data() placeholders must survive GC so their id cannot be
        # recycled into a fake feed slot
        main = static.Program()
        with static.program_guard(main):
            with paddle.no_grad():
                y = static.data("x", [2, 2], "float32") + 1.0
                for _ in range(8):
                    _t = paddle.to_tensor(np.full((2, 2), 103.0,
                                                  np.float32))
                    y = y + 0.0 * _t
        (out,) = static.Executor().run(
            main, feed={"x": np.full((2, 2), 103.0, np.float32)},
            fetch_list=[y])
        np.testing.assert_allclose(out, 104.0)

    def test_extend_program_recompiles(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            y = x + 1
        exe = static.Executor()
        feed = {"x": np.array([1.0, 2.0], np.float32)}
        np.testing.assert_allclose(exe.run(main, feed, [y])[0], [2, 3])
        with static.program_guard(main):
            w = paddle.to_tensor(np.array([10.0, 20.0], np.float32))
            z = y + w
        # original fetch still works after extension (new capture)
        np.testing.assert_allclose(exe.run(main, feed, [y])[0], [2, 3])
        np.testing.assert_allclose(exe.run(main, feed, [z])[0], [12, 23])

    def test_feed_dtype_declaration_honored(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            y = x / 2
        (out,) = static.Executor().run(
            main, feed={"x": np.array([1, 3], np.int32)}, fetch_list=[y])
        np.testing.assert_allclose(out, [0.5, 1.5])  # cast, not int div
