"""Importable compile targets for the persistent-compilation-cache tests.

Lives in a module (not a test body) so the function fingerprint and
``module:qualname`` warm target resolve identically in the pytest
process and in subprocesses — the cross-process cache-hit proof depends
on both deriving the same content key.
"""
import numpy as np

import paddle_tpu as paddle


def affine_fn(x, y):
    return paddle.ops.matmul(x, y) + 1.0


def breaking_fn(x):
    """Graph-breaks mid-function (SOT segment-cache exercise)."""
    y = paddle.ops.matmul(x, x)
    n = float(y.numpy().sum())   # concretization -> segment flush
    scale = 1.0 if n >= 0 else 2.0
    return y * scale + 1.0


def example_inputs():
    x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(4, 8) / 32)
    y = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(8, 3) / 24)
    return x, y
