"""Must-flag: every TPU75x alias hazard in one record stream —

* TPU753: an in-place write through a ``getitem`` VIEW whose base is
  read afterwards (functional XLA arrays never update the base);
* TPU752: a write into a buffer already donated to the compiled step;
* TPU751: a statically-overlapping read of the pre-write value;
* TPU754: a data-dependent (regionless) write whose pre-write value is
  still read.
"""
EXPECT = ["TPU751", "TPU752", "TPU753", "TPU754"]


def build():
    from paddle_tpu.static import verifier

    R = verifier.Record
    f32 = "float32"
    records = [
        # v2 = view of base v1; write through it while v1 is read later
        R("getitem", in_ids=[1], out_ids=[2],
          in_shapes=[(8, 8)], out_shapes=[(4, 8)],
          in_dtypes=[f32], out_dtypes=[f32],
          attrs={"read_region": ((0, 4), (0, 8))}),
        R("setitem", in_ids=[2, 10], out_ids=[3],
          in_shapes=[(4, 8), (2, 8)], out_shapes=[(4, 8)],
          in_dtypes=[f32, f32], out_dtypes=[f32],
          attrs={"write_region": ((0, 2), (0, 8))}),        # TPU753
        R("sum", in_ids=[1], out_ids=[4],
          in_shapes=[(8, 8)], out_shapes=[()],
          in_dtypes=[f32], out_dtypes=[f32]),
        # write into the donated entry v5
        R("setitem", in_ids=[5, 10], out_ids=[6],
          in_shapes=[(8, 8), (2, 8)], out_shapes=[(8, 8)],
          in_dtypes=[f32, f32], out_dtypes=[f32],
          attrs={"write_region": ((0, 2), (0, 8))}),        # TPU752
        # overwrite rows [0,2) of v7, then read rows [1,3): overlap
        R("setitem", in_ids=[7, 10], out_ids=[8],
          in_shapes=[(8, 8), (2, 8)], out_shapes=[(8, 8)],
          in_dtypes=[f32, f32], out_dtypes=[f32],
          attrs={"write_region": ((0, 2), (0, 8))}),        # TPU751
        R("getitem", in_ids=[7], out_ids=[9],
          in_shapes=[(8, 8)], out_shapes=[(2, 8)],
          in_dtypes=[f32], out_dtypes=[f32],
          attrs={"read_region": ((1, 3), (0, 8))}),
        # tensor-indexed write: region unprovable, pre-write value read
        R("setitem", in_ids=[11, 10], out_ids=[12],
          in_shapes=[(8, 8), (2, 8)], out_shapes=[(8, 8)],
          in_dtypes=[f32, f32], out_dtypes=[f32]),          # TPU754
        R("mean", in_ids=[11], out_ids=[13],
          in_shapes=[(8, 8)], out_shapes=[()],
          in_dtypes=[f32], out_dtypes=[f32]),
    ]
    return verifier.check(records, fetch_ids=[4, 9, 13],
                          donated_ids=[5],
                          label="flag_alias_chain")
