"""Must-flag: a train step whose body host-reads a parameter marked
for donation — after the donating compiled call, that buffer holds
nothing; the read the round-17 runtime registry would only catch in
production is flagged statically here. TPU601."""
import numpy as np

EXPECT = ["TPU601"]


def build():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.static import verifier

    paddle.seed(11)
    lin = nn.Linear(8, 8)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))

    def step(inp):
        out = lin(inp).sum()
        _snapshot = lin.weight.numpy()        # stale after donation
        return out

    return verifier.audit_step(step, (x,),
                               donate_params=list(lin.parameters()),
                               label="flag_donated_read")
