"""Must-flag: both arms run the same collectives but in OPPOSITE
order — ranks taking different arms cross-match transports (A's
all_reduce pairs with B's broadcast). TPU404."""
import numpy as np

EXPECT = ["TPU404"]


def build():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import static
    from paddle_tpu.static import verifier

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 8], "float32")

        def reduce_then_bcast():
            a = dist.all_reduce(x * 2.0)
            return dist.broadcast(a, 0)

        def bcast_then_reduce():
            a = dist.broadcast(x * 3.0, 0)
            return dist.all_reduce(a)

        out = static.nn.cond(paddle.to_tensor(True), reduce_then_bcast,
                             bcast_then_reduce)
    return verifier.check(prog, fetch_ids=[id(out)],
                          label="flag_branch_collective_order")
