"""Must-flag: static peak under capacity but >= 90% of it — the TPU902
pressure warning (the program compiles, but one fragmentation event or
batch bump OOMs it). Peak here is 12 MiB against a 13 MB cap (~97%)."""
EXPECT = ["TPU902"]


def build():
    from paddle_tpu.static import verifier

    R = verifier.Record
    records = [
        R("matmul", in_ids=[1, 2], out_ids=[3],
          in_shapes=[(1024, 1024), (1024, 1024)],
          out_shapes=[(1024, 1024)],
          in_dtypes=["float32", "float32"], out_dtypes=["float32"]),
    ]
    return verifier.check(records, fetch_ids=[3],
                          capacity_bytes=13e6,
                          label="flag_memory_pressure")
