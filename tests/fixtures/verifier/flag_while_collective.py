"""Must-flag (warn severity): a collective under a data-dependent
while_loop — per-rank predicates can disagree on the trip count, so
ranks run different collective COUNTS. TPU401."""
import numpy as np

EXPECT = ["TPU401"]


def build():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import static
    from paddle_tpu.static import verifier

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4], "float32")
        i0 = paddle.to_tensor(0)

        def keep(i, v):
            return i < 3

        def body(i, v):
            return [i + 1, dist.all_reduce(v)]

        _i, out = static.nn.while_loop(keep, body, [i0, x])
    return verifier.check(prog, fetch_ids=[id(out)],
                          label="flag_while_collective")
