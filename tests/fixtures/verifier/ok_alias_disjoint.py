"""Must-NOT-flag: a region write followed by a read of the pre-write
value whose static regions are PROVABLY disjoint (rows [0,2) written,
rows [4,6) read) — the precision that separates the TPU75x alias pass
from the whole-buffer TPU704 check, which would have flagged this."""
EXPECT = []


def build():
    from paddle_tpu.static import verifier

    R = verifier.Record
    f32 = "float32"
    records = [
        R("setitem", in_ids=[1, 5], out_ids=[2],
          in_shapes=[(8, 8), (2, 8)], out_shapes=[(8, 8)],
          in_dtypes=[f32, f32], out_dtypes=[f32],
          attrs={"write_region": ((0, 2), (0, 8))}),
        R("getitem", in_ids=[1], out_ids=[3],
          in_shapes=[(8, 8)], out_shapes=[(2, 8)],
          in_dtypes=[f32], out_dtypes=[f32],
          attrs={"read_region": ((4, 6), (0, 8))}),
        R("relu", in_ids=[3], out_ids=[4],
          in_shapes=[(2, 8)], out_shapes=[(2, 8)],
          in_dtypes=[f32], out_dtypes=[f32]),
    ]
    return verifier.check(records, fetch_ids=[4],
                          label="ok_alias_disjoint")
