"""Must-NOT-flag: a cleanly sharded program on the same (data, tp)
mesh — every sharded dim divides its axes, no Partial leaks (the
contracted dim stays replicated), every op carries a rule."""
import numpy as np

EXPECT = []


def build():
    import paddle_tpu as paddle
    from jax.sharding import PartitionSpec as P
    from paddle_tpu import static
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.static import verifier

    mesh = mesh_mod.build_mesh(dict(data=2, tp=4))
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [8, 16], "float32")
        w = paddle.to_tensor(np.ones((16, 16), np.float32))
        y = paddle.matmul(x, w)               # k replicated: no Partial
        z = y + 1.0
    return verifier.check(
        prog, mesh=mesh,
        in_specs={"x": P("data", None)},      # 8 % 2 == 0
        param_specs=lambda t: P(None, "tp"),  # column-parallel: 16 % 4
        fetch_ids=[id(z)], label="ok_sharding")
