"""Must-NOT-flag: four ranks whose program dumps agree op-for-op —
collective sequence, content, order, and the surrounding op stream.
The static diff over a clean data-parallel launch stays silent."""
EXPECT = []


def _op(seq, name, collective):
    return {"seq": seq, "name": name, "attrs": {"group": 0},
            "in_shapes": [[4, 4]], "out_shapes": [[4, 4]],
            "in_dtypes": ["float32"], "out_dtypes": ["float32"],
            "loc": "", "collective": collective}


def build():
    from paddle_tpu.static import crossrank

    ops = [_op(0, "matmul", False), _op(1, "all_reduce", True),
           _op(2, "relu", False), _op(3, "all_gather", True)]
    dumps = {
        r: {"format": crossrank.FORMAT, "rank": r, "world": 4,
            "programs": [{"label": "step", "ops": ops}]}
        for r in range(4)
    }
    return crossrank.diff_programs(dumps)
