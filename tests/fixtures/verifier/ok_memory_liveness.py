"""Must-NOT-flag: an elementwise chain whose SUM of activations busts
the capacity but whose liveness PEAK fits comfortably — the precision
the interval model buys over the old every-activation-resident
estimate. Ten 1 MiB intermediates (10.5 MiB summed with the entry)
against a 6 MB cap; at most two chain buffers are ever live (~3 MiB
peak with the entry)."""
EXPECT = []


def build():
    from paddle_tpu.static import verifier

    R = verifier.Record
    shape, dt = (512, 512), "float32"
    records = []
    vid = 1
    for i in range(10):
        records.append(R("relu", in_ids=[vid], out_ids=[vid + 1],
                         in_shapes=[shape], out_shapes=[shape],
                         in_dtypes=[dt], out_dtypes=[dt]))
        vid += 1
    return verifier.check(records, fetch_ids=[vid],
                          capacity_bytes=6e6,
                          label="ok_memory_liveness")
