"""Must-flag: a pipeline stage partition whose cross-stage send/recv
contract was tampered after the cut — the static desync family.

Stage 1's recv expects the wrong shape (TPU802) and claims the wrong
transfer sequence number (TPU803); stage 2 dropped a recv entirely so
the boundary counts disagree (TPU801). This is exactly the runtime
failure mode of a hand-edited stage program: the sender ships
activations the receiver re-interprets — XLA would type-check nothing
across the processes, the desync surfaces as garbage loss at best.
TPU801 + TPU802 + TPU803."""

EXPECT = ["TPU801", "TPU802", "TPU803"]


def build():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import static
    from paddle_tpu.distributed.pipeline import partition_program
    from paddle_tpu.static import verifier

    paddle.seed(7)
    blocks = []
    for _ in range(3):
        blocks += [nn.Linear(8, 8), nn.GELU()]
    model = nn.Sequential(*blocks)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 8], "float32")
        loss = (model(x) ** 2).mean()
    part = partition_program(prog, 3, strategy="uniform",
                             fetch_ids=[id(loss)])
    stages = [list(recs) for recs in part.stage_records()]

    # stage 1: first recv re-declares the boundary value's shape and
    # transfer order — content desync (TPU802) + order desync (TPU803)
    for rec in stages[1]:
        if rec.name == "recv":
            rec.out_shapes = ((4, 9),)
            rec.attrs["seq"] = 5
            break
    # stage 2: drop its recv — the 1->2 boundary count disagrees
    stages[2] = [r for r in stages[2] if r.name != "recv"]
    return verifier.check_stages(stages, label="flag_stage_desync")
