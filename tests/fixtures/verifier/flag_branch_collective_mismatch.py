"""Must-flag: cond arms trace DIFFERENT collective sequences — the
static desync (rank A takes the all-reducing arm, rank B the silent
one; A blocks inside the transport forever). TPU402."""
import numpy as np

EXPECT = ["TPU402"]


def build():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import static
    from paddle_tpu.static import verifier

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 8], "float32")

        def with_reduce():
            return dist.all_reduce(x * 2.0)

        def without():
            return x * 3.0

        out = static.nn.cond(paddle.to_tensor(True), with_reduce,
                             without)
    return verifier.check(prog, fetch_ids=[id(out)],
                          label="flag_branch_collective_mismatch")
