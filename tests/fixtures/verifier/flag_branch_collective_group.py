"""Must-flag: the arms run the same collective SEQUENCE but with
different payload content (shape here; group/axes are compared the
same way) — the transports pair positionally and then mismatch, the
exact content-divergence ``flight.diff_ranks`` names at runtime.
TPU403."""
import numpy as np

EXPECT = ["TPU403"]


def build():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import static
    from paddle_tpu.static import verifier

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 8], "float32")

        def full_then_sum():
            # collective over the (4, 8) activations
            return dist.all_reduce(x * 2.0).sum()

        def sum_then_reduce():
            # collective over the () scalar — same op, different content
            return dist.all_reduce((x * 3.0).sum())

        out = static.nn.cond(paddle.to_tensor(True), full_then_sum,
                             sum_then_reduce)
    return verifier.check(prog, fetch_ids=[id(out)],
                          label="flag_branch_collective_group")
