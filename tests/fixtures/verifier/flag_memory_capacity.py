"""Must-flag: a program whose STATIC liveness peak exceeds the HBM
capacity — TPU901 fires at compile time, before XLA ever sees the
program (strict mode raises). The matmul holds both 4 MiB operands and
the 4 MiB output live at once; capacity is 1 MB."""
EXPECT = ["TPU901"]


def build():
    from paddle_tpu.static import verifier

    R = verifier.Record
    records = [
        R("matmul", in_ids=[1, 2], out_ids=[3],
          in_shapes=[(1024, 1024), (1024, 1024)],
          out_shapes=[(1024, 1024)],
          in_dtypes=["float32", "float32"], out_dtypes=["float32"]),
        R("relu", in_ids=[3], out_ids=[4],
          in_shapes=[(1024, 1024)], out_shapes=[(1024, 1024)],
          in_dtypes=["float32"], out_dtypes=["float32"]),
    ]
    return verifier.check(records, fetch_ids=[4],
                          capacity_bytes=1e6,
                          label="flag_memory_capacity")
