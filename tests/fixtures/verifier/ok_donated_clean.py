"""Must-NOT-flag: the same donated step WITHOUT the host read — state
flows through the step's returns, exactly how a donating caller must
read it back."""
import numpy as np

EXPECT = []


def build():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.static import verifier

    paddle.seed(11)
    lin = nn.Linear(8, 8)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))

    def step(inp):
        return lin(inp).sum()

    return verifier.audit_step(step, (x,),
                               donate_params=list(lin.parameters()),
                               label="ok_donated_clean")
