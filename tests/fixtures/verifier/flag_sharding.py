"""Must-flag: the sharding/mesh pre-flight (TPU5xx) over a real
recorded Program on a (data, tp) mesh —

* the feed's batch dim (6) is sharded over the 4-way tp axis: 6 % 4
  != 0, the constraint silently drops or pads (TPU501);
* a matmul whose CONTRACTED dim is sharded emits a Partial
  (reduce-pending) value that a plain add then consumes without any
  reduction (TPU503);
* an op with no sharding rule sits on the hot path and replicates
  everything downstream (TPU502 — plus TPU700: the unregistered name
  is exactly why it has no rule).
"""
import numpy as np

EXPECT = ["TPU501", "TPU502", "TPU503", "TPU700"]


def build():
    import paddle_tpu as paddle
    from jax.sharding import PartitionSpec as P
    from paddle_tpu import static
    from paddle_tpu.core import dispatch
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.static import verifier

    mesh = mesh_mod.build_mesh(dict(data=2, tp=4))
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [6, 8], "float32")       # 6 % 4 != 0
        w = paddle.to_tensor(np.ones((8, 8), np.float32))
        y = paddle.matmul(x, w)                       # k sharded below
        z = y + 1.0                                   # consumes Partial
        out = dispatch.call("no_rule_op_for_fixture",
                            lambda a: a * 2.0, [z])   # replicate-warn
    return verifier.check(
        prog, mesh=mesh,
        # dim 0 of x over tp (divisibility violation) AND the matmul's
        # contracted dim sharded via the param spec (Partial source)
        in_specs={"x": P("tp", None)},
        param_specs=lambda t: P("tp", None),
        fetch_ids=[id(out)], label="flag_sharding")
