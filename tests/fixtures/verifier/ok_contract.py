"""Must-NOT-flag: a well-formed record list — registered ops,
broadcastable shapes, dtype-preserving math, every value consumed or
fetched. The contract pass must stay silent (and so must every other
pass)."""
EXPECT = []


def build():
    from paddle_tpu.static import verifier

    R = verifier.Record
    records = [
        R("matmul", in_ids=[1, 2], out_ids=[3],
          in_shapes=[(4, 8), (8, 8)], out_shapes=[(4, 8)],
          in_dtypes=["float32", "float32"], out_dtypes=["float32"]),
        R("add", in_ids=[3, 2], out_ids=[4],
          in_shapes=[(4, 8), (8,)], out_shapes=[(4, 8)],
          in_dtypes=["float32", "float32"], out_dtypes=["float32"]),
        R("gelu", in_ids=[4], out_ids=[5],
          in_shapes=[(4, 8)], out_shapes=[(4, 8)],
          in_dtypes=["float32"], out_dtypes=["float32"]),
    ]
    return verifier.check(records, fetch_ids=[5],
                          in_specs={1: None, 2: None},
                          label="ok_contract")
