"""Must-not-flag: a cost-partitioned pipeline whose stage programs
carry a consistent cross-stage contract — every boundary value's send
pairs with a recv of the same shape/dtype, in the same transfer order,
between adjacent stages. The partitioner emits this by construction;
the fixture pins that check_stages stays quiet on it."""

EXPECT = []


def build():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import static
    from paddle_tpu.distributed.pipeline import partition_program
    from paddle_tpu.static import verifier

    paddle.seed(7)
    blocks = []
    for _ in range(4):
        blocks += [nn.Linear(8, 8), nn.GELU()]
    model = nn.Sequential(*blocks)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 8], "float32")
        loss = (model(x) ** 2).mean()
    part = partition_program(prog, 2, fetch_ids=[id(loss)])
    return verifier.check_stages(part.stage_records(),
                                 label="ok_stage_match")
