"""Must-flag: every TPU45x cross-rank divergence over synthetic
per-rank program dumps —

* TPU451: rank 1 runs an extra all_reduce (collective membership);
* TPU452: same position, different group content;
* TPU453: same collectives, swapped order;
* TPU454: identical collectives but divergent non-collective op
  streams (a rank-dependent branch in the traced step).
"""
EXPECT = ["TPU451", "TPU452", "TPU453", "TPU454"]


def _op(seq, name, group=0, shape=(4, 4), collective=True):
    return {"seq": seq, "name": name, "attrs": {"group": group},
            "in_shapes": [list(shape)], "out_shapes": [list(shape)],
            "in_dtypes": ["float32"], "out_dtypes": ["float32"],
            "loc": "", "collective": collective}


def _prog(label, names, groups=None, extra_op=None):
    groups = groups or [0] * len(names)
    ops = [_op(i, n, g) for i, (n, g) in enumerate(zip(names, groups))]
    if extra_op is not None:
        ops.append(dict(extra_op, seq=len(ops)))
    return {"label": label, "ops": ops}


def build():
    from paddle_tpu.static import crossrank

    mm = _op(0, "matmul", collective=False)
    rl = _op(0, "relu", collective=False)
    dumps = {
        0: {"format": crossrank.FORMAT, "rank": 0, "world": 2,
            "programs": [
                _prog("membership", ["all_reduce", "all_gather"]),
                _prog("content", ["all_reduce", "all_gather"]),
                _prog("order", ["all_reduce", "all_gather"]),
                _prog("opstream", ["all_reduce"], extra_op=mm),
            ]},
        1: {"format": crossrank.FORMAT, "rank": 1, "world": 2,
            "programs": [
                _prog("membership",
                      ["all_reduce", "all_reduce", "all_gather"]),
                _prog("content", ["all_reduce", "all_gather"],
                      groups=[0, 3]),
                _prog("order", ["all_gather", "all_reduce"]),
                _prog("opstream", ["all_reduce"], extra_op=rl),
            ]},
    }
    return crossrank.diff_programs(dumps)
