"""Must-NOT-flag: both cond arms trace the SAME collective sequence
(same op, same group identity, same payload shape) — whichever arm a
rank takes, the transports pair up."""
import numpy as np

EXPECT = []


def build():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import static
    from paddle_tpu.static import verifier

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 8], "float32")

        def arm_a():
            return dist.all_reduce(x * 2.0)

        def arm_b():
            return dist.all_reduce(x * 3.0)

        out = static.nn.cond(paddle.to_tensor(False), arm_a, arm_b)
    return verifier.check(prog, fetch_ids=[id(out)],
                          label="ok_branch_collective_match")
