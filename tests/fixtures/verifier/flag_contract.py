"""Must-flag: the contract pass (TPU7xx) over a hand-built record
list — the IR shape every compile path hands the verifier. One
program exercising every contract code:

* an op name the registry has never seen (TPU700);
* a broadcast-illegal elementwise add (TPU701) — recorded programs
  can't produce this (they executed), but fusion rewrites and
  synthetic IRs can;
* a silent f32 -> bf16 downcast outside the AMP white-list (TPU702,
  the round-15 fusion-review bug class);
* a dead op whose outputs nothing consumes or fetches (TPU703);
* an in-place op whose target is read again later — the replay env
  serves the stale pre-mutation value (TPU704);
* a fetch of a value no op produces (TPU705).
"""
EXPECT = ["TPU700", "TPU701", "TPU702", "TPU703", "TPU704", "TPU705"]


def build():
    from paddle_tpu.static import verifier

    R = verifier.Record
    records = [
        # v1, v2 feeds; v3 = mystery_op(v1)           -> TPU700
        R("mystery_op", in_ids=[1], out_ids=[3],
          in_shapes=[(4, 8)], out_shapes=[(4, 8)],
          loc="fixture.py:1"),
        # v4 = add(v3, v2) with non-broadcast shapes  -> TPU701
        R("add", in_ids=[3, 2], out_ids=[4],
          in_shapes=[(4, 8), (3, 5)], out_shapes=[(4, 8)],
          loc="fixture.py:2"),
        # v5 = multiply(v4, v2): f32 in, bf16 out     -> TPU702
        R("multiply", in_ids=[4, 2], out_ids=[5],
          in_shapes=[(4, 8), (4, 8)], out_shapes=[(4, 8)],
          in_dtypes=["float32", "float32"], out_dtypes=["bfloat16"],
          loc="fixture.py:3"),
        # v6 = exp(v5): nothing ever reads v6         -> TPU703
        R("exp", in_ids=[5], out_ids=[6],
          in_shapes=[(4, 8)], out_shapes=[(4, 8)],
          loc="fixture.py:4"),
        # abs_(v5) mutates v5 in place...             -> TPU704
        R("abs_", in_ids=[5], out_ids=[7],
          in_shapes=[(4, 8)], out_shapes=[(4, 8)],
          loc="fixture.py:5"),
        # ...but v5's pre-mutation value is read here
        R("add", in_ids=[5, 7], out_ids=[8],
          in_shapes=[(4, 8), (4, 8)], out_shapes=[(4, 8)],
          loc="fixture.py:6"),
    ]
    # fetch v8 plus v99, which nothing produces       -> TPU705
    return verifier.check(records, fetch_ids=[8, 99],
                          in_specs={1: None, 2: None},
                          label="flag_contract")
