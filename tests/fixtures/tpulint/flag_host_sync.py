"""Must-flag corpus for pass 1 (TPU1xx trace-safety).

Every line carrying an ``# expect: CODE`` marker must be flagged with
exactly those codes; every other line must stay clean.
"""
import numpy as np

from paddle_tpu.core import dispatch
from paddle_tpu.core.tensor import Tensor, as_tensor


def leaky_mean(x):
    t = as_tensor(x)
    m = t.mean()
    return float(m)  # expect: TPU103


def numpy_roundtrip(t: Tensor):
    host = np.asarray(t._data)  # expect: TPU104
    return host


def scalarize(t: Tensor):
    a = t.numpy()  # expect: TPU101
    b = t.item()  # expect: TPU102
    c = t.tolist()  # expect: TPU102
    return a, b, c


def tensor_branch(x, y):
    t = as_tensor(x)
    if t.sum() > 0:  # expect: TPU105
        return y
    while t.any():  # expect: TPU106
        t = t - 1
    return t


def lowering_host_math(x):
    # f is handed to dispatch.call, so its parameters are tracers: host
    # constructs inside it break the one-XLA-program guarantee
    def f(a, b):
        s = np.sqrt(a)  # expect: TPU104
        if b.sum() > 0:  # expect: TPU105
            return s
        return int(s[0])  # expect: TPU103

    return dispatch.call("bad_op", f, [x, x])


def host_dp(t: Tensor):
    # the loss.py edit_distance shape: tensor data pulled through numpy,
    # then consumed as a python scalar several statements later
    a = np.asarray(t._data)  # expect: TPU104
    dp = np.arange(4)
    dp[1] = dp[0] + (a[0] != a[1])
    return float(dp[3])  # expect: TPU103
