"""Must-flag corpus for pass 2 (TPU2xx tracer-leak)."""
from paddle_tpu.core.tensor import Tensor, as_tensor

_CACHE = {}
_LAST = None


def stash_global(x):
    global _LAST
    t = as_tensor(x)
    _LAST = t  # expect: TPU201
    return t


def stash_container(x):
    t = as_tensor(x)
    _CACHE["last"] = t  # expect: TPU201
    return t


def bad_default(x, acc=[]):  # expect: TPU202
    acc.append(x)
    return acc


def tensor_key(t: Tensor):
    local = {}
    local[t] = 1  # expect: TPU203
    return local
