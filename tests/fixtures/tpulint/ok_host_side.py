"""Must-NOT-flag corpus: legitimately host-side framework idioms.

Modeled on core/dispatch.py internals (quiet_scope / branch-trace
bookkeeping), static-metadata checks, and plain-numpy host math — none of
which touch live tensor values, so tpulint must stay silent here.
"""
import threading

import numpy as np

from paddle_tpu.core.tensor import Tensor, as_tensor

_state = threading.local()


def enter_branch_trace(bt):
    # control-flow capture bookkeeping swaps a python object, never tensor
    # data (mirrors core/dispatch.py enter_branch_trace)
    prev = getattr(_state, "branch_trace", None)
    _state.branch_trace = bt
    return prev


class quiet_scope:
    def __enter__(self):
        self._prev = getattr(_state, "quiet", False)
        _state.quiet = True
        return self

    def __exit__(self, *exc):
        _state.quiet = self._prev
        return False


def static_metadata(t: Tensor):
    # shape/dtype/ndim are trace-static attributes, not tensor values
    if t.ndim > 2 or t.shape[0] == 0:
        return str(t.dtype)
    return "ok"


def none_check(t):
    x = as_tensor(t)
    if x is None:
        return 0
    return x


def host_math(values):
    # plain numpy over host data — no tensor anywhere in the dataflow
    arr = np.asarray(values)
    return float(np.sqrt(arr).sum())


def metadata_keyed_cache(t: Tensor, cache):
    # caching keyed on STATIC metadata is the sanctioned pattern; the
    # container holding tensors does not make membership data-dependent
    key = (tuple(t.shape), str(t.dtype))
    if key in cache:
        return cache[key]
    cache[key] = 1
    return 1


def suppressed_sync(t: Tensor):
    # an explicit, justified host boundary is opt-out-able per line
    return t.numpy()  # tpulint: disable=TPU101 — documented host API
