"""Must-NOT-flag corpus: re-binding patterns that used to (or could)
false-positive the taint engine.

Covers the FP classes fixed alongside the program-verifier round:

* a loop/comprehension target re-bound over an UNTAINTED iterable after
  the same name held a tensor (the two-pass back-edge union used to
  leak the stale taint into later augmented assignments / predicates);
* augmented assignment on such a re-bound counter;
* walrus assignment re-binding a name to host metadata;
* try/finally re-binds clearing a tensor-held name;
* bare truthiness of a container that HOLDS tensors (an emptiness
  check — ``bool()`` never touches the elements);
* branching on a cache entry that stores ``jax.jit`` wrappers
  (callables, not device data).

Every construct here is trace-safe; the analyzer must emit nothing.
"""
import jax
import jax.numpy as jnp


def aug_assign_after_loop_rebind(ts):
    out = []
    for t in jnp.stack(ts):          # t: tensor loop variable
        out.append(t)
    n = 0
    for t in range(3):               # t re-bound over host ints
        n += t                       # augmented assign on the re-bind
    if n > 2:                        # predicate on the host counter
        return len(out)
    return 0


def comprehension_shadow(ts):
    rows = jnp.stack(ts)
    picked = [r for r in rows]       # r: tensor comprehension target
    small = [r for r in range(4)]    # r shadowed over host ints
    total = 0
    total += len(small)
    if total:
        return picked
    return []


def walrus_rebind(t, names):
    total = jnp.sum(t)               # total holds a tensor...
    if (total := len(names)) > 0:    # ...walrus re-binds it to an int
        return total
    while (k := t.ndim):             # static metadata walrus predicate
        return k
    return 0


def try_finally_rebind(t):
    acc = jnp.sum(t)                 # tensor-held before the try
    try:
        out = acc + 1
    finally:
        acc = None                   # finally clears the binding
    if acc:                          # predicate on the cleared name
        return None
    return out


def container_emptiness(ps, state_dict):
    params = [p for p in ps if p is not None]
    if not params:                   # emptiness check on a tensor list
        return None
    st = {}
    for name in state_dict:
        st[name] = jnp.asarray(state_dict[name])
    if st:                           # emptiness check on a tensor dict
        return st
    return params


def jit_cache_entry(fn, key):
    cache = {}
    cache[key] = jax.jit(fn)         # stores a CALLABLE, not data
    ent = cache.get(key)
    if ent is None:
        return None
    elif ent:                        # truthiness of the wrapper is safe
        return ent
    return None
