"""Tensor-parallel (fleet mpu) tests on the 8-device virtual mesh.

Mirrors the reference TP test (reference:
test/collective/fleet/hybrid_parallel_mp_layers.py — parallel layers must
match the single-device computation numerically).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.mpu import raw_ops
from paddle_tpu.distributed.fleet import sequence_parallel as sp


@pytest.fixture(autouse=True)
def _mesh():
    prev = mesh_mod._global_mesh
    mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 4, "mp": 2}))
    yield
    mesh_mod._global_mesh = prev


# ------------------------------------------------------------------ raw ops
class TestRawOps:
    def _mesh1d(self):
        return mesh_mod.get_mesh()

    def test_identity_bwd_allreduce(self):
        mesh = self._mesh1d()
        from paddle_tpu.distributed.communication.collective import shard_map

        def loss(x):
            def body(xl):
                y = raw_ops.identity(xl, "mp")
                # each shard scales differently -> grads differ per shard
                r = jax.lax.axis_index("mp").astype(jnp.float32) + 1.0
                return jnp.sum(y * r)
            smapped = shard_map(body, mesh=mesh, in_specs=(P(),),
                                out_specs=P())
            return smapped(x).sum()

        x = jnp.ones((4,))
        g = jax.grad(loss)(x)
        # bwd allreduce: sum of per-shard scales 1+2 = 3
        np.testing.assert_allclose(np.asarray(g), 3.0 * np.ones(4), rtol=1e-6)

    def test_allreduce_bwd_identity(self):
        mesh = self._mesh1d()
        from paddle_tpu.distributed.communication.collective import shard_map

        def loss(x):
            def body(xl):
                return raw_ops.all_reduce(xl, "mp")
            # keep the output sharded: each shard emits its (identical)
            # reduced copy, so the global result is the tiled concat
            y = shard_map(body, mesh=mesh, in_specs=(P("mp"),),
                          out_specs=P("mp"))(x)
            return jnp.sum(y)

        x = jnp.arange(8.0)
        y, g = jax.value_and_grad(loss)(x)
        # each of 2 shards holds the elementwise psum [4,6,8,10]; sum = 56
        assert float(y) == pytest.approx(56.0)
        np.testing.assert_allclose(np.asarray(g), np.ones(8), rtol=1e-6)

    def test_allgather_reducescatter_pair(self):
        mesh = self._mesh1d()
        from paddle_tpu.distributed.communication.collective import shard_map

        def rt(x):
            def body(xl):
                full = raw_ops.all_gather(xl, "mp", 0)
                return raw_ops.reduce_scatter(full, "mp", 0) / 2.0
            return shard_map(body, mesh=mesh, in_specs=(P("mp"),),
                             out_specs=P("mp"))(x)

        x = jnp.arange(8.0)
        y = rt(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-6)
        g = jax.grad(lambda a: jnp.sum(rt(a) * jnp.arange(8.0)))(x)
        np.testing.assert_allclose(np.asarray(g), np.arange(8.0), rtol=1e-6)


# ------------------------------------------------------------- layer parity
def _copy_linear(dst, w, b):
    dst.weight.set_value(w)
    if dst.bias is not None and b is not None:
        dst.bias.set_value(b)


class TestTPLayers:
    def test_column_parallel_linear_matches_serial(self):
        w = np.random.randn(16, 24).astype(np.float32)
        b = np.random.randn(24).astype(np.float32)
        x = np.random.randn(4, 16).astype(np.float32)

        serial = nn.Linear(16, 24)
        _copy_linear(serial, w, b)
        col = fleet.ColumnParallelLinear(16, 24, has_bias=True,
                                         gather_output=True)
        _copy_linear(col, w, b)
        # the weight is actually sharded over mp
        assert "mp" in str(col.weight._data.sharding.spec)

        xs = paddle.to_tensor(x, stop_gradient=False)
        xc = paddle.to_tensor(x, stop_gradient=False)
        ys, yc = serial(xs), col(xc)
        np.testing.assert_allclose(yc.numpy(), ys.numpy(), rtol=2e-5,
                                   atol=2e-5)
        ys.backward(paddle.to_tensor(np.ones_like(ys.numpy())))
        yc.backward(paddle.to_tensor(np.ones_like(yc.numpy())))
        np.testing.assert_allclose(col.weight.grad.numpy(),
                                   serial.weight.grad.numpy(),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(xc.grad.numpy(), xs.grad.numpy(),
                                   rtol=2e-5, atol=2e-5)

    def test_row_parallel_linear_matches_serial(self):
        w = np.random.randn(24, 16).astype(np.float32)
        b = np.random.randn(16).astype(np.float32)
        x = np.random.randn(4, 24).astype(np.float32)

        serial = nn.Linear(24, 16)
        _copy_linear(serial, w, b)
        row = fleet.RowParallelLinear(24, 16, has_bias=True,
                                      input_is_parallel=False)
        _copy_linear(row, w, b)

        xs = paddle.to_tensor(x, stop_gradient=False)
        xr = paddle.to_tensor(x, stop_gradient=False)
        ys, yr = serial(xs), row(xr)
        np.testing.assert_allclose(yr.numpy(), ys.numpy(), rtol=2e-5,
                                   atol=2e-5)
        ys.backward(paddle.to_tensor(np.ones_like(ys.numpy())))
        yr.backward(paddle.to_tensor(np.ones_like(yr.numpy())))
        np.testing.assert_allclose(row.weight.grad.numpy(),
                                   serial.weight.grad.numpy(),
                                   rtol=2e-5, atol=2e-5)

    def test_mlp_col_row_stack(self):
        """Column(gather_output=False) -> Row(input_is_parallel=True): the
        canonical Megatron block, no comm between the two matmuls."""
        w1 = np.random.randn(8, 32).astype(np.float32)
        w2 = np.random.randn(32, 8).astype(np.float32)
        x = np.random.randn(4, 8).astype(np.float32)

        col = fleet.ColumnParallelLinear(8, 32, has_bias=False,
                                         gather_output=False)
        row = fleet.RowParallelLinear(32, 8, has_bias=False,
                                      input_is_parallel=True)
        col.weight.set_value(w1)
        row.weight.set_value(w2)

        xt = paddle.to_tensor(x, stop_gradient=False)
        y = row(F.gelu(col(xt)))
        ref = F.gelu(paddle.to_tensor(x) @ paddle.to_tensor(w1)) \
            @ paddle.to_tensor(w2)
        np.testing.assert_allclose(y.numpy(), ref.numpy(), rtol=2e-5,
                                   atol=2e-5)
        y.backward(paddle.to_tensor(np.ones_like(y.numpy())))
        assert col.weight.grad is not None and row.weight.grad is not None

    def test_vocab_parallel_embedding(self):
        w = np.random.randn(32, 8).astype(np.float32)
        ids = np.random.randint(0, 32, (4, 6)).astype(np.int64)
        serial = nn.Embedding(32, 8)
        serial.weight.set_value(w)
        par = fleet.VocabParallelEmbedding(32, 8)
        par.weight.set_value(w)
        assert "mp" in str(par.weight._data.sharding.spec)

        ys = serial(paddle.to_tensor(ids))
        yp = par(paddle.to_tensor(ids))
        np.testing.assert_allclose(yp.numpy(), ys.numpy(), rtol=2e-5,
                                   atol=2e-5)

    def test_parallel_cross_entropy(self):
        logits = np.random.randn(6, 16).astype(np.float32)
        label = np.random.randint(0, 16, (6, 1)).astype(np.int64)
        lt = paddle.to_tensor(logits, stop_gradient=False)
        # shard the class dim like a gather_output=False lm head would
        from paddle_tpu.distributed.fleet.mpu import mp_ops
        lt_sharded = mp_ops._c_split(lt, axis=-1)
        loss_p = fleet.ParallelCrossEntropy()(lt_sharded,
                                              paddle.to_tensor(label))
        loss_s = F.softmax_with_cross_entropy(paddle.to_tensor(logits),
                                              paddle.to_tensor(label))
        np.testing.assert_allclose(loss_p.numpy(), loss_s.numpy(),
                                   rtol=2e-5, atol=2e-5)


# ------------------------------------------------------------ SP layers
class TestSequenceParallel:
    def test_col_row_sequence_parallel(self):
        b, s, h, ffn = 2, 8, 8, 16
        w1 = np.random.randn(h, ffn).astype(np.float32)
        w2 = np.random.randn(ffn, h).astype(np.float32)
        x = np.random.randn(b, s, h).astype(np.float32)

        col = sp.ColumnSequenceParallelLinear(h, ffn, has_bias=False,
                                              gather_output=False)
        row = sp.RowSequenceParallelLinear(ffn, h, has_bias=False,
                                           input_is_parallel=True)
        col.weight.set_value(w1)
        row.weight.set_value(w2)

        xt = paddle.to_tensor(x, stop_gradient=False)
        x_sp = sp.scatter(xt)          # sequence-shard the activation
        y = row(F.gelu(col(x_sp)))
        y_full = sp.gather(y)
        ref = F.gelu(paddle.to_tensor(x) @ paddle.to_tensor(w1)) \
            @ paddle.to_tensor(w2)
        np.testing.assert_allclose(y_full.numpy(), ref.numpy(), rtol=2e-5,
                                   atol=2e-5)
        y_full.backward(paddle.to_tensor(np.ones_like(ref.numpy())))
        assert xt.grad is not None


# --------------------------------------------------------------- GPT TP-2
class TestGPTTensorParallel:
    def test_gpt_mp2_matches_serial(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg_kw = dict(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, max_seq_len=16, use_flash_attention=False)
        paddle.seed(0)
        serial = GPTForCausalLM(GPTConfig(**cfg_kw))
        paddle.seed(0)
        par = GPTForCausalLM(GPTConfig(mp_degree=2, **cfg_kw))
        par.set_state_dict(serial.state_dict())

        ids = np.random.randint(0, 64, (2, 16)).astype(np.int64)
        _, loss_s = serial(paddle.to_tensor(ids),
                           labels=paddle.to_tensor(ids))
        _, loss_p = par(paddle.to_tensor(ids), labels=paddle.to_tensor(ids))
        np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=1e-4)

        loss_s.backward()
        loss_p.backward()
        sd_s = {k: v for k, v in zip(
            [n for n, _ in serial.named_parameters()],
            [p for _, p in serial.named_parameters()])}
        for name, p in par.named_parameters():
            if p.grad is None:
                continue
            ref = sd_s[name].grad
            if ref is None:
                continue
            np.testing.assert_allclose(
                p.grad.numpy(), ref.numpy(), rtol=5e-4, atol=5e-4,
                err_msg=f"grad mismatch for {name}")
