"""Tier-1 contract for the static memory analyzer (static.liveness).

Three claims, each load-bearing for the TPU9xx verifier pass and the
planner's liveness-at-peak HBM term:

* **intervals** — def/last-use residency with the documented edge
  rules: entries caller-held to program end, donation shortening,
  fetch pinning, in-place/write-family alias extension;
* **prediction vs measurement** — the static peak is within 10% of an
  eager replay's measured high-water AND of the perf census high-water
  gauge on the REAL ladder programs (the tiny GPT-with-loss and llama
  forward that ``tools.tpulint --programs`` verifies), so the size
  model is anchored to actual buffer sizes, not to itself;
* **enforcement** — ``FLAGS_verify_programs=strict`` +
  ``FLAGS_verifier_hbm_capacity`` raises TPU901 from ``Program.run``
  BEFORE ``jax.jit`` ever sees the program (the jit cache stays empty).
"""
import gc

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.ops as ops
from paddle_tpu import static
from paddle_tpu.static import liveness, verifier

R = verifier.Record
F32 = "float32"


def _r(name, in_ids, out_ids, shape=(8, 8), **kw):
    n_in, n_out = len(in_ids), len(out_ids)
    return R(name, in_ids=in_ids, out_ids=out_ids,
             in_shapes=[shape] * n_in, out_shapes=[shape] * n_out,
             in_dtypes=[F32] * n_in, out_dtypes=[F32] * n_out, **kw)


NB = 8 * 8 * 4                       # bytes of one (8, 8) float32


# ==========================================================================
# intervals
# ==========================================================================
class TestIntervals:
    def test_chain_def_to_last_use(self):
        recs = [_r("matmul", [1, 2], [3]), _r("relu", [3], [4]),
                _r("sum", [4], [5], shape=())]
        res = liveness.analyze(recs, fetch_ids=[5])
        iv = res.intervals
        # entries are caller-held buffers: resident through program end
        assert iv[1].start == -1 and iv[1].end == 3
        assert iv[1].origin == "param"
        # interior value: def at its op, dead after its last use
        assert (iv[3].start, iv[3].end) == (0, 1)
        assert (iv[4].start, iv[4].end) == (1, 2)
        # fetched value: pinned through program end
        assert iv[5].end == 3
        assert res.n_ops == 3 and len(res.curve) == 3

    def test_donation_frees_entry_after_last_use(self):
        recs = [_r("relu", [1], [2]), _r("relu", [2], [3])]
        kept = liveness.analyze(recs, fetch_ids=[3])
        donated = liveness.analyze(recs, fetch_ids=[3],
                                   donated_ids=[1])
        assert kept.intervals[1].end == 2      # held to program end
        assert donated.intervals[1].end == 0   # freed after op#0
        # the donated buffer is gone at op#1, so the curve is lower
        assert donated.curve[1] == kept.curve[1] - NB

    def test_write_family_alias_extends_result(self):
        # t[0:2] = v then t read much later: the setitem RESULT buffer
        # stays reachable through the target's identity (eager payload
        # swap), so its interval extends to the target's last use
        recs = [
            _r("setitem", [1, 9], [2],
               attrs={"write_region": ((0, 2), (0, 8))}),
            _r("relu", [8], [3]),
            _r("relu", [3], [4]),
            _r("add", [2, 1], [5]),
        ]
        res = liveness.analyze(recs, fetch_ids=[5])
        assert res.intervals[2].end >= res.intervals[1].end

    def test_elementwise_chain_peak_is_three_buffers(self):
        # entry + previous output + current output at every interior op
        recs = [_r("relu", [i], [i + 1]) for i in range(1, 7)]
        res = liveness.analyze(recs, fetch_ids=[7])
        assert res.peak_bytes == pytest.approx(3 * NB)
        # NOT the all-resident estimate (entry + 6 outputs)
        assert res.peak_bytes < 7 * NB

    def test_peak_report_attribution(self):
        recs = [_r("matmul", [1, 2], [3]), _r("relu", [3], [4])]
        rep = liveness.peak_report(recs, fetch_ids=[4],
                                   capacity_bytes=10 * NB)
        assert rep["peak_op"]["name"] in ("matmul", "relu")
        assert rep["peak_bytes"] == pytest.approx(rep["curve"][
            rep["peak_index"]])
        assert rep["utilization"] == pytest.approx(
            rep["peak_bytes"] / (10 * NB))
        sizes = [tv["nbytes"] for tv in rep["top_values"]]
        assert sizes == sorted(sizes, reverse=True)
        assert "static peak HBM" in liveness.render_peak_report(rep)


# ==========================================================================
# static prediction vs measured replay + perf census (10% tolerance)
# ==========================================================================
def _ladder_gpt():
    from tools.tpulint.program_check import _gpt_loss_program
    prog, fetch, model = _gpt_loss_program()
    return prog, fetch, model


def _ladder_llama():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    paddle.seed(7)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, max_seq_len=32,
        use_flash_attention=False))
    prog = static.Program()
    with static.program_guard(prog):
        ids = static.data("ids", [2, 8], "int64")
        logits = model(ids)
        if isinstance(logits, (tuple, list)):
            logits = logits[0]
    return prog, [id(logits)], model


class TestStaticPeakVsCensus:
    @pytest.mark.parametrize("build,phase", [
        (_ladder_gpt, "liveness_gpt"),
        (_ladder_llama, "liveness_llama"),
    ])
    def test_prediction_within_10pct_of_census(self, build, phase):
        prog, fetch, _model = build()
        gc.collect()                 # stabilize the process-wide census
        res = liveness.measure_peak(prog, fetch_ids=fetch, phase=phase)
        static_peak = res["static_peak_bytes"]
        assert static_peak > 0

        # claim 1: replay under the same deletion schedule
        measured = res["peak_bytes"]
        assert abs(static_peak - measured) <= 0.10 * measured, res

        # claim 2: the perf census gauge saw the same high-water —
        # census counts every live buffer in the process, so compare
        # the replay's contribution (delta over its floor + entries)
        census = (res["entry_bytes"]
                  + res["census_high_water"] - res["census_floor"])
        assert census > 0
        assert abs(static_peak - census) <= 0.10 * census, res

    def test_peak_report_on_ladder_program(self):
        prog, fetch, _model = _ladder_gpt()
        rep = liveness.peak_report(prog, fetch_ids=fetch)
        assert rep["n_ops"] == len(prog.global_block().ops)
        assert 0 <= rep["peak_index"] < rep["n_ops"]
        assert rep["peak_bytes"] >= rep["entry_bytes"]
        assert len(rep["top_values"]) == 5


# ==========================================================================
# TPU901 enforcement: strict mode raises BEFORE compile
# ==========================================================================
@pytest.fixture
def _flags_guard():
    prev = paddle.get_flags(
        ["FLAGS_verify_programs", "FLAGS_verifier_hbm_capacity"])
    yield
    paddle.set_flags(prev)


class TestStrictEnforcement:
    def _program(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [64, 64], "float32")
            y = ops.matmul(x, x)
            z = ops.tanh(y)
        return prog, z

    def test_tpu901_raises_before_compile(self, _flags_guard):
        prog, z = self._program()
        paddle.set_flags({"FLAGS_verify_programs": "strict",
                          "FLAGS_verifier_hbm_capacity": 1024})
        with pytest.raises(verifier.ProgramVerifierError) as ei:
            prog.run({"x": np.zeros((64, 64), np.float32)}, [id(z)])
        assert "TPU901" in str(ei.value)
        # the whole point: the diagnostic fired before jax.jit was
        # ever built for this program
        assert not prog._jit_cache

    def test_fitting_program_runs_clean_in_strict(self, _flags_guard):
        prog, z = self._program()
        paddle.set_flags({"FLAGS_verify_programs": "strict",
                          "FLAGS_verifier_hbm_capacity": 10 ** 9})
        out = prog.run({"x": np.ones((64, 64), np.float32)}, [id(z)])
        assert np.asarray(out[0]).shape == (64, 64)
        assert prog._jit_cache       # compiled this time
