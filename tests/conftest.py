"""Test harness config.

All tests run on a virtual 8-device CPU platform so sharding/collective
tests work without TPU hardware (reference test strategy: SURVEY.md §4 —
TestDistBase simulates the cluster on localhost; here the virtual mesh
plays that role).

The agent image's sitecustomize imports jax and points it at the real-TPU
platform before pytest starts, so a plain env var is too late — switch the
platform through jax.config before any backend is initialized.
"""
import os

prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(2024)
    import paddle_tpu as paddle
    paddle.seed(2024)
    yield


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')")
