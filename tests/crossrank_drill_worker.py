"""Cross-rank program-diff drill worker — the real 4-process proof.

Runs under ``python -m paddle_tpu.distributed.launch`` like the other
drill workers. Two recording phases in one job, each into its own
``PADDLE_TPU_PROGRAM_RECORD`` base under <outdir>:

Phase CLEAN (``progs_clean``) — every rank launches the same two eager
all_reduce collectives (the ``collective._coll_begin`` seam notes them
into the ``<collective-stream>`` pseudo-program) and records the SAME
static Program. The harness then asserts ``tpulint --cross-rank``
reports all ranks agree with exit code 0 — the zero-false-positive half
of the TPU45x acceptance.

Phase DIVERGENT (``progs_div``) — after re-pointing the record base,
``DRILL_TARGET_RANK`` (default 2) takes an injected branch while
tracing the step: its recorded op stream carries an extra ``scale`` op
(TPU454), and it records an extra ``debug_probe`` program no other rank
compiles (TPU451). Nothing actually desyncs at runtime — every eager
collective is still launched identically by all ranks — which is the
point: the static diff names the divergent rank and first divergent
sequence number from the dumps alone, BEFORE a real launch-time
mismatch could hang the fleet.

Usage: crossrank_drill_worker.py <outdir>
"""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

OUTDIR = sys.argv[1]
TARGET = int(os.environ.get("DRILL_TARGET_RANK", "2"))

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
import paddle_tpu.ops as ops  # noqa: E402
from paddle_tpu import static  # noqa: E402
from paddle_tpu.distributed.communication import collective as C  # noqa: E402
from paddle_tpu.static import crossrank  # noqa: E402

dist.init_parallel_env()
rank = jax.process_index()
world = jax.process_count()
assert world == 4, f"drill expects 4 processes, got {world}"


def _record_step(divergent: bool):
    """Trace a tiny step; a divergent rank's branch adds one extra op —
    the rank-dependent-control-flow bug class TPU454 exists to catch."""
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 8], "float32")
        y = ops.add(ops.multiply(x, paddle.to_tensor(2.0)),
                    paddle.to_tensor(1.0))
        if divergent:
            y = ops.scale(y, scale=0.5)
        z = ops.tanh(y)
    return prog, [id(z)]


# ------------------------------------------------------------- phase CLEAN
os.environ[crossrank.RECORD_ENV] = os.path.join(OUTDIR, "progs_clean")
crossrank.reset()

t = paddle.to_tensor(np.ones((4, 4), np.float32))
dist.all_reduce(t)        # -> <collective-stream> seq 0, every rank
dist.all_reduce(t)        # -> <collective-stream> seq 1, every rank

prog, fetch = _record_step(divergent=False)
crossrank.dump_program(prog, "drill_step")

C.barrier()               # every rank's clean dump is on disk
print(f"[drill] rank {rank} clean phase recorded", flush=True)

# --------------------------------------------------------- phase DIVERGENT
os.environ[crossrank.RECORD_ENV] = os.path.join(OUTDIR, "progs_div")
crossrank.reset()

dist.all_reduce(t)        # identical eager collectives — no runtime
#                           desync is ever injected; the divergence
#                           below is purely in what gets RECORDED

prog, fetch = _record_step(divergent=(rank == TARGET))
crossrank.dump_program(prog, "drill_step")

if rank == TARGET:
    # a program label only this rank ever compiles (TPU451). Dumped
    # from records rather than a live trace: under multi-process jax,
    # static.data's mesh device_put runs multihost_utils.assert_equal
    # — a real broadcast collective — so tracing on ONE rank would
    # desync the job at runtime, which is exactly the failure mode
    # this pass exists to catch statically.
    from paddle_tpu.static import verifier
    crossrank.dump_program(
        [verifier.Record("relu", in_ids=[1], out_ids=[2],
                         in_shapes=[(2, 2)], out_shapes=[(2, 2)],
                         in_dtypes=["float32"],
                         out_dtypes=["float32"])],
        "debug_probe")

C.barrier()               # every rank's divergent dump is on disk
print(f"[drill] rank {rank} divergent phase recorded", flush=True)
sys.exit(0)
