"""Round-6 satellite regressions (ISSUE 1).

1. gloo_* sync primitives route to the real barrier once the parallel
   env is up (VERDICT Weak #4 — a silent no-op corrupts ported
   rank-0-writes-checkpoint scripts).
2. Pallas autotune: positive-list TPU backend gate, schema-stamped cache
   entries that invalidate stale winners, timings emitted under the
   log-level flag (ADVICE r5 lows).
3. Auto-parallel Engine folds per-param ParamAttr regularizers into the
   traced grads exactly as eager Optimizer.step does.
4. Sharding stage-2/3 no longer silently drop offload=True.
"""
from __future__ import annotations

import json
import logging

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import mesh as mesh_mod


@pytest.fixture(autouse=True)
def _rng_neutral():
    """Keep the global key stream exactly as downstream test files expect:
    layer inits / paddle.seed here must not shift order-fragile tests
    (e.g. svd_lowrank in test_submodule_tail) that draw from it later."""
    state = paddle.get_rng_state()
    yield
    paddle.set_rng_state(state)


@pytest.fixture()
def sharding_mesh():
    old = mesh_mod.get_mesh()
    mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 2, "sharding": 4}))
    yield mesh_mod.get_mesh()
    mesh_mod.set_mesh(old)


# ------------------------------------------------------------------- gloo
class TestGlooRouting:
    def test_barrier_noop_before_init(self, monkeypatch):
        from paddle_tpu.distributed import parallel, tail
        calls = []
        monkeypatch.setattr(parallel, "_initialized", False)
        monkeypatch.setattr(mesh_mod, "has_mesh", lambda: False)
        monkeypatch.setattr(
            "paddle_tpu.distributed.communication.collective.barrier",
            lambda group=None: calls.append(1))
        tail.gloo_barrier()   # pre-init: nothing to synchronize against
        assert calls == []

    def test_barrier_real_after_init(self, monkeypatch):
        from paddle_tpu.distributed import parallel, tail
        calls = []
        monkeypatch.setattr(parallel, "_initialized", True)
        monkeypatch.setattr(
            "paddle_tpu.distributed.communication.collective.barrier",
            lambda group=None: calls.append(1))
        tail.gloo_barrier()
        assert calls == [1]
        tail.gloo_release()   # release fences once more
        assert calls == [1, 1]

    def test_gloo_init_fences_but_never_forces_init(self, monkeypatch):
        # pre-init: a no-op that must NOT call init_parallel_env (that
        # would lock the default mesh and silently discard a later
        # init_parallel_env(mesh_shape=...) topology choice); post-init:
        # fences startup like the gloo ring rendezvous would
        from paddle_tpu.distributed import parallel, tail
        inits, fences = [], []
        monkeypatch.setattr(
            "paddle_tpu.distributed.parallel.init_parallel_env",
            lambda *a, **k: inits.append(1))
        monkeypatch.setattr(
            "paddle_tpu.distributed.communication.collective.barrier",
            lambda group=None: fences.append(1))
        monkeypatch.setattr(parallel, "_initialized", False)
        monkeypatch.setattr(mesh_mod, "has_mesh", lambda: False)
        tail.gloo_init_parallel_env(0, 1, "127.0.0.1:6170")
        assert inits == [] and fences == []
        monkeypatch.setattr(parallel, "_initialized", True)
        tail.gloo_init_parallel_env(0, 1, "127.0.0.1:6170")
        assert inits == [] and fences == [1]

    def test_end_to_end_barrier_executes(self):
        # on the 8-device virtual platform the routed barrier really runs
        # the all-reduce fence (init_parallel_env is idempotent)
        from paddle_tpu.distributed import parallel, tail
        parallel.init_parallel_env()
        tail.gloo_barrier()   # must not raise


# --------------------------------------------------------------- autotune
class TestAutotuneFixes:
    def test_backend_gate_is_positive_list(self, monkeypatch):
        import jax
        from paddle_tpu.ops.pallas import autotune as at
        # CPU test platform: not a TPU backend
        assert at.is_tpu_backend() is False
        # a GPU backend must NOT pass the gate (the old "not cpu" check
        # let GPU runs cache TPU tile probes)
        monkeypatch.setattr(jax, "default_backend", lambda: "gpu")
        assert at.is_tpu_backend() is False
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert at.is_tpu_backend() is True
        monkeypatch.setattr(jax, "default_backend", lambda: "axon")
        assert at.is_tpu_backend() is True

    def test_cache_entries_schema_stamped(self, tmp_path):
        from paddle_tpu.ops.pallas import autotune as at
        path = str(tmp_path / "a.json")
        cache = at.AutotuneCache(path)
        cache.put("k", [512, 512])
        with open(path) as f:
            raw = json.load(f)
        assert raw["k"]["schema"] == at.SCHEMA_VERSION
        assert raw["k"]["stamp"] > 0
        assert cache.get("k") == [512, 512]

    def test_stale_schema_invalidated(self, tmp_path):
        from paddle_tpu.ops.pallas import autotune as at
        path = str(tmp_path / "b.json")
        with open(path, "w") as f:
            json.dump({
                "legacy": [1024, 1024],  # pre-stamp bare value
                "old": {"schema": at.SCHEMA_VERSION - 1, "stamp": 1.0,
                        "value": [2048, 2048]},
                "ok": {"schema": at.SCHEMA_VERSION, "stamp": 2.0,
                       "value": [256, 256]},
            }, f)
        cache = at.AutotuneCache(path)
        assert cache.get("legacy") is None
        assert cache.get("old") is None
        assert cache.get("ok") == [256, 256]

    def test_timings_logged_under_flag(self, tmp_path, monkeypatch,
                                       caplog):
        import jax.numpy as jnp
        from paddle_tpu.core import flags
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setattr(at, "_cache",
                            at.AutotuneCache(str(tmp_path / "c.json")))
        old = flags.get_flag("log_level")
        flags.set_flags({"log_level": 1})
        # the paddle_tpu parent logger does not propagate to root (rank-
        # aware handler), so capture on the logger itself
        lg = logging.getLogger("paddle_tpu.autotune")
        lg.addHandler(caplog.handler)
        try:
            with caplog.at_level(logging.INFO, "paddle_tpu.autotune"):
                at.autotune("ktimings", [(1, 1), (2, 2)],
                            lambda c, i: jnp.zeros(()), default=(0, 0),
                            warmup=1, iters=1)
        finally:
            lg.removeHandler(caplog.handler)
            flags.set_flags({"log_level": old})
        msgs = [r.getMessage() for r in caplog.records]
        assert any("ktimings" in m and "ms" in m for m in msgs)


# ------------------------------------------------- Engine regularizer fold
class TestEngineRegularizerParity:
    def test_engine_matches_eager_with_param_attr_regularizer(
            self, monkeypatch):
        import jax.numpy as jnp
        import paddle_tpu.distributed as dist
        from paddle_tpu.regularizer import L2Decay

        old = mesh_mod.get_mesh()
        mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
        try:
            def build():
                paddle.seed(11)
                net = nn.Linear(
                    6, 3,
                    weight_attr=nn.ParamAttr(regularizer=L2Decay(0.3)))
                opt = paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters())
                return net, opt

            rng = np.random.RandomState(4)
            x = rng.randn(8, 6).astype(np.float32)
            y = rng.randn(8, 3).astype(np.float32)

            def loss_fn(out, yy):
                return paddle.ops.mean((out - yy) ** 2)

            # eager reference step
            net_e, opt_e = build()
            loss = loss_fn(net_e(paddle.to_tensor(x)),
                           paddle.to_tensor(y))
            loss.backward()
            opt_e.step()
            want = [np.asarray(p._data) for p in net_e.parameters()]

            # the weight carries a regularizer: the eager update must
            # differ from a no-regularizer run (guards the guard)
            net_p, opt_p = build()
            for p in net_p.parameters():
                p.regularizer = None
            loss = loss_fn(net_p(paddle.to_tensor(x)),
                           paddle.to_tensor(y))
            loss.backward()
            opt_p.step()
            assert not np.allclose(np.asarray(net_p.weight._data),
                                   want[0])

            # Engine traced step
            net_s, opt_s = build()
            eng = dist.Engine(net_s, loss=loss_fn, optimizer=opt_s)
            eng.prepare()
            pa = [p._data for p in eng._params]
            state = eng._init_opt_state(pa)
            _, new_pa, _ = eng._train_step(pa, state,
                                           jnp.asarray(0.1, jnp.float32),
                                           jnp.asarray(x), jnp.asarray(y))
            by_id = {id(p): a for p, a in zip(eng._params, new_pa)}
            got = [np.asarray(by_id[id(p)]) for p in net_s.parameters()]
            for g, w in zip(got, want):
                np.testing.assert_allclose(g, w, rtol=2e-5, atol=2e-6)
        finally:
            mesh_mod.set_mesh(old)


# ------------------------------------------------------------ offload flag
class TestOffloadNotSilentlyDropped:
    def test_stage2_warns_and_stores(self, sharding_mesh):
        from paddle_tpu.distributed.fleet.meta_parallel.sharding. \
            group_sharded_optimizer_stage2 import \
            GroupShardedOptimizerStage2
        model = nn.Linear(16, 16)
        inner = paddle.optimizer.Adam(learning_rate=0.01,
                                      parameters=model.parameters())
        with pytest.warns(UserWarning, match="offload"):
            opt = GroupShardedOptimizerStage2(model.parameters(),
                                              optim=inner, offload=True)
        assert opt._offload is True
        opt.untag_grads()

    def test_stage3_warns_and_stores(self, sharding_mesh):
        from paddle_tpu.distributed.fleet.meta_parallel.sharding. \
            group_sharded_stage3 import GroupShardedStage3
        model = nn.Linear(16, 16)
        with pytest.warns(UserWarning, match="offload"):
            wrapped = GroupShardedStage3(model, offload=True)
        assert wrapped._offload is True

    def test_no_warning_without_offload(self, sharding_mesh,
                                        recwarn):
        from paddle_tpu.distributed.fleet.meta_parallel.sharding. \
            group_sharded_stage3 import GroupShardedStage3
        GroupShardedStage3(nn.Linear(16, 16), offload=False)
        assert not [w for w in recwarn.list
                    if "offload" in str(w.message)]
