"""Vision model zoo + transforms + datasets tests.

Reference: python/paddle/vision/models/, transforms/, datasets/.
"""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import datasets, models, transforms as T


class TestZooForward:
    # one representative per family runs in tier-1; sibling variants of
    # an already-covered family (same blocks, different width/depth
    # config) are `slow` — each costs 5-18s of conv compiles and tier-1
    # must fit its 870s budget. The full matrix still runs without
    # `-m 'not slow'`.
    _slow = pytest.mark.slow
    @pytest.mark.parametrize("ctor,size", [
        # plain stacked-conv path stays in tier-1 via alexnet; vgg11 is
        # the same idiom at ~12s of conv compiles
        pytest.param("vgg11", 64, marks=_slow),
        # depthwise/pointwise conv path stays in tier-1 via
        # shufflenet_v2_x0_25; the whole mobilenet family (v1/v2/v3)
        # runs in the full matrix
        pytest.param("mobilenet_v2", 64, marks=_slow),
        pytest.param("mobilenet_v1", 64, marks=_slow),
        pytest.param("mobilenet_v3_small", 64, marks=_slow),
        pytest.param("mobilenet_v3_large", 64, marks=_slow),
        ("alexnet", 96), ("squeezenet1_1", 96),
        pytest.param("squeezenet1_0", 96, marks=_slow),
        ("shufflenet_v2_x0_25", 64),
        pytest.param("shufflenet_v2_swish", 64, marks=_slow),
        # the deepest zoo forward (~33s of conv compiles, the single
        # most expensive tier-1 test): concat-chain graphs stay
        # represented in tier-1 by googlenet (inception concat) and
        # shufflenet (concat + channel shuffle)
        pytest.param("densenet121", 64, marks=_slow),
    ])
    def test_forward_shape(self, ctor, size):
        net = getattr(models, ctor)(num_classes=7)
        net.eval()
        x = paddle.to_tensor(np.random.RandomState(0).rand(
            2, 3, size, size).astype(np.float32))
        assert net(x).shape == [2, 7]

    def test_googlenet_aux_heads(self):
        net = models.googlenet(num_classes=5)
        net.eval()
        x = paddle.to_tensor(np.random.RandomState(0).rand(
            1, 3, 96, 96).astype(np.float32))
        out, a1, a2 = net(x)
        assert out.shape == [1, 5] and a1.shape == [1, 5] and a2.shape == [1, 5]

    @_slow
    def test_inception_v3(self):
        # inception family stays represented in tier-1 by googlenet
        # (which also checks the aux-head contract); v3's larger stem
        # costs ~12s of conv compiles
        net = models.inception_v3(num_classes=4)
        net.eval()
        x = paddle.to_tensor(np.random.RandomState(0).rand(
            1, 3, 128, 128).astype(np.float32))
        assert net(x).shape == [1, 4]

    def test_lenet_zoo_variant(self):
        net = models.LeNet()
        x = paddle.to_tensor(np.random.RandomState(0).rand(
            2, 1, 28, 28).astype(np.float32))
        assert net(x).shape == [2, 10]

    @_slow
    def test_mobilenet_v2_trains(self):
        # ~35s of depthwise-conv backward compiles; "a zoo CNN trains"
        # stays in tier-1 via resnet18 (test_models_hapi) and the
        # mobilenet_v2 forward above still runs
        net = models.mobilenet_v2(scale=0.25, num_classes=2)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(4, 3, 32, 32).astype(np.float32))
        y = paddle.to_tensor((rng.rand(4) > 0.5).astype(np.int64))
        import paddle_tpu.nn.functional as F
        l0 = lN = None
        for i in range(6):
            loss = F.cross_entropy(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if i == 0:
                l0 = float(loss.numpy())
            lN = float(loss.numpy())
        assert lN < l0


class TestTransforms:
    def _img(self, h=32, w=48):
        rng = np.random.RandomState(0)
        return rng.randint(0, 255, (h, w, 3), dtype=np.uint8)

    def test_to_tensor_scales_and_chw(self):
        t = T.ToTensor()
        out = t(self._img())
        assert out.shape == [3, 32, 48]
        a = out.numpy()
        assert a.max() <= 1.0 and a.min() >= 0.0

    def test_resize_int_keeps_aspect(self):
        out = T.Resize(16)(self._img(32, 48))
        assert np.asarray(out).shape[:2] == (16, 24)
        out2 = T.Resize((8, 9))(self._img())
        assert np.asarray(out2).shape[:2] == (8, 9)

    def test_center_crop(self):
        out = T.CenterCrop(16)(self._img())
        arr = np.asarray(out)
        assert arr.shape[:2] == (16, 16)
        np.testing.assert_array_equal(arr, self._img()[8:24, 16:32])

    def test_random_crop_within_bounds(self):
        out = T.RandomCrop(20)(self._img())
        assert np.asarray(out).shape[:2] == (20, 20)

    def test_flips(self):
        img = self._img()
        np.testing.assert_array_equal(
            np.asarray(T.RandomHorizontalFlip(prob=1.0)(img)),
            img[:, ::-1])
        np.testing.assert_array_equal(
            np.asarray(T.RandomVerticalFlip(prob=1.0)(img)), img[::-1])

    def test_normalize_chw(self):
        x = np.ones((3, 4, 4), np.float32)
        out = T.Normalize(mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5])(x)
        np.testing.assert_allclose(np.asarray(out), np.ones((3, 4, 4)))

    def test_compose_pipeline(self):
        pipe = T.Compose([
            T.Resize(40), T.RandomCrop(32), T.RandomHorizontalFlip(),
            T.ColorJitter(0.1, 0.1, 0.1, 0.1), T.ToTensor(),
            T.Normalize([0.5] * 3, [0.25] * 3)])
        out = pipe(self._img(64, 64))
        assert out.shape == [3, 32, 32]
        assert np.isfinite(out.numpy()).all()

    def test_pad_and_rotation_and_gray(self):
        img = self._img()
        assert np.asarray(T.Pad(2)(img)).shape == (36, 52, 3)
        assert np.asarray(T.RandomRotation(30)(img)).shape == (32, 48, 3)
        g = T.Grayscale()(img)
        assert np.asarray(g).ndim == 2 or np.asarray(g).shape[2] == 1
        g3 = T.Grayscale(3)(img)
        a3 = np.asarray(g3)
        np.testing.assert_array_equal(a3[..., 0], a3[..., 1])

    def test_random_erasing(self):
        x = paddle.to_tensor(np.ones((3, 16, 16), np.float32))
        out = T.RandomErasing(prob=1.0, value=0.0)(x)
        assert (out.numpy() == 0).sum() > 0

    def test_transpose(self):
        out = T.Transpose()(self._img())
        assert np.asarray(out).shape == (3, 32, 48)


def _write_idx(tmp, images, labels, tag):
    ip = os.path.join(tmp, f"{tag}-images-idx3-ubyte.gz")
    lp = os.path.join(tmp, f"{tag}-labels-idx1-ubyte.gz")
    n, r, c = images.shape
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, r, c))
        f.write(images.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return ip, lp


class TestDatasets:
    def test_mnist_idx_roundtrip(self, tmp_path):
        rng = np.random.RandomState(0)
        images = rng.randint(0, 255, (10, 28, 28), dtype=np.uint8)
        labels = rng.randint(0, 10, 10).astype(np.uint8)
        ip, lp = _write_idx(str(tmp_path), images, labels, "train")
        ds = datasets.MNIST(image_path=ip, label_path=lp, mode="train")
        assert len(ds) == 10
        img, lbl = ds[3]
        np.testing.assert_array_equal(img, images[3])
        assert int(lbl) == int(labels[3])

    def test_mnist_with_transform_and_loader(self, tmp_path):
        rng = np.random.RandomState(1)
        images = rng.randint(0, 255, (8, 28, 28), dtype=np.uint8)
        labels = rng.randint(0, 10, 8).astype(np.uint8)
        ip, lp = _write_idx(str(tmp_path), images, labels, "t10k")
        ds = datasets.MNIST(image_path=ip, label_path=lp, mode="test",
                            transform=T.Compose([T.ToTensor()]))
        loader = paddle.io.DataLoader(ds, batch_size=4)
        batch = next(iter(loader))
        x, y = batch
        assert list(x.shape) == [4, 1, 28, 28]
        assert list(y.shape) == [4]

    def test_missing_file_raises_clearly(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="no-egress|not found"):
            datasets.MNIST(image_path=str(tmp_path / "nope.gz"),
                           label_path=str(tmp_path / "nope2.gz"))

    def test_cifar10_tar(self, tmp_path):
        rng = np.random.RandomState(0)
        os.makedirs(tmp_path / "cifar-10-batches-py")
        for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
            batch = {b"data": rng.randint(0, 255, (5, 3072),
                                          dtype=np.uint8),
                     b"labels": rng.randint(0, 10, 5).tolist()}
            with open(tmp_path / "cifar-10-batches-py" / name, "wb") as f:
                pickle.dump(batch, f)
        tar = tmp_path / "cifar-10-python.tar.gz"
        with tarfile.open(tar, "w:gz") as tf:
            tf.add(tmp_path / "cifar-10-batches-py",
                   arcname="cifar-10-batches-py")
        tr = datasets.Cifar10(str(tar), mode="train")
        te = datasets.Cifar10(str(tar), mode="test")
        assert len(tr) == 25 and len(te) == 5
        img, lbl = tr[0]
        assert img.shape == (32, 32, 3) and 0 <= int(lbl) < 10

    def test_dataset_folder(self, tmp_path):
        from PIL import Image
        for cls in ("cat", "dog"):
            os.makedirs(tmp_path / "train" / cls)
            for i in range(3):
                arr = np.full((8, 8, 3), 100 + i, np.uint8)
                Image.fromarray(arr).save(
                    tmp_path / "train" / cls / f"{i}.png")
        ds = datasets.DatasetFolder(str(tmp_path / "train"))
        assert len(ds) == 6
        assert ds.classes == ["cat", "dog"]
        img, lbl = ds[0]
        assert int(lbl) == 0
        flat = datasets.ImageFolder(str(tmp_path / "train"))
        assert len(flat) == 6


class TestTransformsFloatAndGray:
    def test_resize_preserves_float(self):
        rng = np.random.RandomState(0)
        x = rng.rand(8, 8, 3).astype(np.float32)
        out = T.resize(x, 4)
        assert out.dtype == np.float32
        # bilinear downscale of values in [0,1] stays in range, non-trivial
        assert 0.2 < float(np.asarray(out).mean()) < 0.8

    def test_rotate_preserves_float(self):
        x = np.ones((8, 8, 1), np.float32) * 0.5
        out = T.rotate(x, 90)
        assert out.dtype == np.float32
        np.testing.assert_allclose(np.asarray(out)[2:-2, 2:-2], 0.5)

    def test_pad_grayscale_pil(self):
        from PIL import Image
        img = Image.fromarray(np.zeros((8, 8), np.uint8))
        out = T.Pad(2)(img)
        assert np.asarray(out).shape[:2] == (12, 12)

    def test_brightness_float_dtype_preserving(self):
        x = np.full((4, 4, 3), 0.4, np.float32)
        out = T.adjust_brightness(x, 1.5)
        assert out.dtype == np.float32
        np.testing.assert_allclose(out, 0.6, rtol=1e-6)

    def test_hue_on_float_raises(self):
        with pytest.raises(TypeError, match="uint8"):
            T.adjust_hue(np.random.rand(4, 4, 3).astype(np.float32), 0.1)


class TestFlowersVOC:
    def test_flowers(self, tmp_path):
        from PIL import Image
        from scipy.io import savemat
        # 4-image miniature in the reference layout
        jpg_dir = tmp_path / "jpg"
        os.makedirs(jpg_dir)
        for i in range(1, 5):
            arr = np.full((6, 6, 3), i * 40, np.uint8)
            Image.fromarray(arr).save(jpg_dir / ("image_%05d.jpg" % i))
        data_tar = tmp_path / "102flowers.tgz"
        with tarfile.open(data_tar, "w:gz") as tf:
            tf.add(jpg_dir, arcname="jpg")
        savemat(tmp_path / "imagelabels.mat",
                {"labels": np.array([[3, 1, 4, 1]])})
        savemat(tmp_path / "setid.mat",
                {"trnid": np.array([[1, 3]]), "valid": np.array([[2]]),
                 "tstid": np.array([[4]])})
        from paddle_tpu.vision.datasets import Flowers
        tr = Flowers(str(data_tar), str(tmp_path / "imagelabels.mat"),
                     str(tmp_path / "setid.mat"), mode="train")
        assert len(tr) == 2
        img, lbl = tr[0]
        assert int(lbl[0]) == 3 and np.asarray(img).shape == (6, 6, 3)
        te = Flowers(str(data_tar), str(tmp_path / "imagelabels.mat"),
                     str(tmp_path / "setid.mat"), mode="test")
        assert len(te) == 1 and int(te[0][1][0]) == 1

    def test_voc2012(self, tmp_path):
        from PIL import Image
        root = tmp_path / "VOCdevkit" / "VOC2012"
        os.makedirs(root / "JPEGImages")
        os.makedirs(root / "SegmentationClass")
        os.makedirs(root / "ImageSets" / "Segmentation")
        names = ["2007_000032", "2007_000033"]
        for n in names:
            Image.fromarray(np.zeros((5, 7, 3), np.uint8)).save(
                root / "JPEGImages" / f"{n}.jpg")
            Image.fromarray(np.full((5, 7), 2, np.uint8)).save(
                root / "SegmentationClass" / f"{n}.png")
        (root / "ImageSets" / "Segmentation" / "trainval.txt").write_text(
            "\n".join(names))
        (root / "ImageSets" / "Segmentation" / "train.txt").write_text(
            "\n".join(names))
        (root / "ImageSets" / "Segmentation" / "val.txt").write_text(
            names[0])
        tar = tmp_path / "voc.tar"
        with tarfile.open(tar, "w") as tf:
            tf.add(tmp_path / "VOCdevkit", arcname="VOCdevkit")
        from paddle_tpu.vision.datasets import VOC2012
        tr = VOC2012(str(tar), mode="train")
        assert len(tr) == 2
        img, seg = tr[0]
        assert np.asarray(img).shape == (5, 7, 3)
        assert seg.shape == (5, 7) and int(seg[0, 0]) == 2
        va = VOC2012(str(tar), mode="valid")
        assert len(va) == 1

    def test_missing_archives_raise(self, tmp_path):
        from paddle_tpu.vision.datasets import VOC2012, Flowers
        with pytest.raises(FileNotFoundError):
            Flowers(str(tmp_path / "a"), str(tmp_path / "b"),
                    str(tmp_path / "c"))
        with pytest.raises(FileNotFoundError):
            VOC2012(str(tmp_path / "nope.tar"))
