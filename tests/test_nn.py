"""nn package tests (layer mechanics, functionals vs numpy/torch-free refs).

Mirrors the reference's OpTest-style numeric comparison (SURVEY.md §4).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t(arr, sg=True):
    return paddle.to_tensor(np.asarray(arr), stop_gradient=sg)


class TestLayerMechanics:
    def test_parameter_registration(self):
        l = nn.Linear(4, 3)
        names = [n for n, _ in l.named_parameters()]
        assert names == ["weight", "bias"]
        assert l.weight.shape == [4, 3]

    def test_sublayer_nesting(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        net = Net()
        names = {n for n, _ in net.named_parameters()}
        assert names == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
        assert len(net.sublayers()) == 2

    def test_state_dict_roundtrip(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = net.state_dict()
        net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        missing, unexpected = net2.set_state_dict(sd)
        assert missing == [] and unexpected == []
        np.testing.assert_array_equal(net[0].weight.numpy(),
                                      net2[0].weight.numpy())

    def test_buffers_in_state_dict(self):
        bn = nn.BatchNorm2D(4)
        sd = bn.state_dict()
        assert "_mean" in sd and "_variance" in sd

    def test_train_eval_mode(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        net.eval()
        assert not net[1].training
        net.train()
        assert net[1].training

    def test_forward_hooks(self):
        l = nn.Linear(2, 2)
        calls = []
        h1 = l.register_forward_pre_hook(lambda layer, inp: calls.append("pre"))
        h2 = l.register_forward_post_hook(
            lambda layer, inp, out: calls.append("post"))
        l(t(np.ones((1, 2), "float32")))
        assert calls == ["pre", "post"]
        h1.remove()
        h2.remove()
        calls.clear()
        l(t(np.ones((1, 2), "float32")))
        assert calls == []

    def test_to_dtype(self):
        l = nn.Linear(2, 2)
        l.to(dtype="bfloat16")
        assert l.weight.dtype == paddle.bfloat16


class TestFunctionals:
    def test_linear_matches_numpy(self):
        x = np.random.randn(3, 4).astype("float32")
        l = nn.Linear(4, 5)
        out = l(t(x)).numpy()
        ref = x @ l.weight.numpy() + l.bias.numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_conv2d_matches_naive(self):
        x = np.random.randn(1, 2, 5, 5).astype("float32")
        w = np.random.randn(3, 2, 3, 3).astype("float32")
        out = F.conv2d(t(x), t(w), padding=1).numpy()
        assert out.shape == (1, 3, 5, 5)
        # center pixel check vs direct correlation
        patch = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))[0, :, 1:4, 1:4]
        np.testing.assert_allclose(out[0, 0, 1, 1],
                                   np.sum(patch * w[0]), rtol=1e-4, atol=1e-4)

    def test_conv2d_transpose_shape_inverts_conv(self):
        x = np.random.randn(2, 4, 8, 8).astype("float32")
        w = np.random.randn(4, 6, 3, 3).astype("float32")
        y = F.conv2d_transpose(t(x), t(w), stride=2, padding=1,
                               output_padding=1)
        assert y.shape == [2, 6, 16, 16]

    def test_softmax_cross_entropy_consistency(self):
        logits = np.random.randn(6, 10).astype("float32")
        labels = np.random.randint(0, 10, (6,))
        loss = F.cross_entropy(t(logits), t(labels)).numpy()
        # manual
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(6), labels]).mean()
        np.testing.assert_allclose(loss, ref, rtol=1e-5, atol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.randn(4, 5).astype("float32")
        labels = np.array([1, -100, 3, -100])
        loss = F.cross_entropy(t(logits), t(labels)).numpy()
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[[0, 2], [1, 3]]).mean()
        np.testing.assert_allclose(loss, ref, rtol=1e-5, atol=1e-5)

    def test_layer_norm(self):
        x = np.random.randn(2, 3, 8).astype("float32")
        ln = nn.LayerNorm(8)
        out = ln(t(x)).numpy()
        mu = x.mean(-1, keepdims=True)
        sd = x.std(-1, keepdims=True)
        np.testing.assert_allclose(out, (x - mu) / np.sqrt(sd ** 2 + 1e-5),
                                   rtol=1e-4, atol=1e-4)

    def test_rms_norm(self):
        x = np.random.randn(2, 8).astype("float32")
        out = F.rms_norm(t(x)).numpy()
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_batch_norm_train_updates_stats(self):
        bn = nn.BatchNorm2D(3, momentum=0.9)
        x = np.random.randn(4, 3, 5, 5).astype("float32") * 2 + 1
        bn.train()
        bn(t(x))
        batch_mean = x.mean(axis=(0, 2, 3))
        np.testing.assert_allclose(bn._mean.numpy(), 0.1 * batch_mean,
                                   rtol=1e-3, atol=1e-3)

    def test_batch_norm_eval_uses_running(self):
        bn = nn.BatchNorm2D(3)
        bn.eval()
        x = np.random.randn(2, 3, 4, 4).astype("float32")
        out = bn(t(x)).numpy()
        np.testing.assert_allclose(out, x / np.sqrt(1 + 1e-5), rtol=1e-4,
                                   atol=1e-4)

    def test_max_avg_pool(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        mp = F.max_pool2d(t(x), 2).numpy()
        np.testing.assert_array_equal(mp[0, 0], [[5, 7], [13, 15]])
        ap = F.avg_pool2d(t(x), 2).numpy()
        np.testing.assert_allclose(ap[0, 0], [[2.5, 4.5], [10.5, 12.5]])

    def test_avg_pool_exclusive_padding(self):
        x = np.ones((1, 1, 3, 3), "float32")
        out = F.avg_pool2d(t(x), 2, stride=2, padding=1, exclusive=True).numpy()
        np.testing.assert_allclose(out, np.ones_like(out))

    def test_adaptive_pool(self):
        x = np.random.randn(2, 3, 8, 8).astype("float32")
        out = F.adaptive_avg_pool2d(t(x), 2).numpy()
        ref = x.reshape(2, 3, 2, 4, 2, 4).mean(axis=(3, 5))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
        # non-divisible path
        out = F.adaptive_avg_pool2d(t(x), 3)
        assert out.shape == [2, 3, 3, 3]

    def test_dropout_train_eval(self):
        x = np.ones((100, 100), "float32")
        train_out = F.dropout(t(x), 0.5, training=True).numpy()
        assert abs((train_out == 0).mean() - 0.5) < 0.05
        np.testing.assert_allclose(train_out[train_out != 0], 2.0)
        eval_out = F.dropout(t(x), 0.5, training=False).numpy()
        np.testing.assert_array_equal(eval_out, x)

    def test_embedding_grad_and_padding(self):
        w = t(np.random.randn(10, 4).astype("float32"), sg=False)
        ids = t(np.array([1, 2, 0, 1]))
        out = F.embedding(ids, w, padding_idx=0)
        assert np.allclose(out.numpy()[2], 0)
        out.backward(t(np.ones((4, 4), "float32")))
        g = w.grad.numpy()
        assert np.allclose(g[1], 2.0) and np.allclose(g[2], 1.0)
        assert np.allclose(g[5], 0.0)

    def test_interpolate_nearest_bilinear(self):
        x = np.arange(4, dtype="float32").reshape(1, 1, 2, 2)
        out = F.interpolate(t(x), size=[4, 4], mode="nearest").numpy()
        assert out.shape == (1, 1, 4, 4)
        out2 = F.interpolate(t(x), scale_factor=2, mode="bilinear").numpy()
        assert out2.shape == (1, 1, 4, 4)

    def test_sdpa_matches_naive(self):
        q = np.random.randn(2, 5, 2, 8).astype("float32")
        out = F.scaled_dot_product_attention(t(q), t(q), t(q),
                                             is_causal=False).numpy()
        # naive
        qq = q.transpose(0, 2, 1, 3)
        logits = qq @ qq.transpose(0, 1, 3, 2) / np.sqrt(8)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = (p @ qq).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)

    def test_pad_modes(self):
        x = np.random.randn(1, 1, 3, 3).astype("float32")
        out = F.pad(t(x), [1, 1, 1, 1]).numpy()
        assert out.shape == (1, 1, 5, 5)
        assert out[0, 0, 0, 0] == 0

    def test_pixel_shuffle_roundtrip(self):
        x = np.random.randn(1, 8, 4, 4).astype("float32")
        y = F.pixel_shuffle(t(x), 2)
        z = F.pixel_unshuffle(y, 2).numpy()
        np.testing.assert_array_equal(z, x)

    def test_activations_finite(self):
        x = t(np.linspace(-5, 5, 64, dtype="float32").reshape(8, 8))
        for fn in [F.relu, F.gelu, F.sigmoid, F.tanh, F.silu, F.mish,
                   F.hardswish, F.softplus, F.elu, F.selu, F.leaky_relu]:
            out = fn(x).numpy()
            assert np.all(np.isfinite(out)), fn


class TestGradients:
    def test_linear_grad_numeric(self):
        np.random.seed(0)
        x = np.random.randn(3, 4).astype("float32")
        l = nn.Linear(4, 2)
        xt = t(x, sg=False)
        loss = F.mse_loss(l(xt), t(np.zeros((3, 2), "float32")))
        loss.backward()
        # numeric grad on one weight element
        eps = 1e-3
        w = l.weight.numpy().copy()
        for (i, j) in [(0, 0), (3, 1)]:
            wp = w.copy()
            wp[i, j] += eps
            lp = float(F.mse_loss(
                F.linear(t(x), t(wp), l.bias),
                t(np.zeros((3, 2), "float32"))).numpy())
            wm = w.copy()
            wm[i, j] -= eps
            lm = float(F.mse_loss(
                F.linear(t(x), t(wm), l.bias),
                t(np.zeros((3, 2), "float32"))).numpy())
            num = (lp - lm) / (2 * eps)
            np.testing.assert_allclose(l.weight.grad.numpy()[i, j], num,
                                       rtol=1e-2, atol=1e-3)

    def test_conv_grad_flows(self):
        conv = nn.Conv2D(2, 3, 3, padding=1)
        x = t(np.random.randn(1, 2, 4, 4).astype("float32"), sg=False)
        out = conv(x)
        from paddle_tpu.ops import reduction
        reduction.sum(out).backward()
        assert conv.weight.grad is not None
        assert x.grad is not None and x.grad.shape == [1, 2, 4, 4]
