"""Parameter-server mode: tables, accessors, client routing, the
PS-backed embedding, and the fleet PS lifecycle.

Reference contracts: paddle/fluid/distributed/ps/table/
(memory_sparse_table, memory_dense_table, accessors),
service/brpc_ps_{server,client}.cc (pull/push/save/load/barrier), and
python/paddle/distributed/ps/the_one_ps.py + fleet role lifecycle
(role_maker.py:849-1003).
"""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import ps
from paddle_tpu.distributed.ps import (DistributedEmbedding, PsClient,
                                       PsServer, SparseTable)


# ----------------------------------------------------------- fixtures
@pytest.fixture()
def cluster():
    """Two in-process PS shards + a client (2-server sharding)."""
    servers = [PsServer(i, 2, token="t0").start() for i in range(2)]
    client = PsClient([s.endpoint for s in servers], token="t0")
    yield servers, client
    client.close()
    for s in servers:
        s.stop()


# ------------------------------------------------------------- tables
def test_sparse_table_lazy_rows_and_sgd():
    t = SparseTable(dim=4, accessor="sgd", lr=0.5, initializer="constant",
                    init_range=1.0)
    v = t.pull([7, 3, 7])
    assert v.shape == (3, 4)
    np.testing.assert_allclose(v, 1.0)
    assert t.size == 2  # lazy creation, deduped storage
    t.push([7], np.full((1, 4), 2.0, np.float32))
    np.testing.assert_allclose(t.pull([7]), 1.0 - 0.5 * 2.0)
    np.testing.assert_allclose(t.pull([3]), 1.0)  # untouched row


def test_adam_accessor_matches_local_adam():
    """Server-side adam == a local reference adam loop on the same rows."""
    t = SparseTable(dim=3, accessor="adam", lr=0.1, initializer="constant",
                    init_range=0.0)
    rng = np.random.RandomState(0)
    w = t.pull([5])[0].copy()
    m = np.zeros(3); v = np.zeros(3)
    for step in range(1, 6):
        g = rng.randn(3).astype(np.float32)
        t.push([5], g[None])
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mhat = m / (1 - 0.9 ** step)
        vhat = v / (1 - 0.999 ** step)
        w = w - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(t.pull([5])[0], w, rtol=1e-5, atol=1e-6)


def test_adagrad_and_sum_accessors():
    t = SparseTable(dim=2, accessor="adagrad", lr=1.0,
                    initializer="constant", init_range=0.0)
    g = np.array([[3.0, 4.0]], np.float32)
    t.push([1], g)
    np.testing.assert_allclose(
        t.pull([1]), -1.0 * g / (np.sqrt(g * g) + 1e-6), rtol=1e-5)
    s = SparseTable(dim=2, accessor="sum", initializer="constant",
                    init_range=0.0)
    s.push([1], g)
    s.push([1], g)
    np.testing.assert_allclose(s.pull([1]), 2 * g)


def test_sparse_state_dict_roundtrip():
    t = SparseTable(dim=3, accessor="adam", lr=0.1)
    t.push(np.arange(10), np.ones((10, 3), np.float32))
    sd = t.state_dict()
    t2 = SparseTable(dim=3, accessor="adam", lr=0.1)
    t2.load_state_dict(sd)
    np.testing.assert_allclose(t2.pull(np.arange(10)), t.pull(np.arange(10)))
    # optimizer state carried: the next identical push matches too
    t.push([4], np.ones((1, 3), np.float32))
    t2.push([4], np.ones((1, 3), np.float32))
    np.testing.assert_allclose(t2.pull([4]), t.pull([4]), rtol=1e-6)


# ----------------------------------------------------- client/server
def test_client_routing_and_dedup(cluster):
    servers, client = cluster
    client.create_table(0, {"type": "sparse", "dim": 4, "accessor": "sgd",
                            "lr": 1.0, "initializer": "constant",
                            "init_range": 0.0})
    ids = np.array([2, 3, 2, 5, 3, 2], np.int64)
    vals = client.pull_sparse(0, ids)
    assert vals.shape == (6, 4)
    # rows landed on both shards (id%2 routing)
    sizes = [s._tables[0].size for s in servers]
    assert sizes == [1, 2]  # {2} on shard0, {3,5} on shard1
    # duplicate-id push merges client-side: id 2 appears 3x with grad 1
    # → one sgd step of summed grad 3
    client.push_sparse(0, ids, np.ones((6, 4), np.float32))
    np.testing.assert_allclose(client.pull_sparse(0, [2])[0], -3.0)
    np.testing.assert_allclose(client.pull_sparse(0, [5])[0], -1.0)


def test_client_auth_rejected(cluster):
    servers, _ = cluster
    bad = PsClient([servers[0].endpoint], token="WRONG")
    with pytest.raises(Exception):
        bad.pull_sparse(0, [1])
    bad.close()


def test_dense_table_chunking(cluster):
    servers, client = cluster
    client.create_table(1, {"type": "dense", "length": 7, "accessor": "sgd",
                            "lr": 0.5, "init_value": 0.0})
    v = np.arange(7, dtype=np.float32)
    client.set_dense(1, v)
    np.testing.assert_allclose(client.pull_dense(1), v)
    # chunked across servers: 4 + 3
    assert servers[0]._tables[1].length == 4
    assert servers[1]._tables[1].length == 3
    client.push_dense(1, np.ones(7, np.float32))
    np.testing.assert_allclose(client.pull_dense(1), v - 0.5)


def test_save_load_roundtrip(cluster, tmp_path):
    servers, client = cluster
    client.create_table(0, {"type": "sparse", "dim": 2, "accessor": "sgd",
                            "lr": 1.0})
    ids = np.arange(20)
    before = client.pull_sparse(0, ids)
    client.save(str(tmp_path))
    client.push_sparse(0, ids, np.ones((20, 2), np.float32))  # perturb
    client.load(str(tmp_path))
    np.testing.assert_allclose(client.pull_sparse(0, ids), before)


def test_table_create_conflict_and_missing(cluster):
    _, client = cluster
    client.create_table(3, {"type": "sparse", "dim": 2})
    client.create_table(3, {"type": "sparse", "dim": 2})  # idempotent
    with pytest.raises(ValueError):
        client.create_table(3, {"type": "sparse", "dim": 8})
    with pytest.raises(KeyError):
        client.pull_sparse(99, [1])


def test_worker_barrier(cluster):
    _, client = cluster
    c2 = PsClient(client.endpoints, token="t0")
    results = []

    def w(c):
        c.barrier("sync", 2)
        results.append(1)

    th = threading.Thread(target=w, args=(c2,))
    th.start()
    client.barrier("sync", 2)
    th.join(timeout=10)
    assert len(results) == 1
    # reusable: second generation also completes
    th2 = threading.Thread(target=w, args=(c2,))
    th2.start()
    client.barrier("sync", 2)
    th2.join(timeout=10)
    assert len(results) == 2
    c2.close()


# ------------------------------------------------- PS-backed embedding
def test_distributed_embedding_trains(cluster):
    _, client = cluster
    emb = DistributedEmbedding(0, 8, client=client, accessor="sgd", lr=0.3,
                               init_range=0.05)
    lin = paddle.nn.Linear(8, 2)
    opt = paddle.optimizer.SGD(parameters=lin.parameters(),
                               learning_rate=0.3)
    ids = paddle.to_tensor(np.array([[1, 2, 3], [4, 2, 9]], np.int64))
    labels = paddle.to_tensor(np.array([0, 1], np.int64))
    losses = []
    for _ in range(20):
        h = emb(ids).mean(axis=1)
        loss = paddle.nn.functional.cross_entropy(lin(h), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < 0.5 * losses[0], losses


def test_distributed_embedding_matches_local(cluster):
    """PS-backed training == local-embedding training, step for step.

    Same init rows, same duplicate-heavy batch, plain SGD on both sides;
    the PS path (pull → device gather → push → server-side sgd) must
    reproduce the local embedding's weights exactly.
    """
    _, client = cluster
    dim, lr = 4, 0.2
    emb = DistributedEmbedding(7, dim, client=client, accessor="sgd",
                               lr=lr, initializer="constant",
                               init_range=0.1)
    ids_np = np.array([[0, 1, 1], [2, 1, 0]], np.int64)
    ids = paddle.to_tensor(ids_np)

    # local reference: same constant init
    W = np.full((3, dim), 0.1, np.float32)
    for step in range(3):
        out = emb(ids)                      # [2, 3, dim]
        loss = (out * out).sum()
        loss.backward()
        # local numpy replica
        g_out = 2 * W[ids_np]               # dL/d(out)
        gW = np.zeros_like(W)
        np.add.at(gW, ids_np.reshape(-1), g_out.reshape(-1, dim))
        W -= lr * gW
        np.testing.assert_allclose(
            client.pull_sparse(7, [0, 1, 2]), W, rtol=1e-5, atol=1e-6)


def test_embedding_not_trainable_pulls_only(cluster):
    _, client = cluster
    emb = DistributedEmbedding(8, 4, client=client, accessor="sgd", lr=1.0,
                               trainable=False)
    ids = paddle.to_tensor(np.array([1, 2], np.int64))
    before = client.pull_sparse(8, [1, 2])
    out = emb(ids)
    s = out.sum()
    # no tape reaches the PS: rows are stop_gradient, output too
    assert out.stop_gradient
    np.testing.assert_allclose(client.pull_sparse(8, [1, 2]), before)


# ------------------------------------------------------ fleet PS mode
def _free_ports(n):
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_fleet_ps_lifecycle(monkeypatch):
    """Server + worker roles through the fleet facade (single process:
    the server runs on a thread, the worker on the main thread)."""
    from paddle_tpu.distributed.fleet.fleet_base import Fleet
    from paddle_tpu.distributed.ps import (PaddleCloudRoleMaker, Role,
                                           UserDefinedRoleMaker)

    (port,) = _free_ports(1)
    eps = f"127.0.0.1:{port}"
    monkeypatch.setenv("PADDLE_PS_TOKEN", "fleet-tok")

    # ---- server role (background thread, its own Fleet instance,
    # programmatic roles — no env needed)
    server_ready = threading.Event()
    server_done = threading.Event()

    def run_server():
        f = Fleet()
        f.init(UserDefinedRoleMaker(current_id=0, role=Role.SERVER,
                                    worker_num=1, server_endpoints=[eps]))
        assert f.is_server() and not f.is_worker()
        assert f.server_index() == 0 and f.server_num() == 1
        f.init_server()
        server_ready.set()
        f.run_server()  # blocks until stop_worker
        server_done.set()

    th = threading.Thread(target=run_server, daemon=True)
    th.start()
    assert server_ready.wait(timeout=30)

    # ---- worker role (env-driven role maker, reference contract)
    for k, v in {"PADDLE_PSERVERS_IP_PORT_LIST": eps,
                 "PADDLE_TRAINERS_NUM": "1", "TRAINING_ROLE": "TRAINER",
                 "PADDLE_TRAINER_ID": "0"}.items():
        monkeypatch.setenv(k, v)
    f = Fleet()
    f.init(PaddleCloudRoleMaker())
    assert f.is_worker() and not f.is_server()
    assert f.worker_num() == 1 and f.is_first_worker()
    client = f.init_worker()
    emb = DistributedEmbedding(0, 4, accessor="sgd", lr=0.5)  # via fleet ctx
    ids = paddle.to_tensor(np.array([3, 4], np.int64))
    out = emb(ids)
    loss = out.sum()
    loss.backward()
    f.barrier_worker()
    after = client.pull_sparse(0, [3, 4])
    np.testing.assert_allclose(after, out.numpy() - 0.5 * 1.0, atol=1e-6)
    f.stop_worker()
    assert server_done.wait(timeout=30)
    th.join(timeout=10)


# ----------------------------------------------------------- geo-SGD
def test_geo_sparse_table_dirty_tracking():
    from paddle_tpu.distributed.ps import GeoSparseTable
    t = GeoSparseTable(dim=2, trainer_num=3, initializer="constant",
                       init_range=0.0)
    t.pull([1, 2])  # materialize
    t.push_delta(0, [1], np.array([[1.0, 1.0]], np.float32))
    # trainer 0's own push doesn't dirty trainer 0
    ids0, _ = t.pull_geo(0)
    assert ids0.size == 0
    ids1, vals1 = t.pull_geo(1)
    assert ids1.tolist() == [1]
    np.testing.assert_allclose(vals1, [[1.0, 1.0]])
    # drained: second pull is empty
    ids1b, _ = t.pull_geo(1)
    assert ids1b.size == 0
    # trainer 2 still has it pending
    ids2, _ = t.pull_geo(2)
    assert ids2.tolist() == [1]


def test_geo_embedding_two_trainers_converge(cluster):
    """Two geo trainers sharing the PS: after both sync, both local
    replicas equal the server value = init + delta0 + delta1."""
    from paddle_tpu.distributed.ps import GeoDistributedEmbedding
    _, client = cluster
    dim = 4
    t0 = GeoDistributedEmbedding(11, dim, trainer_id=0, trainer_num=2,
                                 client=client, lr=0.5, sync_steps=1,
                                 initializer="constant", init_range=0.2)
    t1 = GeoDistributedEmbedding(11, dim, trainer_id=1, trainer_num=2,
                                 client=client, lr=0.5, sync_steps=10**9,
                                 initializer="constant", init_range=0.2)
    ids = paddle.to_tensor(np.array([3, 8], np.int64))  # both shards

    # each trainer runs one local step: loss = sum(out) → grad 1 per elt
    for tr in (t0, t1):
        out = tr(ids)
        out.sum().backward()
    # t0 synced automatically (sync_steps=1); t1 syncs manually
    t1.sync()
    # server merged both deltas: 0.2 - 0.5 - 0.5 = -0.8
    server_vals = client.pull_sparse(11, [3, 8])
    np.testing.assert_allclose(server_vals, -0.8, atol=1e-6)
    # t1 pushed then pulled: its replica is the merged value
    np.testing.assert_allclose(np.stack([t1._local[3], t1._local[8]]),
                               -0.8, atol=1e-6)
    # t0 synced BEFORE t1 pushed → still has only its own step; the next
    # sync absorbs t1's delta
    np.testing.assert_allclose(t0._local[3], -0.3, atol=1e-6)
    t0.sync()
    np.testing.assert_allclose(t0._local[3], -0.8, atol=1e-6)


def test_geo_embedding_trains_locally(cluster):
    """Single geo trainer: local SGD converges and, after sync, the
    server mirrors the local replica exactly."""
    from paddle_tpu.distributed.ps import GeoDistributedEmbedding
    _, client = cluster
    emb = GeoDistributedEmbedding(12, 8, trainer_id=0, trainer_num=1,
                                  client=client, lr=0.3, sync_steps=3)
    lin = paddle.nn.Linear(8, 2)
    opt = paddle.optimizer.SGD(parameters=lin.parameters(),
                               learning_rate=0.3)
    ids = paddle.to_tensor(np.array([[1, 5, 9], [2, 5, 7]], np.int64))
    labels = paddle.to_tensor(np.array([0, 1], np.int64))
    losses = []
    for _ in range(18):
        h = emb(ids).mean(axis=1)
        loss = paddle.nn.functional.cross_entropy(lin(h), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < 0.6 * losses[0], losses
    emb.sync()
    all_ids = sorted(emb._local)
    server_vals = client.pull_sparse(12, all_ids)
    local_vals = np.stack([emb._local[i] for i in all_ids])
    np.testing.assert_allclose(server_vals, local_vals, atol=1e-5)


def test_static_nn_sparse_embedding(cluster):
    """static.nn.sparse_embedding routes through the PS tier (reference
    static/nn/common.py:3691), including the geo table_class."""
    from paddle_tpu.distributed import ps as ps_mod
    _, client = cluster
    ps_mod._CTX["client"] = client  # bind as the PS-mode client
    try:
        import paddle_tpu.static as static
        ids = paddle.to_tensor(np.array([[1, 2], [3, 1]], np.int64))
        out = static.nn.sparse_embedding(
            ids, [100, 6], param_attr="emb_a")
        assert list(out.shape) == [2, 2, 6]
        out.sum().backward()  # pushes grads to the PS (sgd accessor)
        out2 = static.nn.sparse_embedding(
            ids, [100, 6], param_attr="emb_a")
        # same param name -> same table: values moved by the sgd step
        assert not np.allclose(out.numpy(), out2.numpy())
        # geo path shares one stateful replica across calls
        g1 = static.nn.sparse_embedding(
            ids, [100, 6], param_attr="emb_geo",
            table_class="MemorySparseGeoTable")
        g1.sum().backward()
        g2 = static.nn.sparse_embedding(
            ids, [100, 6], param_attr="emb_geo",
            table_class="MemorySparseGeoTable")
        assert not np.allclose(g1.numpy(), g2.numpy())
        # is_test freezes the lookup: output carries no grad graph and
        # repeated eval lookups see identical values
        frozen = static.nn.sparse_embedding(
            ids, [100, 6], param_attr="emb_a", is_test=True)
        assert frozen.stop_gradient
        after = static.nn.sparse_embedding(
            ids, [100, 6], param_attr="emb_a", is_test=True)
        np.testing.assert_allclose(frozen.numpy(), after.numpy())
    finally:
        ps_mod._CTX["client"] = None
        from paddle_tpu.static.nn import _GEO_LAYERS
        _GEO_LAYERS.clear()


PS_SERVER_PROC = r"""
import sys
sys.path.insert(0, {repo!r})
from paddle_tpu.distributed.fleet.fleet_base import Fleet
from paddle_tpu.distributed.ps import PaddleCloudRoleMaker
f = Fleet()
f.init(PaddleCloudRoleMaker())
assert f.is_server()
f.init_server()
print("server-ready", f.server_index(), flush=True)
f.run_server()
print("server-done", f.server_index(), flush=True)
"""

PS_WORKER_PROC = r"""
import sys
import numpy as np
sys.path.insert(0, {repo!r})
from paddle_tpu.distributed.fleet.fleet_base import Fleet
from paddle_tpu.distributed.ps import PaddleCloudRoleMaker
f = Fleet()
f.init(PaddleCloudRoleMaker())
assert f.is_worker()
rank = f.worker_index()
client = f.init_worker()
client.create_table(0, {{"type": "sparse", "dim": 2, "accessor": "sgd",
                         "lr": 0.5, "initializer": "constant",
                         "init_range": 0.1}})
# ids 7 and 8 land on different shards (id % 2)
if rank == 0:
    client.push_sparse(0, [7], np.ones((1, 2), np.float32))
f.barrier_worker()
if rank == 1:
    got = client.pull_sparse(0, [7])[0]
    np.testing.assert_allclose(got, 0.1 - 0.5, atol=1e-6)
    client.push_sparse(0, [8], 2 * np.ones((1, 2), np.float32))
f.barrier_worker()
if rank == 0:
    got = client.pull_sparse(0, [8])[0]
    np.testing.assert_allclose(got, 0.1 - 1.0, atol=1e-6)
f.barrier_worker()
print("worker-ok", rank, flush=True)
f.stop_worker()
"""


def test_ps_cross_process(tmp_path):
    """2 server + 2 worker PROCESSES over the reference env contract:
    cross-process row visibility on both shards, reusable barriers,
    worker-0-driven shutdown."""
    import subprocess
    import sys as _sys

    repo = __file__.rsplit("/tests/", 1)[0]
    ports = _free_ports(2)
    eps = ",".join(f"127.0.0.1:{p}" for p in ports)
    sscript = tmp_path / "ps_server.py"
    sscript.write_text(PS_SERVER_PROC.format(repo=repo))
    wscript = tmp_path / "ps_worker.py"
    wscript.write_text(PS_WORKER_PROC.format(repo=repo))

    import os as _os
    base = dict(_os.environ)
    base.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": "",
                 "PADDLE_PSERVERS_IP_PORT_LIST": eps,
                 "PADDLE_TRAINERS_NUM": "2",
                 "PADDLE_PS_TOKEN": "xproc-tok"})
    procs = []
    try:
        for i, p in enumerate(ports):
            env = {**base, "TRAINING_ROLE": "PSERVER",
                   "POD_IP": "127.0.0.1", "PADDLE_PORT": str(p)}
            procs.append(subprocess.Popen(
                [_sys.executable, str(sscript)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        for r in range(2):
            env = {**base, "TRAINING_ROLE": "TRAINER",
                   "PADDLE_TRAINER_ID": str(r)}
            procs.append(subprocess.Popen(
                [_sys.executable, str(wscript)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = [p.communicate(timeout=180)[0] for p in procs]
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert "server-done 0" in outs[0]
        assert "server-done 1" in outs[1]
        assert "worker-ok 0" in outs[2]
        assert "worker-ok 1" in outs[3]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_fleet_init_non_collective_env_and_stop_worker_noop(monkeypatch):
    """init(is_collective=False) with no role maker resolves roles from
    the env (reference contract); stop_worker outside PS mode is a
    no-op, not a crash."""
    from paddle_tpu.distributed.fleet.fleet_base import Fleet
    f = Fleet()
    f.init()  # collective
    f.stop_worker()  # must not raise
    f.stop_worker()  # idempotent

    (port,) = _free_ports(1)
    for k, v in {"PADDLE_PSERVERS_IP_PORT_LIST": f"127.0.0.1:{port}",
                 "PADDLE_TRAINERS_NUM": "1", "TRAINING_ROLE": "TRAINER",
                 "PADDLE_TRAINER_ID": "0"}.items():
        monkeypatch.setenv(k, v)
    # the PS transport refuses to run tokenless (pickle on the wire)
    monkeypatch.delenv("PADDLE_PS_TOKEN", raising=False)
    with pytest.raises(RuntimeError, match="PADDLE_PS_TOKEN"):
        Fleet().init(is_collective=False)
    monkeypatch.setenv("PADDLE_PS_TOKEN", "env-tok")
    f2 = Fleet()
    f2.init(is_collective=False)
    assert f2.is_worker() and not f2.is_server()
    assert f2.worker_num() == 1
    from paddle_tpu.distributed import ps as ps_mod
    ps_mod._reset()  # no server started; just unbind the client


def test_role_maker_env_validation(monkeypatch):
    from paddle_tpu.distributed.ps import PaddleCloudRoleMaker
    monkeypatch.delenv("PADDLE_PSERVERS_IP_PORT_LIST", raising=False)
    with pytest.raises(ValueError, match="PADDLE_PSERVERS_IP_PORT_LIST"):
        PaddleCloudRoleMaker()
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "127.0.0.1:1")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
    monkeypatch.setenv("TRAINING_ROLE", "BOGUS")
    with pytest.raises(ValueError, match="TRAINING_ROLE"):
        PaddleCloudRoleMaker()
    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    rm = PaddleCloudRoleMaker()
    assert rm._is_worker() and rm._worker_index() == 1
    assert rm._worker_num() == 2 and not rm._is_first_worker()
