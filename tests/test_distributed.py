"""Distributed core tests on the 8-device virtual CPU mesh.

Mirrors the reference's collective API tests (test/collective/
collective_allreduce_api.py etc. — SURVEY.md §4 mechanism 2), with the
virtual mesh playing the 8-GPU host.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import (Partial, ProcessMesh, Replicate, Shard,
                                    ReduceOp)


@pytest.fixture(autouse=True)
def _mesh():
    dist.set_mesh(dist.build_mesh({"dp": 8}))
    yield


def _ranked(shape_per_rank, n=8):
    """Build a dim0-sharded tensor whose shard i holds value i."""
    vals = np.stack([np.full(shape_per_rank, i, "float32") for i in range(n)])
    mesh = ProcessMesh(list(range(n)), dim_names=["dp"])
    return dist.shard_tensor(paddle.to_tensor(vals.reshape(
        (n * shape_per_rank[0],) + shape_per_rank[1:])), mesh, [Shard(0)]), mesh


class TestCollectives:
    def test_all_reduce_sum(self):
        t, _ = _ranked((1, 4))
        dist.all_reduce(t)
        np.testing.assert_allclose(t.numpy(), np.tile(
            np.full((1, 4), sum(range(8)), "float32"), (8, 1)))

    def test_all_reduce_max(self):
        t, _ = _ranked((1, 4))
        dist.all_reduce(t, op=ReduceOp.MAX)
        np.testing.assert_allclose(t.numpy(), np.full((8, 4), 7.0))

    def test_all_reduce_replicated_semantics(self):
        x = paddle.to_tensor(np.ones((2, 2), "float32"))
        dist.all_reduce(x)
        np.testing.assert_allclose(x.numpy(), np.full((2, 2), 8.0))

    def test_all_gather(self):
        t, _ = _ranked((2, 3))
        out = []
        dist.all_gather(out, t)
        assert len(out) == 8
        np.testing.assert_allclose(out[3].numpy(), np.full((2, 3), 3.0))

    def test_reduce_scatter(self):
        # every rank contributes [0..7]; rank i receives sum of chunk i
        vals = np.tile(np.arange(8, dtype="float32")[None], (8, 1)).reshape(-1)
        mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
        t = dist.shard_tensor(paddle.to_tensor(vals), mesh, [Shard(0)])
        out = dist.reduce_scatter(None, t)
        np.testing.assert_allclose(
            out.numpy(), np.repeat(np.arange(8) * 8.0, 1))

    def test_broadcast(self):
        t, _ = _ranked((1, 4))
        dist.broadcast(t, src=5)
        np.testing.assert_allclose(t.numpy(), np.full((8, 4), 5.0))

    def test_alltoall(self):
        # rank i sends tensor full(j) to rank j => rank j receives [full(j)]*8
        n = 8
        mesh = ProcessMesh(list(range(n)), dim_names=["dp"])
        vals = np.stack([np.arange(n, dtype="float32")] * n)  # row i = 0..7
        # stacked per-rank inputs: shard i (row i) has slabs for each dst
        stacked = vals.reshape(n * n, 1)
        t = dist.shard_tensor(paddle.to_tensor(stacked), mesh, [Shard(0)])
        ins = []
        from paddle_tpu.ops import manipulation
        # emulate list-of-tensors API: split the local stacked view
        out = dist.alltoall_single(None, t)
        res = out.numpy().reshape(n, n)
        # rank j's received block = column j of vals = all j's
        for j in range(n):
            np.testing.assert_allclose(res[j], np.full(n, j, "float32"))

    def test_barrier_and_groups(self):
        dist.barrier()
        g = dist.new_group(axes=("dp",))
        assert g.nranks == 8

    def test_shift_along_axis_in_graph(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        mesh = dist.get_mesh()
        x = jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P("dp")))
        f = jax.jit(shard_map(
            lambda a: dist.shift_along_axis(a, "dp", 1, mesh),
            mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")))
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


class TestAutoParallel:
    def test_shard_tensor_placements(self):
        mesh = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                           dim_names=["x", "y"])
        dist.set_mesh(dist.build_mesh({"x": 2, "y": 4}))
        x = paddle.to_tensor(np.arange(64, dtype="float32").reshape(8, 8))
        st = dist.shard_tensor(x, mesh, [Shard(0), Shard(1)])
        assert st.placements == [Shard(0), Shard(1)]
        sh = st.sharding
        assert sh is not None
        np.testing.assert_array_equal(st.numpy(), x.numpy())

    def test_reshard_s_to_r(self):
        mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
        x = paddle.to_tensor(np.arange(16, dtype="float32").reshape(8, 2))
        st = dist.shard_tensor(x, mesh, [Shard(0)])
        rt = dist.reshard(st, mesh, [Replicate()])
        assert rt.placements == [Replicate()]
        np.testing.assert_array_equal(rt.numpy(), x.numpy())

    def test_shard_layer_replicates_params(self):
        import paddle_tpu.nn as nn
        mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
        layer = nn.Linear(4, 4)
        dist.shard_layer(layer, mesh)
        assert layer.weight.sharding is not None

    def test_sharded_compute_produces_correct_values(self):
        """Ops on sharded tensors match single-device math (GSPMD)."""
        mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
        x = np.random.randn(16, 4).astype("float32")
        w = np.random.randn(4, 4).astype("float32")
        xs = dist.shard_tensor(paddle.to_tensor(x), mesh, [Shard(0)])
        wt = paddle.to_tensor(w)
        from paddle_tpu.ops import linalg
        out = linalg.matmul(xs, wt)
        np.testing.assert_allclose(out.numpy(), x @ w, rtol=1e-5, atol=1e-5)

    def test_data_parallel_end_to_end(self):
        """DP training step: sharded batch, replicated params, grads match
        the single-device run (the reference's EagerReducer correctness
        contract)."""
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        paddle.seed(0)
        net = nn.Linear(4, 2)
        w0 = net.weight.numpy().copy()
        x = np.random.randn(16, 4).astype("float32")
        y = np.random.randn(16, 2).astype("float32")

        # single-device reference grads
        loss_ref = F.mse_loss(net(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss_ref.backward()
        gref = net.weight.grad.numpy().copy()
        net.clear_gradients()

        dp = dist.DataParallel(net)
        loss = F.mse_loss(dp(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        np.testing.assert_allclose(float(loss.numpy()),
                                   float(loss_ref.numpy()), rtol=1e-5)
        np.testing.assert_allclose(net.weight.grad.numpy(), gref,
                                   rtol=1e-4, atol=1e-5)

    def test_shard_optimizer_states(self):
        import paddle_tpu.nn as nn
        from paddle_tpu import optimizer as optim
        mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
        layer = nn.Linear(8, 8)
        # shard weight rows over dp
        st = dist.shard_tensor(layer.weight, mesh, [Shard(0)])
        layer.weight._swap_payload(st._data)
        layer.weight.process_mesh = mesh
        layer.weight.placements = [Shard(0)]
        opt = dist.shard_optimizer(
            optim.Adam(learning_rate=0.1, parameters=layer.parameters()))
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        loss = F.mse_loss(layer(x), paddle.to_tensor(
            np.zeros((4, 8), "float32")))
        loss.backward()
        opt.step()
        m1 = opt._accumulators[id(layer.weight)]["moment1"]
        assert m1.sharding is not None
