"""tpulint self-tests: fixture corpus, seeded violations, baseline gate,
registry pass, and the in-graph edit_distance fix the analyzer motivated.

Tier-1 (fast, not slow): the repo must lint clean against the checked-in
baseline, and any seeded host-sync / tracer-leak / registry violation must
fail the gate.
"""
import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.tpulint import cli, registry_check, trace_safety, tracer_leak  # noqa: E402
from tools.tpulint.core import (SourceFile, diff_against_baseline,  # noqa: E402
                                load_baseline, save_baseline)

FIXTURES = os.path.join(REPO, "tests", "fixtures", "tpulint")
BASELINE = os.path.join(REPO, "tools", "tpulint", "baseline.json")
_EXPECT_RE = re.compile(r"#\s*expect:\s*([A-Z0-9,\s]+?)\s*$")


def _lint_file(path):
    sf = SourceFile(path, os.path.relpath(path, REPO))
    trace_safety.run(sf)
    tracer_leak.run(sf)
    return sf.findings


def _expected_by_line(path):
    out = {}
    with open(path) as f:
        for i, line in enumerate(f, 1):
            m = _EXPECT_RE.search(line)
            if m:
                out[i] = sorted(c.strip() for c in m.group(1).split(",")
                                if c.strip())
    return out


class TestFixtureCorpus:
    @pytest.mark.parametrize("name", ["flag_host_sync.py",
                                      "flag_tracer_leak.py"])
    def test_must_flag(self, name):
        path = os.path.join(FIXTURES, name)
        expected = _expected_by_line(path)
        assert expected, f"fixture {name} has no expect markers"
        got = {}
        for f in _lint_file(path):
            got.setdefault(f.line, []).append(f.code)
        got = {k: sorted(v) for k, v in got.items()}
        assert got == expected

    @pytest.mark.parametrize("name", ["ok_host_side.py", "ok_rebinds.py"])
    def test_must_not_flag(self, name):
        # quiet_scope / branch-trace style internals, static-metadata
        # branching, plain-numpy host math — and the re-bind /
        # container-emptiness / jit-wrapper FP classes (ok_rebinds.py,
        # fixed this round): all clean
        findings = _lint_file(os.path.join(FIXTURES, name))
        assert findings == []

    def test_every_tpu1xx_and_2xx_code_exercised(self):
        seen = set()
        for name in ("flag_host_sync.py", "flag_tracer_leak.py"):
            for codes in _expected_by_line(
                    os.path.join(FIXTURES, name)).values():
                seen.update(codes)
        assert {"TPU101", "TPU102", "TPU103", "TPU104", "TPU105", "TPU106",
                "TPU201", "TPU202", "TPU203"} <= seen


class TestSeededViolations:
    def _seed(self, tmp_path, body):
        p = tmp_path / "seeded.py"
        p.write_text("from paddle_tpu.core.tensor import Tensor, "
                     "as_tensor\nimport numpy as np\n" + body)
        return str(p)

    def test_seeded_host_sync_fails_gate(self, tmp_path):
        p = self._seed(tmp_path,
                       "def f(x):\n    return float(as_tensor(x))\n")
        assert cli.main([p, "--no-registry", "-q"]) == 1

    def test_seeded_tracer_leak_fails_gate(self, tmp_path):
        p = self._seed(tmp_path, "_G = {}\n\ndef f(x):\n"
                       "    _G['t'] = as_tensor(x)\n")
        assert cli.main([p, "--no-registry", "-q"]) == 1

    def test_suppression_comment_quiets_gate(self, tmp_path):
        p = self._seed(
            tmp_path, "def f(x):\n    return float(as_tensor(x))"
            "  # tpulint: disable=TPU103 — test boundary\n")
        assert cli.main([p, "--no-registry", "-q"]) == 0

    def test_update_baseline_roundtrip(self, tmp_path):
        p = self._seed(tmp_path,
                       "def f(x):\n    return float(as_tensor(x))\n")
        bl = str(tmp_path / "bl.json")
        assert cli.main([p, "--no-registry", "-q",
                         "--baseline", bl, "--update-baseline"]) == 0
        # frozen debt passes ...
        assert cli.main([p, "--no-registry", "-q", "--baseline", bl]) == 0
        # ... but NEW debt still fails
        with open(p, "a") as f:
            f.write("\ndef g(x):\n    return int(as_tensor(x))\n")
        assert cli.main([p, "--no-registry", "-q", "--baseline", bl]) == 1

    def test_seeded_registry_violation(self):
        from paddle_tpu.ops.registry import OPS, OpDef
        name = "_tpulint_seeded_bad_op"
        OPS[name] = OpDef(name=name, category="not_a_category",
                          lowering=lambda x: x, doc="",
                          inplace_variant="_tpulint_missing_")
        try:
            codes = {f.code for f in registry_check.run()
                     if f.line_text == f"op:{name}"}
            assert {"TPU301", "TPU302", "TPU303"} <= codes
        finally:
            del OPS[name]


class TestRepoGate:
    """The tier-1 gate: the tree must be clean vs the frozen baseline."""

    def test_repo_clean_against_baseline(self):
        findings = cli.collect_findings([os.path.join(REPO, "paddle_tpu")])
        new = diff_against_baseline(findings, load_baseline(BASELINE))
        assert new == [], "\n".join(f.render() for f in new[:25])

    def test_registry_debt_is_zero(self):
        # satellite: docs/categories backfilled — TPU3xx ships with an
        # EMPTY baseline, so every registry finding is a hard failure
        regs = [f for f in registry_check.run()]
        assert regs == [], "\n".join(f.render() for f in regs[:25])
        with open(BASELINE) as f:
            frozen = json.load(f)["findings"]
        assert not any("|TPU3" in k for k in frozen)

    def test_cli_module_entrypoint(self):
        # `python -m tools.tpulint` is the documented workflow
        r = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "--list-codes"],
            capture_output=True, text=True, cwd=REPO)
        assert r.returncode == 0 and "TPU101" in r.stdout

    def test_audit_reuses_tpulint_loader(self):
        from tools import op_parity_audit
        assert op_parity_audit.our_ops.__module__ != "tools.op_parity_audit" \
            or "load_registry" in op_parity_audit.our_ops.__code__.co_names


class TestEditDistanceInGraph:
    """The burn-down headliner: loss.py edit_distance computes the DP
    in-graph (vmapped wavefront over lax.cummin), so to_static captures it
    with NO graph break — the seed version np.asarray'd the inputs."""

    def _ref(self, a, b, ign=(), normalized=False):
        out = []
        for s1, s2 in zip(a, b):
            s1 = [t for t in s1 if t not in ign]
            s2 = [t for t in s2 if t not in ign]
            m, n = len(s1), len(s2)
            dp = list(range(n + 1))
            for r in range(1, m + 1):
                prev, dp = dp, [r] + [0] * n
                for c in range(1, n + 1):
                    dp[c] = min(prev[c] + 1, dp[c - 1] + 1,
                                prev[c - 1] + (s1[r - 1] != s2[c - 1]))
            d = dp[n] / max(n, 1) if normalized else dp[n]
            out.append(d)
        return np.asarray(out, np.float32).reshape(-1, 1)

    def test_eager_matches_reference(self):
        import paddle_tpu as paddle
        F = paddle.nn.functional
        a = paddle.to_tensor([[1, 2, 3, 4], [5, 5, 5, 5]])
        b = paddle.to_tensor([[1, 9, 3, 4], [5, 6, 7, 8]])
        d, n = F.edit_distance(a, b, normalized=False)
        np.testing.assert_allclose(d.numpy(),
                                   self._ref([[1, 2, 3, 4], [5, 5, 5, 5]],
                                             [[1, 9, 3, 4], [5, 6, 7, 8]]))
        assert int(n.numpy()[0]) == 2

    def test_lengths_ignored_tokens_normalized(self):
        import paddle_tpu as paddle
        F = paddle.nn.functional
        a_np = [[1, 2, 0, 7], [3, 3, 1, 2]]
        b_np = [[1, 3, 0, 0], [3, 1, 2, 9]]
        il, ll = [3, 4], [4, 3]
        d, _ = F.edit_distance(
            paddle.to_tensor(a_np), paddle.to_tensor(b_np), normalized=True,
            ignored_tokens=[0], input_length=paddle.to_tensor(il),
            label_length=paddle.to_tensor(ll))
        ref = self._ref([r[:l] for r, l in zip(a_np, il)],
                        [r[:l] for r, l in zip(b_np, ll)],
                        ign=(0,), normalized=True)
        np.testing.assert_allclose(d.numpy(), ref, rtol=1e-6)

    def test_to_static_parity_no_graph_break(self):
        import paddle_tpu as paddle
        F = paddle.nn.functional

        def f(a, b):
            d, _ = F.edit_distance(a, b, normalized=True)
            return d

        st = paddle.jit.to_static(f, full_graph=True)
        a = paddle.to_tensor([[1, 2, 3], [4, 5, 6]])
        b = paddle.to_tensor([[1, 3, 3], [9, 9, 9]])
        np.testing.assert_allclose(st(a, b).numpy(), f(a, b).numpy())
        assert st.graph_break_reason is None

    def test_tpulint_no_longer_flags_edit_distance(self):
        import inspect
        from paddle_tpu.nn.functional import loss as loss_mod
        src_path = inspect.getsourcefile(loss_mod)
        lines, start = inspect.getsourcelines(loss_mod.edit_distance)
        sf = SourceFile(src_path, "paddle_tpu/nn/functional/loss.py")
        trace_safety.run(sf)
        hits = [f for f in sf.findings
                if start <= f.line < start + len(lines)]
        assert hits == [], "\n".join(f.render() for f in hits)
