"""fleet.utils.fs: LocalFS on a real tmp dir; HDFSClient against a faked
hadoop shell (command construction + ls parsing + retry/abort contract).

Reference: python/paddle/distributed/fleet/utils/fs.py (LocalFS :114,
HDFSClient :446, exit 134 -> FSShellCmdAborted).
"""
import pytest

from paddle_tpu.distributed.fleet.utils import (ExecuteError,
                                                FSFileExistsError,
                                                FSFileNotExistsError,
                                                FSShellCmdAborted,
                                                HDFSClient, LocalFS)


class TestLocalFS:
    def test_ls_and_list_dirs(self, tmp_path):
        fs = LocalFS()
        (tmp_path / "d1").mkdir()
        (tmp_path / "d2").mkdir()
        (tmp_path / "f1").write_text("x")
        dirs, files = fs.ls_dir(str(tmp_path))
        assert sorted(dirs) == ["d1", "d2"] and files == ["f1"]
        assert sorted(fs.list_dirs(str(tmp_path))) == ["d1", "d2"]
        assert fs.ls_dir(str(tmp_path / "missing")) == ([], [])

    def test_touch_mv_delete(self, tmp_path):
        fs = LocalFS()
        src = str(tmp_path / "a")
        dst = str(tmp_path / "b")
        fs.touch(src)
        with pytest.raises(FSFileExistsError):
            fs.touch(src, exist_ok=False)
        fs.mv(src, dst)
        assert not fs.is_exist(src) and fs.is_file(dst)
        with pytest.raises(FSFileNotExistsError):
            fs.mv(src, dst)
        fs.touch(src)
        with pytest.raises(FSFileExistsError):
            fs.mv(src, dst)  # dst exists, no overwrite
        fs.mv(src, dst, overwrite=True)
        assert fs.is_file(dst)
        fs.delete(dst)
        assert not fs.is_exist(dst)
        fs.delete(dst)  # idempotent

    def test_mkdirs_upload_cat(self, tmp_path):
        fs = LocalFS()
        d = str(tmp_path / "x" / "y")
        fs.mkdirs(d)
        assert fs.is_dir(d)
        f = tmp_path / "src.txt"
        f.write_text("hello\n")
        fs.upload(str(f), str(tmp_path / "x" / "dst.txt"))
        assert fs.cat(str(tmp_path / "x" / "dst.txt")) == "hello"
        assert not fs.need_upload_download()
        with pytest.raises(AssertionError):
            fs.mkdirs(str(f))  # path is a file


class _FakeHDFS(HDFSClient):
    """HDFSClient with the shell replaced by an in-memory fake."""

    def __init__(self, tree=None, fail_times=0, abort=False):
        super().__init__("/opt/hadoop", {"fs.default.name": "hdfs://nn:54310"})
        self.tree = tree or {}
        self.calls = []
        self.fail_times = fail_times
        self.abort = abort

    def _shell(self, exe_cmd):
        self.calls.append(exe_cmd)
        assert exe_cmd.startswith(
            "/opt/hadoop/bin/hadoop fs -Dfs.default.name=hdfs://nn:54310 -")
        cmd = exe_cmd.split(" -Dfs.default.name=hdfs://nn:54310 -", 1)[1]
        if self.abort:
            return 134, ""
        if self.fail_times > 0:
            self.fail_times -= 1
            return 1, "transient"
        op, _, rest = cmd.partition(" ")
        if op == "test":
            flag, path = rest.split()
            flag = flag.lstrip("-")
            entry = self.tree.get(path)
            ok = (entry is not None and
                  (flag == "e" or (flag == "d") == (entry == "dir")))
            return (0 if ok else 1), ""
        if op == "ls":
            lines = ["Found 3 items"]
            for name, kind in self.tree.get(rest, {}).items() \
                    if isinstance(self.tree.get(rest), dict) else []:
                bits = "drwxr-xr-x" if kind == "dir" else "-rw-r--r--"
                lines.append(f"{bits} 3 u g 0 2026-07-31 10:00 "
                             f"{rest}/{name}")
            return 0, "\n".join(lines)
        return 0, ""


class TestHDFSClient:
    def test_command_construction_and_test_flags(self):
        fs = _FakeHDFS(tree={"/a": "file", "/d": "dir"})
        assert fs.is_file("/a") and not fs.is_dir("/a")
        assert fs.is_dir("/d") and fs.is_exist("/d")
        assert not fs.is_exist("/missing")
        assert fs.calls[0].endswith("-test -f /a")
        assert fs.need_upload_download()

    def test_ls_parsing_skips_non_entry_lines(self):
        # a dict value marks an existing directory whose -ls output has a
        # "Found N items" header the 8-column parse must skip
        fs = _FakeHDFS(tree={"/data": {"sub": "dir", "part-0": "file"}})
        dirs, files = fs.ls_dir("/data")
        assert dirs == ["sub"] and files == ["part-0"]

    def test_retry_then_success(self):
        fs = _FakeHDFS(tree={"/x": "file"}, fail_times=2)
        fs._sleep_inter = 0
        ret, _ = fs._run_cmd("put /l /x")
        assert ret == 0
        assert len(fs.calls) == 3  # 2 failures + 1 success

    def test_abort_raises(self):
        fs = _FakeHDFS(abort=True)
        fs._sleep_inter = 0
        with pytest.raises(FSShellCmdAborted):
            fs._run_cmd("rm -r /x")

    def test_upload_missing_local_raises(self, tmp_path):
        fs = _FakeHDFS()
        with pytest.raises(FSFileNotExistsError):
            fs.upload(str(tmp_path / "nope"), "/dst")

    def test_mv_contract(self):
        fs = _FakeHDFS(tree={"/src": "file"})
        fs.mv("/src", "/dst")
        assert any(c.endswith("-mv /src /dst") for c in fs.calls)
        with pytest.raises(FSFileNotExistsError):
            fs.mv("/gone", "/dst2")
