"""Multi-process training worker for the TestDistBase-style parity harness.

Runs under ``python -m paddle_tpu.distributed.launch`` (which exports the
jax.distributed coordinates). Every rank builds the SAME model (seeded) and
feeds the SAME deterministic global batch each step; the parallel wrappers
shard it over the mesh. Losses are written per-rank for the harness to
compare against the single-process baseline.

Reference contract: test/legacy_test/test_dist_base.py:952 (TestDistBase
forks trainer processes, trains the same model, compares multi-process loss
to the single-process run) and the per-strategy launcher scripts under
test/collective/fleet/ (e.g. dygraph_group_sharded_stage2.py,
hybrid_parallel_pp_alexnet.py).

Usage: dist_train_worker.py <strategy> <outdir>
  strategy: single | dp | dp_sharding | dp_mp | dp_pp | dp_sep
          | auto_tp | auto_fsdp

The auto_* strategies train the SAME plain GPT through the SPMD
sharding-propagation subsystem (distributed.spmd): one mesh declaration
(data×tp / data×fsdp) + regex param rules, per-op spmd_rules annotate
the whole jitted step, GSPMD picks the collectives — no fleet parallel
layers. Their losses must match the single-process baseline exactly
like the hand-built paths do, and the worker asserts ZERO
replicate-fallback ops.
"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

STRATEGY = sys.argv[1]
OUTDIR = sys.argv[2]

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
import paddle_tpu.distributed.fleet as fleet_pkg  # noqa: E402
from paddle_tpu.models import GPTConfig, GPTForCausalLM  # noqa: E402

dist.init_parallel_env()
world = jax.process_count()
rank = jax.process_index()
# degrees are over DEVICES: N processes x 1 device each, or 1 process
# with an N-device virtual mesh — the parity the harness asserts is that
# these two are the same program
ndev = jax.device_count()

strategy = fleet_pkg.DistributedStrategy()
if STRATEGY in ("auto_tp", "auto_fsdp"):
    pass  # no fleet wrappers: the spmd subsystem owns the mesh
elif STRATEGY == "dp_sharding":
    strategy.hybrid_configs = {"dp_degree": ndev // 2,
                               "sharding_degree": 2}
elif STRATEGY == "dp_mp":
    strategy.hybrid_configs = {"dp_degree": ndev // 2, "mp_degree": 2}
elif STRATEGY == "dp_pp":
    strategy.hybrid_configs = {"dp_degree": ndev // 2, "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "schedule_mode": "1F1B"}
elif STRATEGY == "dp_sep":
    strategy.hybrid_configs = {"dp_degree": ndev // 2, "sep_degree": 2}
fleet_pkg.fleet.init(is_collective=True, strategy=strategy)

paddle.seed(1234)
GLOBAL_BATCH, SEQ, STEPS = 8, 16, 6
rng = np.random.RandomState(0)  # identical stream on every rank
losses = []

if STRATEGY in ("auto_tp", "auto_fsdp"):
    # SPMD auto-sharding: plain GPT + one mesh declaration + regex
    # param-placement rules; the Engine traces ONE step under the
    # propagation scope and XLA partitions it. Batches enter the jit
    # uncommitted (identical on every process) — the seeded
    # with_sharding_constraint inside the program distributes them, so
    # the same worker runs single- and multi-process unchanged.
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import nn, ops
    from paddle_tpu.distributed import mesh as mesh_mod, spmd
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.nn import functional as F

    axis = "tp" if STRATEGY == "auto_tp" else "fsdp"
    mesh = mesh_mod.build_mesh({"data": ndev // 2, axis: 2})
    mesh_mod.set_mesh(mesh)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=16,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    if STRATEGY == "auto_tp":
        rules = [
            (r".*qkv_proj\.weight", P(None, "tp")),
            (r".*qkv_proj\.bias", P("tp")),
            (r".*fc1\.weight", P(None, "tp")),
            (r".*fc1\.bias", P("tp")),
            (r".*(out_proj|fc2)\.weight", P("tp", None)),
            (r".*wte\.weight", P("tp", None)),
        ]
    else:
        rules = [(r".*\.weight", P("fsdp")), (r".*\.bias", P("fsdp"))]
    placed = spmd.shard_params(model, mesh, rules)
    assert placed, "no parameter matched a placement rule"

    class _LM(nn.Layer):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, x):
            return self.inner(x)  # logits

    def _loss(logits, y):
        v = logits.shape[-1]
        return F.cross_entropy(
            ops.reshape(logits[:, :-1, :], [-1, v]),
            ops.reshape(y[:, 1:], [-1]))

    engine = Engine(_LM(model), loss=_loss,
                    optimizer=paddle.optimizer.AdamW(
                        learning_rate=1e-2,
                        parameters=model.parameters()),
                    mesh=mesh, in_specs=(P("data"), P("data")))
    engine.prepare()
    fixed = rng.randint(0, cfg.vocab_size,
                        (GLOBAL_BATCH, SEQ)).astype(np.int64)
    pa = [p._data for p in engine._params]
    opt_state = engine._init_opt_state(pa)
    for step in range(STEPS):
        lr = jnp.asarray(1e-2, jnp.float32)
        loss, pa, opt_state = engine._train_step(pa, opt_state, lr,
                                                 fixed, fixed)
        losses.append(float(np.asarray(loss)))
    assert engine.spmd_stats is not None
    assert not engine.spmd_stats["fallback"], \
        f"replicate-fallback ops: {engine.spmd_stats['fallback']}"
elif STRATEGY == "dp_pp":
    # pipeline path: a 4-block MLP stack over pp=2 stages trained with
    # fleet's train_batch (scan + ppermute SPMD pipeline, cross-process)
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer

    D = 16

    class _Blk(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(D, D)

        def forward(self, x):
            return paddle.ops.tanh(self.fc(x))

    pl = PipelineLayer(
        layers=[LayerDesc(_Blk) for _ in range(4)], num_stages=2,
        loss_fn=lambda o, y: paddle.ops.mean((o - y) ** 2))
    ppm = fleet_pkg.fleet.distributed_model(pl)
    opt = fleet_pkg.fleet.distributed_optimizer(
        paddle.optimizer.AdamW(learning_rate=1e-2,
                               parameters=pl.parameters()))
    xb = paddle.to_tensor(rng.randn(GLOBAL_BATCH, D).astype(np.float32))
    yb = paddle.to_tensor(
        rng.randn(GLOBAL_BATCH, D).astype(np.float32) * 0.1)
    for step in range(STEPS):
        losses.append(float(ppm.train_batch((xb, yb), opt).numpy()))
elif STRATEGY == "dp_sep":
    # context-parallel path: ring flash attention over the sep axis
    # (lax.scan + ppermute ring), trained cross-process
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.fleet import ring_flash_attention

    mesh = mesh_mod.get_mesh()
    wq = paddle.Tensor(jnp.eye(8, dtype=jnp.float32) * 0.5,
                       stop_gradient=False)
    xs_np = rng.randn(2, 32, 4, 8).astype(np.float32)
    xs = paddle.Tensor(jax.device_put(
        jnp.asarray(xs_np),
        NamedSharding(mesh, P(None, "sep", None, None))))
    for step in range(STEPS):
        q = paddle.ops.matmul(xs, wq)
        attn = ring_flash_attention(q, xs, xs, causal=True)
        loss = paddle.ops.mean((attn - xs) ** 2)
        loss.backward()
        wq._swap_payload(wq._data - 2.0 * wq.grad._data)
        wq.clear_grad()
        losses.append(float(loss.numpy()))
else:
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=16,
                    use_flash_attention=False,
                    mp_degree=2 if STRATEGY == "dp_mp" else 1)
    model = GPTForCausalLM(cfg)
    model = fleet_pkg.fleet.distributed_model(model)
    opt = fleet_pkg.fleet.distributed_optimizer(
        paddle.optimizer.AdamW(learning_rate=1e-2,
                               parameters=model.parameters()))
    fixed = rng.randint(0, cfg.vocab_size,
                        (GLOBAL_BATCH, SEQ)).astype(np.int64)
    for step in range(STEPS):
        # one fixed batch: the loss must DESCEND, so parity is a
        # statement about the whole train step (fwd + bwd + optimizer)
        ids = paddle.to_tensor(fixed)
        _, loss = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))

assert all(np.isfinite(losses)), losses
with open(os.path.join(OUTDIR, f"losses.{STRATEGY}.r{rank}.json"), "w") as f:
    json.dump({"strategy": STRATEGY, "world": world, "rank": rank,
               "losses": losses}, f)
print(f"trained {STRATEGY} rank={rank}/{world} losses={losses}", flush=True)
