"""Round-4 batch 2: static.nn parity tail + EMA, serving native-dtype KV
and batched prefill.

Reference contracts: static/nn/common.py (fc:48, instance_norm:271,
conv2d:779, batch_norm:2616, py_func:3118, spectral_norm:3417,
layer_norm:3555, ExponentialMovingAverage:4040);
block_multi_head_attention serving family.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn

static = paddle.static


class TestStaticNN:
    def test_conv_bn_layer_norm_build_and_run(self):
        paddle.seed(3)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 1, 8, 8], "float32")
            h = static.nn.conv2d(x, 4, 3, padding=1, act="relu")
            h = static.nn.batch_norm(h)
            h = h.flatten(start_axis=1)
            h = static.nn.layer_norm(h, begin_norm_axis=1)
            out = static.nn.fc(h, 3)
        exe = static.Executor()
        for b in (2, 5):
            xv = np.random.RandomState(b).randn(b, 1, 8, 8).astype(
                np.float32)
            (o,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
            assert o.shape == (b, 3)
            assert np.isfinite(o).all()

    def test_static_lenet_end_to_end(self):
        """BASELINE ladder config 1 built ONLY from static.nn primitives."""
        paddle.seed(5)
        main = static.Program()
        with static.program_guard(main):
            img = static.data("img", [None, 1, 28, 28], "float32")
            c1 = static.nn.conv2d(img, 6, 5, padding=2, act="relu")
            p1 = nn.functional.max_pool2d(c1, 2, 2)
            c2 = static.nn.conv2d(p1, 16, 5, act="relu")
            p2 = nn.functional.max_pool2d(c2, 2, 2)
            flat = p2.flatten(start_axis=1)
            f1 = static.nn.fc(flat, 120, activation="relu")
            f2 = static.nn.fc(f1, 84, activation="relu")
            logits = static.nn.fc(f2, 10)
        exe = static.Executor()
        xv = np.random.RandomState(0).randn(4, 1, 28, 28).astype(
            np.float32)
        (o,) = exe.run(main, feed={"img": xv}, fetch_list=[logits])
        assert o.shape == (4, 10) and np.isfinite(o).all()

    def test_instance_and_spectral_norm(self):
        paddle.seed(1)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, 5, 5).astype(np.float32))
        out = static.nn.instance_norm(x)
        # per-(sample, channel) spatial statistics are normalized
        v = out.numpy().reshape(2, 3, -1)
        np.testing.assert_allclose(v.mean(-1), 0.0, atol=1e-4)
        w = paddle.to_tensor(
            np.random.RandomState(1).randn(6, 4).astype(np.float32))
        wn = static.nn.spectral_norm(w, power_iters=20)
        s = np.linalg.svd(wn.numpy(), compute_uv=False)[0]
        assert abs(s - 1.0) < 1e-2   # largest singular value ~ 1

    def test_py_func_forward_and_backward(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        x.stop_gradient = False
        template = paddle.to_tensor(np.zeros((2, 3), np.float32))
        out = static.nn.py_func(
            lambda a: a * 3.0, x, template,
            backward_func=lambda g: g * 3.0)
        np.testing.assert_allclose(out.numpy(), x.numpy() * 3.0)
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   np.full((2, 3), 3.0, np.float32))


class TestEMA:
    def test_shadow_average_matches_hand_rolled(self):
        paddle.seed(7)
        m = nn.Linear(4, 2)
        ema = static.ExponentialMovingAverage(
            decay=0.9, parameters=m.parameters())
        w0 = m.weight.numpy().copy()
        shadow = w0.copy()
        from paddle_tpu.optimizer import SGD
        opt = SGD(learning_rate=0.1, parameters=m.parameters())
        for i in range(3):
            x = paddle.to_tensor(
                np.random.RandomState(i).randn(3, 4).astype(np.float32))
            (m(x) ** 2).mean().backward()
            opt.step()
            opt.clear_grad()
            ema.update()
            shadow = 0.9 * shadow + 0.1 * m.weight.numpy()
        live = m.weight.numpy().copy()
        with ema.apply():
            np.testing.assert_allclose(m.weight.numpy(), shadow,
                                       rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(m.weight.numpy(), live)  # restored

    def test_apply_without_restore(self):
        paddle.seed(7)
        m = nn.Linear(4, 2)
        ema = static.ExponentialMovingAverage(
            decay=0.5, parameters=m.parameters())
        ema.update()
        ctx = ema.apply(need_restore=False)
        with ctx:
            pass
        # shadows remain applied; explicit restore is still possible
        ema.restore()


class TestServingUpgrades:
    def _tiny_llama(self, dtype=None):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_layers=2, num_heads=4,
                          max_seq_len=128, use_flash_attention=False)
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        if dtype is not None:
            for p in m.parameters():
                p._swap_payload(p._data.astype(dtype))
        return m

    def test_kv_dtype_follows_model(self):
        from paddle_tpu.inference.serving import PagedEngine
        eng32 = PagedEngine(self._tiny_llama(), num_blocks=16)
        assert eng32.kv_dtype == jnp.float32
        m16 = self._tiny_llama(jnp.bfloat16)
        eng16 = PagedEngine(m16, num_blocks=16)
        assert eng16.kv_dtype == jnp.bfloat16
        # capacity: same block count costs half the HBM in bf16
        assert (eng16.kc[0].nbytes * 2) == eng32.kc[0].nbytes

    def test_bf16_engine_generates(self):
        from paddle_tpu.inference.serving import PagedEngine
        m = self._tiny_llama(jnp.bfloat16)
        eng = PagedEngine(m, num_blocks=32, max_batch=2)
        eng.add_request([5, 6, 7], max_new_tokens=4)
        out = eng.run_to_completion()
        assert len(out) == 1 and len(list(out.values())[0]) == 4

    def test_batched_prefill_fewer_calls(self):
        """4 same-tick admissions must issue far fewer prefill programs
        than 4 sequential per-request chunk loops (>=2x fewer)."""
        from paddle_tpu.inference import serving as S
        m = self._tiny_llama()
        eng = S.PagedEngine(m, max_batch=4, block_size=8, num_blocks=64)
        calls = {"n": 0}
        orig = eng._run_chunk

        def counting(*a, **k):
            calls["n"] += 1
            return orig(*a, **k)

        eng._run_chunk = counting
        # 4 requests, prompts spanning 2 chunks each -> sequential would
        # be 8 prefill calls; batched is 2
        for r in range(4):
            eng.add_request(list(range(1, 11)), max_new_tokens=1)
        eng._admit()
        assert calls["n"] <= 4  # 2 chunk ticks (+0 decode yet)
        assert calls["n"] * 2 <= 8

    def test_prefill_parity_mixed_lengths(self):
        """Batched left-padded prefill must produce the same first token
        as the unbatched path for every request."""
        from paddle_tpu.inference.serving import PagedEngine
        m = self._tiny_llama()
        prompts = [[3, 1, 4, 1, 5], [9, 2], [6, 5, 3, 5, 8, 9, 7, 9, 3],
                   [2, 7]]
        # batched: all admitted in one tick
        eng = PagedEngine(m, max_batch=4, block_size=4, num_blocks=64)
        for p in prompts:
            eng.add_request(p, max_new_tokens=1)
        batched = eng.run_to_completion()
        # singly: one at a time
        singles = {}
        for p in prompts:
            e1 = PagedEngine(m, max_batch=1, block_size=4, num_blocks=64)
            rid = e1.add_request(p, max_new_tokens=1)
            singles[tuple(p)] = e1.run_to_completion()[rid]
        got = {tuple(p): batched[i + 1] for i, p in enumerate(prompts)}
        assert got == {tuple(p): singles[tuple(p)] for p in prompts}

    def test_run_to_completion_with_never_fitting_request(self):
        # round 11: never-fitting requests are a terminal FAILED status
        # at submit time (no MemoryError out of the serving loop); the
        # servable request's results are returned normally
        from paddle_tpu.inference.serving import PagedEngine, RequestStatus
        m = self._tiny_llama()
        eng = PagedEngine(m, max_batch=2, block_size=4, num_blocks=8,
                          max_blocks_per_seq=4)
        ok = eng.add_request([1, 2, 3], max_new_tokens=2)
        bad = eng.add_request(list(range(1, 40)), max_new_tokens=8)
        assert eng.outcomes[bad].status == RequestStatus.FAILED
        assert eng.rejected[bad]
        out = eng.run_to_completion()
        assert len(out[ok]) == 2
        assert bad not in out

    def test_gpt_position_overflow_rejected_at_add(self):
        from paddle_tpu.inference.serving import PagedEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=16,
                        use_flash_attention=False)
        paddle.seed(0)
        eng = PagedEngine(GPTForCausalLM(cfg), num_blocks=16)
        with pytest.raises(ValueError, match="position table"):
            eng.add_request(list(range(1, 13)), max_new_tokens=8)
