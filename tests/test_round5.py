"""Round-5 additions.

1. Auto-parallel Engine consumes the optimizer package's functional core
   (VERDICT r4 Missing/Weak #3: no more private 4-optimizer subset inside
   prepare()) — every suite optimizer trains through the Engine, LBFGS is
   rejected with a clear error, and LR schedulers tick without retracing.
   Reference contract:
   python/paddle/distributed/auto_parallel/static/engine.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


@pytest.fixture
def dp_mesh():
    from paddle_tpu.distributed import mesh as mesh_mod
    old = mesh_mod._global_mesh
    yield mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
    mesh_mod._global_mesh = old


class _Reg:
    """Tiny fixed regression dataset."""

    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        self.y = (self.x @ rng.randn(8, 4) * 0.5).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _mse(out, y):
    return paddle.ops.mean((out - y) ** 2)


OPTIMIZERS = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta",
              "RMSProp", "Lamb", "NAdam", "RAdam", "Adamax", "ASGD",
              "Rprop"]


@pytest.mark.parametrize("opt_name", OPTIMIZERS)
def test_engine_trains_with_every_suite_optimizer(opt_name, dp_mesh):
    """Row 43's closing condition: the Engine runs the REAL optimizer
    package's update rule, so all of it works — not just Adam/SGD."""
    import paddle_tpu.distributed as dist

    paddle.seed(7)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    cls = getattr(paddle.optimizer, opt_name)
    opt = cls(learning_rate=1e-2, parameters=net.parameters())
    engine = dist.Engine(net, loss=_mse, optimizer=opt)
    hist = engine.fit(_Reg(), epochs=3, batch_size=16)
    assert np.isfinite(hist).all(), (opt_name, hist)
    assert hist[-1] < hist[0], (opt_name, hist)


def test_engine_rejects_lbfgs(dp_mesh):
    import paddle_tpu.distributed as dist

    net = nn.Linear(4, 2)
    opt = paddle.optimizer.LBFGS(parameters=net.parameters())
    with pytest.raises(TypeError, match="LBFGS"):
        dist.Engine(net, loss=_mse, optimizer=opt).prepare()


def test_engine_matches_eager_adam_exactly(dp_mesh):
    """The Engine's SPMD step and the eager optimizer are ONE update
    implementation — training the same model either way must agree."""
    import paddle_tpu.distributed as dist

    ds = _Reg(32)

    def build():
        paddle.seed(11)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=net.parameters())
        return net, opt

    # eager loop over the full dataset as one batch, 5 steps
    net_e, opt_e = build()
    xs = paddle.to_tensor(ds.x)
    ys = paddle.to_tensor(ds.y)
    for _ in range(5):
        loss = _mse(net_e(xs), ys)
        loss.backward()
        opt_e.step()
        opt_e.clear_grad()

    # engine: same data as one batch per step, 5 steps (epochs=5 over a
    # one-batch loader, shuffle is a no-op for a single batch)
    net_g, opt_g = build()
    engine = dist.Engine(net_g, loss=_mse, optimizer=opt_g)
    engine.fit(ds, epochs=5, batch_size=32)

    for pe, pg in zip(net_e.parameters(), net_g.parameters()):
        np.testing.assert_allclose(pe.numpy(), pg.numpy(), rtol=1e-4,
                                   atol=1e-5)


def test_engine_lr_schedule_no_retrace(dp_mesh):
    """The LR enters the compiled step as a traced scalar: a scheduler
    stepping every batch must not trigger recompilation."""
    import paddle_tpu.distributed as dist

    paddle.seed(5)
    net = nn.Linear(8, 4)
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.05, step_size=1,
                                          gamma=0.5)
    opt = paddle.optimizer.Momentum(learning_rate=sched,
                                    parameters=net.parameters())
    engine = dist.Engine(net, loss=_mse, optimizer=opt).prepare()

    # count TRACES (python executions of the step fn), not calls: re-jit
    # the same underlying python fn with a counter wrapped around it
    import jax

    traces = []
    fn = engine._train_step.__wrapped__

    def counting(*a):
        traces.append(1)
        return fn(*a)

    engine._train_step = jax.jit(counting)

    # one fit, 8 steps, 8 DISTINCT lr values. The first two calls may
    # trace (input shardings change once, host arrays -> jit outputs);
    # beyond that, traces must NOT scale with lr changes.
    hist = engine.fit(_Reg(32), epochs=8, batch_size=32)
    assert len(hist) == 8
    assert len(traces) <= 2, \
        f"step retraced {len(traces)} times over 8 lr values"
    assert opt.get_lr() == pytest.approx(0.05 * 0.5 ** 8)


def test_engine_writes_back_optimizer_state(dp_mesh):
    """After fit, the eager optimizer continues from the Engine's state
    (accumulators + step count), so checkpoints and mixed usage agree."""
    import paddle_tpu.distributed as dist

    paddle.seed(13)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    engine = dist.Engine(net, loss=_mse, optimizer=opt)
    engine.fit(_Reg(32), epochs=2, batch_size=32)
    assert opt._step_count == 2
    for p in net.parameters():
        if p.stop_gradient:
            continue
        st = opt._accumulators.get(id(p))
        assert st is not None and any(
            float(np.abs(np.asarray(v)).sum()) > 0 for v in st.values())


# --------------------------------------------------------------- autotuner
class TestAutotune:
    """VERDICT r4 #3: measured per-shape/per-chip kernel tuning with a
    restart-persistent cache (reference phi/kernels/autotune/cache.h +
    switch_autotune.cc)."""

    def _fresh(self, tmp_path, monkeypatch):
        from paddle_tpu.ops.pallas import autotune as at
        path = str(tmp_path / "autotune.json")
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE", path)
        cache = at.AutotuneCache(path)
        return at, path, cache

    def test_cache_disk_round_trip(self, tmp_path, monkeypatch):
        at, path, cache = self._fresh(tmp_path, monkeypatch)
        cache.put("flash_fwd|v5e|sq=8192", [1024, 512])
        # a different process = a different cache object, same file
        cache2 = at.AutotuneCache(path)
        assert cache2.get("flash_fwd|v5e|sq=8192") == [1024, 512]

    def test_cache_merges_concurrent_writers(self, tmp_path, monkeypatch):
        at, path, c1 = self._fresh(tmp_path, monkeypatch)
        c2 = at.AutotuneCache(path)
        c1.put("k1", 1)
        c2.put("k2", 2)     # must not clobber k1
        c3 = at.AutotuneCache(path)
        assert c3.get("k1") == 1 and c3.get("k2") == 2

    def test_autotune_picks_fastest_and_caches(self, tmp_path, monkeypatch):
        import time
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setattr(at, "_cache",
                            at.AutotuneCache(str(tmp_path / "a.json")))
        calls = []

        def run(c, i):
            calls.append(c)
            time.sleep(0.02 if c == (512, 512) else 0.001)
            return jnp.zeros(())

        won = at.autotune("k", [(512, 512), (1024, 1024)], run,
                          default=(256, 256), warmup=1, iters=2)
        assert won == (1024, 1024)
        n = len(calls)
        # second sight: pure cache hit, no measuring
        won2 = at.autotune("k", [(512, 512), (1024, 1024)], run,
                           default=(256, 256))
        assert won2 == (1024, 1024) and len(calls) == n
        # a fresh process reads the winner from disk (tuple via JSON list)
        at2_cache = at.AutotuneCache(str(tmp_path / "a.json"))
        assert tuple(at2_cache.get("k")) == (1024, 1024)

    def test_autotune_skips_failing_candidates(self, tmp_path, monkeypatch):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setattr(at, "_cache",
                            at.AutotuneCache(str(tmp_path / "b.json")))

        def run(c, i):
            if c == "bad":
                raise RuntimeError("no compile")
            return jnp.zeros(())

        assert at.autotune("k2", ["bad", "good"], run, default="d") == "good"
        # all candidates fail -> default cached, failure not re-paid
        ran = []

        def run_all_bad(c, i):
            ran.append(c)
            raise RuntimeError("never compiles")

        assert at.autotune("k3", ["bad"], run_all_bad, default="d") == "d"
        n = len(ran)
        assert at.autotune("k3", ["bad"], run_all_bad, default="x") == "d"
        assert len(ran) == n

    def test_flash_defaults_untouched_off_tpu(self):
        """On CPU (tests), should_autotune is False and the flash path
        keeps its hand-tuned constants — timing the interpreter would
        tune for the interpreter."""
        from paddle_tpu.ops.pallas import autotune as at
        from paddle_tpu.ops.pallas import flash_attention as fa
        assert not at.should_autotune()
        assert fa._tuned_blocks("fwd", 8, 8192, 8192, 128, "float32",
                                True, 0.1) == (fa.DEFAULT_BLOCK_Q,
                                               fa.DEFAULT_BLOCK_K)
        assert fa._tuned_blocks("bwd", 8, 1024, 1024, 128, "float32",
                                True, 0.1) == (1024, 1024)

    def test_serving_block_size_default_off_tpu(self):
        from paddle_tpu.inference.serving import _tuned_decode_block_size
        from paddle_tpu.models import GPTConfig
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=32,
                        use_flash_attention=False)
        assert _tuned_decode_block_size(cfg, 2, 4, 8) == 16

    def test_use_autotune_flag_gates(self, monkeypatch):
        from paddle_tpu.core import flags
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setattr(at, "is_tpu_backend", lambda: True)
        flags.set_flags({"use_autotune": False})
        try:
            assert not at.should_autotune()
        finally:
            flags.set_flags({"use_autotune": True})
        assert at.should_autotune()
        monkeypatch.undo()


# ------------------------------------------------- low-precision moments
class TestMomentDtype:
    """bf16 / blockwise-int8 optimizer states (the HBM knob toward the
    7B north star; VERDICT r4 #6). Update math stays f32."""

    def _train(self, moment_dtype, steps=25):
        paddle.seed(31)
        net = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 4))
        opt = paddle.optimizer.AdamW(learning_rate=5e-3, weight_decay=0.01,
                                     parameters=net.parameters(),
                                     moment_dtype=moment_dtype)
        ds = _Reg(32)
        x = paddle.to_tensor(ds.x)
        y = paddle.to_tensor(ds.y)
        losses = []
        for _ in range(steps):
            loss = _mse(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        return net, opt, losses

    def test_bf16_moments_track_fp32(self):
        _, _, ref = self._train(None)
        _, opt, got = self._train("bfloat16")
        assert got[-1] < got[0] * 0.5
        np.testing.assert_allclose(got[-1], ref[-1], rtol=0.05)
        st = next(iter(opt._accumulators.values()))
        assert st["moment1"].dtype == np.dtype("bfloat16")

    def test_int8_moments_track_fp32(self):
        _, _, ref = self._train(None)
        _, opt, got = self._train("int8")
        assert got[-1] < got[0] * 0.5          # still trains
        np.testing.assert_allclose(got[-1], ref[-1], rtol=0.15)
        st = next(iter(opt._accumulators.values()))
        assert st["moment1"]["q"].dtype == np.dtype("int8")
        assert st["moment1"]["s"].dtype == np.dtype("float32")

    def test_int8_state_checkpoint_round_trip(self):
        net, opt, _ = self._train("int8", steps=5)
        sd = opt.state_dict()
        # checkpoints are portable f32 (decoded), not raw q/s pairs
        some = [v for k, v in sd.items() if k.endswith("_moment1")][0]
        assert np.dtype(some._data.dtype) == np.float32
        opt2 = paddle.optimizer.AdamW(learning_rate=5e-3,
                                      parameters=net.parameters(),
                                      moment_dtype="int8")
        opt2.set_state_dict(sd)
        for pid, st in opt2._accumulators.items():
            ref_st = opt._accumulators[pid]
            np.testing.assert_allclose(
                np.asarray(st["moment1"]["q"]),
                np.asarray(ref_st["moment1"]["q"]), atol=1)

    def test_amsgrad_int8_rejected(self):
        net = nn.Linear(4, 2)
        with pytest.raises(ValueError, match="amsgrad"):
            paddle.optimizer.Adam(parameters=net.parameters(),
                                  amsgrad=True, moment_dtype="int8")

    def test_engine_runs_int8_moments(self, dp_mesh):
        import paddle_tpu.distributed as dist
        paddle.seed(33)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=net.parameters(),
                                    moment_dtype="int8")
        hist = dist.Engine(net, loss=_mse, optimizer=opt).fit(
            _Reg(), epochs=3, batch_size=16)
        assert hist[-1] < hist[0]


# ------------------------------------------------- quantized deployment
class TestQuantizedDeployment:
    """VERDICT r4 #8 (reference onednn_quantizer.cc / inference-TRT int8
    intent): quantized models flow through BOTH deployment paths —
    jit.save -> Predictor, and the continuous-batching serving engine."""

    def _toy_llama(self):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        paddle.seed(41)
        cfg = LlamaConfig(vocab_size=97, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          max_seq_len=256, use_flash_attention=False)
        return LlamaForCausalLM(cfg)

    @staticmethod
    def _weight_bytes(model):
        seen, total = set(), 0
        for layer in [model] + [l for _, l in model.named_sublayers()]:
            tensors = list(layer.__dict__.values()) \
                + list(getattr(layer, "_parameters", {}).values()) \
                + list(getattr(layer, "_buffers", {}).values())
            for v in tensors:
                if hasattr(v, "_data") and id(v) not in seen:
                    seen.add(id(v))
                    a = v._data
                    total += int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
        return total

    def test_weight_only_serving_token_parity(self):
        from paddle_tpu.inference.serving import LlamaPagedEngine
        from paddle_tpu.quantization import PTQ

        model = self._toy_llama()
        rng = np.random.RandomState(3)
        prompt = [int(t) for t in rng.randint(1, 97, size=9)]
        n_new = 12

        eng_fp = LlamaPagedEngine(model, max_batch=2, block_size=4,
                                  num_blocks=64, max_blocks_per_seq=16)
        rid = eng_fp.add_request(prompt, max_new_tokens=n_new)
        fp_tokens = eng_fp.run_to_completion()[rid]

        qmodel = PTQ().quantize(model)
        eng_q = LlamaPagedEngine(qmodel, max_batch=2, block_size=4,
                                 num_blocks=64, max_blocks_per_seq=16)
        rid = eng_q.add_request(prompt, max_new_tokens=n_new)
        q_tokens = eng_q.run_to_completion()[rid]

        # documented tolerance: int8 per-channel weight quantization may
        # flip late greedy picks; the prefix must agree
        match = sum(a == b for a, b in zip(fp_tokens, q_tokens))
        assert match >= int(0.75 * n_new), (fp_tokens, q_tokens)

        # the point of int8 serving: measured weight-HBM saving
        fp_bytes = self._weight_bytes(model)
        q_bytes = self._weight_bytes(qmodel)
        assert q_bytes < fp_bytes * 0.45, (fp_bytes, q_bytes)

    def test_ptq_jit_save_predictor_parity(self, tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        from paddle_tpu.quantization import PTQ
        from paddle_tpu.static import InputSpec

        paddle.seed(43)
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
        qnet = PTQ().quantize(net)
        x = np.random.RandomState(5).randn(3, 8).astype(np.float32)
        ref = qnet(paddle.to_tensor(x)).numpy()

        prefix = str(tmp_path / "qmodel")
        paddle.jit.save(qnet, prefix,
                        input_spec=[InputSpec([-1, 8], "float32")])
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        h = pred.get_input_handle("input_0")
        h.copy_from_cpu(x)
        pred.run()
        got = pred.get_output_handle("output_0").copy_to_cpu()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_attention_impl_selection_gating(monkeypatch):
    """Algorithm selection (XLA dense vs Pallas flash) consults the
    autotuner only when the chip can be measured AND the user has not
    pinned flash_min_seq_len; otherwise the flag crossover decides."""
    import importlib

    import jax.numpy as jnp
    from paddle_tpu.core import flags
    from paddle_tpu.ops.pallas import autotune as at
    fa = importlib.import_module("paddle_tpu.nn.functional.flash_attention")

    # CPU: autotune off -> flag path, no probe
    assert not at.should_autotune()
    called = []
    monkeypatch.setattr(fa, "_tuned_attn_impl",
                        lambda *a: called.append(a) or "pallas")
    fa._use_pallas(2048, 64, jnp.bfloat16, True)
    assert not called

    # pretend we are on a measurable chip: still no probe until the
    # ALGORITHM flag opts in (tile tuning has bounded downside,
    # algorithm selection does not)
    monkeypatch.setattr(at, "should_autotune", lambda: True)
    monkeypatch.setattr(fa.jax, "default_backend", lambda: "tpu")
    assert fa._use_pallas(2048, 64, jnp.bfloat16, True) is True
    assert not called
    flags.set_flags({"autotune_attn_impl": True})
    try:
        assert fa._use_pallas(2048, 64, jnp.bfloat16, True) is True
        assert called
    finally:
        flags.set_flags({"autotune_attn_impl": False})
    flags.set_flags({"autotune_attn_impl": True})

    # a user-pinned flash_min_seq_len overrides measurement entirely
    called.clear()
    flags.set_flags({"flash_min_seq_len": 4096})
    try:
        assert fa._use_pallas(2048, 64, jnp.bfloat16, True) is False
        assert not called
    finally:
        flags.set_flags({"flash_min_seq_len": 1024,
                         "autotune_attn_impl": False})
