"""ResNet / BERT / hapi Model / metric tests.

Reference analogs: test/legacy_test/test_resnet*.py (loss decreases),
test_bert fixtures under to_static, python/paddle/hapi tests (fit/
evaluate/predict round trip), metric unit tests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall


class TestResNet:
    def test_resnet18_trains(self):
        from paddle_tpu.vision.models import resnet18
        paddle.seed(0)
        m = resnet18(num_classes=4)
        m.train()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        x = paddle.to_tensor(np.random.randn(4, 3, 32, 32)
                             .astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 2, 3]))
        losses = []
        for _ in range(4):
            loss = paddle.nn.functional.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_resnet50_structure(self):
        from paddle_tpu.vision.models import resnet50
        m = resnet50()
        n = sum(p.size for p in m.parameters())
        # reference resnet50 (1000 classes): 25.6M params
        assert abs(n - 25_557_032) < 10_000, n

    def test_bn_running_stats_update(self):
        from paddle_tpu.vision.models import resnet18
        m = resnet18(num_classes=2)
        m.train()
        before = np.asarray(m.bn1._mean._data).copy()
        x = paddle.to_tensor(
            np.random.randn(2, 3, 32, 32).astype(np.float32) + 3.0)
        m(x)
        after = np.asarray(m.bn1._mean._data)
        assert not np.allclose(before, after)


class TestBert:
    def _cfg(self):
        from paddle_tpu.models.bert import BertConfig
        return BertConfig(vocab_size=128, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=32,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)

    def test_classification_trains(self):
        from paddle_tpu.models.bert import BertForSequenceClassification
        paddle.seed(0)
        m = BertForSequenceClassification(self._cfg(), num_classes=2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (4, 16)).astype(np.int64))
        y = paddle.to_tensor(np.array([0, 1, 0, 1]))
        losses = []
        for _ in range(4):
            _, loss = m(ids, labels=y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_attention_mask_effect(self):
        from paddle_tpu.models.bert import BertModel
        paddle.seed(1)
        m = BertModel(self._cfg())
        m.eval()
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (1, 8)).astype(np.int64))
        full = np.ones((1, 8), np.float32)
        half = full.copy()
        half[0, 4:] = 0
        s1, _ = m(ids, attention_mask=paddle.to_tensor(full))
        s2, _ = m(ids, attention_mask=paddle.to_tensor(half))
        assert not np.allclose(s1.numpy(), s2.numpy())

    def test_under_to_static(self):
        from paddle_tpu.models.bert import BertForSequenceClassification
        paddle.seed(2)
        m = BertForSequenceClassification(self._cfg(), num_classes=2)
        m.eval()
        ids = paddle.to_tensor(
            np.random.randint(0, 128, (2, 16)).astype(np.int64))
        ref = m(ids).numpy()
        st = paddle.jit.to_static(m)
        out = st(ids).numpy()
        np.testing.assert_allclose(ref, out, atol=1e-5)


class TestHapiModel:
    def _dataset(self, n=32):
        from paddle_tpu.io import Dataset

        class XorDs(Dataset):
            def __init__(self):
                rng = np.random.RandomState(0)
                self.x = rng.randn(n, 8).astype(np.float32)
                self.y = (self.x[:, :1] > 0).astype(np.int64).reshape(-1)

            def __len__(self):
                return n

            def __getitem__(self, i):
                return self.x[i], self.y[i]

        return XorDs()

    def test_fit_evaluate_predict(self, tmp_path):
        from paddle_tpu.hapi import Model
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
        model = Model(net)
        model.prepare(
            optimizer=paddle.optimizer.AdamW(learning_rate=1e-2,
                                             parameters=net.parameters()),
            loss=nn.CrossEntropyLoss(),
            metrics=Accuracy())
        ds = self._dataset()
        hist = model.fit(ds, batch_size=8, epochs=3, verbose=0)
        assert hist[-1] < hist[0]
        res = model.evaluate(ds, batch_size=8, verbose=0)
        assert res["acc"] > 0.6
        preds = model.predict(ds, batch_size=8, stack_outputs=True)
        assert preds[0].shape == (32, 2)
        model.save(str(tmp_path / "ckpt"))
        model.load(str(tmp_path / "ckpt"))

    def test_summary(self):
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        info = paddle.summary(net, (1, 8))
        assert info["total_params"] == 8 * 16 + 16 + 16 * 2 + 2


class TestMetrics:
    def test_accuracy_topk(self):
        m = Accuracy(topk=(1, 2))
        pred = np.array([[0.1, 0.7, 0.2], [0.8, 0.1, 0.1]])
        label = np.array([1, 2])
        m.update(m.compute(pred, label))
        top1, top2 = m.accumulate()
        assert abs(top1 - 0.5) < 1e-6
        assert abs(top2 - 0.5) < 1e-6

    def test_precision_recall(self):
        p, r = Precision(), Recall()
        preds = np.array([0.9, 0.8, 0.2, 0.7])
        labels = np.array([1, 0, 1, 1])
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-6
        assert abs(r.accumulate() - 2 / 3) < 1e-6

    def test_auc_perfect_separation(self):
        auc = Auc()
        auc.update(np.array([0.9, 0.8, 0.1, 0.2]),
                   np.array([1, 1, 0, 0]))
        assert auc.accumulate() > 0.99

    def test_accuracy_column_labels(self):
        # conventional [B, 1] integer label column is indices, not one-hot
        m = Accuracy()
        pred = np.array([[0.1, 0.9], [0.2, 0.8]])
        label = np.array([[1], [1]])
        m.update(m.compute(pred, label))
        assert abs(m.accumulate() - 1.0) < 1e-6

    def test_auc_saturated_bins(self):
        # all scores land in one histogram bin: AUC is 0.5, not 0
        auc = Auc()
        auc.update(np.array([1.0, 1.0]), np.array([1, 0]))
        assert abs(auc.accumulate() - 0.5) < 1e-3


class TestCallbacks:
    def _setup(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.io import TensorDataset
        paddle.seed(0)
        rng = np.random.RandomState(0)
        x = rng.rand(32, 4).astype(np.float32)
        y = (x.sum(-1) > 2).astype(np.int64)
        ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
        net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
        model = paddle.hapi.Model(net)
        return paddle, model, net, ds

    def test_callback_hooks_fire_in_order(self):
        paddle, model, net, ds = self._setup()

        calls = []

        class Spy(paddle.hapi.Callback):
            def on_train_begin(self, logs=None):
                calls.append("train_begin")

            def on_epoch_begin(self, epoch, logs=None):
                calls.append(f"epoch_begin{epoch}")

            def on_train_batch_end(self, step, logs=None):
                calls.append("batch")

            def on_epoch_end(self, epoch, logs=None):
                calls.append(f"epoch_end{epoch}")
                assert "loss" in (logs or {})

            def on_train_end(self, logs=None):
                calls.append("train_end")

        model.prepare(paddle.optimizer.Adam(
            learning_rate=1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        model.fit(ds, epochs=2, batch_size=16, verbose=0,
                  callbacks=[Spy()])
        assert calls[0] == "train_begin" and calls[-1] == "train_end"
        assert calls.count("batch") == 4  # 2 epochs x 2 steps
        assert "epoch_begin0" in calls and "epoch_end1" in calls

    def test_early_stopping(self):
        paddle, model, net, ds = self._setup()
        model.prepare(paddle.optimizer.Adam(
            learning_rate=0.0, parameters=net.parameters()),  # no progress
            paddle.nn.CrossEntropyLoss())
        es = paddle.hapi.EarlyStopping(monitor="loss", patience=1,
                                       verbose=0)
        model.fit(ds, eval_data=ds, epochs=10, batch_size=16, verbose=0,
                  callbacks=[es])
        assert model.stop_training
        assert es.wait >= 1

    def test_lr_scheduler_callback_steps(self):
        paddle, model, net, ds = self._setup()
        sched = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        model.prepare(paddle.optimizer.SGD(
            learning_rate=sched, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        model.fit(ds, epochs=1, batch_size=16, verbose=0,
                  callbacks=[paddle.hapi.LRScheduler(by_step=True)])
        # 2 steps -> scheduler advanced twice -> lr halved once
        assert abs(sched() - 0.05) < 1e-9

    def test_model_checkpoint(self, tmp_path):
        paddle, model, net, ds = self._setup()
        model.prepare(paddle.optimizer.Adam(
            learning_rate=1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        model.fit(ds, epochs=2, batch_size=16, verbose=0,
                  callbacks=[paddle.hapi.ModelCheckpoint(
                      save_freq=1, save_dir=str(tmp_path))])
        import os
        assert os.path.exists(str(tmp_path / "0.pdparams")) or \
            os.path.exists(str(tmp_path / "0"))
        assert any("final" in f for f in os.listdir(tmp_path))

    def test_early_stopping_saves_best(self, tmp_path):
        paddle, model, net, ds = self._setup()
        model.prepare(paddle.optimizer.Adam(
            learning_rate=1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        model.fit(ds, eval_data=ds, epochs=3, batch_size=16, verbose=0,
                  save_dir=str(tmp_path),
                  callbacks=[paddle.hapi.EarlyStopping(
                      monitor="loss", patience=10, verbose=0)])
        import os
        assert any("best_model" in f for f in os.listdir(tmp_path))

    def test_epoch_logs_namespaced(self):
        paddle, model, net, ds = self._setup()
        seen = {}

        class Spy(paddle.hapi.Callback):
            def on_epoch_end(self, epoch, logs=None):
                seen.update(logs or {})

        model.prepare(paddle.optimizer.Adam(
            learning_rate=1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        model.fit(ds, eval_data=ds, epochs=1, batch_size=16, verbose=0,
                  callbacks=[Spy()])
        assert isinstance(seen["loss"], float)        # train loss
        assert isinstance(seen["eval_loss"], float)   # namespaced eval
