"""Op numeric tests via the OpTest-style harness (reference:
test/legacy_test/ per-op tests; harness op_test.py:418)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output


def rand(*shape):
    return np.random.uniform(-1, 1, shape).astype(np.float32)


# ------------------------------------------------------------------ math
@pytest.mark.parametrize("name,np_fn", [
    ("exp", np.exp), ("tanh", np.tanh), ("sqrt", None), ("abs", np.abs),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x))), ("log", None),
    ("sin", np.sin), ("cos", np.cos), ("floor", np.floor), ("ceil", np.ceil),
])
def test_unary(name, np_fn):
    x = rand(3, 4)
    if name in ("sqrt", "log"):
        x = np.abs(x) + 0.5
        np_fn = {"sqrt": np.sqrt, "log": np.log}[name]
    check_output(getattr(paddle, name), lambda a: np_fn(a), [x])
    if name not in ("floor", "ceil", "abs"):
        check_grad(getattr(paddle, name), [x])


@pytest.mark.parametrize("name,np_fn", [
    ("add", np.add), ("subtract", np.subtract), ("multiply", np.multiply),
    ("divide", np.divide), ("maximum", np.maximum), ("minimum", np.minimum),
    ("atan2", np.arctan2),
])
def test_binary(name, np_fn):
    x, y = rand(3, 4), rand(3, 4) + 2.0
    check_output(getattr(paddle, name), lambda a, b: np_fn(a, b), [x, y])


def test_broadcasting():
    x, y = rand(3, 1, 4), rand(2, 1)
    check_output(paddle.add, np.add, [x, y])
    check_grad(paddle.add, [x, y])


def test_matmul():
    x, y = rand(3, 4), rand(4, 5)
    check_output(paddle.matmul, np.matmul, [x, y])
    check_grad(paddle.matmul, [x, y])


def test_matmul_batched_transpose():
    x, y = rand(2, 3, 4), rand(2, 5, 4)
    out = paddle.matmul(paddle.to_tensor(x), paddle.to_tensor(y), transpose_y=True)
    np.testing.assert_allclose(out.numpy(), x @ y.transpose(0, 2, 1), rtol=1e-5)


def test_reductions():
    x = rand(3, 4, 5)
    check_output(paddle.sum, lambda a: np.sum(a), [x])
    check_output(lambda t: paddle.sum(t, axis=1),
                 lambda a: np.sum(a, axis=1), [x])
    check_output(lambda t: paddle.mean(t, axis=[0, 2], keepdim=True),
                 lambda a: np.mean(a, axis=(0, 2), keepdims=True), [x])
    check_output(lambda t: paddle.max(t, axis=1), lambda a: np.max(a, 1), [x])
    check_grad(lambda t: paddle.mean(t, axis=1), [x])


def test_cumsum_logsumexp():
    x = rand(3, 4)
    check_output(lambda t: paddle.cumsum(t, axis=1),
                 lambda a: np.cumsum(a, axis=1), [x])
    from scipy.special import logsumexp as np_lse  # scipy ships with the image
    check_output(lambda t: paddle.logsumexp(t, axis=1),
                 lambda a: np_lse(a, axis=1), [x], rtol=1e-5)


def test_manipulation():
    x = rand(2, 3, 4)
    check_output(lambda t: paddle.reshape(t, [6, 4]),
                 lambda a: a.reshape(6, 4), [x])
    check_output(lambda t: paddle.transpose(t, [2, 0, 1]),
                 lambda a: a.transpose(2, 0, 1), [x])
    check_output(lambda t: paddle.flatten(t, start_axis=1),
                 lambda a: a.reshape(2, 12), [x])
    check_output(lambda t: paddle.squeeze(paddle.unsqueeze(t, 0), 0),
                 lambda a: a, [x])
    check_output(lambda t: paddle.flip(t, axis=1),
                 lambda a: np.flip(a, 1), [x])


def test_concat_stack_split():
    x, y = rand(2, 3), rand(2, 3)
    check_output(lambda a, b: paddle.concat([a, b], axis=0),
                 lambda a, b: np.concatenate([a, b], 0), [x, y])
    check_output(lambda a, b: paddle.stack([a, b], axis=1),
                 lambda a, b: np.stack([a, b], 1), [x, y])
    parts = paddle.split(paddle.to_tensor(rand(6, 3)), 3, axis=0)
    assert len(parts) == 3 and parts[0].shape == [2, 3]


def test_gather_scatter_index():
    x = rand(5, 3)
    idx = np.array([0, 2, 4])
    check_output(lambda t: paddle.gather(t, paddle.to_tensor(idx)),
                 lambda a: a[idx], [x])
    check_output(lambda t: paddle.index_select(t, paddle.to_tensor(idx), axis=0),
                 lambda a: a[idx], [x])


def test_where_topk_argmax():
    x = rand(4, 5)
    check_output(lambda t: paddle.argmax(t, axis=1),
                 lambda a: np.argmax(a, 1), [x])
    v, i = paddle.topk(paddle.to_tensor(x), k=2, axis=1)
    ref = np.sort(x, axis=1)[:, ::-1][:, :2]
    np.testing.assert_allclose(v.numpy(), ref, rtol=1e-6)
    cond = x > 0
    check_output(lambda t: paddle.where(paddle.to_tensor(cond), t, t * 2),
                 lambda a: np.where(cond, a, a * 2), [x])


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([2], dtype="int32").dtype == np.int32
    np.testing.assert_array_equal(paddle.arange(0, 10, 2).numpy(), [0, 2, 4, 6, 8])
    e = paddle.eye(3)
    np.testing.assert_allclose(e.numpy(), np.eye(3, dtype=np.float32))
    fl = paddle.full([2, 2], 7.0)
    np.testing.assert_allclose(fl.numpy(), 7.0)
    z = paddle.zeros_like(paddle.ones([4]))
    np.testing.assert_allclose(z.numpy(), 0.0)
    ls = paddle.linspace(0, 1, 5)
    np.testing.assert_allclose(ls.numpy(), np.linspace(0, 1, 5, dtype=np.float32))


def test_random_ops_reproducible():
    paddle.seed(123)
    a = paddle.randn([3, 3])
    paddle.seed(123)
    b = paddle.randn([3, 3])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    u = paddle.uniform([1000], min=0.0, max=1.0)
    assert 0 <= u.numpy().min() and u.numpy().max() <= 1
    r = paddle.randint(0, 10, [100])
    assert r.numpy().min() >= 0 and r.numpy().max() < 10


def test_linalg_ops():
    x = rand(3, 3)
    spd = x @ x.T + 3 * np.eye(3, dtype=np.float32)
    check_output(paddle.inverse, np.linalg.inv, [spd], rtol=1e-4)
    check_output(lambda t: paddle.cholesky(t),
                 lambda a: np.linalg.cholesky(a), [spd], rtol=1e-4)
    check_output(paddle.trace, np.trace, [x])
    check_output(lambda t: paddle.norm(t),
                 lambda a: np.linalg.norm(a), [x], rtol=1e-5)


def test_einsum():
    x, y = rand(2, 3, 4), rand(2, 4, 5)
    check_output(lambda a, b: paddle.einsum("bij,bjk->bik", a, b),
                 lambda a, b: np.einsum("bij,bjk->bik", a, b), [x, y])


def test_cast_dtype_promotion():
    a = paddle.to_tensor([1, 2], dtype="int32")
    b = paddle.to_tensor([0.5, 0.5])
    out = a + b
    assert out.dtype == np.float32
