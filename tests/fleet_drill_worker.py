"""Fleet-observability drill worker — the real 4-process proof.

Runs under ``python -m paddle_tpu.distributed.launch`` like
dist_train_worker.py. Two deterministic fault drills in one job
(reference analogue: the comm task manager's stuck-rank report,
paddle/phi/core/distributed/comm_task_manager.cc):

Phase 1 — straggler: every rank runs the same small jitted step under
the fleet beacon (window from ``PADDLE_TPU_BEACON_WINDOW``, the harness
sets 2); ``DRILL_TARGET_RANK`` arms the ``fleet.slow_step`` fault point,
so that rank sleeps inside every step. The beacon's cross-rank gather
must name the target rank as the straggler within 2 windows — each rank
writes its verdict (plus a cross-rank ``fleet.snapshot()`` and the
``clock_sync`` offsets) to ``drill.r<rank>.json`` for the harness.

Phase 2 — collective desync: after a sync barrier, the target rank arms
``collective.desync`` and every rank issues one more barrier inside a 3s
watchdog. The target BYPASSES it — its flight entry completes instantly
while the peers block *inside* theirs (the barrier synchronizes, so the
pending ring entry is real evidence) — and then parks without issuing
another collective (issuing one would shift the transport's collective
matching and produce undefined cross-rank behavior; a desynced rank
going quiet is also the realistic failure). Every rank's watchdog fires,
persists its flight-recorder ring to ``PADDLE_TPU_FLIGHT_RECORD``
(rank-suffixed), diffs the tails out-of-band through the filesystem, and
prints the verdict naming the desynced rank + sequence number — then
aborts. The harness asserts the job died, the per-rank flight files
exist, and the printed diff names the right rank.

Usage: fleet_drill_worker.py <outdir>
"""
import json
import os
import signal
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

OUTDIR = sys.argv[1]
TARGET = int(os.environ.get("DRILL_TARGET_RANK", "2"))

import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed.communication import collective as C  # noqa: E402
from paddle_tpu.distributed.watchdog import Watchdog  # noqa: E402
from paddle_tpu.fault import inject  # noqa: E402
from paddle_tpu.observability import fleet, flight  # noqa: E402

dist.init_parallel_env()
rank = jax.process_index()
world = jax.process_count()
assert world == 4, f"drill expects 4 processes, got {world}"

# SIGTERM (the launcher tearing the group down after the first abort)
# must still leave this rank's flight record behind — production
# behavior for any drain path, and it keeps the drill deterministic.
signal.signal(signal.SIGTERM,
              lambda *_: (flight.dump(reason="sigterm"), os._exit(1)))

# cross-process clock handshake first: offsets ride the snapshot and
# every later chrome-trace export
clock = fleet.clock_sync(rounds=3)

# ---------------------------------------------------------------- phase 1
if rank == TARGET:
    inject.arm("fleet.slow_step", times=10 ** 6, seconds=0.06)

import jax.numpy as jnp  # noqa: E402

w = jnp.asarray(np.random.RandomState(0).randn(64, 64), jnp.float32)
step = jax.jit(lambda x: jnp.tanh(x @ w))
bcn = fleet.beacon()
x = jnp.ones((8, 64), jnp.float32)
for _ in range(3 * bcn.window):
    bcn.step_begin()
    jax.block_until_ready(step(x))
    bcn.step_end()
inject.disarm_all()

report = bcn.last_report
assert report is not None, "beacon never flushed"

# cross-rank aggregation: every rank receives every rank's local
# snapshot (REAL per-rank payloads — distinct pids prove the object
# gather is not the in-process replicate path)
snap = fleet.snapshot(trace_tail=20)

with open(os.path.join(OUTDIR, f"drill.r{rank}.json"), "w") as f:
    json.dump({
        "rank": rank,
        "slowest_rank": report["slowest_rank"],
        "slowest_score": report["slowest_score"],
        "dominant_bucket": report["dominant_bucket"],
        "first_flagged_window": bcn.first_flagged_window,
        "windows": bcn.windows,
        "snapshot_world": snap["world"],
        "snapshot_ranks": [r["rank"] for r in snap["ranks"]],
        "snapshot_pids": [r["pid"] for r in snap["ranks"]],
        "clock_world": clock["world"],
        "clock_offsets": {str(k): v
                          for k, v in clock["offsets"].items()},
    }, f)
print(f"[drill] rank {rank} phase 1 done: straggler="
      f"{report['slowest_rank']} score={report['slowest_score']:.2f} "
      f"window={bcn.first_flagged_window}", flush=True)

# ---------------------------------------------------------------- phase 2
import time  # noqa: E402

C.barrier()          # phase-1 result files are complete on every rank

wd = Watchdog(timeout=3.0, poll_interval=0.5, abort_on_hang=True).start()
if rank == TARGET:
    inject.arm("collective.desync", times=1, op="barrier")

wd.begin_work()
C.barrier()          # target bypasses (flight entry done in µs);
#                      peers block INSIDE (entry left pending)
time.sleep(3600)     # only the target gets here — it parks, desynced,
#                      until its watchdog names it and aborts
# unreachable: every rank hangs above until its watchdog aborts the
# process — reaching this line means the drill failed to produce a hang
wd.end_work()
print(f"[drill] rank {rank} ERROR: desync did not hang", flush=True)
sys.exit(7)
