"""Registry-driven OpTest sweep (VERDICT r3 #3).

The reference runs OpTest against essentially every op
(test/legacy_test/op_test.py:418 forward-vs-numpy, :3026 check_grad,
:1084 tolerances). Here the sweep is driven by ``ops/registry.py``: every
registered op must either carry a RECIPE (inputs/attrs (+ optional numpy
reference)) and pass

  1. execution + finite outputs,
  2. forward vs an independent NumPy reference (when one exists),
  3. eager == jit parity (the dispatch / compiled-lowering-cache paths),
  4. analytic-vs-finite-difference gradients (differentiable float ops),

or appear in SKIP with a written reason (dedicated suite / unsweepable
signature). ``test_registry_fully_classified`` pins that partition, so a
newly registered op FAILS the suite until it is classified.

The sweep runs under ``jax.default_matmul_precision('highest')`` — this
backend's default f32 matmul is reduced-precision, which would drown the
finite-difference checks in contraction noise.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.registry import OPS

RNG = np.random.RandomState(0)


def sym(*shape):
    return RNG.uniform(-0.9, 0.9, shape).astype(np.float32)


def pos(*shape):
    return RNG.uniform(0.2, 0.9, shape).astype(np.float32)


def unit(*shape):
    return RNG.uniform(0.05, 0.95, shape).astype(np.float32)


def gt1(*shape):
    return RNG.uniform(1.1, 2.0, shape).astype(np.float32)


def ints(hi, *shape):
    return RNG.randint(0, hi, shape).astype(np.int64)


def boolean(*shape):
    return RNG.rand(*shape) > 0.5


def pd(*shape):
    a = RNG.randn(*shape).astype(np.float32)
    return (a @ a.T + shape[0] * np.eye(shape[0])).astype(np.float32)


def spaced(*shape):
    """Well-separated values (gap >> the FD delta) for max-style ops:
    near-ties would let the finite-difference perturbation flip an
    argmax and break the gradient check spuriously."""
    n = int(np.prod(shape))
    vals = np.linspace(-1.0, 1.0, n).astype(np.float32)
    return np.random.RandomState(1234 + n).permutation(vals).reshape(shape)


R = {}


def rec(name, inputs, attrs=None, ref=None, grad=True, grad_idx=None,
        rtol=1e-4, atol=1e-5, jit=True, grad_tol=5e-3):
    R[name] = dict(inputs=inputs, attrs=attrs or {}, ref=ref, grad=grad,
                   grad_idx=grad_idx, rtol=rtol, atol=atol, jit=jit,
                   grad_tol=grad_tol)


def np_ref(name):
    for mod in (np, np.linalg):
        f = getattr(mod, name, None)
        if f is not None:
            return f
    return None


# ---------------------------------------------------------------- math unary
for n in ("abs sign neg floor ceil round trunc exp expm1 sin cos tan "
          "sinh cosh tanh erf square reciprocal sigmoid frac "
          "asinh atan sqrt rsqrt").split():
    dom = pos if n in ("sqrt", "rsqrt", "reciprocal") else sym
    refs = {"neg": np.negative, "square": lambda x: x * x,
            "reciprocal": lambda x: 1.0 / x, "frac": lambda x: x - np.trunc(x),
            "sigmoid": lambda x: 1 / (1 + np.exp(-x)),
            "rsqrt": lambda x: 1 / np.sqrt(x), "erf": None}
    rec(n, [dom(3, 4)], ref=refs.get(n, np_ref(n)),
        grad=n not in ("sign", "floor", "ceil", "round", "trunc"))
for n in "log log2 log10 log1p digamma lgamma gammaln i0 i0e i1 i1e".split():
    rec(n, [pos(3, 4)], ref=np_ref(n), grad=True)
for n in "acos asin atanh erfinv logit".split():
    dom = unit if n in ("erfinv", "logit") else (lambda *s: sym(*s) * 0.8)
    rec(n, [dom(3, 4)], ref=np_ref(n))
rec("acosh", [gt1(3, 4)], ref=np.arccosh)
rec("asin", [sym(3, 4) * 0.8], ref=np.arcsin)
rec("acos", [sym(3, 4) * 0.8], ref=np.arccos)
rec("atanh", [sym(3, 4) * 0.8], ref=np.arctanh)
rec("stanh", [sym(3, 4)])
rec("angle", [sym(3, 4)], ref=np.angle, grad=False)
rec("conj", [sym(3, 4)], ref=np.conj, grad=False)
rec("real", [sym(3, 4)], ref=np.real, grad=False)
rec("imag", [(sym(3, 4) + 1j * sym(3, 4)).astype(np.complex64)],
    ref=np.imag, grad=False)
rec("nan_to_num", [np.array([[1.0, np.nan], [np.inf, -np.inf]], np.float32)],
    ref=np.nan_to_num, grad=False)
rec("polygamma", [pos(3, 4)], attrs={"n": 1}, grad=False)
rec("sign", [sym(3, 4)], ref=np.sign, grad=False)
rec("logit", [unit(3, 4)], grad=True)
rec("heaviside", [sym(3, 4), sym(3, 4)], ref=np.heaviside, grad=False)
rec("clip", [sym(3, 4)], attrs={"min": -0.5, "max": 0.5},
    ref=lambda x, **kw: np.clip(x, -0.5, 0.5))
rec("scale", [sym(3, 4)], attrs={"scale": 2.5, "bias": 1.0},
    ref=lambda x, **kw: 2.5 * x + 1.0)
rec("increment", [sym(1)], grad=False)
rec("cast", [sym(3, 4)], attrs={"dtype": "float32"}, grad=False)

# --------------------------------------------------------------- math binary
for n in ("add subtract multiply maximum minimum fmax fmin hypot "
          "copysign logaddexp atan2").split():
    rec(n, [sym(3, 4), sym(3, 4)], ref=np_ref(n) or {
        "atan2": np.arctan2}.get(n))
rec("atan2", [sym(3, 4), pos(3, 4)], ref=np.arctan2)
rec("divide", [sym(3, 4), pos(3, 4)], ref=np.divide)
rec("pow", [pos(3, 4), sym(3, 4)], ref=np.power)
rec("mod", [pos(3, 4), pos(3, 4)], ref=np.mod, grad=False)
rec("floor_mod", [pos(3, 4), pos(3, 4)], ref=np.mod, grad=False)
rec("remainder", [pos(3, 4), pos(3, 4)], ref=np.remainder, grad=False)
rec("floor_divide", [pos(3, 4) * 10, pos(3, 4)], ref=np.floor_divide,
    grad=False)
rec("nextafter", [sym(3, 4), sym(3, 4)], ref=np.nextafter, grad=False)
rec("ldexp", [sym(3, 4), ints(4, 3, 4)], ref=np.ldexp, grad=False)
rec("gcd", [ints(20, 3, 4), ints(20, 3, 4)], ref=np.gcd, grad=False)
rec("lcm", [ints(10, 3, 4) + 1, ints(10, 3, 4) + 1], ref=np.lcm,
    grad=False)
rec("lerp", [sym(3, 4), sym(3, 4), unit(3, 4)],
    ref=lambda x, y, w: x + w * (y - x))
rec("gammainc", [pos(3, 4) * 3, pos(3, 4) * 3], grad=False)
rec("gammaincc", [pos(3, 4) * 3, pos(3, 4) * 3], grad=False)
rec("diff", [sym(3, 5)], ref=np.diff, grad=True)
rec("trapezoid", [sym(3, 5)], ref=np.trapezoid if hasattr(np, "trapezoid")
    else np.trapz, grad=True)
rec("logical_and", [boolean(3, 4), boolean(3, 4)], ref=np.logical_and,
    grad=False)
rec("logical_or", [boolean(3, 4), boolean(3, 4)], ref=np.logical_or,
    grad=False)
rec("logical_xor", [boolean(3, 4), boolean(3, 4)], ref=np.logical_xor,
    grad=False)
rec("logical_not", [boolean(3, 4)], ref=np.logical_not, grad=False)
for n in "bitwise_and bitwise_or bitwise_xor".split():
    rec(n, [ints(16, 3, 4).astype(np.int32), ints(16, 3, 4).astype(np.int32)],
        ref=np_ref(n), grad=False)
rec("bitwise_not", [ints(16, 3, 4).astype(np.int32)], ref=np.bitwise_not,
    grad=False)
rec("bitwise_left_shift", [ints(8, 3, 4).astype(np.int32),
                           ints(4, 3, 4).astype(np.int32)],
    ref=np.left_shift, grad=False)
rec("bitwise_right_shift", [ints(64, 3, 4).astype(np.int32),
                            ints(4, 3, 4).astype(np.int32)],
    ref=np.right_shift, grad=False)
for n in ("equal not_equal greater_equal less_equal greater_than "
          "less_than greater less").split():
    npn = {"greater_than": np.greater, "less_than": np.less,
           "greater": np.greater, "less": np.less}.get(n, np_ref(n))
    rec(n, [ints(3, 3, 4).astype(np.float32),
            ints(3, 3, 4).astype(np.float32)], ref=npn, grad=False)
for n in "isfinite isinf isnan".split():
    rec(n, [np.array([[1.0, np.nan], [np.inf, 0.5]], np.float32)],
        ref=np_ref(n), grad=False)
rec("isclose", [sym(3, 4), sym(3, 4)], ref=np.isclose, grad=False)
rec("allclose", [sym(3, 4), sym(3, 4)], ref=np.allclose, grad=False)
rec("equal_all", [ints(3, 3, 4), ints(3, 3, 4)],
    ref=lambda a, b: np.array_equal(a, b), grad=False)
rec("multiplex", [[sym(4, 3), sym(4, 3)],
                  np.array([[0], [1], [0], [1]], np.int32)], grad=False,
    jit=False)
rec("fill_diagonal", [sym(4, 4)], attrs={"value": 0.0}, grad=False)
rec("fill_diagonal_tensor", [sym(4, 4), sym(4)], grad=False)
rec("copysign", [sym(3, 4), sym(3, 4)], ref=np.copysign, grad=False)
rec("renorm", [sym(3, 4)], attrs={"p": 2.0, "axis": 0, "max_norm": 1.0})
rec("reduce_as", [sym(3, 4), sym(1, 4)],
    ref=lambda x, t: x.sum(0, keepdims=True), grad_idx=[0])

# ---------------------------------------------------------------- reduction
for n in "max min amax amin mean sum prod".split():
    rec(n, [sym(3, 4)], ref=np_ref(n) or getattr(np, n, None))
rec("std", [sym(3, 4)], ref=lambda x: np.std(x, ddof=1), rtol=1e-3)
rec("var", [sym(3, 4)], ref=lambda x: np.var(x, ddof=1), rtol=1e-3)
rec("nanmean", [sym(3, 4)], ref=np.nanmean)
rec("nansum", [sym(3, 4)], ref=np.nansum)
rec("median", [sym(3, 5)], ref=np.median, grad=False)
rec("nanmedian", [sym(3, 5)], ref=np.nanmedian, grad=False)
rec("quantile", [sym(3, 5)], attrs={"q": 0.5},
    ref=lambda x, **kw: np.quantile(x, 0.5), grad=False)
rec("nanquantile", [sym(3, 5)], attrs={"q": 0.5},
    ref=lambda x, **kw: np.nanquantile(x, 0.5), grad=False)
rec("logsumexp", [sym(3, 4)],
    ref=lambda x: np.log(np.exp(x).sum()))
rec("logcumsumexp", [sym(3, 4)], attrs={"axis": 1},
    ref=lambda x, **kw: np.log(np.cumsum(np.exp(x), 1)))
rec("cumsum", [sym(3, 4)], attrs={"axis": 1},
    ref=lambda x, **kw: np.cumsum(x, 1))
rec("cumprod", [pos(3, 4)], attrs={"dim": 1},
    ref=lambda x, **kw: np.cumprod(x, 1))
rec("cummax", [sym(3, 4)], attrs={"axis": 1}, grad=False)
rec("cummin", [sym(3, 4)], attrs={"axis": 1}, grad=False)
rec("count_nonzero", [ints(2, 3, 4).astype(np.float32)],
    ref=np.count_nonzero, grad=False)
rec("mode", [sym(3, 5)], grad=False)
rec("all", [boolean(3, 4)], ref=np.all, grad=False)
rec("any", [boolean(3, 4)], ref=np.any, grad=False)

# --------------------------------------------------------------- activation
for n in ("relu relu6 elu celu selu silu swish mish softplus softsign "
          "hardtanh hardshrink softshrink tanhshrink hardsigmoid "
          "hardswish leaky_relu log_sigmoid thresholded_relu").split():
    rec(n, [sym(3, 4)])
rec("gelu", [sym(3, 4)])
rec("softmax", [sym(3, 4)],
    ref=lambda x: np.exp(x) / np.exp(x).sum(-1, keepdims=True))
rec("log_softmax", [sym(3, 4)],
    ref=lambda x: x - np.log(np.exp(x).sum(-1, keepdims=True)))
rec("glu", [sym(3, 4)])
rec("maxout", [sym(2, 4, 3, 3)], attrs={"groups": 2})
rec("prelu", [sym(3, 4), np.asarray([0.25], np.float32)])
rec("swiglu", [sym(3, 4), sym(3, 4)])
rec("gumbel_softmax", [sym(3, 4)], grad=False, ref=None, jit=False)
rec("rrelu", [sym(3, 4)], attrs={"training": False}, grad=False)

# ------------------------------------------------------------- manipulation
rec("reshape", [sym(3, 4)], attrs={"shape": [4, 3]},
    ref=lambda x, **kw: x.reshape(4, 3))
rec("transpose", [sym(3, 4)], attrs={"perm": [1, 0]},
    ref=lambda x, **kw: x.T)
rec("squeeze", [sym(3, 1, 4)], ref=np.squeeze)
rec("unsqueeze", [sym(3, 4)], attrs={"axis": 1},
    ref=lambda x, **kw: x[:, None])
rec("flatten", [sym(2, 3, 4)], ref=lambda x: x.reshape(2 * 3 * 4))
rec("flip", [sym(3, 4)], attrs={"axis": 0},
    ref=lambda x, **kw: np.flip(x, 0))
rec("reverse", [sym(3, 4)], attrs={"axis": 0},
    ref=lambda x, **kw: np.flip(x, 0))
rec("roll", [sym(3, 4)], attrs={"shifts": 1},
    ref=lambda x, **kw: np.roll(x, 1))
rec("rot90", [sym(3, 4)], ref=np.rot90)
rec("tile", [sym(3, 4)], attrs={"repeat_times": [2, 1]},
    ref=lambda x, **kw: np.tile(x, (2, 1)))
rec("broadcast_to", [sym(1, 4)], attrs={"shape": [3, 4]},
    ref=lambda x, **kw: np.broadcast_to(x, (3, 4)))
rec("expand", [sym(1, 4)], attrs={"shape": [3, 4]},
    ref=lambda x, **kw: np.broadcast_to(x, (3, 4)))
rec("expand_as", [sym(1, 4), sym(3, 4)],
    ref=lambda x, y: np.broadcast_to(x, (3, 4)), grad_idx=[0])
rec("concat", [[sym(2, 3), sym(2, 3)]], jit=False, grad=False,
    ref=lambda xs: np.concatenate(xs))
rec("stack", [[sym(2, 3), sym(2, 3)]], jit=False, grad=False,
    ref=lambda xs: np.stack(xs))
rec("split", [sym(4, 3)], attrs={"num_or_sections": 2}, grad=False)
rec("chunk", [sym(4, 3)], attrs={"chunks": 2}, grad=False)
rec("unbind", [sym(3, 4)], grad=False)
rec("unstack", [sym(3, 4)], grad=False)
rec("pad", [sym(3, 4)], attrs={"pad": [1, 1, 1, 1]})
rec("swapaxes", [sym(3, 4)], attrs={"axis0": 0, "axis1": 1},
    ref=lambda x, **kw: np.swapaxes(x, 0, 1))
rec("moveaxis", [sym(3, 4)], attrs={"source": 0, "destination": 1},
    ref=lambda x, **kw: np.moveaxis(x, 0, 1))
rec("diagonal", [sym(4, 4)], ref=np.diagonal)
rec("diag_embed", [sym(3, 4)], grad=False)
rec("kron", [sym(2, 2), sym(3, 3)], ref=np.kron, grad_tol=2e-2)
rec("take", [sym(3, 4), ints(12, 5)], ref=np.take, grad_idx=[0])
rec("take_along_axis", [sym(3, 4), ints(3, 3, 4), 0], jit=False,
    grad=False)
rec("repeat_interleave", [sym(3, 4)], attrs={"repeats": 2, "axis": 1},
    ref=lambda x, **kw: np.repeat(x, 2, 1))
rec("masked_fill", [sym(3, 4), boolean(3, 4), -1.0], jit=False,
    grad=False)
rec("numel", [sym(3, 4)], ref=lambda x: np.asarray(x.size), grad=False)
rec("atleast_1d", [np.float32(3.0)], grad=False)
rec("atleast_2d", [sym(4)], grad=False)
rec("atleast_3d", [sym(3, 4)], grad=False)
rec("as_complex", [sym(3, 4, 2)], grad=False)
rec("as_real", [(sym(3, 4) + 1j * sym(3, 4)).astype(np.complex64)],
    grad=False)
rec("crop", [sym(4, 5)], attrs={"shape": [2, 3], "offsets": [1, 1]},
    ref=lambda x, **kw: x[1:3, 1:4])
rec("slice", [sym(4, 5)], attrs={"axes": [0], "starts": [1], "ends": [3]},
    ref=lambda x, **kw: x[1:3])
rec("strided_slice", [sym(6, 5)],
    attrs={"axes": [0], "starts": [0], "ends": [6], "strides": [2]},
    ref=lambda x, **kw: x[0:6:2])
rec("index_add", [sym(4, 3), np.asarray([0, 2], np.int64), 0, sym(2, 3)],
    grad=False, jit=False)
rec("index_sample", [sym(3, 5), ints(5, 3, 2)], grad_idx=[0])
rec("index_put", [sym(3, 4), (ints(3, 2), ints(4, 2)), sym(2)],
    jit=False, grad=False)
rec("put_along_axis", [sym(3, 4), ints(3, 3, 4), sym(3, 4)],
    attrs={"axis": 0}, grad=False, jit=False)
rec("select_scatter", [sym(3, 4), sym(4)],
    attrs={"axis": 0, "index": 1}, grad_idx=[0, 1])
rec("slice_scatter", [sym(4, 4), sym(2, 4)],
    attrs={"axes": [0], "starts": [0], "ends": [2], "strides": [1]},
    grad_idx=[0, 1])
rec("tensor_split", [sym(4, 3)], attrs={"num_or_indices": 2}, grad=False)
rec("tensordot", [sym(3, 4), sym(4, 5)], attrs={"axes": 1},
    ref=lambda x, y, **kw: np.tensordot(x, y, 1), grad_tol=2e-2)
rec("broadcast_tensors", [[sym(1, 4), sym(3, 1)]], jit=False, grad=False)
rec("unfold", [sym(1, 1, 6, 6)], attrs={"kernel_sizes": 2}, grad=False)
rec("shard_index", [ints(20, 5, 1)],
    attrs={"index_num": 20, "nshards": 2, "shard_id": 0}, grad=False)
rec("view", [sym(3, 4)], attrs={"shape_or_dtype": [4, 3]},
    ref=lambda x, **kw: x.reshape(4, 3), grad=False)
rec("view_as", [sym(3, 4), sym(4, 3)], grad=False)
rec("meshgrid", [[sym(3), sym(4)]], jit=False, grad=False)

# ------------------------------------------------------------------ linalg
rec("matmul", [sym(3, 4), sym(4, 5)], ref=np.matmul, grad_tol=2e-2)
rec("mm", [sym(3, 4), sym(4, 5)], ref=np.matmul, grad_tol=2e-2)
rec("bmm", [sym(2, 3, 4), sym(2, 4, 5)], ref=np.matmul, grad_tol=2e-2)
rec("dot", [sym(4), sym(4)], ref=np.dot, grad_tol=2e-2)
rec("inner", [sym(3, 4), sym(5, 4)], ref=np.inner, grad_tol=2e-2)
rec("outer", [sym(3), sym(4)], ref=np.outer, grad_tol=2e-2)
rec("mv", [sym(3, 4), sym(4)], ref=np.matmul, grad_tol=2e-2)
rec("addmm", [sym(3, 5), sym(3, 4), sym(4, 5)], grad_tol=2e-2)
rec("t", [sym(3, 4)], ref=np.transpose)
rec("matrix_transpose", [sym(2, 3, 4)],
    ref=lambda x: np.swapaxes(x, -1, -2))
rec("trace", [sym(4, 4)], ref=np.trace)
rec("norm", [sym(3, 4)], ref=lambda x: np.linalg.norm(x), rtol=1e-3)
rec("p_norm", [sym(3, 4)], attrs={"p": 2},
    ref=lambda x, **kw: np.linalg.norm(x.reshape(-1)), rtol=1e-3)
rec("dist", [sym(3, 4), sym(3, 4)],
    ref=lambda x, y: np.linalg.norm((x - y).reshape(-1)))
rec("det", [pd(3)], ref=np.linalg.det, rtol=1e-3, grad_tol=2e-2)
rec("slogdet", [pd(3)], grad=False)
rec("inverse", [pd(3)], ref=np.linalg.inv, rtol=1e-3, grad_tol=5e-2)
rec("solve", [pd(3), sym(3, 2)], ref=np.linalg.solve, rtol=1e-3,
    grad_tol=5e-2)
rec("cholesky", [pd(3)], ref=np.linalg.cholesky, rtol=1e-3, grad=False)
rec("cholesky_solve", [sym(3, 1), np.linalg.cholesky(pd(3))], grad=False)
rec("triangular_solve", [np.tril(pd(3)).astype(np.float32), sym(3, 2)],
    attrs={"upper": False}, grad=False)
rec("eigvalsh", [pd(3)], ref=np.linalg.eigvalsh, rtol=1e-3, grad=False)
rec("eigh", [pd(3)], grad=False)
rec("eig", [pd(3)], grad=False)     # pure_callback: jits since round 15
rec("eigvals", [pd(3)], grad=False)
rec("svd", [sym(4, 3)], grad=False)
rec("qr", [sym(4, 3)], grad=False)
rec("lu", [pd(3)], grad=False)
rec("lstsq", [sym(4, 3), sym(4, 2)], grad=False)
rec("pinv", [sym(4, 3)], ref=np.linalg.pinv, rtol=1e-2, atol=1e-3,
    grad=False)
rec("matrix_power", [pd(3)], attrs={"n": 2},
    ref=lambda x, **kw: np.linalg.matrix_power(x, 2), rtol=1e-3,
    grad=False)
rec("matrix_rank", [pd(3)], ref=np.linalg.matrix_rank, grad=False)
rec("rank", [sym(3, 4)], ref=lambda x: np.asarray(x.ndim), grad=False)
rec("cross", [sym(4, 3), sym(4, 3)], ref=np.cross)  # paddle picks the
# first len-3 axis; (4,3) makes that the last axis, matching np
rec("cdist", [sym(3, 4), sym(5, 4)], grad=False)
rec("cov", [sym(3, 6)], ref=np.cov, rtol=1e-3, grad=False)
rec("corrcoef", [sym(3, 6)], ref=np.corrcoef, rtol=1e-3, grad=False)
rec("bincount", [ints(5, 10)], ref=np.bincount, grad=False, jit=False)
rec("histogram", [sym(10)], grad=False)  # in-graph since round 15
rec("vander", [sym(4)], grad=False)
rec("einsum", ["ij,jk->ik", sym(3, 4), sym(4, 5)], jit=False, grad=False)
rec("multi_dot", [[sym(3, 4), sym(4, 5)]], jit=False, grad=False)
rec("householder_product", [sym(4, 3), sym(3)], grad=False)

# -------------------------------------------------------------------- loss
rec("mse_loss", [sym(4, 3), sym(4, 3)],
    ref=lambda x, y: ((x - y) ** 2).mean())
rec("l1_loss", [sym(4, 3), sym(4, 3)],
    ref=lambda x, y: np.abs(x - y).mean(), grad_idx=[0])
rec("smooth_l1_loss", [sym(4, 3), sym(4, 3)], grad_idx=[0])
rec("huber_loss", [sym(4, 3), sym(4, 3)], grad_idx=[0])
rec("log_loss", [unit(4, 1), boolean(4, 1).astype(np.float32)],
    grad_idx=[0])
rec("square_error_cost", [sym(4, 3), sym(4, 3)],
    ref=lambda x, y: (x - y) ** 2)
rec("binary_cross_entropy", [unit(4, 3), boolean(4, 3).astype(np.float32)],
    grad_idx=[0])
rec("binary_cross_entropy_with_logits",
    [sym(4, 3), boolean(4, 3).astype(np.float32)], grad_idx=[0])
rec("kl_div", [np.log(unit(4, 3)), unit(4, 3)], grad_idx=[0])
rec("nll_loss", [np.log(unit(4, 5)), ints(5, 4)], grad_idx=[0])
rec("cross_entropy", [sym(4, 5), ints(5, 4)], grad_idx=[0])
rec("softmax_with_cross_entropy", [sym(4, 5), ints(5, 4, 1)],
    grad=False)
rec("sigmoid_focal_loss", [sym(4, 3), boolean(4, 3).astype(np.float32)],
    grad_idx=[0])
rec("margin_ranking_loss", [sym(4), sym(4),
                            np.sign(sym(4)).astype(np.float32)],
    grad_idx=[0, 1])
rec("hinge_embedding_loss", [sym(4, 3),
                             np.where(boolean(4, 3), 1, -1).astype(
                                 np.float32)], grad_idx=[0])
rec("cosine_embedding_loss", [sym(4, 3), sym(4, 3),
                              np.where(boolean(4), 1, -1).astype(
                                  np.float32)], grad_idx=[0, 1])
rec("triplet_margin_loss", [sym(4, 3), sym(4, 3), sym(4, 3)],
    grad_idx=[0])
rec("fused_linear_cross_entropy", [sym(6, 4), sym(4, 8), ints(8, 6)],
    grad_idx=[0, 1], grad_tol=2e-2)

# ------------------------------------------------------- fused (compile/fusion)
_sig = lambda v: 1.0 / (1.0 + np.exp(-v))
rec("fused_bias_act", [sym(6, 8), sym(8)], attrs={"activation": "silu"},
    ref=lambda x, b, **kw: (x + b) * _sig(x + b), grad_tol=2e-2)
rec("fused_residual_norm", [sym(6, 8), sym(6, 8), pos(8), sym(8)],
    grad_idx=[0, 1], grad_tol=2e-2)
rec("fused_norm_linear", [sym(6, 8), sym(8, 5)],
    attrs={"norm_type": "rms_norm", "epsilon": 1e-5},
    ref=lambda x, w, **kw: (
        x / np.sqrt((x * x).mean(-1, keepdims=True) + 1e-5)) @ w,
    rtol=1e-3, grad_tol=2e-2)
rec("fused_rope_proj", [sym(2, 4, 8), sym(8, 8)],
    attrs={"num_heads": 2}, grad_tol=2e-2)

# --------------------------------------------------------------- nn_common
rec("linear", [sym(3, 4), sym(4, 5)], ref=np.matmul, grad_tol=2e-2)
rec("embedding", [ints(6, 3), sym(6, 4)], grad_idx=[1])
rec("embedding_bag", [ints(6, 3, 2), sym(6, 4)], grad_idx=[1],
    ref=lambda i, w, **kw: w[i].sum(-2))
rec("dropout", [sym(3, 4)], attrs={"p": 0.0}, ref=lambda x, **kw: x)
rec("alpha_dropout", [sym(3, 4)], attrs={"p": 0.0},
    ref=lambda x, **kw: x)
rec("dropout2d", [sym(2, 3, 4, 4)], attrs={"p": 0.0},
    ref=lambda x, **kw: x)
rec("dropout3d", [sym(2, 3, 4, 4, 4)], attrs={"p": 0.0},
    ref=lambda x, **kw: x)
rec("cosine_similarity", [sym(3, 4), sym(3, 4)])
rec("label_smooth", [unit(3, 4)],
    ref=lambda x: x * 0.9 + 0.1 / 4)
rec("sequence_mask", [ints(5, 4) + 1], attrs={"maxlen": 6}, grad=False)
rec("pixel_shuffle", [sym(1, 8, 3, 3)], attrs={"upscale_factor": 2})
rec("pixel_unshuffle", [sym(1, 2, 4, 4)], attrs={"downscale_factor": 2})
rec("channel_shuffle", [sym(1, 4, 3, 3)], attrs={"groups": 2})
rec("zeropad2d", [sym(1, 2, 3, 3)], attrs={"padding": [1, 1, 1, 1]})
rec("bilinear", [sym(3, 4), sym(3, 5), sym(2, 4, 5)], grad_idx=[0, 1])
rec("interpolate", [sym(1, 2, 4, 4)], attrs={"scale_factor": 2},
    grad=False)
rec("upsample", [sym(1, 2, 4, 4)], attrs={"scale_factor": 2},
    grad=False)
rec("fold", [sym(1, 4, 4)],
    attrs={"output_sizes": [3, 3], "kernel_sizes": 2}, grad=False)

# --------------------------------------------------------------------- norm
rec("layer_norm", [sym(3, 4)], attrs={"normalized_shape": [4]},
    rtol=1e-3)
rec("rms_norm", [sym(3, 4), np.ones(4, np.float32)], jit=False,
    grad_idx=[0], rtol=1e-3)
rec("normalize", [sym(3, 4)], rtol=1e-3)
rec("group_norm", [sym(2, 4, 3, 3)], attrs={"num_groups": 2}, rtol=1e-3)
rec("instance_norm", [sym(2, 3, 4, 4)], rtol=1e-3)
rec("batch_norm", [sym(4, 3), np.zeros(3, np.float32),
                   np.ones(3, np.float32)],
    attrs={"training": True}, grad_idx=[0], rtol=1e-3, jit=False)
rec("local_response_norm", [sym(2, 4, 5, 5)], attrs={"size": 3},
    rtol=1e-3, grad=False)

# ------------------------------------------------------------------ pooling
for nd, shape in (("1d", (1, 2, 8)), ("2d", (1, 2, 6, 6)),
                  ("3d", (1, 2, 4, 4, 4))):
    rec(f"avg_pool{nd}", [sym(*shape)], attrs={"kernel_size": 2})
    rec(f"max_pool{nd}", [spaced(*shape)], attrs={"kernel_size": 2})
    rec(f"adaptive_avg_pool{nd}", [sym(*shape)], attrs={"output_size": 2})
    rec(f"adaptive_max_pool{nd}", [spaced(*shape)],
        attrs={"output_size": 2})
rec("lp_pool1d", [sym(1, 2, 8)],
    attrs={"norm_type": 2, "kernel_size": 2}, grad=False)
rec("lp_pool2d", [sym(1, 2, 6, 6)],
    attrs={"norm_type": 2, "kernel_size": 2}, grad=False)

# --------------------------------------------------------------------- conv
rec("conv1d", [sym(1, 2, 8), sym(3, 2, 3)], grad_tol=2e-2)
rec("conv2d", [sym(1, 2, 6, 6), sym(3, 2, 3, 3)], grad_tol=2e-2)
rec("conv3d", [sym(1, 2, 4, 4, 4), sym(2, 2, 2, 2, 2)], grad_tol=2e-2)
rec("conv1d_transpose", [sym(1, 2, 6), sym(2, 3, 3)], grad_tol=2e-2)
rec("conv2d_transpose", [sym(1, 2, 5, 5), sym(2, 3, 3, 3)],
    grad_tol=2e-2)
rec("conv3d_transpose", [sym(1, 2, 3, 3, 3), sym(2, 2, 2, 2, 2)],
    grad_tol=2e-2)

# ----------------------------------------------------------------- indexing
rec("gather", [sym(4, 3), ints(4, 5)], ref=lambda x, i: x[i],
    grad_idx=[0])
rec("gather_nd", [sym(4, 3), ints(3, 2, 1)], grad_idx=[0])
rec("index_select", [sym(4, 3), ints(4, 2)], attrs={"axis": 0},
    grad_idx=[0])
rec("scatter", [sym(4, 3), ints(4, 2), sym(2, 3)], grad_idx=[0, 2],
    jit=False)
rec("scatter_nd_add", [sym(4, 3), ints(4, 2, 1), sym(2, 3)],
    grad_idx=[0, 2], jit=False)


def _scatter_add_ref(x, i, u, **kw):
    out = np.copy(x)
    np.add.at(out, i, u)
    return out


rec("scatter_add", [sym(4, 3), ints(4, 5), sym(5, 3)], grad_idx=[0, 2],
    ref=_scatter_add_ref)
rec("masked_select", [sym(3, 4), boolean(3, 4)], grad=False, jit=False)

# ------------------------------------------------------------------- search
rec("where", [boolean(3, 4), sym(3, 4), sym(3, 4)], ref=np.where,
    grad_idx=[1, 2])
rec("sort", [sym(3, 5)], ref=np.sort, grad=False)
rec("argsort", [sym(3, 5)], ref=np.argsort, grad=False)
rec("argmax", [sym(3, 5)], ref=np.argmax, grad=False)
rec("argmin", [sym(3, 5)], ref=np.argmin, grad=False)
rec("topk", [sym(3, 5)], attrs={"k": 2}, grad=False)
rec("top_k", [sym(3, 5)], attrs={"k": 2}, grad=False)
rec("kthvalue", [sym(3, 5)], attrs={"k": 2}, grad=False)
rec("nonzero", [ints(2, 3, 4).astype(np.float32)], grad=False,
    jit=False)
rec("unique", [ints(4, 10).astype(np.float32)], grad=False, jit=False)
rec("unique_consecutive", [np.sort(ints(4, 10)).astype(np.float32)],
    grad=False, jit=False)
rec("searchsorted", [np.sort(sym(6)), sym(4)], ref=np.searchsorted,
    grad=False)
rec("bucketize", [sym(4), np.sort(sym(6))],
    ref=lambda x, b: np.searchsorted(b, x), grad=False)
rec("isin", [ints(5, 6).astype(np.float32),
             ints(5, 3).astype(np.float32)], ref=np.isin, grad=False)
rec("masked_scatter", [sym(3, 4), boolean(3, 4), sym(12)], grad=False,
    jit=False)
rec("index_of_max", [sym(3, 5)], grad=False)
rec("gather_tree", [ints(3, 5, 2, 3), ints(3, 5, 2, 3)], grad=False)

# -------------------------------------------------------------- creation
rec("tril", [sym(4, 4)], ref=np.tril)
rec("triu", [sym(4, 4)], ref=np.triu)
rec("diag", [sym(4)], ref=np.diag, grad=False)
rec("diagflat", [sym(4)], ref=np.diagflat, grad=False)
rec("assign", [sym(3, 4)], ref=lambda x: x, grad=False)
rec("clone", [sym(3, 4)], ref=lambda x: x)
rec("ones_like", [sym(3, 4)], ref=np.ones_like, grad=False)
rec("zeros_like", [sym(3, 4)], ref=np.zeros_like, grad=False)
rec("full_like", [sym(3, 4)], attrs={"fill_value": 2.5},
    ref=lambda x, **kw: np.full_like(x, 2.5), grad=False)
rec("empty_like", [sym(3, 4)], grad=False)
rec("one_hot", [ints(4, 5)], attrs={"num_classes": 4}, grad=False)
rec("complex", [sym(3, 4), sym(3, 4)], grad=False)
rec("polar", [pos(3, 4), sym(3, 4)], grad=False)
rec("to_tensor", [sym(3, 4)], ref=lambda x: x, grad=False)

# ---------------------------------------------------------------- signal
rec("frame", [sym(1, 16)], attrs={"frame_length": 4, "hop_length": 2},
    grad=False)
rec("overlap_add", [sym(1, 4, 7)], attrs={"hop_length": 2}, grad=False)


# --------------------------------------------------------- op-surface tail
rec("rad2deg", [sym(3, 4)], ref=np.rad2deg)
rec("deg2rad", [sym(3, 4)], ref=np.deg2rad)
rec("sinc", [sym(3, 4)], ref=np.sinc)
rec("sgn", [sym(3, 4)], ref=np.sign)
rec("signbit", [sym(3, 4)], ref=np.signbit, grad=False)
rec("isneginf", [np.array([[1.0, -np.inf], [np.inf, 0.0]], np.float32)],
    ref=np.isneginf, grad=False)
rec("isposinf", [np.array([[1.0, -np.inf], [np.inf, 0.0]], np.float32)],
    ref=np.isposinf, grad=False)
rec("isreal", [sym(3, 4)], ref=np.isreal, grad=False)
rec("multigammaln", [gt1(3, 4) + 2.0], attrs={"p": 2}, grad=True)
rec("cumulative_trapezoid", [sym(3, 6)],
    ref=lambda a: np.cumsum((a[..., 1:] + a[..., :-1]) * 0.5, axis=-1))
rec("pdist", [sym(5, 3)],
    ref=lambda a: np.sqrt(
        ((a[:, None, :] - a[None, :, :]) ** 2).sum(-1))[
            np.triu_indices(5, k=1)],
    grad_tol=3e-2)  # sqrt'(d) amplifies FD error at small distances
rec("block_diag", [[sym(2, 2), sym(3, 1)]],
    ref=None, grad=False, jit=False)
rec("hsplit", [sym(4, 6)], attrs={"num_or_indices": 2}, grad=False)
rec("vsplit", [sym(4, 6)], attrs={"num_or_indices": 2}, grad=False)
rec("dsplit", [sym(2, 2, 4)], attrs={"num_or_indices": 2}, grad=False)
rec("unflatten", [sym(6, 2)], attrs={"axis": 0, "shape": [2, 3]},
    ref=lambda a: a.reshape(2, 3, 2))
rec("index_fill", [sym(4, 3), np.array([0, 2], np.int64)],
    attrs={"axis": 0, "value": 7.0}, grad=False)
rec("diagonal_scatter", [sym(4, 4), sym(4)],
    ref=lambda a, b: (a * (1 - np.eye(4, dtype=a.dtype))
                      + np.diag(b).astype(a.dtype)))
rec("scatter_nd", [np.array([[1], [3]], np.int64), sym(2)],
    attrs={"shape": [6]}, grad=False)
rec("add_n", [[sym(3, 4), sym(3, 4), sym(3, 4)]],
    ref=lambda xs: xs[0] + xs[1] + xs[2], grad=False, jit=False)
# list-input ops: the harness hands ref the list itself (concat idiom)
for _sname, _sref in (("hstack", np.hstack), ("vstack", np.vstack),
                      ("dstack", np.dstack),
                      ("column_stack", np.column_stack),
                      ("row_stack", np.vstack)):
    rec(_sname, [[sym(2, 3), sym(2, 3)]], ref=_sref, grad=False,
        jit=False)


# ---------------------------------------------------------------- skips
from paddle_tpu.ops.inplace import INPLACE_OF  # noqa: E402

SKIP = {
    # higher-order control-flow ops: their operands are callables plus
    # whatever Tensors the branches close over — there is no sweepable
    # (inputs, attrs) recipe; eager/compiled/gradient behavior has a
    # dedicated suite
    **{n: "higher-order control-flow op (callable operands); covered by "
          "tests/test_control_flow.py"
       for n in ("conditional_block", "while_loop", "case",
                 "switch_case")},
    # in-place variants: payload-swap wrappers over the swept base ops
    **{n: f"in-place alias of {b} (payload swap; base op swept)"
       for n, b in INPLACE_OF.items()},
    "where_": "hand-written in-place where (adopts into x, not the "
              "condition — see ADVICE r4); semantics in test_advice_fixes",
    **{n: "random in-place fill; seeded behavior in test_api_tail.py"
       for n in ("normal_", "bernoulli_", "log_normal_", "cauchy_",
                 "geometric_")},
    # linalg tail: numerically verified against numpy/scipy in
    # tests/test_submodule_tail.py (decompositions need scipy refs)
    **{n: "covered by tests/test_submodule_tail.py (scipy/numpy refs)"
       for n in ("inv cholesky_inverse matrix_exp vector_norm "
                 "matrix_norm cond svd_lowrank ormqr").split()},
    # dispatched names the program verifier's TPU700 pass surfaced as
    # unregistered (round 20): now carry OpDefs; dedicated coverage
    "scaled_dot_product_attention":
        "pallas/XLA fused attention; eager/compiled/grad parity in "
        "tests/test_flash_attention.py and the model suites",
    "rotary_embedding":
        "RoPE with python-int/traced/per-batch offset contract; covered "
        "by the llama suites + fusion rope_proj tests",
    "getitem":
        "tensor indexing protocol (t[idx]); exercised pervasively via "
        "__getitem__ across the whole suite",
    "setitem":
        "in-place indexing protocol (t[idx] = v, registered round 22 "
        "for the TPU75x alias pass); exercised via __setitem__ across "
        "the suite and region-attr semantics in test_program_verifier",
    # registered lazily on fleet.moe import, so they only appear in the
    # registry when an earlier test pulled in the MoE stack
    "moe_gate":
        "gating softmax + top-k capacity dispatch: data-dependent "
        "routing has no elementwise sweep contract; parity-tested in "
        "test_moe_sep and verified in the tpulint --programs "
        "moe_layer ladder rung",
    "moe_layer":
        "monolithic GShard dispatch/expert/combine op: grouped einsum "
        "over routed tokens has no elementwise sweep contract; "
        "parity-tested in test_moe_sep and verified in the tpulint "
        "--programs moe_layer ladder rung",
    # op-surface tail without a sweepable contract
    "histogramdd": "multi-output (hist, edges-list) contract; "
                   "numpy-parity tested in test_api_tail",
    "as_strided": "gather-based strided view; covered in test_api_tail",
    "combinations": "index enumeration; covered in test_api_tail",
    "frexp": "dual-output decomposition; covered in test_api_tail",
    "binomial": "random draws; covered in test_api_tail",
    "standard_gamma": "random draws; covered in test_api_tail",
    "log_normal": "random draws (factory); covered in test_api_tail",
    # creation ops without a tensor input (shape-driven factories) —
    # exercised throughout the suite and in tests/test_ops.py
    **{n: "factory op (no tensor input); covered across the suite"
       for n in ("arange empty eye full linspace logspace ones zeros "
                 "rand randn randint_like randperm standard_normal "
                 "tril_indices triu_indices normal multinomial "
                 "bernoulli poisson exponential_ gaussian randint "
                 "uniform").split()},
    # stateful / random semantics (seeded paths covered in test_ops.py /
    # test_distributions.py)
    "shuffle_batch": "random shuffle; seeded behavior in test_ops.py",
    "top_p_sampling": "random sampling; covered by test_serving.py",
    "class_center_sample": "random sampling; covered in test_opset_round2.py",
    # dedicated suites
    "block_multihead_attention": "covered by tests/test_paged_attention.py",
    "ctc_loss": "covered by tests/test_ops_round2b.py (CTC numerics)",
    "ctc_align": "covered by tests/test_ops_round2b.py",
    "rnnt_loss": "covered by tests/test_text_onnx.py / round2b",
    "edit_distance": "covered by tests/test_ops_round2b.py",
    "hsigmoid_loss": "tree-code signature; covered by round2b tests",
    "stft": "complex windowed transform; covered by test_ops_round2b.py",
    "istft": "complex windowed transform; covered by test_ops_round2b.py",
    **{n: "covered by tests/test_vision_ops.py"
       for n in ("affine_grid bipartite_match box_clip box_coder "
                 "correlation decode_jpeg deform_conv2d "
                 "distribute_fpn_proposals generate_proposals "
                 "grid_sample matrix_nms multiclass_nms nms prior_box "
                 "psroi_pool read_file roi_align roi_pool "
                 "temporal_shift yolo_box yolo_loss").split()},
    **{n: "covered by tests/test_sparse_ops.py geometric section"
       for n in ("reindex_graph reindex_heter_graph sample_neighbors "
                 "segment_max segment_mean segment_min segment_sum "
                 "send_u_recv send_ue_recv send_uv "
                 "weighted_sample_neighbors").split()},
    **{n: "covered by tests/test_coverage_round2b.py quantization"
       for n in ("apply_per_channel_scale fake_quant llm_int8_linear "
                 "weight_dequantize weight_only_linear "
                 "weight_quantize").split()},
    # in-place aliases of swept ops
    "reshape_": "in-place alias of reshape",
    "squeeze_": "in-place alias of squeeze",
    "unsqueeze_": "in-place alias of unsqueeze",
    # pooling variants with auxiliary-index plumbing
    "max_unpool1d": "needs indices from return_mask pool; test_nn.py",
    "max_unpool2d": "needs indices from return_mask pool; test_nn.py",
    "max_unpool3d": "needs indices from return_mask pool; test_nn.py",
    "fractional_max_pool2d": "random regions; covered in test_nn.py",
    "fractional_max_pool3d": "random regions; covered in test_nn.py",
    "adaptive_max_pool3d": "covered in test_nn.py (mask variant)",
    "lu_unpack": "consumes lu() pivots tuple; covered with lu in "
                 "test_ops.py",
    "pca_lowrank": "randomized algorithm; property-tested in test_ops.py",
}


def _to_tensor(v):
    if isinstance(v, paddle.Tensor):
        return v
    if isinstance(v, (list, tuple)) and all(
            isinstance(x, np.ndarray) for x in v):
        return [paddle.to_tensor(x) for x in v]
    if isinstance(v, np.ndarray):
        return paddle.to_tensor(v)
    return v


def _leaves(out):
    if isinstance(out, paddle.Tensor):
        return [out]
    if isinstance(out, (list, tuple)):
        res = []
        for o in out:
            res.extend(_leaves(o))
        return res
    return []


#: the registry as it stands at import (collection) time — ops that
#: OTHER tests register at runtime (custom-op suites exercising the
#: registration API) are not part of the framework surface this sweep
#: pins, and their presence must not depend on test execution order
_BASELINE_OPS = set(OPS)

ALL_SWEPT = sorted(set(R) & set(OPS))


def test_registry_fully_classified():
    """Every registered op is either swept or skip-listed with a reason —
    an unclassified new op fails the suite. Ops registered at RUNTIME by
    other tests (custom-op tests register from test modules) are out of
    scope — only the framework's own surface is pinned."""
    framework = {n for n in _BASELINE_OPS
                 if getattr(OPS.get(n), "lowering", None) is not None
                 and getattr(OPS[n].lowering, "__module__",
                             "").startswith("paddle_tpu")}
    unclassified = sorted(framework - set(R) - set(SKIP))
    assert not unclassified, (
        f"{len(unclassified)} registry ops lack a sweep recipe or a "
        f"skip reason: {unclassified}")
    # and no recipe/skip entry names a non-existent op (a typo would
    # silently test nothing)
    phantom = sorted((set(R) | set(SKIP)) - set(OPS))
    assert not phantom, f"recipes/skips for unknown ops: {phantom}"
    # and the partition is meaningful: the large majority is swept
    assert len(ALL_SWEPT) >= 300, (len(ALL_SWEPT), len(OPS))


@pytest.mark.parametrize("name", ALL_SWEPT)
def test_op(name):
    spec = R[name]
    d = OPS[name]
    fn = d.lowering
    with jax.default_matmul_precision("highest"):
        tensors = [_to_tensor(np.copy(v) if isinstance(v, np.ndarray)
                              else v) for v in spec["inputs"]]
        out = fn(*tensors, **spec["attrs"])
        leaves = _leaves(out)
        assert leaves, f"{name} returned no tensors"
        for o in leaves:
            a = o.numpy()
            if np.issubdtype(a.dtype, np.floating):
                assert np.isfinite(a).all(), f"{name}: non-finite output"

        if spec["ref"] is not None:
            ref = spec["ref"](*[np.copy(v) if isinstance(v, np.ndarray)
                                else v for v in spec["inputs"]])
            refs = ref if isinstance(ref, (list, tuple)) else [ref]
            for o, r in zip(leaves, refs):
                np.testing.assert_allclose(
                    o.numpy().astype(np.float64),
                    np.asarray(r).astype(np.float64),
                    rtol=spec["rtol"], atol=spec["atol"],
                    err_msg=f"{name}: forward mismatch vs NumPy")

        # eager == jit parity (array-only signatures)
        if spec["jit"] and all(isinstance(v, np.ndarray)
                               for v in spec["inputs"]):
            def jfn(*arrays):
                o = fn(*[paddle.Tensor(a) for a in arrays],
                       **spec["attrs"])
                return [t._data for t in _leaves(o)]

            jout = jax.jit(jfn)(*[jnp.asarray(v) for v in spec["inputs"]])
            for o, jo in zip(leaves, jout):
                np.testing.assert_allclose(
                    np.asarray(o.numpy(), np.float64),
                    np.asarray(jo, np.float64), rtol=1e-5, atol=1e-6,
                    err_msg=f"{name}: eager/jit divergence")

        # finite-difference gradient check
        if spec["grad"] and d.differentiable:
            from op_test import check_grad
            float_idx = [i for i, v in enumerate(spec["inputs"])
                         if isinstance(v, np.ndarray)
                         and np.issubdtype(v.dtype, np.floating)]
            idxs = spec["grad_idx"] if spec["grad_idx"] is not None \
                else float_idx
            if idxs and all(isinstance(v, np.ndarray)
                            for v in spec["inputs"]):
                check_grad(fn, [np.copy(v) for v in spec["inputs"]],
                           attrs=spec["attrs"], grad_input_idx=idxs,
                           max_relative_error=spec["grad_tol"])


# ---------------------------------------------------------------- bf16 pass
# Per-dtype sweep (reference op_test.py:1084,1492 — per-dtype tolerance
# defaults; bf16 atol 1e-2, grad 0.03): every float recipe re-runs with
# bf16 inputs and must stay within bf16 tolerances of its own f32 result.
# bf16 is THE dtype this framework trains in, so its coverage is pinned
# like the fp32 partition: eligible = all-float32-ndarray-input recipes;
# an op that cannot run bf16 needs a written reason in BF16_SKIP.

BF16_SKIP = {
    # LAPACK-style decompositions / solvers: f32/f64-only algorithms
    # (also f32/f64-only in the reference's MKL/cuSOLVER backends)
    **{n: "LAPACK-backed linalg; f32/f64 only (reference parity)"
       for n in ("cholesky cholesky_solve eig eigh eigvals eigvalsh "
                 "svd qr lu matrix_power det slogdet inverse "
                 "lstsq solve triangular_solve matrix_rank "
                 "corrcoef cov pinv householder_product").split()},
    **{n: "constructs complex64 outputs; complex has no bf16 analog"
       for n in ("complex", "as_complex", "polar")},
    "erfinv": "XLA bf16 erfinv lowering unsupported; f32 upcast is the "
              "documented usage",
    "i0": "Bessel series needs f32 accumulation; reference CPU kernel "
          "is f32/f64 only",
    "i0e": "as i0", "i1": "as i0", "i1e": "as i0",
    "polygamma": "series expansion; f32/f64 only in reference too",
    "digamma": "as polygamma", "lgamma": "as polygamma",
    "gammaln": "as polygamma",
    "logit": "log(p/(1-p)) near saturation overflows bf16's 8-bit "
             "mantissa beyond any fixed tolerance",
    "histogram": "bin boundary assignment flips under bf16 rounding",
    "histogramdd": "as histogram", "bincount": "integer-driven",
    "searchsorted": "boundary comparisons flip under bf16 rounding",
    "bucketize": "as searchsorted",
    "isclose": "tolerance semantics are dtype-relative; bf16 run is "
               "a different contract, covered by its own unit test",
    "allclose": "as isclose",
}


def _bf16_eligible(name):
    spec = R[name]
    ins = spec["inputs"]
    return (spec["jit"] and ins
            and all(isinstance(v, np.ndarray) for v in ins)
            and all(v.dtype == np.float32 for v in ins))


BF16_SWEPT = sorted(n for n in ALL_SWEPT
                    if _bf16_eligible(n) and n not in BF16_SKIP)


def test_bf16_partition_pinned():
    """The bf16-covered count is pinned the way the fp32 one is: a new
    float op must either sweep in bf16 or carry a written reason."""
    assert len(BF16_SWEPT) >= 150, len(BF16_SWEPT)
    phantom = sorted(set(BF16_SKIP) - set(OPS))
    assert not phantom, f"BF16_SKIP names unknown ops: {phantom}"


@pytest.mark.parametrize("name", BF16_SWEPT)
def test_op_bf16(name):
    """bf16 run vs the op's own f32 result, at reference bf16
    tolerances. Outputs that are integral/bool (argmax, counts) must be
    EXACT; float outputs get rtol/atol 3e-2 over the f32 baseline plus
    the input-rounding error bf16 casting introduces."""
    spec = R[name]
    fn = OPS[name].lowering
    with jax.default_matmul_precision("highest"):
        f32_in = [np.copy(v) for v in spec["inputs"]]
        # the f32 BASELINE uses the bf16-rounded values, so the compare
        # isolates the op's own bf16 arithmetic from input rounding
        rounded = [np.asarray(jnp.asarray(v, jnp.bfloat16)
                              .astype(jnp.float32)) for v in f32_in]
        ref = _leaves(fn(*[_to_tensor(v) for v in rounded],
                         **spec["attrs"]))
        got = _leaves(fn(*[paddle.Tensor(jnp.asarray(v, jnp.bfloat16))
                           for v in f32_in], **spec["attrs"]))
        assert len(ref) == len(got)
        def is_float(dt):
            # ml_dtypes' bfloat16/float8 are NOT np.floating subtypes
            return (np.issubdtype(dt, np.floating)
                    or jnp.issubdtype(dt, jnp.floating))

        for r, g in zip(ref, got):
            ga = g.numpy()
            ra = r.numpy()
            if is_float(ra.dtype):
                assert is_float(ga.dtype), \
                    f"{name}: float output became {ga.dtype}"
                np.testing.assert_allclose(
                    ga.astype(np.float64), ra.astype(np.float64),
                    rtol=3e-2, atol=3e-2,
                    err_msg=f"{name}: bf16 output diverged")
            else:
                np.testing.assert_array_equal(
                    ga, ra, err_msg=f"{name}: integral output changed "
                                    f"under bf16")
