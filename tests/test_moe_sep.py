"""MoE + sequence/context parallelism tests on the 8-dev virtual mesh.

Reference analogs: test/collective/collective_global_gather.py MoE routing
tests; the ring attention must equal full attention (the segment-parallel
correctness contract).
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet import (MoELayer, TopKGate,
                                          ring_flash_attention,
                                          scatter_gather_attention)


@pytest.fixture
def sep_mesh():
    old = mesh_mod._global_mesh
    mesh = mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 2, "sep": 4}))
    yield mesh
    mesh_mod._global_mesh = old


@pytest.fixture
def mp_mesh():
    old = mesh_mod._global_mesh
    mesh = mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 2, "mp": 4}))
    yield mesh
    mesh_mod._global_mesh = old


def _ref_attn(q, k, v, causal, scale):
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * scale
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool))
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", p, v)


class TestGate:
    def test_top1_routing_shapes_and_capacity(self):
        paddle.seed(0)
        gate = TopKGate(16, 4, top_k=1, capacity_factor=1.0)
        x = paddle.to_tensor(np.random.randn(32, 16).astype(np.float32))
        combine, dispatch_m, aux = gate(x)
        n, e, c = combine.shape
        assert (n, e) == (32, 4) and c == max(int(1.0 * 32 * 1 / 4), 1)
        d = np.asarray(dispatch_m._data)
        # each capacity slot of each expert holds at most one token
        assert d.sum(axis=0).max() <= 1.0 + 1e-6
        # each token dispatched at most once (top-1)
        assert d.sum(axis=(1, 2)).max() <= 1.0 + 1e-6
        assert float(aux.numpy()) > 0

    def test_top2_dispatches_two_experts(self):
        paddle.seed(1)
        gate = TopKGate(16, 4, top_k=2, capacity_factor=4.0)
        x = paddle.to_tensor(np.random.randn(16, 16).astype(np.float32))
        combine, dispatch_m, aux = gate(x)
        d = np.asarray(dispatch_m._data)
        # ample capacity: every token goes to exactly 2 experts
        np.testing.assert_allclose(d.sum(axis=(1, 2)), 2.0)

    def test_gate_weight_receives_grad(self):
        paddle.seed(2)
        gate = TopKGate(8, 2, top_k=1, capacity_factor=2.0)
        x = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
        combine, _, aux = gate(x)
        loss = paddle.ops.sum(combine) + aux
        loss.backward()
        assert gate.weight.grad is not None


class TestMoELayer:
    def test_single_expert_equals_dense(self, mp_mesh):
        paddle.seed(3)
        moe = MoELayer(16, num_experts=1, d_hidden=32, top_k=1,
                       capacity_factor=8.0)
        x = paddle.to_tensor(np.random.randn(2, 8, 16).astype(np.float32))
        out = moe(x)
        # with one expert and ample capacity every token routes to it with
        # weight softmax([logit])=1
        expert = moe.experts[0]
        ref = expert(paddle.ops.reshape(x, [-1, 16]))
        np.testing.assert_allclose(
            np.asarray(out._data).reshape(-1, 16),
            np.asarray(ref._data), atol=1e-5)

    def test_expert_parallel_runs_and_backprops(self, mp_mesh):
        paddle.seed(4)
        moe = MoELayer(16, num_experts=4, d_hidden=32, top_k=2,
                       capacity_factor=2.0, ep_axis="mp")
        x = paddle.to_tensor(np.random.randn(4, 8, 16).astype(np.float32))
        out = moe(x)
        assert out.shape == [4, 8, 16]
        loss = paddle.ops.mean(out ** 2) + 0.01 * moe.l_aux
        loss.backward()
        n_grads = sum(1 for p in moe.parameters() if p.grad is not None)
        assert n_grads == len(list(moe.parameters()))

    def test_heterogeneous_experts_rejected(self, mp_mesh):
        class OtherExpert(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(16, 16)

            def forward(self, x):
                return self.fc(x)

        from paddle_tpu.distributed.fleet.moe import _ExpertMLP
        with pytest.raises(ValueError, match="identical in structure"):
            MoELayer(16, num_experts=2,
                     experts=[_ExpertMLP(16, 32), OtherExpert()])

    def test_incubate_import_path(self):
        from paddle_tpu.incubate.nn import MoELayer as M2
        assert M2 is MoELayer


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, sep_mesh, causal):
        rng = np.random.RandomState(0)
        b, s, h, d = 2, 32, 4, 8
        q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
        sh = NamedSharding(sep_mesh, P(None, "sep", None, None))
        qt = paddle.Tensor(jax.device_put(q, sh), stop_gradient=False)
        kt = paddle.Tensor(jax.device_put(k, sh), stop_gradient=False)
        vt = paddle.Tensor(jax.device_put(v, sh), stop_gradient=False)
        out = ring_flash_attention(qt, kt, vt, causal=causal)
        ref = _ref_attn(q, k, v, causal, 1.0 / math.sqrt(d))
        np.testing.assert_allclose(np.asarray(out._data), np.asarray(ref),
                                   atol=2e-5)

    def test_gradients_match(self, sep_mesh):
        rng = np.random.RandomState(1)
        b, s, h, d = 1, 16, 2, 8
        qv = rng.randn(b, s, h, d).astype(np.float32)
        kv = rng.randn(b, s, h, d).astype(np.float32)
        vv = rng.randn(b, s, h, d).astype(np.float32)
        sh = NamedSharding(sep_mesh, P(None, "sep", None, None))

        qt = paddle.Tensor(jax.device_put(jnp.asarray(qv), sh),
                           stop_gradient=False)
        kt = paddle.Tensor(jax.device_put(jnp.asarray(kv), sh),
                           stop_gradient=False)
        vt = paddle.Tensor(jax.device_put(jnp.asarray(vv), sh),
                           stop_gradient=False)
        out = ring_flash_attention(qt, kt, vt, causal=True)
        paddle.ops.sum(out ** 2).backward()

        sc = 1.0 / math.sqrt(d)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(_ref_attn(q, k, v, True, sc) ** 2),
            argnums=(0, 1, 2))(jnp.asarray(qv), jnp.asarray(kv),
                               jnp.asarray(vv))
        for t, g in zip((qt, kt, vt), g_ref):
            np.testing.assert_allclose(np.asarray(t.grad._data),
                                       np.asarray(g), atol=5e-5)

    def test_ring_sharding_preserved(self, sep_mesh):
        b, s, h, d = 2, 32, 4, 8
        sh = NamedSharding(sep_mesh, P(None, "sep", None, None))
        mk = lambda: paddle.Tensor(jax.device_put(
            jnp.ones((b, s, h, d), jnp.float32), sh))
        out = ring_flash_attention(mk(), mk(), mk(), causal=False)
        spec = out._data.sharding.spec
        entries = tuple(spec) + (None,) * (4 - len(tuple(spec)))
        assert entries == (None, "sep", None, None)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, sep_mesh, causal):
        rng = np.random.RandomState(2)
        b, s, h, d = 2, 32, 4, 8   # h divisible by sep=4
        q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
        sh = NamedSharding(sep_mesh, P(None, "sep", None, None))
        qt = paddle.Tensor(jax.device_put(q, sh))
        kt = paddle.Tensor(jax.device_put(k, sh))
        vt = paddle.Tensor(jax.device_put(v, sh))
        out = scatter_gather_attention(qt, kt, vt, causal=causal)
        ref = _ref_attn(q, k, v, causal, 1.0 / math.sqrt(d))
        np.testing.assert_allclose(np.asarray(out._data), np.asarray(ref),
                                   atol=2e-5)
