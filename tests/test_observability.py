"""Observability stack tests: metrics registry, exporters, dispatch/jit/
collective instrumentation, the rebuilt profiler (real host latency,
scheduler boundaries, merged chrome trace), and the zero-overhead-when-off
guarantee the tier-1 suite enforces.
"""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, profiler
from paddle_tpu.observability import REGISTRY, metrics, trace


@pytest.fixture(autouse=True)
def _metrics_hygiene():
    """Each test starts with a zeroed registry and the flag OFF, and
    leaves no collection enabled behind."""
    paddle.set_flags({"FLAGS_enable_metrics": False})
    REGISTRY.reset()
    trace.deactivate()
    trace.clear()
    yield
    paddle.set_flags({"FLAGS_enable_metrics": False})
    REGISTRY.reset()
    trace.deactivate()
    trace.clear()


def _enable():
    paddle.set_flags({"FLAGS_enable_metrics": True})


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_counter_labels_and_prometheus(self):
        _enable()
        c = metrics.counter("test_requests_total", "help text",
                            labelnames=("code",))
        c.inc(code="200")
        c.inc(2, code="500")
        assert c.value(code="200") == 1
        assert c.value(code="500") == 2
        text = REGISTRY.to_prometheus()
        assert '# TYPE test_requests_total counter' in text
        assert 'test_requests_total{code="200"} 1' in text
        assert 'test_requests_total{code="500"} 2' in text

    def test_histogram_buckets_cumulative(self):
        _enable()
        h = metrics.histogram("test_lat_seconds", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        assert h.count() == 4
        assert h.sum() == pytest.approx(5.555)
        text = REGISTRY.to_prometheus()
        assert 'test_lat_seconds_bucket{le="0.01"} 1' in text
        assert 'test_lat_seconds_bucket{le="0.1"} 2' in text
        assert 'test_lat_seconds_bucket{le="1.0"} 3' in text
        assert 'test_lat_seconds_bucket{le="+Inf"} 4' in text
        assert 'test_lat_seconds_count 4' in text

    def test_gauge_callback_evaluated_at_snapshot(self):
        g = metrics.gauge("test_cb_gauge")
        box = {"v": 7.0}
        g.set_function(lambda: box["v"])
        snap = REGISTRY.snapshot()
        assert snap["test_cb_gauge"]["series"][0]["value"] == 7.0
        box["v"] = 9.0
        assert REGISTRY.snapshot()["test_cb_gauge"]["series"][0]["value"] == 9.0

    def test_get_or_create_and_kind_conflict(self):
        c1 = metrics.counter("test_same_name")
        assert metrics.counter("test_same_name") is c1
        with pytest.raises(TypeError):
            metrics.gauge("test_same_name")

    def test_device_live_bytes_gauge_present(self):
        snap = REGISTRY.snapshot()
        assert "paddle_tpu_device_live_bytes" in snap
        assert snap["paddle_tpu_device_live_bytes"]["series"][0]["value"] >= 0

    def test_reset_zeroes_but_keeps_instruments(self):
        _enable()
        c = metrics.counter("test_reset_total")
        c.inc()
        REGISTRY.reset()
        assert c.total() == 0
        assert REGISTRY.get("test_reset_total") is c

    def test_prometheus_escapes_label_values(self):
        _enable()
        c = metrics.counter("test_escape_total", labelnames=("key",))
        c.inc(key='tile("8,128")|b\\s\nx')
        text = REGISTRY.to_prometheus()
        assert r'key="tile(\"8,128\")|b\\s\nx"' in text

    def test_snapshot_roundtrips_through_json(self):
        _enable()
        metrics.counter("test_json_total", labelnames=("k",)).inc(k="a")
        snap = json.loads(json.dumps(REGISTRY.snapshot()))
        text = metrics.render_prometheus(snap)
        assert 'test_json_total{k="a"} 1' in text


# ---------------------------------------------------------------------------
# disabled = compiled out
# ---------------------------------------------------------------------------
class TestDisabledIsFree:
    def test_zero_collection_when_flag_off(self):
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        for _ in range(5):
            _ = x @ x + x
        snap = REGISTRY.snapshot()
        for name, m in snap.items():
            if m["kind"] == "counter":
                assert all(s["value"] == 0 for s in m["series"]), name
            elif m["kind"] == "histogram":
                assert all(s["value"]["count"] == 0
                           for s in m["series"]), name
        # no framework counter/histogram series should even exist
        assert not any(m["kind"] in ("counter", "histogram")
                       for m in snap.values())

    def test_dispatch_never_reads_clock_when_off(self, monkeypatch):
        """The ~zero-overhead guarantee, deterministically: with metrics
        off, no hooks, and no trace session, dispatch must not touch the
        telemetry clock at all."""
        from paddle_tpu.core import dispatch
        assert not dispatch._op_hooks, "leaked op hook from another test"
        calls = {"n": 0}
        real = time.perf_counter

        def counting():
            calls["n"] += 1
            return real()

        monkeypatch.setattr(dispatch, "_perf_counter", counting)
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        _ = x + x
        assert calls["n"] == 0
        _enable()
        _ = x + x
        assert calls["n"] > 0

    def test_instrument_calls_are_noops_when_off(self):
        c = metrics.counter("test_off_total")
        c.inc()
        h = metrics.histogram("test_off_seconds")
        h.observe(1.0)
        assert c.total() == 0 and h.total_count() == 0


# ---------------------------------------------------------------------------
# dispatch + eager-jit instrumentation
# ---------------------------------------------------------------------------
class TestDispatchMetrics:
    def test_op_latency_collected_per_op(self):
        _enable()
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        for _ in range(3):
            _ = x * x
        h = REGISTRY.get("paddle_tpu_dispatch_op_latency_seconds")
        assert h.count(op="multiply") == 3
        assert h.sum(op="multiply") > 0

    def test_eager_jit_cache_events(self):
        _enable()
        x = paddle.to_tensor(np.ones((3, 3), np.float32))
        for _ in range(8):
            _ = x + x
        c = REGISTRY.get("paddle_tpu_eager_jit_cache_total")
        assert c.total() >= 8  # every dispatch classified


# ---------------------------------------------------------------------------
# to_static / SOT instrumentation
# ---------------------------------------------------------------------------
class TestCompileMetrics:
    def test_compile_initial_and_retrace(self):
        _enable()

        @paddle.jit.to_static
        def f(a):
            return a * 2 + 1

        f(paddle.to_tensor(np.ones((2, 2), np.float32)))
        f(paddle.to_tensor(np.ones((2, 2), np.float32)))   # cached
        f(paddle.to_tensor(np.ones((4, 4), np.float32)))   # retrace
        c = REGISTRY.get("paddle_tpu_to_static_compile_total")
        assert c.value(kind="initial") == 1
        assert c.value(kind="retrace") == 1
        r = REGISTRY.get("paddle_tpu_to_static_retrace_total")
        assert r.value(reason="new_input_shapes") == 1
        t = REGISTRY.get("paddle_tpu_to_static_compile_seconds")
        assert t.count(kind="initial") == 1
        assert t.count(kind="retrace") == 1
        assert t.sum(kind="initial") > 0

    def test_graph_break_reason_counter(self):
        _enable()

        @paddle.jit.to_static
        def g(a):
            if float(a.sum()) > 0:     # host sync -> graph break
                return a + 1
            return a - 1

        with pytest.warns(UserWarning):
            g(paddle.to_tensor(np.ones((2, 2), np.float32)))
        c = REGISTRY.get("paddle_tpu_graph_break_total")
        assert c.total() >= 1
        sot = REGISTRY.get("paddle_tpu_sot_frame_total")
        assert sot.value(mode="replay") >= 1


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------
class TestCollectiveMetrics:
    def test_all_reduce_counts_bytes_and_latency(self):
        import paddle_tpu.distributed as dist
        dist.set_mesh(dist.build_mesh({"dp": 8}))
        _enable()
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        dist.all_reduce(x)
        dist.all_reduce(x)
        calls = REGISTRY.get("paddle_tpu_collective_calls_total")
        byts = REGISTRY.get("paddle_tpu_collective_bytes_total")
        lat = REGISTRY.get("paddle_tpu_collective_latency_seconds")
        assert calls.value(op="all_reduce") == 2
        assert byts.value(op="all_reduce") == 2 * 4 * 4 * 4  # fp32 bytes
        assert lat.count(op="all_reduce") == 2

    def test_barrier_records_once_not_as_all_reduce(self):
        import paddle_tpu.distributed as dist
        dist.set_mesh(dist.build_mesh({"dp": 8}))
        _enable()
        dist.barrier()
        calls = REGISTRY.get("paddle_tpu_collective_calls_total")
        assert calls.value(op="barrier") == 1
        assert calls.value(op="all_reduce") == 0


# ---------------------------------------------------------------------------
# autotune
# ---------------------------------------------------------------------------
class TestAutotuneMetrics:
    def test_cache_hit_miss_and_winner(self, tmp_path):
        from paddle_tpu.ops.pallas import autotune as at
        _enable()
        cache = at.AutotuneCache(str(tmp_path / "at.json"))
        orig = at._cache
        at._cache = cache
        try:
            key = "test_kernel|unit"
            run = lambda cand, i: np.float32(cand)
            win = at.autotune(key, [1, 2], run, default=1, warmup=1, iters=1)
            assert win in (1, 2)
            at.autotune(key, [1, 2], run, default=1)     # served from cache
        finally:
            at._cache = orig
        c = REGISTRY.get("paddle_tpu_autotune_cache_total")
        assert c.value(event="miss") == 1
        assert c.value(event="hit") == 1
        g = REGISTRY.get("paddle_tpu_autotune_winner_seconds")
        assert g.value(key=key) >= 0


# ---------------------------------------------------------------------------
# scheduler boundaries (satellite)
# ---------------------------------------------------------------------------
class TestMakeScheduler:
    def test_skip_first_shifts_cycle(self):
        from paddle_tpu.profiler import ProfilerState as S, make_scheduler
        sch = make_scheduler(closed=1, ready=1, record=1, repeat=1,
                             skip_first=3)
        assert [sch(i) for i in range(3)] == [S.CLOSED] * 3
        assert sch(3) == S.CLOSED
        assert sch(4) == S.READY
        assert sch(5) == S.RECORD_AND_RETURN

    def test_record_and_return_at_cycle_end(self):
        from paddle_tpu.profiler import ProfilerState as S, make_scheduler
        sch = make_scheduler(closed=0, ready=0, record=3, repeat=0)
        assert [sch(i) for i in range(6)] == [
            S.RECORD, S.RECORD, S.RECORD_AND_RETURN,
            S.RECORD, S.RECORD, S.RECORD_AND_RETURN]

    def test_repeat_closes_after_n_cycles(self):
        from paddle_tpu.profiler import ProfilerState as S, make_scheduler
        sch = make_scheduler(closed=1, ready=0, record=1, repeat=2)
        assert sch(0) == S.CLOSED and sch(1) == S.RECORD_AND_RETURN
        assert sch(2) == S.CLOSED and sch(3) == S.RECORD_AND_RETURN
        for i in range(4, 10):
            assert sch(i) == S.CLOSED


# ---------------------------------------------------------------------------
# profiler
# ---------------------------------------------------------------------------
class TestProfilerLatency:
    def test_summary_reports_real_host_time(self, capsys):
        net = nn.Linear(8, 8)
        x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        with profiler.Profiler(timer_only=True) as p:
            for _ in range(3):
                net(x)
                p.step()
        stats = p.op_stats()
        name = "linear" if "linear" in stats else "matmul"
        assert stats[name]["calls"] >= 3
        assert stats[name]["total_s"] > 0          # the fixed latency bug
        assert stats[name]["max_s"] > 0
        counts = p.summary(sorted_by="time")
        out = capsys.readouterr().out
        assert "total(ms)" in out and "avg(ms)" in out
        assert counts[name] >= 3

    def test_summary_sort_orders(self):
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        with profiler.Profiler(timer_only=True) as p:
            _ = x + x
            _ = x * x
            _ = x * x
        from paddle_tpu.profiler import SortedKeys
        by_calls = list(p.summary(sorted_by=SortedKeys.Calls))
        assert by_calls[0] == "multiply"
        with pytest.raises(ValueError):
            p.summary(sorted_by="bogus")

    def test_session_state_reset_on_restart(self):
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        p = profiler.Profiler(timer_only=True)
        p.start()
        _ = x + x
        with profiler.RecordEvent("first_session"):
            pass
        p.stop()
        assert p._op_stats and p._events
        p.start()   # re-entry: previous session must not leak through
        assert not p._op_stats and not p._events and p._step == 0
        _ = x * x
        p.stop()
        assert "add" not in p._op_stats
        assert all(name != "first_session" for name, _, _ in p._events)

    def test_step_timer_and_metrics(self):
        _enable()
        with profiler.Profiler(timer_only=True) as p:
            for _ in range(3):
                time.sleep(0.002)
                p.step(num_samples=16)
        assert len(p._step_times) == 3
        info = p.step_info()
        assert "steps/sec" in info and "steps: 3" in info
        assert REGISTRY.get("paddle_tpu_train_steps_total").total() == 3
        assert REGISTRY.get("paddle_tpu_steps_per_second").value() > 0
        assert REGISTRY.get("paddle_tpu_examples_per_second").value() > 0

    def test_hook_unregistered_after_stop(self):
        from paddle_tpu.core import dispatch
        before = len(dispatch._op_hooks)
        with profiler.Profiler(timer_only=True):
            pass
        assert len(dispatch._op_hooks) == before

    def test_step_info_examples_per_sec_uses_num_samples(self):
        with profiler.Profiler(timer_only=True) as p:
            for _ in range(2):
                time.sleep(0.002)
                p.step(num_samples=100)
        info = p.step_info()
        ips = float(info.split("steps/sec: ")[1].split()[0])
        eps = float(info.split("examples/sec: ")[1].split()[0])
        assert eps == pytest.approx(100 * ips, rel=0.05)

    def test_legacy_hook_double_register_unregister_symmetric(self):
        from paddle_tpu.core import dispatch
        before = len(dispatch._op_hooks)
        seen = []

        def legacy(op, ins, outs, attrs):   # 4-arg form
            seen.append(op)

        dispatch.register_op_hook(legacy)
        dispatch.register_op_hook(legacy)
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        _ = x + x
        assert seen.count("add") == 2       # both registrations fire
        dispatch.unregister_op_hook(legacy)
        dispatch.unregister_op_hook(legacy)
        assert len(dispatch._op_hooks) == before
        assert legacy not in dispatch._hook_adapters


class TestChromeExport:
    def test_merged_trace_valid_and_monotonic(self, tmp_path):
        net = nn.Linear(8, 8)
        x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        with profiler.Profiler(
                on_trace_ready=profiler.export_chrome_tracing(
                    str(tmp_path), worker_name="t0")) as p:
            with profiler.RecordEvent("fwd"):
                net(x)
            p.step()
        assert p.trace_path and os.path.exists(p.trace_path)
        with open(p.trace_path) as f:
            doc = json.load(f)            # valid JSON
        evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert evs, "no complete events exported"
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)                       # monotonic ts
        assert all(isinstance(e["ts"], int) for e in evs)
        assert all(e["dur"] >= 0 for e in evs)
        cats = {e.get("cat") for e in evs}
        assert "dispatch" in cats                     # per-op spans
        assert "user" in cats                         # RecordEvent range
        names = {e["name"] for e in evs}
        assert "fwd" in names
        # a user range must export exactly once (not via _events AND the
        # span buffer)
        assert sum(1 for e in evs if e["name"] == "fwd") == 1

    def test_span_overflow_marked_in_trace(self, tmp_path, monkeypatch):
        monkeypatch.setattr(trace, "MAX_EVENTS", 4)
        with pytest.warns(UserWarning, match="span buffer overflowed"):
            with profiler.Profiler(
                    on_trace_ready=profiler.export_chrome_tracing(
                        str(tmp_path))) as p:
                x = paddle.to_tensor(np.ones((2, 2), np.float32))
                for _ in range(10):
                    _ = x + x
                p.step()
        assert p._spans_dropped > 0
        with open(p.trace_path) as f:
            doc = json.load(f)
        marker = [e for e in doc["traceEvents"]
                  if e["name"] == "spans_dropped"]
        assert marker and marker[0]["args"]["count"] == p._spans_dropped

    def test_compile_and_collective_spans_in_one_timeline(self, tmp_path):
        import paddle_tpu.distributed as dist
        dist.set_mesh(dist.build_mesh({"dp": 8}))

        @paddle.jit.to_static
        def step(a):
            return a * 2.0

        with profiler.Profiler(
                on_trace_ready=profiler.export_chrome_tracing(
                    str(tmp_path))) as p:
            y = step(paddle.to_tensor(np.ones((64, 8), np.float32)))
            dist.all_reduce(y)
            p.step()
        with open(p.trace_path) as f:
            doc = json.load(f)
        cats = {e.get("cat") for e in doc["traceEvents"] if e["ph"] == "X"}
        assert "compile" in cats
        assert "collective" in cats


# ---------------------------------------------------------------------------
# CLI (satellite)
# ---------------------------------------------------------------------------
class TestCLI:
    def test_dump_live_prometheus(self, capsys):
        from paddle_tpu.observability.__main__ import main
        assert main([]) == 0
        out = capsys.readouterr().out
        assert "paddle_tpu_device_live_bytes" in out

    def test_dump_snapshot_file_json_and_prom(self, tmp_path, capsys):
        _enable()
        metrics.counter("test_cli_total", "from a run",
                        labelnames=("op",)).inc(op="x")
        snap_file = tmp_path / "snap.json"
        snap_file.write_text(json.dumps(REGISTRY.snapshot()))
        from paddle_tpu.observability.__main__ import main
        assert main(["--input", str(snap_file)]) == 0
        assert 'test_cli_total{op="x"} 1' in capsys.readouterr().out
        assert main(["--input", str(snap_file), "--format", "json"]) == 0
        assert "test_cli_total" in capsys.readouterr().out
        assert main(["--input", str(tmp_path / "missing.json")]) == 1


# ---------------------------------------------------------------------------
# acceptance: one short training loop, everything at once
# ---------------------------------------------------------------------------
class TestEndToEnd:
    def test_training_loop_full_telemetry(self, tmp_path):
        _enable()
        net = nn.Linear(16, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        with profiler.Profiler(
                on_trace_ready=profiler.export_chrome_tracing(
                    str(tmp_path))) as p:
            for _ in range(3):
                x = paddle.to_tensor(
                    np.random.randn(8, 16).astype(np.float32))
                loss = (net(x) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                p.step(num_samples=8)
        # per-op host latency in summary
        stats = p.op_stats()
        assert any(v["total_s"] > 0 for v in stats.values())
        # chrome trace merges dispatch spans
        with open(p.trace_path) as f:
            cats = {e.get("cat") for e in json.load(f)["traceEvents"]}
        assert "dispatch" in cats
        # metrics snapshot: dispatch latency + step throughput
        snap = REGISTRY.snapshot()
        assert "paddle_tpu_dispatch_op_latency_seconds" in snap
        assert "paddle_tpu_train_steps_total" in snap
        assert REGISTRY.get("paddle_tpu_steps_per_second").value() > 0
