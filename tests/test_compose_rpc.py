"""Composition coverage: ZeRO-2 + recompute + TP together; minimal RPC."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import mesh as mesh_mod


@pytest.fixture
def hybrid_mesh():
    old = mesh_mod._global_mesh
    mesh = mesh_mod.set_mesh(
        mesh_mod.build_mesh({"sharding": 4, "mp": 2}))
    yield mesh
    mesh_mod._global_mesh = old


class TPBlock(nn.Layer):
    def __init__(self, d=32):
        super().__init__()
        from paddle_tpu.distributed.fleet import (ColumnParallelLinear,
                                                  RowParallelLinear)
        self.fc1 = ColumnParallelLinear(d, 4 * d, has_bias=True,
                                        gather_output=False)
        self.fc2 = RowParallelLinear(4 * d, d, has_bias=True,
                                     input_is_parallel=True)
        self.ln = nn.LayerNorm(d)

    def forward(self, x):
        return x + self.fc2(paddle.nn.functional.gelu(self.fc1(
            self.ln(x))))


def test_zero2_recompute_tp_composition(hybrid_mesh):
    """ZeRO-2 sharded optimizer + activation recompute + TP layers in one
    training loop (the SURVEY §3.5 hybrid step minus pp)."""
    from paddle_tpu.distributed.fleet import recompute
    from paddle_tpu.distributed.fleet.meta_parallel import (
        GroupShardedOptimizerStage2, GroupShardedStage2)

    paddle.seed(0)
    blocks = nn.LayerList([TPBlock() for _ in range(2)])
    head = nn.Linear(32, 4)
    params = list(blocks.parameters()) + list(head.parameters())
    inner = paddle.optimizer.AdamW(learning_rate=3e-3, parameters=params)
    opt = GroupShardedOptimizerStage2(params, inner)

    x = paddle.to_tensor(np.random.randn(8, 32).astype(np.float32))
    y = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32) * 0.1)
    losses = []
    for _ in range(5):
        h = x
        for blk in blocks:
            h = recompute(blk, h)
        loss = paddle.ops.mean((head(h) - y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


class TestRpc:
    def test_sync_async_round_trip(self):
        import paddle_tpu.distributed.rpc as rpc
        info = rpc.init_rpc("worker0")
        assert info.name == "worker0"
        assert rpc.rpc_sync("worker0", lambda a, b: a + b,
                            args=(2, 3)) == 5
        fut = rpc.rpc_async("worker0", lambda: "done")
        assert fut.result() == "done"
        assert rpc.get_worker_info().rank == 0
        rpc.shutdown()

    def test_unknown_worker_raises(self):
        import paddle_tpu.distributed.rpc as rpc
        rpc.init_rpc("w0")
        with pytest.raises(RuntimeError, match="unknown RPC worker"):
            rpc.rpc_sync("nope", lambda: 1)
        rpc.shutdown()
