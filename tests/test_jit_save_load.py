"""jit.save / jit.load program-artifact round-trip tests.

Reference analog: test/dygraph_to_static/test_save_load.py — save a traced
program + params, load as TranslatedLayer, run WITHOUT the model class, and
match the original outputs.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.static import InputSpec


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def test_save_load_round_trip(tmp_path):
    paddle.seed(0)
    net = SmallNet()
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    ref = net(x).numpy()

    path = str(tmp_path / "model")
    paddle.jit.save(net, path, input_spec=[InputSpec([4, 8], "float32")])

    loaded = paddle.jit.load(path)
    assert isinstance(loaded, paddle.jit.TranslatedLayer)
    out = loaded(x)
    np.testing.assert_allclose(np.asarray(out._data), ref, atol=1e-6)


def test_load_runs_without_model_class(tmp_path):
    """The loaded program must execute from the artifact alone — state dict
    + serialized StableHLO, no SmallNet involved."""
    paddle.seed(1)
    net = SmallNet()
    path = str(tmp_path / "model2")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 8], "float32")])
    x = np.random.randn(2, 8).astype(np.float32)
    ref = net(paddle.to_tensor(x)).numpy()

    import pickle

    from jax import export as jax_export

    from paddle_tpu.framework.io import load as fio_load
    from paddle_tpu.jit.api import TranslatedLayer

    with open(path + ".pdmodel", "rb") as f:
        blob = pickle.load(f)
    fresh = TranslatedLayer(jax_export.deserialize(blob["stablehlo"]),
                            fio_load(path + ".pdparams"))
    out = fresh(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(out._data), ref, atol=1e-6)


def test_symbolic_batch_dim(tmp_path):
    paddle.seed(2)
    net = SmallNet()
    path = str(tmp_path / "model3")
    paddle.jit.save(net, path, input_spec=[InputSpec([-1, 8], "float32")])
    loaded = paddle.jit.load(path)
    for b in (1, 3, 16):
        x = np.random.randn(b, 8).astype(np.float32)
        ref = net(paddle.to_tensor(x)).numpy()
        out = loaded(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out._data), ref, atol=1e-6)


def test_to_static_layer_save(tmp_path):
    paddle.seed(3)
    net = paddle.jit.to_static(
        SmallNet(), input_spec=[InputSpec([4, 8], "float32")])
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    ref = net(x).numpy()
    path = str(tmp_path / "model4")
    paddle.jit.save(net, path)
    out = paddle.jit.load(path)(x)
    np.testing.assert_allclose(np.asarray(out._data), ref, atol=1e-6)


def test_set_state_dict_on_translated_layer(tmp_path):
    paddle.seed(4)
    net = SmallNet()
    path = str(tmp_path / "model5")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 8], "float32")])
    loaded = paddle.jit.load(path)
    new_state = {k: paddle.to_tensor(np.zeros(v.shape, np.float32))
                 for k, v in loaded.state_dict().items()}
    loaded.set_state_dict(new_state)
    x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
    out = loaded(x)
    np.testing.assert_allclose(np.asarray(out._data), 0.0, atol=1e-6)


def test_train_raises(tmp_path):
    paddle.seed(5)
    net = SmallNet()
    path = str(tmp_path / "model6")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 8], "float32")])
    loaded = paddle.jit.load(path)
    with pytest.raises(RuntimeError):
        loaded.train()


def test_params_only_fallback(tmp_path):
    """paddle.save'd raw state (no .pdmodel) still loads as a dict."""
    paddle.seed(6)
    net = SmallNet()
    from paddle_tpu.framework.io import save as fio_save
    path = str(tmp_path / "weights")
    fio_save(net.state_dict(), path + ".pdparams")
    out = paddle.jit.load(path)
    assert isinstance(out, dict) and "fc1.weight" in out


class TestGraphBreakFallback:
    """SOT-style graph breaks (reference sot/translate.py fallback)."""

    def test_data_dependent_branch_falls_back(self):
        import warnings
        import numpy as np
        import paddle_tpu as paddle

        @paddle.jit.to_static(full_graph=False)
        def f(x):
            if float(x.sum().numpy()) > 0:  # python branch on data
                return x * 2
            return x - 1

        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = f(x)
        assert any("graph break" in str(m.message) for m in w)
        np.testing.assert_allclose(out.numpy(), 2.0 * np.ones((2, 2)))
        assert f.graph_break_reason is not None
        # both branches work eagerly after the break
        out2 = f(paddle.to_tensor(-np.ones((2, 2), np.float32)))
        np.testing.assert_allclose(out2.numpy(), -2.0 * np.ones((2, 2)))

    def test_full_graph_true_raises(self):
        import numpy as np
        import pytest
        import paddle_tpu as paddle

        @paddle.jit.to_static(full_graph=True)
        def g(x):
            if float(x.sum().numpy()) > 0:
                return x * 2
            return x - 1

        import jax
        with pytest.raises((jax.errors.TracerBoolConversionError,
                            jax.errors.TracerArrayConversionError,
                            jax.errors.ConcretizationTypeError)):
            g(paddle.to_tensor(np.ones((2,), np.float32)))

    def test_static_function_still_captures(self):
        import numpy as np
        import paddle_tpu as paddle

        @paddle.jit.to_static(full_graph=False)
        def h(x):
            return x @ x + 1

        out = h(paddle.to_tensor(np.eye(3, dtype=np.float32)))
        np.testing.assert_allclose(out.numpy(), np.eye(3) + 1)
        assert h.graph_break_reason is None

    def test_break_is_per_signature(self):
        import numpy as np
        import warnings
        import paddle_tpu as paddle

        @paddle.jit.to_static(full_graph=False)
        def f(x, branchy):
            if branchy:  # static python flag -> separate signatures
                if float(x.sum().numpy()) > 0:  # breaks only this sig
                    return x * 2
                return x - 1
            return x + 10

        x = paddle.to_tensor(np.ones((2,), np.float32))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(x, True)  # breaks
        assert f.graph_break_reason is not None
        # the traceable signature still compiles and runs jitted
        out = f(x, False)
        np.testing.assert_allclose(out.numpy(), np.full((2,), 11.0))

    def test_boolean_index_break_falls_back(self):
        import warnings
        import numpy as np
        import paddle_tpu as paddle

        @paddle.jit.to_static(full_graph=False)
        def f(x):
            return x[x > 0]  # data-dependent shape

        x = paddle.to_tensor(np.array([1.0, -2.0, 3.0], np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = f(x)
        assert any("graph break" in str(m.message) for m in w)
        np.testing.assert_allclose(out.numpy(), [1.0, 3.0])
