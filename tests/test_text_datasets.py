"""paddle.text.datasets tests (reference python/paddle/text/datasets/)
— miniature archives in the exact reference formats."""
import os
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import Imdb, Imikolov, UCIHousing


class TestUCIHousing:
    def _write(self, tmp_path, rows=20):
        rng = np.random.RandomState(0)
        data = rng.rand(rows, 14).astype(np.float32) * 10
        p = tmp_path / "housing.data"
        with open(p, "w") as f:
            for r in data:
                f.write(" ".join(f"{v:.4f}" for v in r) + "\n")
        return str(p), data

    def test_split_and_normalization(self, tmp_path):
        p, raw = self._write(tmp_path)
        tr = UCIHousing(data_file=p, mode="train")
        te = UCIHousing(data_file=p, mode="test")
        assert len(tr) == 16 and len(te) == 4
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        # features normalized ((v-avg)/(max-min)) -> bounded by 1
        assert np.abs(x).max() <= 1.0
        # target column untouched
        np.testing.assert_allclose(float(y[0]), raw[0, -1], rtol=1e-4)

    def test_missing_file(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="No-egress"):
            UCIHousing(data_file=str(tmp_path / "nope"))


def _write_imdb(tmp_path):
    root = tmp_path / "aclImdb"
    texts = {
        ("train", "pos"): ["great movie really great", "loved it great fun"],
        ("train", "neg"): ["terrible film really terrible",
                           "hated it terrible bore"],
        ("test", "pos"): ["great fun"],
        ("test", "neg"): ["terrible bore"],
    }
    for (split, senti), docs in texts.items():
        d = root / split / senti
        os.makedirs(d)
        for i, t in enumerate(docs):
            (d / f"{i}.txt").write_text(t)
    tar = tmp_path / "aclImdb_v1.tar.gz"
    with tarfile.open(tar, "w:gz") as tf:
        tf.add(root, arcname="aclImdb")
    return str(tar)


class TestImdb:
    def test_word_dict_and_labels(self, tmp_path):
        tar = _write_imdb(tmp_path)
        ds = Imdb(data_file=tar, mode="train", cutoff=1)
        # words with freq > 1 across the whole corpus
        assert "great" in ds.word_idx and "terrible" in ds.word_idx
        assert "<unk>" in ds.word_idx
        assert len(ds) == 4
        labels = [int(ds[i][1]) for i in range(len(ds))]
        assert labels.count(0) == 2 and labels.count(1) == 2  # pos=0, neg=1
        ids, lbl = ds[0]
        assert ids.dtype == np.int64 and ids.ndim == 1
        assert lbl.shape == (1,)  # reference label shape

    def test_test_split(self, tmp_path):
        tar = _write_imdb(tmp_path)
        ds = Imdb(data_file=tar, mode="test", cutoff=1)
        assert len(ds) == 2


class TestImikolov:
    def _write(self, tmp_path):
        root = tmp_path / "simple-examples" / "data"
        os.makedirs(root)
        train = "the cat sat\nthe dog sat\nthe cat ran\n" * 20
        valid = "the cat sat\n"
        (root / "ptb.train.txt").write_text(train)
        (root / "ptb.valid.txt").write_text(valid)
        tar = tmp_path / "simple-examples.tgz"
        with tarfile.open(tar, "w:gz") as tf:
            tf.add(root.parent, arcname="simple-examples")
        return str(tar)

    def test_ngram_windows(self, tmp_path):
        tar = self._write(tmp_path)
        ds = Imikolov(data_file=tar, data_type="NGRAM", window_size=3,
                      mode="train", min_word_freq=5)
        assert "the" in ds.word_idx and "cat" in ds.word_idx
        (w,) = ds[0]
        assert w.shape == (3,)
        # each 5-token wrapped sentence yields 3 windows; 60 train + 1
        # valid sentences feed the DICT, windows come from train only
        assert len(ds) == 180

    def test_seq_mode_valid_split(self, tmp_path):
        tar = self._write(tmp_path)
        ds = Imikolov(data_file=tar, data_type="SEQ", mode="valid",
                      min_word_freq=5)
        assert len(ds) == 1
        src, trg = ds[0]  # reference pair contract
        assert src.shape == (4,) and trg.shape == (4,)
        # src starts with <s>, trg ends with <e>
        assert int(src[0]) == ds.word_idx["<s>"]
        assert int(trg[-1]) == ds.word_idx["<e>"]
        np.testing.assert_array_equal(src[1:], trg[:-1])

    def test_seq_window_filter(self, tmp_path):
        tar = self._write(tmp_path)
        ds = Imikolov(data_file=tar, data_type="SEQ", mode="train",
                      window_size=3, min_word_freq=5)
        assert len(ds) == 0  # all src sequences are length 4 > 3

    def test_boundary_tokens_in_dict(self, tmp_path):
        tar = self._write(tmp_path)
        ds = Imikolov(data_file=tar, data_type="NGRAM", window_size=2,
                      mode="train", min_word_freq=5)
        assert "<s>" in ds.word_idx and "<e>" in ds.word_idx


class TestMovielens:
    def _write(self, tmp_path):
        import zipfile
        z = tmp_path / "ml-1m.zip"
        movies = ("1::Toy Story (1995)::Animation|Comedy\n"
                  "2::Heat (1995)::Action|Crime\n")
        users = ("1::M::25::3::55117\n"
                 "2::F::18::7::02460\n")
        ratings = "".join(f"{u}::{m}::{r}::978300760\n"
                          for u, m, r in [(1, 1, 5), (1, 2, 3),
                                          (2, 1, 4), (2, 2, 1)] * 10)
        with zipfile.ZipFile(z, "w") as zf:
            zf.writestr("ml-1m/movies.dat", movies)
            zf.writestr("ml-1m/users.dat", users)
            zf.writestr("ml-1m/ratings.dat", ratings)
        return str(z)

    def test_parse_and_split(self, tmp_path):
        from paddle_tpu.text import Movielens
        z = self._write(tmp_path)
        tr = Movielens(data_file=z, mode="train", test_ratio=0.25,
                       rand_seed=0)
        te = Movielens(data_file=z, mode="test", test_ratio=0.25,
                       rand_seed=0)
        assert len(tr) + len(te) == 40
        assert len(te) > 0
        item = tr[0]
        # (uid, gender, age_idx, job, movie_id, categories, title, rating)
        assert len(item) == 8
        uid, gender, age, job, mid, cats, title, rating = item
        assert gender[0] in (0, 1)
        assert rating.shape == (1,) and -5.0 <= float(rating[0]) <= 5.0
        # rating rescale r*2-5: raw 5 -> 5.0, raw 1 -> -3.0
        all_ratings = {float(tr[i][7][0]) for i in range(len(tr))}
        assert all_ratings.issubset({5.0, 1.0, 3.0, -3.0})

    def test_vocab_dicts(self, tmp_path):
        from paddle_tpu.text import Movielens
        z = self._write(tmp_path)
        ds = Movielens(data_file=z, mode="train")
        assert set(ds.categories_dict) == {"Animation", "Comedy",
                                           "Action", "Crime"}
        assert "toy" in ds.movie_title_dict and "heat" in ds.movie_title_dict


class TestConll05st:
    def _write(self, tmp_path):
        import gzip
        root = tmp_path / "conll05st-release" / "test.wsj"
        os.makedirs(root / "words")
        os.makedirs(root / "props")
        wlines, plines = [], []
        # sentence 1: one predicate
        for w, pr, tg in zip(["The", "cat", "sat", "."],
                             [["-"], ["-"], ["sat"], ["-"]],
                             [["(A0*"], ["*)"], ["(V*)"], ["*"]]):
            wlines.append(w)
            plines.append("\t".join(pr + tg))
        wlines.append("")
        plines.append("")
        # sentence 2: TWO predicates (two tag columns) — exercises the
        # column transposition + verb_list alignment
        for w, pr, t1, t2 in zip(
                ["Dogs", "ran", "and", "barked"],
                [["-"], ["ran"], ["-"], ["barked"]],
                [["(A0*)"], ["(V*)"], ["*"], ["*"]],
                [["(A0*)"], ["*"], ["*"], ["(V*)"]]):
            wlines.append(w)
            plines.append("\t".join(pr + t1 + t2))
        wlines.append("")
        plines.append("")
        with gzip.open(root / "words" / "test.wsj.words.gz", "wt") as f:
            f.write("\n".join(wlines) + "\n")
        with gzip.open(root / "props" / "test.wsj.props.gz", "wt") as f:
            f.write("\n".join(plines) + "\n")
        tar = tmp_path / "conll05st-tests.tar.gz"
        with tarfile.open(tar, "w:gz") as tf:
            tf.add(tmp_path / "conll05st-release",
                   arcname="conll05st-release")
        (tmp_path / "wordDict.txt").write_text(
            "UNK\nThe\ncat\nsat\n.\n")
        (tmp_path / "verbDict.txt").write_text("sat\nran\nbarked\n")
        (tmp_path / "targetDict.txt").write_text(
            "B-A0\nI-A0\nB-V\nI-V\nO\n")
        return (str(tar), str(tmp_path / "wordDict.txt"),
                str(tmp_path / "verbDict.txt"),
                str(tmp_path / "targetDict.txt"))

    def test_parse_and_getitem(self, tmp_path):
        from paddle_tpu.text import Conll05st
        tar, wd, vd, td = self._write(tmp_path)
        ds = Conll05st(data_file=tar, word_dict_file=wd,
                       verb_dict_file=vd, target_dict_file=td)
        assert len(ds) == 3  # 1 predicate + 2 predicates
        (word, n2, n1, c0, p1, p2, pred, mark, label) = ds[0]
        assert word.shape == (4,)
        # BIO conversion: (A0* *) (V*) * -> B-A0 I-A0 B-V O
        names = {v: k for k, v in ds.label_dict.items()}
        assert [names[int(x)] for x in label] == \
            ["B-A0", "I-A0", "B-V", "O"]
        # mark flags the verb window
        assert mark.tolist().count(1) >= 3
        assert int(pred[0]) == ds.predicate_dict["sat"]
        # multi-predicate sentence: each item aligned to ITS verb column
        names = {v: k for k, v in ds.label_dict.items()}
        (_, _, _, _, _, _, pred2, _, lab2) = ds[1]
        assert int(pred2[0]) == ds.predicate_dict["ran"]
        assert [names[int(x)] for x in lab2] == ["B-A0", "B-V", "O", "O"]
        (_, _, _, _, _, _, pred3, _, lab3) = ds[2]
        assert int(pred3[0]) == ds.predicate_dict["barked"]
        assert [names[int(x)] for x in lab3] == ["B-A0", "O", "O", "B-V"]

    def test_mode_validation(self, tmp_path):
        from paddle_tpu.text import Conll05st
        with pytest.raises(ValueError, match="test"):
            Conll05st(data_file="x", mode="train")

    def test_missing_files_raise(self, tmp_path):
        from paddle_tpu.text import Conll05st
        with pytest.raises(FileNotFoundError, match="No-egress"):
            Conll05st(data_file=str(tmp_path / "x"))


class TestWMT16:
    def _write(self, tmp_path):
        root = tmp_path / "wmt16"
        os.makedirs(root)
        train = ("the cat\tdie katze\n"
                 "the dog\tder hund\n"
                 "a cat\teine katze\n") * 5
        (root / "train").write_text(train)
        (root / "val").write_text("the cat\tdie katze\n")
        (root / "test").write_text("a dog\tein hund\n")
        tar = tmp_path / "wmt16.tar.gz"
        with tarfile.open(tar, "w:gz") as tf:
            tf.add(root, arcname="wmt16")
        return str(tar)

    def test_dict_and_items(self, tmp_path):
        from paddle_tpu.text import WMT16
        tar = self._write(tmp_path)
        ds = WMT16(data_file=tar, mode="train", lang="en")
        # special marks head the dict
        assert ds.src_dict["<s>"] == 0 and ds.src_dict["<e>"] == 1
        assert ds.src_dict["<unk>"] == 2
        assert "the" in ds.src_dict and "katze" in ds.trg_dict
        assert len(ds) == 15
        src, trg, trg_next = ds[0]
        # <s> the cat <e> / <s> die katze / die katze <e>
        assert src[0] == 0 and src[-1] == 1
        assert trg[0] == 0 and trg_next[-1] == 1
        np.testing.assert_array_equal(trg[1:], trg_next[:-1])

    def test_val_split_and_dict_cache(self, tmp_path):
        from paddle_tpu.text import WMT16
        tar = self._write(tmp_path)
        va = WMT16(data_file=tar, mode="val", lang="en")
        assert len(va) == 1
        # dict files cached next to the archive
        import glob
        assert glob.glob(str(tmp_path / "wmt16.tar.gz.*dict"))

    def test_dict_size_cap_and_de_lang(self, tmp_path):
        from paddle_tpu.text import WMT16
        tar = self._write(tmp_path)
        ds = WMT16(data_file=tar, mode="train", lang="de",
                   src_dict_size=5, trg_dict_size=5)
        assert len(ds.src_dict) == 5  # 3 marks + 2 words
        # de source: src column is the German side
        src, _, _ = ds[0]
        assert len(src) == 4  # <s> die katze <e>

    def test_no_trailing_separator_still_parses(self, tmp_path):
        import gzip
        from paddle_tpu.text import Conll05st
        root = tmp_path / "conll05st-release" / "test.wsj"
        os.makedirs(root / "words")
        os.makedirs(root / "props")
        # no trailing blank line after the last sentence
        with gzip.open(root / "words" / "test.wsj.words.gz", "wt") as f:
            f.write("The\ncat\nsat\n.")
        with gzip.open(root / "props" / "test.wsj.props.gz", "wt") as f:
            f.write("-\t(A0*\n-\t*)\nsat\t(V*)\n-\t*")
        tar = tmp_path / "c.tar.gz"
        with tarfile.open(tar, "w:gz") as tf:
            tf.add(tmp_path / "conll05st-release",
                   arcname="conll05st-release")
        (tmp_path / "wd.txt").write_text("UNK\nThe\ncat\nsat\n.\n")
        (tmp_path / "vd.txt").write_text("sat\n")
        (tmp_path / "td.txt").write_text("B-A0\nI-A0\nB-V\nO\n")
        ds = Conll05st(data_file=str(tar),
                       word_dict_file=str(tmp_path / "wd.txt"),
                       verb_dict_file=str(tmp_path / "vd.txt"),
                       target_dict_file=str(tmp_path / "td.txt"))
        assert len(ds) == 1
