"""paddle.text.datasets tests (reference python/paddle/text/datasets/)
— miniature archives in the exact reference formats."""
import os
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import Imdb, Imikolov, UCIHousing


class TestUCIHousing:
    def _write(self, tmp_path, rows=20):
        rng = np.random.RandomState(0)
        data = rng.rand(rows, 14).astype(np.float32) * 10
        p = tmp_path / "housing.data"
        with open(p, "w") as f:
            for r in data:
                f.write(" ".join(f"{v:.4f}" for v in r) + "\n")
        return str(p), data

    def test_split_and_normalization(self, tmp_path):
        p, raw = self._write(tmp_path)
        tr = UCIHousing(data_file=p, mode="train")
        te = UCIHousing(data_file=p, mode="test")
        assert len(tr) == 16 and len(te) == 4
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        # features normalized ((v-avg)/(max-min)) -> bounded by 1
        assert np.abs(x).max() <= 1.0
        # target column untouched
        np.testing.assert_allclose(float(y[0]), raw[0, -1], rtol=1e-4)

    def test_missing_file(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="No-egress"):
            UCIHousing(data_file=str(tmp_path / "nope"))


def _write_imdb(tmp_path):
    root = tmp_path / "aclImdb"
    texts = {
        ("train", "pos"): ["great movie really great", "loved it great fun"],
        ("train", "neg"): ["terrible film really terrible",
                           "hated it terrible bore"],
        ("test", "pos"): ["great fun"],
        ("test", "neg"): ["terrible bore"],
    }
    for (split, senti), docs in texts.items():
        d = root / split / senti
        os.makedirs(d)
        for i, t in enumerate(docs):
            (d / f"{i}.txt").write_text(t)
    tar = tmp_path / "aclImdb_v1.tar.gz"
    with tarfile.open(tar, "w:gz") as tf:
        tf.add(root, arcname="aclImdb")
    return str(tar)


class TestImdb:
    def test_word_dict_and_labels(self, tmp_path):
        tar = _write_imdb(tmp_path)
        ds = Imdb(data_file=tar, mode="train", cutoff=1)
        # words with freq > 1 across the whole corpus
        assert "great" in ds.word_idx and "terrible" in ds.word_idx
        assert "<unk>" in ds.word_idx
        assert len(ds) == 4
        labels = [int(ds[i][1]) for i in range(len(ds))]
        assert labels.count(0) == 2 and labels.count(1) == 2  # pos=0, neg=1
        ids, lbl = ds[0]
        assert ids.dtype == np.int64 and ids.ndim == 1
        assert lbl.shape == (1,)  # reference label shape

    def test_test_split(self, tmp_path):
        tar = _write_imdb(tmp_path)
        ds = Imdb(data_file=tar, mode="test", cutoff=1)
        assert len(ds) == 2


class TestImikolov:
    def _write(self, tmp_path):
        root = tmp_path / "simple-examples" / "data"
        os.makedirs(root)
        train = "the cat sat\nthe dog sat\nthe cat ran\n" * 20
        valid = "the cat sat\n"
        (root / "ptb.train.txt").write_text(train)
        (root / "ptb.valid.txt").write_text(valid)
        tar = tmp_path / "simple-examples.tgz"
        with tarfile.open(tar, "w:gz") as tf:
            tf.add(root.parent, arcname="simple-examples")
        return str(tar)

    def test_ngram_windows(self, tmp_path):
        tar = self._write(tmp_path)
        ds = Imikolov(data_file=tar, data_type="NGRAM", window_size=3,
                      mode="train", min_word_freq=5)
        assert "the" in ds.word_idx and "cat" in ds.word_idx
        (w,) = ds[0]
        assert w.shape == (3,)
        # each 5-token wrapped sentence yields 3 windows; 60 train + 1
        # valid sentences feed the DICT, windows come from train only
        assert len(ds) == 180

    def test_seq_mode_valid_split(self, tmp_path):
        tar = self._write(tmp_path)
        ds = Imikolov(data_file=tar, data_type="SEQ", mode="valid",
                      min_word_freq=5)
        assert len(ds) == 1
        src, trg = ds[0]  # reference pair contract
        assert src.shape == (4,) and trg.shape == (4,)
        # src starts with <s>, trg ends with <e>
        assert int(src[0]) == ds.word_idx["<s>"]
        assert int(trg[-1]) == ds.word_idx["<e>"]
        np.testing.assert_array_equal(src[1:], trg[:-1])

    def test_seq_window_filter(self, tmp_path):
        tar = self._write(tmp_path)
        ds = Imikolov(data_file=tar, data_type="SEQ", mode="train",
                      window_size=3, min_word_freq=5)
        assert len(ds) == 0  # all src sequences are length 4 > 3

    def test_boundary_tokens_in_dict(self, tmp_path):
        tar = self._write(tmp_path)
        ds = Imikolov(data_file=tar, data_type="NGRAM", window_size=2,
                      mode="train", min_word_freq=5)
        assert "<s>" in ds.word_idx and "<e>" in ds.word_idx


class TestMovielens:
    def _write(self, tmp_path):
        import zipfile
        z = tmp_path / "ml-1m.zip"
        movies = ("1::Toy Story (1995)::Animation|Comedy\n"
                  "2::Heat (1995)::Action|Crime\n")
        users = ("1::M::25::3::55117\n"
                 "2::F::18::7::02460\n")
        ratings = "".join(f"{u}::{m}::{r}::978300760\n"
                          for u, m, r in [(1, 1, 5), (1, 2, 3),
                                          (2, 1, 4), (2, 2, 1)] * 10)
        with zipfile.ZipFile(z, "w") as zf:
            zf.writestr("ml-1m/movies.dat", movies)
            zf.writestr("ml-1m/users.dat", users)
            zf.writestr("ml-1m/ratings.dat", ratings)
        return str(z)

    def test_parse_and_split(self, tmp_path):
        from paddle_tpu.text import Movielens
        z = self._write(tmp_path)
        tr = Movielens(data_file=z, mode="train", test_ratio=0.25,
                       rand_seed=0)
        te = Movielens(data_file=z, mode="test", test_ratio=0.25,
                       rand_seed=0)
        assert len(tr) + len(te) == 40
        assert len(te) > 0
        item = tr[0]
        # (uid, gender, age_idx, job, movie_id, categories, title, rating)
        assert len(item) == 8
        uid, gender, age, job, mid, cats, title, rating = item
        assert gender[0] in (0, 1)
        assert rating.shape == (1,) and -5.0 <= float(rating[0]) <= 5.0
        # rating rescale r*2-5: raw 5 -> 5.0, raw 1 -> -3.0
        all_ratings = {float(tr[i][7][0]) for i in range(len(tr))}
        assert all_ratings.issubset({5.0, 1.0, 3.0, -3.0})

    def test_vocab_dicts(self, tmp_path):
        from paddle_tpu.text import Movielens
        z = self._write(tmp_path)
        ds = Movielens(data_file=z, mode="train")
        assert set(ds.categories_dict) == {"Animation", "Comedy",
                                           "Action", "Crime"}
        assert "toy" in ds.movie_title_dict and "heat" in ds.movie_title_dict
