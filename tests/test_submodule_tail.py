"""Submodule tails: linalg, hermitian FFTs, ASGD/Rprop/LBFGS, sparse
surface, metric.accuracy, amp capability checks, LKJCholesky.

References: python/paddle/{linalg.py,fft.py}, optimizer/{asgd,rprop,
lbfgs}.py, sparse/__init__.py, metric/metrics.py:763,
distribution/lkj_cholesky.py. scipy/numpy/torch provide independent
numerics.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

RNG = np.random.RandomState(3)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestLinalgTail:
    def setup_method(self):
        x = RNG.randn(4, 4).astype(np.float32)
        self.spd = x @ x.T + 4 * np.eye(4, dtype=np.float32)

    def test_inv_and_cholesky_inverse(self):
        ref = np.linalg.inv(self.spd)
        np.testing.assert_allclose(
            np.asarray(paddle.linalg.inv(_t(self.spd)).numpy()), ref,
            rtol=1e-3, atol=1e-4)
        chol = np.linalg.cholesky(self.spd)
        np.testing.assert_allclose(
            np.asarray(paddle.linalg.cholesky_inverse(_t(chol)).numpy()),
            ref, rtol=1e-3, atol=1e-4)
        # upper variant
        np.testing.assert_allclose(
            np.asarray(paddle.linalg.cholesky_inverse(
                _t(chol.T.copy()), upper=True).numpy()),
            ref, rtol=1e-3, atol=1e-4)

    def test_matrix_exp(self):
        import scipy.linalg as sla
        a = RNG.randn(3, 3).astype(np.float32) * 0.3
        np.testing.assert_allclose(
            np.asarray(paddle.linalg.matrix_exp(_t(a)).numpy()),
            sla.expm(a.astype(np.float64)), rtol=1e-4, atol=1e-5)

    def test_norms_and_cond(self):
        v = RNG.randn(3, 5).astype(np.float32)
        np.testing.assert_allclose(
            float(paddle.linalg.vector_norm(_t(v), p=3).numpy()),
            np.sum(np.abs(v) ** 3) ** (1 / 3), rtol=1e-5)
        np.testing.assert_allclose(
            float(paddle.linalg.vector_norm(
                _t(v), p=float("inf")).numpy()),
            np.abs(v).max(), rtol=1e-6)
        for p in ("fro", "nuc", 1, np.inf):
            np.testing.assert_allclose(
                float(paddle.linalg.matrix_norm(_t(v), p=p).numpy()),
                np.linalg.norm(v, p), rtol=1e-4)
        for p in (None, 1, "fro"):
            np.testing.assert_allclose(
                float(paddle.linalg.cond(_t(self.spd), p=p).numpy()),
                np.linalg.cond(self.spd, p=p or 2), rtol=1e-3)

    def test_svd_lowrank_and_ormqr(self):
        # pinned local stream — the module RNG's state depends on which
        # tests ran before, and this test's accuracy claim should not
        rng = np.random.RandomState(1234)
        paddle.seed(1234)
        A = rng.randn(8, 5).astype(np.float32)
        s_ref = np.linalg.svd(A, compute_uv=False)
        U, S, V = paddle.linalg.svd_lowrank(_t(A), q=5, niter=4)
        np.testing.assert_allclose(np.sort(np.asarray(S.numpy()))[::-1],
                                   s_ref, rtol=2e-3, atol=1e-5)
        # ormqr: Q (from householder reflectors) applied to a matrix —
        # columns keep their norms under the orthonormal-column Q
        import scipy.linalg as sla
        (h, tau), _ = sla.qr(A.astype(np.float64), mode="raw")
        C = rng.randn(5, 3).astype(np.float32)
        ours = paddle.linalg.ormqr(
            _t(np.tril(h, -1)[:, :5].astype(np.float32)),
            _t(tau.astype(np.float32)), _t(C))
        assert list(ours.shape) == [8, 3]
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(ours.numpy()), axis=0),
            np.linalg.norm(C, axis=0), rtol=1e-3)


class TestHermitianFFT:
    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_hfft2_ihfft2_hfftn(self, norm):
        import scipy.fft as sfft
        a = (RNG.randn(4, 5) + 1j * RNG.randn(4, 5)).astype(np.complex64)
        np.testing.assert_allclose(
            np.asarray(paddle.fft.hfft2(_t(a), norm=norm).numpy()),
            sfft.hfft2(a.astype(np.complex128), norm=norm),
            rtol=2e-4, atol=2e-4)
        r = RNG.randn(4, 6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.fft.ihfft2(_t(r), norm=norm).numpy()),
            sfft.ihfft2(r.astype(np.float64), norm=norm),
            rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(paddle.fft.hfftn(_t(a), norm=norm).numpy()),
            sfft.hfftn(a.astype(np.complex128), norm=norm),
            rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(paddle.fft.ihfftn(_t(r), norm=norm).numpy()),
            sfft.ihfftn(r.astype(np.float64), norm=norm),
            rtol=2e-4, atol=2e-4)


class TestOptimizerExtras:
    def test_asgd_converges(self):
        w = _t(np.array([3.0, -2.0], np.float32))
        w.stop_gradient = False
        opt = paddle.optimizer.ASGD(learning_rate=0.2, batch_num=3,
                                    parameters=[w])
        for _ in range(40):
            loss = (w * w).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss.numpy()) < 1e-2

    def test_rprop_adapts_step_sizes(self):
        w = _t(np.array([3.0, -2.0], np.float32))
        w.stop_gradient = False
        opt = paddle.optimizer.Rprop(learning_rate=0.1, parameters=[w])
        for _ in range(40):
            loss = (w * w).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss.numpy()) < 1e-3

    def test_lbfgs_rosenbrock(self):
        xy = _t(np.array([-1.2, 1.0], np.float32))
        xy.stop_gradient = False
        opt = paddle.optimizer.LBFGS(
            learning_rate=1.0, max_iter=80, history_size=10,
            line_search_fn="strong_wolfe", parameters=[xy])

        def closure():
            a, b = xy[0], xy[1]
            return (1 - a) ** 2 + 100.0 * (b - a * a) ** 2

        opt.step(closure)
        assert float(closure().numpy()) < 1e-4
        np.testing.assert_allclose(xy.numpy(), [1.0, 1.0], atol=1e-2)


class TestSparseMetricAmp:
    def test_sparse_slice_mask_pca(self):
        sp = paddle.sparse
        st = sp.sparse_coo_tensor(
            np.array([[0, 1, 2], [0, 1, 2]]),
            np.array([1.0, 2.0, 3.0], np.float32), (3, 3))
        sl = sp.slice(st, [0], [1], [3])
        assert list(sl.shape) == [2, 3]
        np.testing.assert_allclose(
            np.asarray(sl.to_dense().numpy()), [[0, 2, 0], [0, 0, 3]])
        dense = _t(np.arange(9, dtype=np.float32).reshape(3, 3))
        masked = sp.mask_as(dense, st)
        np.testing.assert_allclose(np.asarray(masked.to_dense().numpy()),
                                   np.diag([0.0, 4.0, 8.0]))
        U, S, V = sp.pca_lowrank(st, q=2)
        assert S.shape[-1] == 2

    def test_metric_accuracy(self):
        pred = _t(np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1],
                            [0.2, 0.3, 0.5]], np.float32))
        lab = _t(np.array([[1], [0], [1]]))
        # row 2 predicts argmax=2 (wrong at k=1) but label 1 is second
        np.testing.assert_allclose(
            float(paddle.metric.accuracy(pred, lab, k=1).numpy()),
            2.0 / 3.0, rtol=1e-6)
        np.testing.assert_allclose(
            float(paddle.metric.accuracy(pred, lab, k=2).numpy()),
            1.0, rtol=1e-6)

    def test_amp_capability(self):
        assert paddle.amp.is_bfloat16_supported()
        assert paddle.amp.is_float16_supported()


class TestLKJCholesky:
    def test_samples_valid_and_unbiased(self):
        for method in ("onion", "cvine"):
            d = paddle.distribution.LKJCholesky(3, 1.5,
                                                sample_method=method)
            L = np.asarray(d.sample([1500]).numpy()).reshape(1500, 3, 3)
            corr = L @ np.swapaxes(L, -1, -2)
            np.testing.assert_allclose(
                np.diagonal(corr, axis1=-2, axis2=-1), 1.0, atol=1e-5)
            assert np.abs(np.triu(L, 1)).max() < 1e-6
            # unbiased: mean off-diagonal correlation ~ 0
            assert abs(corr[:, 1, 0].mean()) < 0.06, method
            assert abs(corr[:, 2, 1].mean()) < 0.06, method

    def test_log_prob_matches_torch(self):
        torch = pytest.importorskip("torch")
        td = torch.distributions.LKJCholesky(3, 1.5)
        pd = paddle.distribution.LKJCholesky(3, 1.5)
        Ls = td.sample((8,))
        ours = np.asarray(
            pd.log_prob(_t(Ls.numpy())).numpy()).squeeze()
        np.testing.assert_allclose(ours, td.log_prob(Ls).numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_validation(self):
        with pytest.raises(ValueError):
            paddle.distribution.LKJCholesky(1)
        with pytest.raises(ValueError):
            paddle.distribution.LKJCholesky(3, -1.0)
        with pytest.raises(ValueError):
            paddle.distribution.LKJCholesky(3, 1.0, sample_method="x")
