"""Mesh-sharded giant-embedding subsystem
(paddle_tpu/distributed/embedding/): dedup lookups, row-sharded
optimizer state, the host-PS parity bridge, the DLRM workload on a
virtual (data, fsdp) mesh with the liveness capacity proof, and the
dense serving path behind the Router.

The PS bridge is the tier-1 contract ISSUE 20 pins: the host-resident
``DistributedEmbedding`` (overflow tier) and the on-chip
``ShardedEmbedding`` (default tier) must produce identical forward
values and row gradients on the same table.
"""
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.embedding import (
    RowShardedAdagrad, RowShardedAdam, ShardedEmbedding, dedup_stats,
    exchange_bytes, naive_gather_bytes, sharded_embedding_bag,
    sharded_embedding_lookup)


@pytest.fixture()
def mesh24():
    """(data=2, fsdp=4) over the virtual 8-device CPU platform."""
    prev = mesh_mod._global_mesh
    mesh_mod._global_mesh = None
    m = mesh_mod.build_mesh({"data": 2, "fsdp": 4})
    mesh_mod.set_mesh(m)
    yield m
    mesh_mod._global_mesh = prev


# ------------------------------------------------------ dedup lookups
class TestDedupLookup:
    def _table(self, vocab=64, dim=8, seed=0):
        paddle.seed(seed)
        return ShardedEmbedding(vocab, dim)

    def test_lookup_matches_plain_embedding(self):
        emb = self._table()
        ids = paddle.to_tensor(
            np.array([[3, 3, 7], [1, 3, 1]], np.int64))
        got = emb(ids)
        ref = F.embedding(ids, emb.weight)
        np.testing.assert_allclose(got.numpy(), ref.numpy())

    def test_dedup_grad_matches_no_dedup(self):
        """The unique→gather→inverse-gather composition must be grad-
        transparent: duplicate ids still sum their row grads."""
        ids = paddle.to_tensor(np.array([5, 5, 5, 2], np.int64))
        grads = {}
        for dedup in (True, False):
            emb = self._table(seed=7)
            out = sharded_embedding_lookup(ids, emb.weight, dedup=dedup)
            (out * out).sum().backward()
            grads[dedup] = np.asarray(emb.weight.grad.numpy())
        np.testing.assert_allclose(grads[True], grads[False],
                                   rtol=1e-6, atol=1e-7)
        assert np.abs(grads[True][5]).sum() > 0  # 3x-summed row

    def test_bag_sum_and_mean(self):
        emb = self._table()
        ids_np = np.array([[1, 2, 2], [4, 0, 1]], np.int64)
        ids = paddle.to_tensor(ids_np)
        W = np.asarray(emb.weight.numpy())
        got_sum = emb.bag(ids, mode="sum").numpy()
        np.testing.assert_allclose(got_sum, W[ids_np].sum(axis=1),
                                   rtol=1e-6)
        got_mean = emb.bag(ids, mode="mean").numpy()
        np.testing.assert_allclose(got_mean, W[ids_np].mean(axis=1),
                                   rtol=1e-6)

    def test_padding_idx_rows_are_zero(self):
        paddle.seed(0)
        emb = ShardedEmbedding(16, 4, padding_idx=0)
        ids = paddle.to_tensor(np.array([0, 3, 0], np.int64))
        out = emb(ids).numpy()
        np.testing.assert_allclose(out[0], 0.0)
        np.testing.assert_allclose(out[2], 0.0)
        assert np.abs(out[1]).sum() > 0

    def test_dedup_capacity_overflow_raises_eagerly(self):
        emb = self._table()
        ids = paddle.to_tensor(np.arange(8, dtype=np.int64))
        with pytest.raises(ValueError, match="capacity"):
            sharded_embedding_lookup(ids, emb.weight, dedup_capacity=4)

    def test_lookup_under_jit_fixed_capacity(self):
        emb = self._table(seed=3)
        ids_np = np.array([9, 9, 1, 4], np.int64)

        def f(ids_a):
            return sharded_embedding_lookup(
                paddle.Tensor(ids_a), emb.weight,
                dedup_capacity=4)._data

        got = jax.jit(f)(jnp.asarray(ids_np))
        ref = np.asarray(emb.weight.numpy())[ids_np]
        np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-6)

    def test_dedup_metrics_and_wire_model(self):
        stats = dedup_stats(np.array([1, 1, 1, 2], np.int64))
        assert stats["n_ids"] == 4 and stats["n_unique"] == 2
        assert stats["unique_ratio"] == 0.5
        # ring wire model: dedup moves fewer bytes than per-id gather
        assert exchange_bytes(2, 8, 4) < naive_gather_bytes(4, 8, 4)
        assert exchange_bytes(2, 8, 1) == 0    # single shard: no wire

    def test_unique_ratio_gauge_rides_lookups(self):
        from paddle_tpu.observability import metrics as M
        prev = paddle.get_flags(["FLAGS_enable_metrics"])[
            "FLAGS_enable_metrics"]
        paddle.set_flags({"FLAGS_enable_metrics": True})
        try:
            emb = self._table()
            ids = paddle.to_tensor(
                np.array([3, 3, 3, 3, 1, 1, 2, 2], np.int64))
            emb(ids)
            g = M.REGISTRY.get("paddle_tpu_embedding_unique_ratio")
            assert g is not None
            assert abs(g.value() - 3 / 8) < 1e-6
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": prev})


# ------------------------------------------------- sharded placement
class TestShardedPlacement:
    def test_shard_over_fsdp_axes(self, mesh24):
        paddle.seed(0)
        emb = ShardedEmbedding(64, 8, mesh=mesh24)
        assert emb.vocab_shards == 4           # fsdp=4; tp absent
        spec = emb.weight._spmd_spec
        assert spec is not None and spec[1] is None
        axes = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
        assert "fsdp" in axes

    def test_sharded_lookup_matches_replicated(self, mesh24):
        paddle.seed(5)
        repl = ShardedEmbedding(64, 8)
        paddle.seed(5)
        shard = ShardedEmbedding(64, 8, mesh=mesh24)
        ids = paddle.to_tensor(
            np.array([[11, 11, 60], [1, 0, 11]], np.int64))
        np.testing.assert_allclose(shard(ids).numpy(),
                                   repl(ids).numpy(), rtol=1e-6)
        # grads agree too (the Partial pending reduce resolves here)
        shard(ids).sum().backward()
        repl(ids).sum().backward()
        np.testing.assert_allclose(
            np.asarray(shard.weight.grad.numpy()),
            np.asarray(repl.weight.grad.numpy()), rtol=1e-6, atol=1e-7)


# ------------------------------------------------- host-PS parity
@pytest.fixture()
def cluster():
    """Two in-process PS shards + a client (test_ps.py's fixture)."""
    from paddle_tpu.distributed.ps import PsClient, PsServer
    servers = [PsServer(i, 2, token="t0").start() for i in range(2)]
    client = PsClient([s.endpoint for s in servers], token="t0")
    yield servers, client
    client.close()
    for s in servers:
        s.stop()


class TestPsParityBridge:
    def test_ps_and_sharded_embedding_parity(self, cluster):
        """ISSUE-20 tier-1 contract: same table → identical forward
        values AND identical row gradients from both tiers. The PS
        table uses the 'sum' accessor so the pushed row grads read
        back as (after - before)."""
        from paddle_tpu.distributed.ps import DistributedEmbedding
        _, client = cluster
        vocab, dim = 32, 8
        ps_emb = DistributedEmbedding(
            11, dim, client=client, accessor="sum",
            initializer="uniform", init_range=0.1)
        all_ids = list(range(vocab))
        W0 = client.pull_sparse(11, all_ids)   # materialize init rows
        paddle.seed(0)
        sh_emb = ShardedEmbedding(vocab, dim)
        sh_emb.weight._swap_payload(jnp.asarray(W0))

        ids = paddle.to_tensor(
            np.array([[1, 2, 2], [5, 1, 7]], np.int64))
        out_ps = ps_emb(ids)
        out_sh = sh_emb(ids)
        np.testing.assert_allclose(out_ps.numpy(), out_sh.numpy(),
                                   rtol=1e-6, atol=1e-7)

        (out_ps * out_ps).sum().backward()
        (out_sh * out_sh).sum().backward()
        pushed = client.pull_sparse(11, all_ids) - W0  # sum accessor
        np.testing.assert_allclose(
            pushed, np.asarray(sh_emb.weight.grad.numpy()),
            rtol=1e-5, atol=1e-6)


# ------------------------------------------- row-sharded optimizers
class TestRowShardedOptimizers:
    def _grad_rows(self, dim=6):
        ids = np.array([4, 9, 4, 0], np.int64)      # duplicate id 4
        rng = np.random.RandomState(1)
        return ids, rng.randn(len(ids), dim).astype(np.float32)

    def test_adagrad_sparse_matches_dense(self):
        paddle.seed(2)
        dim = 6
        a = ShardedEmbedding(16, dim)
        paddle.seed(2)
        b = ShardedEmbedding(16, dim)
        ids, g_rows = self._grad_rows(dim)
        dense_g = np.zeros((16, dim), np.float32)
        np.add.at(dense_g, ids, g_rows)

        opt_a = RowShardedAdagrad(a.weight, lr=0.1)
        opt_a.step(jnp.asarray(dense_g))
        opt_b = RowShardedAdagrad(b.weight, lr=0.1)
        opt_b.step_rows(jnp.asarray(ids), jnp.asarray(g_rows))
        np.testing.assert_allclose(np.asarray(a.weight.numpy()),
                                   np.asarray(b.weight.numpy()),
                                   rtol=1e-5, atol=1e-6)

    def test_adam_sparse_touches_only_used_rows(self):
        paddle.seed(3)
        emb = ShardedEmbedding(16, 6)
        before = np.asarray(emb.weight.numpy()).copy()
        ids, g_rows = self._grad_rows(6)
        opt = RowShardedAdam(emb.weight, lr=0.01)
        opt.step_rows(jnp.asarray(ids), jnp.asarray(g_rows))
        after = np.asarray(emb.weight.numpy())
        touched = sorted(set(ids.tolist()))
        untouched = [i for i in range(16) if i not in touched]
        np.testing.assert_allclose(after[untouched], before[untouched])
        for i in touched:
            assert np.abs(after[i] - before[i]).sum() > 0

    def test_slots_inherit_table_sharding(self, mesh24):
        paddle.seed(4)
        emb = ShardedEmbedding(64, 8, mesh=mesh24)
        opt = RowShardedAdam(emb.weight)
        table_sh = emb.weight._data.sharding
        for slot in opt.slots():
            assert slot.sharding == table_sh
        # slot bytes scale with the table (global accounting)
        assert opt.slot_nbytes() == 2 * 64 * 8 * 4


# --------------------------------------------------- DLRM on the mesh
class TestDLRMOnMesh:
    def _data(self, cfg, batch=8, seed=0):
        rng = np.random.RandomState(seed)
        dense = rng.randn(batch, cfg.n_dense).astype(np.float32)
        ids = (rng.zipf(1.5, (batch, cfg.n_sparse, cfg.bag_size)) - 1) \
            % cfg.num_embeddings
        labels = rng.randint(0, 2, (batch,)).astype(np.float32)
        return dense, ids.astype(np.int64), labels

    def test_sharded_training_loss_parity(self, mesh24):
        """Replicated vs (data, fsdp)-sharded DLRM: same weights, same
        batches, 3 plain-SGD steps — losses agree to rtol 1e-3 (the
        ISSUE-20 acceptance bar)."""
        from paddle_tpu.models import DLRM, dlrm_tiny
        cfg = dlrm_tiny(num_embeddings=256)
        paddle.seed(11)
        repl = DLRM(cfg)
        state = {k: np.asarray(v.numpy())
                 for k, v in repl.state_dict().items()}
        paddle.seed(11)
        shard = DLRM(cfg, mesh=mesh24)
        shard.set_state_dict(state)
        shard.shard_(mesh24)          # re-pin after the payload swap

        dense_np, ids_np, labels_np = self._data(cfg)
        for step in range(3):
            losses = []
            for model in (repl, shard):
                d = paddle.to_tensor(dense_np)
                i = paddle.to_tensor(ids_np)
                y = paddle.to_tensor(labels_np)
                loss = model.loss(d, i, y)
                loss.backward()
                for p in model.parameters():
                    if p.grad is not None:
                        p._swap_payload(p._data - 0.1 * p.grad._data)
                        p.clear_grad()
                losses.append(float(loss.numpy()))
            assert losses[0] == pytest.approx(losses[1], rel=1e-3), (
                step, losses)

    def test_pod_capacity_proof_and_zero_fallbacks(self, mesh24):
        """The liveness analyzer proves the point of sharding: on the
        8-chip pod there is a per-chip budget the replicated DLRM
        exceeds and the row-sharded one fits under — with the table
        placement surviving propagation (zero replicate-fallbacks on
        the embedding path)."""
        from paddle_tpu import static
        from paddle_tpu.distributed.spmd.propagate import \
            propagate_program
        from paddle_tpu.models import DLRM, DLRMConfig
        from paddle_tpu.static import liveness
        from jax.sharding import PartitionSpec as P

        cfg = DLRMConfig(num_embeddings=16384, embedding_dim=32,
                         n_dense=4, n_sparse=4, bag_size=2,
                         bottom_mlp=(16,), top_mlp=(16,))
        paddle.seed(0)
        model = DLRM(cfg, mesh=mesh24)
        batch = 8
        prog = static.Program()
        with static.program_guard(prog):
            d = static.data("dense", [batch, cfg.n_dense], "float32")
            i = static.data("ids",
                            [batch, cfg.n_sparse, cfg.bag_size],
                            "int64")
            y = static.data("labels", [batch], "float32")
            out = model.loss(d, i, y)
        fetch = [id(out)]
        in_specs = {"dense": P("data"), "ids": P("data"),
                    "labels": P("data")}
        plan = propagate_program(prog, mesh24, in_specs)
        # the embedding path must not fall back to replication
        for op in ("embedding", "embedding_bag", "scatter_add"):
            assert op not in plan.fallback_ops, plan.fallback_ops
        # the table's fsdp placement survived into the plan env
        table = model.embedding.weight
        vid = next(v for v, t in prog._captured.items()
                   if t is table)
        spec0 = plan.env[vid][0]
        axes = spec0 if isinstance(spec0, tuple) else (spec0,)
        assert "fsdp" in axes

        sh = liveness.peak_report(prog, fetch_ids=fetch, plan=plan,
                                  mesh=mesh24)
        repl = liveness.peak_report(prog, fetch_ids=fetch)
        table_bytes = cfg.num_embeddings * cfg.embedding_dim * 4
        # replicated peak carries the full table; sharded sheds >= half
        assert repl["peak_bytes"] >= table_bytes
        assert sh["peak_bytes"] <= repl["peak_bytes"] - table_bytes / 2
        # a budget between the peaks: the table provably exceeds one
        # chip's share replicated, and fits row-sharded
        budget = (sh["peak_bytes"] * repl["peak_bytes"]) ** 0.5
        assert repl["peak_bytes"] > budget > sh["peak_bytes"]

    def test_pod_proof_is_device_independent(self):
        """The same proof runs against a duck-typed pod mesh (axis
        sizes only) — what the bench rung does on a 1-device host."""
        from paddle_tpu import static
        from paddle_tpu.distributed.spmd.propagate import \
            propagate_program
        from paddle_tpu.models import DLRM, dlrm_tiny
        from paddle_tpu.static import liveness
        from jax.sharding import PartitionSpec as P

        cfg = dlrm_tiny(num_embeddings=8192, embedding_dim=32)
        paddle.seed(0)
        model = DLRM(cfg)                  # no real mesh at all
        pod = types.SimpleNamespace(shape={"data": 2, "fsdp": 4})
        prog = static.Program()
        with static.program_guard(prog):
            d = static.data("dense", [4, cfg.n_dense], "float32")
            i = static.data("ids", [4, cfg.n_sparse, cfg.bag_size],
                            "int64")
            y = static.data("labels", [4], "float32")
            out = model.loss(d, i, y)
        table = model.embedding.weight
        plan = propagate_program(
            prog, pod, {"dense": P("data"), "ids": P("data"),
                        "labels": P("data")},
            param_specs=lambda t: ("fsdp", None) if t is table
            else None)
        sh = liveness.peak_report(prog, fetch_ids=[id(out)], plan=plan,
                                  mesh=pod)
        repl = liveness.peak_report(prog, fetch_ids=[id(out)])
        assert sh["peak_bytes"] < repl["peak_bytes"]


# ------------------------------------------------- dense serving path
class TestDenseServing:
    def _engine(self, max_batch=4):
        from paddle_tpu.inference.serving import PagedEngine
        from paddle_tpu.models import DLRM, dlrm_tiny
        paddle.seed(0)
        model = DLRM(dlrm_tiny())
        return model, PagedEngine(model, max_batch=max_batch)

    def test_score_token_matches_serve_dense(self):
        model, eng = self._engine()
        ids = [3, 1, 4, 1, 5, 9, 2, 6][: model.serve_dense_width]
        rid = eng.add_request(ids, max_new_tokens=1)
        out = eng.run_to_completion()
        flat = paddle.to_tensor(
            np.asarray([ids], np.int64))
        ref = float(np.asarray(model.serve_dense(flat)._data)[0])
        assert out[rid] == [int(round(ref * 10000))]
        assert eng.kv_bytes_per_token == 0

    def test_warmup_batching_and_outcomes(self):
        from paddle_tpu.inference.serving import RequestStatus
        model, eng = self._engine(max_batch=4)
        eng.warmup()
        assert eng.lifecycle.ready()
        rids = [eng.add_request([1 + i] * model.serve_dense_width)
                for i in range(6)]          # > max_batch: two ticks
        out = eng.run_to_completion()
        assert set(rids) <= set(out)
        for rid in rids:
            oc = eng.outcomes[rid]
            assert oc.status == RequestStatus.FINISHED
            assert len(oc.tokens) == 1

    def test_prompt_wider_than_model_rejected(self):
        model, eng = self._engine()
        with pytest.raises(ValueError, match="serve width"):
            eng.add_request([1] * (model.serve_dense_width + 1))

    def test_dlrm_behind_router(self):
        from paddle_tpu.serving.router import Router
        model, eng = self._engine()
        router = Router([eng]).warmup()
        rids = [router.add_request([2 + i] * model.serve_dense_width,
                                   max_new_tokens=1)
                for i in range(5)]
        out = router.run_to_completion()
        assert set(rids) <= set(out)
        assert all(len(v) == 1 for v in out.values())
        assert router.health()["per_replica"][0]["kv_bytes_per_token"] == 0

    def test_llm_engines_unaffected(self):
        """The dense seam must not change the LM path's arch pick."""
        from paddle_tpu.inference.serving import _pick_arch, _GPTArch
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        paddle.seed(0)
        gpt = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=16, num_layers=1, num_heads=2,
            max_seq_len=32, use_flash_attention=False))
        assert isinstance(_pick_arch(gpt), _GPTArch)
