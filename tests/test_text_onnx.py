"""paddle.text / paddle.onnx surface tests."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle


def _brute_force(pot_b, trans, length, include_tag):
    """Best path over `length` steps; BOS = last trans row added at t=0,
    EOS = second-to-last row added at the final valid step (reference
    viterbi_decode_kernel.cc semantics)."""
    n = pot_b.shape[1]
    best, bp = -1e30, None
    for seq in itertools.product(range(n), repeat=length):
        s = pot_b[0, seq[0]]
        if include_tag:
            s += trans[n - 1, seq[0]]
        for i in range(1, length):
            s += trans[seq[i - 1], seq[i]] + pot_b[i, seq[i]]
        if include_tag:
            s += trans[n - 2, seq[length - 1]]
        if s > best:
            best, bp = s, seq
    return best, bp


@pytest.mark.parametrize("include_tag", [False, True])
def test_viterbi_matches_brute_force(include_tag):
    from paddle_tpu.text import viterbi_decode
    B, T, N = 2, 5, 3
    rng = np.random.RandomState(0)
    pot = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    scores, paths = viterbi_decode(paddle.to_tensor(pot),
                                   paddle.to_tensor(trans),
                                   include_bos_eos_tag=include_tag)
    for b in range(B):
        best, bp = _brute_force(pot[b], trans, T, include_tag)
        assert abs(best - float(scores.numpy()[b])) < 1e-4
        assert list(bp) == list(paths.numpy()[b])


def test_viterbi_respects_lengths():
    from paddle_tpu.text import viterbi_decode
    B, T, N = 2, 6, 3
    rng = np.random.RandomState(1)
    pot = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    lengths = np.array([3, 6])
    scores, paths = viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        lengths=paddle.to_tensor(lengths), include_bos_eos_tag=False)
    for b in range(B):
        best, bp = _brute_force(pot[b], trans, int(lengths[b]), False)
        assert abs(best - float(scores.numpy()[b])) < 1e-4, b
        got = list(paths.numpy()[b])
        assert got[:lengths[b]] == list(bp)
        assert all(v == 0 for v in got[lengths[b]:])   # padding masked


def test_viterbi_decoder_layer():
    from paddle_tpu.text import ViterbiDecoder
    rng = np.random.RandomState(2)
    dec = ViterbiDecoder(paddle.to_tensor(
        rng.randn(4, 4).astype(np.float32)))
    scores, paths = dec(paddle.to_tensor(
        rng.randn(1, 4, 4).astype(np.float32)))
    assert paths.shape == [1, 4]


def test_onnx_export_requires_input_spec():
    import paddle_tpu.onnx as onnx
    with pytest.raises(ValueError, match="input_spec"):
        onnx.export(None, "x")


def test_text_datasets_raise_clearly():
    # implemented loaders require a local archive; the rest still stub
    from paddle_tpu.text import WMT14, WMT16, Conll05st, Imdb
    with pytest.raises(FileNotFoundError, match="No-egress"):
        Imdb()
    with pytest.raises(FileNotFoundError, match="No-egress"):
        Conll05st()
    with pytest.raises(FileNotFoundError, match="No-egress"):
        WMT16()
    with pytest.raises(NotImplementedError, match="egress"):
        WMT14()
