"""Round-2 op-set widening tests: RNN family, FFT, signal, distributions,
weight_norm, on-device grad clip, broadcast_object_list, input_spec guard,
and the previously-untested composition gaps (alltoall list API,
batch_isend_irecv, AMP O2+scaler+DP, to_static train step).
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestRNN:
    def test_lstm_matches_torch(self):
        import torch
        paddle.seed(0)
        B, T, I, H = 2, 5, 4, 8
        lstm = nn.LSTM(I, H, num_layers=2, direction="bidirectional")
        x = np.random.randn(B, T, I).astype(np.float32)
        out, (h, c) = lstm(paddle.to_tensor(x))
        assert out.shape == [B, T, 2 * H]
        assert h.shape == [4, B, H] and c.shape == [4, B, H]

        tl = torch.nn.LSTM(I, H, num_layers=2, bidirectional=True,
                           batch_first=True)
        sd = {}
        for layer in range(2):
            for d in range(2):
                cell = lstm.cells[layer * 2 + d]
                sfx = "_reverse" if d else ""
                for ours, theirs in (("weight_ih", f"weight_ih_l{layer}{sfx}"),
                                     ("weight_hh", f"weight_hh_l{layer}{sfx}"),
                                     ("bias_ih", f"bias_ih_l{layer}{sfx}"),
                                     ("bias_hh", f"bias_hh_l{layer}{sfx}")):
                    sd[theirs] = torch.tensor(
                        np.asarray(getattr(cell, ours)._data))
        tl.load_state_dict(sd)
        to, _ = tl(torch.tensor(x))
        np.testing.assert_allclose(out.numpy(), to.detach().numpy(),
                                   atol=1e-6)

    def test_gru_matches_torch(self):
        import torch
        paddle.seed(1)
        gru = nn.GRU(4, 8)
        x = np.random.randn(2, 5, 4).astype(np.float32)
        go, gh = gru(paddle.to_tensor(x))
        cell = gru.cells[0]
        tg = torch.nn.GRU(4, 8, batch_first=True)
        tg.load_state_dict({
            "weight_ih_l0": torch.tensor(np.asarray(cell.weight_ih._data)),
            "weight_hh_l0": torch.tensor(np.asarray(cell.weight_hh._data)),
            "bias_ih_l0": torch.tensor(np.asarray(cell.bias_ih._data)),
            "bias_hh_l0": torch.tensor(np.asarray(cell.bias_hh._data))})
        tgo, _ = tg(torch.tensor(x))
        np.testing.assert_allclose(go.numpy(), tgo.detach().numpy(),
                                   atol=1e-6)

    def test_rnn_trains(self):
        paddle.seed(2)
        model = nn.Sequential()
        lstm = nn.LSTM(4, 8)
        head = nn.Linear(8, 1)
        opt = paddle.optimizer.AdamW(
            learning_rate=1e-2,
            parameters=list(lstm.parameters()) + list(head.parameters()))
        x = paddle.to_tensor(np.random.randn(8, 5, 4).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(8, 1).astype(np.float32))
        losses = []
        for _ in range(5):
            out, _ = lstm(x)
            loss = paddle.ops.mean((head(out[:, -1]) - y) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_cells_and_rnn_driver(self):
        paddle.seed(3)
        cell = nn.LSTMCell(4, 8)
        x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        h, (h2, c2) = cell(x)
        assert h.shape == [2, 8]
        rnn = nn.RNN(nn.GRUCell(4, 8))
        seq = paddle.to_tensor(np.random.randn(2, 5, 4).astype(np.float32))
        out, final = rnn(seq)
        assert out.shape == [2, 5, 8] and final.shape == [2, 8]
        bi = nn.BiRNN(nn.SimpleRNNCell(4, 8), nn.SimpleRNNCell(4, 8))
        out, _ = bi(seq)
        assert out.shape == [2, 5, 16]


class TestFFTSignal:
    def test_fft_round_trip_and_grad(self):
        import paddle_tpu.fft as fft
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32),
                             stop_gradient=False)
        sp = fft.rfft(x)
        rec = fft.irfft(sp, n=16)
        np.testing.assert_allclose(rec.numpy(), x.numpy(), atol=1e-5)
        loss = paddle.ops.sum(paddle.ops.abs(sp) ** 2)
        loss.backward()
        assert x.grad is not None

    def test_fft_matches_numpy(self):
        import paddle_tpu.fft as fft
        x = np.random.randn(8, 32).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(fft.fft2(paddle.to_tensor(x))._data),
            np.fft.fft2(x), atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(fft.fftshift(paddle.to_tensor(x))._data),
            np.fft.fftshift(x), atol=1e-6)

    def test_stft_istft_round_trip(self):
        import paddle_tpu.signal as sig
        x = paddle.to_tensor(np.random.randn(2, 512).astype(np.float32))
        win = paddle.to_tensor(np.hanning(128).astype(np.float32))
        spec = sig.stft(x, n_fft=128, hop_length=32, window=win)
        rec = sig.istft(spec, n_fft=128, hop_length=32, window=win,
                        length=512)
        np.testing.assert_allclose(rec.numpy()[:, 64:-64],
                                   x.numpy()[:, 64:-64], atol=1e-4)


class TestDistributions:
    def test_normal(self):
        from paddle_tpu.distribution import Normal, kl_divergence
        paddle.seed(0)
        d = Normal(0.0, 1.0)
        s = d.sample((2000,))
        assert abs(float(paddle.ops.mean(s).numpy())) < 0.1
        lp = d.log_prob(paddle.to_tensor(np.array([0.0], np.float32)))
        assert abs(float(lp.numpy()[0]) - (-0.5 * math.log(2 * math.pi))) \
            < 1e-5
        q = Normal(1.0, 2.0)
        kl = kl_divergence(d, q)
        expected = math.log(2) + (1 + 1) / 8 - 0.5
        assert abs(float(kl.numpy()) - expected) < 1e-5

    def test_rsample_differentiable(self):
        from paddle_tpu.distribution import Normal
        loc = paddle.to_tensor(np.array([0.5], np.float32),
                               stop_gradient=False)
        d = Normal(loc, 1.0)
        s = d.rsample((16,))
        paddle.ops.sum(s).backward()
        assert loc.grad is not None

    def test_categorical_bernoulli(self):
        from paddle_tpu.distribution import Bernoulli, Categorical
        paddle.seed(1)
        c = Categorical(paddle.to_tensor(
            np.array([0.0, 0.0, 10.0], np.float32)))
        s = c.sample((100,))
        assert np.mean(np.asarray(s._data) == 2) > 0.95
        ent = c.entropy()
        assert float(ent.numpy()) < 0.05
        b = Bernoulli(paddle.to_tensor(np.array([0.9], np.float32)))
        lp = b.log_prob(paddle.to_tensor(np.array([1.0], np.float32)))
        assert abs(float(lp.numpy()[0]) - math.log(0.9)) < 1e-4


class TestWeightNorm:
    def test_weight_norm_round_trip(self):
        from paddle_tpu.nn.utils import remove_weight_norm, weight_norm
        paddle.seed(0)
        fc = nn.Linear(4, 8)
        w0 = np.asarray(fc.weight._data).copy()
        x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        ref = fc(x).numpy()
        weight_norm(fc, "weight", dim=0)
        names = dict(fc.named_parameters())
        assert "weight_g" in names and "weight_v" in names
        np.testing.assert_allclose(fc(x).numpy(), ref, atol=1e-5)
        # grads flow to g and v
        loss = paddle.ops.sum(fc(x) ** 2)
        loss.backward()
        assert fc.weight_g.grad is not None
        assert fc.weight_v.grad is not None
        remove_weight_norm(fc, "weight")
        names = dict(fc.named_parameters())
        assert "weight" in names and "weight_g" not in names
        np.testing.assert_allclose(fc(x).numpy(), ref, atol=1e-5)
        np.testing.assert_allclose(np.asarray(fc.weight._data), w0,
                                   atol=1e-5)


class TestClipGradNorm:
    def test_on_device_clip(self):
        from paddle_tpu.nn.utils import clip_grad_norm_
        p = paddle.to_tensor(np.ones((4,), np.float32),
                             stop_gradient=False)
        p.grad = paddle.to_tensor(np.full((4,), 3.0, np.float32))
        total = clip_grad_norm_([p], max_norm=1.0)
        assert abs(float(total.numpy()) - 6.0) < 1e-5
        np.testing.assert_allclose(np.linalg.norm(np.asarray(p.grad._data)),
                                   1.0, atol=1e-4)

    def test_no_clip_below_max(self):
        from paddle_tpu.nn.utils import clip_grad_norm_
        p = paddle.to_tensor(np.ones((4,), np.float32),
                             stop_gradient=False)
        g = np.full((4,), 0.1, np.float32)
        p.grad = paddle.to_tensor(g)
        clip_grad_norm_([p], max_norm=10.0)
        np.testing.assert_allclose(np.asarray(p.grad._data), g, atol=1e-6)


class TestCompositionGaps:
    """VERDICT weak #9: previously untested compositions."""

    def test_alltoall_list_api(self):
        import paddle_tpu.distributed as dist
        ins = [paddle.to_tensor(np.full((2, 2), float(i), np.float32))
               for i in range(8)]
        outs = []
        dist.alltoall(outs, ins)
        assert len(outs) == 8
        for o in outs:
            assert o.shape == [2, 2]

    def test_batch_isend_irecv(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import P2POp
        send = paddle.to_tensor(np.ones((4,), np.float32))
        recv = paddle.to_tensor(np.zeros((4,), np.float32))
        g = dist.new_group(axes=("dp",))
        ops = [P2POp(dist.isend, send, 1, group=g),
               P2POp(dist.irecv, recv, 1, group=g)]
        tasks = dist.batch_isend_irecv(ops)
        for t in tasks:
            if hasattr(t, "wait"):
                t.wait()
        assert np.all(np.isfinite(np.asarray(recv._data)))

    def test_amp_o2_scaler_with_data_parallel(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu import amp
        paddle.seed(0)
        net = nn.Linear(8, 8)
        model = dist.DataParallel(net)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=net.parameters())
        scaler = amp.GradScaler(init_loss_scaling=128.0)
        x = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
        losses = []
        for _ in range(3):
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                loss = paddle.ops.mean(model(x) ** 2)
            scaled = scaler.scale(loss)
            scaled.backward()
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_to_static_train_step_with_optimizer(self):
        paddle.seed(1)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))

        losses = []
        for _ in range(5):
            loss = paddle.ops.mean((net(x) - y) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_input_spec_validation(self):
        from paddle_tpu.static import InputSpec
        net = nn.Linear(8, 4)
        st = paddle.jit.to_static(
            net, input_spec=[InputSpec([-1, 8], "float32")])
        ok = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        st(ok)
        bad = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        with pytest.raises(ValueError, match="input_spec"):
            st(bad)

    def test_broadcast_object_list(self):
        import paddle_tpu.distributed as dist
        objs = [{"a": 1, "b": [1, 2, 3]}, "hello"]
        out = dist.broadcast_object_list(objs, src=0)
        assert out[0] == {"a": 1, "b": [1, 2, 3]}
        assert out[1] == "hello"
