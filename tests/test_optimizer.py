"""Optimizer + lr scheduler + amp tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer as optim


def _quadratic_losses(opt_cls, steps=60, **kw):
    """Minimize ||w - c||^2; return final distance."""
    target = np.array([1.0, -2.0, 3.0], dtype="float32")
    w = paddle.to_tensor(np.zeros(3, "float32"), stop_gradient=False)
    w.persistable = True
    from paddle_tpu.nn.parameter import Parameter
    p = Parameter(w._data)
    p.stop_gradient = False
    steps = kw.pop("steps", steps)
    opt = opt_cls(learning_rate=kw.pop("lr", 0.1), parameters=[p], **kw)
    for _ in range(steps):
        diff = p - paddle.to_tensor(target)
        loss = (diff * diff).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return np.abs(p.numpy() - target).max()


class TestOptimizers:
    @pytest.mark.parametrize("cls,kw", [
        (optim.SGD, {}),
        (optim.Momentum, {}),
        (optim.Adam, {}),
        (optim.AdamW, {}),
        (optim.Adagrad, {"lr": 0.5}),
        (optim.RMSProp, {}),
        (optim.Adamax, {}),
        (optim.Lamb, {"lr": 0.05, "steps": 200}),
        (optim.NAdam, {}),
        (optim.RAdam, {}),
    ])
    def test_converges_on_quadratic(self, cls, kw):
        err = _quadratic_losses(cls, **kw)
        assert err < 0.5, f"{cls.__name__} final err {err}"

    def test_adam_matches_reference_formula(self):
        from paddle_tpu.nn.parameter import Parameter
        import jax.numpy as jnp
        p = Parameter(jnp.asarray(np.array([1.0, 2.0], "float32")))
        p.stop_gradient = False
        opt = optim.Adam(learning_rate=0.1, parameters=[p])
        g = np.array([0.5, -0.5], "float32")
        p.grad = paddle.to_tensor(g)
        opt.step()
        # step 1: m=0.1g v=0.001g^2, mhat=g, vhat=g^2 -> w -= lr*g/(|g|+eps)
        expect = np.array([1.0, 2.0]) - 0.1 * g / (np.abs(g) + 1e-8)
        np.testing.assert_allclose(p.numpy(), expect, rtol=1e-5, atol=1e-6)

    def test_weight_decay_l2(self):
        from paddle_tpu.nn.parameter import Parameter
        import jax.numpy as jnp
        p = Parameter(jnp.asarray(np.array([2.0], "float32")))
        p.stop_gradient = False
        opt = optim.SGD(learning_rate=0.1, parameters=[p], weight_decay=0.5)
        p.grad = paddle.to_tensor(np.array([0.0], "float32"))
        opt.step()
        np.testing.assert_allclose(p.numpy(), [2.0 - 0.1 * 0.5 * 2.0])

    def test_grad_clip_global_norm(self):
        from paddle_tpu.nn.parameter import Parameter
        import jax.numpy as jnp
        p = Parameter(jnp.asarray(np.zeros(4, "float32")))
        p.stop_gradient = False
        clip = nn.ClipGradByGlobalNorm(1.0)
        opt = optim.SGD(learning_rate=1.0, parameters=[p], grad_clip=clip)
        p.grad = paddle.to_tensor(np.full(4, 10.0, "float32"))  # norm 20
        opt.step()
        np.testing.assert_allclose(np.linalg.norm(p.numpy()), 1.0, rtol=1e-4)

    def test_state_dict_roundtrip(self):
        from paddle_tpu.nn.parameter import Parameter
        import jax.numpy as jnp
        p = Parameter(jnp.asarray(np.ones(2, "float32")), name="w0")
        p.stop_gradient = False
        opt = optim.Adam(learning_rate=0.1, parameters=[p])
        p.grad = paddle.to_tensor(np.ones(2, "float32"))
        opt.step()
        sd = opt.state_dict()
        assert "w0_moment1" in sd
        p2 = Parameter(jnp.asarray(np.ones(2, "float32")), name="w0")
        p2.stop_gradient = False
        opt2 = optim.Adam(learning_rate=0.1, parameters=[p2])
        opt2.set_state_dict(sd)
        assert opt2._step_count == 1
        np.testing.assert_allclose(
            opt2._accumulators[id(p2)]["moment1"],
            opt._accumulators[id(p)]["moment1"])

    def test_multi_precision_master_weights(self):
        from paddle_tpu.nn.parameter import Parameter
        import jax.numpy as jnp
        p = Parameter(jnp.ones(4, dtype=jnp.bfloat16))
        p.stop_gradient = False
        opt = optim.AdamW(learning_rate=0.01, parameters=[p],
                          multi_precision=True)
        p.grad = paddle.Tensor(jnp.full((4,), 0.001, dtype=jnp.bfloat16))
        opt.step()
        assert id(p) in opt._master_weights
        assert opt._master_weights[id(p)].dtype == np.float32
        assert p.dtype == paddle.bfloat16


class TestLRSchedulers:
    def test_step_decay(self):
        sched = optim.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(sched())
            sched.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    def test_cosine(self):
        sched = optim.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(sched() - 1.0) < 1e-6
        for _ in range(10):
            sched.step()
        assert sched() < 1e-6

    def test_warmup(self):
        sched = optim.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0,
                                      end_lr=0.1)
        assert sched() < 0.02
        for _ in range(12):
            sched.step()
        assert abs(sched() - 0.1) < 1e-6

    def test_optimizer_uses_scheduler(self):
        from paddle_tpu.nn.parameter import Parameter
        import jax.numpy as jnp
        p = Parameter(jnp.ones(1))
        p.stop_gradient = False
        sched = optim.lr.StepDecay(1.0, step_size=1, gamma=0.1)
        opt = optim.SGD(learning_rate=sched, parameters=[p])
        assert opt.get_lr() == 1.0
        sched.step()
        assert abs(opt.get_lr() - 0.1) < 1e-9


class TestAmp:
    def test_auto_cast_matmul_bf16(self):
        from paddle_tpu.ops import linalg
        x = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        with paddle.amp.auto_cast(level="O1"):
            y = linalg.matmul(x, x)
        assert y.dtype == paddle.bfloat16
        y2 = linalg.matmul(x, x)
        assert y2.dtype == np.dtype("float32")

    def test_auto_cast_blacklist_stays_fp32(self):
        x = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        with paddle.amp.auto_cast(level="O1"):
            y = F.softmax(x)
        assert y.dtype == np.dtype("float32")

    def test_grad_scaler_scales_and_skips_inf(self):
        from paddle_tpu.nn.parameter import Parameter
        import jax.numpy as jnp
        p = Parameter(jnp.ones(2, dtype=jnp.float32))
        p.stop_gradient = False
        opt = optim.SGD(learning_rate=0.1, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0,
                                       incr_every_n_steps=1000)
        loss = (p * p).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        # grads are 4x; unscale_ restores and step applies
        scaler.step(opt)
        opt.clear_grad()
        np.testing.assert_allclose(p.numpy(), 1.0 - 0.1 * 2.0, rtol=1e-5)
        # inf grad skips the step and shrinks the scale
        before = p.numpy().copy()
        p.grad = paddle.to_tensor(np.array([np.inf, 1.0], "float32"))
        scaler.step(opt)
        np.testing.assert_array_equal(p.numpy(), before)
        assert scaler._scale == 2.0

    def test_decorate_o2(self):
        model = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
        from paddle_tpu.nn.parameter import Parameter
        opt = optim.AdamW(learning_rate=0.01, parameters=model.parameters())
        model, opt = paddle.amp.decorate(model, opt, level="O2")
        assert model[0].weight.dtype == paddle.bfloat16
        assert model[1].weight.dtype == np.dtype("float32")  # LN excluded
        assert opt._multi_precision


class TestDecayExclusion:
    def test_adamw_apply_decay_param_fun(self):
        from paddle_tpu.nn.parameter import Parameter
        import jax.numpy as jnp
        w = Parameter(jnp.ones(2), name="weight_w")
        b = Parameter(jnp.ones(2), name="bias_b")
        for p in (w, b):
            p.stop_gradient = False
        opt = optim.AdamW(learning_rate=0.1, parameters=[w, b],
                          weight_decay=0.5,
                          apply_decay_param_fun=lambda n: "bias" not in n)
        z = np.zeros(2, "float32")
        w.grad = paddle.to_tensor(z)
        b.grad = paddle.to_tensor(z)
        opt.step()
        # zero grads: only decay moves params; bias must be untouched
        np.testing.assert_allclose(w.numpy(), 1.0 - 0.1 * 0.5, rtol=1e-6)
        np.testing.assert_allclose(b.numpy(), 1.0, rtol=1e-6)

    def test_linear_warmup_state_roundtrip(self):
        inner = optim.lr.CosineAnnealingDecay(0.1, T_max=10)
        sched = optim.lr.LinearWarmup(inner, warmup_steps=3, start_lr=0.0,
                                      end_lr=0.1)
        for _ in range(7):
            sched.step()
        sd = sched.state_dict()
        inner2 = optim.lr.CosineAnnealingDecay(0.1, T_max=10)
        sched2 = optim.lr.LinearWarmup(inner2, warmup_steps=3, start_lr=0.0,
                                       end_lr=0.1)
        sched2.set_state_dict(sd)
        assert abs(sched2() - sched()) < 1e-9
        assert sched2.lr_sched.last_epoch == sched.lr_sched.last_epoch
