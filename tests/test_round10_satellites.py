"""Round-10 satellites: jit.save version stamping + ArtifactVersionError,
and DataLoader multiprocess-worker lifecycle guarantees."""
import gc
import os
import pickle
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.static import InputSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# jit.save version stamp / ArtifactVersionError
# ---------------------------------------------------------------------------
class TestArtifactVersionStamp:
    def _save(self, tmp_path):
        net = nn.Linear(4, 2)
        prefix = str(tmp_path / "m")
        jit.save(net, prefix, input_spec=[InputSpec([3, 4], "float32")])
        return prefix

    def test_blob_carries_toolchain_stamp(self, tmp_path):
        import jax
        import jaxlib
        prefix = self._save(tmp_path)
        with open(prefix + ".pdmodel", "rb") as f:
            blob = pickle.load(f)
        assert blob["format"] == "paddle_tpu.jit/2"
        assert blob["jax_version"] == jax.__version__
        assert blob["jaxlib_version"] == jaxlib.__version__
        assert blob["platform"]

    def test_roundtrip_still_loads(self, tmp_path):
        prefix = self._save(tmp_path)
        out = jit.load(prefix)(
            paddle.to_tensor(np.ones((3, 4), np.float32)))
        assert out.shape == [3, 2]

    def test_version_skew_raises_clear_error(self, tmp_path):
        prefix = self._save(tmp_path)
        with open(prefix + ".pdmodel", "rb") as f:
            blob = pickle.load(f)
        # stamped by an older toolchain AND undecodable program bytes:
        # the load must name both versions, not dump a deserialize trace
        blob["jax_version"] = "0.3.99"
        blob["jaxlib_version"] = "0.3.99"
        blob["stablehlo"] = b"\x00garbage"
        with open(prefix + ".pdmodel", "wb") as f:
            pickle.dump(blob, f)
        with pytest.raises(jit.ArtifactVersionError) as ei:
            jit.load(prefix)
        msg = str(ei.value)
        assert "0.3.99" in msg and "jit.save" in msg

    def test_same_version_corruption_not_masked(self, tmp_path):
        prefix = self._save(tmp_path)
        with open(prefix + ".pdmodel", "rb") as f:
            blob = pickle.load(f)
        blob["stablehlo"] = b"\x00garbage"          # versions match
        with open(prefix + ".pdmodel", "wb") as f:
            pickle.dump(blob, f)
        with pytest.raises(Exception) as ei:
            jit.load(prefix)
        assert not isinstance(ei.value, jit.ArtifactVersionError)

    def test_foreign_blob_rejected(self, tmp_path):
        prefix = self._save(tmp_path)
        with open(prefix + ".pdmodel", "wb") as f:
            pickle.dump({"format": "something_else/7"}, f)
        with pytest.raises(jit.ArtifactVersionError):
            jit.load(prefix)


# ---------------------------------------------------------------------------
# DataLoader worker lifecycle
# ---------------------------------------------------------------------------
class _Range(Dataset):
    def __init__(self, n=64):
        self.n = n

    def __getitem__(self, i):
        return np.full((4,), i, np.float32)

    def __len__(self):
        return self.n


def _alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    # reaped-but-zombie also counts as gone once waited on; poll /proc
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split()[2] != "Z"
    except OSError:
        return False


def _wait_dead(pids, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not any(_alive(p) for p in pids):
            return True
        time.sleep(0.1)
    return False


class TestDataLoaderWorkerCleanup:
    def test_workers_join_after_full_iteration(self):
        loader = DataLoader(_Range(16), batch_size=4, num_workers=2)
        it = iter(loader)
        pids = [w.pid for w in it._workers]
        batches = list(it)
        assert len(batches) == 4
        assert _wait_dead(pids), "workers outlived a completed epoch"

    def test_workers_terminated_after_consumer_exception(self):
        loader = DataLoader(_Range(64), batch_size=4, num_workers=2)
        pids = []

        def consume():
            it = iter(loader)
            pids.extend(w.pid for w in it._workers)
            for i, _batch in enumerate(it):
                if i == 2:
                    raise ValueError("consumer blew up mid-epoch")

        with pytest.raises(ValueError):
            consume()
        # the iterator died with the consumer frame; GC must reap workers
        gc.collect()
        assert _wait_dead(pids), (
            "orphaned DataLoader workers after a consumer-loop exception")

    def test_workers_terminated_on_explicit_del(self):
        loader = DataLoader(_Range(64), batch_size=4, num_workers=2)
        it = iter(loader)
        pids = [w.pid for w in it._workers]
        next(it)
        del it
        gc.collect()
        assert _wait_dead(pids), "workers survived iterator deletion"

    def test_workers_reaped_at_interpreter_exit(self, tmp_path):
        """A child interpreter that abandons a mid-epoch iterator (the
        finalize/atexit path) must leave no orphan workers behind."""
        script = r"""
import os, sys
import numpy as np
from paddle_tpu.io import DataLoader, Dataset

class DS(Dataset):
    def __getitem__(self, i):
        return np.full((4,), i, np.float32)
    def __len__(self):
        return 64

loader = DataLoader(DS(), batch_size=4, num_workers=2)
it = iter(loader)
next(it)
print("PIDS", " ".join(str(w.pid) for w in it._workers))
sys.stdout.flush()
# exit with the iterator still alive and batches in flight
"""
        env = dict(os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              cwd=REPO, capture_output=True, text=True,
                              timeout=180)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        pids = [int(p) for line in proc.stdout.splitlines()
                if line.startswith("PIDS")
                for p in line.split()[1:]]
        assert pids
        assert _wait_dead(pids), (
            f"workers {pids} orphaned after interpreter exit")
