"""Request-level serving observability (round 19).

Covers paddle_tpu/observability/reqtrace.py + the instrumentation seams
in inference/serving.py, serving/router.py and serving/stream.py, the
tools/request_trace.py renderer, loadgen's exemplar/trace-out riders,
and the persistence/fleet-carry paths:

* recorder semantics (bounded rings, post-terminal stream marks,
  per-timeline event caps);
* exact wall-segment decomposition + completeness validation + router
  stitching;
* the FLAGS_reqtrace disabled path reads ZERO clocks (round-8 metrics
  gate discipline, deterministic);
* SLO multiwindow burn-rate gauges from the ResilienceConfig knobs;
* TTFT/ITL exemplar linkage (worst-k samples keep their request id);
* the fault-drill matrix: under serving.tick_stall,
  serving.crash_at_tick, deadline expiry, preemption and mid-flight
  re-route, EVERY terminal request's timeline is complete (terminal
  present, segments sum to total, no unclosed events) — FakeClock
  seams from round 11;
* the acceptance scenario: one request chunk-prefilled, preempted AND
  re-routed across replicas, reconstructed as a causal timeline whose
  segments sum to its total wall time, merged with the engine's device
  spans on one clock.
"""
import io
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fault import inject
from paddle_tpu.inference import PagedEngine, ReplicaState, ResilienceConfig
from paddle_tpu.inference.resilience import (RequestStatus,
                                             TERMINAL_STATUSES)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import REGISTRY, reqtrace
from paddle_tpu.observability import trace as otrace
from paddle_tpu.serving import Router
from tools import request_trace as rt_tool


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = LlamaConfig(vocab_size=97, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, max_seq_len=256,
                      use_flash_attention=False)
    return LlamaForCausalLM(cfg)


@pytest.fixture(autouse=True)
def _clean():
    inject.disarm_all()
    reqtrace.RECORDER.clear()
    reqtrace.EXEMPLARS.clear()
    paddle.set_flags({"FLAGS_reqtrace": True})
    yield
    inject.disarm_all()
    reqtrace.RECORDER.clear()
    reqtrace.EXEMPLARS.clear()
    paddle.set_flags({"FLAGS_reqtrace": True,
                      "FLAGS_enable_metrics": False})


def make_engine(model, *, max_batch=2, block_size=4, num_blocks=32,
                max_blocks_per_seq=16, **res_kw):
    res = ResilienceConfig(**res_kw) if res_kw else None
    return PagedEngine(model, max_batch=max_batch, block_size=block_size,
                       num_blocks=num_blocks,
                       max_blocks_per_seq=max_blocks_per_seq,
                       resilience=res)


def prompt(seed, n=5):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(1, 97, size=n)]


class FakeClock:
    """Deterministic clock seam (engine + lifecycle), counting reads."""

    def __init__(self):
        self.t = 0.0
        self.reads = 0

    def __call__(self):
        self.reads += 1
        return self.t

    def install(self, eng):
        eng._clock = self
        eng.lifecycle._clock = self
        return self


def assert_complete(tl):
    problems = reqtrace.validate(tl)
    assert problems == [], (tl["scope"], tl["rid"], problems)
    seg = reqtrace.segments(tl)
    covered = sum(seg[b] for b in reqtrace.SEGMENT_BUCKETS)
    assert abs(covered - seg["total"]) <= 1e-6 + 1e-9 * abs(seg["total"])
    assert seg["complete"]
    return seg


def engine_timelines(eng, rids):
    out = {}
    for r in rids:
        tl = reqtrace.RECORDER.timeline(eng.reqtrace_scope, r)
        assert tl is not None and tl["events"], f"rid {r}: no timeline"
        out[r] = tl
    return out


# ---------------------------------------------------------------------------
# Recorder unit semantics
# ---------------------------------------------------------------------------
class TestRecorder:
    def test_terminal_moves_live_to_ring(self):
        rec = reqtrace.RequestTraceRecorder(retain=4)
        rec.event("s", 1, "submitted", 0.0)
        assert rec.live_timelines() and not rec.tail()
        rec.event("s", 1, "terminal", 1.0, {"outcome": "FINISHED"})
        assert not rec.live_timelines()
        tail = rec.tail()
        assert len(tail) == 1 and tail[0]["rid"] == 1
        assert rec.timeline("s", 1)["events"][-1]["event"] == "terminal"

    def test_ring_bounded_evicts_oldest(self):
        rec = reqtrace.RequestTraceRecorder(retain=3)
        for rid in range(6):
            rec.event("s", rid, "submitted", float(rid))
            rec.event("s", rid, "terminal", rid + 0.5)
        tail = rec.tail()
        assert [t["rid"] for t in tail] == [3, 4, 5]
        assert rec.timeline("s", 0) is None
        assert rec.evicted == 3

    def test_post_terminal_stream_marks_attach_to_done(self):
        rec = reqtrace.RequestTraceRecorder()
        rec.event("s", 7, "submitted", 0.0)
        rec.event("s", 7, "terminal", 1.0, {"outcome": "FINISHED"})
        rec.event("s", 7, "stream_closed", 2.0, {"status": "FINISHED"})
        tl = rec.timeline("s", 7)
        assert tl["events"][-1]["event"] == "stream_closed"
        # a NON-stream event after terminal must not reopen a timeline
        rec.event("s", 7, "decode_tick", 3.0)
        assert not rec.live_timelines()
        assert reqtrace.validate(tl) == []
        # a stream mark for an UNKNOWN/evicted request must not open a
        # ghost timeline that never closes
        rec.event("s", 99, "stream_closed", 4.0)
        assert not rec.live_timelines()
        assert rec.timeline("s", 99) is None

    def test_delivery_marks_are_singular_per_request(self):
        """Re-attaching a second stream must not restamp
        first_delivery/stream_closed with later timestamps."""
        rec = reqtrace.RequestTraceRecorder()
        rec.event("s", 1, "submitted", 0.0)
        rec.event("s", 1, "first_delivery", 0.5)
        rec.event("s", 1, "first_delivery", 0.7)      # duplicate: drop
        rec.event("s", 1, "terminal", 1.0, {"outcome": "FINISHED"})
        rec.event("s", 1, "stream_closed", 1.5)
        rec.event("s", 1, "stream_closed", 2.0)       # duplicate: drop
        evs = rec.timeline("s", 1)["events"]
        assert [e["event"] for e in evs].count("first_delivery") == 1
        assert [e["event"] for e in evs].count("stream_closed") == 1
        assert next(e["t"] for e in evs
                    if e["event"] == "first_delivery") == 0.5

    def test_done_event_budget_stays_honest_under_stream_marks(self):
        """Post-terminal stream marks count toward the retained-events
        budget, so eviction (which subtracts FULL timeline lengths)
        cannot drift the counter negative and unbind the memory cap."""
        rec = reqtrace.RequestTraceRecorder(retain=2)
        for rid in range(5):
            rec.event("s", rid, "submitted", float(rid))
            rec.event("s", rid, "terminal", rid + 0.25,
                      {"outcome": "FINISHED"})
            rec.event("s", rid, "stream_closed", rid + 0.5)
        assert rec._done_events == sum(len(t["events"])
                                       for t in rec.tail())
        assert rec._done_events == 6          # 2 retained x 3 events

    def test_per_timeline_event_cap_counts_drops(self):
        rec = reqtrace.RequestTraceRecorder(max_events=4)
        rec.event("s", 1, "submitted", 0.0)
        for i in range(10):
            rec.event("s", 1, "decode_tick", float(i + 1))
        tl = rec.live_timelines()[0]
        assert len(tl["events"]) == 4 and tl["dropped"] == 7
        assert "dropped" in " ".join(reqtrace.validate(tl))


# ---------------------------------------------------------------------------
# Segment decomposition + validation + stitching (synthetic timelines)
# ---------------------------------------------------------------------------
def _tl(events, scope="s", rid=1):
    return {"scope": scope, "rid": rid,
            "events": [{"event": e, "t": t, **({"meta": m} if m else {})}
                       for e, t, m in events]}


class TestSegments:
    def test_exact_decomposition(self):
        tl = _tl([("submitted", 0.0, None), ("admitted", 2.0, None),
                  ("prefill_chunk", 3.0, None), ("first_token", 5.0, None),
                  ("decode_tick", 6.0, None),
                  ("preempted", 7.0, None), ("admitted", 9.0, None),
                  ("decode_tick", 10.0, None),
                  ("terminal", 11.0, {"outcome": "FINISHED"})])
        seg = assert_complete(tl)
        assert seg["queue"] == 2.0
        assert seg["prefill"] == 3.0 + 1.0   # admitted→first_token + re-prefill
        assert seg["decode"] == 1.0 + 1.0 + 1.0
        assert seg["preempted"] == 2.0
        assert seg["total"] == 11.0

    def test_incomplete_timeline_flagged(self):
        tl = _tl([("submitted", 0.0, None), ("admitted", 1.0, None)])
        seg = reqtrace.segments(tl)
        assert not seg["complete"]
        assert any("terminal" in p for p in reqtrace.validate(tl))

    def test_validate_catches_bad_start_and_order(self):
        tl = _tl([("admitted", 0.0, None),
                  ("terminal", 1.0, {"outcome": "FINISHED"})])
        assert any("submitted" in p for p in reqtrace.validate(tl))
        tl2 = _tl([("submitted", 5.0, None), ("admitted", 1.0, None),
                   ("terminal", 6.0, {"outcome": "FINISHED"})])
        assert any("non-monotonic" in p for p in reqtrace.validate(tl2))

    def test_stitched_stranding_bills_rerouted(self):
        router = _tl([("submitted", 0.0, None),
                      ("routed", 0.5, {"replica": "r0", "replica_rid": 3}),
                      ("rerouted", 4.0, {"from_replica": "r0"}),
                      ("routed", 4.0, {"replica": "r1", "replica_rid": 9}),
                      ("terminal", 10.0, {"outcome": "FINISHED"})],
                     scope="router")
        legs = {
            ("r0", 3): _tl([("submitted", 0.5, None),
                            ("admitted", 1.0, None),
                            ("first_token", 2.0, None),
                            ("terminal", 3.0, {"outcome": "FAILED"})],
                           "r0", 3),
            ("r1", 9): _tl([("submitted", 4.0, None),
                            ("admitted", 5.0, None),
                            ("first_token", 6.0, None),
                            ("decode_tick", 9.0, None),
                            ("terminal", 10.0, {"outcome": "FINISHED"})],
                           "r1", 9),
        }
        st = reqtrace.stitch(router, lookup=lambda s, r: legs.get((s, r)))
        assert st["stitched"]
        seg = assert_complete(st)
        # r0 FAILED@3 → rerouted until the re-route lands at 4.0; the
        # 4.0→5.0 wait for r1's admission bills to queue again
        assert seg["rerouted"] == pytest.approx(1.0)
        assert seg["queue"] == pytest.approx(2.0)
        assert seg["total"] == pytest.approx(10.0)

    def test_intervals_tile_without_gaps(self):
        tl = _tl([("submitted", 0.0, None), ("admitted", 1.0, None),
                  ("first_token", 2.5, None),
                  ("terminal", 4.0, {"outcome": "FINISHED"})])
        iv, complete = reqtrace.segment_intervals(tl)
        assert complete
        assert iv[0][1] == 0.0 and iv[-1][2] == 4.0
        for (s1, a1, b1), (s2, a2, b2) in zip(iv, iv[1:]):
            assert b1 == a2          # no gaps, no overlaps


# ---------------------------------------------------------------------------
# Disabled path: zero clock reads, zero recordings (round-8 proof)
# ---------------------------------------------------------------------------
class TestZeroCostWhenOff:
    def test_module_record_never_reads_clock_when_off(self, monkeypatch):
        calls = {"n": 0}
        real = reqtrace._now

        def counting():
            calls["n"] += 1
            return real()

        monkeypatch.setattr(reqtrace, "_now", counting)
        paddle.set_flags({"FLAGS_reqtrace": False})
        reqtrace.record("s", 1, "submitted")
        assert calls["n"] == 0
        assert not reqtrace.RECORDER.live_timelines()
        paddle.set_flags({"FLAGS_reqtrace": True})
        reqtrace.record("s", 1, "submitted")
        assert calls["n"] == 1

    def test_engine_off_records_nothing_and_reads_fewer_clocks(
            self, model):
        def run_once():
            eng = make_engine(model)
            clock = FakeClock().install(eng)
            rids = [eng.add_request(prompt(i, 6), max_new_tokens=4)
                    for i in range(2)]
            eng.run_to_completion()
            return eng, clock.reads, rids

        paddle.set_flags({"FLAGS_reqtrace": False})
        _eng, reads_off, _ = run_once()
        assert not reqtrace.RECORDER.tail(), \
            "flag off must record no timelines"
        reads_off2 = run_once()[1]
        assert reads_off == reads_off2, "off-path must be deterministic"
        paddle.set_flags({"FLAGS_reqtrace": True})
        eng_on, reads_on, rids = run_once()
        # the instrumentation's own clock reads exist ONLY when on
        assert reads_on > reads_off
        engine_timelines(eng_on, rids)


# ---------------------------------------------------------------------------
# SLO burn-rate accounting
# ---------------------------------------------------------------------------
class TestSloBurnRate:
    def test_tracker_multiwindow_math(self):
        tr = reqtrace.SloTracker("s", target=0.99, fast_window_s=10.0,
                                 slow_window_s=100.0)
        for t in range(8):
            tr.note(float(t), good=True)
        tr.note(8.0, good=False)
        tr.note(9.0, good=False)
        r = tr.burn_rates()
        # 2 bad of 10 in both windows: 0.2 / 0.01 = 20x budget burn
        assert r["fast"] == pytest.approx(20.0)
        assert r["slow"] == pytest.approx(20.0)
        # 30s later the fast window is empty, slow still sees 2/10
        r2 = tr.burn_rates(now=40.0)
        assert r2["fast"] == 0.0
        assert r2["slow"] == pytest.approx(20.0)
        # 200s later both windows aged out
        r3 = tr.burn_rates(now=200.0)
        assert r3 == {"fast": 0.0, "slow": 0.0}

    def test_tracker_validates_knobs(self):
        with pytest.raises(ValueError):
            reqtrace.SloTracker("s", target=1.5)
        with pytest.raises(ValueError):
            reqtrace.SloTracker("s", fast_window_s=100.0,
                                slow_window_s=10.0)
        with pytest.raises(ValueError):
            ResilienceConfig(slo_target=0.0)
        with pytest.raises(ValueError):
            ResilienceConfig(slo_fast_window_s=0.0)

    def test_engine_burn_gauges_from_deadline_misses(self, model):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        eng = make_engine(model, slo_target=0.9)
        clock = FakeClock().install(eng)
        eng._slo = reqtrace.SloTracker(eng.lifecycle.name, target=0.9,
                                       fast_window_s=60.0,
                                       slow_window_s=600.0)
        ok = eng.add_request(prompt(1, 4), max_new_tokens=2)
        eng.run_to_completion()
        bad = eng.add_request(prompt(2, 4), max_new_tokens=2,
                              ttft_deadline_s=0.5)
        clock.t = 10.0                       # expire it in the queue
        eng.step()
        assert eng.outcomes[bad].status == RequestStatus.DEADLINE_MISSED
        g = REGISTRY.get("paddle_tpu_serving_slo_fast_burn_rate")
        # 1 bad of 2 outcomes / 0.1 budget = 5x burn
        assert g.value(scope=eng.lifecycle.name) == pytest.approx(5.0)
        assert eng.outcomes[ok].status == RequestStatus.FINISHED

    def test_burn_gauges_decay_on_health_poll(self, model):
        """An idle-after-incident replica must not pin the alert level:
        the probe path prunes the windows and re-exports the gauges."""
        paddle.set_flags({"FLAGS_enable_metrics": True})
        eng = make_engine(model)
        clock = FakeClock().install(eng)
        eng._slo = reqtrace.SloTracker(eng.lifecycle.name,
                                       fast_window_s=10.0,
                                       slow_window_s=20.0)
        eng._slo.note(1.0, good=False)
        g = REGISTRY.get("paddle_tpu_serving_slo_fast_burn_rate")
        assert g.value(scope=eng.lifecycle.name) > 0
        clock.t = 100.0                      # both windows aged out
        h = eng.health()
        assert h["slo_burn_rate"] == {"fast": 0.0, "slow": 0.0}
        assert g.value(scope=eng.lifecycle.name) == 0.0

    def test_router_burn_gauges_on_shed(self, model):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        rep = make_engine(model, max_queue=1)
        router = Router([rep])           # replica STARTING≠READY: sheds
        rid = router.add_request(prompt(3, 4))
        assert router.outcomes[rid].status == RequestStatus.SHED
        g = REGISTRY.get("paddle_tpu_serving_slo_fast_burn_rate")
        assert g.value(scope=router.name) == pytest.approx(100.0)


# ---------------------------------------------------------------------------
# Exemplars
# ---------------------------------------------------------------------------
class TestExemplars:
    def test_store_keeps_topk_with_identity(self):
        st = reqtrace.ExemplarStore(k=3)
        for rid, v in enumerate([0.1, 0.9, 0.2, 0.8, 0.3, 0.05]):
            st.note("ttft", "s", rid, v, t=float(rid))
        worst = st.worst("ttft")
        assert [w["rid"] for w in worst] == [1, 3, 4]
        assert worst[0]["value"] == pytest.approx(0.9)

    def test_engine_populates_ttft_exemplars(self, model):
        eng = make_engine(model)
        rids = [eng.add_request(prompt(i, 6), max_new_tokens=3)
                for i in range(3)]
        eng.run_to_completion()
        worst = reqtrace.EXEMPLARS.worst("ttft")
        assert worst, "no TTFT exemplars recorded"
        assert {w["rid"] for w in worst} <= set(rids)
        assert all(w["scope"] == eng.reqtrace_scope for w in worst)
        # the exemplar's timeline is retrievable — the whole point
        tl = reqtrace.RECORDER.timeline(worst[0]["scope"],
                                        worst[0]["rid"])
        assert tl is not None and assert_complete(tl)


# ---------------------------------------------------------------------------
# Fault-drill matrix: every terminal request's timeline is complete
# ---------------------------------------------------------------------------
class TestDrillMatrix:
    def test_clean_run_timelines_complete(self, model):
        eng = make_engine(model)
        rids = [eng.add_request(prompt(i, 6), max_new_tokens=4)
                for i in range(3)]
        eng.run_to_completion()
        for rid, tl in engine_timelines(eng, rids).items():
            seg = assert_complete(tl)
            events = [e["event"] for e in tl["events"]]
            assert events[0] == "submitted" and "admitted" in events
            assert "first_token" in events and "prefill_chunk" in events
            assert seg["total"] > 0

    def test_tick_stall_timelines_complete(self, model):
        eng = make_engine(model)
        rids = [eng.add_request(prompt(i, 5), max_new_tokens=3)
                for i in range(2)]
        with inject.armed("serving.tick_stall", times=2, seconds=0.02):
            eng.run_to_completion()
        for tl in engine_timelines(eng, rids).values():
            assert_complete(tl)

    def test_crash_at_tick_failed_timelines_complete(self, model):
        eng = make_engine(model)
        r1 = eng.add_request(prompt(11, 5), max_new_tokens=8)
        eng.step()
        with inject.armed("serving.crash_at_tick", tick=eng._ticks + 1):
            eng.step()
        assert eng.outcomes[r1].status == RequestStatus.FAILED
        tl = engine_timelines(eng, [r1])[r1]
        assert_complete(tl)
        term = tl["events"][-1]
        assert term["meta"]["outcome"] == RequestStatus.FAILED
        assert "tick" in term["meta"]["detail"]

    def test_deadline_expiry_queued_and_midflight(self, model):
        eng = make_engine(model, max_batch=1)
        clock = FakeClock().install(eng)
        running = eng.add_request(prompt(20, 4), max_new_tokens=50,
                                  deadline_s=5.0)
        queued = eng.add_request(prompt(21, 4), max_new_tokens=4,
                                 ttft_deadline_s=2.0)
        eng.step()                           # admits `running` only
        clock.t = 10.0                       # expires both
        eng.step()
        for rid in (running, queued):
            assert eng.outcomes[rid].status == \
                RequestStatus.DEADLINE_MISSED
        tls = engine_timelines(eng, [running, queued])
        seg_r = assert_complete(tls[running])
        seg_q = assert_complete(tls[queued])
        assert seg_r["total"] == pytest.approx(10.0)
        # the queued request never left the queue: all wall = queue
        assert seg_q["queue"] == pytest.approx(seg_q["total"])

    def test_preemption_timeline_records_victim_and_completes(
            self, model):
        eng = make_engine(model, max_batch=2, num_blocks=5,
                          max_blocks_per_seq=4)
        r1 = eng.add_request(prompt(33, 4), max_new_tokens=6)
        r2 = eng.add_request(prompt(34, 4), max_new_tokens=6,
                             deadline_s=3600.0)
        out = eng.run_to_completion(max_ticks=300)
        assert len(out[r1]) == 6 and len(out[r2]) == 6
        tls = engine_timelines(eng, [r1, r2])
        seg1 = assert_complete(tls[r1])
        assert_complete(tls[r2])
        ev1 = [e["event"] for e in tls[r1]["events"]]
        # r1 (most slack) was the livelock victim; after preemption it
        # re-admits and re-prefills — both visible in the timeline
        assert "preempted" in ev1
        pre = next(e for e in tls[r1]["events"]
                   if e["event"] == "preempted")
        assert "victim_reason" in pre["meta"]
        assert ev1.index("preempted") < len(ev1) - 1
        assert ev1.count("admitted") >= 2
        assert seg1["preempted"] >= 0.0

    def test_shed_and_overload_timelines_complete(self, model):
        eng = make_engine(model, max_batch=1, max_queue=8,
                          queue_high_water=2)
        rids = [eng.add_request(prompt(40 + i, 4), max_new_tokens=3)
                for i in range(6)]
        eng.run_to_completion()
        shed = [r for r in rids
                if eng.outcomes[r].status == RequestStatus.SHED]
        assert shed, "high-water shedding did not trigger"
        for tl in engine_timelines(eng, rids).values():
            assert_complete(tl)

    def test_midflight_reroute_stitched_complete(self, model):
        reps = [make_engine(model) for _ in range(2)]
        router = Router(reps).warmup()
        rid = router.add_request(prompt(50, 6), max_new_tokens=10)
        for _ in range(3):
            router.step()
        victim = router._by_rid[rid].replica_idx
        with inject.armed("serving.crash_at_tick",
                          tick=reps[victim]._ticks + 1):
            router.step()
        out = router.run_to_completion()
        assert len(out[rid]) == 10
        tl = reqtrace.RECORDER.timeline(router.name, rid)
        events = [e["event"] for e in tl["events"]]
        assert events.count("routed") == 2 and "rerouted" in events
        st = reqtrace.stitch(tl)
        seg = assert_complete(st)
        assert seg["rerouted"] > 0
        re = next(e for e in tl["events"] if e["event"] == "rerouted")
        assert re["meta"]["from_replica"] == \
            reps[victim].lifecycle.name
        assert re["meta"]["stranding_outcome"] == RequestStatus.FAILED


# ---------------------------------------------------------------------------
# Loadgen riders: every outcome has a timeline; p99 exemplar decomposition
# ---------------------------------------------------------------------------
class TestLoadgenIntegration:
    def test_every_outcome_has_nonempty_timeline_incl_router_shed(
            self, model):
        """Satellite bugfix regression: router-level SHED requests must
        appear in the reqtrace ring with a timestamped cause — a shed
        storm is diagnosable per request, not just countable."""
        from tools.loadgen import run_load

        rep = make_engine(model, max_batch=2, max_queue=2)
        router = Router([rep]).warmup()
        report = run_load(router, offered_rps=10_000.0, n_requests=16,
                          max_new_tokens=3, seed=3)
        assert report["shed"] > 0, "overload did not shed at the router"
        n_shed_events = 0
        for rid in range(1, report["submitted"] + 1):
            tl = reqtrace.RECORDER.timeline(router.name, rid)
            assert tl is not None and tl["events"], \
                f"router rid {rid} has no timeline"
            assert_complete(tl)
            events = [e["event"] for e in tl["events"]]
            term = tl["events"][-1] if events[-1] == "terminal" else None
            if term and term["meta"]["outcome"] == RequestStatus.SHED:
                assert "shed" in events, "SHED outcome lacks cause event"
                n_shed_events += 1
        assert n_shed_events == report["shed"]

    def test_report_carries_p99_exemplar_decomposition(self, model):
        from tools.loadgen import run_load

        eng = make_engine(model, max_batch=2).warmup()
        report = run_load(eng, offered_rps=200.0, n_requests=8,
                          max_new_tokens=3, seed=1)
        ex = report["p99_ttft_exemplar"]
        assert ex is not None and ex["complete"]
        segs = ex["segments_s"]
        assert set(segs) == set(reqtrace.SEGMENT_BUCKETS)
        assert sum(segs.values()) == pytest.approx(ex["total_s"],
                                                   abs=1e-5)

    def test_trace_out_exports_chrome_and_raw(self, model, tmp_path):
        from tools.loadgen import run_load

        eng = make_engine(model, max_batch=2).warmup()
        prefix = str(tmp_path / "pt" / "rate_8")
        run_load(eng, offered_rps=50.0, n_requests=6, max_new_tokens=3,
                 seed=2, trace_out=prefix, trace_worst_k=3)
        with open(prefix + ".trace.json") as f:
            tracef = json.load(f)
        names = {e["name"] for e in tracef["traceEvents"]}
        assert {"queue", "prefill", "decode"} & names
        assert "serving.prefill" in names or "serving.decode" in names
        with open(prefix + ".reqtrace.json") as f:
            raw = json.load(f)
        assert raw["format"] == "paddle_tpu.reqtrace/1"
        assert 0 < len(raw["timelines"]) <= 3


# ---------------------------------------------------------------------------
# Streams: delivery marks ride the timeline post-terminal
# ---------------------------------------------------------------------------
class TestStreamMarks:
    def test_stream_records_delivery_and_close(self, model):
        eng = make_engine(model)
        rid = eng.add_request(prompt(60, 5), max_new_tokens=4)
        toks = list(eng.stream(rid))
        assert len(toks) == 4
        tl = reqtrace.RECORDER.timeline(eng.reqtrace_scope, rid)
        events = [e["event"] for e in tl["events"]]
        assert "first_delivery" in events
        assert events[-1] == "stream_closed"
        closed = tl["events"][-1]
        assert closed["meta"]["status"] == RequestStatus.FINISHED
        assert closed["meta"]["delivered"] == 4
        # stream marks do not break completeness validation
        assert_complete(tl)


# ---------------------------------------------------------------------------
# Persistence, fleet carry, watchdog hang path
# ---------------------------------------------------------------------------
class TestPersistence:
    def test_dump_and_load_roundtrip(self, model, tmp_path, monkeypatch):
        eng = make_engine(model)
        rid = eng.add_request(prompt(70, 5), max_new_tokens=3)
        eng.run_to_completion()
        live = eng.add_request(prompt(71, 5), max_new_tokens=50)
        eng.step()                      # leave one request mid-flight
        base = str(tmp_path / "reqtrace.json")
        monkeypatch.setenv(reqtrace.RECORD_ENV, base)
        path = reqtrace.dump(reason="test")
        assert path == base + ".r0" and os.path.exists(path)
        payload = reqtrace.load_dump(path)
        assert payload["reason"] == "test"
        by_key = {(t["scope"], t["rid"]): t
                  for t in payload["timelines"]}
        scope = eng.reqtrace_scope
        assert (scope, rid) in by_key
        assert by_key[(scope, live)].get("open") is True
        assert "ttft" in payload["exemplars"]
        eng.drain()

    def test_watchdog_hang_path_dumps_reqtrace(self, model, tmp_path,
                                               monkeypatch):
        from paddle_tpu.distributed.watchdog import Watchdog

        base = str(tmp_path / "hang_reqtrace.json")
        monkeypatch.setenv(reqtrace.RECORD_ENV, base)
        eng = make_engine(model)
        eng.add_request(prompt(80, 5), max_new_tokens=50)
        eng.step()                            # one request mid-flight
        wd = Watchdog(timeout=60.0)           # never started: direct dump
        buf = io.StringIO()
        wd.dump_diagnostics(file=buf)
        text = buf.getvalue()
        assert "request(s) mid-flight" in text
        assert "request-trace record persisted" in text
        assert os.path.exists(base + ".r0")
        eng.drain()

    def test_fleet_snapshot_carries_reqtrace_tail(self, model):
        from paddle_tpu.observability import fleet

        eng = make_engine(model)
        eng.add_request(prompt(90, 5), max_new_tokens=2)
        eng.run_to_completion()
        snap = fleet.local_snapshot()
        assert any(tl["scope"] == eng.reqtrace_scope
                   for tl in snap["reqtrace"])

    def test_fleet_snapshot_truncates_long_live_timelines(self):
        from paddle_tpu.observability.fleet import _truncate_timelines

        long_tl = {"scope": "s", "rid": 1,
                   "events": [{"event": "submitted", "t": 0.0}]
                   + [{"event": "decode_tick", "t": float(i)}
                      for i in range(1, 500)]}
        out = _truncate_timelines([long_tl] * 30, max_timelines=5,
                                  max_events=100)
        assert len(out) == 5
        for tl in out:
            assert len(tl["events"]) == 100
            assert tl["events"][0]["event"] == "submitted"  # anchor kept
            assert tl["truncated"] == 400


# ---------------------------------------------------------------------------
# tools/request_trace.py renderer + CLI
# ---------------------------------------------------------------------------
class TestRequestTraceTool:
    def test_waterfall_text(self, model):
        eng = make_engine(model)
        rid = eng.add_request(prompt(95, 6), max_new_tokens=3)
        eng.run_to_completion()
        tl = reqtrace.RECORDER.timeline(eng.reqtrace_scope, rid)
        text = rt_tool.waterfall(tl)
        assert "submitted" in text and "terminal" in text
        assert "segments:" in text and "WARNING" not in text

    def test_cli_worst_and_chrome_out(self, model, tmp_path,
                                      monkeypatch, capsys):
        eng = make_engine(model)
        for i in range(3):
            eng.add_request(prompt(100 + i, 5), max_new_tokens=3)
        eng.run_to_completion()
        base = str(tmp_path / "rt.json")
        monkeypatch.setenv(reqtrace.RECORD_ENV, base)
        dump_path = reqtrace.dump()
        out = str(tmp_path / "merged.json")
        rc = rt_tool.main(["--dump", dump_path, "--worst", "2",
                           "--out", out])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "segments:" in printed
        with open(out) as f:
            tracef = json.load(f)
        lanes = {e.get("tid") for e in tracef["traceEvents"]
                 if e.get("ph") == "X"}
        assert lanes
        rc = rt_tool.main(["--dump", dump_path, "--list"])
        assert rc == 0


# ---------------------------------------------------------------------------
# ACCEPTANCE: one request chunk-prefilled + preempted + re-routed,
# reconstructed end-to-end and merged with device spans on one clock.
# ---------------------------------------------------------------------------
class TestAcceptance:
    def test_full_lifecycle_reconstruction_with_device_spans(
            self, model, tmp_path):
        # tight replicas: block_size 4 so an 8-token prompt chunk-
        # prefills in 2+ chunks; 5 usable blocks force livelock
        # preemption once two sequences grow
        def mk():
            return make_engine(model, max_batch=2, block_size=4,
                               num_blocks=6, max_blocks_per_seq=5)

        reps = [mk(), mk()]
        router = Router(reps).warmup()
        # keep replica1 out of rotation so contention lands on replica0
        reps[1].lifecycle.to(ReplicaState.DEGRADED, "test: hold back")

        own_trace = not otrace.active()
        if own_trace:
            otrace.clear()
            otrace.activate()
        try:
            victim = router.add_request(prompt(200, 8),
                                        max_new_tokens=8)
            fillers = [router.add_request(prompt(201 + i, 4),
                                          max_new_tokens=8,
                                          deadline_s=3600.0)
                       for i in range(2)]
            vtl = lambda: reqtrace.RECORDER.timeline(router.name, victim)

            # run until the victim (most deadline slack) is preempted
            # on replica0, then re-admitted (re-prefill visible)
            def stitched_events():
                return [e["event"] for e in
                        reqtrace.stitch(vtl())["events"]]

            for _ in range(200):
                router.step()
                ev = stitched_events()
                if "preempted" in ev and ev.count("admitted") >= 2:
                    break
            else:
                pytest.fail("victim never preempted+readmitted: "
                            + str(stitched_events()))

            # bring replica1 back, crash replica0 mid-flight → re-route
            reps[1].recover("test: rejoin")
            rr = router._by_rid[victim]
            assert rr.replica_idx == 0
            with inject.armed("serving.crash_at_tick",
                              tick=reps[0]._ticks + 1):
                router.step()
            out = router.run_to_completion()
            assert len(out[victim]) == 8
        finally:
            if own_trace:
                otrace.deactivate()
        spans = otrace.drain() if own_trace else []

        st = reqtrace.stitch(vtl())
        ev = [e["event"] for e in st["events"]]
        scopes = {e["scope"] for e in st["events"]}
        # ALL THREE behaviors on the one request, across both replicas
        assert ev.count("prefill_chunk") >= 2
        assert "preempted" in ev and "rerouted" in ev
        assert {reps[0].lifecycle.name,
                reps[1].lifecycle.name} <= scopes
        seg = assert_complete(st)
        for b in ("queue", "prefill", "decode", "preempted", "rerouted"):
            assert seg[b] >= 0.0
        assert seg["preempted"] > 0 and seg["rerouted"] > 0
        # total == router-level submit→terminal wall time
        oc_wall = (st["events"][-1]["t"] - st["events"][0]["t"])
        assert seg["total"] == pytest.approx(oc_wall)

        # merged chrome trace: request lane + device spans, one clock
        out_path = str(tmp_path / "acceptance_trace.json")
        rt_tool.export(out_path, [st],
                       spans=rt_tool.serving_spans(spans))
        with open(out_path) as f:
            tracef = json.load(f)
        evs = tracef["traceEvents"]
        req_x = [e for e in evs if e.get("ph") == "X"
                 and e.get("pid") == 1]
        dev_x = [e for e in evs if e.get("ph") == "X"
                 and e.get("pid") == 0]
        assert req_x and dev_x
        assert any(e["name"].startswith("serving.") for e in dev_x)
        # one clock: the request lane overlaps the device-span window
        dev_lo = min(e["ts"] for e in dev_x)
        dev_hi = max(e["ts"] + e.get("dur", 0) for e in dev_x)
        req_lo = min(e["ts"] for e in req_x)
        req_hi = max(e["ts"] + e.get("dur", 0) for e in req_x)
        assert req_lo < dev_hi and dev_lo < req_hi, \
            "request lane and device spans do not share a clock"

        # the waterfall renders the whole causal story
        text = rt_tool.waterfall(st)
        for needle in ("prefill_chunk", "preempted", "rerouted",
                       "segments:"):
            assert needle in text
