"""Round-2 coverage batch C: static Engine, quantization, auto_tuner,
hybrid sync utils, TensorArray/SelectedRows, and the 3D hybrid
(dp x pp x mp) pipeline composition.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import mesh as mesh_mod


@pytest.fixture
def dp_mesh():
    old = mesh_mod._global_mesh
    mesh = mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
    yield mesh
    mesh_mod._global_mesh = old


@pytest.fixture
def hybrid3d_mesh():
    old = mesh_mod._global_mesh
    mesh = mesh_mod.set_mesh(
        mesh_mod.build_mesh({"dp": 2, "pp": 2, "mp": 2}))
    yield mesh
    mesh_mod._global_mesh = old


class TestEngine:
    def test_fit_evaluate(self, dp_mesh):
        import paddle_tpu.distributed as dist
        from paddle_tpu.io import Dataset

        class Ds(Dataset):
            def __init__(self, n=64):
                rng = np.random.RandomState(0)
                self.x = rng.randn(n, 16).astype(np.float32)
                self.y = (self.x @ rng.randn(16, 4)).astype(np.float32)

            def __len__(self):
                return len(self.x)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 4))
        opt = paddle.optimizer.SGD(learning_rate=1e-2,
                                   parameters=net.parameters())
        engine = dist.Engine(
            net, loss=lambda out, y: paddle.ops.mean((out - y) ** 2),
            optimizer=opt)
        hist = engine.fit(Ds(), epochs=3, batch_size=16)
        assert hist[-1] < hist[0]
        res = engine.evaluate(Ds(), batch_size=16)
        assert res["loss"] == pytest.approx(hist[-1], rel=0.5)
        preds = engine.predict(Ds(), batch_size=16)
        assert preds.shape == (64, 4)


class TestQuantization:
    def test_weight_quantize_round_trip(self):
        from paddle_tpu.quantization import (weight_dequantize,
                                             weight_quantize)
        w = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32))
        q, scale = weight_quantize(w)
        assert str(q._data.dtype) == "int8"
        deq = weight_dequantize(q, scale)
        err = np.max(np.abs(deq.numpy() - w.numpy()))
        assert err < np.max(np.abs(w.numpy())) / 100

    def test_ptq_swaps_linears(self):
        from paddle_tpu.quantization import PTQ, QuantedLinear
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        ref = net(x).numpy()
        qnet = PTQ().quantize(net)
        kinds = [type(l).__name__ for _, l in qnet.named_sublayers()]
        assert kinds.count("QuantedLinear") == 2
        out = qnet(x).numpy()
        assert np.max(np.abs(out - ref)) < 0.1
        # original model untouched
        assert [type(l).__name__ for _, l in net.named_sublayers()
                ].count("QuantedLinear") == 0

    def test_qat_trains_with_ste(self):
        from paddle_tpu.quantization import QAT
        paddle.seed(1)
        net = nn.Linear(8, 4)
        fp_out = None
        x = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32))
        fp_out = net(x).numpy()
        QAT().quantize(net)
        assert getattr(net, "_qat_wrapped", False)   # root layer wrapped
        qat_out = net(x).numpy()
        # fake-quant actually changes the forward (weights are rounded)
        assert not np.allclose(qat_out, fp_out, atol=1e-7)
        assert np.max(np.abs(qat_out - fp_out)) < 0.05
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        y = paddle.to_tensor(np.random.randn(16, 4).astype(np.float32))
        losses = []
        for _ in range(5):
            loss = paddle.ops.mean((net(x) - y) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]   # STE lets fp weights learn

    def test_engine_adamw_momentum_state(self, dp_mesh):
        """Engine must honor the optimizer class (AdamW state threads
        through), not silently degrade to SGD."""
        import paddle_tpu.distributed as dist
        paddle.seed(3)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        engine = dist.Engine(
            net, loss=lambda o, y: paddle.ops.mean((o - y) ** 2),
            optimizer=opt).prepare()
        pa = [p._data for p in engine._params]
        state = engine._init_opt_state(pa)
        assert len(state) == 3       # (t, masters, per-param state dicts)
        assert all("moment1" in st or "m" in str(st.keys()).lower()
                   or len(st) >= 2 for st in state[2])  # adam moments exist
        import jax.numpy as jnp
        x = jnp.zeros((4, 8)); y = jnp.zeros((4, 4))
        lr = jnp.asarray(1e-2, jnp.float32)
        loss, new_p, new_state = engine._train_step(pa, state, lr, x, y)
        assert int(new_state[0]) == 1


class TestAutoTuner:
    def test_candidate_and_prune(self):
        from paddle_tpu.distributed.auto_tuner.tuner import (
            candidate_configs, prune)
        cands = candidate_configs(8, axes=("dp", "mp"))
        assert {(c["dp"], c["mp"]) for c in cands} == \
            {(1, 8), (2, 4), (4, 2), (8, 1)}
        kept = prune(cands, {"num_heads": 4, "hidden_size": 64,
                             "num_layers": 2})
        assert all(c["mp"] in (1, 2, 4) for c in kept)

    def test_tune_picks_fastest(self):
        from paddle_tpu.distributed import auto_tuner

        def probe(cfg):
            if cfg["mp"] == 8:
                raise RuntimeError("invalid layout")
            return 1.0 / cfg["dp"]      # favor max dp

        best = auto_tuner.tune(probe, n_devices=8, axes=("dp", "mp"))
        assert best["dp"] == 8 and best["mp"] == 1

    def test_cost_model_prunes_without_execution(self):
        # reference auto_parallel/static/cost_model.py contract: configs
        # whose estimated per-chip HBM exceeds the cluster budget are
        # rejected BEFORE any trial run
        from paddle_tpu.distributed.auto_tuner.cost_model import (
            ClusterSpec, estimate, prune_by_cost)
        from paddle_tpu.distributed.auto_tuner.tuner import AutoTuner

        model_cfg = {"num_layers": 32, "hidden_size": 4096,
                     "num_heads": 32, "vocab_size": 32000,
                     "seq_len": 2048}
        train_cfg = {"global_batch": 8, "micro_batch": 1,
                     "recompute": True}
        # 7B-class params on 16GB chips: pure-dp replication cannot fit
        est_dp = estimate(model_cfg, {"dp": 8}, train_cfg,
                          ClusterSpec.v5e())
        assert not est_dp["fits"] and "OOM" in est_dp["reasons"][0]
        est_mp = estimate(model_cfg, {"mp": 4, "pp": 2}, train_cfg,
                          ClusterSpec.v5e())
        assert est_mp["mem_bytes"] < est_dp["mem_bytes"]

        probed = []

        def probe(cfg):
            probed.append(dict(cfg))
            return 1.0

        tuner = AutoTuner(probe, model_cfg, train_cfg,
                          cluster=ClusterSpec.v5e())
        best = tuner.tune(n_devices=8, axes=("dp", "mp", "pp"))
        # every pure-dp (replicated-weights) config was pruned unexecuted
        assert all(c["mp"] * c["pp"] > 1 for c in probed)
        pruned = [r for r in tuner.results if "pruned" in r]
        assert any(r["dp"] == 8 for r in pruned)
        assert all("OOM" in r["pruned"] for r in pruned)
        assert best["mp"] * best["pp"] > 1

        kept, rejected = prune_by_cost(
            [{"dp": 8}, {"mp": 4, "pp": 2}, {"mp": 8}], model_cfg,
            train_cfg, ClusterSpec.v5e())
        assert {"dp": 8} not in kept
        # survivors come back ordered by estimated step time
        assert len(kept) >= 1 and all("pruned" in r for r in rejected)


class TestSyncUtils:
    def test_broadcasts_and_fused_allreduce(self, dp_mesh):
        from paddle_tpu.distributed.fleet.utils import (
            broadcast_dp_parameters, fused_allreduce_gradients)
        net = nn.Linear(8, 8)
        broadcast_dp_parameters(net)
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        paddle.ops.mean(net(x) ** 2).backward()
        fused_allreduce_gradients(list(net.parameters()))
        for p in net.parameters():
            assert p.grad is not None


class TestContainers:
    def test_tensor_array(self):
        from paddle_tpu.framework import TensorArray
        ta = TensorArray()
        for i in range(3):
            ta.write(i, paddle.to_tensor(
                np.full((2,), float(i), np.float32)))
        assert len(ta) == 3
        st = ta.stack()
        assert st.shape == [3, 2]
        np.testing.assert_array_equal(np.asarray(st._data)[:, 0],
                                      [0, 1, 2])
        cc = ta.concat()
        assert cc.shape == [6]

    def test_selected_rows(self):
        from paddle_tpu.framework import SelectedRows
        sr = SelectedRows([1, 3, 1],
                          np.array([[1.0, 1], [2, 2], [3, 3]], np.float32),
                          height=5)
        dense = sr.to_dense().numpy()
        np.testing.assert_array_equal(dense[1], [4, 4])   # 1+3 merged
        np.testing.assert_array_equal(dense[3], [2, 2])
        np.testing.assert_array_equal(dense[0], [0, 0])
        merged = sr.merge()
        assert merged.rows.shape[0] == 2


class TestHybrid3D:
    @pytest.mark.slow
    def test_pp_tp_dp_pipeline(self, hybrid3d_mesh):
        """2-stage pipeline of TP-2 GPT blocks over a dp2 x pp2 x mp2 mesh
        — the composed hybrid story (SURVEY §3.5 call stack).

        Slow-marked (~8s, 870s tier-1 budget): the hybrid composition
        stays in tier-1 via test_compose_rpc's zero2+recompute+tp and
        test_pipeline_ir's (data, pp) mesh GPT training."""
        import paddle_tpu.distributed.fleet as fleet_pkg
        from paddle_tpu.distributed.fleet import (LayerDesc, PipelineLayer,
                                                  PipelineParallel)
        from paddle_tpu.models.gpt import GPTBlock, GPTConfig

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=2, max_seq_len=16,
                        use_flash_attention=False, mp_degree=2)

        pl = PipelineLayer(
            layers=[LayerDesc(GPTBlock, cfg) for _ in range(4)],
            num_stages=2,
            loss_fn=lambda o, y: paddle.ops.mean((o - y) ** 2))
        strategy = fleet_pkg.DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": 2,
                                     "schedule_mode": "1F1B"}
        pp = PipelineParallel(pl, None, strategy)
        assert pp._run is not None, "TP blocks must stack for SPMD PP"

        x = paddle.to_tensor(np.random.randn(4, 16, 32).astype(np.float32))
        y = paddle.to_tensor(
            np.random.randn(4, 16, 32).astype(np.float32) * 0.1)
        loss = pp.forward_backward_pipeline((x, y))
        ref = float(paddle.ops.mean((pl(x) - y) ** 2).numpy())
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-4)

        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=pl.parameters())
        losses = [float(pp.train_batch((x, y), opt).numpy())
                  for _ in range(4)]
        assert losses[-1] < losses[0]
