"""Chunked checkpoint format tests (reference framework/io.py:743 —
large-pickle chunking + protocol handling)."""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import io as fio


class TestChunkedFormat:
    def test_segment_roundtrip_mixed_dtypes(self, tmp_path):
        import jax.numpy as jnp
        big_f32 = paddle.to_tensor(
            np.arange(2 * fio._SEG_THRESHOLD // 4, dtype=np.float32))
        big_bf16 = paddle.Tensor(
            jnp.arange(fio._SEG_THRESHOLD, dtype=jnp.bfloat16))
        small = paddle.to_tensor(np.asarray([1.5, 2.5], np.float32))
        state = {"w": big_f32, "h": big_bf16, "b": small,
                 "step": 7, "name": "ckpt"}
        path = str(tmp_path / "chunked.pdparams")
        fio.save(state, path)
        with open(path, "rb") as f:
            assert f.read(8) == fio._MAGIC2   # round-9 verified format
        out = fio.load(path)
        np.testing.assert_array_equal(np.asarray(out["w"]._data),
                                      np.asarray(big_f32._data))
        assert str(out["h"].dtype) == "bfloat16"
        np.testing.assert_array_equal(
            np.asarray(out["h"]._data.astype(jnp.float32)),
            np.asarray(big_bf16._data.astype(jnp.float32)))
        np.testing.assert_array_equal(np.asarray(out["b"]._data),
                                      [1.5, 2.5])
        assert out["step"] == 7 and out["name"] == "ckpt"

    def test_legacy_plain_pickle_still_loads(self, tmp_path):
        path = str(tmp_path / "legacy.pdparams")
        legacy = {"w": np.asarray([[1.0, 2.0]], np.float32),
                  "h": {fio._BF16_TAG: True,
                        "data": np.asarray([3.0], np.float32)}}
        with open(path, "wb") as f:
            pickle.dump(legacy, f, protocol=4)
        out = fio.load(path)
        np.testing.assert_array_equal(np.asarray(out["w"]._data),
                                      [[1.0, 2.0]])
        assert str(out["h"].dtype) == "bfloat16"

    def test_protocol_pinned(self, tmp_path):
        with pytest.raises(ValueError, match="protocol"):
            fio.save({"a": 1}, str(tmp_path / "x"), protocol=1)
        fio.save({"a": 1}, str(tmp_path / "y"), protocol=2)
        assert fio.load(str(tmp_path / "y"))["a"] == 1

    @pytest.mark.slow
    def test_over_4gb_state_dict(self, tmp_path):
        """A >4 GB state_dict streams through without any pickle frame
        near the 4 GB limit (reference io.py:743 chunking contract).

        slow: materialising + round-tripping 4.5 GiB costs ~2 min on a
        1-core CI box; the chunk-boundary contract itself is covered at
        small sizes by the rest of this class."""
        gib = 1 << 30
        state = {
            "embed": paddle.to_tensor(
                np.zeros(gib // 2, np.float32)),      # 2.0 GiB
            "ffn": paddle.to_tensor(
                np.zeros(gib // 2, np.float32)),      # 2.0 GiB
            "head": paddle.to_tensor(
                np.full(gib // 8, 3.0, np.float32)),  # 0.5 GiB
        }
        path = str(tmp_path / "big.pdparams")
        fio.save(state, path)
        assert os.path.getsize(path) > 4 * gib
        out = fio.load(path)
        assert out["embed"].shape == [gib // 2]
        assert float(out["head"]._data[0]) == 3.0
        assert float(out["ffn"]._data[-1]) == 0.0
        del state, out
