"""Round-3 advisor/verdict fix tests: top_p threshold, llm_int8 STE
gradient, ASP decorate fallback, correlation kernel_size>1, static.nn
embedding dtypes."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestTopPThreshold:
    def test_threshold_excludes_low_prob_tokens(self):
        # row: p=0.9 nucleus over [0.5, 0.3, 0.15, 0.05]; threshold 0.1
        # must also drop the 0.05 token even though p would admit it
        probs = np.asarray([[0.5, 0.3, 0.15, 0.05]], np.float32)
        ps = np.asarray([0.999], np.float32)
        seen = set()
        for seed in range(40):
            _, ids = paddle.ops.top_p_sampling(
                paddle.to_tensor(probs), paddle.to_tensor(ps),
                threshold=0.1, seed=seed)
            seen.add(int(np.asarray(ids.numpy()).ravel()[0]))
        assert 3 not in seen        # below threshold: never sampled
        assert seen <= {0, 1, 2}

    def test_no_threshold_unchanged(self):
        probs = np.asarray([[0.6, 0.4]], np.float32)
        ps = np.asarray([1.0], np.float32)
        _, ids = paddle.ops.top_p_sampling(
            paddle.to_tensor(probs), paddle.to_tensor(ps), seed=0)
        assert int(np.asarray(ids.numpy()).ravel()[0]) in (0, 1)


class TestLlmInt8Gradient:
    def test_activation_gradient_flows_through_int8_path(self):
        import jax.numpy as jnp
        from paddle_tpu.quantization import llm_int8_linear
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        x.stop_gradient = False
        w8 = paddle.to_tensor(
            rng.randint(-127, 127, (8, 5)).astype(np.int8))
        scale = paddle.to_tensor(np.full((5,), 0.01, np.float32))
        out = llm_int8_linear(x, w8, weight_scale=scale, threshold=6.0)
        paddle.ops.mean(out ** 2).backward()
        g = np.asarray(x.grad._data)
        # STE: every activation column (none are outliers here) carries
        # gradient; before the fix round()'s zero derivative killed it
        assert np.abs(g).max() > 1e-6
        assert np.count_nonzero(np.abs(g).sum(axis=0)) == 8

    def test_forward_matches_int8_math(self):
        from paddle_tpu.quantization import llm_int8_linear
        rng = np.random.RandomState(1)
        x = rng.randn(2, 4).astype(np.float32)
        w8 = rng.randint(-127, 127, (4, 3)).astype(np.int8)
        out = llm_int8_linear(paddle.to_tensor(x), paddle.to_tensor(w8),
                              threshold=6.0)
        # exact path reproducible in numpy
        row_scale = np.maximum(np.abs(x).max(-1, keepdims=True) / 127.0,
                               1e-8)
        aq = np.clip(np.round(x / row_scale), -128, 127)
        ref = (aq @ w8.astype(np.float32)) * row_scale \
            + (x - x) @ w8.astype(np.float32)
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   rtol=1e-5, atol=1e-5)


class TestAspDecorateFallback:
    def test_masks_reapplied_without_parameter_list(self):
        import jax.numpy as jnp
        from paddle_tpu.incubate import asp
        from paddle_tpu import nn
        paddle.seed(5)
        asp.ASPHelper.reset()
        model = nn.Linear(8, 8)
        asp.prune_model(model, n=2, m=4)
        w0 = np.asarray(model.weight.numpy())
        assert (w0 == 0).sum() >= w0.size // 2

        class OddOptimizer:
            # stores params under a nonstandard attribute
            def __init__(self, params):
                self.my_params = list(params)

            def step(self):
                for p in self.my_params:
                    p._swap_payload(p._data + 1.0)  # breaks sparsity

        opt = asp.decorate(OddOptimizer(model.parameters()))
        opt.step()
        w1 = np.asarray(model.weight.numpy())
        # the fallback over registered masks re-zeroed pruned entries
        assert ((w1 == 0) == (w0 == 0)).all()


class TestCorrelationKernelSize:
    def test_k3_matches_box_filtered_k1(self):
        from paddle_tpu.vision.ops import correlation
        rng = np.random.RandomState(2)
        x1 = rng.randn(1, 3, 10, 10).astype(np.float32)
        x2 = rng.randn(1, 3, 10, 10).astype(np.float32)
        out = correlation(paddle.to_tensor(x1), paddle.to_tensor(x2),
                          pad_size=3, kernel_size=3, max_displacement=2,
                          stride1=1, stride2=1)
        arr = np.asarray(out.numpy())
        assert arr.shape[1] == 25          # (2*2+1)^2 displacements

        # brute-force reference at one position/displacement
        p = 3
        x1p = np.pad(x1, ((0, 0), (0, 0), (p, p), (p, p)))
        x2p = np.pad(x2, ((0, 0), (0, 0), (p, p), (p, p)))
        di, dj = -2, 1
        k_idx = (di + 2) * 5 + (dj + 2)
        i = j = 4  # output position -> padded center (border=3)
        ci, cj = i + 3, j + 3
        acc = 0.0
        for u in (-1, 0, 1):
            for v in (-1, 0, 1):
                acc += (x1p[0, :, ci + u, cj + v]
                        * x2p[0, :, ci + di + u, cj + dj + v]).mean()
        np.testing.assert_allclose(arr[0, k_idx, i, j], acc / 9.0,
                                   rtol=1e-5)

    def test_pad_too_small_raises(self):
        from paddle_tpu.vision.ops import correlation
        x = paddle.to_tensor(np.zeros((1, 1, 8, 8), np.float32))
        with pytest.raises(ValueError, match="pad_size"):
            correlation(x, x, pad_size=2, kernel_size=3,
                        max_displacement=2, stride1=1, stride2=1)


class TestStaticNnEmbeddingDtype:
    def test_non_float32_dtypes(self):
        from paddle_tpu.static import nn as snn
        ids = paddle.to_tensor(np.asarray([[0, 2], [1, 3]], np.int64))
        # float64 additionally needs JAX_ENABLE_X64 (jax truncates it to
        # f32 otherwise), so the portable set is fp32/bf16/fp16
        for dt in ("float32", "bfloat16", "float16"):
            out = snn.embedding(ids, size=(4, 6), dtype=dt)
            assert str(out.dtype) == dt
            assert out.shape == [2, 2, 6]
        # without x64 mode a silent f64->f32 truncation must be an error
        import jax
        if not jax.config.jax_enable_x64:
            with pytest.raises(NotImplementedError, match="X64"):
                snn.embedding(ids, size=(4, 6), dtype="float64")
