"""TestDistBase-style multi-process end-to-end training parity.

The framework's core promise — same model, same data, same losses, whether
the mesh axes live in one process or across N real processes — proven by
actually forking trainer processes, exactly like the reference's
workhorse distributed test (test/legacy_test/test_dist_base.py:952
TestDistBase._run_cluster: fork trainers, train, compare losses against
the single-process run; strategy scripts under test/collective/fleet/).

Every strategy goes through the real launcher + ``init_parallel_env``
(jax.distributed over Gloo CPU) + ``fleet.init``, then trains 6 steps on
fixed data (the loss must descend, so parity is a statement about
fwd+bwd+update, not about noise). Per-strategy training paths:

* dp / dp_sharding / dp_mp — ``fleet.distributed_model`` ->
  ``fleet.distributed_optimizer`` -> eager loss.backward()/opt.step()
* dp_pp — ``fleet.distributed_model`` (PipelineParallel) ->
  ``fleet.distributed_optimizer`` -> ``train_batch`` (SPMD 1F1B)
* dp_sep — ``ring_flash_attention`` over the sep axis with a hand-rolled
  SGD step (the fleet wrappers carry no sep-specific model logic; the
  axis' cross-process claim is the ring collective's fwd+bwd itself)

This harness caught a real bug on its first run: TP weight init used
Python's per-process-randomized ``hash()`` in the RNG tracker's lazy seed
derivation, giving every process different weights (fixed in
fleet/mpu/random.py).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_train_worker.py")
DRILL_WORKER = os.path.join(REPO, "tests", "fleet_drill_worker.py")
CROSSRANK_WORKER = os.path.join(REPO, "tests", "crossrank_drill_worker.py")
FAULT_WORKER = os.path.join(REPO, "tests", "fault_drill_worker.py")


def _clean_env():
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""   # skip the TPU register hook
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""              # one CPU device per process
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _free_port_pair():
    import socket
    for _ in range(50):
        s1 = socket.socket()
        s1.bind(("127.0.0.1", 0))
        port = s1.getsockname()[1]
        s2 = socket.socket()
        try:
            s2.bind(("127.0.0.1", port + 1))
        except OSError:
            continue
        finally:
            s1.close()
            s2.close()
        return port
    raise RuntimeError("no consecutive free port pair found")


def _read_losses(outdir, strategy, rank):
    with open(os.path.join(outdir, f"losses.{strategy}.r{rank}.json")) as f:
        return json.load(f)


def _run_single(outdir, strategy="single", virtual_devices=1):
    """One PROCESS; `virtual_devices` > 1 puts the same mesh axes on a
    virtual device mesh instead of across processes."""
    os.makedirs(outdir, exist_ok=True)
    env = _clean_env()
    if virtual_devices > 1:
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                            f"{virtual_devices}")
    proc = subprocess.run(
        [sys.executable, WORKER, strategy, str(outdir)],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return _read_losses(outdir, strategy, 0)["losses"]


def _run_cluster(outdir, strategy, nproc):
    """Fork `nproc` trainer processes through the real launcher."""
    port = _free_port_pair()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", str(nproc),
         "--master", f"127.0.0.1:{port}", WORKER, strategy, str(outdir)],
        env=_clean_env(), cwd=REPO, capture_output=True, text=True,
        timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    per_rank = [_read_losses(outdir, strategy, r)["losses"]
                for r in range(nproc)]
    # the loss is replicated state: every rank must report the same curve
    for r in range(1, nproc):
        np.testing.assert_allclose(per_rank[r], per_rank[0], rtol=1e-6,
                                   err_msg=f"rank {r} diverged from rank 0")
    return per_rank[0]


@pytest.fixture(scope="module")
def baseline(tmp_path_factory):
    outdir = tmp_path_factory.mktemp("single")
    losses = _run_single(outdir)
    assert losses[-1] < losses[0] - 0.5, f"baseline did not train: {losses}"
    return losses


@pytest.mark.parametrize("strategy,nproc", [
    ("dp", 2),
    # 4 real processes cost ~50s of spawn+compile on a 1-core box; the
    # 2-process run keeps cross-process parity in tier-1 and the
    # sharding math is covered in-process by the auto_fsdp variant below
    pytest.param("dp_sharding", 4, marks=pytest.mark.slow),
])
def test_multiproc_training_loss_parity(baseline, strategy, nproc,
                                        tmp_path):
    """N real processes train to the same loss curve as one process."""
    losses = _run_cluster(tmp_path, strategy, nproc)
    np.testing.assert_allclose(
        losses, baseline, rtol=2e-4, atol=2e-4,
        err_msg=f"{strategy} ({nproc} processes) diverged from the "
                f"single-process baseline")


@pytest.mark.parametrize("strategy", ["auto_tp", "auto_fsdp"])
def test_auto_spmd_matches_single_process_baseline(baseline, strategy,
                                                   tmp_path):
    """The SPMD sharding-propagation subsystem (distributed.spmd): the
    SAME plain GPT auto-sharded over a (data, tp) / (data, fsdp) mesh
    — no fleet parallel layers — trains to the single-process loss
    curve. The worker additionally asserts zero replicate-fallback
    ops."""
    losses = _run_single(tmp_path, strategy, virtual_devices=4)
    np.testing.assert_allclose(
        losses, baseline, rtol=2e-4, atol=2e-4,
        err_msg=f"{strategy} (virtual 4-device mesh) diverged from the "
                f"single-process baseline")


@pytest.mark.slow
@pytest.mark.parametrize("strategy", ["auto_tp", "auto_fsdp"])
def test_auto_spmd_multiproc_matches_baseline(baseline, strategy,
                                              tmp_path):
    """Auto-sharded training across 4 REAL processes == the
    single-process baseline — the same cross-process claim the fleet
    strategies make, now for the propagation subsystem. Together with
    test_gpt_auto_shard_matches_fleet_tp_same_weights (tests/test_spmd)
    this closes auto == fleet-TP == single-device."""
    losses = _run_cluster(tmp_path, strategy, 4)
    np.testing.assert_allclose(
        losses, baseline, rtol=2e-4, atol=2e-4,
        err_msg=f"{strategy} (4 processes) diverged from the "
                f"single-process baseline")


def test_fleet_observability_drill(tmp_path):
    """The fleet-observability acceptance drill, in the REAL 4-process
    harness (tests/fleet_drill_worker.py): an injected slow rank is
    flagged by the beacon (correct rank, within 2 windows) on EVERY
    rank, cross-rank ``fleet.snapshot`` gathers genuinely distinct
    per-rank payloads, ``clock_sync`` hands every rank the offset
    table — then an injected collective desync hangs the job, every
    rank's watchdog persists its flight-recorder ring, and the
    out-of-band diff names the desynced rank + sequence number before
    aborting."""
    import re

    port = _free_port_pair()
    env = _clean_env()
    flight_base = os.path.join(str(tmp_path), "flight.json")
    env["PADDLE_TPU_FLIGHT_RECORD"] = flight_base
    env["PADDLE_TPU_BEACON_WINDOW"] = "2"
    env["DRILL_TARGET_RANK"] = "2"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4",
         "--master", f"127.0.0.1:{port}", DRILL_WORKER, str(tmp_path)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    out = proc.stdout + proc.stderr

    # phase 2 hung the job on purpose; the watchdogs must have killed it
    assert proc.returncode != 0, f"drill did not abort:\n{out}"

    # phase 1: every rank flagged rank 2 within 2 beacon windows, with
    # the dominant bucket of an un-instrumented host sleep (idle), and
    # the cross-rank snapshot really gathered 4 distinct processes
    for r in range(4):
        path = os.path.join(str(tmp_path), f"drill.r{r}.json")
        assert os.path.exists(path), f"rank {r} phase-1 missing:\n{out}"
        with open(path) as f:
            res = json.load(f)
        assert res["slowest_rank"] == 2, res
        assert res["slowest_score"] > 0.2, res
        assert res["first_flagged_window"] is not None \
            and res["first_flagged_window"] <= 2, res
        assert res["dominant_bucket"] == "idle", res
        assert sorted(res["snapshot_ranks"]) == [0, 1, 2, 3], res
        assert len(set(res["snapshot_pids"])) == 4, res
        assert res["clock_world"] == 4, res
        assert sorted(res["clock_offsets"]) == ["0", "1", "2", "3"], res
    assert "[fleet] straggler: rank 2" in out, out

    # phase 2: a flight record per rank, and the watchdog diff named
    # the desynced rank + its sequence number
    for r in range(4):
        assert os.path.exists(f"{flight_base}.r{r}"), \
            f"rank {r} flight record missing:\n{out}"
    assert re.search(r"status=desync rank=2 seq=\d+", out), out
    assert "rank 2 moved past seq" in out, out


def test_crossrank_program_diff_drill(tmp_path):
    """The TPU45x static cross-rank diff, in the REAL 4-process harness
    (tests/crossrank_drill_worker.py): one launch records program dumps
    into two bases — a clean phase where every rank traces the same
    step and launches the same eager collectives, and a divergent phase
    where DRILL_TARGET_RANK=2 takes an injected branch (extra op in its
    traced step, plus a program label only it compiles). The real
    ``tpulint --cross-rank`` CLI must then (a) name rank 2 and the
    first divergent sequence number from the dumps alone, exit 1, and
    (b) report zero findings on the clean base, exit 0."""
    import re

    port = _free_port_pair()
    env = _clean_env()
    env["DRILL_TARGET_RANK"] = "2"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4",
         "--master", f"127.0.0.1:{port}", CROSSRANK_WORKER,
         str(tmp_path)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"drill job failed:\n{out}"

    clean_base = os.path.join(str(tmp_path), "progs_clean")
    div_base = os.path.join(str(tmp_path), "progs_div")
    for r in range(4):
        assert os.path.exists(f"{clean_base}.r{r}"), \
            f"rank {r} clean dump missing:\n{out}"
        assert os.path.exists(f"{div_base}.r{r}"), \
            f"rank {r} divergent dump missing:\n{out}"

    lint_env = _clean_env()
    # divergent base: the CLI names the rank and first divergent seq
    lint = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "--cross-rank",
         div_base],
        env=lint_env, cwd=REPO, capture_output=True, text=True,
        timeout=120)
    assert lint.returncode == 1, lint.stdout + lint.stderr
    assert "TPU454" in lint.stdout, lint.stdout
    assert "TPU451" in lint.stdout, lint.stdout
    assert re.search(r"rank=2 seq=\d+", lint.stdout), lint.stdout

    # clean base: dp-style launch with identical programs + identical
    # collective streams — zero findings
    lint = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "--cross-rank",
         clean_base],
        env=lint_env, cwd=REPO, capture_output=True, text=True,
        timeout=120)
    assert lint.returncode == 0, lint.stdout + lint.stderr
    assert "all ranks agree" in lint.stdout, lint.stdout


# ---------------------------------------------------------------------------
# Self-healing fleet: the fault-drill matrix (tests/fault_drill_worker.py)
# ---------------------------------------------------------------------------
def _assert_no_drill_orphans(out):
    """Every drill must end with ALL ranks terminal — a wedged worker
    surviving its launcher is exactly the failure mode the abort plane
    exists to prevent."""
    import glob
    import time as _time

    deadline = _time.monotonic() + 10.0
    while _time.monotonic() < deadline:
        alive = []
        for p in glob.glob("/proc/[0-9]*/cmdline"):
            try:
                with open(p, "rb") as f:
                    cmd = f.read().decode(errors="replace")
            except OSError:
                continue
            if "fault_drill_worker.py" in cmd:
                alive.append(p)
        if not alive:
            return
        _time.sleep(0.5)
    raise AssertionError(f"orphaned drill workers: {alive}\n{out}")


def _run_fault_drill(tmp_path, mode, target, extra_env=None,
                     max_restarts=0):
    port = _free_port_pair()
    env = _clean_env()
    env["PADDLE_TPU_FLIGHT_RECORD"] = os.path.join(str(tmp_path),
                                                   "flight.json")
    env["PADDLE_TPU_GOODPUT"] = os.path.join(str(tmp_path), "goodput.json")
    env["DRILL_TARGET_RANK"] = str(target)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4", "--master", f"127.0.0.1:{port}",
         "--max_restarts", str(max_restarts), "--abort_grace", "15",
         FAULT_WORKER, mode, str(tmp_path)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    out = proc.stdout + proc.stderr
    _assert_no_drill_orphans(out)
    return proc.returncode, out


def test_fault_crash_consensus_rewind_drill(tmp_path):
    """The self-healing acceptance drill, one launch end-to-end: rank 3
    SIGKILLs itself at step 6 → the survivors' collective-timeout plane
    detects the blocked all_reduce within FLAGS_collective_timeout_s,
    the cross-rank flight diff names the dead rank (it left no dump),
    every survivor exits EXIT_COLLECTIVE_TIMEOUT (coordinated abort, not
    an indefinite block) → the launcher group-restarts → every rank
    resumes from the CONSENSUS step 3 (rank 1 stopped saving after step
    3, so 3 is the newest step on every manifest) → the recomputed steps
    are billed to the goodput ``rewind`` bucket → the final weights on
    every rank equal the closed-form uninterrupted run."""
    import re

    rc, out = _run_fault_drill(tmp_path, "crash", target=3,
                               max_restarts=1)
    assert rc == 0, f"crash drill did not recover:\n{out}"

    # detection: the abort plane, not the scheduler, caught the death
    assert "rank.crash_at_step fired at step 6" in out, out
    assert re.search(
        r"collective seq=\d+ op=gather_rows .*open for .*"
        r"FLAGS_collective_timeout_s", out), out
    # the diff names the SIGKILLed rank from its ABSENT dump
    assert re.search(r"status=stall rank=3 seq=\d+", out), out
    assert "rank 3 never issued seq" in out, out
    # the launcher saw the verdict codes, not a SIGTERM reap
    assert "COLLECTIVE_TIMEOUT" in out, out
    assert "signal SIGKILL" in out, out
    # consensus: all four relaunched ranks agreed on step 3
    assert out.count("consensus resume step=3") == 4, out

    # closed-form uninterrupted run (must match fault_drill_worker.py)
    D, LR, STEPS, WORLD = 4, 0.1, 10, 4
    base = np.arange(1, D + 1, dtype=np.float64)
    w = np.zeros(D)
    for s in range(1, STEPS + 1):
        mean_g = np.mean([base * (r + 1) * 0.001 * ((s % 5) + 1)
                          for r in range(WORLD)], axis=0)
        w -= LR * mean_g
    results = []
    for r in range(4):
        with open(os.path.join(str(tmp_path), f"fault.r{r}.json")) as f:
            results.append(json.load(f))
    for res in results:
        assert res["resume_step"] == 3, res
        np.testing.assert_allclose(
            res["final_w"], w, rtol=1e-5,
            err_msg=f"rank {res['rank']} diverged from the "
                    f"uninterrupted closed form")
    # goodput rewind: survivors recover crashed_step=5 from their exit
    # dumps -> 2 recomputed steps billed; the SIGKILLed rank left no
    # dump, so its account honestly shows no known rewind
    for res in results:
        if res["rank"] == 3:
            assert res["rewind_steps"] == 0, res
        else:
            assert res["rewind_steps"] == 2, res
            assert res["resumes"][0]["crashed_step"] == 5, res
            # the rewind bucket IS the measured recomputed-step wall
            assert abs(res["rewind_s"] - res["measured_recompute_s"]) \
                <= max(0.05, 0.5 * res["measured_recompute_s"]), res


def test_fault_hang_drill_names_stalled_rank(tmp_path):
    """Rank 2 wedges at step 4 with its heartbeat lease kept FRESH (a
    wedged host looks alive) — only the collective-timeout plane can
    catch it. The survivors must abort with EXIT_COLLECTIVE_TIMEOUT and
    the verdict must name the stalled rank + the collective seq it never
    issued, via flight.diff_ranks over the peer dumps."""
    import re

    rc, out = _run_fault_drill(tmp_path, "hang", target=2)
    assert rc == 117, f"expected EXIT_COLLECTIVE_TIMEOUT (117), got " \
                      f"{rc}:\n{out}"
    assert "rank.hang_at_step fired at step 4" in out, out
    assert re.search(r"status=stall rank=2 seq=\d+", out), out
    assert "rank 2 never issued seq" in out, out
    assert "COLLECTIVE_TIMEOUT" in out, out


def test_fault_lease_loss_drill(tmp_path):
    """Rank 1 stops publishing its lease at step 4 but KEEPS stepping —
    a partition, invisible to the collective plane. The survivors must
    exit EXIT_HEARTBEAT_LOST naming the expired rank, and the launcher
    must report the distinct heartbeat code — proving the exit-code
    taxonomy separates the two abort planes.  (The partitioned rank's
    own-lease self-detection is pinned by an in-process unit in
    test_fault_supervisor.py — here it races the coordination-service
    cascade that follows the first survivor exit.)"""
    rc, out = _run_fault_drill(tmp_path, "lease", target=1)
    assert rc == 118, f"expected EXIT_HEARTBEAT_LOST (118), got " \
                      f"{rc}:\n{out}"
    assert "heartbeat.lease_lost fired at step 4" in out, out
    assert "rank(s) [1] lease expired" in out, out
    assert "aborting coordinated" in out, out
    assert "HEARTBEAT_LOST" in out, out


@pytest.mark.slow  # ~60 s each: a virtual-mesh run PLUS a 4-process
# cluster run. Cross-process coverage for these axes lives in the full
# (slow-inclusive) run; tier-1 keeps the dp/dp_sharding cluster runs and
# the auto_tp/auto_fsdp virtual-mesh parity below the 1200 s budget.
@pytest.mark.parametrize("strategy,min_drop", [
    ("dp_mp", 0.5),     # tensor parallel (TP init differs from mp=1)
    ("dp_pp", 0.05),    # SPMD 1F1B pipeline via fleet train_batch
    ("dp_sep", 0.1),    # ring flash attention over the sep axis
])
def test_multiproc_axis_matches_single_process_virtual_mesh(
        strategy, min_drop, tmp_path):
    """Each remaining mesh axis across 4 real processes == the same
    4-device mesh inside one process. Together with the dp/dp_sharding
    cases above, ALL FIVE axes (dp, sharding, mp, pp, sep) are proven
    cross-process."""
    ref = _run_single(tmp_path / "virt", strategy, virtual_devices=4)
    losses = _run_cluster(tmp_path, strategy, 4)
    assert losses[-1] < losses[0] - min_drop, \
        f"{strategy} did not train: {losses}"
    np.testing.assert_allclose(
        losses, ref, rtol=2e-4, atol=2e-4,
        err_msg=f"{strategy} across 4 processes diverged from the same "
                f"mesh in one process")
