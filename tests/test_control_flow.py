"""In-graph data-dependent control flow: static.nn.cond / while_loop /
case / switch_case.

Covers the ISSUE-1 acceptance criteria: eager/compiled output parity and
gradient parity for both branch selections, pytree loop-carried state,
nesting, and the greedy decode loop compiling as exactly ONE program
(no graph break, no host sync, no SOT fallback).
"""
from __future__ import annotations

import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import sot
from paddle_tpu.ops.registry import OPS

nn = paddle.static.nn


@pytest.fixture(autouse=True)
def _rng_neutral():
    """New test file inserted mid-suite: restore the global key stream
    after each test so order-fragile downstream tests see the same
    stream as before this file existed."""
    state = paddle.get_rng_state()
    yield
    paddle.set_rng_state(state)


def t(x, dtype=np.float32, grad=False):
    out = paddle.to_tensor(np.asarray(x, dtype=dtype))
    if grad:
        out.stop_gradient = False
    return out


class TestSurface:
    def test_public_surface(self):
        # acceptance criterion: the reference entry points exist
        assert hasattr(paddle.static.nn, "cond")
        assert hasattr(paddle.static.nn, "while_loop")
        assert hasattr(paddle.static.nn, "case")
        assert hasattr(paddle.static.nn, "switch_case")

    def test_registered_ops(self):
        # cond registers under the reference yaml op name
        for name in ("conditional_block", "while_loop", "case",
                     "switch_case"):
            assert name in OPS, name
            assert OPS[name].category == "control_flow"


class TestCondEager:
    def test_branch_selection(self):
        x = t([3.0])
        hi = nn.cond(t(True, np.bool_), lambda: x * 2, lambda: x * 3)
        lo = nn.cond(t(False, np.bool_), lambda: x * 2, lambda: x * 3)
        np.testing.assert_allclose(hi.numpy(), [6.0])
        np.testing.assert_allclose(lo.numpy(), [9.0])

    def test_int_pred(self):
        x = t([1.0])
        out = nn.cond(t(2, np.int32), lambda: x + 1, lambda: x - 1)
        np.testing.assert_allclose(out.numpy(), [2.0])

    def test_one_sided_eager(self):
        hits = []
        nn.cond(t(False, np.bool_), lambda: hits.append("t"))
        assert hits == []
        nn.cond(t(True, np.bool_), lambda: hits.append("t"))
        assert hits == ["t"]

    def test_nonscalar_pred_raises(self):
        with pytest.raises(ValueError, match="scalar"):
            nn.cond(t([True, False], np.bool_), lambda: t(1.0),
                    lambda: t(2.0))

    def test_grad_through_taken_branch(self):
        for xval, want in ((1.0, 1.0), (10.0, 0.0)):
            w = t([2.0], grad=True)
            x = t([xval])
            loss = (x * w).sum()
            clipped = nn.cond(loss > 3.0, lambda: loss * 0.0 + 3.0,
                              lambda: loss)
            clipped.backward()
            np.testing.assert_allclose(w.grad.numpy(), [want])

    def test_pytree_output(self):
        x = t([1.0, 2.0])
        out = nn.cond(t(True, np.bool_),
                      lambda: {"a": x + 1, "b": [x * 2, x * 3]},
                      lambda: {"a": x - 1, "b": [x, x]})
        np.testing.assert_allclose(out["a"].numpy(), [2.0, 3.0])
        np.testing.assert_allclose(out["b"][1].numpy(), [3.0, 6.0])


class TestCondCompiled:
    def test_output_parity_both_branches(self):
        w = t([2.0])

        def f(x):
            loss = (x * w).sum()
            return nn.cond(loss > 3.0, lambda: loss * 0.0 + 3.0,
                           lambda: loss)

        st = paddle.jit.to_static(f, full_graph=True)
        for xval in ([1.0], [10.0]):
            x = t(xval)
            np.testing.assert_allclose(st(x).numpy(), f(x).numpy())
        assert st.graph_break_reason is None

    def test_grad_parity_both_branches(self):
        # compiled gradient (jax.vjp of the lax.cond lowering) must match
        # the eager tape through whichever branch executes
        w = t([2.0], grad=True)

        @paddle.jit.to_static(full_graph=True)
        def f(x):
            loss = (x * w).sum()
            clipped = nn.cond(loss > 3.0, lambda: loss * 0.0 + 3.0,
                              lambda: loss)
            g, = paddle.autograd.grad([clipped], [w])
            return clipped, g

        for xval in ([1.0], [10.0]):
            x = t(xval)
            c, g = f(x)
            w.clear_grad()
            loss = (x * w).sum()
            eager_c = nn.cond(loss > 3.0, lambda: loss * 0.0 + 3.0,
                              lambda: loss)
            eager_c.backward()
            np.testing.assert_allclose(c.numpy(), eager_c.numpy())
            np.testing.assert_allclose(g.numpy(), w.grad.numpy())

    def test_passthrough_branch_is_operand_not_constant(self):
        # a branch that returns an external tensor WITHOUT running any op
        # on it (pure select) must still record that tensor as an op
        # operand: value parity on both selections, and the identity
        # gradient flows to the selected tensor (not silently dropped)
        x = t([1.0, 2.0], grad=True)
        y = t([10.0, 20.0], grad=True)

        @paddle.jit.to_static(full_graph=True)
        def f(p):
            out = nn.cond(p.sum() > 0, lambda: x, lambda: y)
            gx, gy = paddle.autograd.grad([out.sum()], [x, y])
            return out, gx, gy

        out, gx, gy = f(t([1.0]))
        np.testing.assert_allclose(out.numpy(), x.numpy())
        np.testing.assert_allclose(gx.numpy(), [1.0, 1.0])
        np.testing.assert_allclose(gy.numpy(), [0.0, 0.0])
        out, gx, gy = f(t([-1.0]))
        np.testing.assert_allclose(out.numpy(), y.numpy())
        np.testing.assert_allclose(gx.numpy(), [0.0, 0.0])
        np.testing.assert_allclose(gy.numpy(), [1.0, 1.0])

    def test_one_sided_capture_raises(self):
        @paddle.jit.to_static(full_graph=True)
        def f(x):
            return nn.cond(x.sum() > 0, lambda: x * 2)

        with pytest.raises(Exception, match="true_fn and false_fn"):
            f(t([1.0]))

    def test_mismatched_structures_raise(self):
        @paddle.jit.to_static(full_graph=True)
        def f(x):
            return nn.cond(x.sum() > 0, lambda: (x, x), lambda: x)

        with pytest.raises(Exception, match="different structures"):
            f(t([1.0]))

    def test_no_graph_break_full_graph_false(self):
        # the capture layer must route the op through the program, not
        # treat the tensor-boolean as a graph break
        w = t([1.5])

        def f(x):
            s = (x * w).sum()
            return nn.cond(s > 0.0, lambda: s * 2.0, lambda: s * 0.5)

        st = paddle.jit.to_static(f, full_graph=False)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            out = st(t([1.0, 2.0]))
        assert st.graph_break_reason is None
        assert st.sot_stats is None
        np.testing.assert_allclose(out.numpy(), f(t([1.0, 2.0])).numpy())


class TestWhileLoop:
    def test_eager_basic(self):
        i, s = nn.while_loop(lambda i, s: i < 5,
                             lambda i, s: [i + 1, s + 2.0],
                             [t(0, np.int32), t(0.0)])
        assert int(i) == 5
        np.testing.assert_allclose(s.numpy(), 10.0)

    def test_zero_trip(self):
        i, s = nn.while_loop(lambda i, s: i < 0,
                             lambda i, s: [i + 1, s + 2.0],
                             [t(3, np.int32), t(1.0)])
        assert int(i) == 3
        np.testing.assert_allclose(s.numpy(), 1.0)

    def test_compiled_parity(self):
        def f(n):
            i2, s2 = nn.while_loop(lambda i, s: i < n,
                                   lambda i, s: [i + 1, s + 2.0],
                                   [t(0, np.int32), t(0.0)])
            return s2

        st = paddle.jit.to_static(f, full_graph=True)
        n = t(7, np.int32)
        np.testing.assert_allclose(st(n).numpy(), f(n).numpy())

    def test_pytree_carried_state(self):
        def f():
            state = {"i": t(0, np.int32), "acc": [t(1.0), t(0.0)]}

            def keep(st):
                return st["i"] < 4

            def body(st):
                return {"i": st["i"] + 1,
                        "acc": [st["acc"][0] * 2.0,
                                st["acc"][1] + st["acc"][0]]}

            return nn.while_loop(keep, body, [state])[0]

        eager = f()
        compiled = paddle.jit.to_static(f, full_graph=True)()
        for k0, k1 in ((("acc", 0)), (("acc", 1))):
            np.testing.assert_allclose(compiled[k0][k1].numpy(),
                                       eager[k0][k1].numpy())
        assert int(compiled["i"]) == 4
        np.testing.assert_allclose(eager["acc"][0].numpy(), 16.0)
        np.testing.assert_allclose(eager["acc"][1].numpy(), 15.0)

    def test_eager_grad_through_unrolled_tape(self):
        # reference dygraph semantics: eager while_loop differentiates
        # through the unrolled iterations
        w = t(1.5, grad=True)
        i, s = nn.while_loop(lambda i, s: i < 3,
                             lambda i, s: [i + 1, s * w],
                             [t(0, np.int32), t(1.0)])
        s.backward()
        # d(w^3)/dw = 3 w^2
        np.testing.assert_allclose(w.grad.numpy(), 3 * 1.5 ** 2,
                                   rtol=1e-6)

    def test_shape_invariance_error(self):
        @paddle.jit.to_static(full_graph=True)
        def f(x):
            return nn.while_loop(
                lambda v: v.sum() < 100.0,
                lambda v: [paddle.ops.concat([v, v])], [x])

        with pytest.raises(Exception, match="invariant|changes"):
            f(t([1.0]))

    def test_bad_cond_error(self):
        @paddle.jit.to_static(full_graph=True)
        def f(x):
            return nn.while_loop(lambda v: v < 5.0, lambda v: [v + 1], [x])

        with pytest.raises(Exception, match="scalar"):
            f(t([1.0, 2.0]))

    def test_loop_vars_type_error(self):
        with pytest.raises(TypeError):
            nn.while_loop(lambda i: i < 2, lambda i: i + 1, t(0, np.int32))


class TestCaseSwitch:
    def test_case_eager(self):
        x = t([1.0])
        out = nn.case([(t(False, np.bool_), lambda: x * 0),
                       (t(True, np.bool_), lambda: x * 5)],
                      default=lambda: x * 9)
        np.testing.assert_allclose(out.numpy(), [5.0])
        out = nn.case([(t(False, np.bool_), lambda: x * 0),
                       (t(False, np.bool_), lambda: x * 5)],
                      default=lambda: x * 9)
        np.testing.assert_allclose(out.numpy(), [9.0])

    def test_case_last_fn_is_default(self):
        x = t([1.0])
        out = nn.case([(t(False, np.bool_), lambda: x * 0),
                       (t(False, np.bool_), lambda: x * 5)])
        np.testing.assert_allclose(out.numpy(), [5.0])

    def test_case_compiled_parity(self):
        def f(a):
            s = a.sum()
            return nn.case([(s > 10.0, lambda: s - 10.0),
                            (s > 0.0, lambda: s * 2.0)],
                           default=lambda: s * 0.0 - 1.0)

        st = paddle.jit.to_static(f, full_graph=True)
        for vals in ([20.0], [3.0], [-5.0]):
            np.testing.assert_allclose(st(t(vals)).numpy(),
                                       f(t(vals)).numpy())

    def test_switch_eager_and_compiled(self):
        x = t([1.0])

        def f(idx):
            return nn.switch_case(idx, [lambda: x + 1.0,
                                        lambda: x + 10.0,
                                        lambda: x + 100.0])

        st = paddle.jit.to_static(f, full_graph=True)
        for i in (0, 1, 2, 9):  # 9 = out of range -> largest key
            idx = t(i, np.int32)
            np.testing.assert_allclose(st(idx).numpy(), f(idx).numpy())
        np.testing.assert_allclose(f(t(9, np.int32)).numpy(), [101.0])

    def test_switch_pairs_and_default(self):
        x = t([1.0])

        def f(idx):
            return nn.switch_case(idx,
                                  [(3, lambda: x * 3.0),
                                   (7, lambda: x * 7.0)],
                                  default=lambda: x * 0.0)

        st = paddle.jit.to_static(f, full_graph=True)
        for i in (3, 7, 5):
            idx = t(i, np.int32)
            np.testing.assert_allclose(st(idx).numpy(), f(idx).numpy())
        np.testing.assert_allclose(f(t(5, np.int32)).numpy(), [0.0])

    def test_switch_duplicate_keys_raise(self):
        with pytest.raises(ValueError, match="duplicate"):
            nn.switch_case(t(0, np.int32),
                           [(1, lambda: t(1.0)), (1, lambda: t(2.0))])

    def test_switch_grad_through_closed_over_param(self):
        w = t([2.0], grad=True)

        @paddle.jit.to_static(full_graph=True)
        def f(idx):
            out = nn.switch_case(idx, [lambda: (w * 2.0).sum(),
                                       lambda: (w * w).sum()])
            g, = paddle.autograd.grad([out], [w])
            return out, g

        out, g = f(t(0, np.int32))
        np.testing.assert_allclose(g.numpy(), [2.0])
        out, g = f(t(1, np.int32))
        np.testing.assert_allclose(g.numpy(), [4.0])


class TestNesting:
    def test_cond_in_while_body(self):
        i0, a0 = t(0, np.int32), t(1.0)

        def f(n):
            def body(i, a):
                a2 = nn.cond(a > 10.0, lambda: a * 0.5, lambda: a * 2.0)
                return [i + 1, a2]

            return nn.while_loop(lambda i, a: i < n, body, [i0, a0])[1]

        st = paddle.jit.to_static(f, full_graph=True)
        n = t(6, np.int32)
        np.testing.assert_allclose(st(n).numpy(), f(n).numpy())
        np.testing.assert_allclose(f(n).numpy(), 16.0)

    def test_cond_in_cond(self):
        def f(x):
            s = x.sum()
            return nn.cond(
                s > 0.0,
                lambda: nn.cond(s > 10.0, lambda: s * 100.0,
                                lambda: s * 10.0),
                lambda: s)

        st = paddle.jit.to_static(f, full_graph=True)
        for vals in ([20.0], [3.0], [-5.0]):
            np.testing.assert_allclose(st(t(vals)).numpy(),
                                       f(t(vals)).numpy())


class TestSOTCapture:
    def test_cond_records_into_segment_journal(self):
        rng = np.random.RandomState(0)
        w = t(rng.randn(4), grad=True)
        x = t(np.ones(4))

        def loss_fn():
            loss = (x * w).sum()
            return nn.cond(loss > 0.0, lambda: loss * 2.0,
                           lambda: loss * 0.5)

        with sot.capture():
            out = loss_fn()
        out.backward()
        g_sot = np.asarray(w.grad._data)
        w.clear_grad()
        loss_fn().backward()
        np.testing.assert_allclose(g_sot, np.asarray(w.grad._data),
                                   atol=1e-6)

    def test_while_loop_inside_sot_capture(self):
        x = t(np.array([2.0], dtype=np.float32))

        def f():
            i, v = nn.while_loop(lambda i, v: i < 3,
                                 lambda i, v: [i + 1, v * 2.0],
                                 [t(0, np.int32), x])
            return v.sum()

        with sot.capture():
            out = f()
        np.testing.assert_allclose(np.asarray(out.numpy()), 16.0)


class TestProgramCapture:
    def test_cond_recorded_as_one_op(self):
        static = paddle.static
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", shape=[4], dtype="float32")
            s = (x * 2.0).sum()
            y = nn.cond(s > 4.0, lambda: s - 1.0, lambda: s + 1.0)
        names = [op.name for op in prog.global_block().ops]
        # recorded under the registered (reference yaml) op name
        assert names.count("conditional_block") == 1
        # branch internals must NOT leak into the program
        assert "subtract" not in names and "add" not in names
        exe = static.Executor()
        hi, = exe.run(prog, feed={"x": np.ones(4, dtype=np.float32)},
                      fetch_list=[y])
        np.testing.assert_allclose(hi, 7.0)
        lo, = exe.run(prog,
                      feed={"x": np.full(4, 0.25, dtype=np.float32)},
                      fetch_list=[y])
        np.testing.assert_allclose(lo, 3.0)


class TestGreedyDecode:
    """The worked example: a greedy decode loop (while_loop over KV-cache
    state) compiling as ONE program, with eager/compiled parity."""

    V, D, T = 7, 5, 6

    def _build(self):
        V, D, T = self.V, self.D, self.T
        rng = np.random.RandomState(0)
        emb = t(rng.randn(V, D).astype(np.float32))
        wo = t(rng.randn(D, V).astype(np.float32))
        traces = []

        def decode(tok0):
            traces.append(1)
            state = {
                "step": t(0, np.int32),
                "tok": tok0,
                "kv": t(np.zeros((T, D))),
                "out": t(np.zeros(T, np.int32), np.int32),
            }

            def keep(st):
                return st["step"] < T

            def body(st):
                h = paddle.ops.gather(emb, st["tok"].reshape([1]))
                kv = paddle.ops.scatter(st["kv"],
                                        st["step"].reshape([1]), h)
                ctx = kv.sum(axis=0) / (st["step"].astype("float32")
                                        + 1.0)
                logits = paddle.ops.matmul(ctx.reshape([1, D]), wo)
                nxt = paddle.ops.argmax(logits, axis=-1,
                                        dtype="int32").reshape([])
                out = paddle.ops.scatter(
                    st["out"].reshape([T, 1]), st["step"].reshape([1]),
                    nxt.reshape([1, 1])).reshape([T])
                return {"step": st["step"] + 1, "tok": nxt,
                        "kv": kv, "out": out}

            final = nn.while_loop(keep, body, [state])[0]
            return final["out"], final["kv"]

        return decode, traces

    def test_parity_and_single_program(self):
        decode, traces = self._build()
        tok0 = t(3, np.int32)
        out_e, kv_e = decode(tok0)

        st = paddle.jit.to_static(decode, full_graph=True)
        out_c, kv_c = st(tok0)
        out_c2, _ = st(tok0)

        np.testing.assert_array_equal(out_e.numpy(), out_c.numpy())
        np.testing.assert_array_equal(out_c.numpy(), out_c2.numpy())
        np.testing.assert_allclose(kv_e.numpy(), kv_c.numpy(),
                                   rtol=1e-6)
        # exactly ONE compiled program: one eager run + one trace; the
        # second compiled call replays the cached executable
        assert len(traces) == 2
        assert st.graph_break_reason is None  # no host sync / split
        assert st.sot_stats is None           # never fell back to SOT

    def test_host_sync_fallback_matches(self):
        # the pre-subsystem fallback (python loop, scalar synced to host
        # each step) must agree with the in-graph loop
        decode, _ = self._build()
        V, D, T = self.V, self.D, self.T
        rng = np.random.RandomState(0)
        emb = rng.randn(V, D).astype(np.float32)
        wo = rng.randn(D, V).astype(np.float32)
        kv = np.zeros((T, D), np.float32)
        out = np.zeros(T, np.int32)
        tok = 3
        for step in range(T):
            kv[step] = emb[tok]
            ctx = kv.sum(axis=0) / (step + 1.0)
            tok = int(np.argmax(ctx @ wo))
            out[step] = tok
        got, _ = decode(t(3, np.int32))
        np.testing.assert_array_equal(got.numpy(), out)


class TestAMPInterplay:
    def test_cond_under_auto_cast(self):
        w = t(np.ones((4, 4)))
        x = t(np.ones((2, 4)))
        with paddle.amp.auto_cast(level="O1"):
            s = paddle.ops.matmul(x, w).sum()
            out = nn.cond(s > 0.0, lambda: s * 2.0, lambda: s * 0.5)
        np.testing.assert_allclose(float(out), 64.0, rtol=1e-2)
