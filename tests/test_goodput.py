"""Goodput ledger + anomaly sentinel suite (round-23 tentpole).

Proves the observability contract end to end: FakeClock ledger
arithmetic (buckets exhaustive and summing to wall EXACTLY, billed
overlap priority, interval folding), rewind badput equal to the
recomputed-step wall after a crash/auto-resume, the zero-clock-reads
disabled path (counting clock), every sentinel incident kind as a unit,
the injected ``fleet.slow_step`` + compile-storm drills flagged within
two windows, per-rank dump/merge persistence, the metrics export plane
(``paddle_tpu_goodput_seconds_total`` through ``--merge``), the MoE
expert-load telemetry satellite, and one hapi crash→resume acceptance
drill with checkpoint / compile / data-stall / rewind attribution.
"""
import io
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault
from paddle_tpu.fault import inject
from paddle_tpu.observability import REGISTRY, fleet, goodput, sentinel


@pytest.fixture(autouse=True)
def _hygiene(monkeypatch):
    monkeypatch.delenv(goodput.RECORD_ENV, raising=False)

    def _reset():
        paddle.set_flags({"FLAGS_enable_metrics": False,
                          "FLAGS_goodput": True,
                          "FLAGS_sentinel": True})
        REGISTRY.reset()
        goodput.reset_ledger()
        sentinel.reset(stream=io.StringIO())
        fleet.reset_beacon()
        inject.disarm_all()

    _reset()
    yield
    _reset()


class FakeClock:
    """Deterministic injectable clock that counts its own reads."""

    def __init__(self):
        self.t = 0.0
        self.reads = 0

    def __call__(self):
        self.reads += 1
        return self.t

    def advance(self, dt):
        self.t += dt


def _fake_run():
    clk = FakeClock()
    led = goodput.reset_ledger(clock=clk)
    led.run_begin()
    return clk, led


def _step(clk, led, secs=1.0, step=None):
    led.step_begin()
    clk.advance(secs)
    return led.step_end(step=step)


# ---------------------------------------------------------------------------
# Ledger arithmetic (FakeClock)
# ---------------------------------------------------------------------------
class TestLedger:
    def test_buckets_sum_to_wall_exactly(self):
        clk, led = _fake_run()
        _step(clk, led, 1.0)                      # productive 1.0
        with goodput.bill("checkpoint"):          # checkpoint 0.5
            clk.advance(0.5)
        led.step_begin()                          # step with 0.2 compile
        with goodput.bill("compile"):
            clk.advance(0.2)
        clk.advance(0.8)
        assert led.step_end() == pytest.approx(1.0)
        clk.advance(0.5)                          # idle -> host
        snap = led.snapshot()
        b = snap["buckets"]
        assert snap["wall_s"] == 3.0
        assert b["productive"] == pytest.approx(1.8)
        assert b["checkpoint"] == pytest.approx(0.5)
        assert b["compile"] == pytest.approx(0.2)
        assert b["host"] == pytest.approx(0.5)
        assert sum(b.values()) == snap["wall_s"]  # residual => EXACT
        assert set(b) == set(goodput.BUCKETS)
        assert snap["goodput_fraction"] == pytest.approx(0.6)

    def test_overlap_priority_checkpoint_owns_compile(self):
        clk, led = _fake_run()
        led.bill_interval("compile", 0.0, 1.0)
        led.bill_interval("checkpoint", 0.5, 1.5)
        clk.advance(2.0)
        b = led.snapshot()["buckets"]
        # the overlapping 0.5s is a checkpoint second, never double-billed
        assert b["checkpoint"] == pytest.approx(1.0)
        assert b["compile"] == pytest.approx(0.5)
        assert b["host"] == pytest.approx(0.5)

    def test_fold_preserves_totals(self, monkeypatch):
        monkeypatch.setattr(goodput, "_MAX_BILLED", 8)
        clk, led = _fake_run()
        for i in range(40):                        # forces many folds
            led.bill_interval("checkpoint", i * 1.0, i * 1.0 + 0.25)
        clk.advance(40.0)
        b = led.snapshot()["buckets"]
        assert b["checkpoint"] == pytest.approx(10.0)
        assert b["host"] == pytest.approx(30.0)

    def test_rewind_badput_equals_recomputed_wall(self):
        """Crash at step 7, resume from the step-3 checkpoint: steps
        4..7 re-run as rewind badput worth exactly their step wall."""
        clk, led = _fake_run()
        for i in range(8):
            _step(clk, led, 1.0, step=i)
        assert led.last_step == 7
        led.note_resume(restored_step=3)          # in-process crash info
        for i in range(4, 10):                    # 4 recomputed + 2 new
            _step(clk, led, 1.0, step=i)
        snap = led.snapshot()
        assert snap["rewind_steps"] == 4
        assert snap["buckets"]["rewind"] == pytest.approx(4.0)
        assert snap["steps"] == 10                # rewound steps excluded
        assert snap["buckets"]["productive"] == pytest.approx(10.0)
        assert sum(snap["buckets"].values()) == snap["wall_s"] == 14.0
        assert snap["resumes"] == [{"restored_step": 3, "crashed_step": 7,
                                    "rewind_steps": 4}]

    def test_straggler_skew_carved_from_productive(self):
        clk, led = _fake_run()
        for _ in range(4):
            _step(clk, led, 1.0)
        led.note_skew(steps=4, own_mean_s=1.0, median_mean_s=0.75)
        b = led.snapshot()["buckets"]
        assert b["straggler"] == pytest.approx(1.0)
        assert b["productive"] == pytest.approx(3.0)

    def test_overbilling_renormalised_sum_stays_exact(self):
        """Concurrent seams (async-save waits spanning closed steps) can
        over-bill; host clamps at 0 and the account is shaved back."""
        clk, led = _fake_run()
        _step(clk, led, 1.0)
        led.bill_interval("checkpoint", 0.0, 1.5)  # overlaps the step
        clk.advance(1.0)
        snap = led.snapshot()
        b = snap["buckets"]
        assert b["host"] == 0.0
        assert sum(b.values()) == snap["wall_s"] == 2.0

    def test_disabled_path_reads_zero_clocks(self):
        paddle.set_flags({"FLAGS_goodput": False,
                          "FLAGS_sentinel": False})
        clk = FakeClock()
        led = goodput.reset_ledger(clock=clk)
        led.run_begin()
        led.step_begin()
        led.step_end()
        led.bill_since_step_begin("compile")
        with goodput.bill("checkpoint"):
            pass
        goodput.bill_interval("data_stall", 0.0, 1.0)
        goodput.on_compile(0.5, kind="retrace")
        sentinel.get().observe_step(0.5, loss=float("nan"))
        assert clk.reads == 0
        assert sentinel.get().counts() == {}
        snap = led.snapshot()
        assert snap["wall_s"] == 0.0 and not snap["running"]

    def test_mid_run_flag_off_goes_cold(self):
        clk, led = _fake_run()
        _step(clk, led, 1.0)
        reads = clk.reads
        paddle.set_flags({"FLAGS_goodput": False})
        led.step_begin()
        led.step_end()
        with goodput.bill("compile"):
            clk.advance(1.0)
        assert clk.reads == reads


# ---------------------------------------------------------------------------
# Persistence: rank-suffixed dumps, merge, cross-process rewind
# ---------------------------------------------------------------------------
class TestPersistence:
    def test_dump_roundtrip_rank_suffix(self, tmp_path, monkeypatch):
        base = str(tmp_path / "goodput.json")
        monkeypatch.setenv(goodput.RECORD_ENV, base)
        clk, led = _fake_run()
        _step(clk, led, 1.0, step=5)
        p = goodput.dump(reason="test")
        assert p == base + ".r0"
        payload = goodput.load_dump(p)
        assert payload["format"] == "paddle_tpu.goodput/1"
        assert payload["last_step"] == 5
        assert payload["reason"] == "test"
        assert payload["goodput"]["buckets"]["productive"] == 1.0
        assert "sentinel" in payload
        assert [d["rank"] for d in goodput.merge_dumps(base)] == [0]

    def test_dump_is_noop_without_env_or_run(self, tmp_path):
        assert goodput.dump() is None             # env unset
        bad = tmp_path / "x.json"
        bad.write_text("{}")
        with pytest.raises(ValueError, match="not a goodput dump"):
            goodput.load_dump(str(bad))

    def test_note_resume_reads_prior_process_dump(self, tmp_path,
                                                  monkeypatch):
        base = str(tmp_path / "goodput.json")
        monkeypatch.setenv(goodput.RECORD_ENV, base)
        clk, led = _fake_run()
        for i in range(10):
            _step(clk, led, 1.0, step=i)
        goodput.dump(reason="crash")
        # "new process": fresh ledger with no in-memory crash progress
        clk, led = _fake_run()
        led.note_resume(restored_step=4)
        assert led.resumes[-1]["crashed_step"] == 9
        for i in range(4, 11):
            _step(clk, led, 1.0, step=i)
        snap = led.snapshot()
        assert snap["rewind_steps"] == 5
        assert snap["buckets"]["rewind"] == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# Metrics export plane
# ---------------------------------------------------------------------------
class TestMetricsExport:
    def test_seconds_counter_monotone_and_fraction_gauge(self):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        clk, led = _fake_run()
        _step(clk, led, 2.0)
        with goodput.bill("checkpoint"):
            clk.advance(1.0)
        led.export_metrics()
        m = REGISTRY.get("paddle_tpu_goodput_seconds_total")
        assert m.value(bucket="productive") == pytest.approx(2.0)
        assert m.value(bucket="checkpoint") == pytest.approx(1.0)
        before = m.total()
        led.export_metrics()                       # no double counting
        assert m.total() == before
        _step(clk, led, 2.0)
        led.export_metrics()
        assert m.value(bucket="productive") == pytest.approx(4.0)
        frac = REGISTRY.get("paddle_tpu_goodput_fraction")
        assert frac.value() == pytest.approx(4.0 / 5.0)

    def test_fleet_snapshot_carries_goodput_and_sentinel(self):
        clk, led = _fake_run()
        _step(clk, led, 1.0)
        snap = fleet.local_snapshot()
        assert snap["goodput"]["buckets"]["productive"] == 1.0
        assert snap["sentinel"]["observed_steps"] == 0

    def test_metrics_dump_merge_aggregates_goodput(self, tmp_path):
        """tools/metrics_dump.py --merge folds the per-rank goodput
        counters into one rank-labeled aggregate."""
        paddle.set_flags({"FLAGS_enable_metrics": True})
        clk, led = _fake_run()
        _step(clk, led, 3.0)
        led.export_metrics()
        snap = REGISTRY.snapshot()
        base = str(tmp_path / "metrics.json")
        json.dump(snap, open(base, "w"))
        json.dump(snap, open(base + ".rank1", "w"))
        from paddle_tpu.observability.__main__ import main as dump_main
        out = str(tmp_path / "merged.json")
        assert dump_main(["--merge", base, "--format", "json",
                          "--output", out]) == 0
        merged = json.load(open(out))
        m = merged["paddle_tpu_goodput_seconds_total"]
        assert m["labelnames"] == ["rank", "bucket"]
        ranks = {s["labels"][0] for s in m["series"]}
        assert ranks == {"0", "1"}


# ---------------------------------------------------------------------------
# Sentinel detector units — one per incident kind
# ---------------------------------------------------------------------------
class TestSentinel:
    def test_step_time_spike_once_per_window(self):
        buf = io.StringIO()
        snt = sentinel.reset(window=8, stream=buf)
        for _ in range(8):
            snt.observe_step(0.01)
        snt.observe_step(0.1)
        snt.observe_step(0.1)                      # cooldown: no refire
        assert snt.counts() == {"step_time_spike": 1}
        lines = [ln for ln in buf.getvalue().splitlines() if ln]
        assert len(lines) == 1
        assert "step_time_spike" in lines[0]
        inc = snt.incidents()[-1]
        assert "vs median" in inc["detail"]
        assert set(inc["diff"]) == {"pre", "post", "dominant_bucket"}

    def test_step_time_drift_two_window_changepoint(self):
        snt = sentinel.reset(window=4, stream=io.StringIO())
        for _ in range(4):
            snt.observe_step(0.01)
        for _ in range(4):                         # +40%: drift, no spike
            snt.observe_step(0.014)
        assert snt.counts() == {"step_time_drift": 1}
        assert "1.40x" in snt.incidents()[-1]["detail"]

    def test_nonfinite_loss_fires_immediately(self):
        snt = sentinel.reset(window=8, stream=io.StringIO())
        snt.observe_step(0.01, loss=float("nan"), step=3)
        assert snt.counts() == {"nonfinite_loss": 1}
        assert snt.incidents()[-1]["step"] == 3

    def test_compile_storm_counts_retraces_only(self):
        snt = sentinel.reset(window=4, stream=io.StringIO())
        for _ in range(5):
            snt.note_compile("initial")            # expected compiles
        for _ in range(4):
            snt.observe_step(0.01)
        assert snt.counts() == {}
        for _ in range(3):
            snt.note_compile("retrace")
        for _ in range(4):
            snt.observe_step(0.01)
        assert snt.counts() == {"compile_storm": 1}
        assert "3 retraces" in snt.incidents()[-1]["detail"]

    def test_straggler_flip(self):
        snt = sentinel.reset(window=4, stream=io.StringIO())
        snt.note_straggler(1, True, skew=1.5)
        snt.note_straggler(1, True, skew=1.5)      # same rank: no news
        assert snt.counts() == {}
        snt._n = 10                                # past the cooldown
        snt.note_straggler(2, True, skew=1.8)
        assert snt.counts() == {"straggler_flip": 1}
        assert "1 -> 2" in snt.incidents()[-1]["detail"]

    def test_data_stall_regression_names_dominant_bucket(self):
        clk, led = _fake_run()
        snt = sentinel.reset(window=4, stream=io.StringIO())
        for _ in range(4):                         # clean window
            snt.observe_step(_step(clk, led, 1.0))
        for _ in range(4):                         # stall-heavy window
            t = clk.t
            clk.advance(1.0)
            led.bill_interval("data_stall", t, t + 1.0)
            snt.observe_step(_step(clk, led, 1.0))
        assert snt.counts() == {"data_stall_regression": 1}
        inc = snt.incidents()[-1]
        assert inc["diff"]["dominant_bucket"] == "data_stall"
        assert inc["diff"]["post"]["data_stall"] == pytest.approx(0.5)
        assert inc["diff"]["pre"]["data_stall"] == pytest.approx(0.0)

    def test_incidents_counted_in_metrics(self):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        snt = sentinel.reset(window=8, stream=io.StringIO())
        snt.observe_step(0.01, loss=float("inf"))
        assert REGISTRY.get("paddle_tpu_sentinel_incidents_total").value(
            kind="nonfinite_loss") == 1.0


# ---------------------------------------------------------------------------
# Drills: injected faults must be flagged within two windows
# ---------------------------------------------------------------------------
class TestDrills:
    def test_slow_step_drill_flagged_within_two_windows(self):
        buf = io.StringIO()
        snt = sentinel.reset(window=4, stream=buf)
        led = goodput.reset_ledger()
        led.run_begin()
        b = fleet.reset_beacon(window=4)

        def one_step():
            led.step_begin()
            b.step_begin()
            b.step_end()
            snt.observe_step(led.step_end())

        for _ in range(6):                         # baseline history
            one_step()
        with inject.armed("fleet.slow_step", times=100, seconds=0.02):
            for i in range(8):                     # two windows
                one_step()
                if snt.counts():
                    break
        kinds = set(snt.counts())
        assert kinds & {"step_time_spike", "step_time_drift"}, kinds
        assert i < 8                               # within 2 windows

    def test_compile_storm_drill_via_jit_retraces(self):
        snt = sentinel.reset(window=4, stream=io.StringIO())
        led = goodput.reset_ledger()
        led.run_begin()
        fn = paddle.jit.to_static(lambda t: t * 2.0 + 1.0)
        for n in (1, 2, 3, 4):                     # 1 initial + 3 retraces
            fn(paddle.to_tensor(np.ones((n,), np.float32)))
        for _ in range(8):                         # <= two windows
            snt.observe_step(0.01)
        assert snt.counts().get("compile_storm") == 1
        # the retrace wall was billed to the compile bucket
        assert led.snapshot()["buckets"]["compile"] > 0.0


# ---------------------------------------------------------------------------
# Satellites: Engine LR hoist, MoE expert-load telemetry, report tool
# ---------------------------------------------------------------------------
class _XY:
    def __len__(self):
        return 16

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        return (rng.randn(4).astype(np.float32),
                rng.randn(2).astype(np.float32))


class TestSatellites:
    def test_engine_constant_lr_read_once(self):
        """Async-stretch hygiene: without an LRScheduler the Engine
        transfers the LR once, not host-read + H2D per step."""
        from paddle_tpu.distributed.auto_parallel.engine import Engine
        m = paddle.nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        calls = {"n": 0}
        orig = opt.get_lr

        def counting_get_lr():
            calls["n"] += 1
            return orig()

        opt.get_lr = counting_get_lr
        e = Engine(m, loss=lambda o, t: paddle.ops.mean((o - t) ** 2),
                   optimizer=opt)
        e.fit(_XY(), epochs=2, batch_size=8)       # 4 steps total
        assert calls["n"] == 1

    def test_moe_expert_load_telemetry(self):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        from paddle_tpu.distributed.fleet.moe import MoELayer
        moe = MoELayer(d_model=8, num_experts=4, top_k=1, d_hidden=16,
                       capacity_factor=2.0)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(16, 8).astype(np.float32))
        moe(x)
        tokens = REGISTRY.get("paddle_tpu_moe_expert_tokens_total")
        # top-1 routing with slack capacity: every token lands somewhere
        assert tokens.total() == 16.0
        assert REGISTRY.get("paddle_tpu_moe_load_imbalance").value() >= 1.0

    def test_goodput_report_tool(self, tmp_path):
        base = str(tmp_path / "goodput.json")
        clk, led = _fake_run()
        _step(clk, led, 1.0, step=0)
        with goodput.bill("checkpoint"):
            clk.advance(1.0)
        goodput.dump(path=base + ".r0", reason="exit")
        worse = goodput.load_dump(base + ".r0")
        worse["rank"] = 1
        worse["goodput"]["goodput_fraction"] = 0.25
        json.dump(worse, open(base + ".r1", "w"))

        from tools import goodput_report as gr
        report = gr.job_report(gr.collect(dump_base=base))
        assert report["job_goodput_fraction"] == 0.25
        assert report["worst_rank"] == 1
        md = gr.render_markdown(report)
        assert "Goodput report" in md and "Incident timeline" in md
        for bucket in goodput.BUCKETS:
            assert bucket in md
        out = str(tmp_path / "report.json")
        assert gr.main(["--dump", base, "--json", "--out", out]) == 0
        assert json.load(open(out))["worst_rank"] == 1


# ---------------------------------------------------------------------------
# End-to-end acceptance drill (hapi crash -> resume, full attribution)
# ---------------------------------------------------------------------------
class _DS:
    def __len__(self):
        return 32

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        return rng.randn(4).astype("float32"), np.int64(i % 3)


def _make_model():
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 3))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    return model, net


class _CrashAt(paddle.hapi.Callback):
    def __init__(self, at):
        super().__init__()
        self.at = at

    def on_train_batch_end(self, step, logs=None):
        if self.model._global_step == self.at:
            raise RuntimeError("injected crash")


class TestEndToEnd:
    def test_crash_resume_drill_full_attribution(self, tmp_path):
        """ISSUE acceptance: injected crash + auto_resume, a forced
        compile, and a data-stall window — buckets sum to wall within
        1% and every badput lands in the right bucket."""
        led = goodput.reset_ledger()
        sentinel.reset(stream=io.StringIO())
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=8)

        model, net = _make_model()
        cb = paddle.hapi.ModelCheckpoint(manager=mgr, save_steps=4)
        with pytest.raises(RuntimeError, match="injected crash"):
            model.fit(_DS(), epochs=2, batch_size=8, verbose=0,
                      shuffle=False, callbacks=[cb, _CrashAt(6)])
        assert led.last_step == 6                 # crash progress captured

        model2, net2 = _make_model()
        model2.fit(_DS(), epochs=2, batch_size=8, verbose=0, shuffle=False,
                   callbacks=[paddle.hapi.ModelCheckpoint(
                       manager=mgr, save_steps=4)], resume=mgr)
        assert model2._global_step == 8
        snap = led.snapshot()
        # restored at the step-4 checkpoint, crashed at 6 -> 2 rewound
        assert snap["rewind_steps"] == 2
        assert snap["buckets"]["rewind"] > 0.0
        assert snap["buckets"]["checkpoint"] > 0.0  # saves + restore
        assert snap["resumes"] == [{"restored_step": 4, "crashed_step": 6,
                                    "rewind_steps": 2}]

        # forced cache-miss compile while the run is live
        fn = paddle.jit.to_static(lambda t: t * 3.0)
        fn(paddle.to_tensor(np.ones((5,), np.float32)))
        assert led.snapshot()["buckets"]["compile"] > 0.0

        # data-stall window: a starved DevicePrefetcher bills the wait
        from paddle_tpu.io import DevicePrefetcher

        def slow_source():
            yield np.ones((2,), np.float32)
            time.sleep(0.06)
            yield np.ones((2,), np.float32)

        pf = DevicePrefetcher(slow_source(), depth=1)
        for _ in pf:
            pass
        snap = led.snapshot()
        assert snap["buckets"]["data_stall"] >= 0.03

        # the exhaustiveness contract, on a real wall clock
        assert sum(snap["buckets"].values()) == pytest.approx(
            snap["wall_s"], rel=0.01)
        assert 0.0 < snap["goodput_fraction"] < 1.0
        assert snap["steps"] == 8                  # 6 + 2 net-new
