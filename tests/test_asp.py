"""Automatic SParsity (ASP) tests (reference python/paddle/incubate/asp/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate import asp


@pytest.fixture(autouse=True)
def _reset():
    asp.ASPHelper.reset()
    asp.reset_excluded_layers()
    yield
    asp.ASPHelper.reset()
    asp.reset_excluded_layers()


class TestMasks:
    def test_mask_1d_structure_and_magnitude(self):
        rng = np.random.RandomState(0)
        w = rng.randn(8, 16).astype(np.float32)
        mask = asp.get_mask_1d(w, 2, 4)
        assert asp.check_mask_1d(mask, 2, 4)
        assert abs(asp.calculate_density(mask) - 0.5) < 1e-6
        # kept entries are the 2 largest |w| per group of 4
        groups = np.abs(w.reshape(-1, 4))
        kept = mask.reshape(-1, 4).astype(bool)
        for g, k in zip(groups, kept):
            assert set(np.argsort(-g)[:2]) == set(np.nonzero(k)[0])

    def test_mask_2d_greedy(self):
        rng = np.random.RandomState(1)
        w = rng.randn(8, 8).astype(np.float32)
        mask = asp.get_mask_2d_greedy(w, 2, 4)
        assert asp.check_mask_2d(mask, 2, 4)
        assert asp.calculate_density(mask) <= 0.5 + 1e-6

    def test_check_rejects_dense(self):
        assert not asp.check_mask_1d(np.ones((4, 8)), 2, 4)
        assert not asp.check_mask_2d(np.ones((8, 8)), 2, 4)

    def test_checking_method_mapping(self):
        assert asp.CheckMethod.get_checking_method(
            asp.MaskAlgo.MASK_1D) == asp.CheckMethod.CHECK_1D
        assert asp.CheckMethod.get_checking_method(
            asp.MaskAlgo.MASK_2D_GREEDY) == asp.CheckMethod.CHECK_2D


class TestPruneAndTrain:
    def _model(self):
        paddle.seed(0)
        return nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                             nn.Linear(32, 4))

    def test_prune_model_sparsifies_weights_only(self):
        net = self._model()
        masks = asp.prune_model(net)
        assert len(masks) == 2  # two Linear weights, no biases
        for name, p in net.named_parameters():
            if name in masks:
                arr = p.numpy()
                assert asp.check_mask_1d(arr, 2, 4)
                assert abs(asp.calculate_density(arr) - 0.5) < 0.01

    def test_excluded_layers(self):
        net = self._model()
        asp.set_excluded_layers(["0."])  # first Linear
        masks = asp.prune_model(net)
        assert len(masks) == 1

    def test_decorated_optimizer_preserves_sparsity(self):
        net = self._model()
        asp.prune_model(net)
        opt = asp.decorate(paddle.optimizer.Adam(
            learning_rate=0.05, parameters=net.parameters()))
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, 8).astype(np.int64))
        import paddle_tpu.nn.functional as F
        l0 = lN = None
        for i in range(10):
            loss = F.cross_entropy(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if i == 0:
                l0 = float(loss.numpy())
            lN = float(loss.numpy())
        assert lN < l0  # still trains
        for name, p in net.named_parameters():
            if "bias" not in name:
                arr = p.numpy()
                assert asp.check_mask_1d(arr, 2, 4), name
                assert abs(asp.calculate_density(arr) - 0.5) < 0.01


class TestReviewFixes:
    def test_exclusion_prefix_no_overmatch(self):
        # "0." must not exclude layer "10."
        layers = [nn.Linear(8, 8) for _ in range(11)]
        net = nn.Sequential(*layers)
        asp.set_excluded_layers(["0."])
        masks = asp.prune_model(net)
        assert not any(k.startswith("0.") for k in masks)
        assert any(k.startswith("10.") for k in masks)

    def test_two_models_same_names_independent_masks(self):
        a = nn.Sequential(nn.Linear(8, 16))
        b = nn.Sequential(nn.Linear(8, 32))  # same name "0.weight"
        asp.prune_model(a)
        asp.prune_model(b)
        # each decorated optimizer applies its own model's mask
        pa = dict(a.named_parameters())["0.weight"]
        pb = dict(b.named_parameters())["0.weight"]
        ma = asp.ASPHelper.mask_for(pa)
        mb = asp.ASPHelper.mask_for(pb)
        assert ma.shape == (8, 16) and mb.shape == (8, 32)

    def test_stopped_epoch_recorded(self):
        import paddle_tpu as paddle
        from paddle_tpu.io import TensorDataset
        rng = np.random.RandomState(0)
        ds = TensorDataset([
            paddle.to_tensor(rng.rand(16, 4).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 2, 16).astype(np.int64))])
        net = nn.Linear(4, 2)
        model = paddle.hapi.Model(net)
        model.prepare(paddle.optimizer.SGD(learning_rate=0.0,
                                           parameters=net.parameters()),
                      paddle.nn.CrossEntropyLoss())
        es = paddle.hapi.EarlyStopping(monitor="loss", patience=1,
                                       verbose=0)
        model.fit(ds, eval_data=ds, epochs=10, batch_size=16, verbose=0,
                  callbacks=[es])
        assert es.stopped_epoch >= 0
