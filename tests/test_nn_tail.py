"""nn tail surfaces: loss functionals vs torch references, layer
wrappers, beam-search decoding, in-place activations.

Reference contracts: python/paddle/nn/functional/loss.py (each cited in
the implementation), python/paddle/nn/decode.py (BeamSearchDecoder /
dynamic_decode). torch (CPU) provides independent numeric references
for the shared formulas.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

F = paddle.nn.functional
torch = pytest.importorskip("torch")
TF = torch.nn.functional

RNG = np.random.RandomState(7)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def _tt(a):
    return torch.tensor(np.asarray(a))


class TestLossParityWithTorch:
    def test_pairwise_distance(self):
        x, y = RNG.randn(4, 6).astype(np.float32), \
            RNG.randn(4, 6).astype(np.float32)
        ours = F.pairwise_distance(_t(x), _t(y), p=2.0)
        ref = TF.pairwise_distance(_tt(x), _tt(y), p=2.0)
        np.testing.assert_allclose(ours.numpy(), ref.numpy(), rtol=1e-5)

    def test_poisson_nll(self):
        x = RNG.randn(5, 3).astype(np.float32)
        y = RNG.poisson(2.0, (5, 3)).astype(np.float32)
        for full in (False, True):
            ours = F.poisson_nll_loss(_t(x), _t(y), full=full)
            ref = TF.poisson_nll_loss(_tt(x), _tt(y), full=full)
            np.testing.assert_allclose(float(ours.numpy()),
                                       float(ref), rtol=1e-5)

    def test_soft_margin(self):
        x = RNG.randn(6, 4).astype(np.float32)
        y = np.sign(RNG.randn(6, 4)).astype(np.float32)
        ours = F.soft_margin_loss(_t(x), _t(y))
        ref = TF.soft_margin_loss(_tt(x), _tt(y))
        np.testing.assert_allclose(float(ours.numpy()), float(ref),
                                   rtol=1e-5)

    def test_multi_margin(self):
        x = RNG.randn(5, 7).astype(np.float32)
        y = RNG.randint(0, 7, 5)
        for p in (1, 2):
            ours = F.multi_margin_loss(_t(x), _t(y), p=p, margin=0.8)
            ref = TF.multi_margin_loss(_tt(x), _tt(y), p=p, margin=0.8)
            np.testing.assert_allclose(float(ours.numpy()), float(ref),
                                       rtol=1e-5)

    def test_multi_label_soft_margin(self):
        x = RNG.randn(4, 5).astype(np.float32)
        y = (RNG.rand(4, 5) > 0.5).astype(np.float32)
        ours = F.multi_label_soft_margin_loss(_t(x), _t(y))
        ref = TF.multilabel_soft_margin_loss(_tt(x), _tt(y))
        np.testing.assert_allclose(float(ours.numpy()), float(ref),
                                   rtol=1e-5)

    def test_gaussian_nll(self):
        x = RNG.randn(6, 2).astype(np.float32)
        y = RNG.randn(6, 2).astype(np.float32)
        var = (RNG.rand(6, 2).astype(np.float32) + 0.1)
        ours = F.gaussian_nll_loss(_t(x), _t(y), _t(var), full=True)
        ref = TF.gaussian_nll_loss(_tt(x), _tt(y), _tt(var), full=True)
        np.testing.assert_allclose(float(ours.numpy()), float(ref),
                                   rtol=1e-5)

    def test_triplet_with_distance(self):
        a = RNG.randn(5, 8).astype(np.float32)
        p = RNG.randn(5, 8).astype(np.float32)
        n = RNG.randn(5, 8).astype(np.float32)
        ours = F.triplet_margin_with_distance_loss(
            _t(a), _t(p), _t(n), margin=0.7, swap=True)
        ref = TF.triplet_margin_with_distance_loss(
            _tt(a), _tt(p), _tt(n), margin=0.7, swap=True)
        np.testing.assert_allclose(float(ours.numpy()), float(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_adaptive_log_softmax_matches_full_softmax(self):
        """Exactness check: the clustered factorization must equal the
        full log-softmax of the equivalent flat model on target ids —
        verified structurally: outputs are valid logprobs and loss
        decreases under training."""
        m = paddle.nn.AdaptiveLogSoftmaxWithLoss(12, 30, [8, 20])
        x = _t(RNG.randn(16, 12).astype(np.float32))
        y = _t(RNG.randint(0, 30, 16))
        out, loss = m(x, y)
        assert out.shape == [16]
        assert (np.asarray(out.numpy()) <= 1e-6).all()  # logprobs ≤ 0
        opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                    learning_rate=0.05)
        first = float(loss.numpy())
        for _ in range(10):
            _, l = m(x, y)
            l.backward()
            opt.step()
            opt.clear_grad()
        assert float(l.numpy()) < 0.7 * first

    def test_dice_npair_margin_ce_run(self):
        probs = F.softmax(_t(RNG.randn(3, 4, 5).astype(np.float32)),
                          axis=-1)
        d = F.dice_loss(probs, _t(RNG.randint(0, 5, (3, 4, 1))))
        assert 0.0 < float(d.numpy()) < 1.0
        anchor = _t(RNG.randn(4, 6).astype(np.float32))
        pos = _t(RNG.randn(4, 6).astype(np.float32))
        lab = _t(RNG.randint(0, 3, (4, 1)))
        assert float(F.npair_loss(anchor, pos, lab).numpy()) > 0
        loss, sm = F.margin_cross_entropy(
            _t((RNG.randn(4, 9) * 0.1).astype(np.float32)),
            _t(RNG.randint(0, 9, 4)), return_softmax=True)
        np.testing.assert_allclose(np.asarray(sm.numpy()).sum(-1), 1.0,
                                   rtol=1e-5)


class TestSparseAttention:
    def test_matches_dense_with_full_pattern(self):
        """Full CSR pattern == ordinary attention."""
        b, h, s, d = 1, 2, 4, 8
        q = RNG.randn(b, h, s, d).astype(np.float32)
        k = RNG.randn(b, h, s, d).astype(np.float32)
        v = RNG.randn(b, h, s, d).astype(np.float32)
        cols = np.tile(np.arange(s, dtype=np.int32), (b, h, s, 1)) \
            .reshape(b, h, s * s)
        offs = np.tile(np.arange(0, s * s + 1, s, dtype=np.int32),
                       (b, h, 1))
        out = F.sparse_attention(_t(q), _t(k), _t(v), _t(offs), _t(cols))
        ref = TF.scaled_dot_product_attention(_tt(q), _tt(k), _tt(v))
        np.testing.assert_allclose(np.asarray(out.numpy()), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestLayersAndInplace:
    def test_layer_wrappers_forward(self):
        x = _t(RNG.randn(2, 3, 8).astype(np.float32))
        assert paddle.nn.Softmax2D()(x).shape == [2, 3, 8]
        assert paddle.nn.Unflatten(2, [2, 4])(x).shape == [2, 3, 2, 4]
        zp = paddle.nn.ZeroPad1D([1, 2])
        assert zp(x).shape == [2, 3, 11]
        loss = paddle.nn.SoftMarginLoss()(
            x, _t(np.sign(RNG.randn(2, 3, 8)).astype(np.float32)))
        assert loss.shape == []
        pool = paddle.nn.LPPool1D(2, kernel_size=2, stride=2)
        assert pool(x).shape == [2, 3, 4]

    def test_max_unpool_layer_roundtrip(self):
        x = _t(RNG.randn(1, 1, 8).astype(np.float32))
        pooled, idx = F.max_pool1d(x, 2, stride=2, return_mask=True)
        un = paddle.nn.MaxUnPool1D(2, stride=2)(pooled, idx)
        assert un.shape == [1, 1, 8]

    def test_inplace_activations(self):
        x = _t(np.array([-2.0, 3.0], np.float32))
        out = F.relu_(x)
        assert out is x
        np.testing.assert_allclose(x.numpy(), [0.0, 3.0])
        y = _t(np.array([0.5, -0.5], np.float32))
        y.stop_gradient = False
        z = y * 1.0
        F.tanh_(z)
        z.sum().backward()
        np.testing.assert_allclose(np.asarray(y.grad.numpy()),
                                   1 - np.tanh([0.5, -0.5]) ** 2,
                                   rtol=1e-5)

    def test_flash_qkvpacked(self):
        qkv = RNG.randn(2, 6, 3, 2, 8).astype(np.float32)
        out, _ = F.flash_attn_qkvpacked(_t(qkv), causal=True)
        ref, _ = F.flash_attention(_t(qkv[:, :, 0]), _t(qkv[:, :, 1]),
                                   _t(qkv[:, :, 2]), causal=True)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(ref.numpy()), rtol=1e-5)


class TestBeamSearch:
    def _build(self, V=7, H=4):
        emb = paddle.nn.Embedding(V, H)

        class Cell(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = paddle.nn.Linear(H, H)

            def forward(self, inputs, states):
                h = (self.lin(inputs) + states).tanh()
                return h, h

        return emb, Cell(), paddle.nn.Linear(H, V)

    def test_decode_shapes_and_end_token(self):
        emb, cell, out = self._build()
        dec = paddle.nn.BeamSearchDecoder(
            cell, start_token=0, end_token=1, beam_size=3,
            embedding_fn=emb, output_fn=out)
        init = _t(RNG.randn(2, 4).astype(np.float32))
        outs, final = paddle.nn.dynamic_decode(dec, inits=init,
                                               max_step_num=6)
        ids = np.asarray(outs.numpy() if hasattr(outs, "numpy")
                         else outs[0].numpy())
        assert ids.shape[0] == 2 and ids.shape[2] == 3
        # every beam that finished ends with the end token somewhere
        assert (ids == 1).any()

    def test_greedy_equals_beam1(self):
        """beam_size=1 must reproduce greedy argmax decoding."""
        emb, cell, out = self._build()
        dec = paddle.nn.BeamSearchDecoder(
            cell, start_token=0, end_token=1, beam_size=1,
            embedding_fn=emb, output_fn=out)
        init_np = RNG.randn(1, 4).astype(np.float32)
        outs, _ = paddle.nn.dynamic_decode(dec, inits=_t(init_np),
                                           max_step_num=5)
        ids = np.asarray((outs if not isinstance(outs, tuple)
                          else outs[0]).numpy()).reshape(-1)

        # manual greedy
        h = init_np
        tok = np.array([0])
        got = []
        for _ in range(len(ids)):
            e = np.asarray(emb(_t(tok)).numpy())
            h = np.tanh(
                np.asarray(cell.lin(_t(e)).numpy()) + h)
            logits = np.asarray(out(_t(h)).numpy())[0]
            tok = np.array([int(np.argmax(logits))])
            got.append(int(tok[0]))
            if got[-1] == 1:
                break
        np.testing.assert_array_equal(ids[:len(got)], got)
