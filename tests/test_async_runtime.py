"""Async runtime (round 17): device prefetch, buffer donation,
decomposed ZeRO gathers, async loss fetch.

Covers the tentpole contracts — DevicePrefetcher ordering/teardown
(including worker-process reaping through a wrapped multiprocess
DataLoader iterator), to_static/Engine donation safety (framework error
on stale reads, pcc separation, FLAGS-off bit-exactness), stage-2/3
decomposed gathers + the stage-3 lookahead schedule, the hapi non-finite
degradation path under the async pipeline, and the fleet_trace
transfer/compute span-overlap report.
"""
import gc
import json
import os
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax                                             # noqa: E402
import jax.numpy as jnp                                # noqa: E402

import paddle_tpu as paddle                            # noqa: E402
from paddle_tpu import nn                              # noqa: E402
from paddle_tpu.core.donation import DonatedBufferError  # noqa: E402
from paddle_tpu.core.tensor import Tensor              # noqa: E402
from paddle_tpu.io import DataLoader, Dataset, DevicePrefetcher  # noqa: E402


class _Range(Dataset):
    def __init__(self, n=64, width=4):
        self.n = n
        self.width = width

    def __getitem__(self, i):
        return np.full((self.width,), i, np.float32)

    def __len__(self):
        return self.n


def _alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split()[2] != "Z"
    except OSError:
        return False


def _wait_dead(pids, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not any(_alive(p) for p in pids):
            return True
        time.sleep(0.1)
    return False


# =========================================================================
# DevicePrefetcher
# =========================================================================
class TestDevicePrefetcher:
    def test_order_and_values_match_plain_iteration(self):
        loader = DataLoader(_Range(32), batch_size=4)
        plain = [b.numpy() for b in loader]
        pre = [b.numpy() for b in DevicePrefetcher(iter(loader))]
        assert len(plain) == len(pre)
        for a, b in zip(plain, pre):
            np.testing.assert_array_equal(a, b)

    def test_depth_flag_and_counters(self):
        pf = DevicePrefetcher(iter(range(10)), depth=3,
                              place_fn=lambda x: x)
        out = list(pf)
        assert out == list(range(10))
        assert pf.depth == 3
        assert pf.hits + 1 >= 1          # counters exist and accumulate
        assert pf.stall_seconds >= 0.0

    def test_exhaustion_closes(self):
        pf = DevicePrefetcher(iter([1, 2]), place_fn=lambda x: x)
        assert list(pf) == [1, 2]
        assert pf.closed
        with pytest.raises(StopIteration):
            next(pf)

    def test_close_idempotent_and_context_manager(self):
        with DevicePrefetcher(iter([1, 2, 3]),
                              place_fn=lambda x: x) as pf:
            assert next(pf) == 1
        assert pf.closed
        pf.close()                        # second close is a no-op

    def test_inner_error_propagates(self):
        def gen():
            yield 1
            raise ValueError("producer blew up")

        pf = DevicePrefetcher(gen(), place_fn=lambda x: x)
        assert next(pf) == 1
        with pytest.raises(ValueError, match="producer blew up"):
            for _ in range(5):
                next(pf)

    def test_place_fn_runs_on_producer_thread(self):
        import threading
        seen = []

        def place(x):
            seen.append(threading.current_thread().name)
            return x

        list(DevicePrefetcher(iter([1, 2]), place_fn=place))
        assert seen and all(n == "paddle_tpu-prefetch" for n in seen)

    # ---- satellite: shutdown propagation to multiprocess workers ----
    def test_abandoned_prefetcher_reaps_dataloader_workers(self):
        loader = DataLoader(_Range(64), batch_size=4, num_workers=2)
        pids = []

        def consume():
            it = iter(loader)
            pids.extend(w.pid for w in it._workers)
            pf = DevicePrefetcher(it)
            next(pf)
            next(pf)
            # abandon mid-epoch WITHOUT closing: the finalize path must
            # reap the prefetch thread AND the worker processes

        consume()
        gc.collect()
        assert _wait_dead(pids), (
            "DataLoader workers orphaned after a prefetching iterator "
            "was abandoned mid-epoch")

    def test_explicit_close_propagates_to_workers(self):
        loader = DataLoader(_Range(64), batch_size=4, num_workers=2)
        it = iter(loader)
        pids = [w.pid for w in it._workers]
        pf = DevicePrefetcher(it)
        next(pf)
        pf.close()
        assert _wait_dead(pids), (
            "DataLoader workers survived DevicePrefetcher.close()")

    def test_consumer_exception_mid_epoch_reaps_workers(self):
        loader = DataLoader(_Range(64), batch_size=4, num_workers=2)
        pids = []

        def consume():
            it = iter(loader)
            pids.extend(w.pid for w in it._workers)
            for i, _b in enumerate(DevicePrefetcher(it)):
                if i == 2:
                    raise ValueError("consumer blew up")

        with pytest.raises(ValueError):
            consume()
        gc.collect()
        assert _wait_dead(pids), (
            "workers orphaned after consumer exception under prefetch")


# =========================================================================
# Donation — to_static
# =========================================================================
class TestToStaticDonation:
    def _model(self):
        paddle.seed(11)
        return nn.Linear(6, 6)

    def test_donated_call_rebinds_params_and_deletes_old(self):
        lin = self._model()
        step = paddle.jit.to_static(lin.forward, donate=True,
                                    full_graph=True)
        x = paddle.to_tensor(np.ones((2, 6), np.float32))
        old_w = lin.weight._data
        out1 = step(x)
        assert old_w.is_deleted()
        assert not lin.weight._data.is_deleted()
        out2 = step(x)      # params rebound: repeated calls work
        np.testing.assert_allclose(out1.numpy(), out2.numpy(), rtol=1e-6)

    def test_stale_read_raises_framework_error(self):
        lin = self._model()
        step = paddle.jit.to_static(lin.forward, donate=True,
                                    full_graph=True)
        x = paddle.to_tensor(np.ones((2, 6), np.float32))
        stale = Tensor(lin.weight._data)
        step(x)
        with pytest.raises(DonatedBufferError,
                           match="donated"):
            stale.numpy()
        with pytest.raises(DonatedBufferError):
            stale.item(0, 0)

    def test_aliased_params_raise_clear_error(self):
        lin = self._model()
        lin2 = nn.Linear(6, 6)
        lin2.weight._data = lin.weight._data   # shared buffer

        class Both(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = lin
                self.b = lin2

            def forward(self, x):
                return self.b(self.a(x))

        m = Both()
        step = paddle.jit.to_static(m.forward, donate=True,
                                    full_graph=True)
        with pytest.raises(DonatedBufferError, match="share one"):
            step(paddle.to_tensor(np.ones((2, 6), np.float32)))

    def test_flag_off_path_bit_exact(self):
        """donate=False (the default): identical results AND no buffer
        ever deleted — the seed behavior."""
        x = paddle.to_tensor(np.random.RandomState(3).randn(
            4, 6).astype(np.float32))
        lin_a = self._model()
        base = paddle.jit.to_static(lin_a.forward, full_graph=True)(x)
        assert not lin_a.weight._data.is_deleted()
        lin_b = self._model()
        don = paddle.jit.to_static(lin_b.forward, donate=True,
                                   full_graph=True)(x)
        np.testing.assert_array_equal(base.numpy(), don.numpy())

    def test_pcc_key_separates_donated(self):
        lin = self._model()
        f_plain = paddle.jit.to_static(lin.forward, full_graph=True)
        f_don = paddle.jit.to_static(lin.forward, donate=True,
                                     full_graph=True)
        x = paddle.to_tensor(np.ones((2, 6), np.float32))
        sig = ((), (), ((tuple(x.shape), "float32"),))
        params = lin.parameters()
        assert f_plain._pcc_key(sig, params) != f_don._pcc_key(sig,
                                                               params)

    def test_pcc_roundtrip_no_cross_hit(self, tmp_path):
        """A donated program published to the persistent cache must only
        be served to donated wrappers; a fresh undonated wrapper of the
        same function sees a miss (and vice versa)."""
        from paddle_tpu.core import flags as flags_mod

        prev = {k: flags_mod.get_flag(k)
                for k in ("compile_cache", "compile_cache_dir")}
        paddle.set_flags({"FLAGS_compile_cache": True,
                          "FLAGS_compile_cache_dir": str(tmp_path)})
        try:
            x = paddle.to_tensor(np.ones((2, 6), np.float32))

            lin = self._model()
            f_don = paddle.jit.to_static(lin.forward, donate=True,
                                         full_graph=True)
            out_don = f_don(x)            # compiles + publishes donated

            # fresh process-equivalent: new StaticFunction objects over
            # a model with the same weights
            lin2 = self._model()
            f_plain = paddle.jit.to_static(lin2.forward,
                                           full_graph=True)
            out_plain = f_plain(x)        # must NOT hit the donated entry
            assert not lin2.weight._data.is_deleted()
            np.testing.assert_allclose(out_plain.numpy(),
                                       out_don.numpy(), rtol=1e-6)

            lin3 = self._model()
            f_don2 = paddle.jit.to_static(lin3.forward, donate=True,
                                          full_graph=True)
            old = lin3.weight._data
            out2 = f_don2(x)              # donated wrapper may hit —
            assert old.is_deleted()       # and donation still happens
            assert not lin3.weight._data.is_deleted()
            np.testing.assert_allclose(out2.numpy(), out_don.numpy(),
                                       rtol=1e-6)
        finally:
            paddle.set_flags({f"FLAGS_{k}": v for k, v in prev.items()})

    def test_entry_guard_rejects_predeleted_params(self):
        lin = self._model()
        step = paddle.jit.to_static(lin.forward, donate=True,
                                    full_graph=True)
        x = paddle.to_tensor(np.ones((2, 6), np.float32))
        step(x)
        # sabotage: rebind a param to a deleted buffer (simulates a
        # caller feeding stale donated state back in)
        donated = [p._data for p in lin.parameters()]
        fresh = step(x)                   # fine: params are live
        lin.weight._data = donated[0] if donated[0].is_deleted() else \
            lin.weight._data
        if lin.weight._data.is_deleted():
            with pytest.raises(DonatedBufferError, match="entry"):
                step(x)
        del fresh


# =========================================================================
# Donation — Engine + async loss + prefetch parity
# =========================================================================
class _XY(Dataset):
    def __init__(self, n=48):
        self.n = n

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        return (rng.randn(8).astype(np.float32),
                rng.randn(2).astype(np.float32))

    def __len__(self):
        return self.n


class TestEngineAsync:
    def _run(self, epochs=1, **kw):
        from paddle_tpu.distributed.auto_parallel.engine import Engine
        from paddle_tpu.optimizer import Adam

        paddle.seed(5)
        np.random.seed(5)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        opt = Adam(learning_rate=1e-3, parameters=m.parameters())
        e = Engine(m, loss=lambda o, t: paddle.ops.mean((o - t) ** 2),
                   optimizer=opt, **kw)
        hist = e.fit(_XY(), epochs=epochs, batch_size=8)
        return hist, m

    def test_parity_across_async_knobs(self):
        base, _ = self._run(donate=False, prefetch=False)
        for kw in ({"donate": True, "prefetch": False},
                   {"donate": False, "prefetch": True},
                   {"donate": True, "prefetch": True}):
            hist, m = self._run(**kw)
            assert hist == pytest.approx(base, rel=1e-5), kw
            assert all(not p._data.is_deleted()
                       for p in m.parameters()), kw

    def test_history_finite_and_per_epoch(self):
        hist, _ = self._run(epochs=2, donate=True, prefetch=True)
        assert len(hist) == 2
        assert all(np.isfinite(h) for h in hist)

    def test_abort_mid_fit_writes_back_live_params(self):
        from paddle_tpu.distributed.auto_parallel.engine import Engine
        from paddle_tpu.optimizer import Adam

        class Exploding(_XY):
            def __getitem__(self, i):
                if i >= 24:
                    raise RuntimeError("loader died mid-epoch")
                return super().__getitem__(i)

        paddle.seed(5)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        opt = Adam(learning_rate=1e-3, parameters=m.parameters())
        e = Engine(m, loss=lambda o, t: paddle.ops.mean((o - t) ** 2),
                   optimizer=opt, donate=True)
        with pytest.raises(RuntimeError, match="loader died"):
            e.fit(Exploding(), epochs=1, batch_size=8)
        # donation invalidated the pre-fit payloads; the finally-block
        # writeback must leave every Parameter on a LIVE buffer
        for p in m.parameters():
            assert not p._data.is_deleted()
            p.numpy()                      # readable, no DonatedBufferError

    def test_engine_census_recorded(self):
        from paddle_tpu.observability.perf import memory as mem

        mem.reset_high_water()
        self._run(donate=True, prefetch=True)
        assert mem.high_water("engine_step_donated")["total"] > 0


# =========================================================================
# hapi Model.fit under the async pipeline (satellite)
# =========================================================================
class TestHapiAsyncNonfinite:
    def test_nonfinite_loss_skips_step_under_prefetch(self):
        from paddle_tpu.core import flags as flags_mod
        from paddle_tpu.fault import inject
        from paddle_tpu.hapi.model import Model
        from paddle_tpu.optimizer import SGD

        assert flags_mod.get_flag("prefetch"), \
            "prefetch must be ON by default in hapi fit"
        paddle.seed(9)
        net = nn.Linear(4, 2)
        model = Model(net)
        model.prepare(
            optimizer=SGD(learning_rate=0.1,
                          parameters=net.parameters()),
            loss=lambda o, t: paddle.ops.mean((o - t) ** 2))

        class DS(Dataset):
            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                return (rng.randn(4).astype(np.float32),
                        rng.randn(2).astype(np.float32))

            def __len__(self):
                return 16

        inject.arm("grads.nan_at_step", step=2)
        try:
            before = None
            hist = None
            w_before_nan = None
            # the concrete-loss materialization happens inside
            # train_batch, BEFORE the optimizer step — a NaN loss under
            # the async pipeline must still be caught
            hist = model.fit(DS(), epochs=1, batch_size=4, verbose=0)
        finally:
            inject.disarm("grads.nan_at_step")
        assert model._nonfinite_steps == 1
        # weights stayed finite: the poisoned grads never applied
        assert np.isfinite(net.weight.numpy()).all()
        assert hist is not None

    def test_fit_prefetch_off_flag(self):
        from paddle_tpu.hapi.model import Model
        from paddle_tpu.optimizer import SGD

        prev = paddle.get_flags("FLAGS_prefetch")["FLAGS_prefetch"]
        paddle.set_flags({"FLAGS_prefetch": False})
        try:
            paddle.seed(9)
            net = nn.Linear(4, 2)
            model = Model(net)
            model.prepare(
                optimizer=SGD(learning_rate=0.1,
                              parameters=net.parameters()),
                loss=lambda o, t: paddle.ops.mean((o - t) ** 2))

            class DS(Dataset):
                def __getitem__(self, i):
                    rng = np.random.RandomState(i)
                    return (rng.randn(4).astype(np.float32),
                            rng.randn(2).astype(np.float32))

                def __len__(self):
                    return 16

            hist = model.fit(DS(), epochs=1, batch_size=4, verbose=0)
            assert hist
        finally:
            paddle.set_flags({"FLAGS_prefetch": prev})


# =========================================================================
# Decomposed gathers
# =========================================================================
class TestDecomposedGather:
    def test_plan_groups_budget_and_order(self):
        from paddle_tpu.distributed.sharding import plan_groups

        paddle.seed(1)
        params = [nn.Linear(32, 32).weight for _ in range(6)]
        nbytes = int(params[0]._data.nbytes)
        groups = plan_groups(params, max_bytes=2 * nbytes)
        assert all(len(g) <= 2 for g in groups)
        flat = [p for g in groups for p in g]
        assert [p.name for p in flat] == [p.name for p in params]

    def test_gather_grouped_installs_target_layout(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.distributed.sharding import gather_grouped

        prev = mesh_mod._global_mesh
        try:
            mesh_mod._global_mesh = None
            mesh = mesh_mod.build_mesh({"sharding": 4},
                                       devices=jax.devices()[:4])
            mesh_mod.set_mesh(mesh)
            paddle.seed(1)
            params = [nn.Linear(16, 16).weight for _ in range(5)]
            vals = [p.numpy() for p in params]
            sharded = NamedSharding(mesh, P("sharding"))
            for p in params:
                p._data = jax.device_put(p._data, sharded)
            rep = NamedSharding(mesh, P())
            gather_grouped([(p, rep) for p in params], site="test",
                           max_bytes=2 * int(params[0]._data.nbytes))
            for p, v in zip(params, vals):
                assert p._data.sharding.spec == P()
                np.testing.assert_allclose(p.numpy(), v, rtol=1e-6)
        finally:
            mesh_mod._global_mesh = prev

    def test_zero_levels_parity_and_stage3_schedule(self):
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.distributed.sharding import (
            GroupShardedStage3, group_sharded_parallel)
        from paddle_tpu.optimizer import Adam

        prev = mesh_mod._global_mesh
        try:
            mesh_mod._global_mesh = None
            mesh_mod.set_mesh(mesh_mod.build_mesh(
                {"sharding": 4}, devices=jax.devices()[:4]))
            x = paddle.to_tensor(np.random.RandomState(0).randn(
                8, 16).astype(np.float32))
            y = paddle.to_tensor(np.random.RandomState(1).randn(
                8, 4).astype(np.float32))

            def fresh():
                paddle.seed(0)
                m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                                  nn.Linear(32, 32), nn.ReLU(),
                                  nn.Linear(32, 4))
                return m, Adam(learning_rate=1e-3,
                               parameters=m.parameters())

            m0, _ = fresh()
            ref = float(paddle.ops.mean((m0(x) - y) ** 2).numpy())
            finals = {}
            for level in ("os", "os_g", "p_g_os"):
                m, opt = fresh()
                wm, wo, _ = group_sharded_parallel(m, opt, level)
                for it in range(3):
                    loss = paddle.ops.mean((wm(x) - y) ** 2)
                    if it == 0:
                        assert float(loss.numpy()) == pytest.approx(
                            ref, rel=1e-4), level
                    loss.backward()
                    wo.step()
                    wo.clear_grad()
                finals[level] = float(
                    paddle.ops.mean((wm(x) - y) ** 2).numpy())
                if isinstance(wm, GroupShardedStage3):
                    assert wm._gather_schedule is not None
                    assert wm._gather_schedule._groups
            # every level trained to the same loss
            vals = list(finals.values())
            assert max(vals) - min(vals) < 1e-4, finals
        finally:
            mesh_mod._global_mesh = prev

    def test_stage3_save_roundtrip_stays_sharded(self, tmp_path):
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.distributed.sharding import (
            group_sharded_parallel, save_group_sharded_model)
        from paddle_tpu.optimizer import Adam

        prev = mesh_mod._global_mesh
        try:
            mesh_mod._global_mesh = None
            mesh_mod.set_mesh(mesh_mod.build_mesh(
                {"sharding": 4}, devices=jax.devices()[:4]))
            paddle.seed(0)
            m = nn.Sequential(nn.Linear(16, 32), nn.Linear(32, 4))
            opt = Adam(learning_rate=1e-3, parameters=m.parameters())
            wm, wo, _ = group_sharded_parallel(m, opt, "p_g_os")
            save_group_sharded_model(wm, str(tmp_path / "ck"))
            # post-save the ZeRO-3 placement is restored
            w = m[0].weight._data
            assert w.sharding.spec != P()
        finally:
            mesh_mod._global_mesh = prev

    def test_stage3_schedule_installs_split_groups(self):
        """A byte-budget split INSIDE one sublayer must still install
        every group — a min-index-only hook would leave the tail group
        staged (replicated copy pinned) but never installed."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.distributed.fleet.meta_optimizers. \
            dygraph_sharding_optimizer import shard_spec_for
        from paddle_tpu.distributed.sharding import Stage3GatherSchedule

        prev = mesh_mod._global_mesh
        try:
            mesh_mod._global_mesh = None
            mesh = mesh_mod.build_mesh({"sharding": 4},
                                       devices=jax.devices()[:4])
            mesh_mod.set_mesh(mesh)
            paddle.seed(6)
            big = nn.Linear(64, 64)
            shardings = {}
            for p in big.parameters():
                spec = shard_spec_for(p.shape, 4, "sharding")
                if spec is not None:
                    sh = NamedSharding(mesh, spec)
                    p._data = jax.device_put(p._data, sh)
                    shardings[p.name] = sh
            sched = Stage3GatherSchedule(
                big, shardings, NamedSharding(mesh, P()),
                max_bytes=int(big.weight._data.nbytes) // 2 + 1)
            assert len(sched._groups) >= 2
            sched.begin_step()
            big(paddle.to_tensor(np.ones((4, 64), np.float32)))
            assert sched._installed == set(range(len(sched._groups)))
            assert not sched._staged     # nothing pinned in staging
        finally:
            mesh_mod._global_mesh = prev

    def test_gather_groups_metric(self):
        from paddle_tpu.core import flags as flags_mod
        from paddle_tpu.observability.metrics import REGISTRY

        prev = flags_mod.get_flag("enable_metrics")
        paddle.set_flags({"FLAGS_enable_metrics": True})
        try:
            self.test_gather_grouped_installs_target_layout()
            snap = REGISTRY.snapshot()
            fam = snap.get("paddle_tpu_sharding_gather_groups_total")
            assert fam is not None
            assert any(s["value"] > 0 for s in fam["series"])
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": prev})


# =========================================================================
# perf layer: donated census + alias-aware peak
# =========================================================================
class TestPerfDonationAccounting:
    def test_census_counts_deleted_buffers_as_zero(self):
        from paddle_tpu.observability.perf import memory as mem

        big = jnp.ones((256, 256), jnp.float32)
        holder = [big]
        pid = mem.register_provider("kv_cache", lambda: list(holder))
        try:
            before = mem.census()["kv_cache"]
            assert before >= big.nbytes
            step = jax.jit(lambda a: a + 1, donate_argnums=(0,))
            out = step(big)
            assert big.is_deleted()
            after = mem.census()["kv_cache"]
            assert after == 0.0
            del out
        finally:
            mem.unregister_provider(pid)

    def test_record_compiled_alias_bytes_lower_peak(self):
        from paddle_tpu.observability.perf import device as pdev

        def f(state):
            return [s * 2 for s in state]

        args = [jnp.ones((128, 128)) for _ in range(4)]
        plain = jax.jit(f).lower(args).compile()
        donated = jax.jit(f, donate_argnums=(0,)).lower(args).compile()
        rec_plain = pdev.record_compiled("test", "plain", plain)
        rec_don = pdev.record_compiled("test", "donated", donated)
        assert rec_plain is not None and rec_don is not None
        if rec_don["alias_bytes"]:
            assert rec_don["peak_bytes"] < rec_plain["peak_bytes"]


# =========================================================================
# fleet_trace transfer/compute overlap report (satellite)
# =========================================================================
class TestTransferComputeOverlap:
    def test_synthetic_overlap_detected(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        from tools.fleet_trace import transfer_compute_overlap

        mk = lambda cat, t0, dur, tid=0: {
            "name": "s", "cat": cat, "ph": "X", "pid": 0, "tid": tid,
            "ts": int(t0 * 1e6), "dur": int(dur * 1e6)}
        # io [0,10ms) ∥ device [5,20ms): 5ms overlap
        trace = {"traceEvents": [mk("io", 0.0, 0.010, tid=451),
                                 mk("device", 0.005, 0.020, tid=460)]}
        rep = transfer_compute_overlap(trace)
        assert rep[0]["overlap_s"] == pytest.approx(0.005, abs=1e-6)
        assert rep[0]["overlap_frac_of_io"] == pytest.approx(0.5,
                                                             abs=1e-3)

    def test_no_overlap_when_serial(self):
        from tools.fleet_trace import transfer_compute_overlap

        mk = lambda cat, t0, dur: {
            "name": "s", "cat": cat, "ph": "X", "pid": 0, "tid": 0,
            "ts": int(t0 * 1e6), "dur": int(dur * 1e6)}
        trace = {"traceEvents": [mk("io", 0.0, 0.005),
                                 mk("device", 0.005, 0.010)]}
        rep = transfer_compute_overlap(trace)
        assert rep[0]["overlap_s"] == 0.0

    def test_end_to_end_prefetched_loop_shows_overlap(self, tmp_path):
        """A real prefetched train loop, profiled and exported: the
        merged timeline must VISIBLY show transfer/compute overlap —
        the async runtime's acceptance evidence."""
        from paddle_tpu import profiler
        from paddle_tpu.observability.perf.device import timed_section
        from tools.fleet_trace import (merge_traces,
                                       transfer_compute_overlap)

        paddle.seed(3)
        w = jnp.asarray(np.random.RandomState(0).randn(
            256, 256).astype(np.float32))

        @jax.jit
        def step(w, x):
            for _ in range(8):
                x = jnp.tanh(x @ w)
            return x

        batches = [np.random.RandomState(i).randn(
            256, 256).astype(np.float32) for i in range(6)]
        # warm
        jax.block_until_ready(step(w, jnp.asarray(batches[0])))

        def place(b):
            time.sleep(0.002)    # representative host-side fetch work
            return jnp.asarray(b)

        prof = profiler.Profiler(
            on_trace_ready=profiler.export_chrome_tracing(
                str(tmp_path)))
        prof.start()
        pf = DevicePrefetcher(iter(batches), depth=2, place_fn=place)
        try:
            out = None
            for x in pf:
                with timed_section("train") as ts:
                    out = ts.track(step(w, x))
        finally:
            pf.close()
        prof.stop()
        trace_file = prof.trace_path
        merged = merge_traces([trace_file])
        rep = transfer_compute_overlap(merged)
        total_overlap = sum(o["overlap_s"] for o in rep.values())
        total_io = sum(o["io_s"] for o in rep.values())
        assert total_io > 0, "no io.prefetch spans in the timeline"
        assert total_overlap > 0, (
            "prefetch transfer never overlapped device compute "
            f"(report: {rep})")
