"""In-process units for the self-healing fleet supervisor
(paddle_tpu/fault/supervisor.py).

The real 4-process drills live in test_multiproc_train.py
(fault_drill_worker.py); these units pin the pieces those drills
compose: the exit-code taxonomy the elastic agent keys restarts off,
lease staleness judgement, cross-rank consensus (both transports),
the collective-timeout monitor's arm/disarm lifecycle and verdict
path, bounded sentinel remediation, and the consensus-bounded
checkpoint restore.
"""
import json
import os
import threading
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from paddle_tpu.core import flags  # noqa: E402
from paddle_tpu.fault import CheckpointManager  # noqa: E402
from paddle_tpu.fault import capture_train_state  # noqa: E402
from paddle_tpu.fault import supervisor as sup  # noqa: E402
from paddle_tpu.fault.checkpoint_manager import auto_resume  # noqa: E402
from paddle_tpu.observability import flight  # noqa: E402


class _Net:
    def __init__(self):
        self.w = np.zeros(3, np.float32)

    def state_dict(self):
        return {"w": self.w.copy()}

    def set_state_dict(self, sd):
        self.w = np.asarray(sd["w"], np.float32).copy()


# ------------------------------------------------------------ exit codes
def test_exit_code_taxonomy():
    """The elastic agent's restart decision table: supervisor fault
    codes and signal deaths spend a restart; config errors never do."""
    for code in (sup.EXIT_COLLECTIVE_TIMEOUT, sup.EXIT_HEARTBEAT_LOST,
                 sup.EXIT_DESYNC, sup.EXIT_WATCHDOG_HANG):
        assert sup.restart_worthy(code), code
    assert sup.restart_worthy(-9)        # SIGKILL (OOM killer, preempt)
    assert sup.restart_worthy(1)         # generic crash
    assert not sup.restart_worthy(sup.EXIT_CONFIG)
    assert not sup.restart_worthy(2)     # argparse usage error
    assert not sup.restart_worthy(0)
    assert not sup.restart_worthy(None)

    assert "SIGKILL" in sup.describe_exit(-9)
    assert "COLLECTIVE_TIMEOUT" in sup.describe_exit(117)
    assert "HEARTBEAT_LOST" in sup.describe_exit(118)
    assert "CONFIG" in sup.describe_exit(113)
    assert sup.describe_exit(None) == "running"
    # the five codes must be distinct and outside the shell's common set
    codes = [sup.EXIT_CONFIG, sup.EXIT_COLLECTIVE_TIMEOUT,
             sup.EXIT_HEARTBEAT_LOST, sup.EXIT_DESYNC,
             sup.EXIT_WATCHDOG_HANG]
    assert len(set(codes)) == 5
    assert all(2 < c < 126 for c in codes)


# ----------------------------------------------------------- file lease
def test_file_lease_staleness_is_freshest_relative(tmp_path):
    """A rank is dead only when it lags the FRESHEST stamp by ttl — a
    slow observer cannot fake everyone else's death."""
    d = str(tmp_path)
    lease = sup.FileLease(d, rank=0, world=3, ttl=1.0)
    lease.publish()
    now = time.time()
    # rank 1: fresh; rank 2: 5 s behind the freshest stamp -> dead
    for r, ts in ((1, now), (2, now - 5.0)):
        with open(os.path.join(d, f"lease.r{r}"), "w") as f:
            f.write(repr(ts))
    assert lease.dead_ranks() == [2]
    # everyone equally old -> nobody dead (the job is just slow)
    for r in range(3):
        with open(os.path.join(d, f"lease.r{r}"), "w") as f:
            f.write(repr(now - 100.0))
    assert lease.dead_ranks() == []


def test_supervisor_detects_dead_rank(tmp_path):
    """The in-process loop notices an expired peer lease and fires the
    on_dead callback (exit_on_dead off so the test survives)."""
    d = str(tmp_path)
    seen = []
    lease = sup.FileLease(d, rank=0, world=2, ttl=0.4)
    s = sup.Supervisor(lease, interval=0.1, on_dead=seen.append,
                       exit_on_dead=False)
    # peer published once, then went silent
    with open(os.path.join(d, "lease.r1"), "w") as f:
        f.write(repr(time.time()))
    s.start()
    try:
        assert sup.get() is s
        deadline = time.monotonic() + 5.0
        while not seen and time.monotonic() < deadline:
            time.sleep(0.05)
        assert seen and seen[0] == [1], seen
        assert s.dead == [1]
    finally:
        s.stop()
    assert sup.get() is None


def test_supervisor_detects_own_lease_loss(tmp_path, capsys):
    """The PARTITIONED side: our own stamp is the stale one (peers look
    fresh), so the abort message says so and the exit code is still the
    coordinated EXIT_HEARTBEAT_LOST."""
    d = str(tmp_path)
    lease = sup.FileLease(d, rank=0, world=2, ttl=0.5)
    with open(os.path.join(d, "lease.r0"), "w") as f:
        f.write(repr(time.time() - 60.0))
    with open(os.path.join(d, "lease.r1"), "w") as f:
        f.write(repr(time.time()))
    assert lease.dead_ranks() == [0]
    codes = []
    old = sup._exit["fn"]
    sup._exit["fn"] = codes.append
    try:
        s = sup.Supervisor(lease, interval=0.1)
        s._handle_dead(lease.dead_ranks())
    finally:
        sup._exit["fn"] = old
    assert codes == [sup.EXIT_HEARTBEAT_LOST]
    err = capsys.readouterr().err
    assert "including OWN lease (partitioned)" in err
    assert "aborting coordinated" in err


# ------------------------------------------------------------- consensus
def test_consensus_step_single_world():
    assert sup.consensus_step([3, 5, 1], rank=0, world=1) == 5
    assert sup.consensus_step([], rank=0, world=1) is None


def test_consensus_step_kv_transport():
    """Two 'ranks' (threads) exchange split manifests through a live KV
    master: rank 0 saved {1..5}, rank 1 stalled at {1,2,3} -> the
    consensus is 3, the newest step present on EVERY rank."""
    from paddle_tpu.distributed.launch.kv_server import KVServer
    srv = KVServer(0, host="127.0.0.1").start()
    try:
        master = f"127.0.0.1:{srv.port}"
        results = {}

        def run(rank, steps):
            results[rank] = sup.consensus_step(
                steps, rank=rank, world=2, kv=master, epoch=7,
                timeout=10.0)

        t0 = threading.Thread(target=run, args=(0, [1, 2, 3, 4, 5]))
        t1 = threading.Thread(target=run, args=(1, [3, 2, 1]))
        t0.start(); t1.start(); t0.join(10); t1.join(10)
        assert results == {0: 3, 1: 3}

        # disjoint manifests -> None (resume from scratch, not diverge)
        def run2(rank, steps):
            results[rank] = sup.consensus_step(
                steps, rank=rank, world=2, kv=master, epoch=8,
                timeout=10.0)

        t0 = threading.Thread(target=run2, args=(0, [4, 5]))
        t1 = threading.Thread(target=run2, args=(1, [1, 2]))
        t0.start(); t1.start(); t0.join(10); t1.join(10)
        assert results == {0: None, 1: None}
    finally:
        srv.stop()


def test_consensus_kv_times_out_on_missing_rank():
    from paddle_tpu.distributed.launch.kv_server import KVServer
    srv = KVServer(0, host="127.0.0.1").start()
    try:
        with pytest.raises(TimeoutError, match=r"ranks \[1\] never"):
            sup.consensus_step([1, 2], rank=0, world=2,
                               kv=f"127.0.0.1:{srv.port}", epoch=9,
                               timeout=1.5)
    finally:
        srv.stop()


def test_checkpoint_restore_bounded_by_consensus(tmp_path):
    """max_step filters the candidate walk: newer-than-consensus
    checkpoints are skipped unilaterally (they exist on this rank but
    not on every rank), not burned as corrupt."""
    net = _Net()
    mgr = CheckpointManager(str(tmp_path), keep_n=5)
    for s in range(1, 5):
        net.w[:] = float(s)
        mgr.save(capture_train_state(network=net), step=s)
    assert mgr.steps() == [4, 3, 2, 1]

    net.w[:] = -1.0
    meta = auto_resume(mgr, network=net, max_step=2)
    assert meta is not None and meta["step"] == 2
    np.testing.assert_allclose(net.w, 2.0)
    # unbounded resume still takes the newest
    meta = auto_resume(mgr, network=net)
    assert meta["step"] == 4
    np.testing.assert_allclose(net.w, 4.0)


def test_consensus_resume_single_process(tmp_path):
    """world==1 degrades to plain auto_resume (no exchange)."""
    net = _Net()
    mgr = CheckpointManager(str(tmp_path), keep_n=3)
    net.w[:] = 7.0
    mgr.save(capture_train_state(network=net), step=7)
    net.w[:] = 0.0
    meta = sup.consensus_resume(mgr, network=net)
    assert meta["step"] == 7
    np.testing.assert_allclose(net.w, 7.0)


# ------------------------------------------- collective-timeout monitor
def test_monitor_thread_tracks_flag():
    """Disarmed = NO thread (the zero-cost claim is structural); arming
    the flag starts it, disarming joins it."""
    assert float(flags.get_flag("collective_timeout_s") or 0.0) == 0.0
    assert sup._monitor["thread"] is None
    flags.set_flags({"collective_timeout_s": 5.0})
    try:
        th = sup._monitor["thread"]
        assert th is not None and th.is_alive()
    finally:
        flags.set_flags({"collective_timeout_s": 0.0})
    deadline = time.monotonic() + 3.0
    while sup._monitor["thread"] is not None \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    assert sup._monitor["thread"] is None


def test_abort_on_timeout_verdict_and_exit(capsys):
    """The abort path names the overdue collective and exits 117
    (world==1 here, so no dump exchange — the multi-process naming is
    the hang drill's job)."""
    codes = []
    old = sup._exit["fn"]
    sup._exit["fn"] = codes.append
    try:
        rec = {"seq": 42, "op": "all_reduce", "group": 0,
               "shape": (4,), "dtype": "float32", "bytes": 16,
               "t0": time.perf_counter() - 3.0, "t1": None}
        sup._abort_on_timeout(rec, age=3.0, timeout_s=2.0)
    finally:
        sup._exit["fn"] = old
    assert codes == [sup.EXIT_COLLECTIVE_TIMEOUT]
    err = capsys.readouterr().err
    assert "collective seq=42 op=all_reduce" in err
    assert "FLAGS_collective_timeout_s=2" in err


def test_diff_ranks_names_missing_rank():
    """world= pads absent dumps with empty rings: a SIGKILLed rank that
    never wrote a dump is named by its ABSENCE."""
    ent = {"seq": 3, "op": "all_reduce", "group": 0, "shape": (4,),
           "dtype": "float32", "bytes": 16, "t0": 0.0, "t1": None}
    dumps = {0: {"entries": [ent]}}
    v = flight.diff_ranks(dumps, world=2)
    assert v["status"] == "stall" and v["rank"] == 1 and v["seq"] == 3
    assert "rank 1 never issued seq 3" in v["detail"]


# ---------------------------------------------------------- remediation
@pytest.fixture
def _engine():
    eng = sup.RemediationEngine(min_interval_s=0.0, max_per_kind=8)
    eng.start()
    old_flag = bool(flags.get_flag("remediation"))
    flags.set_flags({"remediation": True})
    try:
        yield eng
    finally:
        flags.set_flags({"remediation": old_flag})
        eng.stop()
        sup.register_scaler(None)


def test_remediation_prefetch_depth(_engine):
    old = int(flags.get_flag("prefetch_depth") or 0)
    try:
        _engine.submit({"kind": "data_stall_regression", "step": 10})
        _engine.drain()
        assert int(flags.get_flag("prefetch_depth")) == old + 1
        entry = _engine.audit[-1]
        assert entry["ok"] and entry["action"] == "raise_prefetch_depth"
        assert f"prefetch_depth {old} -> {old + 1}" in entry["detail"]
    finally:
        flags.set_flags({"prefetch_depth": old})


def test_remediation_scaler_backoff(_engine):
    class _Scaler:
        _scale = 8.0

    s = _Scaler()
    sup.register_scaler(s)
    _engine.submit({"kind": "nonfinite_loss", "step": 3})
    _engine.drain()
    assert s._scale == 4.0
    assert "loss-scale backoff 8 -> 4" in _engine.audit[-1]["detail"]
    # at the floor the action reports failure rather than going below 1
    s._scale = 1.0
    _engine.submit({"kind": "nonfinite_loss", "step": 4})
    _engine.drain()
    assert s._scale == 1.0
    assert not _engine.audit[-1]["ok"]
    assert "floor" in _engine.audit[-1]["detail"]


def test_remediation_rate_limit_and_cap():
    eng = sup.RemediationEngine(min_interval_s=3600.0, max_per_kind=8)
    eng.start()
    old_flag = bool(flags.get_flag("remediation"))
    flags.set_flags({"remediation": True})

    class _Scaler:
        _scale = 16.0

    s = _Scaler()
    sup.register_scaler(s)
    try:
        eng.submit({"kind": "nonfinite_loss", "step": 1})
        eng.submit({"kind": "nonfinite_loss", "step": 2})
        eng.drain()
        assert s._scale == 8.0            # exactly one backoff landed
        assert len(eng.audit) == 2
        assert eng.audit[0]["ok"]
        assert "rate-limited" in eng.audit[1]["detail"]
        # unknown kinds never enqueue; flag off drops at the gate
        eng.submit({"kind": "not_a_kind", "step": 3})
        flags.set_flags({"remediation": False})
        eng.submit({"kind": "nonfinite_loss", "step": 4})
        eng.drain()
        assert len(eng.audit) == 2
    finally:
        flags.set_flags({"remediation": old_flag})
        eng.stop()
        sup.register_scaler(None)


def test_remediation_incident_trace_capture(tmp_path, _engine,
                                            monkeypatch):
    monkeypatch.setenv(sup.INCIDENT_TRACE_ENV, str(tmp_path))
    old = int(flags.get_flag("prefetch_depth") or 0)
    try:
        _engine.submit({"kind": "data_stall_regression", "step": 5})
        _engine.drain()
    finally:
        flags.set_flags({"prefetch_depth": old})
    traces = [f for f in os.listdir(str(tmp_path))
              if f.endswith(".trace.json")]
    assert len(traces) == 1, traces
    with open(os.path.join(str(tmp_path), traces[0])) as f:
        doc = json.load(f)
    assert doc["incident"] == {"kind": "data_stall_regression",
                               "action": "raise_prefetch_depth"}
    names = [e["name"] for e in doc["traceEvents"]]
    assert "remediation:raise_prefetch_depth" in names


def test_enable_disable_remediation_lifecycle():
    # earlier flag flips may have built the global engine via the
    # on_change observer — start from a clean slate
    sup.disable_remediation()
    assert sup.remediation_engine() is None
    eng = sup.enable_remediation(min_interval_s=0.0)
    try:
        assert sup.remediation_engine() is eng
        assert flags.get_flag("remediation")
        assert sup.enable_remediation() is eng     # idempotent
    finally:
        sup.disable_remediation()
    assert sup.remediation_engine() is None
    assert not flags.get_flag("remediation")
