"""OpTest-style numeric harness.

Capability parity with the reference's OpTest (test/legacy_test/op_test.py:418):
run an op through the framework, compare outputs against a NumPy reference,
and check analytic gradients against numeric finite differences
(op_test.py:3026 check_grad). Default tolerances mirror the reference
(fp32 1e-5, op_test.py:1084).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle


def check_output(fn, np_fn, inputs, attrs=None, rtol=1e-5, atol=1e-6):
    """fn: framework op over Tensors; np_fn: numpy reference over ndarrays."""
    attrs = attrs or {}
    tensors = [paddle.to_tensor(x) for x in inputs]
    out = fn(*tensors, **attrs)
    ref = np_fn(*inputs, **attrs)
    outs = out if isinstance(out, (list, tuple)) else [out]
    refs = ref if isinstance(ref, (list, tuple)) else [ref]
    assert len(outs) == len(refs)
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(o.numpy(), np.asarray(r), rtol=rtol, atol=atol)


def check_grad(fn, inputs, attrs=None, grad_input_idx=None,
               max_relative_error=5e-3, delta=1e-3):
    """Compare analytic grads (backward through the tape) vs central finite
    differences on a scalar sum-of-outputs loss."""
    attrs = attrs or {}
    # float inputs are canonicalized to f32 for the FD math; integer/bool
    # inputs (indices, masks) must keep their dtype
    inputs = [np.asarray(x).astype(np.float32)
              if np.issubdtype(np.asarray(x).dtype, np.floating)
              else np.asarray(x) for x in inputs]
    idxs = grad_input_idx if grad_input_idx is not None else range(len(inputs))

    def loss_np(arrs):
        tensors = [paddle.to_tensor(a) for a in arrs]
        out = fn(*tensors, **attrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return float(sum(o.sum().item() for o in outs))

    tensors = [paddle.to_tensor(x, stop_gradient=False) for x in inputs]
    out = fn(*tensors, **attrs)
    outs = out if isinstance(out, (list, tuple)) else [out]
    loss = outs[0].sum()
    for o in outs[1:]:
        loss = loss + o.sum()
    loss.backward()

    for i in idxs:
        analytic = tensors[i].grad.numpy().astype(np.float64)
        numeric = np.zeros_like(analytic)
        flat = inputs[i].reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + delta
            hi = loss_np(inputs)
            flat[j] = orig - delta
            lo = loss_np(inputs)
            flat[j] = orig
            numeric.reshape(-1)[j] = (hi - lo) / (2 * delta)
        denom = np.maximum(np.abs(numeric), 1.0)
        err = np.abs(analytic - numeric) / denom
        assert err.max() <= max_relative_error, (
            f"grad mismatch on input {i}: max rel err {err.max():.3e}\n"
            f"analytic:\n{analytic}\nnumeric:\n{numeric}")
