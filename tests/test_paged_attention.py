"""Paged (block-table) KV-cache attention tests.

Reference capability: block_multi_head_attention
(phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu). Oracle:
dense softmax attention over the ragged per-sequence history.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _dense_attn(q, k, v, causal_offset):
    """q (T,H,D), k/v (S,KVH,D) -> (T,H,D) with causal mask at offset."""
    T, H, D = q.shape
    S, KVH, _ = k.shape
    g = H // KVH
    qg = q.reshape(T, KVH, g, D).astype(np.float64)
    s = np.einsum("tkgd,skd->tkgs", qg, k.astype(np.float64)) / np.sqrt(D)
    jpos = np.arange(S)[None, None, None, :]
    qpos = (causal_offset + np.arange(T)).reshape(T, 1, 1, 1)
    s = np.where(jpos <= qpos, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("tkgs,skd->tkgd", p, v.astype(np.float64)).reshape(
        T, H, D)


def _build_cache(rng, lens, bs, H, KVH, D, max_blocks, shuffle=True):
    """Random ragged KV histories scattered into a paged cache."""
    B = len(lens)
    nb = B * max_blocks + 3
    kc = np.zeros((nb, bs, KVH, D), np.float32)
    vc = np.zeros((nb, bs, KVH, D), np.float32)
    ids = np.arange(1, nb)  # keep block 0 unused to catch indexing bugs
    if shuffle:
        rng.shuffle(ids)
    tables = np.zeros((B, max_blocks), np.int32)
    ks, vs = [], []
    pos = 0
    for b in range(B):
        kseq = rng.randn(lens[b], KVH, D).astype(np.float32)
        vseq = rng.randn(lens[b], KVH, D).astype(np.float32)
        ks.append(kseq)
        vs.append(vseq)
        for blk_i in range(max_blocks):
            tables[b, blk_i] = ids[pos]
            lo = blk_i * bs
            chunk = kseq[lo:lo + bs]
            kc[ids[pos], :chunk.shape[0]] = chunk
            vc[ids[pos], :chunk.shape[0]] = vseq[lo:lo + bs]
            pos += 1
    return kc, vc, tables, ks, vs


class TestPagedAttention:
    def test_decode_matches_dense(self):
        rng = np.random.RandomState(0)
        B, H, KVH, D, bs, mb = 3, 4, 4, 16, 8, 4
        lens = [5, 17, 32]
        kc, vc, tables, ks, vs = _build_cache(rng, [l - 1 for l in lens],
                                              bs, H, KVH, D, mb)
        q = rng.randn(B, 1, H, D).astype(np.float32)
        nk = rng.randn(B, 1, KVH, D).astype(np.float32)
        nv = rng.randn(B, 1, KVH, D).astype(np.float32)
        out, kc2, vc2 = F.block_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(kc), paddle.to_tensor(vc),
            paddle.to_tensor(tables), paddle.to_tensor(np.asarray(lens)),
            new_k=paddle.to_tensor(nk), new_v=paddle.to_tensor(nv))
        for b in range(B):
            k_full = np.concatenate([ks[b], nk[b]], axis=0)
            v_full = np.concatenate([vs[b], nv[b]], axis=0)
            ref = _dense_attn(q[b], k_full, v_full, lens[b] - 1)
            np.testing.assert_allclose(out.numpy()[b], ref, atol=2e-5)

    def test_gqa_heads(self):
        rng = np.random.RandomState(1)
        B, H, KVH, D, bs, mb = 2, 8, 2, 8, 4, 3
        lens = [6, 11]
        kc, vc, tables, ks, vs = _build_cache(rng, lens, bs, H, KVH, D, mb)
        q = rng.randn(B, 1, H, D).astype(np.float32)
        out, _, _ = F.block_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(kc), paddle.to_tensor(vc),
            paddle.to_tensor(tables), paddle.to_tensor(np.asarray(lens)))
        for b in range(B):
            ref = _dense_attn(q[b], ks[b][:lens[b]], vs[b][:lens[b]],
                              lens[b] - 1)
            np.testing.assert_allclose(out.numpy()[b], ref, atol=2e-5)

    def test_chunked_prefill_causal(self):
        # T=4 new tokens appended to a 6-token history; each new token must
        # only see history + itself/earlier new tokens
        rng = np.random.RandomState(2)
        B, H, KVH, D, bs, mb = 1, 2, 2, 8, 4, 4
        hist = 6
        T = 4
        kc, vc, tables, ks, vs = _build_cache(rng, [hist], bs, H, KVH, D, mb)
        q = rng.randn(B, T, H, D).astype(np.float32)
        nk = rng.randn(B, T, KVH, D).astype(np.float32)
        nv = rng.randn(B, T, KVH, D).astype(np.float32)
        out, kc2, vc2 = F.block_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(kc), paddle.to_tensor(vc),
            paddle.to_tensor(tables),
            paddle.to_tensor(np.asarray([hist + T])),
            new_k=paddle.to_tensor(nk), new_v=paddle.to_tensor(nv))
        k_full = np.concatenate([ks[0], nk[0]], axis=0)
        v_full = np.concatenate([vs[0], nv[0]], axis=0)
        ref = _dense_attn(q[0], k_full, v_full, hist)
        np.testing.assert_allclose(out.numpy()[0], ref, atol=2e-5)

    def test_cache_write_positions(self):
        # new KV must land exactly at [len-T, len) in logical order
        rng = np.random.RandomState(3)
        B, H, KVH, D, bs, mb = 1, 2, 2, 4, 4, 3
        kc, vc, tables, ks, vs = _build_cache(rng, [5], bs, H, KVH, D, mb,
                                              shuffle=True)
        nk = np.full((1, 2, KVH, D), 7.0, np.float32)
        nv = np.full((1, 2, KVH, D), 9.0, np.float32)
        q = rng.randn(1, 2, H, D).astype(np.float32)
        _, kc2, vc2 = F.block_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(kc), paddle.to_tensor(vc),
            paddle.to_tensor(tables), paddle.to_tensor(np.asarray([7])),
            new_k=paddle.to_tensor(nk), new_v=paddle.to_tensor(nv))
        kc2 = kc2.numpy()
        # logical positions 5, 6 -> block idx 1, offsets 1, 2
        blk = tables[0, 1]
        np.testing.assert_allclose(kc2[blk, 1], 7.0)
        np.testing.assert_allclose(kc2[blk, 2], 7.0)
        # history untouched
        np.testing.assert_allclose(kc2[tables[0, 0]], kc[tables[0, 0]])

    def test_jitted_decode_loop_matches_full_context(self):
        """Greedy paged decode step-by-step == one dense pass (serving
        steady state: the step jits once, caches donated)."""
        import jax
        rng = np.random.RandomState(4)
        H, KVH, D, bs, mb = 2, 2, 8, 4, 4
        S = 10
        ks = rng.randn(S, KVH, D).astype(np.float32)
        vs = rng.randn(S, KVH, D).astype(np.float32)
        qs = rng.randn(S, H, D).astype(np.float32)
        kc = np.zeros((mb + 1, bs, KVH, D), np.float32)
        vc = np.zeros_like(kc)
        tables = np.arange(1, mb + 1, dtype=np.int32)[None]

        kc_t, vc_t = paddle.to_tensor(kc), paddle.to_tensor(vc)
        outs = []
        for t in range(S):
            out, kc_t, vc_t = F.block_multihead_attention(
                paddle.to_tensor(qs[None, t:t + 1]), kc_t, vc_t,
                paddle.to_tensor(tables),
                paddle.to_tensor(np.asarray([t + 1])),
                new_k=paddle.to_tensor(ks[None, t:t + 1]),
                new_v=paddle.to_tensor(vs[None, t:t + 1]))
            outs.append(out.numpy()[0, 0])
        stepped = np.stack(outs)
        ref = _dense_attn(qs, ks, vs, 0)
        np.testing.assert_allclose(stepped, ref, atol=2e-5)

    def test_padded_row_no_corruption_and_zero_output(self):
        # seq_len=0 row with new KV of T=1... pos=-1 must NOT wrap into a
        # live block; its output must be 0, not NaN
        rng = np.random.RandomState(5)
        H, KVH, D, bs, mb = 2, 2, 4, 4, 2
        kc = rng.randn(5, bs, KVH, D).astype(np.float32)
        vc = rng.randn(5, bs, KVH, D).astype(np.float32)
        tables = np.array([[1, 2], [3, 4]], np.int32)
        lens = np.array([0, 3])  # row 0 is padding
        q = rng.randn(2, 1, H, D).astype(np.float32)
        nk = np.full((2, 1, KVH, D), 55.0, np.float32)
        nv = np.full((2, 1, KVH, D), 66.0, np.float32)
        out, kc2, vc2 = F.block_multihead_attention(
            paddle.to_tensor(q), paddle.to_tensor(kc), paddle.to_tensor(vc),
            paddle.to_tensor(tables), paddle.to_tensor(lens),
            new_k=paddle.to_tensor(nk), new_v=paddle.to_tensor(nv))
        o = out.numpy()
        assert np.isfinite(o).all()
        np.testing.assert_allclose(o[0], 0.0)          # padded row -> 0
        kc2 = kc2.numpy()
        # row 1 wrote at logical pos 2 -> block 3 offset 2
        np.testing.assert_allclose(kc2[3, 2], 55.0)
        # no other slot of any block got the 55 write (no wrap into
        # blocks 1/2/4 from the padded row)
        mask = np.ones_like(kc2, bool)
        mask[3, 2] = False
        assert not np.any(kc2[mask] == 55.0)

    def test_tensor_parallel_paged_decode(self):
        """Serving composition: KV-cache heads sharded over the mp axis,
        one jitted decode step with sharded caches (the multi-chip
        serving layout), parity vs the unsharded computation."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.distributed import mesh as mesh_mod

        if len(jax.devices()) < 4:
            pytest.skip("needs >= 4 devices")
        mesh = mesh_mod.build_mesh({"dp": 2, "mp": 2},
                                   devices=jax.devices()[:4])
        # save WITHOUT the lazy-create side effect of get_mesh()
        prev = mesh_mod._global_mesh
        mesh_mod.set_mesh(mesh)
        try:
            rng = np.random.RandomState(7)
            B, H, KVH, D, bs, mb = 2, 4, 4, 8, 4, 3
            lens = np.array([5, 9])
            kc, vc, tables, ks, vs = _build_cache(rng, lens, bs, H, KVH,
                                                  D, mb)
            q = rng.randn(B, 1, H, D).astype(np.float32)
            # reference (unsharded) output
            ref, _, _ = F.block_multihead_attention(
                paddle.to_tensor(q), paddle.to_tensor(kc),
                paddle.to_tensor(vc), paddle.to_tensor(tables),
                paddle.to_tensor(lens))
            # shard caches + queries over mp (head axis), batch over dp
            kv_sh = NamedSharding(mesh, P(None, None, "mp", None))
            q_sh = NamedSharding(mesh, P("dp", None, "mp", None))
            kc_d = jax.device_put(kc, kv_sh)
            vc_d = jax.device_put(vc, kv_sh)
            q_d = jax.device_put(q, q_sh)
            out, _, _ = F.block_multihead_attention(
                paddle.Tensor(q_d), paddle.Tensor(kc_d),
                paddle.Tensor(vc_d), paddle.to_tensor(tables),
                paddle.to_tensor(lens))
            np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                       atol=2e-5)
        finally:
            mesh_mod.set_mesh(prev)  # restore exactly, including None
