"""Multi-host execution tests: 2-process rendezvous + real cross-process
collective + elastic kill/restart, and cross-process RPC.

Reference contracts: launch/controllers/master.py (HTTPMaster rendezvous),
fleet/elastic/manager.py:124 (lease-driven membership -> relaunch
decisions), distributed/rpc/rpc.py (init_rpc/rpc_sync across workers).
These run REAL subprocesses on localhost — the closest CPU analog of the
reference's multi-node TestDistBase strategy.
"""
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.distributed.launch.elastic import (ElasticManager,
                                                   parse_nnodes)
from paddle_tpu.distributed.launch.kv_server import (Heartbeat, KVClient,
                                                     KVServer)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""   # skip the TPU register hook
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""              # no virtual 8-device mesh in workers
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _free_port_pair():
    """A port whose successor is also free: the launcher binds the KV
    master on master_port + 1."""
    import socket
    for _ in range(50):
        s1 = socket.socket()
        s1.bind(("127.0.0.1", 0))
        port = s1.getsockname()[1]
        s2 = socket.socket()
        try:
            s2.bind(("127.0.0.1", port + 1))
        except OSError:
            continue
        finally:
            s1.close()
            s2.close()
        return port
    raise RuntimeError("no consecutive free port pair found")


class TestParseNnodes:
    def test_forms(self):
        assert parse_nnodes(2) == (2, 2)
        assert parse_nnodes("2:4") == (2, 4)
        with pytest.raises(ValueError):
            parse_nnodes("0:2")


class TestElasticDecisions:
    def _mgr(self, master, nnodes="1:4"):
        return ElasticManager(master, 0, nnodes=nnodes, grace=1.0,
                              interval=0.3, job_id="dec")

    def test_decide_pure(self):
        kv = KVServer(0).start()
        try:
            m = self._mgr(f"127.0.0.1:{kv.port}")
            assert m.decide([0, 1], [0, 1]) == ("noop", [0, 1])
            assert m.decide([0, 1], [0]) == ("rescale", [0])
            assert m.decide([0], [0, 1]) == ("rescale", [0, 1])
            m2 = ElasticManager(f"127.0.0.1:{kv.port}", 0, nnodes="2:4",
                                job_id="dec2")
            assert m2.decide([0, 1], [0])[0] == "fail"
            m3 = ElasticManager(f"127.0.0.1:{kv.port}", 0, nnodes="1:2",
                                job_id="dec3")
            # scale-out capped at max_nodes
            assert m3.decide([0, 1], [0, 1, 2]) == ("noop", [0, 1])
        finally:
            kv.stop()

    def test_watch_scale_in_and_out(self):
        kv = KVServer(0).start()
        master = f"127.0.0.1:{kv.port}"
        try:
            mgr = ElasticManager(master, 0, nnodes="1:2", grace=1.2,
                                 interval=0.3, job_id="watch")
            hb1 = Heartbeat(master, 1, job_id="watch", interval=0.3,
                            ttl=1.2).start()
            mgr.start(initial_world=[0, 1])
            time.sleep(1.0)
            assert mgr.current_epoch() == 0  # both beating: no decision

            hb1.stop()                        # node 1 dies -> scale-in
            t0 = time.time()
            while mgr.current_epoch() < 1 and time.time() - t0 < 15:
                time.sleep(0.2)
            assert mgr.current_epoch() >= 1
            assert mgr.current_world() == [0]

            hb1 = Heartbeat(master, 1, job_id="watch", interval=0.3,
                            ttl=1.2).start()  # node 1 returns -> scale-out
            t0 = time.time()
            while (mgr.current_world() != [0, 1]
                   and time.time() - t0 < 15):
                time.sleep(0.2)
            assert mgr.current_world() == [0, 1]
            hb1.stop()
            mgr.stop()
        finally:
            kv.stop()


class TestCrossProcessRpc:
    WORKER = r"""
import os, sys, operator
sys.path.insert(0, {repo!r})
from paddle_tpu.distributed import rpc
rank = int(sys.argv[1]); master = sys.argv[2]
me = f"worker{{rank}}".format(rank=rank)
rpc.init_rpc(me, rank=rank, world_size=2, master_endpoint=master)
peer = "worker%d" % (1 - rank)
out = rpc.rpc_sync(peer, operator.add, args=(10 * (rank + 1), 5))
assert out == 10 * (rank + 1) + 5, out
fut = rpc.rpc_async(peer, operator.mul, args=(3, 4))
assert fut.result() == 12
print("rpc-ok", rank, flush=True)
rpc.shutdown()
"""

    def test_two_process_rpc(self, tmp_path):
        kv = KVServer(0).start()
        master = f"127.0.0.1:{kv.port}"
        script = tmp_path / "rpc_worker.py"
        script.write_text(self.WORKER.format(repo=REPO))
        env = _clean_env()
        try:
            procs = [subprocess.Popen(
                [sys.executable, str(script), str(r), master],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True) for r in range(2)]
            outs = [p.communicate(timeout=120)[0] for p in procs]
            for r, (p, out) in enumerate(zip(procs, outs)):
                assert p.returncode == 0, f"rank {r} failed:\n{out}"
                assert f"rpc-ok {r}" in out
        finally:
            kv.stop()


COLLECTIVE_WORKER = r"""
import os, sys
rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
epoch = int(os.environ.get("PADDLE_ELASTIC_EPOCH", "0"))
outdir = sys.argv[1]
import jax
jax.config.update("jax_platforms", "cpu")
try:  # CPU cross-process collectives need an explicit transport here
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
jax.distributed.initialize(
    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
    num_processes=world, process_id=rank)
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(jax.devices(), ("dp",))
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), jnp.ones((1, 4)) * (rank + 1),
    (world, 4))
tot = jax.jit(lambda a: jnp.sum(a),
              out_shardings=NamedSharding(mesh, P()))(x)
with open(os.path.join(outdir, f"e{epoch}.r{rank}"), "w") as f:
    f.write(str(float(tot)))
jax.distributed.shutdown()
if epoch == 0 and rank == 1:
    os._exit(13)   # simulated failure AFTER the epoch-0 collective
"""


TWO_NODE_WORKER = r"""
import os, sys
rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
epoch = int(os.environ.get("PADDLE_ELASTIC_EPOCH", "0"))
outdir = sys.argv[1]
import jax
jax.config.update("jax_platforms", "cpu")
try:  # CPU cross-process collectives need an explicit transport here
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
jax.distributed.initialize(
    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
    num_processes=world, process_id=rank)
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(jax.devices(), ("dp",))
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), jnp.ones((1, 4)) * (rank + 1),
    (world, 4))
tot = jax.jit(lambda a: jnp.sum(a),
              out_shardings=NamedSharding(mesh, P()))(x)
with open(os.path.join(outdir, f"e{epoch}.r{rank}"), "w") as f:
    f.write(str(float(tot)))
jax.distributed.shutdown()
if epoch == 0 and rank == 1:
    os._exit(13)   # node 1 fails after the epoch-0 collective
"""


class TestTwoNodeElastic:
    def test_two_launchers_epoch_restart(self, tmp_path):
        """Full multi-NODE elastic flow: two launcher processes (one per
        'host') rendezvous through the KV master, their workers form a
        jax.distributed world; node 1's worker dies, node 1's launcher
        publishes a job-wide epoch, BOTH launchers relaunch in step, and
        the finished node waits on job-wide done markers instead of
        abandoning the job."""
        script = tmp_path / "worker.py"
        script.write_text(TWO_NODE_WORKER)
        outdir = tmp_path / "out"
        outdir.mkdir()
        port = _free_port_pair()
        env = _clean_env()

        def launcher(node_rank):
            return subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--nnodes", "2", "--node_rank", str(node_rank),
                 "--nproc_per_node", "1", "--max_restarts", "1",
                 "--master", f"127.0.0.1:{port}",
                 str(script), str(outdir)],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)

        procs = [launcher(0), launcher(1)]
        logs = [p.communicate(timeout=420)[0] for p in procs]
        for r, (p, log) in enumerate(zip(procs, logs)):
            assert p.returncode == 0, f"node {r} launcher:\n{log}"
        assert any("elastic epoch" in l or "published job-wide" in l
                   for l in logs), logs
        for fname in ("e0.r0", "e0.r1", "e1.r0", "e1.r1"):
            f = outdir / fname
            assert f.exists(), f"{fname} missing; logs:\n" + "\n".join(logs)
            assert float(f.read_text()) == 12.0


class TestLaunchElasticCollective:
    def test_rendezvous_collective_kill_restart(self, tmp_path):
        """The round-3 'Done' criterion: 2 processes rendezvous, run a
        REAL cross-process XLA collective (Gloo CPU), one worker dies,
        the launcher group-restarts at the next elastic epoch, and the
        new world completes another collective."""
        script = tmp_path / "collective_worker.py"
        script.write_text(COLLECTIVE_WORKER)
        outdir = tmp_path / "out"
        outdir.mkdir()
        port = _free_port_pair()
        env = _clean_env()
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--max_restarts", "1",
             "--master", f"127.0.0.1:{port}",
             str(script), str(outdir)],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=420)
        log = proc.stdout + proc.stderr
        assert proc.returncode == 0, log
        assert "group restart" in log
        for fname in ("e0.r0", "e0.r1", "e1.r0", "e1.r1"):
            f = outdir / fname
            assert f.exists(), f"{fname} missing; log:\n{log}"
            # sum over global [2,4] of ones*(rank+1) = 4*1 + 4*2 = 12
            assert float(f.read_text()) == 12.0
