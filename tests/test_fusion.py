"""Graph-fusion pass (paddle_tpu/compile/fusion/) — ISSUE 10.

Contracts under test:

* **pattern corpus** — each pattern matches its canonical chain and is
  REJECTED when an interior value is externally visible (fetched /
  multi-consumer) or when an input is not available at the fusion site;
* **parity** — eager-unfused vs fused numerics AND gradients agree per
  pattern, on the XLA composite and on the Pallas kernel path
  (``INTERPRET=True`` runs the real kernel bodies on CPU);
* **cache key separation** — fused and unfused compiles of one program
  never share a persistent-cache entry (the fusion fingerprint rides
  the pcc key);
* **flag off = seed behavior** — with ``FLAGS_enable_fusion=0`` every
  compile path is bit-exact with eager and the pass never runs;
* **spmd** — a fused program propagates over a ``(data, tp)`` mesh with
  ZERO replicate-fallbacks (the fused ops carry named rules);
* **audit** — ``tools/fusion_audit.py`` is clean (docstring + cost
  model + spmd rule + kernel/composite pair per fused op).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.ops as ops
from paddle_tpu import nn, static
from paddle_tpu.compile import fusion
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.models import llama
from paddle_tpu.nn import functional as F
from paddle_tpu.observability import REGISTRY

RNG = np.random.RandomState(7)


def _arr(*shape, scale=1.0):
    return (RNG.randn(*shape) * scale).astype(np.float32)


@pytest.fixture
def fusion_on():
    paddle.set_flags({"FLAGS_enable_fusion": True})
    yield
    paddle.set_flags({"FLAGS_enable_fusion": False})


@pytest.fixture
def fusion_off():
    paddle.set_flags({"FLAGS_enable_fusion": False})
    yield


# ==========================================================================
# pattern corpus over the static.Program op-list IR
# ==========================================================================
class TestPatternCorpus:
    """Build each chain as a static Program and inspect the pass's plan
    (``fuse_program_ops``) directly: what matched, what got rejected."""

    def _program(self, build):
        paddle.enable_static()
        try:
            main, start = static.Program(), static.Program()
            with static.program_guard(main, start):
                fetches = build(main)
            return main, fetches
        finally:
            paddle.disable_static()

    def _run_pass(self, build, fetch_idx):
        main, fetches = self._program(build)
        if not isinstance(fetches, (list, tuple)):
            fetches = [fetches]
        # Executor.run keys fetches by tensor identity (id())
        plan, stats = fusion.fuse_program_ops(
            main._block.ops, [id(fetches[i]) for i in fetch_idx])
        return plan, stats

    def test_norm_linear_act_matches(self):
        w = paddle.to_tensor(_arr(32, 64, scale=0.1))
        b = paddle.to_tensor(_arr(64, scale=0.1))

        def build(main):
            x = static.data("x", [4, 32], "float32")
            h = F.layer_norm(x, [32])
            return F.gelu(F.linear(h, w, b))

        plan, stats = self._run_pass(build, [0])
        assert stats["rewritten"] == {"norm_linear": 1}
        assert stats["rejected"] == {}
        assert [s.name for s in plan] == ["fused_norm_linear"]
        assert plan[0].attrs["activation"] == "gelu"

    def test_gelu_tanh_rides_the_attr(self):
        w = paddle.to_tensor(_arr(32, 64, scale=0.1))

        def build(main):
            x = static.data("x", [4, 32], "float32")
            return F.gelu(F.linear(F.rms_norm(x), w), approximate=True)

        plan, stats = self._run_pass(build, [0])
        assert stats["rewritten"] == {"norm_linear": 1}
        assert plan[0].attrs["activation"] == "gelu_tanh"
        assert plan[0].attrs["norm_type"] == "rms_norm"

    def test_interior_fetch_rejects(self):
        """The norm output is ALSO fetched: swallowing it would change
        observable behavior, so the candidate must be rejected."""
        w = paddle.to_tensor(_arr(32, 64, scale=0.1))

        def build(main):
            x = static.data("x", [4, 32], "float32")
            h = F.layer_norm(x, [32])
            return h, F.gelu(F.linear(h, w))

        plan, stats = self._run_pass(build, [0, 1])
        # the WIDE candidate (norm swallowed) is rejected; the narrow
        # linear→act pair is still legal (the fetched norm output is an
        # INPUT of that chain, not interior) and fuses on its own
        assert stats["rejected"].get("norm_linear") == 1
        assert stats["rewritten"] == {"linear_act": 1}
        assert [s.name for s in plan] == ["layer_norm",
                                          "fused_norm_linear"]
        assert plan[1].attrs["norm_type"] == ""   # norm NOT swallowed

    def test_interior_multi_consumer_rejects(self):
        """The norm output feeds the linear AND a second op that stays
        in the graph — not swallowable into the wide chain."""
        w = paddle.to_tensor(_arr(32, 64, scale=0.1))

        def build(main):
            x = static.data("x", [4, 32], "float32")
            h = F.layer_norm(x, [32])
            y = F.gelu(F.linear(h, w))
            return y, h * 2.0

        plan, stats = self._run_pass(build, [0, 1])
        assert stats["rejected"].get("norm_linear") == 1
        assert stats["rewritten"] == {"linear_act": 1}
        assert "layer_norm" in [s.name for s in plan]

    def test_residual_norm_matches_with_external_sum(self):
        """residual_norm re-emits the sum as a REAL output, so an
        external consumer of the sum is legal — the chain still fuses."""
        def build(main):
            x = static.data("x", [4, 8, 32], "float32")
            y = static.data("y", [4, 8, 32], "float32")
            s = x + y
            return F.rms_norm(s), s.mean()

        plan, stats = self._run_pass(build, [0, 1])
        assert stats["rewritten"] == {"residual_norm": 1}
        assert plan[0].name == "fused_residual_norm"
        assert len(plan[0].out_ids) == 2   # (normed, summed)

    def test_bias_act_matches(self):
        b = paddle.to_tensor(_arr(32, scale=0.1))

        def build(main):
            x = static.data("x", [4, 32], "float32")
            return F.silu(x + b)

        plan, stats = self._run_pass(build, [0])
        assert stats["rewritten"] == {"bias_act": 1}
        assert plan[0].name == "fused_bias_act"

    def test_linear_act_without_norm_matches(self):
        w = paddle.to_tensor(_arr(32, 64, scale=0.1))

        def build(main):
            x = static.data("x", [4, 32], "float32")
            return F.relu(F.linear(x, w))

        plan, stats = self._run_pass(build, [0])
        assert stats["rewritten"] == {"linear_act": 1}
        assert plan[0].name == "fused_norm_linear"
        assert plan[0].attrs["norm_type"] == ""

    def test_rope_proj_matches(self):
        w = paddle.to_tensor(_arr(32, 64, scale=0.1))

        def build(main):
            x = static.data("x", [2, 8, 32], "float32")
            h = ops.reshape(F.linear(x, w), [2, 8, 4, 16])
            return llama.rotary_embedding(h)

        plan, stats = self._run_pass(build, [0])
        assert stats["rewritten"] == {"rope_proj": 1}
        assert plan[0].name == "fused_rope_proj"
        assert plan[0].attrs["num_heads"] == 4

    def test_unrelated_ops_pass_through_untouched(self):
        def build(main):
            x = static.data("x", [4, 32], "float32")
            return ops.tanh(x) * 2.0

        plan, stats = self._run_pass(build, [0])
        assert stats["rewritten"] == {}
        assert stats["ops_before"] == stats["ops_after"]


# ==========================================================================
# numerics + gradient parity per pattern (XLA composite leg)
# ==========================================================================
class TestParity:
    def _grad_parity(self, unfused, fused, *arrays, tol=1e-5):
        def lu(*a):
            paddle.set_flags({"FLAGS_enable_fusion": False})
            return unfused(*a)

        def lf(*a):
            paddle.set_flags({"FLAGS_enable_fusion": True})
            try:
                out, _ = fusion.rewrite_traced(lambda: fused(*a))
                return out._data
            finally:
                paddle.set_flags({"FLAGS_enable_fusion": False})

        argnums = tuple(range(len(arrays)))
        vu, gu = jax.value_and_grad(lambda *a: lu(*a)._data.sum(),
                                    argnums)(*arrays)
        vf, gf = jax.value_and_grad(lambda *a: lf(*a).sum(),
                                    argnums)(*arrays)
        np.testing.assert_allclose(np.asarray(vu), np.asarray(vf),
                                   rtol=tol, atol=tol)
        for a, b in zip(gu, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=tol, atol=tol)

    def test_norm_linear_chain(self):
        w, b = _arr(32, 64, scale=0.1), _arr(64, scale=0.1)

        def chain(xa, wa, ba):
            h = F.layer_norm(Tensor(xa), [32])
            return F.gelu(F.linear(h, Tensor(wa), Tensor(ba)))

        self._grad_parity(chain, chain, _arr(4, 32), w, b)

    def test_residual_norm_chain(self):
        def chain(xa, ya):
            s = Tensor(xa) + Tensor(ya)
            return F.rms_norm(s).mean()

        self._grad_parity(chain, chain, _arr(4, 8, 32), _arr(4, 8, 32))

    def test_bias_silu_chain(self):
        def chain(xa, ba):
            return F.silu(Tensor(xa) + Tensor(ba))

        self._grad_parity(chain, chain, _arr(4, 32), _arr(32, scale=0.1))

    def test_rope_proj_chain(self):
        def chain(xa, wa):
            h = ops.reshape(F.linear(Tensor(xa), Tensor(wa)),
                            [2, 8, 4, 16])
            return llama.rotary_embedding(h)

        self._grad_parity(chain, chain, _arr(2, 8, 32),
                          _arr(32, 64, scale=0.1))

    def test_to_static_full_block_parity(self, fusion_on):
        """A GPT-style block through to_static: the fused program's
        output matches eager-unfused to float tolerance (the composite
        is the same math, but XLA may round differently across the two
        program shapes)."""
        ln, fc1, fc2 = nn.LayerNorm(32), nn.Linear(32, 64), nn.Linear(64, 32)

        def block(x):
            h = F.gelu(fc1(ln(x)))
            h = fc2(h)
            s = x + h
            return F.rms_norm(s)

        x = paddle.to_tensor(_arr(4, 8, 32))
        sf = paddle.jit.to_static(block)
        out = sf(x)
        assert sf.fusion_stats["rewritten"] == {"norm_linear": 1,
                                                "residual_norm": 1}
        paddle.set_flags({"FLAGS_enable_fusion": False})
        ref = block(x)
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)


# ==========================================================================
# Pallas kernel leg (INTERPRET=True runs the real kernel bodies on CPU)
# ==========================================================================
class TestPallasKernels:
    @pytest.fixture(autouse=True)
    def _interp(self):
        from paddle_tpu.ops.pallas import fused_ops as FK
        old = FK.INTERPRET
        FK.INTERPRET = True
        yield
        FK.INTERPRET = old

    def test_fused_bias_act_kernel_matches_composite(self):
        x = paddle.to_tensor(_arr(16, 256))
        b = paddle.to_tensor(_arr(256, scale=0.1))
        got = F.fused_bias_act(x, b, activation="gelu")
        ref = F.gelu(x + b)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_fused_residual_norm_kernel(self):
        x, r = paddle.to_tensor(_arr(16, 256)), paddle.to_tensor(
            _arr(16, 256))
        w = paddle.to_tensor(np.ones(256, np.float32))
        b = paddle.to_tensor(np.zeros(256, np.float32))
        y, s = F.fused_residual_norm(x, r, w, b, norm_type="layer_norm")
        s_ref = x + r
        y_ref = F.layer_norm(s_ref, [256], weight=w, bias=b)
        np.testing.assert_allclose(s.numpy(), s_ref.numpy(), atol=1e-6)
        np.testing.assert_allclose(y.numpy(), y_ref.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_fused_norm_linear_kernel_and_grads(self):
        x = _arr(16, 256)
        w = _arr(256, 128, scale=0.05)

        def fused(xa, wa):
            return F.fused_norm_linear(
                Tensor(xa), Tensor(wa), activation="silu",
                norm_type="rms_norm")._data.sum()

        def ref(xa, wa):
            h = F.rms_norm(Tensor(xa), epsilon=1e-5)
            return F.silu(F.linear(h, Tensor(wa)))._data.sum()

        vf, gf = jax.value_and_grad(fused, (0, 1))(x, w)
        vr, gr = jax.value_and_grad(ref, (0, 1))(x, w)
        np.testing.assert_allclose(float(vf), float(vr), rtol=1e-4)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_fused_rope_proj_kernel(self):
        x = paddle.to_tensor(_arr(2, 16, 256))
        w = paddle.to_tensor(_arr(256, 128, scale=0.05))
        got = F.fused_rope_proj(x, w, num_heads=8, theta=10000.0,
                                pos_offset=3)
        h = ops.reshape(F.linear(x, w), [2, 16, 8, 16])
        ref = llama.rotary_embedding(h, pos_offset=3)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)


# ==========================================================================
# flag off = seed behavior; cache-key separation; metrics
# ==========================================================================
class TestGating:
    def test_flag_off_is_bit_exact_and_passless(self, fusion_off):
        ln, fc = nn.LayerNorm(32), nn.Linear(32, 64)

        def f(x):
            return F.gelu(fc(ln(x)))

        x = paddle.to_tensor(_arr(4, 32))
        sf = paddle.jit.to_static(f)
        out = sf(x)
        assert sf.fusion_stats is None          # the pass never ran
        np.testing.assert_array_equal(out.numpy(), f(x).numpy())

        # static Program path: flag off leaves the replay plan alone
        paddle.enable_static()
        try:
            main, start = static.Program(), static.Program()
            with static.program_guard(main, start):
                xs = static.data("x", [4, 32], "float32")
                y = F.gelu(F.linear(F.layer_norm(xs, [32]),
                                    paddle.to_tensor(_arr(32, 64))))
            exe = static.Executor()
            exe.run(main, feed={"x": _arr(4, 32)}, fetch_list=[y])
            assert main.fusion_stats is None
        finally:
            paddle.disable_static()

    def test_pcc_keys_never_cross_hit(self, tmp_path):
        """Compile one function fused and unfused against the same
        persistent cache: two distinct entries, zero cross-hits — then a
        re-compile of each variant hits its own entry."""
        cache_dir = str(tmp_path / "pcc")
        paddle.set_flags({"FLAGS_enable_metrics": True,
                          "FLAGS_compile_cache": True,
                          "FLAGS_compile_cache_dir": cache_dir})
        REGISTRY.reset()
        ln, fc = nn.LayerNorm(32), nn.Linear(32, 64)

        def f(x):
            return F.gelu(fc(ln(x)))

        x = paddle.to_tensor(_arr(4, 32))
        try:
            outs = {}
            for flag in (False, True, False, True):
                paddle.set_flags({"FLAGS_enable_fusion": flag})
                sf = paddle.jit.to_static(f, full_graph=True)
                outs[flag] = sf(x).numpy()
            misses = REGISTRY.get("paddle_tpu_pcc_misses_total").value(
                site="to_static")
            hits = REGISTRY.get("paddle_tpu_pcc_hits_total").value(
                site="to_static")
            # first two compiles miss (distinct keys), second pair hits
            # its OWN entry — a cross-hit would show as misses < 2
            assert misses == 2, misses
            assert hits == 2, hits
            np.testing.assert_array_equal(outs[True], outs[False])
        finally:
            paddle.set_flags({"FLAGS_enable_fusion": False,
                              "FLAGS_enable_metrics": False,
                              "FLAGS_compile_cache": False,
                              "FLAGS_compile_cache_dir": ""})
            REGISTRY.reset()

    def test_metrics_count_matched_rewritten_rejected(self, fusion_on):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        REGISTRY.reset()
        try:
            w = paddle.to_tensor(_arr(32, 64, scale=0.1))

            def f(x):
                h = F.layer_norm(x, [32])
                return h, F.gelu(F.linear(h, w))   # h escapes: reject

            def g(x):
                return F.gelu(F.linear(F.layer_norm(x, [32]), w))

            x = paddle.to_tensor(_arr(4, 32))
            paddle.jit.to_static(f)(x)
            paddle.jit.to_static(g)(x)
            m = REGISTRY.get("paddle_tpu_fusion_matched_total")
            r = REGISTRY.get("paddle_tpu_fusion_rewritten_total")
            j = REGISTRY.get("paddle_tpu_fusion_rejected_total")
            assert m.value(pattern="norm_linear") == 2
            assert r.value(pattern="norm_linear") == 1
            assert j.value(pattern="norm_linear") == 1
        finally:
            paddle.set_flags({"FLAGS_enable_metrics": False})
            REGISTRY.reset()

    def test_sot_segments_fuse_with_parity(self, fusion_on):
        ln, fc = nn.LayerNorm(32), nn.Linear(32, 64)

        def f(x):
            h = F.gelu(fc(ln(x)))
            if h.shape[0] > 1:       # python branch → SOT segments
                h = h * 2.0
            return h

        x = paddle.to_tensor(_arr(4, 32))
        out = paddle.jit.to_static(f, full_graph=False)(x)
        paddle.set_flags({"FLAGS_enable_fusion": False})
        np.testing.assert_array_equal(out.numpy(), f(x).numpy())


# ==========================================================================
# spmd: fused program over a (data, tp) mesh — zero fallbacks
# ==========================================================================
@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")
def test_fused_program_zero_spmd_fallback(fusion_on):
    from paddle_tpu.distributed import spmd

    mesh = mesh_mod.build_mesh({"data": 2, "tp": 4})
    paddle.seed(5)
    ln = nn.LayerNorm(32)
    fc1, fc2 = nn.Linear(32, 64), nn.Linear(64, 32)
    spmd.shard_params(
        nn.LayerList([ln, fc1, fc2]), mesh,
        [(r".*1\.weight", P(None, "tp")), (r".*1\.bias", P("tp")),
         (r".*2\.weight", P("tp", None))])

    @paddle.jit.to_static(mesh=mesh, in_specs=P("data"))
    def step(x):
        h = F.gelu(fc1(ln(x)))
        h = fc2(h)
        s = x + h
        return F.rms_norm(s).mean()

    x = paddle.to_tensor(_arr(8, 16, 32))
    out = step(x)
    assert step.fusion_stats["rewritten"], step.fusion_stats
    assert step.spmd_stats["fallback"] == {}, step.spmd_stats
    # value parity vs the unfused, unsharded eager path
    paddle.set_flags({"FLAGS_enable_fusion": False})
    ref = F.rms_norm(x + fc2(F.gelu(fc1(ln(x))))).mean()
    np.testing.assert_allclose(float(out.numpy()), float(ref.numpy()),
                               rtol=1e-5)


# ==========================================================================
# audit tool
# ==========================================================================
def test_fusion_audit_clean():
    from tools.fusion_audit import audit
    rep = audit()
    assert rep["problems"] == [], rep["problems"]
    assert {r["op"] for r in rep["ops"]} >= {
        "fused_bias_act", "fused_residual_norm", "fused_norm_linear",
        "fused_rope_proj"}
    # every pattern maps to a registered fused op
    targets = {p for r in rep["ops"] for p in r["patterns"]}
    assert targets == set(rep["patterns"])
