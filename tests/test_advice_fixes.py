"""Regression tests for round-1 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.communication.collective import ReduceOp


@pytest.fixture
def mesh8():
    dist.init_parallel_env({"dp": 8})
    yield dist.mesh.get_mesh()


def test_all_reduce_prod_negative_and_zero(mesh8):
    # exp(psum(log)) would NaN here; a true product must not.
    x = paddle.to_tensor(np.array([-2.0, 0.0, 3.0], np.float32))
    dist.all_reduce(x, op=ReduceOp.PROD)
    # replicated input: product over 8 identical copies
    expect = np.array([-2.0, 0.0, 3.0]) ** 8
    np.testing.assert_allclose(x.numpy(), expect, rtol=1e-5)


def test_reduce_scatter_max(mesh8):
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(16, 1))
    out = dist.reduce_scatter(None, x, op=ReduceOp.MAX)
    # replicated input: max == input; each rank keeps chunk of size 2
    assert out.shape == [2, 1]
    np.testing.assert_allclose(out.numpy(), x.numpy()[:2])


def test_reduce_scatter_avg(mesh8):
    x = paddle.to_tensor(np.ones((16, 2), np.float32))
    out = dist.reduce_scatter(None, x, op=ReduceOp.AVG)
    np.testing.assert_allclose(out.numpy(), np.ones((2, 2)), rtol=1e-6)


def test_alltoall_single_uneven_splits_raises(mesh8):
    x = paddle.to_tensor(np.zeros((16, 2), np.float32))
    with pytest.raises(NotImplementedError):
        dist.alltoall_single(None, x, in_split_sizes=[3, 1, 2, 2, 2, 2, 2, 2])


def test_ctc_loss_mean_divides_by_label_length():
    T, N, C = 12, 2, 5
    rng = np.random.RandomState(0)
    logits = paddle.to_tensor(rng.randn(T, N, C).astype(np.float32))
    labels = paddle.to_tensor(np.array([[1, 2, 3], [2, 4, 0]], np.int32))
    in_len = paddle.to_tensor(np.array([12, 12], np.int64))
    lab_len = paddle.to_tensor(np.array([3, 2], np.int64))
    import paddle_tpu.nn.functional as F
    none_loss = F.ctc_loss(logits, labels, in_len, lab_len,
                           reduction="none").numpy()
    mean_loss = F.ctc_loss(logits, labels, in_len, lab_len,
                           reduction="mean").numpy()
    expect = np.mean(none_loss / np.array([3.0, 2.0]))
    np.testing.assert_allclose(mean_loss, expect, rtol=1e-5)


def test_to_static_batchnorm_training_updates_stats():
    import paddle_tpu.nn as nn

    bn = nn.BatchNorm2D(3)
    bn.train()

    @paddle.jit.to_static
    def step(layer, x):
        return layer(x)

    x = paddle.to_tensor(np.random.RandomState(1).randn(4, 3, 5, 5)
                         .astype(np.float32))
    before = bn._mean.numpy().copy()
    out = step(bn, x)
    assert out.shape == [4, 3, 5, 5]
    after = bn._mean.numpy()
    # running stats moved and did not become tracers
    assert np.isfinite(after).all()
    assert not np.allclose(before, after)
    # a second eager call must not crash on a leaked tracer
    bn.eval()
    bn(x)
