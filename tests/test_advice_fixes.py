"""Regression tests for round-1 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.communication.collective import ReduceOp


@pytest.fixture
def mesh8():
    dist.init_parallel_env({"dp": 8})
    yield dist.mesh.get_mesh()


def test_all_reduce_prod_negative_and_zero(mesh8):
    # exp(psum(log)) would NaN here; a true product must not.
    x = paddle.to_tensor(np.array([-2.0, 0.0, 3.0], np.float32))
    dist.all_reduce(x, op=ReduceOp.PROD)
    # replicated input: product over 8 identical copies
    expect = np.array([-2.0, 0.0, 3.0]) ** 8
    np.testing.assert_allclose(x.numpy(), expect, rtol=1e-5)


def test_reduce_scatter_max(mesh8):
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(16, 1))
    out = dist.reduce_scatter(None, x, op=ReduceOp.MAX)
    # replicated input: max == input; each rank keeps chunk of size 2
    assert out.shape == [2, 1]
    np.testing.assert_allclose(out.numpy(), x.numpy()[:2])


def test_reduce_scatter_avg(mesh8):
    x = paddle.to_tensor(np.ones((16, 2), np.float32))
    out = dist.reduce_scatter(None, x, op=ReduceOp.AVG)
    np.testing.assert_allclose(out.numpy(), np.ones((2, 2)), rtol=1e-6)


def test_alltoall_single_uneven_splits_raises(mesh8):
    x = paddle.to_tensor(np.zeros((16, 2), np.float32))
    with pytest.raises(NotImplementedError):
        dist.alltoall_single(None, x, in_split_sizes=[3, 1, 2, 2, 2, 2, 2, 2])


def test_ctc_loss_mean_divides_by_label_length():
    T, N, C = 12, 2, 5
    rng = np.random.RandomState(0)
    logits = paddle.to_tensor(rng.randn(T, N, C).astype(np.float32))
    labels = paddle.to_tensor(np.array([[1, 2, 3], [2, 4, 0]], np.int32))
    in_len = paddle.to_tensor(np.array([12, 12], np.int64))
    lab_len = paddle.to_tensor(np.array([3, 2], np.int64))
    import paddle_tpu.nn.functional as F
    none_loss = F.ctc_loss(logits, labels, in_len, lab_len,
                           reduction="none").numpy()
    mean_loss = F.ctc_loss(logits, labels, in_len, lab_len,
                           reduction="mean").numpy()
    expect = np.mean(none_loss / np.array([3.0, 2.0]))
    np.testing.assert_allclose(mean_loss, expect, rtol=1e-5)


def test_to_static_batchnorm_training_updates_stats():
    import paddle_tpu.nn as nn

    bn = nn.BatchNorm2D(3)
    bn.train()

    @paddle.jit.to_static
    def step(layer, x):
        return layer(x)

    x = paddle.to_tensor(np.random.RandomState(1).randn(4, 3, 5, 5)
                         .astype(np.float32))
    before = bn._mean.numpy().copy()
    out = step(bn, x)
    assert out.shape == [4, 3, 5, 5]
    after = bn._mean.numpy()
    # running stats moved and did not become tracers
    assert np.isfinite(after).all()
    assert not np.allclose(before, after)
    # a second eager call must not crash on a leaked tracer
    bn.eval()
    bn(x)


# ----------------------------------------------------------- round-5 ADVICE
def test_where_inplace_adopts_into_x_not_condition():
    """ADVICE r4 (medium): an auto-generated where_ adopted into the
    CONDITION. The hand-written one must mutate x and leave cond alone."""
    x = paddle.to_tensor([1.0, 2.0])
    y = paddle.to_tensor([10.0, 20.0])
    cond = paddle.to_tensor([True, False])
    out = paddle.where_(cond, x, y)
    assert out is x
    np.testing.assert_allclose(x.numpy(), [1.0, 20.0])
    assert cond.numpy().dtype == np.bool_
    np.testing.assert_array_equal(cond.numpy(), [True, False])


def test_ps_server_refuses_blank_token_requests():
    """ADVICE r4 (high): tokenless deployments must not expose pickle
    endpoints. A server constructed with token='' mints a random one, so
    a blank-token client is rejected with 403."""
    from paddle_tpu.distributed.ps import PsClient, PsServer
    srv = PsServer(0, 1, token="").start()
    try:
        assert srv.token  # minted, not blank
        bad = PsClient([srv.endpoint], token="")
        with pytest.raises(Exception):
            bad.create_table(0, {"type": "dense", "length": 2})
        good = PsClient([srv.endpoint], token=srv.token)
        good.create_table(0, {"type": "dense", "length": 2})
    finally:
        srv.stop()


def test_ps_barrier_entries_reclaimed():
    """ADVICE r4 (low): completed barrier generations must not leak."""
    from paddle_tpu.distributed.ps import PsServer
    srv = PsServer(0, 1, token="t").start()
    try:
        for gen in range(5):
            srv._handle("barrier", key=f"k#{gen}", world=1)
        assert not srv._barrier_counts and not srv._barrier_events
    finally:
        srv.stop()


def test_hdfs_test_cmd_not_retried(monkeypatch):
    """ADVICE r4 (low): 'hadoop fs -test' exit 1 is an answer, not a
    transient failure — no retry sleeps, and no sleep after the last try."""
    import time as _time
    from paddle_tpu.distributed.fleet.utils.fs import HDFSClient
    cli = HDFSClient("/opt/hadoop", sleep_inter=1000)
    calls = []
    monkeypatch.setattr(cli, "_shell", lambda cmd: (calls.append(cmd) or (1, "")))
    slept = []
    monkeypatch.setattr(_time, "sleep", lambda s: slept.append(s))
    assert cli.is_exist("/no/such/path") is False
    assert len(calls) == 1  # single probe
    assert not slept        # and no sleeping at all
    # non-test commands still retry, but never sleep after the final try
    calls.clear()
    ret, _ = cli._run_cmd("mkdir /x", retry_times=2)
    assert ret == 1 and len(calls) == 3 and len(slept) == 2


def test_sparse_embedding_unique_autonames():
    """ADVICE r4 (low): two unnamed sparse_embedding calls must not hash
    to the same PS table id."""
    from paddle_tpu import static
    import zlib
    n0 = static.nn._SPARSE_EMB_AUTO
    # call through the naming path only (no PS client bound -> expect the
    # runtime error AFTER the name was minted)
    ids = set()
    for _ in range(2):
        try:
            static.nn.sparse_embedding(paddle.to_tensor([[0]]), [10, 4])
        except RuntimeError:
            pass
    assert static.nn._SPARSE_EMB_AUTO == n0 + 2
