"""API-tail surfaces: paddle.flops, paddle.batch, regularizer, Model /
callbacks aliases, version/sysconfig, nn.quant, get_group, vision image
backend, jit.TracedLayer, LazyGuard.

Reference contracts: hapi/dynamic_flops.py, batch.py, regularizer.py,
nn/initializer/lazy_init.py, base/dygraph/jit.py TracedLayer,
communication/group.py get_group, vision/image.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


# ------------------------------------------------------------------ flops
def test_flops_lenet_counts():
    net = paddle.vision.models.LeNet()
    total = paddle.flops(net, input_size=[1, 1, 28, 28])
    assert total > 0
    # conv1: 6 out-ch of 3x3x1 kernels on 28x28 output (padding=1)
    # contributes 28*28*6*9 = 42336; total must exceed just that
    assert total > 42_000


def test_flops_custom_ops_and_detail(capsys):
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU())

    def my_linear(m, x, y):
        m._flops_ops += 999

    total = paddle.flops(net, input_size=[2, 4],
                         custom_ops={paddle.nn.Linear: my_linear},
                         print_detail=True)
    assert total == 999 + 2 * 8  # custom linear + relu elementwise
    out = capsys.readouterr().out
    assert "Total Flops" in out and "Linear" in out


# ------------------------------------------------------------------ batch
def test_batch_reader():
    r = paddle.batch(lambda: iter(range(10)), batch_size=4)
    assert list(r()) == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    r2 = paddle.batch(lambda: iter(range(10)), batch_size=4,
                      drop_last=True)
    assert list(r2()) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    with pytest.raises(ValueError):
        paddle.batch(lambda: iter([]), batch_size=0)


# ------------------------------------------------------------ regularizer
def test_l2_decay_in_optimizer():
    from paddle_tpu.regularizer import L1Decay, L2Decay
    w = paddle.to_tensor(np.array([2.0, -4.0], np.float32))
    w.stop_gradient = False
    opt = paddle.optimizer.SGD(parameters=[w], learning_rate=1.0,
                               weight_decay=L2Decay(0.1))
    (w * 0.0).sum().backward()  # zero loss grad; decay only
    opt.step()
    np.testing.assert_allclose(w.numpy(), [2.0 - 0.2, -4.0 + 0.4],
                               rtol=1e-6)


def test_l1_decay_uses_sign():
    from paddle_tpu.regularizer import L1Decay
    w = paddle.to_tensor(np.array([2.0, -4.0], np.float32))
    w.stop_gradient = False
    opt = paddle.optimizer.SGD(parameters=[w], learning_rate=1.0,
                               weight_decay=L1Decay(0.1))
    (w * 0.0).sum().backward()
    opt.step()
    # L1: w -= lr * coeff * sign(w) — magnitude-independent
    np.testing.assert_allclose(w.numpy(), [1.9, -3.9], rtol=1e-6)


def test_param_attr_regularizer_overrides_optimizer():
    """ParamAttr-level regularizer takes priority (reference
    regularizer.py contract)."""
    from paddle_tpu.nn.parameter import ParamAttr, create_parameter
    from paddle_tpu.regularizer import L1Decay, L2Decay
    import paddle_tpu.nn.initializer as I

    p = create_parameter([2], attr=ParamAttr(regularizer=L1Decay(0.5)),
                         default_initializer=I.Constant(2.0))
    q = create_parameter([2], default_initializer=I.Constant(2.0))
    opt = paddle.optimizer.SGD(parameters=[p, q], learning_rate=1.0,
                               weight_decay=L2Decay(0.1))
    ((p + q) * 0.0).sum().backward()
    opt.step()
    # p: its own L1 (0.5 * sign(2)=0.5), NOT the optimizer L2
    np.testing.assert_allclose(p.numpy(), [1.5, 1.5], rtol=1e-6)
    # q: optimizer-level L2 (0.1 * 2.0)
    np.testing.assert_allclose(q.numpy(), [1.8, 1.8], rtol=1e-6)


def test_destroy_process_group_clears_registry():
    from paddle_tpu.distributed import (destroy_process_group, get_group,
                                        new_group)
    g = new_group(axes=("dp",))
    assert get_group(g.id) is g
    destroy_process_group()
    with pytest.raises(ValueError):
        get_group(g.id)


def test_abandoned_lazy_model_stops_taxing_calls():
    from paddle_tpu.nn import lazy_init
    with paddle.LazyGuard():
        abandoned = paddle.nn.Linear(4, 4)
    assert lazy_init.has_outstanding()
    del abandoned
    import gc
    gc.collect()
    # weakrefs released: the global gate is closed again
    assert not lazy_init.has_outstanding()


def test_traced_layer_fetch_filter(tmp_path):
    class TwoOut(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(3, 3)

        def forward(self, x):
            y = self.lin(x)
            return y, y * 2.0

    net = TwoOut()
    x = paddle.to_tensor(np.ones((1, 3), np.float32))
    (out0, out1), traced = paddle.jit.TracedLayer.trace(net, [x])
    path = str(tmp_path / "fetch1")
    traced.save_inference_model(path, fetch=[1])
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(np.asarray(loaded(x).numpy()),
                               np.asarray(out1.numpy()), rtol=1e-5)
    with pytest.raises(NotImplementedError):
        traced.save_inference_model(str(tmp_path / "feedx"), feed=[0])


# ---------------------------------------------------------- op-tail extras
def test_inplace_family_autograd_continues():
    """In-place ops adopt the result's grad link: backward through the
    mutated tensor matches the out-of-place chain."""
    x = paddle.to_tensor(np.array([0.5, -0.3], np.float32))
    x.stop_gradient = False
    y = x * 2.0
    paddle.tanh_(y)          # y := tanh(2x), graph continues
    y.sum().backward()
    expect = 2.0 * (1 - np.tanh(2 * np.asarray([0.5, -0.3])) ** 2)
    np.testing.assert_allclose(np.asarray(x.grad.numpy()), expect,
                               rtol=1e-5)


def test_inplace_mutates_and_returns_same_object():
    x = paddle.to_tensor(np.array([1.0, 4.0], np.float32))
    r = paddle.sqrt(x)
    out = paddle.square_(x)
    assert out is x
    np.testing.assert_allclose(x.numpy(), [1.0, 16.0])
    # random fills: right shape/moments, severed tape
    z = paddle.zeros([2000])
    paddle.normal_(z, mean=1.0, std=0.5)
    assert abs(float(z.numpy().mean()) - 1.0) < 0.1
    assert z.grad_node is None


def test_top_level_all_parity_with_reference():
    """Every name in the reference's top-level __all__ resolves here
    (the completeness check a reference user would run first)."""
    import ast
    ref_init = "/root/reference/python/paddle/__init__.py"
    try:
        tree = ast.parse(open(ref_init).read())
    except OSError:
        pytest.skip("reference tree not available")
    ref_all = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    ref_all = ast.literal_eval(node.value)
    assert ref_all
    missing = [n for n in ref_all if not hasattr(paddle, n)]
    assert not missing, f"{len(missing)} reference names absent: {missing}"


# ---------------------------------------------------------------- aliases
def test_top_level_aliases():
    assert paddle.Model is paddle.hapi.Model
    assert paddle.callbacks.EarlyStopping is paddle.hapi.EarlyStopping
    assert paddle.version.full_version
    paddle.version.show()
    import os
    assert os.path.isdir(paddle.sysconfig.get_include())
    assert paddle.nn.quant.weight_quantize is not None
    from paddle_tpu.distributed import get_group, new_group
    g = new_group(axes=("dp",))
    assert get_group(g.id) is g
    assert get_group(0).id == 0
    with pytest.raises(ValueError):
        get_group(999999)


def test_vision_image_backend(tmp_path):
    from paddle_tpu.vision import (get_image_backend, image_load,
                                   set_image_backend)
    assert get_image_backend() == "pil"
    with pytest.raises(ValueError):
        set_image_backend("bogus")
    from PIL import Image
    p = tmp_path / "img.png"
    Image.fromarray(np.zeros((4, 5, 3), np.uint8)).save(p)
    img = image_load(str(p))
    assert img.size == (5, 4)
    t = image_load(str(p), backend="tensor")
    assert list(t.shape) == [4, 5, 3]


# ------------------------------------------------------------ TracedLayer
def test_traced_layer_trace_and_replay(tmp_path):
    net = paddle.nn.Linear(4, 3)
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4)
                         .astype(np.float32))
    out, traced = paddle.jit.TracedLayer.trace(net, [x])
    replay = traced([x])
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.asarray(replay.numpy()), rtol=1e-5)
    path = str(tmp_path / "traced_model")
    traced.save_inference_model(path)
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(np.asarray(loaded(x).numpy()),
                               np.asarray(out.numpy()), rtol=1e-5)


# -------------------------------------------------------------- LazyGuard
def test_lazy_guard_defers_then_materializes():
    from paddle_tpu.nn.lazy_init import has_outstanding

    with paddle.LazyGuard():
        net = paddle.nn.Linear(8, 16)
    # deferred: shape/dtype visible, no device buffer yet
    assert list(net.weight.shape) == [8, 16]
    assert has_outstanding()
    import jax
    assert isinstance(net.weight._data, jax.ShapeDtypeStruct)
    # first forward materializes
    y = net(paddle.to_tensor(np.ones((2, 8), np.float32)))
    assert not isinstance(net.weight._data, jax.ShapeDtypeStruct)
    assert list(y.shape) == [2, 16]
    # initializer really ran (xavier: nonzero weights, zero bias)
    assert float(np.abs(np.asarray(net.weight.numpy())).sum()) > 0
    np.testing.assert_allclose(np.asarray(net.bias.numpy()), 0.0)


def test_lazy_guard_explicit_materialize():
    from paddle_tpu.nn.lazy_init import materialize_layer
    with paddle.LazyGuard():
        net = paddle.nn.Sequential(paddle.nn.Linear(3, 3),
                                   paddle.nn.Linear(3, 2))
    n = materialize_layer(net)
    assert n == 4  # 2 weights + 2 biases
    assert materialize_layer(net) == 0  # idempotent
    # normal (non-guard) construction is unaffected
    net2 = paddle.nn.Linear(2, 2)
    import jax
    assert not isinstance(net2.weight._data, jax.ShapeDtypeStruct)


def test_histogramdd_in_graph_numpy_parity():
    """histogramdd lowered through dispatch (in-graph jnp.histogramdd —
    the round-19 tpulint burn-down rewrite) matches np.histogramdd,
    including weights/density and explicit ranges, and traces under
    jit (no host readback of the data)."""
    import jax

    rng = np.random.RandomState(0)
    xs = rng.randn(60, 3).astype(np.float32)
    ws = rng.rand(60).astype(np.float32)
    x = paddle.to_tensor(xs)
    w = paddle.to_tensor(ws)
    for kw in (dict(bins=4),
               dict(bins=3, density=True),
               dict(bins=4, ranges=[(-1.0, 1.0)] * 3),
               dict(bins=2, density=True)):
        np_kw = dict(kw)
        np_kw["range"] = np_kw.pop("ranges", None)
        h, edges = paddle.histogramdd(x, weights=w, **kw)
        hn, en = np.histogramdd(xs, weights=ws, **np_kw)
        np.testing.assert_allclose(np.asarray(h.numpy()),
                                   hn.astype(np.float32), rtol=1e-4,
                                   atol=1e-6)
        assert len(edges) == 3
        for a, b in zip(edges, en):
            np.testing.assert_allclose(np.asarray(a.numpy()),
                                       b.astype(np.float32), rtol=1e-4,
                                       atol=1e-5)

    # traceable: the whole lowering (data-range min/max included) jits
    @jax.jit
    def f(a):
        h, _ = paddle.histogramdd(paddle.to_tensor(a), bins=4)
        return h._data

    np.testing.assert_allclose(
        np.asarray(f(xs)),
        np.histogramdd(xs, bins=4)[0].astype(np.float32), rtol=1e-4)
