"""Recompute (activation checkpointing) tests.

Reference analog: test/collective/fleet/test_dygraph_recompute*.py — grads
with recompute must equal grads without; dropout must replay identically.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet import recompute, recompute_sequential


class Block(nn.Layer):
    def __init__(self, d=16, dropout=0.0):
        super().__init__()
        self.fc1 = nn.Linear(d, 4 * d)
        self.fc2 = nn.Linear(4 * d, d)
        self.p = dropout

    def forward(self, x):
        y = paddle.nn.functional.gelu(self.fc1(x))
        if self.p > 0:
            y = paddle.nn.functional.dropout(y, p=self.p,
                                             training=self.training)
        return x + self.fc2(y)


def _grads(model, x, use_recompute, segments=0):
    for p in model.parameters():
        p.clear_grad()
    h = x
    if use_recompute:
        if segments:
            h = recompute_sequential({"segments": segments},
                                     list(model), h)
        else:
            for blk in model:
                h = recompute(blk, h)
    else:
        for blk in model:
            h = blk(h)
    loss = paddle.ops.mean(h ** 2)
    loss.backward()
    return (float(loss.numpy()),
            {n: np.asarray(p.grad._data)
             for n, p in model.named_parameters() if p.grad is not None})


def test_grads_match_no_recompute():
    paddle.seed(0)
    model = nn.LayerList([Block() for _ in range(4)])
    x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32),
                         stop_gradient=False)
    l1, g1 = _grads(model, x, use_recompute=False)
    l2, g2 = _grads(model, x, use_recompute=True)
    assert abs(l1 - l2) < 1e-6
    assert set(g1) == set(g2) and len(g1) > 0
    for n in g1:
        np.testing.assert_allclose(g1[n], g2[n], atol=1e-6,
                                   err_msg=f"grad mismatch {n}")


def test_input_grad_flows():
    paddle.seed(1)
    blk = Block()
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32),
                         stop_gradient=False)
    out = recompute(blk, x)
    loss = paddle.ops.sum(out ** 2)
    loss.backward()
    assert x.grad is not None
    # reference
    x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
    loss2 = paddle.ops.sum(blk(x2) ** 2)
    loss2.backward()
    np.testing.assert_allclose(np.asarray(x.grad._data),
                               np.asarray(x2.grad._data), atol=1e-6)


def test_rng_replay_with_dropout():
    paddle.seed(3)
    model = nn.LayerList([Block(dropout=0.5) for _ in range(2)])
    model.train()
    x = paddle.to_tensor(np.random.randn(32, 16).astype(np.float32),
                         stop_gradient=False)
    # same seed, recompute on/off: forwards see identical dropout masks
    paddle.seed(123)
    l1, g1 = _grads(model, x, use_recompute=False)
    paddle.seed(123)
    l2, g2 = _grads(model, x, use_recompute=True)
    assert abs(l1 - l2) < 1e-6, "dropout mask not replayed identically"
    for n in g1:
        np.testing.assert_allclose(g1[n], g2[n], atol=1e-6)


def test_recompute_sequential_segments():
    paddle.seed(4)
    model = nn.LayerList([Block() for _ in range(4)])
    x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32),
                         stop_gradient=False)
    l1, g1 = _grads(model, x, use_recompute=False)
    l2, g2 = _grads(model, x, use_recompute=True, segments=2)
    assert abs(l1 - l2) < 1e-6
    for n in g1:
        np.testing.assert_allclose(g1[n], g2[n], atol=1e-6)


def test_no_activation_residuals_held():
    """Forward under recompute must not record tape nodes (that is where
    activation residuals live in the eager engine)."""
    paddle.seed(5)
    blk = Block()
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32),
                         stop_gradient=False)
    out = recompute(blk, x)
    # output's grad node is the single PyLayer node, not the op-level chain
    assert out.grad_node is not None
    assert type(out.grad_node).__name__ == "_PyLayerGradNode"


def test_stop_gradient_input_still_trains():
    """Standard training loop: data input has stop_gradient=True; param
    grads must still flow through the recomputed segment."""
    paddle.seed(10)
    blk = Block()
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
    assert x.stop_gradient
    out = recompute(blk, x)
    loss = paddle.ops.mean(out ** 2)
    loss.backward()
    grads = [p.grad for p in blk.parameters() if not p.stop_gradient]
    assert all(g is not None for g in grads)

    x2 = paddle.to_tensor(x.numpy())
    for p in blk.parameters():
        p.clear_grad()
    loss2 = paddle.ops.mean(blk(x2) ** 2)
    loss2.backward()
    for p, g in zip([p for p in blk.parameters() if not p.stop_gradient],
                    grads):
        np.testing.assert_allclose(np.asarray(g._data),
                                   np.asarray(p.grad._data), atol=1e-6)


def test_mutation_between_forward_and_backward():
    """In-place set_value on an input after the recompute forward must not
    change the replay (inputs are snapshotted at forward time)."""
    paddle.seed(11)
    blk = Block()
    xv = np.random.randn(4, 16).astype(np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    out = recompute(blk, x)
    loss = paddle.ops.mean(out ** 2)
    x.set_value(paddle.to_tensor(np.zeros_like(xv)))  # mutate AFTER forward
    loss.backward()
    got = {n: np.asarray(p.grad._data)
           for n, p in blk.named_parameters() if p.grad is not None}

    x2 = paddle.to_tensor(xv, stop_gradient=False)
    for p in blk.parameters():
        p.clear_grad()
    loss2 = paddle.ops.mean(blk(x2) ** 2)
    loss2.backward()
    for n, p in blk.named_parameters():
        if p.grad is not None:
            np.testing.assert_allclose(got[n], np.asarray(p.grad._data),
                                       atol=1e-6, err_msg=n)


def test_tracker_stream_dropout_replay():
    """Dropout drawing from the fleet RNGStatesTracker stream must replay
    the same mask in the recompute pass."""
    from paddle_tpu.distributed.fleet import get_rng_state_tracker

    class TrackerDropBlock(nn.Layer):
        def __init__(self, d=16):
            super().__init__()
            self.fc = nn.Linear(d, d)

        def forward(self, x):
            y = self.fc(x)
            with get_rng_state_tracker().rng_state():
                y = paddle.nn.functional.dropout(y, p=0.5,
                                                 training=self.training)
            return x + y

    paddle.seed(12)
    blk = TrackerDropBlock()
    blk.train()
    x = paddle.to_tensor(np.random.randn(64, 16).astype(np.float32),
                         stop_gradient=False)
    paddle.seed(77)
    get_rng_state_tracker().reset()
    l1 = paddle.ops.mean(blk(x) ** 2)
    l1.backward()
    g1 = {n: np.asarray(p.grad._data) for n, p in blk.named_parameters()}
    for p in blk.parameters():
        p.clear_grad()

    paddle.seed(77)
    get_rng_state_tracker().reset()
    l2 = paddle.ops.mean(recompute(blk, x) ** 2)
    l2.backward()
    assert abs(float(l1.numpy()) - float(l2.numpy())) < 1e-6
    for n, p in blk.named_parameters():
        np.testing.assert_allclose(g1[n], np.asarray(p.grad._data),
                                   atol=1e-6, err_msg=n)


def test_mixed_outputs_cotangent_alignment():
    """function returning (non_tensor, tensor): cotangents must pair with
    outputs by position."""
    paddle.seed(13)
    blk = Block()
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32),
                         stop_gradient=False)

    def f(x):
        return "aux", blk(x)

    aux, out = recompute(f, x)
    assert aux == "aux"
    loss = paddle.ops.mean(out ** 2)
    loss.backward()
    got = {n: np.asarray(p.grad._data)
           for n, p in blk.named_parameters() if p.grad is not None}
    assert got

    for p in blk.parameters():
        p.clear_grad()
    x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
    loss2 = paddle.ops.mean(blk(x2) ** 2)
    loss2.backward()
    for n, p in blk.named_parameters():
        if p.grad is not None:
            np.testing.assert_allclose(got[n], np.asarray(p.grad._data),
                                       atol=1e-6, err_msg=n)


def test_pipeline_layer_recompute_interval():
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer

    paddle.seed(6)
    pl = PipelineLayer(layers=[LayerDesc(Block) for _ in range(4)],
                       num_stages=1, recompute_interval=2)
    pl.train()
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32),
                         stop_gradient=False)
    out = pl(x)
    loss = paddle.ops.mean(out ** 2)
    loss.backward()
    grads = [np.asarray(p.grad._data) for p in pl.parameters()
             if p.grad is not None]
    assert grads

    pl2 = PipelineLayer(layers=list(pl.run_function), num_stages=1)
    for p in pl2.parameters():
        p.clear_grad()
    x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
    loss2 = paddle.ops.mean(pl2(x2) ** 2)
    loss2.backward()
    grads2 = [np.asarray(p.grad._data) for p in pl2.parameters()
              if p.grad is not None]
    for a, b in zip(grads, grads2):
        np.testing.assert_allclose(a, b, atol=1e-6)
