"""SPMD sharding propagation — rules, whole-program passes, parity.

Contracts under test (ISSUE 8 / ROADMAP "SPMD sharding propagation"):

* per-op rules map input PartitionSpecs to output specs (reference
  ``phi/infermeta/spmd_rules/``), with the documented meet rule for
  conflicts;
* the offline pass shards a recorded ``static.Program`` into ONE jitted
  SPMD program that matches the unsharded replay;
* the online scope auto-shards a traced GPT step over ``(data, tp)``
  and ``(data, fsdp)`` meshes with ZERO replicate-fallback ops, and the
  loss + gradients match single-device ground truth;
* the auto-sharded model matches the hand-built fleet-TP path on the
  same mesh with identical weights;
* the registry rule coverage never regresses (tools/spmd_coverage_audit).
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.ops as ops
from paddle_tpu import nn, static
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import mesh as mesh_mod, spmd
from paddle_tpu.distributed.spmd import rules as R
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.nn import functional as F

TP_RULES = [
    (r".*qkv_proj\.weight", P(None, "tp")),
    (r".*qkv_proj\.bias", P("tp")),
    (r".*fc1\.weight", P(None, "tp")),
    (r".*fc1\.bias", P("tp")),
    (r".*(out_proj|fc2)\.weight", P("tp", None)),
    (r".*wte\.weight", P("tp", None)),
]
FSDP_RULES = [(r".*\.weight", P("fsdp")), (r".*\.bias", P("fsdp"))]

CFG = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
           max_seq_len=16, use_flash_attention=False)


def _mesh(**shape):
    return mesh_mod.build_mesh(dict(shape))


# ==========================================================================
# rules
# ==========================================================================
class TestRules:
    def test_normalize_and_dedupe(self):
        assert R.normalize(P("a", None), 3) == ("a", None, None)
        assert R.normalize(None, 2) == (None, None)
        # an axis may shard only one dim — later uses drop
        assert R.dedupe(("a", "a", None)) == ("a", None, None)
        assert R.dedupe((("a", "b"), "b")) == (("a", "b"), None)

    def test_meet_documented_semantics(self):
        # equal keeps; None yields; disagreement replicates (conflict)
        assert R.meet(("a", None), ("a", None)) == ("a", None)
        assert R.meet((None, "b"), ("a", None)) == ("a", "b")
        assert R.meet(("a", None), ("b", None)) == (None, None)

    def test_matmul_rule_tp_layouts(self):
        # x(B,S,H) @ W(H,N-tp-sharded) -> out n-dim tp-sharded
        res = R.matmul_rule([("data", None, None), (None, "tp")],
                            [(4, 16, 32), (32, 96)], {}, [(4, 16, 96)])
        assert res.out_specs[0] == ("data", None, "tp")
        # transpose_y recovered from shapes: x(B,S,H) @ W(V,H)^T
        res = R.matmul_rule([("data", None, None), ("tp", None)],
                            [(4, 16, 32), (64, 32)], {}, [(4, 16, 64)])
        assert res.out_specs[0] == ("data", None, "tp")

    def test_elementwise_broadcast_and_conflict(self):
        # broadcast: (B,S,H) + (H,) keeps the lhs placement
        res = R.elementwise_rule([("data", None, "tp"), (None,)],
                                 [(4, 16, 32), (32,)], {}, [(4, 16, 32)])
        assert res.out_specs[0] == ("data", None, "tp")
        # conflicting dim -> replicated (meet)
        res = R.elementwise_rule([("a", None), ("b", None)],
                                 [(4, 8), (4, 8)], {}, [(4, 8)])
        assert res.out_specs[0] == (None, None)

    def test_reshape_split_and_merge(self):
        # (B,S,H)->(B,S,nh,hd): split dim hands axes to the major factor
        res = R.reshape_rule([("data", None, "tp")], [(4, 16, 32)], {},
                             [(4, 16, 4, 8)])
        assert res.out_specs[0] == ("data", None, "tp", None)
        # merge (B,S,H)->(B*S,H): first input dim's axes carry
        res = R.reshape_rule([("data", None, "tp")], [(4, 16, 32)], {},
                             [(64, 32)])
        assert res.out_specs[0] == ("data", "tp")

    def test_reduction_drops_reduced_dims(self):
        res = R.reduction_rule([("data", None, "tp")], [(4, 16, 32)], {},
                               [(4, 16)])
        assert res.out_specs[0] == ("data", None)
        res = R.reduction_rule([("data", "tp")], [(4, 32)], {}, [()])
        assert res.out_specs[0] == ()

    def test_embedding_rule(self):
        res = R.embedding_rule([("data", None), ("tp", None)],
                               [(4, 16), (64, 32)], {}, [(4, 16, 32)])
        assert res.out_specs[0] == ("data", None, None)
        res = R.embedding_rule([("data", None), (None, "tp")],
                               [(4, 16), (64, 32)], {}, [(4, 16, 32)])
        assert res.out_specs[0] == ("data", None, "tp")

    def test_embedding_rule_vocab_sharded_emits_partial(self):
        """Regression (giant-embedding round): a row-sharded vocab dim
        means every shard gathers masked rows — the output is Partial
        over the vocab axes until an all-reduce, and the rule must say
        so (a dropped pending-set silently double-counts the rows)."""
        res = R.embedding_rule([("data", None), (("fsdp", "tp"), None)],
                               [(4, 16), (65536, 32)], {},
                               [(4, 16, 32)])
        assert res.out_specs[0] == ("data", None, None)
        assert res.out_partial[0] == ("fsdp", "tp")
        # unsharded vocab: nothing pends
        res = R.embedding_rule([("data", None), (None, "tp")],
                               [(4, 16), (64, 32)], {}, [(4, 16, 32)])
        assert res.out_partial[0] == ()

    def test_embedding_bag_rule(self):
        """Pooled lookup: ids' lead dims carry, the pooled dim is gone,
        the hidden dim takes the table's, and a sharded vocab pends the
        same all-reduce as plain embedding."""
        res = R.embedding_bag_rule(
            [("data", None, None), (("fsdp", "tp"), None)],
            [(4, 8, 4), (65536, 32)], {}, [(4, 8, 32)])
        assert res.out_specs[0] == ("data", None, None)
        assert res.out_partial[0] == ("fsdp", "tp")
        res = R.embedding_bag_rule(
            [("data", None, None), (None, "tp")],
            [(4, 8, 4), (64, 32)], {}, [(4, 8, 32)])
        assert res.out_specs[0] == ("data", None, "tp")
        assert res.out_partial[0] == ()

    def test_scatter_add_rule_keeps_dest_placement(self):
        """The sparse optimizer write-back: the destination table keeps
        its row sharding (each shard applies its own rows' updates), no
        pending reduce."""
        res = R.scatter_add_rule(
            [(("fsdp", "tp"), None), (None,), (None, None)],
            [(65536, 32), (128,), (128, 32)], {}, [(65536, 32)])
        assert res.out_specs[0] == (("fsdp", "tp"), None)
        assert not any(res.out_partial)    # no pending reduce

    def test_attention_rule_constrains_kv(self):
        q = ("data", None, "tp", None)
        res = R.attention_rule([q, q, q],
                               [(2, 16, 4, 8)] * 3, {}, [(2, 16, 4, 8)])
        assert res.out_specs[0] == q
        assert res.in_specs[1] == q and res.in_specs[2] == q

    def test_rule_for_tiers(self):
        spmd.attach_spmd_rules()
        _, tier = R.rule_for("matmul")
        assert tier == "rule"
        _, tier = R.rule_for("definitely_not_an_op_xyz")
        assert tier == "replicate-warn"

    def test_attach_idempotent_and_register_override_wins(self):
        from paddle_tpu.ops import registry as reg
        n1 = spmd.attach_spmd_rules()
        n2 = spmd.attach_spmd_rules()
        assert n1 == n2 >= 20
        marker = lambda *a: R.SpmdResult(out_specs=[()])
        od = reg.OPS["matmul"]
        prev = od.spmd_rule
        try:
            od.spmd_rule = marker
            rule, tier = R.rule_for("matmul")
            assert rule is marker and tier == "rule"
        finally:
            od.spmd_rule = prev


# ==========================================================================
# offline: static.Program pass
# ==========================================================================
class TestShardProgram:
    def test_program_parity_and_plan(self):
        mesh = _mesh(data=2, tp=4)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [8, 16], "float32")
            w1 = paddle.to_tensor(
                np.random.RandomState(0).randn(16, 32).astype(np.float32))
            h = ops.tanh(ops.matmul(x, w1))
            w2 = paddle.to_tensor(
                np.random.RandomState(1).randn(32, 4).astype(np.float32))
            y = ops.matmul(h, w2)
            loss = ops.mean(y * y)
        sp = spmd.shard_program(
            prog, mesh, {"x": P("data")},
            param_specs=lambda t: (P(None, "tp")
                                   if tuple(t.shape) == (16, 32)
                                   else P("tp", None)))
        s = sp.plan.summary()
        assert s["tiers"]["replicate-warn"] == 0
        assert s["annotated"] >= 3
        feed = {"x": np.random.RandomState(2).randn(8, 16)
                .astype(np.float32)}
        got = sp.run(feed, [id(loss)])
        ref = prog.run(feed, [id(loss)])
        np.testing.assert_allclose(got[0], ref[0], rtol=1e-5)

    def test_op_record_carries_attrs_and_shapes(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 8], "float32")
            y = F.softmax(x, axis=-1)
        rec = prog.global_block().ops[-1]
        assert rec.in_shapes == ((4, 8),)
        assert rec.out_shapes == ((4, 8),)
        assert isinstance(rec.attrs, dict)


# ==========================================================================
# online: GPT auto-sharding parity (loss + grads, 2 mesh layouts)
# ==========================================================================
def _gpt_loss_fn(params, model, ids, mesh=None, rules_env=None,
                 stats_box=None):
    def f(pa):
        orig = [p._data for p in params]
        for p, a in zip(params, pa):
            p._data = a
        try:
            if mesh is None:
                t = Tensor(ids)
                _, loss = model(t, labels=t)
                return loss._data
            sc = spmd.trace_scope(mesh)
            with sc:
                for p in params:
                    spec = spmd.param_spec_of(p)
                    if spec is not None:
                        sc.seed(p, spec)
                t = Tensor(ids)
                sc.seed(t, P("data"))
                _, loss = model(t, labels=t)
            if stats_box is not None:
                stats_box.update(sc.stats)
            return loss._data
        finally:
            for p, o in zip(params, orig):
                p._data = o
    return f


@pytest.mark.parametrize("layout,rules", [
    ("tp", TP_RULES),
    ("fsdp", FSDP_RULES),
])
def test_gpt_auto_shard_loss_and_grads_match_single_device(layout, rules):
    ids = np.random.RandomState(0).randint(0, 64, (4, 16)).astype(np.int64)

    paddle.seed(11)
    ref_model = GPTForCausalLM(GPTConfig(**CFG))
    ref_params = list(ref_model.parameters())
    ref_f = _gpt_loss_fn(ref_params, ref_model, ids)
    ref_loss, ref_grads = jax.jit(jax.value_and_grad(ref_f))(
        [p._data for p in ref_params])

    mesh = _mesh(data=2, **{layout: 4})
    paddle.seed(11)
    model = GPTForCausalLM(GPTConfig(**CFG))
    spmd.shard_params(model, mesh, rules)
    params = list(model.parameters())
    stats = {}
    f = _gpt_loss_fn(params, model, ids, mesh=mesh, stats_box=stats)
    loss, grads = jax.jit(jax.value_and_grad(f))(
        [p._data for p in params])

    assert stats["fallback"] == {}, stats
    assert stats["tiers"]["replicate-warn"] == 0
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   rtol=5e-4, atol=5e-5)


def test_gpt_auto_shard_matches_fleet_tp_same_weights():
    """Direct fleet parity: the SAME weights through (a) the hand-built
    fleet TP layers (mp_degree=2) and (b) the plain model auto-sharded
    over the same mesh produce the same loss."""
    import paddle_tpu.distributed.fleet as fleet_pkg
    ids = np.random.RandomState(3).randint(0, 64, (4, 16)).astype(np.int64)

    strategy = fleet_pkg.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    fleet_pkg.fleet.init(is_collective=True, strategy=strategy)
    try:
        paddle.seed(5)
        tp_model = GPTForCausalLM(GPTConfig(mp_degree=2, **CFG))
        state = {k: np.asarray(v.numpy())
                 for k, v in tp_model.state_dict().items()}
        _, tp_loss = tp_model(paddle.to_tensor(ids),
                              labels=paddle.to_tensor(ids))
    finally:
        mesh_mod._global_mesh = None

    mesh = _mesh(data=4, tp=2)
    paddle.seed(5)
    auto_model = GPTForCausalLM(GPTConfig(**CFG))
    auto_model.set_state_dict(state)
    spmd.shard_params(auto_model, mesh, TP_RULES)
    params = list(auto_model.parameters())
    stats = {}
    f = _gpt_loss_fn(params, auto_model, ids, mesh=mesh, stats_box=stats)
    loss = jax.jit(f)([p._data for p in params])
    assert stats["fallback"] == {}
    np.testing.assert_allclose(float(loss), float(tp_loss.numpy()),
                               rtol=1e-4)


def test_engine_auto_mode_trains_with_zero_fallback():
    mesh = _mesh(data=2, tp=4)
    from paddle_tpu.distributed.auto_parallel.engine import Engine

    paddle.seed(9)
    model = GPTForCausalLM(GPTConfig(**CFG))
    spmd.shard_params(model, mesh, TP_RULES)

    class _LM(nn.Layer):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, x):
            return self.inner(x)

    def loss_fn(logits, y):
        v = logits.shape[-1]
        return F.cross_entropy(ops.reshape(logits[:, :-1, :], [-1, v]),
                               ops.reshape(y[:, 1:], [-1]))

    eng = Engine(_LM(model), loss=loss_fn,
                 optimizer=paddle.optimizer.AdamW(
                     learning_rate=1e-2, parameters=model.parameters()),
                 mesh=mesh, in_specs=(P("data"), P("data")))
    eng.prepare()
    ids = np.random.RandomState(1).randint(0, 64, (8, 16)).astype(np.int64)
    pa = [p._data for p in eng._params]
    st = eng._init_opt_state(pa)
    losses = []
    for _ in range(3):
        loss, pa, st = eng._train_step(
            pa, st, jnp.asarray(1e-2, jnp.float32), ids, ids)
        losses.append(float(np.asarray(loss)))
    assert eng.spmd_stats["fallback"] == {}
    assert losses[-1] < losses[0], losses


def test_to_static_mesh_kwarg_auto_shards():
    mesh = _mesh(data=2, tp=4)
    from paddle_tpu.jit import to_static

    paddle.seed(13)
    model = GPTForCausalLM(GPTConfig(**CFG))
    spmd.shard_params(model, mesh, TP_RULES)

    @to_static(mesh=mesh, in_specs=(P("data"), P("data")))
    def fwd(x, y):
        _, loss = model(x, labels=y)
        return loss

    ids = np.random.RandomState(2).randint(0, 64, (4, 16)).astype(np.int64)
    got = float(fwd(paddle.to_tensor(ids), paddle.to_tensor(ids)).numpy())
    assert fwd.spmd_stats["fallback"] == {}

    paddle.seed(13)
    ref_model = GPTForCausalLM(GPTConfig(**CFG))
    _, ref = ref_model(paddle.to_tensor(ids), labels=paddle.to_tensor(ids))
    np.testing.assert_allclose(got, float(ref.numpy()), rtol=1e-4)


def test_bare_partition_spec_is_atomic():
    """P('a', None) subclasses tuple — a bare 2-entry spec must
    broadcast as ONE spec, never be shredded into per-input entries
    (engine._spec_pair / trace_scope.seed_tree regression)."""
    mesh = _mesh(data=2, tp=4)
    sc = spmd.trace_scope(mesh)
    t1 = paddle.to_tensor(np.ones((4, 8), np.float32))
    t2 = paddle.to_tensor(np.ones((4, 8), np.float32))
    with sc:
        sc.seed_tree((t1, t2), P("data", None))
    assert sc.env[id(t1)] == ("data", None)
    assert sc.env[id(t2)] == ("data", None)

    from paddle_tpu.distributed.auto_parallel.engine import Engine

    class _Id(nn.Layer):
        def forward(self, x):
            return x

    eng = Engine(_Id(), loss=lambda o, y: (o - y).sum(), mesh=mesh,
                 in_specs=P("data", None))
    assert eng._spec_pair() == (P("data", None), P("data", None))
    eng2 = Engine(_Id(), loss=lambda o, y: (o - y).sum(), mesh=mesh,
                  in_specs=(P("data"), None))
    assert eng2._spec_pair() == (P("data"), None)


def test_fallback_warns_and_counts():
    mesh = _mesh(data=8)
    sc = spmd.trace_scope(mesh)
    from paddle_tpu.core import dispatch
    with sc, warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        t = paddle.to_tensor(np.ones((4, 4), np.float32))
        dispatch.call("definitely_not_an_op_xyz", lambda a: a + 0.0, [t])
    assert sc.stats["fallback"] == {"definitely_not_an_op_xyz": 1}
    assert sc.stats["tiers"]["replicate-warn"] == 1
    assert any("no sharding rule" in str(x.message) for x in w) or \
        "definitely_not_an_op_xyz" in spmd.propagate._warned_ops


# ==========================================================================
# coverage gate (tools/spmd_coverage_audit.py)
# ==========================================================================
class TestCoverageGate:
    def test_audit_runs_and_counts_match(self):
        from tools.spmd_coverage_audit import audit
        rep = audit()
        cov = spmd.coverage()
        assert rep["total_ops"] == len(cov)
        assert rep["tiers"]["rule"] == sum(
            1 for v in cov.values() if v["tier"] == "rule")

    def test_covered_op_count_never_regresses(self):
        """The ratchet: ops carrying a REAL rule and the number of rule
        classes may grow, never shrink (update the floor when adding
        rules)."""
        from tools.spmd_coverage_audit import audit
        rep = audit()
        assert rep["tiers"]["rule"] >= 257, rep["tiers"]
        assert rep["rule_classes"] >= 29, rep["rule_classes"]
        # the high-traffic LLM op set must be tier-'rule' forever —
        # including the compile/fusion rewrite targets (a fused program
        # must propagate with zero replicate-fallbacks)
        for op in ("matmul", "linear", "embedding", "embedding_bag",
                   "scatter_add", "bce_with_logits", "layer_norm",
                   "rms_norm", "flash_attention",
                   "scaled_dot_product_attention", "reshape", "split",
                   "softmax", "cross_entropy", "gelu", "getitem",
                   "transpose", "concat", "sum", "mean", "cumsum",
                   "conv2d", "dropout", "fused_bias_act",
                   "fused_residual_norm", "fused_norm_linear",
                   "fused_rope_proj"):
            _, tier = R.rule_for(op)
            assert tier == "rule", (op, tier)

    def test_fusion_category_is_fully_ruled(self):
        """Every category-'fusion' op must carry a NAMED spmd rule —
        registering a fused op without one fails here (and in
        tools/fusion_audit.py) instead of silently replicating."""
        from tools.spmd_coverage_audit import audit
        rep = audit()
        bad = rep["fusion"]["unruled"]
        assert not bad, f"fusion ops without a named spmd rule: {bad}"
        assert set(rep["fusion"]["ops"]) >= {
            "fused_bias_act", "fused_residual_norm",
            "fused_norm_linear", "fused_rope_proj"}
