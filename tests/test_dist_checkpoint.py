"""Distributed checkpoint tests: sharded save + reshard-on-load.

Reference analog: test/auto_parallel/test_dist_checkpoint_utils.py — save
under one mesh, load under another, values identical.
"""
import numpy as np
import pytest
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import mesh as mesh_mod


@pytest.fixture
def restore_mesh():
    old = mesh_mod._global_mesh
    yield
    mesh_mod._global_mesh = old


def _sharded_tensor(arr, mesh, spec):
    return paddle.Tensor(jax.device_put(arr, NamedSharding(mesh, spec)))


def test_save_load_same_mesh(tmp_path, restore_mesh):
    mesh = mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
    w = np.random.randn(16, 4).astype(np.float32)
    state = {"w": _sharded_tensor(w, mesh, P("dp"))}
    dist.save_state_dict(state, str(tmp_path))

    target = {"w": _sharded_tensor(np.zeros_like(w), mesh, P("dp"))}
    dist.load_state_dict(target, str(tmp_path))
    np.testing.assert_allclose(np.asarray(target["w"]._data), w)


def test_reshard_on_load_different_mesh(tmp_path, restore_mesh):
    # save on {dp:8}, load on {dp:4, mp:2} with different placements
    mesh1 = mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
    w = np.random.randn(8, 6).astype(np.float32)
    b = np.random.randn(12,).astype(np.float32)
    state = {"w": _sharded_tensor(w, mesh1, P("dp", None)),
             "b": _sharded_tensor(b, mesh1, P())}
    dist.save_state_dict(state, str(tmp_path))

    mesh2 = mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 4, "mp": 2}))
    target = {"w": _sharded_tensor(np.zeros_like(w), mesh2, P(None, "mp")),
              "b": _sharded_tensor(np.zeros_like(b), mesh2, P("dp"))}
    dist.load_state_dict(target, str(tmp_path))
    np.testing.assert_allclose(np.asarray(target["w"]._data), w)
    np.testing.assert_allclose(np.asarray(target["b"]._data), b)
    # target sharding preserved (reshard happened, not replacement)
    assert target["w"]._data.sharding.spec == P(None, "mp")


def test_chunked_files_on_disk(tmp_path, restore_mesh):
    import json
    import os
    mesh = mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
    w = np.arange(32, dtype=np.float32).reshape(8, 4)
    dist.save_state_dict({"w": _sharded_tensor(w, mesh, P("dp"))},
                         str(tmp_path))
    with open(os.path.join(str(tmp_path), "metadata.json")) as f:
        doc = json.load(f)
    meta = doc["state"]   # round-9 v2 metadata wraps the tensor table
    assert doc["version"] == 2
    # 8 distinct slices of rows, one per dp shard
    assert len(meta["w"]["chunks"]) == 8
    assert meta["w"]["shape"] == [8, 4]
    offs = sorted(c["offsets"][0] for c in meta["w"]["chunks"])
    assert offs == list(range(8))


def test_bf16_round_trip(tmp_path, restore_mesh):
    import jax.numpy as jnp
    mesh = mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
    w = np.random.randn(8, 4).astype(np.float32)
    t = paddle.Tensor(jax.device_put(jnp.asarray(w).astype(jnp.bfloat16),
                                     NamedSharding(mesh, P("dp"))))
    dist.save_state_dict({"w": t}, str(tmp_path))
    target = {"w": paddle.Tensor(
        jax.device_put(jnp.zeros((8, 4), jnp.bfloat16),
                       NamedSharding(mesh, P())))}
    dist.load_state_dict(target, str(tmp_path))
    assert target["w"]._data.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(target["w"]._data, dtype=np.float32),
        np.asarray(jnp.asarray(w).astype(jnp.bfloat16), dtype=np.float32))


def test_missing_key_raises(tmp_path, restore_mesh):
    mesh = mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
    w = np.zeros((4, 4), np.float32)
    dist.save_state_dict({"w": _sharded_tensor(w, mesh, P())},
                         str(tmp_path))
    with pytest.raises(KeyError):
        dist.load_state_dict(
            {"nope": _sharded_tensor(w, mesh, P())}, str(tmp_path))


def test_model_state_dict_round_trip(tmp_path, restore_mesh):
    from paddle_tpu import nn
    mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
    paddle.seed(0)
    net = nn.Linear(8, 8)
    ref = {k: np.asarray(v._data) for k, v in net.state_dict().items()}
    dist.save_state_dict(net.state_dict(), str(tmp_path))

    paddle.seed(1)
    net2 = nn.Linear(8, 8)
    dist.load_state_dict(net2.state_dict(), str(tmp_path))
    for k, v in net2.state_dict().items():
        np.testing.assert_allclose(np.asarray(v._data), ref[k])
