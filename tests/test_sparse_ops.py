"""Sparse op surface tests (reference python/paddle/sparse/
{unary,binary,multiary}.py) — validated against dense equivalents."""
import numpy as np
import pytest

import paddle_tpu as paddle

sp = paddle.sparse


def _coo(dense):
    dense = np.asarray(dense, np.float32)
    idx = np.argwhere(dense != 0)
    vals = dense[tuple(idx.T)]
    return sp.sparse_coo_tensor(idx.T, vals.astype(np.float32), dense.shape)


def _rand(shape, density=0.4, seed=0):
    rng = np.random.RandomState(seed)
    d = rng.randn(*shape).astype(np.float32)
    d[rng.rand(*shape) > density] = 0.0
    return d


class TestUnary:
    @pytest.mark.parametrize("name", [
        "sin", "tan", "asinh", "atan", "sinh", "tanh", "square", "log1p",
        "abs", "neg", "expm1",
    ])
    def test_matches_dense(self, name):
        d = _rand((4, 5), seed=1) * 0.5
        x = _coo(d)
        out = getattr(sp, name)(x)
        ref = getattr(np, {"abs": "abs", "neg": "negative",
                           "square": "square"}.get(name, name))(d)
        # value-ops apply only at stored positions; zeros stay zero
        ref[d == 0] = 0.0
        np.testing.assert_allclose(out.to_dense().numpy(), ref, atol=1e-6)

    def test_pow_cast(self):
        d = np.abs(_rand((3, 3), seed=2)) + 0.1
        d[0, 0] = 0.0
        x = _coo(d)
        out = sp.pow(x, 2.0).to_dense().numpy()
        ref = d ** 2
        ref[d == 0] = 0
        np.testing.assert_allclose(out, ref, rtol=1e-5)
        c = sp.cast(x, value_dtype="float32")
        assert c.values().numpy().dtype == np.float32

    def test_unary_grad_flows(self):
        d = _rand((3, 3), seed=3)
        x = _coo(d)
        x.stop_gradient = False
        out = sp.square(x)
        out.values().sum().backward()
        vals = x.values() if hasattr(x, "values") else None
        # gradient w.r.t. stored values = 2v
        assert x.grad is not None


class TestBinaryStructure:
    def test_subtract_union_pattern(self):
        a = np.zeros((3, 3), np.float32)
        b = np.zeros((3, 3), np.float32)
        a[0, 0], a[1, 1] = 2.0, 3.0
        b[1, 1], b[2, 2] = 1.0, 4.0
        out = sp.subtract(_coo(a), _coo(b))
        np.testing.assert_allclose(out.to_dense().numpy(), a - b)

    def test_multiply_intersection(self):
        a = _rand((4, 4), seed=4)
        b = _rand((4, 4), seed=5)
        out = sp.multiply(_coo(a), _coo(b))
        np.testing.assert_allclose(out.to_dense().numpy(), a * b,
                                   atol=1e-6)

    def test_multiply_scalar_divide(self):
        d = _rand((3, 4), seed=6)
        x = _coo(d)
        np.testing.assert_allclose(sp.multiply(x, 2.5).to_dense().numpy(),
                                   d * 2.5, rtol=1e-6)
        np.testing.assert_allclose(sp.divide(x, 2.0).to_dense().numpy(),
                                   d / 2.0, rtol=1e-6)

    def test_mv_and_addmm(self):
        d = _rand((3, 4), seed=7)
        v = np.random.RandomState(8).randn(4).astype(np.float32)
        np.testing.assert_allclose(sp.mv(_coo(d), paddle.to_tensor(v)).numpy(),
                                   d @ v, atol=1e-5)
        y = np.random.RandomState(9).randn(4, 2).astype(np.float32)
        inp = np.random.RandomState(10).randn(3, 2).astype(np.float32)
        out = sp.addmm(paddle.to_tensor(inp), _coo(d), paddle.to_tensor(y),
                       beta=0.5, alpha=2.0)
        np.testing.assert_allclose(out.numpy(), 0.5 * inp + 2.0 * (d @ y),
                                   atol=1e-5)

    def test_masked_matmul_sddmm(self):
        rng = np.random.RandomState(11)
        a = rng.randn(4, 6).astype(np.float32)
        b = rng.randn(6, 5).astype(np.float32)
        mask_d = (_rand((4, 5), seed=12) != 0).astype(np.float32)
        mask = _coo(mask_d)
        out = sp.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                               mask)
        ref = (a @ b) * mask_d
        np.testing.assert_allclose(out.to_dense().numpy(), ref, atol=1e-5)

    def test_masked_matmul_grad(self):
        rng = np.random.RandomState(13)
        a = paddle.to_tensor(rng.randn(3, 4).astype(np.float32))
        a.stop_gradient = False
        b = paddle.to_tensor(rng.randn(4, 3).astype(np.float32))
        mask = _coo(np.eye(3, dtype=np.float32))
        out = sp.masked_matmul(a, b, mask)
        out.values().sum().backward()
        # d/da of sum_i a_i . b_i over diagonal = b columns
        np.testing.assert_allclose(a.grad.numpy(), b.numpy().T, atol=1e-5)


class TestStructureOps:
    def test_transpose(self):
        d = _rand((3, 5), seed=14)
        out = sp.transpose(_coo(d), [1, 0])
        np.testing.assert_allclose(out.to_dense().numpy(), d.T)

    def test_reshape(self):
        d = _rand((2, 6), seed=15)
        out = sp.reshape(_coo(d), [3, 4])
        np.testing.assert_allclose(out.to_dense().numpy(), d.reshape(3, 4))
        out2 = sp.reshape(_coo(d), [4, -1])
        np.testing.assert_allclose(out2.to_dense().numpy(),
                                   d.reshape(4, 3))

    def test_sum_and_coalesce(self):
        d = _rand((4, 4), seed=16)
        assert abs(float(sp.sum(_coo(d)).numpy()) - d.sum()) < 1e-5
        # duplicate coordinates merge
        x = sp.sparse_coo_tensor(
            np.array([[0, 0], [0, 0]]).T,
            np.array([1.0, 2.0], np.float32), (2, 2))
        c = sp.coalesce(x)
        assert c.nnz() == 1
        np.testing.assert_allclose(c.to_dense().numpy()[0, 0], 3.0)

    def test_is_same_shape(self):
        a = _coo(_rand((2, 3), seed=17))
        b = _coo(_rand((2, 3), seed=18))
        assert sp.is_same_shape(a, b)
        assert not sp.is_same_shape(a, _coo(_rand((3, 2), seed=19)))


class TestReviewFixes:
    def test_unary_under_amp(self):
        from paddle_tpu import amp
        d = _rand((3, 3), seed=20)
        with amp.auto_cast(level="O1"):
            out = sp.sin(paddle.to_tensor(d))
        assert np.isfinite(np.asarray(out.numpy())).all()

    def test_coalesce_grad_flows(self):
        x = sp.sparse_coo_tensor(
            np.array([[0, 0], [0, 0]]).T,
            np.array([1.0, 2.0], np.float32), (2, 2))
        x.stop_gradient = False
        c = sp.coalesce(x)
        c.values().sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])

    def test_multiply_tensor_scalar_grad(self):
        d = _rand((3, 3), seed=21)
        x = _coo(d)
        s = paddle.to_tensor(np.float32(2.0))
        s.stop_gradient = False
        out = sp.multiply(x, s)
        out.values().sum().backward()
        assert s.grad is not None
        np.testing.assert_allclose(float(s.grad.numpy()),
                                   d[d != 0].sum(), rtol=1e-5)

    def test_sum_dtype(self):
        d = _rand((3, 3), seed=22)
        out = sp.sum(_coo(d))
        assert abs(float(out.numpy()) - d.sum()) < 1e-5

    def test_divide_same_pattern_and_mismatch_raises(self):
        a = _coo(np.array([[4.0, 0], [0, 6.0]], np.float32))
        b = _coo(np.array([[2.0, 0], [0, 3.0]], np.float32))
        out = sp.divide(a, b)
        np.testing.assert_allclose(out.to_dense().numpy(),
                                   [[2, 0], [0, 2]])
        c = _coo(np.array([[0, 1.0], [0, 1.0]], np.float32))
        with pytest.raises(ValueError, match="pattern"):
            sp.divide(a, c)

    def test_reshape_validates(self):
        d = _rand((2, 6), seed=23)
        with pytest.raises(ValueError, match="size mismatch"):
            sp.reshape(_coo(d), [5, 2])
        with pytest.raises(ValueError, match="-1"):
            sp.reshape(_coo(d), [-1, -1])
