"""Fault-tolerance suite — every recovery path proven end-to-end.

Covers the ISSUE-4 reliability layer: v2 atomic+verified checkpoint
format (corruption matrix: truncation at every section boundary,
single-byte flips caught by CRC), crash-mid-save atomicity via the
deterministic ``io.write_truncate_after_bytes`` fault point, rotation +
fallback-past-corrupt resume with the ``resume_fallback_depth`` metric,
retry/backoff timing through the clock seam (zero real sleeps),
async_save error propagation, the fused found-inf path, and hapi
auto-resume. All injection is deterministic — no timing races, no
``slow`` marks.
"""
import io as stdio
import json
import os
import pickle
import random
import struct
import zlib

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault
from paddle_tpu.fault import inject
from paddle_tpu.fault.retry import RetryPolicy, retry
from paddle_tpu.framework import io as fio
from paddle_tpu.observability import REGISTRY


@pytest.fixture(autouse=True)
def _clean():
    inject.disarm_all()
    paddle.set_flags({"FLAGS_enable_metrics": False})
    REGISTRY.reset()
    yield
    inject.disarm_all()
    paddle.set_flags({"FLAGS_enable_metrics": False})
    REGISTRY.reset()


def _state():
    """One >=1MB raw segment ('w') + small pickled entries."""
    big = paddle.to_tensor(
        np.arange(fio._SEG_THRESHOLD // 4 + 7, dtype=np.float32))
    return {"w": big,
            "b": paddle.to_tensor(np.asarray([1.5, -2.0], np.float32)),
            "step": 3}


def _assert_roundtrip(out):
    assert out["step"] == 3
    np.testing.assert_array_equal(np.asarray(out["b"]._data), [1.5, -2.0])
    np.testing.assert_array_equal(
        np.asarray(out["w"]._data),
        np.arange(fio._SEG_THRESHOLD // 4 + 7, dtype=np.float32))


def _layout(path):
    """(size, pickle_end, footer_off) of a v2 checkpoint."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        assert f.read(8) == fio._MAGIC2
        (blob_len,) = struct.unpack("<Q", f.read(8))
        f.seek(size - fio._TRAILER.size - len(fio._END_MAGIC))
        footer_off, _, _ = fio._TRAILER.unpack(f.read(fio._TRAILER.size))
    return size, 16 + blob_len, footer_off


class TestV2Format:
    def test_roundtrip_and_verify_default(self, tmp_path):
        p = str(tmp_path / "a.pdckpt")
        fio.save(_state(), p)
        _assert_roundtrip(fio.load(p))
        _assert_roundtrip(fio.load(p, verify=False))

    def test_truncation_matrix(self, tmp_path):
        """Truncation at EVERY section boundary raises the corrupt-
        checkpoint error (never struct.error/EOFError)."""
        p = str(tmp_path / "a.pdckpt")
        fio.save(_state(), p)
        size, pickle_end, footer_off = _layout(p)
        raw = open(p, "rb").read()
        cuts = {
            "mid-magic": 4,
            "mid-length": 12,
            "mid-pickle": (16 + pickle_end) // 2,
            "mid-segment": (pickle_end + footer_off) // 2,
            "mid-footer": footer_off + 5,
            "mid-trailer": size - 10,
            "no-end-magic": size - 3,
        }
        for label, cut in cuts.items():
            q = str(tmp_path / f"cut_{cut}.pdckpt")
            with open(q, "wb") as f:
                f.write(raw[:cut])
            with pytest.raises(fio.CheckpointCorruptError):
                fio.load(q)

    def test_single_byte_flips_named_sections(self, tmp_path):
        p = str(tmp_path / "a.pdckpt")
        fio.save(_state(), p)
        size, pickle_end, footer_off = _layout(p)
        raw = open(p, "rb").read()
        flips = {
            20: "pickle",                          # inside pickle blob
            (pickle_end + footer_off) // 2: "segment 0 ('w')",
            footer_off + 3: "footer",
            2: "header",                           # inside magic
        }
        for off, expect in flips.items():
            q = str(tmp_path / f"flip_{off}.pdckpt")
            body = bytearray(raw)
            body[off] ^= 0x40
            with open(q, "wb") as f:
                f.write(bytes(body))
            with pytest.raises(fio.CheckpointCorruptError) as ei:
                fio.load(q)
            assert expect in str(ei.value), \
                f"flip at {off}: expected section {expect!r} in " \
                f"{ei.value}"

    def test_corruption_metric_counts(self, tmp_path):
        p = str(tmp_path / "a.pdckpt")
        fio.save(_state(), p)
        body = bytearray(open(p, "rb").read())
        body[len(body) // 2] ^= 0x01
        open(p, "wb").write(bytes(body))
        paddle.set_flags({"FLAGS_enable_metrics": True})
        with pytest.raises(fio.CheckpointCorruptError):
            fio.load(p)
        m = REGISTRY.get("paddle_tpu_ckpt_corruption_detected_total")
        assert m is not None and m.total() >= 1

    def test_crash_mid_save_leaves_destination_intact(self, tmp_path):
        """Acceptance: arm io.write_truncate_after_bytes mid-save; the
        destination still holds the previous valid checkpoint bytes and
        no temp file survives."""
        p = str(tmp_path / "a.pdckpt")
        fio.save(_state(), p)
        old = open(p, "rb").read()
        with inject.armed("io.write_truncate_after_bytes",
                          after_bytes=len(old) // 2):
            with pytest.raises(inject.InjectedFault):
                fio.save({"other": paddle.to_tensor(
                    np.zeros(fio._SEG_THRESHOLD // 2, np.float32))}, p)
        assert open(p, "rb").read() == old
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]
        _assert_roundtrip(fio.load(p))

    def test_rename_fail_leaves_destination_intact(self, tmp_path):
        p = str(tmp_path / "a.pdckpt")
        fio.save(_state(), p)
        old = open(p, "rb").read()
        with inject.armed("io.rename_fail"):
            with pytest.raises(OSError):
                fio.save({"x": 1}, p)
        assert open(p, "rb").read() == old
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]

    def test_legacy_v1_and_plain_pickle_still_load(self, tmp_path):
        # v1 layout written by the pre-round-9 writer
        small = np.asarray([[1.0, 2.0]], np.float32)
        blob = pickle.dumps({"w": small}, protocol=4)
        footer = pickle.dumps([], protocol=4)
        p1 = str(tmp_path / "v1.pdparams")
        with open(p1, "wb") as f:
            f.write(fio._MAGIC)
            f.write(struct.pack("<Q", len(blob)))
            f.write(blob)
            off = f.tell()
            f.write(footer)
            f.write(struct.pack("<Q", off))
        out = fio.load(p1)
        np.testing.assert_array_equal(np.asarray(out["w"]._data),
                                      [[1.0, 2.0]])
        # round-2 plain pickle
        p2 = str(tmp_path / "legacy.pdparams")
        with open(p2, "wb") as f:
            pickle.dump({"b": small}, f, protocol=4)
        np.testing.assert_array_equal(
            np.asarray(fio.load(p2)["b"]._data), [[1.0, 2.0]])

    def test_truncated_v1_raises_clear_error(self, tmp_path):
        """Satellite: v1 footer parsing validates bounds — truncation
        yields CheckpointCorruptError naming the path, not
        struct.error/EOFError."""
        blob = pickle.dumps({"a": 1}, protocol=4)
        footer = pickle.dumps([], protocol=4)
        p = str(tmp_path / "v1.pdparams")
        with open(p, "wb") as f:
            f.write(fio._MAGIC)
            f.write(struct.pack("<Q", len(blob)))
            f.write(blob)
            off = f.tell()
            f.write(footer)
            f.write(struct.pack("<Q", off))
        raw = open(p, "rb").read()
        for cut in (10, 18, len(raw) - 4):
            q = str(tmp_path / f"cut{cut}")
            open(q, "wb").write(raw[:cut])
            with pytest.raises(fio.CheckpointCorruptError) as ei:
                fio.load(q)
            assert q in str(ei.value)


class TestCheckpointManager:
    def _save_n(self, mgr, n, size=8):
        for s in range(n):
            mgr.save({"model": {"x": paddle.to_tensor(
                np.full(size, float(s), np.float32))}}, step=s, epoch=s)

    def test_rotation_keep_n_and_manifest(self, tmp_path):
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=3)
        self._save_n(mgr, 5)
        assert len(mgr.checkpoints()) == 3
        steps = [e["step"] for e in mgr.manifest()]
        assert steps == [2, 3, 4]
        assert mgr.latest().endswith("ckpt-0000000004.pdckpt")

    def test_fallback_past_corrupt_latest(self, tmp_path):
        """Acceptance: newest checkpoint corrupt -> restore() falls back
        to the prior one and reports resume_fallback_depth=1."""
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=4)
        self._save_n(mgr, 3)
        newest = mgr.latest()
        body = bytearray(open(newest, "rb").read())
        body[len(body) // 2] ^= 0xFF
        open(newest, "wb").write(bytes(body))
        paddle.set_flags({"FLAGS_enable_metrics": True})
        with pytest.warns(UserWarning, match="skipping"):
            state, meta = mgr.restore()
        assert meta["step"] == 1 and mgr.last_fallback_depth == 1
        np.testing.assert_array_equal(
            np.asarray(state["model"]["x"]._data), np.full(8, 1.0))
        assert REGISTRY.get(
            "paddle_tpu_resume_fallback_depth").value() == 1.0
        assert REGISTRY.get(
            "paddle_tpu_resume_fallback_total").value() == 1.0

    def test_fallback_past_partial_write(self, tmp_path):
        """A checkpoint truncated by a crash (no atomic publish would
        produce this, but a torn copy or disk loss can) is skipped."""
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=4)
        self._save_n(mgr, 2)
        newest = mgr.latest()
        raw = open(newest, "rb").read()
        open(newest, "wb").write(raw[:len(raw) // 3])
        with pytest.warns(UserWarning):
            state, meta = mgr.restore()
        assert meta["step"] == 0

    def test_restore_none_when_all_corrupt(self, tmp_path):
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=4)
        self._save_n(mgr, 2)
        for p in mgr.checkpoints():
            open(p, "wb").write(b"garbage")
        with pytest.warns(UserWarning):
            assert mgr.restore() is None
        assert mgr.last_fallback_depth is None

    def test_save_retries_transient_rename_failure(self, tmp_path):
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=2)
        paddle.set_flags({"FLAGS_enable_metrics": True})
        with inject.armed("io.rename_fail", times=1):
            mgr.save({"model": {}}, step=0)   # retried past one failure
        assert len(mgr.checkpoints()) == 1
        assert REGISTRY.get("paddle_tpu_fault_retries_total").value(
            site="ckpt.save") == 1.0

    def test_save_retry_exhaustion_surfaces_original_error(self, tmp_path):
        mgr = fault.CheckpointManager(
            str(tmp_path), keep_n=2,
            retry_policy=RetryPolicy(max_attempts=2, base_delay=0.001))
        with inject.armed("io.rename_fail", times=5):
            with pytest.raises(OSError):
                mgr.save({"model": {}}, step=0)
        assert mgr.checkpoints() == []


class TestRetryBackoff:
    def _fake(self):
        sleeps = []
        clock = {"t": 0.0}

        def sleep(d):
            sleeps.append(d)
            clock["t"] += d

        return sleeps, (lambda: clock["t"]), sleep

    def test_exponential_schedule_no_real_sleeps(self):
        sleeps, clock, sleep = self._fake()
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise TimeoutError("boom")

        pol = RetryPolicy(max_attempts=4, base_delay=0.1, multiplier=2.0,
                          jitter=0.0)
        with pytest.raises(TimeoutError, match="boom"):
            retry(fn, pol, sleep=sleep, clock=clock)
        assert sleeps == pytest.approx([0.1, 0.2, 0.4])
        assert calls["n"] == 4

    def test_max_delay_caps_schedule(self):
        sleeps, clock, sleep = self._fake()
        pol = RetryPolicy(max_attempts=5, base_delay=0.1, multiplier=4.0,
                          max_delay=0.5, jitter=0.0)
        with pytest.raises(OSError):
            retry(lambda: (_ for _ in ()).throw(OSError("x")), pol,
                  sleep=sleep, clock=clock)
        assert sleeps == pytest.approx([0.1, 0.4, 0.5, 0.5])

    def test_deadline_stops_early(self):
        sleeps, clock, sleep = self._fake()
        pol = RetryPolicy(max_attempts=10, base_delay=0.1, multiplier=2.0,
                          jitter=0.0, deadline=0.25)
        with pytest.raises(TimeoutError):
            retry(lambda: (_ for _ in ()).throw(TimeoutError()), pol,
                  sleep=sleep, clock=clock)
        # 0.1 slept; next delay 0.2 would blow the 0.25s deadline
        assert sleeps == pytest.approx([0.1])

    def test_jitter_deterministic_with_seeded_rng(self):
        pol = RetryPolicy(max_attempts=3, base_delay=0.1, jitter=0.5)
        runs = []
        for _ in range(2):
            sleeps, clock, sleep = self._fake()
            with pytest.raises(TimeoutError):
                retry(lambda: (_ for _ in ()).throw(TimeoutError()), pol,
                      sleep=sleep, clock=clock, rng=random.Random(7))
            runs.append(sleeps)
        assert runs[0] == runs[1]
        assert all(0.05 <= d <= 0.3 for d in runs[0])

    def test_success_after_transient_failures(self):
        sleeps, clock, sleep = self._fake()
        state = {"n": 0}

        def fn():
            state["n"] += 1
            if state["n"] < 3:
                raise OSError("transient")
            return "ok"

        assert retry(fn, RetryPolicy(max_attempts=5, jitter=0.0),
                     sleep=sleep, clock=clock) == "ok"
        assert len(sleeps) == 2

    def test_non_retryable_error_propagates_immediately(self):
        sleeps, clock, sleep = self._fake()
        with pytest.raises(KeyError):
            retry(lambda: (_ for _ in ()).throw(KeyError("x")),
                  RetryPolicy(max_attempts=5), sleep=sleep, clock=clock)
        assert sleeps == []


class TestObjectCollectiveRetry:
    def test_all_gather_object_rides_out_timeouts(self):
        import paddle_tpu.distributed as dist
        paddle.set_flags({"FLAGS_enable_metrics": True})
        with inject.armed("collective.timeout", times=2):
            out = dist.all_gather_object([], {"a": 1})
        assert out and all(o == {"a": 1} for o in out)
        assert REGISTRY.get("paddle_tpu_fault_retries_total").value(
            site="all_gather_object") == 2.0

    def test_all_gather_object_exhaustion_raises_timeout(self):
        import paddle_tpu.distributed as dist
        with inject.armed("collective.timeout", times=50):
            with pytest.raises(TimeoutError):
                dist.all_gather_object([], 1)

    def test_broadcast_object_list_rides_out_timeouts(self):
        import paddle_tpu.distributed as dist
        objs = [{"a": 1}, "hello"]
        with inject.armed("collective.timeout", times=1):
            out = dist.broadcast_object_list(objs, src=0)
        assert out[0] == {"a": 1} and out[1] == "hello"


class TestDistributedCheckpoint:
    def _sd(self):
        return {"w": paddle.to_tensor(
            np.arange(16, dtype=np.float32).reshape(4, 4))}

    def test_metadata_carries_chunk_crcs(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as dcp
        d = str(tmp_path / "ck")
        dcp.save_state_dict(self._sd(), d)
        meta = json.load(open(os.path.join(d, "metadata.json")))
        assert meta["version"] == 2
        chunks = meta["state"]["w"]["chunks"]
        assert chunks and all("crc32" in c for c in chunks)

    def test_load_detects_flipped_chunk(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as dcp
        d = str(tmp_path / "ck")
        dcp.save_state_dict(self._sd(), d)
        # rewrite the shard file with altered data but the OLD metadata
        fname = os.path.join(d, "0.distcp")
        arrs = dict(np.load(fname))
        key = next(iter(arrs))
        arrs[key] = arrs[key] + 1.0
        np.savez(fname + ".npz", **arrs)
        os.replace(fname + ".npz", fname)
        out = self._sd()
        with pytest.raises(fio.CheckpointCorruptError, match="chunk"):
            dcp.load_state_dict(out, d)

    def test_async_save_roundtrip_and_error_propagation(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as dcp
        d = str(tmp_path / "ok")
        h = dcp.save_state_dict(self._sd(), d, async_save=True)
        h.wait()
        out = {"w": paddle.to_tensor(np.zeros((4, 4), np.float32))}
        dcp.load_state_dict(out, d)
        np.testing.assert_array_equal(
            np.asarray(out["w"]._data),
            np.arange(16, dtype=np.float32).reshape(4, 4))
        # failure on the writer thread surfaces at wait()
        with inject.armed("io.rename_fail", times=10):
            h = dcp.save_state_dict(self._sd(), str(tmp_path / "bad"),
                                    async_save=True)
            with pytest.raises(OSError):
                h.wait()

    def test_async_save_error_surfaces_at_next_save(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as dcp
        with inject.armed("io.rename_fail", times=10):
            h = dcp.save_state_dict(self._sd(), str(tmp_path / "bad"),
                                    async_save=True)
            h._thread.join()   # let the failure land without consuming it
        with pytest.raises(OSError):
            dcp.save_state_dict(self._sd(), str(tmp_path / "ok"))
        # and the queue is clean afterwards
        dcp.save_state_dict(self._sd(), str(tmp_path / "ok"))

    def test_atomic_shard_write_keeps_previous(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as dcp
        d = str(tmp_path / "ck")
        dcp.save_state_dict(self._sd(), d)
        old = open(os.path.join(d, "0.distcp"), "rb").read()
        with inject.armed("io.rename_fail", times=10):
            with pytest.raises(OSError):
                dcp.save_state_dict(
                    {"w": paddle.to_tensor(np.zeros((4, 4), np.float32))},
                    d)
        assert open(os.path.join(d, "0.distcp"), "rb").read() == old


class TestGradScalerFusedFoundInf:
    def _net_with_grads(self, bad=None):
        import jax.numpy as jnp
        net = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        for i, p in enumerate(net.parameters()):
            val = 1.0 if bad is None or i != 0 else bad
            p.grad = paddle.Tensor(
                jnp.full(p._data.shape, val, jnp.float32))
        return net, opt

    @pytest.mark.parametrize("bad,expect", [
        (None, False), (float("inf"), True), (float("nan"), True)])
    def test_parity_with_per_leaf_reference(self, bad, expect):
        import jax.numpy as jnp
        net, opt = self._net_with_grads(bad)
        # reference: the old per-leaf host-sync loop
        ref = any(bool(jnp.any(~jnp.isfinite(p.grad._data)))
                  for p in opt._parameter_list if p.grad is not None)
        scaler = paddle.amp.GradScaler(enable=True, init_loss_scaling=2.0)
        scaler.unscale_(opt)
        assert scaler._found_inf == ref == expect

    def test_found_inf_metric_and_skipped_step(self):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        net, opt = self._net_with_grads(float("inf"))
        w0 = np.asarray(net.parameters()[0]._data).copy()
        scaler = paddle.amp.GradScaler(enable=True, init_loss_scaling=2.0)
        scaler.step(opt)
        np.testing.assert_array_equal(
            np.asarray(net.parameters()[0]._data), w0)   # step skipped
        assert REGISTRY.get(
            "paddle_tpu_amp_found_inf_total").total() == 1.0

    def test_unscale_divides_by_scale(self):
        net, opt = self._net_with_grads(None)
        scaler = paddle.amp.GradScaler(enable=True, init_loss_scaling=4.0)
        scaler.unscale_(opt)
        np.testing.assert_allclose(
            np.asarray(net.parameters()[0].grad._data), 0.25)


class _DS:
    def __len__(self):
        return 32

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        return rng.randn(4).astype("float32"), np.int64(i % 3)


def _make_model():
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 3))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    return model, net


class TestHapiResume:
    def test_step_granular_auto_resume(self, tmp_path):
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=8)
        model, net = _make_model()
        cb = paddle.hapi.ModelCheckpoint(manager=mgr, save_steps=4)
        model.fit(_DS(), epochs=2, batch_size=8, verbose=0, shuffle=False,
                  callbacks=[cb])
        assert model._global_step == 8
        saved = np.asarray(net.state_dict()["0.weight"]._data).copy()

        model2, net2 = _make_model()
        model2.fit(_DS(), epochs=3, batch_size=8, verbose=0, shuffle=False,
                   callbacks=[paddle.hapi.ModelCheckpoint(
                       manager=mgr, save_steps=4)], resume=mgr)
        # resumed at epoch 2 (0/1 already trained) -> 4 more steps
        assert model2._global_step == 12
        # optimizer state restored: Adam step count carried over
        assert model2._optimizer._step_count == 12

    def test_resume_restores_weights_and_scaler(self, tmp_path):
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=4)
        model, net = _make_model()
        scaler = paddle.amp.GradScaler(enable=True,
                                       init_loss_scaling=1024.0)
        scaler._scale = 123.0
        cb = paddle.hapi.ModelCheckpoint(manager=mgr, scaler=scaler)
        model.fit(_DS(), epochs=1, batch_size=8, verbose=0, shuffle=False,
                  callbacks=[cb])
        w = np.asarray(net.state_dict()["0.weight"]._data).copy()

        model2, net2 = _make_model()
        scaler2 = paddle.amp.GradScaler(enable=True)
        start_epoch, skip = model2._auto_resume(
            mgr, [paddle.hapi.ModelCheckpoint(manager=mgr,
                                              scaler=scaler2)], 0)
        assert (start_epoch, skip) == (1, 0)
        np.testing.assert_array_equal(
            np.asarray(net2.state_dict()["0.weight"]._data), w)
        assert scaler2._scale == 123.0

    def test_resume_skips_corrupt_latest(self, tmp_path):
        """Acceptance: resume falls back past a corrupt newest
        checkpoint to the last verifiable one."""
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=8)
        model, net = _make_model()
        model.fit(_DS(), epochs=2, batch_size=8, verbose=0, shuffle=False,
                  callbacks=[paddle.hapi.ModelCheckpoint(manager=mgr)])
        newest = mgr.latest()
        body = bytearray(open(newest, "rb").read())
        body[len(body) // 2] ^= 0xFF
        open(newest, "wb").write(bytes(body))

        model2, _ = _make_model()
        with pytest.warns(UserWarning, match="skipping"):
            model2.fit(_DS(), epochs=3, batch_size=8, verbose=0,
                       shuffle=False, resume=mgr)
        assert mgr.last_fallback_depth == 1
        # epoch-0 checkpoint (step 4) restored -> epochs 1,2 remain
        assert model2._global_step == 12

    def test_nan_injection_skips_step_keeps_weights_finite(self):
        model, net = _make_model()
        inject.arm("grads.nan_at_step", step=1)
        model.fit(_DS(), epochs=1, batch_size=8, verbose=0, shuffle=False)
        assert model._nonfinite_steps == 1
        for name, p in net.state_dict().items():
            assert np.isfinite(np.asarray(p._data)).all(), name

    def test_restore_on_nonfinite_rolls_back(self, tmp_path):
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=4)
        model, net = _make_model()
        cb = paddle.hapi.ModelCheckpoint(manager=mgr, save_steps=2,
                                         restore_on_nonfinite=True)
        inject.arm("grads.nan_at_step", step=3)
        model.fit(_DS(), epochs=1, batch_size=8, verbose=0, shuffle=False,
                  callbacks=[cb])
        assert cb.restored_nonfinite == 1
        for name, p in net.state_dict().items():
            assert np.isfinite(np.asarray(p._data)).all(), name


class TestReviewRegressions:
    def test_corrupt_error_pickles_across_process_boundary(self):
        e = fio.CheckpointCorruptError("/p/ck", "segment 0 ('w')",
                                       "checksum mismatch")
        e2 = pickle.loads(pickle.dumps(e))
        assert (e2.path, e2.section, e2.detail) == \
            (e.path, e.section, e.detail)
        assert str(e2) == str(e)

    def test_fully_resumed_fit_does_not_overwrite_newest(self, tmp_path):
        """fit(resume=mgr) on an already-finished run must be a no-op:
        no retraining, and the newest checkpoint's meta untouched."""
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=4)
        model, net = _make_model()
        cb = paddle.hapi.ModelCheckpoint(manager=mgr)
        model.fit(_DS(), epochs=2, batch_size=8, verbose=0, shuffle=False,
                  callbacks=[cb])
        newest = mgr.latest()
        before = open(newest, "rb").read()
        model2, _ = _make_model()
        # REUSED callback instance: its _epoch state from fit #1 must not
        # leak into this zero-epoch resumed fit's train-end save
        hist = model2.fit(
            _DS(), epochs=2, batch_size=8, verbose=0, shuffle=False,
            callbacks=[cb], resume=mgr)
        assert hist == []                      # nothing retrained
        assert mgr.latest() == newest
        assert open(newest, "rb").read() == before
        # a third resume still fast-forwards cleanly
        model3, _ = _make_model()
        assert model3._auto_resume(mgr, [], 0) == (2, 0)

    def test_resume_skipping_whole_epoch_reports_no_nan_loss(
            self, tmp_path):
        """A save on the LAST batch of an epoch resumes with every batch
        of that epoch skipped — history must not contain NaN."""
        mgr = fault.CheckpointManager(str(tmp_path), keep_n=8)
        model, net = _make_model()
        model.fit(_DS(), epochs=1, batch_size=8, verbose=0, shuffle=False)
        # checkpoint as a preemption right after the LAST batch of epoch
        # 0 (step_in_epoch=3 of 4) would leave it: mid-epoch meta
        mgr.save(fault.capture_train_state(network=net,
                                           optimizer=model._optimizer),
                 step=4, epoch=0,
                 meta={"epoch_complete": False, "step_in_epoch": 3})
        model2, _ = _make_model()
        hist = model2.fit(_DS(), epochs=2, batch_size=8, verbose=0,
                          shuffle=False, resume=mgr)
        assert all(np.isfinite(hist))
        assert model2._global_step == 8        # only epoch 1 trained

    def test_load_verify_false_skips_checksum_work(self, tmp_path,
                                                   monkeypatch):
        p = str(tmp_path / "a.pdckpt")
        fio.save(_state(), p)
        calls = {"n": 0}
        real = zlib.crc32

        def counting(*a, **k):
            calls["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(fio.zlib, "crc32", counting)
        fio.load(p, verify=False)
        unverified = calls["n"]
        calls["n"] = 0
        fio.load(p, verify=True)
        assert unverified < calls["n"]
        # structural-only load never CRCs segment data (1MB+ segment =
        # at least one crc call per chunk on the verify path)
        assert unverified <= 1   # footer crc only


class TestInjectRegistry:
    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            inject.arm("io.not_a_point")

    def test_times_bounds_fires(self):
        inject.arm("collective.timeout", times=2)
        assert inject.fire("collective.timeout") is not None
        assert inject.fire("collective.timeout") is not None
        assert inject.fire("collective.timeout") is None
        assert inject.fired_count("collective.timeout") == 2

    def test_ctx_matching(self):
        inject.arm("grads.nan_at_step", step=5)
        assert inject.fire("grads.nan_at_step", step=4) is None
        assert inject.fire("grads.nan_at_step", step=5) == {"step": 5}

    def test_disarmed_is_free_and_silent(self):
        assert inject.fire("io.rename_fail") is None
        assert not inject.check("io.rename_fail")


class TestWatchdogDiagnostics:
    def test_dump_contains_timeline(self):
        from paddle_tpu.distributed.watchdog import Watchdog
        paddle.set_flags({"FLAGS_enable_metrics": True})
        _ = (paddle.to_tensor(np.ones(4, np.float32)) * 2).numpy()
        import paddle_tpu.distributed as dist
        dist.all_gather_object([], 1)
        wd = Watchdog(timeout=1e9)
        wd.last_op = "multiply"
        wd.last_op_t = 0.0
        buf = stdio.StringIO()
        wd.dump_diagnostics(file=buf)
        text = buf.getvalue()
        assert "last op: 'multiply'" in text
        assert "last collective:" in text
        assert "metrics snapshot" in text
        assert "span buffer tail" in text
