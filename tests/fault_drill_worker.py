"""Self-healing-fleet drill worker — the real 4-process fault matrix.

Runs under ``python -m paddle_tpu.distributed.launch`` like
fleet_drill_worker.py.  One deterministic mode per launch:

``crash``  — every rank trains a closed-form SGD loop (deterministic
  per-rank gradients, one eager ``all_reduce`` per step, per-step
  checkpoints into per-rank CheckpointManager dirs; rank 1 stops saving
  after step 3 to split the manifests).  At elastic epoch 0 the target
  rank SIGKILLs itself at step 6 (``rank.crash_at_step``); the survivors
  block in the step-6 all_reduce until the collective-timeout abort
  plane fires, exchanges flight dumps, names the dead rank (it left no
  dump — absence is the evidence) and exits
  ``EXIT_COLLECTIVE_TIMEOUT``.  The launcher group-restarts; at epoch 1
  every rank resumes from the CROSS-RANK CONSENSUS step (3 — the newest
  step on every manifest), recomputes, bills the recomputed steps to the
  goodput ``rewind`` bucket, finishes step 10 and writes its final
  weights + ledger evidence to ``fault.r<rank>.json``.

``hang``   — the target rank wedges at step 4 (``rank.hang_at_step``)
  WITHOUT touching its lease (the supervisor thread keeps publishing —
  a wedged host looks alive to the heartbeat plane on purpose).  Only
  the collective-timeout plane can catch it: survivors abort 117 with a
  diff verdict naming the hung rank + the collective seq it never
  issued.

``lease``  — the target rank stops publishing its lease at step 4
  (``heartbeat.lease_lost``) but keeps stepping: a partition, not a
  death, invisible to the collective plane (its collectives still
  complete).  Only the heartbeat plane fires: every rank (including the
  partitioned one, which sees its OWN lease expired) exits
  ``EXIT_HEARTBEAT_LOST``.

Usage: fault_drill_worker.py <mode> <outdir>
"""
import json
import os
import signal
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

MODE = sys.argv[1]
OUTDIR = sys.argv[2]
TARGET = int(os.environ.get("DRILL_TARGET_RANK", "3"))
EPOCH = int(os.environ.get("PADDLE_ELASTIC_EPOCH", "0") or 0)

import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed.communication import collective as C  # noqa: E402
from paddle_tpu.fault import CheckpointManager, inject  # noqa: E402
from paddle_tpu.fault import capture_train_state  # noqa: E402
from paddle_tpu.fault import supervisor as sup  # noqa: E402
from paddle_tpu.observability import flight, goodput  # noqa: E402

dist.init_parallel_env()
rank = jax.process_index()
world = jax.process_count()
assert world == 4, f"drill expects 4 processes, got {world}"

# the launcher's reap path (SIGTERM after the abort grace) must still
# leave the flight record behind — and must never orphan this process
signal.signal(signal.SIGTERM,
              lambda *_: (flight.dump(reason="sigterm"), os._exit(1)))

D = 4
LR = 0.1
STEPS = 10


def grad(r: int, s: int) -> np.ndarray:
    """Deterministic per-rank, per-step gradient — the closed form the
    harness recomputes to check crash+rewind == uninterrupted."""
    base = np.arange(1, D + 1, dtype=np.float32)
    return base * (r + 1) * 0.001 * ((s % 5) + 1)


class _Net:
    """Minimal state_dict carrier so the drill exercises the REAL
    capture_train_state / consensus_resume path."""

    def __init__(self):
        self.w = np.zeros(D, np.float32)

    def state_dict(self):
        return {"w": self.w.copy()}

    def set_state_dict(self, sd):
        self.w = np.asarray(sd["w"], np.float32).copy()


def train_step(net: _Net, s: int) -> float:
    # gather_rows is the per-rank-different-payload collective (host
    # all_reduce replicates via device_put, which requires identical
    # values on every process); it blocks if a peer is gone
    mat = C.gather_rows(grad(rank, s))
    net.w = (net.w - LR * mat.mean(axis=0)).astype(np.float32)
    return float(np.sum(net.w ** 2))


# ---------------------------------------------------------------- modes
if MODE == "crash":
    ttl = 30.0                           # heartbeat plane stays silent:
    #                                      the collective plane owns this
elif MODE == "hang":
    ttl = 60.0
else:
    assert MODE == "lease", MODE
    ttl = 1.0

lease = sup.FileLease(os.path.join(OUTDIR, "leases"), ttl=ttl)
svr = sup.Supervisor(lease, interval=0.25).start()
C.barrier()          # every rank has published before anyone judges

if MODE in ("crash", "hang"):
    # arm the collective-timeout plane only AFTER the startup barrier:
    # process launch is staggered by seconds of jax import, so a drill-
    # tight 2 s deadline would fire on the barrier itself (production
    # deadlines are minutes and don't care).  The monitor thread tracks
    # the flag live — no restart needed.
    from paddle_tpu.core import flags
    flags.set_flags({"collective_timeout_s": 2.0})

if MODE == "crash":
    mgr = CheckpointManager(os.path.join(OUTDIR, "ckpt", f"r{rank}"),
                            keep_n=3)
    net = _Net()
    led = goodput.ledger()
    led.run_begin()
    if EPOCH == 0 and rank == TARGET:
        inject.arm("rank.crash_at_step", step=6)
    start_step = 0
    walls = []
    if EPOCH > 0:
        meta = sup.consensus_resume(mgr, network=net)
        assert meta is not None, "epoch 1 found nothing to resume from"
        start_step = int(meta["step"])
        print(f"[drill] rank {rank} epoch {EPOCH}: resumed step "
              f"{start_step}", flush=True)
    for s in range(start_step + 1, STEPS + 1):
        sup.tick(s)                      # fires the crash on the target
        t0 = time.perf_counter()
        led.step_begin()
        loss = train_step(net, s)
        led.step_end(step=s)
        walls.append(time.perf_counter() - t0)
        if not (rank == 1 and s > 3):    # rank 1 splits the manifests
            mgr.save(capture_train_state(network=net), step=s)
    assert EPOCH > 0, "epoch 0 must die before finishing the loop"
    snap = led.snapshot()
    rewind_steps = int(snap["rewind_steps"])
    with open(os.path.join(OUTDIR, f"fault.r{rank}.json"), "w") as f:
        json.dump({
            "rank": rank, "epoch": EPOCH,
            "resume_step": start_step,
            "final_w": [float(v) for v in net.w],
            "final_loss": loss,
            "manifest_steps": mgr.steps(),
            "rewind_steps": rewind_steps,
            "rewind_s": snap["buckets"]["rewind"],
            "measured_recompute_s": sum(walls[:rewind_steps]),
            "resumes": snap["resumes"],
        }, f)
    print(f"[drill] rank {rank} crash-drill complete: final loss "
          f"{loss:.6f}, rewind {rewind_steps} steps", flush=True)
    svr.stop()
    sys.exit(0)

if MODE == "hang":
    if rank == TARGET:
        inject.arm("rank.hang_at_step", step=4)
    net = _Net()
    for s in range(1, 40):
        sup.tick(s)                      # target wedges here at step 4;
        train_step(net, s)               # peers block in this all_reduce
    print(f"[drill] rank {rank} ERROR: hang drill finished the loop",
          flush=True)
    sys.exit(7)

# MODE == "lease"
if rank == TARGET:
    inject.arm("heartbeat.lease_lost", step=4)
net = _Net()
for s in range(1, 200):
    sup.tick(s)                          # target goes silent at step 4
    train_step(net, s)                   # ...but KEEPS stepping: the
    time.sleep(0.05)                     # collective plane sees nothing
print(f"[drill] rank {rank} ERROR: lease drill finished the loop",
      flush=True)
sys.exit(7)
