"""DataLoader tests + the LeNet/MNIST end-to-end slice (BASELINE config 1)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer as optim
from paddle_tpu.io import DataLoader, Dataset, TensorDataset


class _SyntheticMNIST(Dataset):
    """Deterministic separable synthetic 'MNIST' (class-dependent blobs)."""

    def __init__(self, n=256):
        rng = np.random.RandomState(0)
        self.labels = rng.randint(0, 10, n)
        base = rng.randn(10, 1, 28, 28).astype("float32") * 2
        self.images = (base[self.labels]
                       + rng.randn(n, 1, 28, 28).astype("float32") * 0.3)

    def __getitem__(self, i):
        return self.images[i], np.int64(self.labels[i])

    def __len__(self):
        return len(self.labels)


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.ReLU(),
            nn.Linear(120, 84), nn.ReLU(),
            nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        from paddle_tpu.ops import manipulation
        x = manipulation.flatten(x, 1)
        return self.fc(x)


class TestDataLoader:
    def test_batching_and_order(self):
        ds = TensorDataset([paddle.to_tensor(np.arange(10, dtype="float32")
                                             .reshape(10, 1))])
        dl = DataLoader(ds, batch_size=4, shuffle=False)
        batches = [b[0].numpy() for b in dl]
        assert len(batches) == 3
        np.testing.assert_array_equal(batches[0].ravel(), [0, 1, 2, 3])
        assert batches[2].shape[0] == 2

    def test_drop_last_and_shuffle(self):
        ds = _SyntheticMNIST(50)
        dl = DataLoader(ds, batch_size=8, shuffle=True, drop_last=True)
        batches = list(dl)
        assert len(batches) == 6
        assert batches[0][0].shape == [8, 1, 28, 28]

    def test_multiprocess_workers(self):
        ds = _SyntheticMNIST(64)
        dl = DataLoader(ds, batch_size=16, num_workers=2)
        seen = 0
        for img, lab in dl:
            assert img.shape[0] == 16
            seen += img.shape[0]
        assert seen == 64

    def test_dict_collate(self):
        class DictDs(Dataset):
            def __getitem__(self, i):
                return {"x": np.ones(3, "float32") * i, "y": np.int64(i)}

            def __len__(self):
                return 8

        dl = DataLoader(DictDs(), batch_size=4)
        b = next(iter(dl))
        assert b["x"].shape == [4, 3]
        assert b["y"].shape == [4]


class TestSaveLoad:
    def test_model_roundtrip(self, tmp_path):
        net = LeNet()
        path = str(tmp_path / "lenet.pdparams")
        paddle.save(net.state_dict(), path)
        loaded = paddle.load(path)
        net2 = LeNet()
        missing, unexpected = net2.set_state_dict(loaded)
        assert missing == [] and unexpected == []
        np.testing.assert_array_equal(
            net.fc[0].weight.numpy(), net2.fc[0].weight.numpy())

    def test_optimizer_state_roundtrip(self, tmp_path):
        net = nn.Linear(4, 2)
        opt = optim.Adam(learning_rate=0.01, parameters=net.parameters())
        x = paddle.to_tensor(np.random.randn(8, 4).astype("float32"))
        loss = F.mse_loss(net(x), paddle.to_tensor(np.zeros((8, 2), "float32")))
        loss.backward()
        opt.step()
        path = str(tmp_path / "opt.pdopt")
        paddle.save(opt.state_dict(), path)
        sd = paddle.load(path)
        assert sd["@step_count"] == 1

    def test_bf16_roundtrip(self, tmp_path):
        import jax.numpy as jnp
        t = paddle.Tensor(jnp.ones((3,), dtype=jnp.bfloat16))
        path = str(tmp_path / "t.pd")
        paddle.save({"x": t}, path)
        out = paddle.load(path)["x"]
        assert out.dtype == paddle.bfloat16


class TestLeNetEndToEnd:
    def test_trains_to_high_accuracy(self):
        """The minimum end-to-end slice (SURVEY.md §7 stage 4)."""
        paddle.seed(42)
        net = LeNet()
        opt = optim.Adam(learning_rate=1e-3, parameters=net.parameters())
        ds = _SyntheticMNIST(256)
        dl = DataLoader(ds, batch_size=32, shuffle=True, drop_last=True)
        net.train()
        first_loss = last_loss = None
        for epoch in range(3):
            for img, label in dl:
                logits = net(img)
                loss = F.cross_entropy(logits, label)
                loss.backward()
                opt.step()
                opt.clear_grad()
                if first_loss is None:
                    first_loss = float(loss.numpy())
                last_loss = float(loss.numpy())
        assert last_loss < first_loss * 0.5, (first_loss, last_loss)

        # eval accuracy on the training set (separable -> should be high)
        net.eval()
        correct = total = 0
        for img, label in DataLoader(ds, batch_size=64):
            pred = net(img).numpy().argmax(-1)
            correct += (pred == label.numpy()).sum()
            total += len(pred)
        assert correct / total > 0.9, f"accuracy {correct / total}"
