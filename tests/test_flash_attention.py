"""Pallas flash-attention kernel tests (interpret mode on CPU).

The kernels themselves run through the Pallas interpreter so the exact
kernel code that executes on TPU is what is tested here (reference test
analog: test/legacy_test/test_flash_attention.py comparing against a plain
attention implementation).
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.ops.pallas.flash_attention as fa


def _ref_attn(q, k, v, causal, scale):
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * scale
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool), k=t - s)
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


@pytest.fixture(autouse=True)
def _interpret():
    old = fa.INTERPRET
    fa.INTERPRET = True
    yield
    fa.INTERPRET = old


def _rand_qkv(b=1, s=128, h=2, d=32, t=None, seed=0):
    rng = np.random.RandomState(seed)
    t = t or s
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = _rand_qkv()
    scale = 1.0 / math.sqrt(q.shape[-1])
    out = fa.flash_attention_fwd(q, k, v, causal=causal,
                                 block_q=64, block_k=64)
    ref = _ref_attn(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_forward_unaligned_seq():
    # seq not a multiple of the block: exercises padding/masking
    q, k, v = _rand_qkv(s=100, t=100)
    out = fa.flash_attention_fwd(q, k, v, causal=True,
                                 block_q=64, block_k=64)
    ref = _ref_attn(q, k, v, True, 1.0 / math.sqrt(q.shape[-1]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_cross_attention_lengths():
    q, k, v = _rand_qkv(s=64, t=128)
    out = fa.flash_attention_fwd(q, k, v, causal=True,
                                 block_q=64, block_k=64)
    ref = _ref_attn(q, k, v, True, 1.0 / math.sqrt(q.shape[-1]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_reference(causal):
    q, k, v = _rand_qkv(s=128)
    scale = 1.0 / math.sqrt(q.shape[-1])

    def loss_flash(q, k, v):
        out = fa.flash_attention_fwd(q, k, v, causal=causal,
                                     block_q=64, block_k=64)
        return jnp.sum(out * jnp.cos(out))   # non-trivial cotangent

    def loss_ref(q, k, v):
        out = _ref_attn(q, k, v, causal, scale)
        return jnp.sum(out * jnp.cos(out))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_backward_unaligned_and_different_blocks():
    q, k, v = _rand_qkv(s=100, t=100)

    def loss(q, k, v):
        out = fa.flash_attention_fwd(q, k, v, causal=True,
                                     block_q=64, block_k=32)
        return jnp.sum(out ** 2)

    def loss_ref(q, k, v):
        out = _ref_attn(q, k, v, True, 1.0 / math.sqrt(q.shape[-1]))
        return jnp.sum(out ** 2)

    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4)


def test_bf16_forward_backward():
    q, k, v = _rand_qkv(s=64)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def loss(q, k, v):
        return jnp.sum(fa.flash_attention_fwd(
            q, k, v, causal=True, block_q=32, block_k=32)
            .astype(jnp.float32))

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    scale = 1.0 / math.sqrt(q.shape[-1])

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attn(q, k, v, True, scale).astype(jnp.float32))

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32), np.asarray(b, np.float32),
            atol=0.15, rtol=0.1)


def test_fully_masked_rows_causal_sq_gt_sk():
    # causal with seq_q > seq_k: the first (sq - sk) query rows attend zero
    # keys. FA convention: output 0 for those rows, independent of block
    # size; gradients must not leak probability mass from them.
    q, k, v = _rand_qkv(s=128, t=64)
    n_masked = 128 - 64
    outs = []
    for bq, bk in [(32, 32), (64, 64), (128, 64)]:
        out = np.asarray(fa.flash_attention_fwd(q, k, v, causal=True,
                                                block_q=bq, block_k=bk))
        np.testing.assert_allclose(out[:, :n_masked], 0.0, atol=1e-6)
        outs.append(out)
    for o in outs[1:]:
        np.testing.assert_allclose(o, outs[0], atol=1e-5)

    def loss(q, k, v):
        return jnp.sum(fa.flash_attention_fwd(
            q, k, v, causal=True, block_q=32, block_k=32) ** 2)

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # masked q rows contribute nothing anywhere
    np.testing.assert_allclose(np.asarray(gq)[:, :n_masked], 0.0, atol=1e-6)
    assert np.all(np.isfinite(np.asarray(gk)))
    assert np.all(np.isfinite(np.asarray(gv)))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attn_unpadded_matches_per_sequence(causal):
    """Varlen packed attention == dense attention run per sequence."""
    import paddle_tpu as paddle
    from paddle_tpu.nn.functional import flash_attn_unpadded

    rng = np.random.RandomState(0)
    lens = [5, 9, 3]
    total, h, d = sum(lens), 2, 16
    q = rng.randn(total, h, d).astype(np.float32)
    k = rng.randn(total, h, d).astype(np.float32)
    v = rng.randn(total, h, d).astype(np.float32)
    cu = np.cumsum([0] + lens).astype(np.int32)
    scale = 1.0 / math.sqrt(d)

    out, _ = flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu), paddle.to_tensor(cu),
        max(lens), max(lens), scale, causal=causal)
    got = out.numpy()

    for i, L in enumerate(lens):
        s, e = cu[i], cu[i + 1]
        ref = _ref_attn(jnp.asarray(q[None, s:e]), jnp.asarray(k[None, s:e]),
                        jnp.asarray(v[None, s:e]), causal, scale)
        np.testing.assert_allclose(got[s:e], np.asarray(ref)[0],
                                   atol=2e-5,
                                   err_msg=f"sequence {i} mismatch")


def test_flash_attn_unpadded_no_cross_sequence_leak():
    import paddle_tpu as paddle
    from paddle_tpu.nn.functional import flash_attn_unpadded

    rng = np.random.RandomState(1)
    lens = [4, 4]
    total, h, d = 8, 1, 8
    q = rng.randn(total, h, d).astype(np.float32)
    k = rng.randn(total, h, d).astype(np.float32)
    v = rng.randn(total, h, d).astype(np.float32)
    cu = np.cumsum([0] + lens).astype(np.int32)
    out1, _ = flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu), paddle.to_tensor(cu), 4, 4,
        1.0 / math.sqrt(d))
    # perturb sequence 2's K/V: sequence 1's output must not change
    k2, v2 = k.copy(), v.copy()
    k2[4:] += 100.0
    v2[4:] -= 100.0
    out2, _ = flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k2), paddle.to_tensor(v2),
        paddle.to_tensor(cu), paddle.to_tensor(cu), 4, 4,
        1.0 / math.sqrt(d))
    np.testing.assert_allclose(out1.numpy()[:4], out2.numpy()[:4],
                               atol=1e-6)


def test_grad_under_jit():
    q, k, v = _rand_qkv(s=64)
    f = jax.jit(jax.grad(lambda q: jnp.sum(fa.flash_attention_fwd(
        q, k, v, causal=True, block_q=32, block_k=32) ** 2)))
    g = f(q)
    assert np.all(np.isfinite(np.asarray(g)))
