"""static + distributed surface tails.

References: python/paddle/static/__init__.py (45 names),
python/paddle/distributed/__init__.py (65 names), distributed/io.py,
ps entry admission (CountFilterEntry over SparseTable).
"""
import ast
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


def _ref_all(path):
    tree = ast.parse(open(path).read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    return ast.literal_eval(node.value)


class TestStaticTail:
    def test_full_all_parity(self):
        ref = "/root/reference/python/paddle/static/__init__.py"
        if not os.path.exists(ref):
            pytest.skip("no reference tree")
        ra = _ref_all(ref)
        missing = [n for n in ra if not hasattr(static, n)]
        assert not missing, missing

    def test_append_backward_and_gradients(self):
        with static.program_guard(static.Program()):
            x = static.data("x", [2, 4])
            w = static.create_parameter([4, 3], "float32", name="wab")
            y = paddle.matmul(x, w)
            loss = (y * y).mean()
            pairs = static.append_backward(loss)
            assert len(pairs) == 1 and pairs[0][0] is w
            (g,) = static.gradients([loss], [w])
            np.testing.assert_allclose(np.asarray(g.numpy()),
                                       np.asarray(pairs[0][1].numpy()),
                                       rtol=1e-6)

    def test_save_load_roundtrip(self, tmp_path):
        with static.program_guard(static.Program()):
            x = static.data("x", [1, 2])
            w = static.create_parameter([2, 2], "float32", name="wsl")
            y = paddle.matmul(x, w)
            prog = static.default_main_program()
            static.save(prog, str(tmp_path / "m"))
            before = np.asarray(w.numpy()).copy()
            w._swap_payload(w._data * 0)
            static.load(prog, str(tmp_path / "m"))
            np.testing.assert_allclose(np.asarray(w.numpy()), before)
            st = static.load_program_state(str(tmp_path / "m"))
            assert "wsl" in st
            static.set_program_state(prog, {"wsl": before * 2})
            np.testing.assert_allclose(np.asarray(w.numpy()), before * 2)

    def test_inference_export_and_serialize(self, tmp_path):
        with static.program_guard(static.Program()):
            x = static.data("x", [2, 4])
            w = static.create_parameter([4, 3], "float32", name="wie")
            y = paddle.matmul(x, w)
            wv = np.asarray(w.numpy()).copy()
            prog = static.default_main_program()
            static.save_inference_model(str(tmp_path / "inf"), [x], [y])
            blob = static.serialize_persistables([x], [y], prog)
            w._swap_payload(w._data * 0)
            static.deserialize_persistables(prog, blob)
            np.testing.assert_allclose(np.asarray(w.numpy()), wv)
        layer, feeds, fetches = static.load_inference_model(
            str(tmp_path / "inf"))
        xin = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        np.testing.assert_allclose(np.asarray(layer(xin).numpy()),
                                   np.asarray(xin.numpy()) @ wv,
                                   rtol=1e-5)

    def test_scopes_ipu_misc(self):
        s = static.Scope()
        with static.scope_guard(s):
            assert static.global_scope() is s
            s.var("a").set_tensor(42)
            assert s.find_var("a").get_tensor() == 42
        assert static.global_scope() is not s
        with pytest.raises(RuntimeError, match="IPU"):
            static.IpuStrategy()
        with pytest.raises(RuntimeError, match="IPU"):
            static.IpuCompiledProgram()
        with static.name_scope("blk"), static.device_guard("cpu"):
            pass
        t = static.Print(paddle.to_tensor(np.ones(3, np.float32)),
                         message="dbg")
        assert list(t.shape) == [3]
        assert len(static.cpu_places(2)) == 2

    def test_static_metrics(self):
        pred = paddle.to_tensor(
            np.array([[0.2, 0.8], [0.7, 0.3]], np.float32))
        lab = paddle.to_tensor(np.array([[1], [0]]))
        np.testing.assert_allclose(
            float(static.accuracy(pred, lab).numpy()), 1.0)
        a, b, _ = static.auc(paddle.to_tensor(
            np.array([[0.8], [0.3], [0.9], [0.1]], np.float32)),
            paddle.to_tensor(np.array([[1], [0], [1], [0]])))
        assert float(a.numpy()) == 1.0  # perfectly separable
        bundle = static.ctr_metric_bundle(
            paddle.to_tensor(np.array([0.9, 0.1], np.float32)),
            paddle.to_tensor(np.array([1.0, 0.0], np.float32)))
        assert len(bundle) == 7

    def test_static_auc_matches_host_accumulator(self):
        """The in-graph AUC (round 14 rewrite) must match metric.Auc's
        thresholded-bin math, including non-{0,1} positive encodings
        (the accumulator counts label TRUTHINESS, one per sample)."""
        from paddle_tpu.metric import Auc

        rng = np.random.RandomState(7)
        pred = rng.rand(300).astype(np.float32)
        for lab in ((rng.rand(300) > 0.4).astype(np.float32),
                    2.0 * (rng.rand(300) > 0.6).astype(np.float32)):
            m = Auc(num_thresholds=4095)
            m.update(pred, lab)
            a, _, _ = static.auc(paddle.to_tensor(pred),
                                 paddle.to_tensor(lab))
            np.testing.assert_allclose(float(a.numpy()),
                                       m.accumulate(), atol=1e-5)
        # degenerate single-class batch scores 0.0, like the accumulator
        a, _, _ = static.auc(paddle.to_tensor(pred),
                             paddle.to_tensor(np.ones(300, np.float32)))
        assert float(a.numpy()) == 0.0


class TestDistributedTail:
    def test_full_all_parity(self):
        ref = "/root/reference/python/paddle/distributed/__init__.py"
        if not os.path.exists(ref):
            pytest.skip("no reference tree")
        ra = _ref_all(ref)
        missing = [n for n in ra
                   if not hasattr(paddle.distributed, n)]
        assert not missing, missing

    def test_misc_queries(self):
        dist = paddle.distributed
        assert dist.is_available()
        assert dist.get_backend() == "XCCL"
        assert dist.ParallelMode.DATA_PARALLEL == 0
        assert dist.ReduceType.kRedSum == 0
        t = paddle.to_tensor(np.ones(3, np.float32))
        out = dist.wait(t)
        assert out is t
        dist.gloo_init_parallel_env(0, 1, "x")
        dist.gloo_barrier()
        dist.gloo_release()

    def test_gather_and_scatter_objects(self):
        import jax

        dist = paddle.distributed
        out = []
        t = paddle.to_tensor(np.arange(4, dtype=np.float32))
        dist.gather(t, out, dst=dist.get_rank())
        # tensor collectives run device-world (8 on the virtual mesh),
        # all parts identical in this single-controller run
        assert len(out) == len(jax.devices())
        for part in out:
            np.testing.assert_allclose(np.asarray(part.numpy()),
                                       [0, 1, 2, 3])
        # host-object scatter runs process-world (1 process here)
        world = dist.get_world_size()
        objs = []
        dist.scatter_object_list(objs, [{"i": i} for i in range(world)],
                                 src=0)
        assert objs == [{"i": dist.get_rank()}]
        with pytest.raises(ValueError):
            dist.scatter_object_list([], list(range(world + 1)), src=0)

    def test_entry_admission_on_sparse_table(self):
        from paddle_tpu.distributed import CountFilterEntry
        from paddle_tpu.distributed.ps import SparseTable
        t = SparseTable(dim=2, accessor="sgd", lr=1.0,
                        initializer="constant", init_range=0.5,
                        entry=CountFilterEntry(3))
        # first two accesses: unadmitted → zeros, no storage
        np.testing.assert_allclose(t.pull([7]), 0.0)
        t.push([7], np.ones((1, 2), np.float32))  # dropped
        assert t.size == 0
        # third access admits with a fresh init row
        np.testing.assert_allclose(t.pull([7]), 0.5)
        assert t.size == 1
        t.push([7], np.ones((1, 2), np.float32))
        np.testing.assert_allclose(t.pull([7]), -0.5)  # now training

    def test_probability_and_showclick_entries(self):
        from paddle_tpu.distributed import (ProbabilityEntry,
                                            ShowClickEntry)
        with pytest.raises(ValueError):
            ProbabilityEntry(1.5)
        assert ShowClickEntry("show", "click").admits(0)

    def test_inmemory_and_queue_dataset(self, tmp_path):
        f1 = tmp_path / "a.txt"
        f1.write_text("1 2\n3 4\n5 6\n")
        ds = paddle.distributed.InMemoryDataset()
        ds.init(batch_size=2)
        ds.set_filelist([str(f1)])
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 3
        batches = list(ds)
        assert batches[0] == [["1", "2"], ["3", "4"]]
        ds.local_shuffle(seed=1)
        ds.release_memory()
        assert ds.get_memory_data_size() == 0
        q = paddle.distributed.QueueDataset()
        q.init(batch_size=2)
        q.set_filelist([str(f1)])
        with pytest.raises(RuntimeError):
            q.load_into_memory()
        assert sum(len(b) for b in q) == 3

    def test_to_static_dist_model(self):
        lin = paddle.nn.Linear(4, 2)
        loss_fn = paddle.nn.loss.CrossEntropyLoss() if hasattr(
            paddle.nn, "loss") else None
        opt = paddle.optimizer.SGD(parameters=lin.parameters(),
                                   learning_rate=0.1)
        strategy = paddle.distributed.Strategy()
        dm = paddle.distributed.to_static(lin, None, optimizer=opt,
                                          strategy=strategy)
        dm.eval()
        x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        out = dm(x)
        assert list(out.shape) == [2, 2]
        # unshard returns a host-replicated tensor
        full = paddle.distributed.unshard_dtensor(out)
        assert list(full.shape) == [2, 2]

    def test_distributed_io(self, tmp_path):
        with static.program_guard(static.Program()):
            x = static.data("x", [1, 2])
            w = static.create_parameter([2, 2], "float32", name="wio")
            y = paddle.matmul(x, w)
            prog = static.default_main_program()
            wv = np.asarray(w.numpy()).copy()
            paddle.distributed.io.save_persistables(
                None, str(tmp_path), prog)
            assert paddle.distributed.io.is_persistable(w)
            w._swap_payload(w._data * 0)
            paddle.distributed.io.load_persistables(
                None, str(tmp_path), prog)
            np.testing.assert_allclose(np.asarray(w.numpy()), wv)
