"""ZeRO sharding stages 1/2/3 + Fleet facade on the 8-device virtual mesh.

Reference test strategy: test/collective/fleet/dygraph_group_sharded_stage2.py
/ stage3.py compare sharded training against plain DP numerics; here the
virtual CPU mesh plays the cluster (SURVEY.md §4).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet import (DistributedStrategy,
                                          DygraphShardingOptimizer, fleet)
from paddle_tpu.distributed.sharding import (group_sharded_parallel,
                                             save_group_sharded_model)
from paddle_tpu.optimizer import Adam

HID = 16


def _model_and_data(seed=7):
    np.random.seed(seed)
    paddle.seed(seed)
    m = nn.Sequential(
        nn.Linear(HID, 4 * HID), nn.ReLU(), nn.Linear(4 * HID, HID))
    xs = [np.random.randn(8, HID).astype(np.float32) for _ in range(3)]
    ys = [np.random.randn(8, HID).astype(np.float32) for _ in range(3)]
    return m, xs, ys


def _train(model, opt, xs, ys, wrapper=None):
    net = wrapper if wrapper is not None else model
    losses = []
    for x, y in zip(xs, ys):
        out = net(paddle.to_tensor(x))
        loss = ((out - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses, [np.asarray(p.numpy()) for p in model.parameters()]


def _baseline():
    mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 8}))
    model, xs, ys = _model_and_data()
    opt = Adam(learning_rate=0.01, parameters=model.parameters())
    return _train(model, opt, xs, ys)


@pytest.fixture()
def sharding_mesh():
    old = mesh_mod.get_mesh()
    mesh_mod.set_mesh(mesh_mod.build_mesh({"dp": 2, "sharding": 4}))
    yield mesh_mod.get_mesh()
    mesh_mod.set_mesh(old)


def _spec_axes(arr):
    sh = arr.sharding
    if not isinstance(sh, NamedSharding):
        return set()
    out = set()
    for e in sh.spec:
        if e is None:
            continue
        out.update(e if isinstance(e, tuple) else (e,))
    return out


class TestStage1:
    def test_states_sharded_and_numerics_match(self, sharding_mesh):
        base_losses, base_params = _baseline()

        mesh_mod.set_mesh(sharding_mesh)
        model, xs, ys = _model_and_data()
        opt = DygraphShardingOptimizer(
            Adam(learning_rate=0.01, parameters=model.parameters()))
        losses, params = _train(model, opt, xs, ys)

        np.testing.assert_allclose(losses, base_losses, rtol=2e-4, atol=2e-5)
        for a, b in zip(params, base_params):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)

        # moments of the big weight must actually live on the sharding axis
        w = model[0].weight
        st = opt._accumulators[id(w)]
        assert "sharding" in _spec_axes(st["moment1"])
        assert "sharding" in _spec_axes(st["moment2"])
        # rank-ownership map exists and covers all params (reference :116)
        owned = [p for ps in opt._rank2params.values() for p in ps]
        assert len(owned) == len(list(model.parameters()))


class TestStage2:
    def test_grads_and_states_sharded(self, sharding_mesh):
        base_losses, base_params = _baseline()

        mesh_mod.set_mesh(sharding_mesh)
        model, xs, ys = _model_and_data()
        inner = Adam(learning_rate=0.01, parameters=model.parameters())
        wrapped, opt, _ = group_sharded_parallel(model, inner, "os_g")

        losses = []
        for x, y in zip(xs, ys):
            out = wrapped(paddle.to_tensor(x))
            loss = ((out - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            # grads stored reduce-scattered over the sharding axis
            w = model[0].weight
            assert "sharding" in _spec_axes(w.grad._data)
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        np.testing.assert_allclose(losses, base_losses, rtol=2e-4, atol=2e-5)
        for p, b in zip(model.parameters(), base_params):
            np.testing.assert_allclose(np.asarray(p.numpy()), b,
                                       rtol=2e-4, atol=2e-5)

    def test_no_sync_defers_grad_sharding(self, sharding_mesh):
        mesh_mod.set_mesh(sharding_mesh)
        model, xs, ys = _model_and_data()
        inner = Adam(learning_rate=0.01, parameters=model.parameters())
        wrapped, opt, _ = group_sharded_parallel(model, inner, "os_g")

        w = model[0].weight
        with wrapped.no_sync():
            out = wrapped(paddle.to_tensor(xs[0]))
            ((out - paddle.to_tensor(ys[0])) ** 2).mean().backward()
            # inside no_sync the stored grad is NOT reduce-scattered
            assert "sharding" not in _spec_axes(w.grad._data)
        # tags restored: the next synchronized backward shards again
        out = wrapped(paddle.to_tensor(xs[1]))
        ((out - paddle.to_tensor(ys[1])) ** 2).mean().backward()
        assert "sharding" in _spec_axes(w.grad._data)
        wrapped.sync_buffers()  # surface exists and is a safe no-op here


class TestStage3:
    def test_params_sharded_and_numerics_match(self, sharding_mesh):
        base_losses, base_params = _baseline()

        mesh_mod.set_mesh(sharding_mesh)
        model, xs, ys = _model_and_data()
        inner = Adam(learning_rate=0.01, parameters=model.parameters())
        wrapped, opt, _ = group_sharded_parallel(model, inner, "p_g_os")

        # params demonstrably sharded (the ZeRO-3 memory saving)
        w = model[0].weight
        assert "sharding" in _spec_axes(w._data)

        losses = []
        for x, y in zip(xs, ys):
            out = wrapped(paddle.to_tensor(x))
            loss = ((out - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        np.testing.assert_allclose(losses, base_losses, rtol=2e-4, atol=2e-5)

        # optimizer states inherited the sharded placement
        st = opt._accumulators[id(w)]
        assert "sharding" in _spec_axes(st["moment1"])

        # gather-for-save restores replicated params matching baseline
        wrapped.get_all_parameters()
        for p, b in zip(model.parameters(), base_params):
            assert _spec_axes(p._data) == set()
            np.testing.assert_allclose(np.asarray(p.numpy()), b,
                                       rtol=2e-4, atol=2e-5)


class TestFleetFacade:
    def test_init_builds_hybrid_mesh(self):
        old = mesh_mod.get_mesh()
        try:
            strategy = DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                       "sharding_degree": 2}
            fleet.init(is_collective=True, strategy=strategy)
            hcg = fleet.get_hybrid_communicate_group()
            assert hcg.get_data_parallel_world_size() == 2
            assert hcg.get_model_parallel_world_size() == 2
            assert hcg.get_sharding_parallel_world_size() == 2
            assert hcg.get_pipe_parallel_world_size() == 1
            assert hcg.nranks == 8
            topo = hcg.topology()
            assert topo.world_size() == 8
            groups = topo.get_comm_list("mp")
            assert len(groups) == 4 and all(len(g) == 2 for g in groups)
        finally:
            mesh_mod.set_mesh(old)

    def test_distributed_model_and_optimizer_train(self):
        old = mesh_mod.get_mesh()
        try:
            base_losses, base_params = _baseline()

            strategy = DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": -1, "sharding_degree": 2}
            fleet.init(is_collective=True, strategy=strategy)

            model, xs, ys = _model_and_data()
            opt = Adam(learning_rate=0.01, parameters=model.parameters(),
                       grad_clip=nn.ClipGradByGlobalNorm(1e9))
            dm = fleet.distributed_model(model)
            dopt = fleet.distributed_optimizer(opt)
            losses, params = _train(model, dopt, xs, ys, wrapper=dm)
            np.testing.assert_allclose(losses, base_losses, rtol=2e-4,
                                       atol=2e-5)
            for a, b in zip(params, base_params):
                np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
            # sharding axis present -> hybrid optimizer wrapped ZeRO-1
            assert isinstance(dopt._inner_opt, DygraphShardingOptimizer)
        finally:
            mesh_mod.set_mesh(old)

    def test_collective_perf_smoke(self):
        res = fleet.collective_perf("allreduce", round_num=2,
                                    size_and_time={1024: None})
        assert 1024 in res and res[1024] > 0
