"""incubate.nn fused layers + utils.cpp_extension tests.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py,
python/paddle/utils/cpp_extension/.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import incubate, nn


def _x(b=2, s=6, d=16, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(b, s, d).astype(np.float32))


class TestFusedLayers:
    def test_fused_linear(self):
        fl = incubate.nn.FusedLinear(8, 4)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(3, 8).astype(np.float32))
        ref = x @ fl.weight + fl.bias
        np.testing.assert_allclose(fl(x).numpy(), ref.numpy(), atol=1e-6)

    def test_fused_dropout_add_eval(self):
        fda = incubate.nn.FusedDropoutAdd(p=0.5)
        fda.eval()
        x, y = _x(seed=1), _x(seed=2)
        np.testing.assert_allclose(fda(x, y).numpy(),
                                   (x + y).numpy(), atol=1e-6)

    def test_bias_dropout_residual_ln(self):
        layer = incubate.nn.FusedBiasDropoutResidualLayerNorm(
            16, dropout_rate=0.0)
        layer.eval()
        x, res = _x(seed=3), _x(seed=4)
        out = layer(x, res)
        # matches LN(res + x + bias)
        ref = layer.norm(res + x + layer.linear_bias)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-6)

    @pytest.mark.parametrize("pre", [False, True])
    def test_fused_mha_matches_manual(self, pre):
        paddle.seed(0)
        mha = incubate.nn.FusedMultiHeadAttention(
            16, 4, dropout_rate=0.0, attn_dropout_rate=0.0,
            normalize_before=pre)
        mha.eval()
        x = _x(seed=5)
        out = mha(x)
        # manual: same weights, explicit SDPA path
        import paddle_tpu.nn.functional as F
        from paddle_tpu import ops
        h = mha.norm(x) if pre else x
        b, s, d = h.shape
        qkv = ops.reshape(mha.qkv(h), [b, s, 3, 4, 4])
        q, k, v = ops.unbind(qkv, axis=2)
        att = F.scaled_dot_product_attention(q, k, v)
        ref = x + mha.out_proj(ops.reshape(att, [b, s, d]))
        if not pre:
            ref = mha.norm(ref)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-4)

    def test_fused_ffn_and_encoder_layer_train(self):
        layer = incubate.nn.FusedTransformerEncoderLayer(
            16, 4, 32, dropout_rate=0.0)
        x = _x(seed=6)
        out = layer(x)
        assert out.shape == [2, 6, 16]
        # trains: grads reach every parameter
        out.mean().backward()
        grads = [p.grad for p in layer.parameters()
                 if not p.stop_gradient]
        assert all(g is not None for g in grads)

    def test_need_weights_raises(self):
        with pytest.raises(NotImplementedError):
            incubate.nn.FusedMultiHeadAttention(16, 4, need_weights=True)


class TestCppExtension:
    def test_load_and_run(self, tmp_path):
        src = tmp_path / "ops.cc"
        src.write_text(
            '#include <cstdint>\n'
            'extern "C" void triple(const float* x, float* o, int64_t n)'
            '{ for (int64_t i = 0; i < n; ++i) o[i] = 3.0f * x[i]; }\n')
        ext = paddle.utils.cpp_extension.load(
            "t3", [str(src)], functions=["triple"],
            build_directory=str(tmp_path))
        x = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
        np.testing.assert_allclose(ext.triple(x).numpy(), [3.0, -6.0])

    def test_under_jit(self, tmp_path):
        src = tmp_path / "ops2.cc"
        src.write_text(
            '#include <cstdint>\n'
            'extern "C" void negate(const float* x, float* o, int64_t n)'
            '{ for (int64_t i = 0; i < n; ++i) o[i] = -x[i]; }\n')
        ext = paddle.utils.cpp_extension.load(
            "neg1", [str(src)], functions=["negate"],
            build_directory=str(tmp_path))

        @paddle.jit.to_static
        def f(a):
            return ext.negate(a + 1)

        out = f(paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [-2.0, -3.0])

    def test_missing_symbol_raises(self, tmp_path):
        src = tmp_path / "ops3.cc"
        src.write_text('extern "C" void here() {}\n')
        with pytest.raises(RuntimeError, match="does not export"):
            paddle.utils.cpp_extension.load(
                "m1", [str(src)], functions=["not_here"],
                build_directory=str(tmp_path))

    def test_build_error_raises(self, tmp_path):
        src = tmp_path / "bad.cc"
        src.write_text("this is not C++")
        with pytest.raises(RuntimeError, match="build failed"):
            paddle.utils.cpp_extension.load(
                "bad1", [str(src)], functions=["x"],
                build_directory=str(tmp_path))


class TestReviewFixes:
    def test_fused_linear_transpose_weight(self):
        fl = incubate.nn.FusedLinear(8, 4, transpose_weight=True)
        assert list(fl.weight.shape) == [4, 8]
        assert list(fl.bias.shape) == [4]
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(3, 8).astype(np.float32))
        out = fl(x)
        ref = x.numpy() @ fl.weight.numpy().T + fl.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, atol=1e-6)

    def test_static_data_np_dtype(self):
        static = paddle.static
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], np.float32)  # non-string dtype
            y = x * 2
        (out,) = static.Executor().run(
            main, feed={"x": np.array([1.0, 2.0], np.float32)},
            fetch_list=[y])
        np.testing.assert_allclose(out, [2.0, 4.0])

    def test_cpp_extension_reload_picks_up_edits(self, tmp_path):
        src = tmp_path / "evolve.cc"
        src.write_text(
            '#include <cstdint>\n'
            'extern "C" void f(const float* x, float* o, int64_t n)'
            '{ for (int64_t i = 0; i < n; ++i) o[i] = x[i] + 1.0f; }\n')
        ext1 = paddle.utils.cpp_extension.load(
            "evolve", [str(src)], functions=["f"],
            build_directory=str(tmp_path))
        x = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(ext1.f(x).numpy(), [2.0])
        src.write_text(
            '#include <cstdint>\n'
            'extern "C" void f(const float* x, float* o, int64_t n)'
            '{ for (int64_t i = 0; i < n; ++i) o[i] = x[i] + 10.0f; }\n')
        ext2 = paddle.utils.cpp_extension.load(
            "evolve", [str(src)], functions=["f"],
            build_directory=str(tmp_path))
        np.testing.assert_allclose(ext2.f(x).numpy(), [11.0])

    def test_encoder_cache_raises(self):
        layer = incubate.nn.FusedTransformerEncoderLayer(16, 4, 32)
        with pytest.raises(NotImplementedError, match="cache"):
            layer(_x(), cache={})
