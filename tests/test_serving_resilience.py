"""Serving-tier resilience drills.

Contract under test (ISSUE 6 / README "Serving resilience"): no injected
fault — tick stall, admission OOM race, crash-at-tick — and no overload
condition — deadline expiry, queue shedding, drain — raises out of
``PagedEngine.step()`` or leaks a KV block; every submitted request ends
in exactly one terminal status (FINISHED / SHED / DEADLINE_MISSED /
CANCELLED / FAILED), and the replica lifecycle + watchdog wiring turn a
stalled or crashed tick into a DEGRADED (not dead) replica.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.watchdog import Watchdog
from paddle_tpu.fault import inject
from paddle_tpu.inference import (Overloaded, PagedEngine, ReplicaState,
                                  RequestStatus, ResilienceConfig)
from paddle_tpu.inference.resilience import TERMINAL_STATUSES
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import REGISTRY


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = LlamaConfig(vocab_size=97, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, max_seq_len=256,
                      use_flash_attention=False)
    return LlamaForCausalLM(cfg)


@pytest.fixture(autouse=True)
def _clean_faults():
    inject.disarm_all()
    yield
    inject.disarm_all()
    paddle.set_flags({"FLAGS_enable_metrics": False})


def make_engine(model, *, max_batch=2, block_size=4, num_blocks=32,
                max_blocks_per_seq=16, **res_kw):
    res = ResilienceConfig(**res_kw) if res_kw else None
    return PagedEngine(model, max_batch=max_batch, block_size=block_size,
                       num_blocks=num_blocks,
                       max_blocks_per_seq=max_blocks_per_seq,
                       resilience=res)


def prompt(seed, n=5):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(1, 97, size=n)]


def assert_quiesced(eng, rids):
    """The drill invariant: every submitted request terminal, no KV block
    leaked, no slot occupied, queue empty."""
    for rid in rids:
        oc = eng.outcomes.get(rid)
        assert oc is not None, f"request {rid} has no terminal outcome"
        assert oc.status in TERMINAL_STATUSES, (rid, oc.status)
    assert not eng.queue
    assert all(s is None for s in eng.slots)
    assert eng.bm.available == eng._total_usable, "leaked KV blocks"


class FakeClock:
    """Deterministic deadline clock (engine + lifecycle seam)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def install(self, eng):
        eng._clock = self
        eng.lifecycle._clock = self
        return self


# ---------------------------------------------------------------- drills
class TestAdmissionRace:
    def test_admission_oom_requeues_instead_of_raising(self, model):
        eng = make_engine(model)
        r1 = eng.add_request(prompt(0), max_new_tokens=4)
        r2 = eng.add_request(prompt(1), max_new_tokens=4)
        with inject.armed("serving.admission_oom"):
            out = eng.step()          # must absorb the race, not raise
        assert isinstance(out, dict)
        # the raced request went back to the queue head, not to FAILED
        assert eng.request_status(r1) in (RequestStatus.QUEUED,
                                          RequestStatus.RUNNING)
        out = eng.run_to_completion()
        assert set(out) == {r1, r2}
        assert len(out[r1]) == 4 and len(out[r2]) == 4
        assert_quiesced(eng, [r1, r2])
        assert eng.lifecycle.state == ReplicaState.READY

    def test_real_head_of_line_memory_stall_still_completes(self, model):
        # tight pool: head-of-line waits for blocks, nobody raises
        eng = make_engine(model, max_batch=2, num_blocks=5,
                          max_blocks_per_seq=4)
        rids = [eng.add_request(prompt(i, 4), max_new_tokens=6)
                for i in range(3)]
        out = eng.run_to_completion(max_ticks=300)
        assert all(len(out[r]) == 6 for r in rids)
        assert_quiesced(eng, rids)


class TestCrashAtTick:
    def test_crash_fails_in_flight_degrades_and_keeps_serving(self, model):
        eng = make_engine(model)
        r1 = eng.add_request(prompt(2), max_new_tokens=8)
        r2 = eng.add_request(prompt(3), max_new_tokens=8)
        eng.step()                                    # tick 1: admitted
        with inject.armed("serving.crash_at_tick", tick=2):
            out = eng.step()                          # tick 2: crashes
        assert out == {}                              # nothing raised
        assert eng.outcomes[r1].status == RequestStatus.FAILED
        assert eng.outcomes[r2].status == RequestStatus.FAILED
        assert "crash" in eng.outcomes[r1].detail
        assert eng.lifecycle.state == ReplicaState.DEGRADED
        assert eng.tick_failures == 1
        assert_quiesced(eng, [r1, r2])
        # a DEGRADED replica still serves (readiness is the router's cue)
        assert eng.health()["ready"] is False
        r3 = eng.add_request(prompt(4), max_new_tokens=3)
        out = eng.run_to_completion()
        assert len(out[r3]) == 3
        eng.recover()
        assert eng.lifecycle.state == ReplicaState.READY
        assert eng.health()["ready"] is True


class TestCrashAtFirstTick:
    def test_crash_before_first_success_still_degrades(self, model):
        """degrade() must work from STARTING: a replica crash-looping on
        its very first tick cannot stay probed as STARTING forever."""
        eng = make_engine(model)
        rid = eng.add_request(prompt(70), max_new_tokens=2)
        with inject.armed("serving.crash_at_tick", tick=1):
            out = eng.step()
        assert out == {}
        assert eng.lifecycle.state == ReplicaState.DEGRADED
        # the crash hit before admission: the request is still safely
        # queued and a degraded replica keeps serving it
        assert eng.request_status(rid) == RequestStatus.QUEUED
        out = eng.run_to_completion()
        assert len(out[rid]) == 2
        assert_quiesced(eng, [rid])

    def test_kv_caches_reallocated_after_crash(self, model):
        """The decode call donates kc/vc — after an absorbed tick crash
        the engine must run on FRESH cache pages, never the possibly-
        invalidated donated buffers."""
        eng = make_engine(model)
        r1 = eng.add_request(prompt(71), max_new_tokens=4)
        eng.step()
        assert any(bool(a.any()) for a in eng.kc)   # prefill wrote pages
        with inject.armed("serving.crash_at_tick"):
            eng.step()
        # fresh zero pages, correct geometry
        assert all(not bool(a.any()) for a in eng.kc + eng.vc)
        assert all(a.shape == eng._kv_shape for a in eng.kc)
        # and the fresh pages actually serve traffic
        r2 = eng.add_request(prompt(72), max_new_tokens=3)
        assert len(eng.run_to_completion()[r2]) == 3
        assert_quiesced(eng, [r1, r2])


class TestDeadlines:
    def test_ttft_deadline_expires_in_queue(self, model):
        eng = make_engine(model, max_batch=1)
        clock = FakeClock().install(eng)
        busy = eng.add_request(prompt(5), max_new_tokens=6)
        eng.step()                     # busy owns the only slot
        late = eng.add_request(prompt(6), max_new_tokens=6,
                               ttft_deadline_s=5.0)
        clock.t = 6.0                  # past the TTFT deadline, no token
        eng.step()
        oc = eng.outcomes[late]
        assert oc.status == RequestStatus.DEADLINE_MISSED
        assert "TTFT" in oc.detail
        assert oc.tokens == []
        out = eng.run_to_completion()
        assert len(out[busy]) == 6
        assert_quiesced(eng, [busy, late])

    def test_total_deadline_cancels_mid_flight_and_reclaims_blocks(
            self, model):
        eng = make_engine(model, max_batch=1)
        clock = FakeClock().install(eng)
        rid = eng.add_request(prompt(7), max_new_tokens=50,
                              deadline_s=10.0)
        eng.step()
        eng.step()
        assert eng.request_status(rid) == RequestStatus.RUNNING
        blocks_held = eng._total_usable - eng.bm.available
        assert blocks_held > 0
        clock.t = 11.0                 # expire mid-flight
        eng.step()
        oc = eng.outcomes[rid]
        assert oc.status == RequestStatus.DEADLINE_MISSED
        assert "total deadline" in oc.detail
        assert 0 < len(oc.tokens) < 50          # partial output recorded
        assert_quiesced(eng, [rid])

    def test_default_deadlines_from_config(self, model):
        eng = make_engine(model, max_batch=1, default_deadline_s=10.0)
        clock = FakeClock().install(eng)
        rid = eng.add_request(prompt(8), max_new_tokens=50)
        eng.step()
        clock.t = 11.0
        eng.step()
        assert eng.outcomes[rid].status == RequestStatus.DEADLINE_MISSED


class TestOverload:
    def test_bounded_queue_raises_overloaded(self, model):
        eng = make_engine(model, max_batch=1, max_queue=2)
        eng.add_request(prompt(9), max_new_tokens=4)
        eng.add_request(prompt(10), max_new_tokens=4)
        with pytest.raises(Overloaded, match="queue full"):
            eng.add_request(prompt(11), max_new_tokens=4)
        out = eng.run_to_completion()
        assert len(out) == 2

    def test_shed_past_high_water(self, model):
        eng = make_engine(model, max_batch=1, max_queue=16,
                          queue_high_water=2)
        first = eng.add_request(prompt(12), max_new_tokens=4)
        eng.step()                     # first request takes the slot
        queued = [eng.add_request(prompt(13 + i), max_new_tokens=4)
                  for i in range(4)]
        eng.step()                     # shed sweep: newest past mark go
        shed = [r for r in queued
                if eng.request_status(r) == RequestStatus.SHED]
        assert len(shed) == 2
        assert shed == queued[2:]      # newest shed, oldest kept
        for r in shed:
            assert "high-water" in eng.outcomes[r].detail
        out = eng.run_to_completion()
        assert set(out) == {first, *queued[:2]}
        assert_quiesced(eng, [first, *queued])


    def test_shed_spares_preempted_partial_work(self, model):
        """A recompute-preempted request (carrying generated tokens)
        sitting newest in the queue is spared by the shed sweep — its
        prefill/decode compute is already paid for."""
        eng = make_engine(model, max_batch=1, max_queue=16,
                          queue_high_water=1)
        first = eng.add_request(prompt(50), max_new_tokens=4)
        eng.step()
        a = eng.add_request(prompt(51), max_new_tokens=4)
        b = eng.add_request(prompt(52), max_new_tokens=4)   # newest
        eng.queue[-1].generated.append(7)   # simulate preempted progress
        eng.step()
        assert eng.request_status(a) == RequestStatus.SHED
        assert eng.request_status(b) != RequestStatus.SHED
        out = eng.run_to_completion()
        assert first in out and b in out
        assert_quiesced(eng, [first, a, b])

    def test_burst_at_idle_replica_fills_slots_before_shedding(
            self, model):
        """Admission runs before the shed sweep: free decode slots
        absorb a burst; only the unabsorbable excess is shed."""
        eng = make_engine(model, max_batch=4, max_queue=16,
                          queue_high_water=1)
        rids = [eng.add_request(prompt(55 + i), max_new_tokens=2)
                for i in range(5)]
        out = eng.step()  # 4 into slots, 1 queued == high water: no shed
        statuses = [eng.request_status(r) for r in rids]
        assert RequestStatus.SHED not in statuses
        out.update(eng.run_to_completion())
        assert set(out) == set(rids)
        assert_quiesced(eng, rids)

    def test_drain_outcomes_drops_rejected_mirror(self, model):
        eng = make_engine(model, max_batch=1, num_blocks=4,
                          max_blocks_per_seq=2)
        bad = eng.add_request(list(range(1, 30)), max_new_tokens=4)
        assert bad in eng.rejected
        out = eng.drain_outcomes()
        assert out[bad].status == RequestStatus.FAILED
        assert bad not in eng.rejected      # retention contract


class TestLifecycle:
    def test_warmup_walks_starting_warming_ready(self, model):
        eng = make_engine(model)
        assert eng.lifecycle.state == ReplicaState.STARTING
        assert eng.health()["ready"] is False
        eng.warmup()
        assert eng.lifecycle.state == ReplicaState.READY
        assert eng.health()["ready"] is True
        # warmup traffic left no residue
        assert not eng.outcomes and not eng._done
        assert eng.bm.available == eng._total_usable
        states = [s for _, s, _ in eng.lifecycle.history]
        assert states == [ReplicaState.WARMING, ReplicaState.READY]

    def test_warmup_with_pre_ready_traffic(self, model):
        """Requests may queue from STARTING (they wait for exactly the
        warmup compiles); warmup() serves them alongside its synthetic
        request and their results surface on the next engine call."""
        eng = make_engine(model)
        early = eng.add_request(prompt(73), max_new_tokens=3)
        eng.warmup()
        assert eng.lifecycle.state == ReplicaState.READY
        out = eng.run_to_completion()
        assert len(out[early]) == 3
        assert eng.outcomes[early].status == RequestStatus.FINISHED
        assert_quiesced(eng, [early])

    def test_warmup_ignores_default_deadlines(self, model):
        """The synthetic warmup request must not inherit the config's
        SLO deadlines — expiring it mid-compile would flip READY with
        the decode program never built."""
        eng = make_engine(model, max_batch=1,
                          default_ttft_deadline_s=1e-9,
                          default_deadline_s=1e-9)
        eng.warmup()
        assert eng.lifecycle.state == ReplicaState.READY
        assert not eng.outcomes

    def test_first_step_flips_starting_to_ready(self, model):
        eng = make_engine(model)
        eng.add_request(prompt(20), max_new_tokens=2)
        eng.step()
        assert eng.lifecycle.state == ReplicaState.READY

    def test_drain_finishes_in_flight_cancels_queued_stops(self, model):
        eng = make_engine(model, max_batch=1)
        running = eng.add_request(prompt(21), max_new_tokens=6)
        eng.step()
        queued = [eng.add_request(prompt(22 + i), max_new_tokens=6)
                  for i in range(2)]
        out = eng.drain()
        assert len(out[running]) == 6            # in-flight completed
        for r in queued:
            oc = eng.outcomes[r]
            assert oc.status == RequestStatus.CANCELLED
            assert "drained" in oc.detail
        assert eng.lifecycle.state == ReplicaState.STOPPED
        assert eng.health()["live"] is False
        with pytest.raises(Overloaded, match="STOPPED"):
            eng.add_request(prompt(30))
        assert_quiesced(eng, [running, *queued])
        assert eng.drain() == {}                 # idempotent

    def test_drain_under_memory_pressure_terminates_everyone(self, model):
        """Livelock preemption mid-drain bounces a request back through
        the queue — drain must still carry it to a terminal status, not
        strand it QUEUED in a STOPPED replica."""
        eng = make_engine(model, max_batch=2, num_blocks=5,
                          max_blocks_per_seq=4)
        r1 = eng.add_request(prompt(60, 4), max_new_tokens=6)
        r2 = eng.add_request(prompt(61, 4), max_new_tokens=6)
        eng.step()                     # both decoding, pool nearly full
        out = eng.drain(max_ticks=300)
        for r in (r1, r2):
            st = eng.outcomes[r].status
            assert st in TERMINAL_STATUSES, (r, st)
        # the preempted request finished its decode during the drain
        assert sorted(out) == [r1, r2]
        assert eng.lifecycle.state == ReplicaState.STOPPED
        assert_quiesced(eng, [r1, r2])

    def test_cancel_queued_and_running(self, model):
        eng = make_engine(model, max_batch=1)
        running = eng.add_request(prompt(31), max_new_tokens=20)
        eng.step()
        queued = eng.add_request(prompt(32), max_new_tokens=4)
        assert eng.cancel(queued)
        assert eng.outcomes[queued].status == RequestStatus.CANCELLED
        assert eng.cancel(running)
        assert eng.outcomes[running].status == RequestStatus.CANCELLED
        assert eng.bm.available == eng._total_usable   # blocks reclaimed
        assert not eng.cancel(999)
        assert_quiesced(eng, [running, queued])

    def test_invalid_transition_rejected(self, model):
        eng = make_engine(model)
        eng.drain()
        with pytest.raises(RuntimeError, match="invalid replica"):
            eng.lifecycle.to(ReplicaState.READY)


class TestDeadlineAwareEviction:
    def test_preemption_picks_most_slack_victim(self, model):
        """Livelock preemption: the victim is the request with the most
        deadline slack (no deadline beats any deadline) — NOT simply the
        youngest rid."""
        eng = make_engine(model, max_batch=2, num_blocks=5,
                          max_blocks_per_seq=4)
        # r1 (older) has NO deadline; r2 (younger) has a deadline. The
        # old youngest-rid policy would evict r2 and risk its deadline;
        # deadline-aware ordering must evict r1.
        r1 = eng.add_request(prompt(33, 4), max_new_tokens=6)
        r2 = eng.add_request(prompt(34, 4), max_new_tokens=6,
                             deadline_s=3600.0)
        evicted = None
        for _ in range(50):
            eng.step()
            if eng.queue:                       # someone got preempted
                evicted = eng.queue[0].rid
                break
        assert evicted == r1
        out = eng.run_to_completion(max_ticks=300)
        assert len(out[r1]) == 6 and len(out[r2]) == 6
        assert_quiesced(eng, [r1, r2])


class TestWatchdogWiring:
    def test_heartbeat_quiet_then_stall_degrades(self, model):
        """Satellite regression: normal serving ticks keep the watchdog
        quiet; a stalled tick fires on_hang and flips DEGRADED."""
        eng = make_engine(model)
        # compile the steady-state programs FIRST: a cold first tick is
        # seconds of XLA compile, which a 0.15s watchdog rightly calls a
        # stall (production replicas warm before taking traffic)
        eng.warmup(prompt_len=5, max_new_tokens=6)
        hangs = []
        wd = Watchdog(timeout=0.15, poll_interval=0.03,
                      on_hang=lambda w: hangs.append(w.timeout)).start()
        try:
            eng.attach_watchdog(wd)
            rid = eng.add_request(prompt(35), max_new_tokens=6)
            out = eng.run_to_completion()
            assert len(out[rid]) == 6
            time.sleep(0.25)           # idle engine: no work in flight
            assert wd.hang_count == 0 and not hangs
            assert eng.lifecycle.state == ReplicaState.READY

            r2 = eng.add_request(prompt(36), max_new_tokens=2)
            with inject.armed("serving.tick_stall", seconds=0.5):
                out = eng.step()       # stalls inside the tick, no raise
            assert wd.hang_count >= 1
            assert hangs               # user callback still chained
            assert eng.lifecycle.state == ReplicaState.DEGRADED
            # the stalled request was not lost — it completes
            out.update(eng.run_to_completion())
            assert len(out[r2]) == 2
            assert_quiesced(eng, [r2])
        finally:
            wd.stop()

    def test_end_work_underflow_guard(self):
        wd = Watchdog(timeout=60.0)
        wd.end_work()                  # unbalanced: must not underflow
        assert wd._in_flight == 0
        assert wd.unbalanced_end_count == 1
        wd.begin_work()
        assert wd._in_flight == 1
        wd.end_work()
        assert wd._in_flight == 0
        assert wd.unbalanced_end_count == 1


class TestMetricsAndLoadgen:
    def test_serving_metrics_recorded(self, model):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        REGISTRY.reset()
        eng = make_engine(model, max_batch=1, max_queue=16,
                          queue_high_water=1)
        rids = [eng.add_request(prompt(40 + i), max_new_tokens=3)
                for i in range(4)]
        eng.run_to_completion()
        assert REGISTRY.get("paddle_tpu_serving_admitted").total() >= 1
        assert REGISTRY.get("paddle_tpu_serving_shed").total() >= 1
        assert REGISTRY.get("paddle_tpu_serving_ttft_seconds"
                            ).total_count() >= 1
        assert REGISTRY.get("paddle_tpu_serving_itl_seconds"
                            ).total_count() >= 1
        assert REGISTRY.get("paddle_tpu_serving_tick_seconds"
                            ).total_count() >= 1
        assert REGISTRY.get("paddle_tpu_serving_kv_blocks_in_use"
                            ).value() == 0.0
        by_outcome = REGISTRY.get("paddle_tpu_serving_requests")
        assert by_outcome.value(outcome="FINISHED") >= 1
        assert by_outcome.value(outcome="SHED") >= 1
        state = REGISTRY.get("paddle_tpu_serving_replica_state")
        assert state.value() == ReplicaState.ORDER.index(
            ReplicaState.READY)
        assert_quiesced(eng, rids)

    def test_loadgen_open_loop_report(self, model):
        from tools.loadgen import poisson_arrivals, run_load
        arr = poisson_arrivals(100.0, 20, seed=3)
        assert len(arr) == 20 and np.all(np.diff(arr) > 0)
        assert np.allclose(arr, poisson_arrivals(100.0, 20, seed=3))

        eng = make_engine(model, max_batch=2, num_blocks=64,
                          max_queue=64, queue_high_water=32)
        eng.warmup()
        report = run_load(eng, offered_rps=200.0, n_requests=10,
                          prompt_len_range=(3, 8), max_new_tokens=4,
                          seed=5)
        assert report["submitted"] + report["overloaded"] == 10
        assert report["finished"] >= 1
        assert report["goodput_tokens_per_sec"] > 0
        assert report["p50_ttft_s"] > 0 and report["p99_ttft_s"] > 0
        assert report["p50_itl_s"] > 0
        total = sum(report["outcomes"].values())
        assert total == report["submitted"]
        # run_load drained the outcomes; engine is clean
        assert not eng.outcomes
        assert eng.bm.available == eng._total_usable
        eng.drain()
        assert eng.lifecycle.state == ReplicaState.STOPPED

    def test_loadgen_with_deadlines_accounts_every_request(self, model):
        from tools.loadgen import run_load
        eng = make_engine(model, max_batch=1, max_queue=4,
                          queue_high_water=2)
        report = run_load(eng, offered_rps=500.0, n_requests=12,
                          prompt_len_range=(3, 6), max_new_tokens=6,
                          ttft_deadline_s=0.05, deadline_s=0.2, seed=9)
        # under 500 rps on one slot something must give — but every
        # submitted request is accounted for in a terminal outcome
        assert sum(report["outcomes"].values()) == report["submitted"]
        assert (report["shed"] + report["deadline_missed"]
                + report["overloaded"] + report["finished"]
                + report["failed"]) >= 12 - report["submitted"] \
            + report["submitted"]
        assert eng.bm.available == eng._total_usable
