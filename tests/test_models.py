"""Model zoo tests (BASELINE ladder configs)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM, LeNet


def tiny_gpt(**kw):
    kw.setdefault("vocab_size", 128)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("use_flash_attention", False)
    return GPTConfig(**kw)


def test_gpt_forward_loss_and_grad():
    m = GPTForCausalLM(tiny_gpt())
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int64))
    logits, loss = m(ids, labels=ids)
    assert logits.shape == [2, 16, 128]
    # initial loss ~ ln(vocab)
    assert 3.0 < float(loss) < 7.0
    loss.backward()
    assert m.gpt.wte.weight.grad is not None
    assert m.gpt.blocks[0].mlp.fc1.weight.grad is not None


def test_gpt_trains():
    import paddle_tpu.optimizer as opt

    m = GPTForCausalLM(tiny_gpt())
    optim = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 128, (2, 16)).astype(np.int64))
    losses = []
    for _ in range(8):
        _, loss = m(ids, labels=ids)
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, losses


def test_lenet_shapes():
    m = LeNet()
    x = paddle.to_tensor(np.zeros((3, 1, 28, 28), np.float32))
    y = m(x)
    assert y.shape == [3, 10]
