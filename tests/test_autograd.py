"""Autograd engine tests (reference: eager/backward.cc semantics,
test/legacy_test/test_imperative_* family)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_branching_graph():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    a = x * 2
    b = x * 3
    (a + b).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])


def test_chain_and_shared_subgraph():
    x = paddle.to_tensor([0.5], stop_gradient=False)
    h = paddle.tanh(x)
    y = h * h
    y.backward()
    th = np.tanh(0.5)
    np.testing.assert_allclose(x.grad.numpy(), [2 * th * (1 - th**2)], rtol=1e-5)


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    w = paddle.to_tensor([2.0])  # stop_gradient=True
    y = (x * w).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert w.grad is None


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_backward_twice_raises():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_retain_graph():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0])


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (gx,) = paddle.grad([y], [x])
    np.testing.assert_allclose(gx.numpy(), [12.0])
    assert x.grad is None  # grad() must not pollute .grad


def test_double_backward():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (gx,) = paddle.grad([y], [x], create_graph=True)
    (ggx,) = paddle.grad([gx], [x])
    np.testing.assert_allclose(ggx.numpy(), [12.0])  # d2/dx2 x^3 = 6x


def test_tensor_hook():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []
    h = x * 2
    h.register_hook(lambda g: seen.append(g.numpy().copy()))
    h.sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [1.0])


def test_hook_replaces_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    y.register_hook(lambda g: g * 10)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [20.0])


def test_retain_grads_non_leaf():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    y.retain_grads()
    (y * 3).sum().backward()
    np.testing.assert_allclose(y.grad.numpy(), [3.0])


def test_backward_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    y.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 20.0])


def test_pylayer_custom():
    class Cube(paddle.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor()
            return grad * 3 * x * x

    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = Cube.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0])
