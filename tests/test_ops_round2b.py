"""Tests for the op-breadth batch: pooling variants, math tail, sampling,
geometric (graph) ops, sequence/text losses, quantized linears, metrics.

Reference behaviors: python/paddle/nn/functional/{pooling,loss}.py,
python/paddle/geometric/, python/paddle/tensor/{math,search}.py; torch CPU
used as an independent oracle where it implements the same op.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


class TestPoolingVariants:
    def test_max_unpool2d_roundtrip(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).rand(2, 3, 8, 8).astype(np.float32))
        out, mask = F.max_pool2d(x, 2, return_mask=True)
        rec = F.max_unpool2d(out, mask, 2)
        assert rec.shape == [2, 3, 8, 8]
        # every pooled value lands back at its argmax position
        assert float(np.abs(rec.numpy().sum() - out.numpy().sum())) < 1e-5
        nz = rec.numpy() != 0
        assert nz.sum() == out.numpy().size

    def test_max_unpool2d_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.RandomState(1).rand(1, 2, 6, 6).astype(np.float32)
        out, mask = F.max_pool2d(paddle.to_tensor(x), 2, return_mask=True)
        ours = F.max_unpool2d(out, mask, 2).numpy()
        to, tm = torch.nn.functional.max_pool2d(
            torch.tensor(x), 2, return_indices=True)
        ref = torch.nn.functional.max_unpool2d(to, tm, 2).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-6)

    def test_max_unpool1d_3d(self):
        x1 = paddle.to_tensor(
            np.random.RandomState(2).rand(2, 2, 8).astype(np.float32))
        o1, m1 = F.max_pool1d(x1, 2, return_mask=True)
        assert F.max_unpool1d(o1, m1, 2).shape == [2, 2, 8]
        x3 = paddle.to_tensor(
            np.random.RandomState(3).rand(1, 2, 4, 4, 4).astype(np.float32))
        o3, m3 = F.max_pool3d(x3, 2, return_mask=True)
        assert F.max_unpool3d(o3, m3, 2).shape == [1, 2, 4, 4, 4]

    def test_fractional_max_pool2d_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.RandomState(4).rand(2, 3, 9, 9).astype(np.float32)
        u = 0.37
        ours = F.fractional_max_pool2d(paddle.to_tensor(x), output_size=4,
                                       kernel_size=2, random_u=u).numpy()
        samples = torch.full((2, 3, 2), u, dtype=torch.float64)
        ref = torch.nn.functional.fractional_max_pool2d(
            torch.tensor(x, dtype=torch.float64), 2, output_size=(4, 4),
            _random_samples=samples).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_fractional_max_pool3d_shape_and_mask(self):
        x = paddle.to_tensor(
            np.random.RandomState(5).rand(1, 2, 8, 8, 8).astype(np.float32))
        out, mask = F.fractional_max_pool3d(x, output_size=3, kernel_size=2,
                                            random_u=0.5, return_mask=True)
        assert out.shape == [1, 2, 3, 3, 3]
        assert mask.shape == [1, 2, 3, 3, 3]
        # mask holds flat spatial indices into 8*8*8
        m = mask.numpy()
        assert (m >= 0).all() and (m < 512).all()

    def test_lp_pool2d_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.abs(np.random.RandomState(6).rand(2, 2, 8, 8)
                   ).astype(np.float32)
        for p in (1.0, 2.0, 3.0):
            ours = F.lp_pool2d(paddle.to_tensor(x), p, 2).numpy()
            ref = torch.nn.functional.lp_pool2d(
                torch.tensor(x), p, 2).numpy()
            np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5,
                                       err_msg=f"p={p}")

    def test_lp_pool1d_grad(self):
        x = paddle.to_tensor(
            np.random.RandomState(7).rand(1, 2, 8).astype(np.float32) + 0.1)
        x.stop_gradient = False
        F.lp_pool1d(x, 2.0, 2).sum().backward()
        assert np.isfinite(x.grad.numpy()).all()


class TestMathTail:
    def test_gammainc_pair(self):
        from scipy import special
        a = np.array([0.5, 2.0, 5.0], np.float32)
        x = np.array([1.0, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(
            paddle.gammainc(paddle.to_tensor(a), paddle.to_tensor(x)).numpy(),
            special.gammainc(a, x), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.gammaincc(paddle.to_tensor(a),
                             paddle.to_tensor(x)).numpy(),
            special.gammaincc(a, x), rtol=1e-5)

    def test_lu_unpack_reconstructs(self):
        rng = np.random.RandomState(0)
        a = rng.randn(6, 6).astype(np.float32)
        lu_, piv = paddle.linalg.lu(paddle.to_tensor(a))
        P, L, U = paddle.linalg.lu_unpack(lu_, piv)
        rec = P.numpy() @ L.numpy() @ U.numpy()
        np.testing.assert_allclose(rec, a, atol=1e-5)
        # L unit-lower, U upper
        assert np.allclose(np.triu(L.numpy(), 1), 0)
        assert np.allclose(np.diag(L.numpy()), 1)
        assert np.allclose(np.tril(U.numpy(), -1), 0)

    def test_lu_unpack_rectangular(self):
        rng = np.random.RandomState(1)
        a = rng.randn(5, 3).astype(np.float32)
        lu_, piv = paddle.linalg.lu(paddle.to_tensor(a))
        P, L, U = paddle.linalg.lu_unpack(lu_, piv)
        rec = P.numpy() @ L.numpy() @ U.numpy()
        np.testing.assert_allclose(rec, a, atol=1e-5)
        assert L.shape == [5, 3] and U.shape == [3, 3]

    def test_fill_diagonal_tensor(self):
        x = paddle.zeros([3, 4])
        y = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        out = paddle.fill_diagonal_tensor(x, y)
        np.testing.assert_allclose(np.diag(out.numpy()), [1, 2, 3])
        out2 = paddle.fill_diagonal_tensor(
            paddle.zeros([3, 4]),
            paddle.to_tensor(np.array([5.0, 6.0, 7.0], np.float32)),
            offset=1)
        np.testing.assert_allclose(out2.numpy()[0, 1], 5.0)
        np.testing.assert_allclose(out2.numpy()[2, 3], 7.0)

    def test_reduce_as(self):
        x = paddle.to_tensor(np.ones((2, 3, 4), np.float32))
        t = paddle.zeros([1, 3, 1])
        out = paddle.reduce_as(x, t)
        assert out.shape == [1, 3, 1]
        np.testing.assert_allclose(out.numpy(), np.full((1, 3, 1), 8.0))
        t2 = paddle.zeros([4])
        out2 = paddle.reduce_as(x, t2)
        np.testing.assert_allclose(out2.numpy(), np.full((4,), 6.0))


class TestSampling:
    def test_top_p_sampling_stays_in_nucleus(self):
        probs = np.array([[0.5, 0.3, 0.1, 0.05, 0.05],
                          [0.05, 0.05, 0.1, 0.3, 0.5]], np.float32)
        ps = np.array([0.6, 0.6], np.float32)
        hits = set()
        for seed in range(20):
            _, ids = paddle.top_p_sampling(paddle.to_tensor(probs),
                                           paddle.to_tensor(ps), seed=seed)
            i = ids.numpy().ravel()
            hits.add((int(i[0]), int(i[1])))
            assert i[0] in (0, 1) and i[1] in (3, 4)
        assert len(hits) > 1  # actually random

    def test_gather_tree_matches_manual(self):
        # T=3, B=1, W=2 beam backtrace
        ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64)
        parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], np.int64)
        out = paddle.gather_tree(paddle.to_tensor(ids),
                                 paddle.to_tensor(parents)).numpy()
        # final beam 0 follows parent 1 at t=2: path ids [1, 4, 5]
        np.testing.assert_array_equal(out[:, 0, 0], [1, 4, 5])
        # final beam 1 follows parent 0: [1, 3, 6]
        np.testing.assert_array_equal(out[:, 0, 1], [1, 3, 6])

    def test_class_center_sample(self):
        label = paddle.to_tensor(np.array([2, 7, 2, 9], np.int64))
        remap, sampled = paddle.class_center_sample(label, 20, 6)
        s = sampled.numpy()
        assert set([2, 7, 9]).issubset(set(s.tolist()))
        assert len(s) == 6
        r = remap.numpy()
        # remapped labels index into sampled
        np.testing.assert_array_equal(s[r], [2, 7, 2, 9])

    def test_shuffle_batch_permutes(self):
        x = np.arange(12, dtype=np.float32).reshape(6, 2)
        out = paddle.shuffle_batch(paddle.to_tensor(x), seed=3).numpy()
        assert not np.array_equal(out, x)
        np.testing.assert_allclose(np.sort(out[:, 0]), x[:, 0])


class TestGeometric:
    def test_send_u_recv_reference_example(self):
        # reference docstring example (geometric/message_passing/send_recv.py)
        x = paddle.to_tensor(
            np.array([[0, 2, 3], [1, 4, 5], [2, 6, 7]], np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0]))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0]))
        out = paddle.geometric.send_u_recv(x, src, dst, "sum").numpy()
        np.testing.assert_allclose(out, [[0, 2, 3], [2, 8, 10], [1, 4, 5]])

    def test_send_u_recv_reduce_ops(self):
        x = paddle.to_tensor(
            np.array([[1.0], [2.0], [3.0]], np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2]))
        dst = paddle.to_tensor(np.array([0, 0, 0]))
        assert paddle.geometric.send_u_recv(x, src, dst, "mean").numpy()[0, 0] == 2.0
        assert paddle.geometric.send_u_recv(x, src, dst, "max").numpy()[0, 0] == 3.0
        assert paddle.geometric.send_u_recv(x, src, dst, "min").numpy()[0, 0] == 1.0

    def test_send_ue_recv_and_uv_grads(self):
        x = paddle.to_tensor(np.ones((3, 2), np.float32))
        x.stop_gradient = False
        y = paddle.to_tensor(np.full((4, 2), 2.0, np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0]))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0]))
        out = paddle.geometric.send_ue_recv(x, y, src, dst, "mul", "sum")
        out.sum().backward()
        # each edge contributes y=2 per feature; node0 appears as src twice
        np.testing.assert_allclose(x.grad.numpy()[0], [4.0, 4.0])
        x2 = paddle.to_tensor(
            np.arange(6, dtype=np.float32).reshape(3, 2))
        out2 = paddle.geometric.send_uv(x2, x2, src, dst, "add")
        assert out2.shape == [4, 2]

    def test_segment_ops(self):
        data = paddle.to_tensor(
            np.array([[1.0, 2], [3, 4], [5, 6], [7, 8]], np.float32))
        seg = paddle.to_tensor(np.array([0, 0, 1, 1]))
        np.testing.assert_allclose(
            paddle.geometric.segment_sum(data, seg).numpy(),
            [[4, 6], [12, 14]])
        np.testing.assert_allclose(
            paddle.geometric.segment_mean(data, seg).numpy(),
            [[2, 3], [6, 7]])
        np.testing.assert_allclose(
            paddle.geometric.segment_max(data, seg).numpy(),
            [[3, 4], [7, 8]])
        np.testing.assert_allclose(
            paddle.geometric.segment_min(data, seg).numpy(),
            [[1, 2], [5, 6]])

    def test_segment_sum_grad(self):
        data = paddle.to_tensor(np.ones((4, 2), np.float32))
        data.stop_gradient = False
        seg = paddle.to_tensor(np.array([0, 0, 1, 1]))
        paddle.geometric.segment_sum(data, seg).sum().backward()
        np.testing.assert_allclose(data.grad.numpy(), np.ones((4, 2)))

    def test_reindex_graph(self):
        x = paddle.to_tensor(np.array([0, 5, 8], np.int64))
        neighbors = paddle.to_tensor(np.array([8, 9, 0, 4, 7, 6, 7], np.int64))
        count = paddle.to_tensor(np.array([2, 3, 2], np.int32))
        src, dst, nodes = paddle.geometric.reindex_graph(x, neighbors, count)
        n = nodes.numpy()
        np.testing.assert_array_equal(n[:3], [0, 5, 8])
        # every reindexed edge maps back to the original neighbor ids
        np.testing.assert_array_equal(n[src.numpy()], neighbors.numpy())
        np.testing.assert_array_equal(dst.numpy(),
                                      [0, 0, 1, 1, 1, 2, 2])

    def test_sample_neighbors(self):
        # CSC graph: node0 <- {1,2,3}, node1 <- {0}, node2 <- {}
        row = paddle.to_tensor(np.array([1, 2, 3, 0], np.int64))
        colptr = paddle.to_tensor(np.array([0, 3, 4, 4], np.int64))
        nodes = paddle.to_tensor(np.array([0, 1, 2], np.int64))
        nb, cnt = paddle.geometric.sample_neighbors(row, colptr, nodes,
                                                    sample_size=2)
        c = cnt.numpy()
        np.testing.assert_array_equal(c, [2, 1, 0])
        assert set(nb.numpy()[:2].tolist()).issubset({1, 2, 3})
        full, cf = paddle.geometric.sample_neighbors(row, colptr, nodes)
        np.testing.assert_array_equal(cf.numpy(), [3, 1, 0])

    def test_weighted_sample_neighbors(self):
        row = paddle.to_tensor(np.array([1, 2, 3], np.int64))
        colptr = paddle.to_tensor(np.array([0, 3], np.int64))
        w = paddle.to_tensor(np.array([100.0, 1e-6, 1e-6], np.float32))
        nodes = paddle.to_tensor(np.array([0], np.int64))
        heavy = 0
        for _ in range(10):
            nb, cnt = paddle.geometric.weighted_sample_neighbors(
                row, colptr, w, nodes, sample_size=1)
            heavy += int(nb.numpy()[0] == 1)
        assert heavy >= 8  # weight-proportional sampling


class TestSequenceLosses:
    def test_hsigmoid_loss_trains(self):
        rng = np.random.RandomState(0)
        K, Fdim, B = 8, 4, 16
        x = paddle.to_tensor(rng.randn(B, Fdim).astype(np.float32))
        lab = paddle.to_tensor(rng.randint(0, K, B).astype(np.int64))
        w = paddle.to_tensor(rng.randn(K - 1, Fdim).astype(np.float32) * 0.1)
        w.stop_gradient = False
        losses = []
        opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[w])
        for _ in range(30):
            loss = F.hsigmoid_loss(x, lab, K, w).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.7

    def test_edit_distance(self):
        inp = paddle.to_tensor(np.array([[1, 2, 3, 4]], np.int64))
        lab = paddle.to_tensor(np.array([[1, 3, 4, 0]], np.int64))
        d, n = F.edit_distance(inp, lab, normalized=False,
                               label_length=paddle.to_tensor(
                                   np.array([3], np.int64)))
        # "1234" vs "134" -> one deletion
        assert float(d.numpy()[0, 0]) == 1.0
        assert int(n.numpy()[0]) == 1
        dn, _ = F.edit_distance(inp, lab, normalized=True,
                                label_length=paddle.to_tensor(
                                    np.array([3], np.int64)))
        np.testing.assert_allclose(dn.numpy()[0, 0], 1 / 3, rtol=1e-5)

    def test_ctc_align(self):
        inp = paddle.to_tensor(np.array([[0, 1, 1, 0, 2, 2, 0]], np.int64))
        out, lens = F.ctc_align(inp, blank=0)
        np.testing.assert_array_equal(out.numpy()[0, :2], [1, 2])
        assert int(lens.numpy()[0]) == 2

    def test_rnnt_loss_brute_force(self):
        # tiny case: enumerate all alignments
        B, T, U1, V = 1, 2, 2, 3
        rng = np.random.RandomState(0)
        logits = rng.randn(B, T, U1, V).astype(np.float32)
        labels = np.array([[1]], np.int64)
        loss = F.rnnt_loss(paddle.to_tensor(logits),
                           paddle.to_tensor(labels),
                           paddle.to_tensor(np.array([T])),
                           paddle.to_tensor(np.array([1])),
                           reduction="none")
        lp = logits[0] - np.log(np.exp(logits[0]).sum(-1, keepdims=True))
        # paths: emit at t=0 or t=1
        p0 = lp[0, 0, 1] + lp[0, 1, 0] + lp[1, 1, 0]
        p1 = lp[0, 0, 0] + lp[1, 0, 1] + lp[1, 1, 0]
        expect = -np.logaddexp(p0, p1)
        np.testing.assert_allclose(float(loss.numpy()[0]), expect, rtol=1e-4)

    def test_rnnt_loss_grad(self):
        rng = np.random.RandomState(1)
        logits = paddle.to_tensor(rng.randn(2, 4, 3, 5).astype(np.float32))
        logits.stop_gradient = False
        labels = paddle.to_tensor(rng.randint(1, 5, (2, 2)).astype(np.int64))
        loss = F.rnnt_loss(logits, labels,
                           paddle.to_tensor(np.array([4, 3])),
                           paddle.to_tensor(np.array([2, 1])))
        loss.backward()
        g = logits.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


class TestQuantLinear:
    def test_weight_only_linear_close_to_fp(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 16).astype(np.float32)
        w = rng.randn(16, 8).astype(np.float32)
        from paddle_tpu import quantization as Q
        qw, scale = Q.weight_quantize(paddle.to_tensor(w))
        out = Q.weight_only_linear(paddle.to_tensor(x), qw,
                                   weight_scale=scale)
        ref = x @ w
        err = np.abs(out.numpy() - ref).max() / np.abs(ref).max()
        assert err < 0.03

    def test_llm_int8_linear_outlier_decomposition(self):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 16).astype(np.float32)
        x[:, 3] *= 50  # outlier column
        w = rng.randn(16, 8).astype(np.float32)
        from paddle_tpu import quantization as Q
        qw, scale = Q.weight_quantize(paddle.to_tensor(w))
        out = Q.llm_int8_linear(paddle.to_tensor(x), qw, weight_scale=scale,
                                threshold=6.0)
        ref = x @ (np.round(np.clip(w / (np.abs(w).max(0) / 127), -128, 127))
                   * (np.abs(w).max(0) / 127))
        err = np.abs(out.numpy() - ref).max() / np.abs(ref).max()
        assert err < 0.05

    def test_apply_per_channel_scale(self):
        from paddle_tpu import quantization as Q
        x = paddle.to_tensor(np.full((2, 3), 6.0, np.float32))
        s = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        np.testing.assert_allclose(
            Q.apply_per_channel_scale(x, s).numpy(), [[6, 3, 2], [6, 3, 2]])


class TestCorrelation:
    def test_zero_displacement_channel_is_self_correlation(self):
        from paddle_tpu.vision import ops as vops
        rng = np.random.RandomState(0)
        x = rng.randn(1, 4, 6, 6).astype(np.float32)
        out = vops.correlation(paddle.to_tensor(x), paddle.to_tensor(x),
                               pad_size=2, kernel_size=1,
                               max_displacement=2, stride1=1, stride2=1)
        d = 2
        n_disp = (2 * d + 1) ** 2
        assert out.shape[1] == n_disp
        # output crops the max_displacement border of the padded map:
        # (6 + 2*2 - 2*2) = 6 -> exactly the original extent here
        assert out.shape[2] == 6 and out.shape[3] == 6
        center = out.numpy()[0, n_disp // 2]
        expect = (x[0] ** 2).mean(axis=0)
        np.testing.assert_allclose(center, expect, rtol=1e-4)

    def test_no_wraparound_with_small_pad(self):
        from paddle_tpu.vision import ops as vops
        # pad_size=0 < max_displacement: displaced reads at the border must
        # see zeros, never the opposite edge
        x = np.zeros((1, 1, 4, 4), np.float32)
        x[0, 0, 0, 0] = 1.0
        x[0, 0, -1, -1] = 100.0
        out = vops.correlation(paddle.to_tensor(x), paddle.to_tensor(x),
                               pad_size=1, kernel_size=1,
                               max_displacement=1, stride1=1, stride2=1)
        o = out.numpy()[0]  # (9, 4, 4)
        # channel (dy=-1,dx=-1) at position (0,0): displaced read is out of
        # bounds -> 0, NOT the 100 at the opposite corner
        assert o[0, 0, 0] == 0.0

    def test_roi_align_empty_rois(self):
        from paddle_tpu.vision import ops as vops
        x = paddle.to_tensor(np.ones((1, 3, 8, 8), np.float32))
        boxes = paddle.to_tensor(np.zeros((0, 4), np.float32))
        out = vops.roi_align(x, boxes, [0], output_size=2,
                             sampling_ratio=-1)
        assert out.shape == [0, 3, 2, 2]


class TestMetrics:
    def test_chunk_eval_iob(self):
        from paddle_tpu import metric
        # tags: type0 {B=0, I=1}, type1 {B=2, I=3}; O = 4
        label = np.array([[0, 1, 4, 2, 3, 4]], np.int64)
        pred = np.array([[0, 1, 4, 2, 4, 4]], np.int64)
        p, r, f1, ninf, nlab, ncor = metric.chunk_eval(
            pred, label, "IOB", 2)
        assert int(nlab.numpy()[0]) == 2
        assert int(ninf.numpy()[0]) == 2
        assert int(ncor.numpy()[0]) == 1  # only the type0 chunk matches
        np.testing.assert_allclose(p.numpy()[0], 0.5)
        np.testing.assert_allclose(r.numpy()[0], 0.5)

    def test_detection_map_perfect(self):
        from paddle_tpu import metric
        m = metric.DetectionMAP(class_num=2)
        det = np.array([[0, 0.9, 0, 0, 10, 10], [1, 0.8, 20, 20, 30, 30]],
                       np.float32)
        gt = np.array([[0, 0, 0, 10, 10], [1, 20, 20, 30, 30]], np.float32)
        m.update(det, gt)
        assert m.accumulate() == pytest.approx(1.0)

    def test_detection_map_half(self):
        from paddle_tpu import metric
        m = metric.DetectionMAP(class_num=1)
        det = np.array([[0, 0.9, 0, 0, 10, 10],
                        [0, 0.8, 50, 50, 60, 60]], np.float32)  # 1 fp
        gt = np.array([[0, 0, 0, 10, 10], [0, 80, 80, 90, 90]], np.float32)
        m.update(det, gt)
        # 1 tp of 2 gts, fp at rank 2: integral AP = 0.5
        assert m.accumulate() == pytest.approx(0.5)


class TestReviewFixes:
    def test_segment_min_empty_segment_zero(self):
        data = paddle.to_tensor(
            np.array([[1.0, 2], [3, 4], [5, 6]], np.float32))
        seg = paddle.to_tensor(np.array([0, 0, 2]))
        out = paddle.geometric.segment_min(data, seg).numpy()
        np.testing.assert_allclose(out[1], [0, 0])  # empty segment -> 0
        np.testing.assert_allclose(out[0], [1, 2])

    def test_send_u_recv_int_min_empty_dst(self):
        x = paddle.to_tensor(np.array([[5], [7]], np.int32))
        src = paddle.to_tensor(np.array([0, 1]))
        dst = paddle.to_tensor(np.array([0, 0]))
        out = paddle.geometric.send_u_recv(x, src, dst, "min",
                                           out_size=3).numpy()
        assert out[0, 0] == 5
        assert out[1, 0] == 0 and out[2, 0] == 0  # not INT_MAX

    def test_yolo_box_iou_aware_layout(self):
        from paddle_tpu.vision import ops as vops
        n, na, c, h, w = 1, 2, 3, 2, 2
        # iou block leads: na channels, then na*(5+c)
        arr = np.zeros((n, na + na * (5 + c), h, w), np.float32)
        arr[:, :na] = 5.0  # iou logits -> sigmoid ~ 0.993
        img = paddle.to_tensor(np.full((n, 2), 32, np.int32))
        boxes, scores = vops.yolo_box(
            paddle.to_tensor(arr), img, anchors=[10, 13, 16, 30],
            class_num=c, conf_thresh=0.0, downsample_ratio=16,
            iou_aware=True, iou_aware_factor=0.5)
        assert boxes.shape == [n, na * h * w, 4]
        # conf = sigmoid(0)^0.5 * sigmoid(5)^0.5 ~ 0.705; score = conf * 0.5
        np.testing.assert_allclose(scores.numpy(),
                                   np.full((n, na * h * w, c),
                                           np.sqrt(0.5 * 0.9933) * 0.5),
                                   rtol=1e-3)

    def test_roi_align_adaptive_sampling(self):
        from paddle_tpu.vision import ops as vops
        # large ROI -> adaptive grid (ceil(roi/out) samples/bin): average of
        # a linear ramp over each bin must equal the bin-center value
        H = 16
        ramp = np.broadcast_to(
            np.arange(H, dtype=np.float32)[None, :], (H, H))
        x = paddle.to_tensor(ramp[None, None].copy())
        boxes = paddle.to_tensor(np.array([[0, 0, 16, 16]], np.float32))
        out = vops.roi_align(x, boxes, [1], output_size=2,
                             sampling_ratio=-1, aligned=False)
        # adaptive grid = 8 samples/bin at fraction centers 0.5..7.5:
        # bin0 mean = 4.0; bin1 samples 8.5..15.5 (15.5 clamps to 15)
        np.testing.assert_allclose(out.numpy()[0, 0, 0], [4.0, 11.9375],
                                   atol=1e-3)

    def test_rnnt_fastemit_scales_grad_not_value(self):
        rng = np.random.RandomState(2)
        logits_np = rng.randn(1, 3, 2, 4).astype(np.float32)
        labels = paddle.to_tensor(np.array([[1]], np.int64))
        tl = paddle.to_tensor(np.array([3]))
        ul = paddle.to_tensor(np.array([1]))
        vals, grads = [], []
        for lam in (0.0, 0.5):
            lg = paddle.to_tensor(logits_np)
            lg.stop_gradient = False
            loss = F.rnnt_loss(lg, labels, tl, ul, fastemit_lambda=lam,
                               reduction="sum")
            loss.backward()
            vals.append(float(loss.numpy()))
            grads.append(lg.grad.numpy().copy())
        np.testing.assert_allclose(vals[0], vals[1], rtol=1e-6)
        assert np.abs(grads[0] - grads[1]).max() > 1e-6
