"""Distribution family tests — log_prob/entropy/moments validated against
scipy.stats; samplers validated by moment matching; KL registry against
numerical integration / scipy.

Reference: python/paddle/distribution/ + kl.py.
"""
import math

import numpy as np
import pytest
from scipy import stats

import paddle_tpu as paddle

D = paddle.distribution


def _lp(dist, v):
    return np.asarray(dist.log_prob(paddle.to_tensor(
        np.asarray(v, np.float32))).numpy())


class TestLogProbVsScipy:
    def test_exponential(self):
        d = D.Exponential(1.7)
        v = np.array([0.1, 0.5, 2.0, 5.0], np.float32)
        np.testing.assert_allclose(_lp(d, v),
                                   stats.expon.logpdf(v, scale=1 / 1.7),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   stats.expon.entropy(scale=1 / 1.7),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(d.cdf(paddle.to_tensor(v)).numpy()),
            stats.expon.cdf(v, scale=1 / 1.7), rtol=1e-5)

    def test_gamma(self):
        d = D.Gamma(2.5, 1.3)
        v = np.array([0.2, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(
            _lp(d, v), stats.gamma.logpdf(v, 2.5, scale=1 / 1.3), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   stats.gamma.entropy(2.5, scale=1 / 1.3),
                                   rtol=1e-5)

    def test_chi2(self):
        d = D.Chi2(4.0)
        v = np.array([0.5, 2.0, 7.0], np.float32)
        np.testing.assert_allclose(_lp(d, v), stats.chi2.logpdf(v, 4),
                                   rtol=1e-5)

    def test_beta(self):
        d = D.Beta(2.0, 3.5)
        v = np.array([0.1, 0.4, 0.9], np.float32)
        np.testing.assert_allclose(_lp(d, v), stats.beta.logpdf(v, 2.0, 3.5),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   stats.beta.entropy(2.0, 3.5), rtol=1e-4)

    def test_dirichlet(self):
        c = np.array([1.5, 2.0, 3.0], np.float32)
        d = D.Dirichlet(c)
        v = np.array([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(float(_lp(d, v)),
                                   stats.dirichlet.logpdf(v, c), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   stats.dirichlet.entropy(c), rtol=1e-4)

    def test_laplace(self):
        d = D.Laplace(0.5, 2.0)
        v = np.array([-3.0, 0.5, 4.0], np.float32)
        np.testing.assert_allclose(
            _lp(d, v), stats.laplace.logpdf(v, 0.5, 2.0), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(d.cdf(paddle.to_tensor(v)).numpy()),
            stats.laplace.cdf(v, 0.5, 2.0), rtol=1e-5)

    def test_cauchy(self):
        d = D.Cauchy(1.0, 0.5)
        v = np.array([-2.0, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(
            _lp(d, v), stats.cauchy.logpdf(v, 1.0, 0.5), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(d.cdf(paddle.to_tensor(v)).numpy()),
            stats.cauchy.cdf(v, 1.0, 0.5), rtol=1e-5)

    def test_gumbel(self):
        d = D.Gumbel(0.3, 1.2)
        v = np.array([-1.0, 0.3, 2.5], np.float32)
        np.testing.assert_allclose(
            _lp(d, v), stats.gumbel_r.logpdf(v, 0.3, 1.2), rtol=1e-5)

    def test_lognormal(self):
        d = D.LogNormal(0.2, 0.7)
        v = np.array([0.5, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(
            _lp(d, v), stats.lognorm.logpdf(v, 0.7, scale=np.exp(0.2)),
            rtol=1e-5)

    def test_geometric(self):
        d = D.Geometric(0.3)
        v = np.array([0, 1, 4], np.float32)
        # scipy geom counts trials (support 1..); ours counts failures
        np.testing.assert_allclose(_lp(d, v),
                                   stats.geom.logpmf(v + 1, 0.3), rtol=1e-5)

    def test_poisson(self):
        d = D.Poisson(3.5)
        v = np.array([0, 2, 6], np.float32)
        np.testing.assert_allclose(_lp(d, v),
                                   stats.poisson.logpmf(v, 3.5), rtol=1e-5)

    def test_binomial(self):
        d = D.Binomial(10.0, 0.3)
        v = np.array([0, 3, 10], np.float32)
        np.testing.assert_allclose(_lp(d, v),
                                   stats.binom.logpmf(v, 10, 0.3),
                                   rtol=1e-4)

    def test_multinomial(self):
        p = np.array([0.2, 0.3, 0.5], np.float32)
        d = D.Multinomial(6, p)
        v = np.array([1, 2, 3], np.float32)
        np.testing.assert_allclose(float(_lp(d, v)),
                                   stats.multinomial.logpmf(v, 6, p),
                                   rtol=1e-5)

    def test_student_t(self):
        d = D.StudentT(5.0, 0.5, 2.0)
        v = np.array([-2.0, 0.5, 3.0], np.float32)
        np.testing.assert_allclose(
            _lp(d, v), stats.t.logpdf(v, 5, 0.5, 2.0), rtol=1e-5)

    def test_mvn(self):
        mean = np.array([1.0, -1.0], np.float32)
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        d = D.MultivariateNormal(mean, covariance_matrix=cov)
        v = np.array([0.5, 0.0], np.float32)
        np.testing.assert_allclose(
            float(_lp(d, v)),
            stats.multivariate_normal.logpdf(v, mean, cov), rtol=1e-5)
        np.testing.assert_allclose(
            float(d.entropy().numpy()),
            stats.multivariate_normal.entropy(mean, cov), rtol=1e-5)
        np.testing.assert_allclose(d.variance.numpy(), np.diag(cov),
                                   rtol=1e-6)


class TestSampling:
    @pytest.mark.parametrize("ctor,mean,var", [
        (lambda: D.Exponential(2.0), 0.5, 0.25),
        (lambda: D.Gamma(3.0, 2.0), 1.5, 0.75),
        (lambda: D.Beta(2.0, 2.0), 0.5, 1 / 20),
        (lambda: D.Laplace(1.0, 0.5), 1.0, 0.5),
        (lambda: D.Gumbel(0.0, 1.0), np.euler_gamma, np.pi ** 2 / 6),
        (lambda: D.LogNormal(0.0, 0.5),
         math.exp(0.125), (math.exp(0.25) - 1) * math.exp(0.25)),
        (lambda: D.Geometric(0.4), 1.5, 0.6 / 0.16),
        (lambda: D.Poisson(4.0), 4.0, 4.0),
        (lambda: D.Binomial(20.0, 0.25), 5.0, 3.75),
        (lambda: D.StudentT(10.0, 0.0, 1.0), 0.0, 10 / 8),
    ])
    def test_moments(self, ctor, mean, var):
        paddle.seed(0)
        s = np.asarray(ctor().sample((20000,)).numpy())
        np.testing.assert_allclose(s.mean(), mean,
                                   atol=4 * math.sqrt(var / 20000) + 1e-3)
        np.testing.assert_allclose(s.var(), var, rtol=0.15)

    def test_mvn_sample_cov(self):
        paddle.seed(1)
        cov = np.array([[2.0, 0.8], [0.8, 1.0]], np.float32)
        d = D.MultivariateNormal(np.zeros(2, np.float32),
                                 covariance_matrix=cov)
        s = np.asarray(d.sample((20000,)).numpy())
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.1)

    def test_dirichlet_sample_simplex(self):
        paddle.seed(2)
        d = D.Dirichlet(np.array([2.0, 3.0, 4.0], np.float32))
        s = np.asarray(d.sample((5000,)).numpy())
        np.testing.assert_allclose(s.sum(-1), 1.0, atol=1e-5)
        np.testing.assert_allclose(s.mean(0), [2 / 9, 3 / 9, 4 / 9],
                                   atol=0.02)

    def test_multinomial_sample(self):
        paddle.seed(3)
        d = D.Multinomial(12, np.array([0.5, 0.5], np.float32))
        s = np.asarray(d.sample((2000,)).numpy())
        np.testing.assert_allclose(s.sum(-1), 12.0)
        np.testing.assert_allclose(s.mean(0), [6, 6], atol=0.3)

    def test_rsample_gradient(self):
        # reparameterized gradient: d E[x]/d loc = 1 for Laplace
        loc = paddle.to_tensor(np.float32(0.5))
        loc.stop_gradient = False
        d = D.Laplace(loc, paddle.to_tensor(np.float32(1.0)))
        paddle.seed(4)
        s = d.rsample((256,))
        s.mean().backward()
        np.testing.assert_allclose(float(loc.grad.numpy()), 1.0, atol=1e-5)


class TestKL:
    def test_kl_gamma_mc(self):
        p = D.Gamma(2.0, 1.0)
        q = D.Gamma(3.0, 1.5)
        kl = float(D.kl_divergence(p, q).numpy())
        paddle.seed(0)
        s = p.sample((200000,))
        mc = float((p.log_prob(s) - q.log_prob(s)).mean().numpy())
        np.testing.assert_allclose(kl, mc, rtol=0.05)

    # slow-marked (~7s of digamma/lgamma compiles, 870s tier-1
    # budget): closed-form-vs-MC KL stays in tier-1 via the gamma and
    # MVN cases; the beta/exponential/laplace formulas run in the
    # full matrix
    @pytest.mark.slow
    def test_kl_beta_exponential_laplace(self):
        pairs = [
            (D.Beta(2.0, 3.0), D.Beta(3.0, 2.0)),
            (D.Exponential(1.0), D.Exponential(2.5)),
            (D.Laplace(0.0, 1.0), D.Laplace(1.0, 2.0)),
            (D.Poisson(2.0), D.Poisson(4.0)),
            (D.Geometric(0.3), D.Geometric(0.6)),
        ]
        paddle.seed(1)
        for p, q in pairs:
            kl = float(D.kl_divergence(p, q).numpy())
            s = p.sample((200000,))
            mc = float((p.log_prob(s) - q.log_prob(s)).mean().numpy())
            np.testing.assert_allclose(
                kl, mc, rtol=0.08, atol=0.01,
                err_msg=f"{type(p).__name__}")

    def test_kl_mvn_closed_form(self):
        p = D.MultivariateNormal(np.zeros(2, np.float32),
                                 covariance_matrix=np.eye(2, dtype=np.float32))
        cov_q = np.array([[2.0, 0.3], [0.3, 1.5]], np.float32)
        q = D.MultivariateNormal(np.ones(2, np.float32),
                                 covariance_matrix=cov_q)
        kl = float(D.kl_divergence(p, q).numpy())
        # closed form by hand
        iq = np.linalg.inv(cov_q)
        expect = 0.5 * (np.trace(iq @ np.eye(2))
                        + np.ones(2) @ iq @ np.ones(2) - 2
                        + np.log(np.linalg.det(cov_q)))
        np.testing.assert_allclose(kl, expect, rtol=1e-5)

    def test_register_kl_custom(self):
        class MyDist(D.Distribution):
            pass

        @D.register_kl(MyDist, MyDist)
        def _kl_my(p, q):
            return paddle.to_tensor(np.float32(42.0))

        assert float(D.kl_divergence(MyDist(), MyDist()).numpy()) == 42.0


class TestTransforms:
    def test_affine_roundtrip_and_ldj(self):
        t = D.AffineTransform(1.0, 2.0)
        x = paddle.to_tensor(np.array([0.5, -1.0], np.float32))
        y = t.forward(x)
        np.testing.assert_allclose(y.numpy(), [2.0, -1.0])
        np.testing.assert_allclose(t.inverse(y).numpy(), x.numpy())
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(x).numpy(), np.log(2.0))

    def test_transformed_lognormal_equals_native(self):
        base = D.Normal(0.2, 0.7)
        td = D.TransformedDistribution(base, D.ExpTransform())
        native = D.LogNormal(0.2, 0.7)
        v = np.array([0.5, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(_lp(td, v), _lp(native, v), rtol=1e-5)

    def test_sigmoid_tanh_chain(self):
        for t, finv in ((D.SigmoidTransform(), stats.logistic.cdf),
                        (D.TanhTransform(), np.tanh)):
            x = np.array([-1.5, 0.0, 2.0], np.float32)
            y = t.forward(paddle.to_tensor(x)).numpy()
            np.testing.assert_allclose(y, finv(x), rtol=1e-5)
            np.testing.assert_allclose(
                t.inverse(paddle.to_tensor(y)).numpy(), x, rtol=1e-4,
                atol=1e-5)
            # ldj vs numerical derivative
            eps = 1e-3
            num = (finv(x + eps) - finv(x - eps)) / (2 * eps)
            np.testing.assert_allclose(
                t.forward_log_det_jacobian(paddle.to_tensor(x)).numpy(),
                np.log(num), atol=1e-3)

    def test_chain_transform(self):
        chain = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                                  D.ExpTransform()])
        x = paddle.to_tensor(np.array([0.3], np.float32))
        y = chain.forward(x)
        np.testing.assert_allclose(y.numpy(), np.exp(0.6), rtol=1e-5)
        np.testing.assert_allclose(chain.inverse(y).numpy(), 0.3,
                                   rtol=1e-5)
        # ldj = log2 + 2x
        np.testing.assert_allclose(
            chain.forward_log_det_jacobian(x).numpy(),
            np.log(2.0) + 0.6, rtol=1e-5)


class TestIndependentAndCB:
    def test_independent_sums_event_dims(self):
        base = D.Normal(np.zeros((3, 4), np.float32),
                        np.ones((3, 4), np.float32))
        ind = D.Independent(base, 1)
        v = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        lp = ind.log_prob(paddle.to_tensor(v))
        assert lp.shape == [3]
        np.testing.assert_allclose(
            lp.numpy(), _lp(base, v).sum(-1), rtol=1e-5)

    def test_continuous_bernoulli(self):
        # integrates to 1 and mean matches the closed form
        d = D.ContinuousBernoulli(np.float32(0.3))
        xs = np.linspace(1e-4, 1 - 1e-4, 20001).astype(np.float32)
        pdf = np.exp(_lp(d, xs))
        integral = np.trapezoid(pdf, xs)
        np.testing.assert_allclose(integral, 1.0, rtol=1e-3)
        mean_num = np.trapezoid(pdf * xs, xs)
        np.testing.assert_allclose(float(d.mean.numpy()), mean_num,
                                   rtol=1e-3)
        # near p=0.5 the Taylor branch holds
        d5 = D.ContinuousBernoulli(np.float32(0.5))
        pdf5 = np.exp(_lp(d5, xs))
        np.testing.assert_allclose(np.trapezoid(pdf5, xs), 1.0, rtol=1e-3)


class TestCategoricalBroadcast:
    def test_value_smaller_than_batch(self):
        logits = np.log(np.array([[0.2, 0.8], [0.5, 0.5], [0.9, 0.1]],
                                 np.float32))
        d = D.Categorical(logits)
        lp = d.log_prob(paddle.to_tensor(np.array([1], np.int64)))
        assert lp.shape == [3]
        np.testing.assert_allclose(np.exp(lp.numpy()), [0.8, 0.5, 0.1],
                                   rtol=1e-5)

    def test_sample_dims_over_scalar_batch(self):
        d = D.Categorical(np.log(np.array([0.3, 0.7], np.float32)))
        v = paddle.to_tensor(np.array([0, 1, 1, 0], np.int64))
        lp = d.log_prob(v)
        assert lp.shape == [4]
        np.testing.assert_allclose(np.exp(lp.numpy()),
                                   [0.3, 0.7, 0.7, 0.3], rtol=1e-5)
