"""SOT partial-frame graph-break tests.

Reference contract (python/paddle/jit/sot/translate.py:98,
sot/symbolic/statement_ir.py, symbolic/compile_cache.py + test/sot/): a
function with an untraceable mid-frame construct must still compile the op
sequences around the break — here, a mid-function ``numpy()`` sync yields
exactly TWO compiled XLA subgraphs, cached per site/shape guard.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import sot


class TestLazySegments:
    def test_lazy_then_materialized(self):
        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        with sot.capture() as cap:
            y = paddle.ops.tanh(x)
            z = y + 1.0
            assert isinstance(z._data, sot.LazyArray)
            assert z._data._value is None
            assert z.shape == [4, 4]          # abstract metadata works
            got = z.numpy()                   # break: flush segment
            assert z._data._value is not None
        ref = np.tanh(np.asarray(x.numpy())) + 1.0
        np.testing.assert_allclose(got, ref, atol=1e-6)
        assert cap.stats["segments"] == 1
        assert cap.stats["compiled"] == 1

    def test_two_segments_on_mid_break(self):
        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        with sot.capture() as cap:
            y = paddle.ops.tanh(x)
            s = float(y.numpy().sum())        # graph break
            z = paddle.ops.exp(y) * s
            _ = z.numpy()
        assert cap.stats["segments"] == 2
        assert cap.stats["compiled"] == 2

    def test_cache_reuse_across_runs(self):
        cache = {}
        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))

        def run():
            with sot.capture(cache) as cap:
                y = paddle.ops.tanh(x)
                _ = y.numpy()
                z = paddle.ops.exp(y)
                _ = z.numpy()
            return cap.stats

        s1 = run()
        s2 = run()
        assert s1 == {"segments": 2, "compiled": 2}
        assert s2 == {"segments": 2, "compiled": 0}  # guard cache hit

    def test_data_dependent_shape_op_breaks_implicitly(self):
        x = paddle.to_tensor(np.asarray([1.0, 0.0, 2.0, 0.0], np.float32))
        with sot.capture() as cap:
            y = x * 2.0
            nz = paddle.ops.nonzero(y)        # shape depends on data
            out = nz.numpy()
        np.testing.assert_array_equal(out.ravel(), [0, 2])
        assert cap.stats["segments"] >= 1


class TestToStaticSot:
    def _make(self):
        w = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 8).astype(np.float32) * 0.3)

        def fn(x):
            y = paddle.ops.tanh(paddle.ops.matmul(x, w))
            s = float(y.numpy().sum())        # mid-frame host sync
            if s > 1e9:                        # data-dependent python flow
                y = y * 0.0
            return paddle.ops.exp(y) + s

        return fn, w

    def test_numpy_sync_yields_two_compiled_subgraphs(self):
        fn, w = self._make()
        st = paddle.jit.to_static(fn, full_graph=False)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 8).astype(np.float32))

        with pytest.warns(UserWarning, match="SOT partial-frame"):
            out1 = st(x)
        ref = fn(x)
        np.testing.assert_allclose(np.asarray(out1.numpy()),
                                   np.asarray(ref.numpy()), atol=1e-6)
        assert st.sot_stats == {"segments": 2, "compiled": 2,
                                "bypassed": False}

        # same shapes again: segments replay from the guarded cache
        out2 = st(x)
        np.testing.assert_allclose(np.asarray(out2.numpy()),
                                   np.asarray(ref.numpy()), atol=1e-6)
        assert st.sot_stats == {"segments": 2, "compiled": 0,
                                "bypassed": False}

    def test_new_shape_recompiles_via_guards(self):
        fn, w = self._make()
        st = paddle.jit.to_static(fn, full_graph=False)
        x1 = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        x2 = paddle.to_tensor(np.random.randn(5, 8).astype(np.float32))
        with pytest.warns(UserWarning):
            st(x1)
        with pytest.warns(UserWarning):
            st(x2)                             # new signature, new break
        assert st.sot_stats == {"segments": 2, "compiled": 2,
                                "bypassed": False}
        st(x2)
        assert st.sot_stats == {"segments": 2, "compiled": 0,
                                "bypassed": False}

    def test_full_graph_signatures_unaffected(self):
        calls = []

        def fn(x):
            calls.append(1)
            return paddle.ops.tanh(x) * 2.0

        st = paddle.jit.to_static(fn, full_graph=False)
        x = paddle.to_tensor(np.random.randn(3, 3).astype(np.float32))
        a = st(x)
        b = st(x)
        np.testing.assert_allclose(np.asarray(a.numpy()),
                                   np.asarray(b.numpy()))
        assert st.sot_stats is None            # never broke
        assert len(calls) == 1                 # compiled, not re-traced

    def test_sot_output_usable_in_later_eager_ops(self):
        fn, w = self._make()
        st = paddle.jit.to_static(fn, full_graph=False)
        x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        with pytest.warns(UserWarning):
            out = st(x)
        # escaped payload feeds a plain eager op
        more = paddle.ops.mean(out * 2.0)
        assert np.isfinite(float(more.numpy()))

    def test_training_through_break(self):
        # gradients must survive a mid-frame break: the tape records
        # lazy-vjp nodes whose payloads materialize before backward
        rng = np.random.RandomState(3)
        w = paddle.to_tensor(rng.randn(4, 4).astype(np.float32) * 0.5)
        w.stop_gradient = False
        x = paddle.to_tensor(rng.randn(2, 4).astype(np.float32))

        def loss_fn():
            y = paddle.ops.tanh(paddle.ops.matmul(x, w))
            _ = y.numpy()                      # break
            return paddle.ops.mean(paddle.ops.exp(y))

        with sot.capture():
            loss = loss_fn()
        loss.backward()
        got = np.asarray(w.grad._data)

        w.clear_grad()
        loss2 = loss_fn()
        loss2.backward()
        np.testing.assert_allclose(got, np.asarray(w.grad._data),
                                   atol=1e-6)


class TestSteadyStateBypass:
    """VERDICT r4 #4 (reference symbolic/compile_cache.py guard-hit path):
    after two identical replays, a stable frame executes its stitched
    compiled segments directly — no per-op Python recording."""

    class _Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(8, 8)
            self.gain = 2.0

        def forward(self, x):
            s = float(paddle.ops.mean(x).numpy())   # input-only break
            y = paddle.ops.tanh(self.fc(x))
            if s > 1e9:                              # glue control flow
                y = y * 0.0
            return paddle.ops.exp(y) * self.gain

    def _frozen_net(self):
        paddle.seed(21)
        net = self._Net()
        for p in net.parameters():
            p.stop_gradient = True   # grad-free: bypass-eligible
        return net

    def test_third_call_bypasses_python(self):
        net = self._frozen_net()
        st = paddle.jit.to_static(net.forward, full_graph=False)
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(4, 8).astype(np.float32))
        with pytest.warns(UserWarning):
            ref = st(x)
        assert st.sot_stats["bypassed"] is False
        st(x)                                       # journal match -> stable
        assert st.sot_stats["bypassed"] is False
        out = st(x)                                 # steady state
        assert st.sot_stats["bypassed"] is True
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(ref.numpy()), atol=1e-6)
        # and the python frame really did not run: no new segments compile
        assert st.sot_stats["compiled"] == 0

    def test_bypass_reads_parameters_live(self):
        net = self._frozen_net()
        st = paddle.jit.to_static(net.forward, full_graph=False)
        x = paddle.to_tensor(
            np.random.RandomState(4).randn(4, 8).astype(np.float32))
        with pytest.warns(UserWarning):
            st(x)
        st(x)
        st(x)
        assert st.sot_stats["bypassed"] is True
        # update the weight; the journaled ("param", i) source must re-read
        w = net.fc.weight
        w._swap_payload(w._data * 0.5)
        out = st(x)
        assert st.sot_stats["bypassed"] is True     # no re-record needed
        ref = net.forward(x)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(ref.numpy()), atol=1e-6)

    def test_break_value_guard_falls_back(self):
        net = self._frozen_net()
        st = paddle.jit.to_static(net.forward, full_graph=False)
        x1 = paddle.to_tensor(
            np.random.RandomState(5).randn(4, 8).astype(np.float32))
        with pytest.warns(UserWarning):
            st(x1)
        st(x1)
        st(x1)
        assert st.sot_stats["bypassed"] is True
        # same shapes, different values: the break scalar changes, the
        # guard must miss, and the frame replays honestly (correct result)
        x2 = paddle.to_tensor(
            np.random.RandomState(6).randn(4, 8).astype(np.float32))
        out = st(x2)
        assert st.sot_stats["bypassed"] is False
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(net.forward(x2).numpy()),
                                   atol=1e-6)

    def test_object_attr_guard_invalidates(self):
        net = self._frozen_net()
        st = paddle.jit.to_static(net.forward, full_graph=False)
        x = paddle.to_tensor(
            np.random.RandomState(7).randn(4, 8).astype(np.float32))
        with pytest.warns(UserWarning):
            st(x)
        st(x)
        st(x)
        assert st.sot_stats["bypassed"] is True
        net.gain = 3.0   # frame-level guard: owner attrs one level deep
        out = st(x)
        assert st.sot_stats["bypassed"] is False    # guard missed
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(net.forward(x).numpy()),
                                   atol=1e-6)

    def test_grad_frames_stay_on_replay(self):
        paddle.seed(23)
        net = self._Net()       # params require grad -> outputs carry tape
        st = paddle.jit.to_static(net.forward, full_graph=False)
        x = paddle.to_tensor(
            np.random.RandomState(8).randn(4, 8).astype(np.float32))
        with pytest.warns(UserWarning):
            st(x)
        st(x)
        st(x)
        assert st.sot_stats["bypassed"] is False    # ineligible, honest

    def test_single_segment_branch_guarded(self):
        """Code-review r5 finding: a frame that breaks, branches on the
        scalar, and returns WITHOUT recording further ops must still
        guard that scalar (the final segment's glue reads)."""
        w = paddle.to_tensor(
            np.random.RandomState(31).randn(8, 8).astype(np.float32) * 0.3)

        def fn(x):
            y = paddle.ops.tanh(paddle.ops.matmul(x, w))
            s = float(paddle.ops.mean(y).numpy())
            if s > 0:
                return y
            return y * 0.0

        st = paddle.jit.to_static(fn, full_graph=False)
        # an input with positive mean, twice -> stable
        xp = paddle.to_tensor(np.full((2, 8), 0.5, np.float32))
        with pytest.warns(UserWarning):
            st(xp)
        st(xp)
        st(xp)
        # negative-mean input: the branch must flip, not stale-replay
        xn = paddle.to_tensor(np.full((2, 8), -0.5, np.float32))
        out = st(xn)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(fn(xn).numpy()), atol=1e-6)

    def test_ndarray_inputs_never_bypass(self):
        """Code-review r5 finding: raw ndarray args are re-materialized
        per call (untrackable provenance) — the frame must stay on
        replay and keep answering with CURRENT values."""
        net = self._frozen_net()
        st = paddle.jit.to_static(net.forward, full_graph=False)
        a = np.random.RandomState(8).randn(4, 8).astype(np.float32)
        b = np.random.RandomState(9).randn(4, 8).astype(np.float32)
        with pytest.warns(UserWarning):
            st(a)
        st(a)
        out = st(b)   # would be f(a) under a buggy bypass
        assert st.sot_stats["bypassed"] is False
        np.testing.assert_allclose(
            np.asarray(out.numpy()),
            np.asarray(net.forward(paddle.to_tensor(b)).numpy()),
            atol=1e-6)
