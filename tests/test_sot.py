"""SOT partial-frame graph-break tests.

Reference contract (python/paddle/jit/sot/translate.py:98,
sot/symbolic/statement_ir.py, symbolic/compile_cache.py + test/sot/): a
function with an untraceable mid-frame construct must still compile the op
sequences around the break — here, a mid-function ``numpy()`` sync yields
exactly TWO compiled XLA subgraphs, cached per site/shape guard.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import sot


class TestLazySegments:
    def test_lazy_then_materialized(self):
        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        with sot.capture() as cap:
            y = paddle.ops.tanh(x)
            z = y + 1.0
            assert isinstance(z._data, sot.LazyArray)
            assert z._data._value is None
            assert z.shape == [4, 4]          # abstract metadata works
            got = z.numpy()                   # break: flush segment
            assert z._data._value is not None
        ref = np.tanh(np.asarray(x.numpy())) + 1.0
        np.testing.assert_allclose(got, ref, atol=1e-6)
        assert cap.stats["segments"] == 1
        assert cap.stats["compiled"] == 1

    def test_two_segments_on_mid_break(self):
        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        with sot.capture() as cap:
            y = paddle.ops.tanh(x)
            s = float(y.numpy().sum())        # graph break
            z = paddle.ops.exp(y) * s
            _ = z.numpy()
        assert cap.stats["segments"] == 2
        assert cap.stats["compiled"] == 2

    def test_cache_reuse_across_runs(self):
        cache = {}
        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))

        def run():
            with sot.capture(cache) as cap:
                y = paddle.ops.tanh(x)
                _ = y.numpy()
                z = paddle.ops.exp(y)
                _ = z.numpy()
            return cap.stats

        s1 = run()
        s2 = run()
        assert s1 == {"segments": 2, "compiled": 2}
        assert s2 == {"segments": 2, "compiled": 0}  # guard cache hit

    def test_data_dependent_shape_op_breaks_implicitly(self):
        x = paddle.to_tensor(np.asarray([1.0, 0.0, 2.0, 0.0], np.float32))
        with sot.capture() as cap:
            y = x * 2.0
            nz = paddle.ops.nonzero(y)        # shape depends on data
            out = nz.numpy()
        np.testing.assert_array_equal(out.ravel(), [0, 2])
        assert cap.stats["segments"] >= 1


class TestToStaticSot:
    def _make(self):
        w = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 8).astype(np.float32) * 0.3)

        def fn(x):
            y = paddle.ops.tanh(paddle.ops.matmul(x, w))
            s = float(y.numpy().sum())        # mid-frame host sync
            if s > 1e9:                        # data-dependent python flow
                y = y * 0.0
            return paddle.ops.exp(y) + s

        return fn, w

    def test_numpy_sync_yields_two_compiled_subgraphs(self):
        fn, w = self._make()
        st = paddle.jit.to_static(fn, full_graph=False)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 8).astype(np.float32))

        with pytest.warns(UserWarning, match="SOT partial-frame"):
            out1 = st(x)
        ref = fn(x)
        np.testing.assert_allclose(np.asarray(out1.numpy()),
                                   np.asarray(ref.numpy()), atol=1e-6)
        assert st.sot_stats == {"segments": 2, "compiled": 2}

        # same shapes again: segments replay from the guarded cache
        out2 = st(x)
        np.testing.assert_allclose(np.asarray(out2.numpy()),
                                   np.asarray(ref.numpy()), atol=1e-6)
        assert st.sot_stats == {"segments": 2, "compiled": 0}

    def test_new_shape_recompiles_via_guards(self):
        fn, w = self._make()
        st = paddle.jit.to_static(fn, full_graph=False)
        x1 = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        x2 = paddle.to_tensor(np.random.randn(5, 8).astype(np.float32))
        with pytest.warns(UserWarning):
            st(x1)
        with pytest.warns(UserWarning):
            st(x2)                             # new signature, new break
        assert st.sot_stats == {"segments": 2, "compiled": 2}
        st(x2)
        assert st.sot_stats == {"segments": 2, "compiled": 0}

    def test_full_graph_signatures_unaffected(self):
        calls = []

        def fn(x):
            calls.append(1)
            return paddle.ops.tanh(x) * 2.0

        st = paddle.jit.to_static(fn, full_graph=False)
        x = paddle.to_tensor(np.random.randn(3, 3).astype(np.float32))
        a = st(x)
        b = st(x)
        np.testing.assert_allclose(np.asarray(a.numpy()),
                                   np.asarray(b.numpy()))
        assert st.sot_stats is None            # never broke
        assert len(calls) == 1                 # compiled, not re-traced

    def test_sot_output_usable_in_later_eager_ops(self):
        fn, w = self._make()
        st = paddle.jit.to_static(fn, full_graph=False)
        x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        with pytest.warns(UserWarning):
            out = st(x)
        # escaped payload feeds a plain eager op
        more = paddle.ops.mean(out * 2.0)
        assert np.isfinite(float(more.numpy()))

    def test_training_through_break(self):
        # gradients must survive a mid-frame break: the tape records
        # lazy-vjp nodes whose payloads materialize before backward
        rng = np.random.RandomState(3)
        w = paddle.to_tensor(rng.randn(4, 4).astype(np.float32) * 0.5)
        w.stop_gradient = False
        x = paddle.to_tensor(rng.randn(2, 4).astype(np.float32))

        def loss_fn():
            y = paddle.ops.tanh(paddle.ops.matmul(x, w))
            _ = y.numpy()                      # break
            return paddle.ops.mean(paddle.ops.exp(y))

        with sot.capture():
            loss = loss_fn()
        loss.backward()
        got = np.asarray(w.grad._data)

        w.clear_grad()
        loss2 = loss_fn()
        loss2.backward()
        np.testing.assert_allclose(got, np.asarray(w.grad._data),
                                   atol=1e-6)
