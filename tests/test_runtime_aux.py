"""Aux runtime subsystem tests: profiler, watchdog, launcher, rank logger,
native collation/allocator, device stats.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestProfiler:
    def test_profile_counts_ops(self):
        from paddle_tpu import profiler
        net = nn.Linear(8, 8)
        x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        with profiler.Profiler(timer_only=True) as p:
            for _ in range(3):
                net(x)
                p.step()
        stats = p.summary()
        assert stats.get("linear", stats.get("matmul", 0)) >= 3

    def test_scheduler_state_machine(self):
        from paddle_tpu.profiler import ProfilerState, make_scheduler
        sch = make_scheduler(closed=1, ready=1, record=2, repeat=1)
        states = [sch(i) for i in range(5)]
        assert states[0] == ProfilerState.CLOSED
        assert states[1] == ProfilerState.READY
        assert states[2] == ProfilerState.RECORD
        assert states[3] == ProfilerState.RECORD_AND_RETURN
        assert states[4] == ProfilerState.CLOSED

    def test_record_event_and_chrome_export(self, tmp_path):
        from paddle_tpu import profiler
        with profiler.Profiler(
                timer_only=True,
                on_trace_ready=profiler.export_chrome_tracing(
                    str(tmp_path))) as p:
            with profiler.RecordEvent("forward"):
                time.sleep(0.01)
        assert p.trace_path and os.path.exists(p.trace_path)


class TestWatchdog:
    def test_detects_stall_and_recovers(self):
        from paddle_tpu.distributed.watchdog import Watchdog
        hangs = []
        wd = Watchdog(timeout=0.2, poll_interval=0.05,
                      on_hang=lambda w: hangs.append(1)).start()
        try:
            wd.begin_work()
            time.sleep(0.6)     # no heartbeat -> stall fires
            wd.end_work()
        finally:
            wd.stop()
        assert wd.hang_count >= 1 and hangs

    def test_no_false_positive_with_progress(self):
        from paddle_tpu.distributed.watchdog import Watchdog
        wd = Watchdog(timeout=0.3, poll_interval=0.05).start()
        try:
            wd.begin_work()
            for _ in range(6):
                time.sleep(0.1)
                wd.heartbeat()
            wd.end_work()
        finally:
            wd.stop()
        assert wd.hang_count == 0

    def test_op_dispatch_feeds_heartbeat(self):
        from paddle_tpu.distributed.watchdog import (start_watchdog,
                                                     stop_watchdog)
        wd = start_watchdog(timeout=10.0)
        before = wd._last_progress
        time.sleep(0.01)
        paddle.to_tensor(np.ones(3, np.float32)) + 1
        assert wd._last_progress > before
        stop_watchdog()


class TestLauncher:
    def test_single_proc_round_trip(self, tmp_path):
        script = tmp_path / "train.py"
        script.write_text(
            "import os\n"
            "assert os.environ['PADDLE_TRAINER_ID'] == '0'\n"
            "assert os.environ['PADDLE_TRAINERS_NUM'] == '1'\n"
            "print('worker ok')\n")
        from paddle_tpu.distributed.launch import launch
        code = launch(["--nproc_per_node", "1", str(script)])
        assert code == 0

    def test_elastic_restart(self, tmp_path):
        marker = tmp_path / "marker"
        script = tmp_path / "flaky.py"
        script.write_text(
            f"import os, sys\n"
            f"m = {str(marker)!r}\n"
            f"if not os.path.exists(m):\n"
            f"    open(m, 'w').close()\n"
            f"    sys.exit(3)\n"
            f"print('recovered')\n")
        from paddle_tpu.distributed.launch import launch
        code = launch(["--max_restarts", "1", str(script)])
        assert code == 0

    def test_failure_propagates(self, tmp_path):
        script = tmp_path / "bad.py"
        script.write_text("import sys; sys.exit(7)\n")
        from paddle_tpu.distributed.launch import launch
        code = launch([str(script)])
        assert code == 7


class TestNative:
    def test_native_builds(self):
        from paddle_tpu import native
        assert native.AVAILABLE

    def test_collate_matches_numpy(self):
        from paddle_tpu import native
        arrays = [np.random.randn(64, 64).astype(np.float32)
                  for _ in range(32)]
        np.testing.assert_array_equal(native.collate_stack(arrays),
                                      np.stack(arrays))

    def test_collate_ragged_falls_back(self):
        from paddle_tpu import native
        arrays = [np.zeros((2, 2), np.float32), np.zeros((2, 2), np.float64)]
        out = native.collate_stack(arrays)    # mixed dtype -> numpy path
        assert out.shape == (2, 2, 2)

    def test_host_allocator_stats(self):
        from paddle_tpu import native
        before = native.host_memory_stats()
        buf = native.HostBuffer(1 << 20)
        mid = native.host_memory_stats()
        assert mid["allocated"] >= before["allocated"] + (1 << 20)
        arr = buf.as_array((256, 1024), np.float32)
        arr[:] = 1.0
        assert float(arr.sum()) == 256 * 1024
        # freeing while a view is alive must refuse (no use-after-free)
        with pytest.raises(RuntimeError, match="live array view"):
            buf.free()
        del arr
        buf.free()
        after = native.host_memory_stats()
        assert after["allocated"] <= mid["allocated"] - (1 << 20)
        assert after["peak"] >= mid["allocated"]

    def test_dataloader_uses_native_collate(self):
        from paddle_tpu.io import DataLoader, Dataset

        class Ds(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return np.full((512, 512), i, np.float32)

        loader = DataLoader(Ds(), batch_size=8)
        batch = next(iter(loader))
        assert batch.shape == (8, 512, 512) or \
            list(batch.shape) == [8, 512, 512]


class TestDeviceStats:
    def test_memory_stats_api(self):
        import paddle_tpu.device as device
        n = device.memory_allocated()
        assert n >= 0
        assert device.max_memory_allocated() >= n
        assert device.cuda.device_count() >= 1

    def test_rank_logger(self, capsys):
        from paddle_tpu.distributed.utils import get_logger
        log = get_logger()
        log.info("hello from test")
        err = capsys.readouterr().err
        assert "rank 0" in err and "hello from test" in err


class TestKVRendezvous:
    """HTTP KV master + peer sync + heartbeat (reference
    launch/utils/kv_server.py, controllers/master.py HTTPMaster,
    fleet/elastic/manager.py lease)."""

    def test_kv_put_get_prefix_delete(self):
        from paddle_tpu.distributed.launch.kv_server import (KVClient,
                                                             KVServer)
        srv = KVServer(0).start()
        try:
            c = KVClient(f"127.0.0.1:{srv.port}")
            assert c.put("/job/0", "alpha")
            assert c.put("/job/1", "beta")
            assert c.get("/job/0") == "alpha"
            peers = c.get_prefix("/job")
            assert peers == {"/job/0": "alpha", "/job/1": "beta"}
            assert c.delete("/job")
            assert c.get_prefix("/job") == {}
            assert c.get("/job/0") is None
        finally:
            srv.stop()

    def test_sync_peers_barrier(self):
        import threading
        from paddle_tpu.distributed.launch.kv_server import (KVServer,
                                                             sync_peers)
        srv = KVServer(0).start()
        addr = f"127.0.0.1:{srv.port}"
        results = {}

        def node(rank):
            results[rank] = sync_peers(addr, rank, 3,
                                       payload=f"host{rank}:900{rank}",
                                       job_id="sync_test")

        try:
            threads = [threading.Thread(target=node, args=(r,))
                       for r in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            for r in range(3):
                assert results[r] == ["host0:9000", "host1:9001",
                                      "host2:9002"]
        finally:
            srv.stop()

    def test_heartbeat_detects_dead_node(self):
        import time
        from paddle_tpu.distributed.launch.kv_server import (Heartbeat,
                                                             KVClient,
                                                             KVServer)
        srv = KVServer(0).start()
        addr = f"127.0.0.1:{srv.port}"
        try:
            hb0 = Heartbeat(addr, 0, job_id="hbtest", interval=0.1,
                            ttl=0.5).start()
            # node 1 heartbeats once then dies
            KVClient(addr).put("/heartbeat/hbtest/1", b"", server_stamp=True)
            time.sleep(0.8)
            assert hb0.dead_nodes() == [1]
            hb0.stop()
        finally:
            srv.stop()

    def test_wait_timeout(self):
        import pytest
        from paddle_tpu.distributed.launch.kv_server import (KVClient,
                                                             KVServer)
        srv = KVServer(0).start()
        try:
            c = KVClient(f"127.0.0.1:{srv.port}")
            with pytest.raises(TimeoutError):
                c.wait("/never", timeout=0.5, interval=0.1)
            c.put("/soon", "x")
            assert c.wait("/soon", timeout=1) == "x"
        finally:
            srv.stop()


    def test_sync_peers_tolerates_late_master(self):
        import threading
        import time
        from paddle_tpu.distributed.launch.kv_server import (KVServer,
                                                             sync_peers)
        holder = {}
        ready = threading.Event()

        def late_start():
            time.sleep(0.8)
            holder["srv"] = KVServer(0).start()  # OS-assigned: no rebind race
            ready.set()
            sync_peers(f"127.0.0.1:{holder['srv'].port}", 0, 2,
                       job_id="late")

        t = threading.Thread(target=late_start)
        t.start()
        try:
            # rank 1 cannot know the port before the server exists in this
            # test, so poll for it — the retry-under-refusal path is
            # exercised by connecting to a not-yet-listening port below
            from paddle_tpu.distributed.launch.kv_server import KVClient
            assert not KVClient("127.0.0.1:1").put("/x", "y")  # refused->False
            ready.wait(timeout=10)
            peers = sync_peers(f"127.0.0.1:{holder['srv'].port}", 1, 2,
                               job_id="late", timeout=15)
            assert len(peers) == 2
        finally:
            t.join(timeout=20)
            if holder.get("srv"):
                holder["srv"].stop()

    def test_launch_rejects_bad_master(self):
        import pytest
        from paddle_tpu.distributed.launch.main import launch
        with pytest.raises(SystemExit):
            launch(["--nnodes", "2", "--master", "no-port-here",
                    "script.py"])


class TestAudioBackend:
    def test_wav_roundtrip_mono_stereo(self, tmp_path):
        import numpy as np
        import paddle_tpu as paddle
        sr = 16000
        t = np.linspace(0, 1, sr, endpoint=False)
        mono = (0.5 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)
        p = str(tmp_path / "tone.wav")
        paddle.audio.save(p, paddle.to_tensor(mono), sr)
        meta = paddle.audio.info(p)
        assert (meta.sample_rate, meta.num_channels,
                meta.bits_per_sample) == (sr, 1, 16)
        back, sr2 = paddle.audio.load(p)
        assert sr2 == sr and list(back.shape) == [1, sr]
        np.testing.assert_allclose(back.numpy()[0], mono, atol=2e-4)
        # stereo channels-first
        st = np.stack([mono, -mono])
        p2 = str(tmp_path / "st.wav")
        paddle.audio.save(p2, paddle.to_tensor(st), sr)
        b2, _ = paddle.audio.load(p2)
        assert list(b2.shape) == [2, sr]
        np.testing.assert_allclose(b2.numpy(), st, atol=2e-4)

    def test_load_offset_and_count(self, tmp_path):
        import numpy as np
        import paddle_tpu as paddle
        sig = np.arange(100, dtype=np.float32) / 200.0
        p = str(tmp_path / "seg.wav")
        paddle.audio.save(p, paddle.to_tensor(sig), 8000)
        seg, _ = paddle.audio.load(p, frame_offset=10, num_frames=20)
        assert list(seg.shape) == [1, 20]
        np.testing.assert_allclose(seg.numpy()[0], sig[10:30], atol=2e-4)

    def test_save_int32_rescales(self, tmp_path):
        import numpy as np
        import paddle_tpu as paddle
        sig = (np.sin(np.linspace(0, 6.28, 100)) * 2**30).astype(np.int32)
        p = str(tmp_path / "i32.wav")
        paddle.audio.save(p, sig, 8000)
        back, _ = paddle.audio.load(p)
        ref = sig.astype(np.float64) / 2**31
        np.testing.assert_allclose(back.numpy()[0], ref, atol=2e-4)
