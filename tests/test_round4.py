"""Round-4 features: fused head+loss, model remat flags, gradient merge,
SOT value guards, flag observers, KV atomic increment.

Reference contracts: GradientMergePass (distributed/passes/
auto_parallel_gradient_merge.py:530), SOT compile_cache guards
(jit/sot/symbolic/compile_cache.py), OpTest tolerances
(test/legacy_test/op_test.py:1084).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


# --------------------------------------------------------------- fused loss
class TestFusedLinearCrossEntropy:
    def _data(self, n=50, h=16, v=37):
        rng = np.random.RandomState(0)
        x = rng.randn(n, h).astype(np.float32)
        w = rng.randn(h, v).astype(np.float32)
        y = rng.randint(0, v, (n,))
        y[3] = -100
        return x, w, y

    def test_forward_matches_unfused(self):
        x, w, y = self._data()
        ref = F.cross_entropy(
            paddle.to_tensor(x) @ paddle.to_tensor(w),
            paddle.to_tensor(y), ignore_index=-100, reduction="none")
        fused = F.fused_linear_cross_entropy(
            paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(y),
            chunk_rows=16, reduction="none")
        # tolerance covers the backend's reduced-precision matmul default
        np.testing.assert_allclose(ref.numpy(), fused.numpy(),
                                   rtol=2e-2, atol=5e-2)

    def test_transpose_y_and_reductions(self):
        x, w, y = self._data()
        base = F.fused_linear_cross_entropy(
            paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(y),
            chunk_rows=16, reduction="none").numpy()
        ft = F.fused_linear_cross_entropy(
            paddle.to_tensor(x), paddle.to_tensor(w.T.copy()),
            paddle.to_tensor(y), transpose_y=True, chunk_rows=16,
            reduction="none").numpy()
        np.testing.assert_allclose(base, ft, rtol=2e-2, atol=5e-2)
        s = F.fused_linear_cross_entropy(
            paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(y),
            chunk_rows=16, reduction="sum")
        m = F.fused_linear_cross_entropy(
            paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(y),
            chunk_rows=16, reduction="mean")
        valid = (y != -100).sum()
        np.testing.assert_allclose(float(s) / valid, float(m), rtol=1e-5)

    def test_grad_matches_unfused(self):
        x, w, y = self._data()
        xt, wt = paddle.to_tensor(x), paddle.to_tensor(w)
        xt.stop_gradient = False
        wt.stop_gradient = False
        F.fused_linear_cross_entropy(
            xt, wt, paddle.to_tensor(y), chunk_rows=16).backward()
        xt2, wt2 = paddle.to_tensor(x), paddle.to_tensor(w)
        xt2.stop_gradient = False
        wt2.stop_gradient = False
        F.cross_entropy(paddle.ops.matmul(xt2, wt2), paddle.to_tensor(y),
                        ignore_index=-100).backward()
        np.testing.assert_allclose(xt.grad.numpy(), xt2.grad.numpy(),
                                   rtol=2e-2, atol=5e-2)
        np.testing.assert_allclose(wt.grad.numpy(), wt2.grad.numpy(),
                                   rtol=2e-2, atol=5e-2)

    def test_bias(self):
        x, w, y = self._data()
        b = np.random.RandomState(1).randn(w.shape[1]).astype(np.float32)
        ref = F.cross_entropy(
            paddle.to_tensor(x @ w + b), paddle.to_tensor(y),
            ignore_index=-100)
        fused = F.fused_linear_cross_entropy(
            paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(y),
            bias=paddle.to_tensor(b), chunk_rows=16)
        np.testing.assert_allclose(float(ref), float(fused), rtol=2e-2)


# ----------------------------------------------- model flags (remat+fused)
def _train_loss_and_gradsum(model, ids_np, is_bert=False):
    params = [p for p in model.parameters() if not p.stop_gradient]

    def loss_fn(pa):
        orig = [p._data for p in params]
        for p, a in zip(params, pa):
            p._data = a
        try:
            t = paddle.Tensor(jnp.asarray(ids_np))
            if is_bert:
                out = model(t, masked_lm_labels=t)
            else:
                out = model(t, labels=t)
            return out[-1]._data.astype(jnp.float32)
        finally:
            for p, o in zip(params, orig):
                p._data = o

    pa = [p._data for p in params]
    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(pa)
    return float(loss), float(sum(jnp.sum(jnp.abs(g)) for g in grads))


class TestModelRematFusedFlags:
    """recompute+fused_loss must be numerically invisible under jit."""

    def test_gpt(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        outs = []
        for rec, fl in [(False, False), (True, True)]:
            cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                            num_heads=2, max_seq_len=16,
                            use_flash_attention=False,
                            recompute=rec, fused_loss=fl)
            paddle.seed(11)
            outs.append(_train_loss_and_gradsum(GPTForCausalLM(cfg), ids))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-4)

    # slow-marked (~10s combined, 870s tier-1 budget): the
    # recompute+fused_loss invisibility contract stays in tier-1 via
    # test_gpt above; the llama/bert variants run in the full matrix
    @pytest.mark.slow
    def test_llama(self):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        outs = []
        for rec, fl in [(False, False), (True, True)]:
            cfg = LlamaConfig(vocab_size=128, hidden_size=32,
                              intermediate_size=64, num_layers=2,
                              num_heads=2, max_seq_len=16,
                              use_flash_attention=False,
                              recompute=rec, fused_loss=fl)
            paddle.seed(11)
            outs.append(_train_loss_and_gradsum(LlamaForCausalLM(cfg), ids))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-4)

    @pytest.mark.slow
    def test_bert(self):
        from paddle_tpu.models.bert import BertConfig, BertForPretraining
        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        outs = []
        for rec, fl in [(False, False), (True, True)]:
            cfg = BertConfig(vocab_size=128, hidden_size=32,
                             num_hidden_layers=2, num_attention_heads=2,
                             intermediate_size=64,
                             max_position_embeddings=16,
                             hidden_dropout_prob=0.0,
                             attention_probs_dropout_prob=0.0,
                             recompute=rec, fused_loss=fl)
            paddle.seed(11)
            outs.append(_train_loss_and_gradsum(
                BertForPretraining(cfg), ids, is_bert=True))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-4)

    def test_eager_remat_matches_plain(self):
        """Eager (tape) path: recompute=True grads == recompute=False."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 64, (2, 8)))
        grads = []
        for rec in (False, True):
            cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                            num_heads=2, max_seq_len=8,
                            use_flash_attention=False, recompute=rec)
            paddle.seed(5)
            m = GPTForCausalLM(cfg)
            _, loss = m(ids, labels=ids)
            loss.backward()
            grads.append([p.grad.numpy().copy() for p in m.parameters()
                          if p.grad is not None])
        assert len(grads[0]) == len(grads[1])
        for a, b in zip(grads[0], grads[1]):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------ grad merge
class TestGradientMerge:
    def test_k_steps_equals_big_batch(self):
        """k micro-steps with gradient merge == 1 step on the k-fold batch
        (avg=True divides by k, matching a mean-loss big batch when the
        micro losses are means over equal-sized batches)."""
        from paddle_tpu.distributed.fleet import DistributedStrategy
        from paddle_tpu.distributed.fleet.meta_optimizers import \
            HybridParallelOptimizer
        import paddle_tpu.nn as nn
        from paddle_tpu.optimizer import SGD

        rng = np.random.RandomState(3)
        xs = [rng.randn(4, 8).astype(np.float32) for _ in range(2)]
        ys = [rng.randn(4, 2).astype(np.float32) for _ in range(2)]

        def make():
            paddle.seed(9)
            m = nn.Linear(8, 2)
            return m

        # merged: 2 micro steps, k=2, avg
        m1 = make()
        strat = DistributedStrategy()
        strat.gradient_merge = True
        strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
        opt1 = HybridParallelOptimizer(
            SGD(learning_rate=0.1, parameters=m1.parameters()),
            strategy=strat)
        for x, y in zip(xs, ys):
            loss = ((m1(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2
                    ).mean()
            loss.backward()
            opt1.step()
            opt1.clear_grad()

        # single big batch (mean over both micro batches)
        m2 = make()
        opt2 = SGD(learning_rate=0.1, parameters=m2.parameters())
        x = np.concatenate(xs)
        y = np.concatenate(ys)
        loss = ((m2(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        opt2.step()
        opt2.clear_grad()

        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(),
                                       rtol=1e-5, atol=1e-6)

    def test_non_boundary_step_does_not_update(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        from paddle_tpu.distributed.fleet.meta_optimizers import \
            HybridParallelOptimizer
        import paddle_tpu.nn as nn
        from paddle_tpu.optimizer import SGD

        paddle.seed(9)
        m = nn.Linear(4, 2)
        before = [p.numpy().copy() for p in m.parameters()]
        strat = DistributedStrategy()
        strat.gradient_merge = True
        strat.gradient_merge_configs = {"k_steps": 3}
        opt = HybridParallelOptimizer(
            SGD(learning_rate=0.1, parameters=m.parameters()),
            strategy=strat)
        loss = (m(paddle.to_tensor(
            np.ones((2, 4), np.float32))) ** 2).mean()
        loss.backward()
        opt.step()                     # 1 of 3: banked, no update
        opt.clear_grad()
        for p, b in zip(m.parameters(), before):
            np.testing.assert_array_equal(p.numpy(), b)

    def test_strategy_knobs_have_consumers(self):
        """Every public DistributedStrategy field is consumed somewhere
        (VERDICT weak #5: accepted-and-ignored knobs are worse than
        raising)."""
        from paddle_tpu.distributed.fleet import DistributedStrategy
        from paddle_tpu.distributed.parallel import DataParallel
        import inspect
        sig = inspect.signature(DataParallel.__init__)
        assert "find_unused_parameters" in sig.parameters
        assert "comm_buffer_size" in sig.parameters
        s = DistributedStrategy()
        assert hasattr(s, "gradient_merge")


# ------------------------------------------------------------- SOT guards
class TestSOTValueGuards:
    def test_closure_constant_change_recompiles(self):
        """Changing a python constant captured in the lowering closure
        (NOT passed as an attr) must miss the segment cache."""
        from paddle_tpu.jit import sot
        from paddle_tpu.core import dispatch as D

        def run(scale):
            cache = {}
            with sot.capture(cache) as cap:
                x = paddle.to_tensor(np.ones((4,), np.float32))

                def f(a):
                    return a * scale          # scale captured by closure

                out = D.call("scale_mul", f, [x])
                val = out.numpy()             # flush
            return val, cache

        v1, c1 = run(2.0)
        v2, c2 = run(3.0)
        assert v1[0] == 2.0 and v2[0] == 3.0
        # shared cache: different constants -> different keys
        cache = {}
        for s in (2.0, 3.0):
            with sot.capture(cache):
                x = paddle.to_tensor(np.ones((4,), np.float32))

                def f(a, _s=s):
                    return a * _s

                out = D.call("scale_mul", f, [x])
                assert out.numpy()[0] == s
        assert len(cache) == 2

    def test_segment_cache_bounded(self):
        from paddle_tpu.jit import sot
        assert sot.SEGMENT_CACHE_MAX >= 16
        cache = {}
        for i in range(sot.SEGMENT_CACHE_MAX + 10):
            with sot.capture(cache):
                x = paddle.to_tensor(np.ones((4,), np.float32))

                def f(a, _i=float(i)):
                    return a + _i

                from paddle_tpu.core import dispatch as D
                D.call("shift", f, [x]).numpy()
        assert len(cache) <= sot.SEGMENT_CACHE_MAX


# ------------------------------------------------------- flags observers
def test_flag_observers_all_notified():
    from paddle_tpu.core import flags
    seen = []
    flags.on_change("benchmark", lambda v: seen.append(("a", v)))
    flags.on_change("benchmark", lambda v: seen.append(("b", v)))
    try:
        flags.set_flags({"benchmark": True})
        assert ("a", True) in seen and ("b", True) in seen
        # dispatch's hot mirror (the pre-existing observer) stayed synced
        from paddle_tpu.core.dispatch import _hot_flags
        assert _hot_flags["benchmark"] is True
    finally:
        flags.set_flags({"benchmark": False})


# ------------------------------------------------------------ KV incr CAS
def test_kv_atomic_incr():
    import threading
    from paddle_tpu.distributed.launch.kv_server import KVClient, KVServer
    srv = KVServer(0, host="127.0.0.1").start()
    try:
        cli = KVClient(f"127.0.0.1:{srv.port}")
        got = []

        def bump():
            for _ in range(10):
                got.append(cli.incr("/epoch"))

        ts = [threading.Thread(target=bump) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sorted(got) == list(range(1, 41))   # unique, no lost bump
        assert cli.get("/epoch") == "40"
    finally:
        srv.stop()
