"""Round-12 tests: device-time performance attribution.

Covers the perf layer end to end: analytical cost model closed forms AND
their cross-check against XLA's own cost_analysis on compiled programs,
the attribution-sums-to-step-time property on a real train loop, the
attributed HBM census, compiled-program capture at to_static/SOT compile
time, the per-op metric accumulation in dispatch, the perf_report
renderer, the perf_gate freeze/gate workflow (CI teeth), and the
process-unique metrics-dump suffix.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.observability import REGISTRY, perf
from paddle_tpu.observability.perf import costmodel, device, memory

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import perf_gate, perf_report  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    paddle.set_flags({"FLAGS_enable_metrics": False,
                      "FLAGS_perf_op_cost": False,
                      "FLAGS_perf_capture": False,
                      "FLAGS_benchmark": False})


# =========================================================================
# Cost model — closed forms
# =========================================================================
class TestCostModelClosedForm:
    def test_matmul(self):
        c = costmodel.cost_of("matmul", [(64, 128), (128, 32)],
                              [np.float32, np.float32])
        assert c.flops == 2 * 64 * 128 * 32
        assert c.bytes_read == 4 * (64 * 128 + 128 * 32)

    def test_matmul_transpose_and_batch(self):
        c = costmodel.cost_of("matmul", [(3, 5, 64), (3, 7, 64)],
                              [np.float32] * 2, {"transpose_y": True},
                              [(3, 5, 7)])
        assert c.flops == 2 * 3 * 5 * 64 * 7

    def test_linear_bias(self):
        c = costmodel.cost_of("linear", [(8, 16), (16, 32), (32,)],
                              [np.float32] * 3, {}, [(8, 32)])
        assert c.flops == 2 * 8 * 16 * 32 + 8 * 32

    def test_conv2d(self):
        c = costmodel.cost_of("conv2d", [(2, 3, 16, 16), (8, 3, 3, 3)],
                              [np.float32] * 2, {"stride": 1},
                              [(2, 8, 16, 16)])
        assert c.flops == 2 * 2 * 8 * 16 * 16 * 3 * 3 * 3

    def test_attention(self):
        b, s, h, d = 2, 32, 4, 16
        c = costmodel.cost_of("flash_attention", [(b, s, h, d)] * 3,
                              [np.float32] * 3, {}, [(b, s, h, d)])
        assert c.flops == 4 * b * h * s * s * d + 5 * b * h * s * s
        # flash traffic model: qkv in + out, no S^2 round-trip
        assert c.bytes == 4 * 4 * b * s * h * d

    def test_layer_norm(self):
        c = costmodel.cost_of("layer_norm", [(4, 128)], [np.float32])
        assert c.flops == 8 * 4 * 128

    def test_bf16_bytes(self):
        c = costmodel.cost_of("matmul", [(8, 8), (8, 8)],
                              [jnp.bfloat16, jnp.bfloat16])
        assert c.bytes_read == 2 * (64 + 64)

    def test_collectives(self):
        assert costmodel.collective_cost(
            "all_reduce", 1000, 4).bytes_read == 1500
        assert costmodel.collective_cost(
            "all_gather", 1000, 4).bytes_read == 750
        assert costmodel.collective_cost(
            "broadcast", 1000, 4).bytes_read == 1000
        assert costmodel.collective_cost(
            "all_reduce", 1000, 1).bytes_read == 0

    def test_unknown_op_is_none(self):
        assert costmodel.cost_of("definitely_not_an_op", [(4,)]) is None

    def test_attach_is_idempotent_and_broad(self):
        n1 = perf.attach_cost_models()
        n2 = perf.attach_cost_models()
        assert n1 == n2 >= 300
        from paddle_tpu.ops.registry import OPS
        assert OPS["matmul"].cost_fn is costmodel.matmul_cost

    def test_registry_cost_fn_override_wins(self):
        """register(..., cost_fn=) beats the generic name table — the
        documented extension contract."""
        from paddle_tpu.ops import registry

        def my_fn(shapes, dtypes, attrs, outs):
            return costmodel.OpCost(flops=42.0)

        prev = registry.OPS["matmul"].cost_fn
        registry.OPS["matmul"].cost_fn = my_fn
        try:
            assert costmodel.cost_of("matmul", [(4, 4), (4, 4)]).flops == 42.0
        finally:
            registry.OPS["matmul"].cost_fn = prev
        assert costmodel.cost_of("matmul",
                                 [(4, 4), (4, 4)]).flops == 2 * 4 * 4 * 4

    def test_roofline_bound(self):
        c = costmodel.OpCost(flops=1000.0, bytes_read=10.0,
                             bytes_written=10.0)
        r = costmodel.roofline_bound(c, peak_flops=1e12, peak_bw=1e11)
        assert r["bound"] == "compute"           # AI 50 > ridge 10
        assert r["attainable_flops"] == 1e12


# =========================================================================
# Cost model — XLA cross-check (tolerance-based, per ISSUE fixture list)
# =========================================================================
class TestCostModelVsXLA:
    def _xla(self, f, *args):
        rec = device.analyze(f, *args)
        assert rec is not None and rec["flops"] > 0
        return rec

    def test_matmul_flops_exact(self):
        a = jnp.ones((64, 128), jnp.float32)
        b = jnp.ones((128, 32), jnp.float32)
        rec = self._xla(lambda x, y: x @ y, a, b)
        c = costmodel.cost_of("matmul", [(64, 128), (128, 32)],
                              [np.float32] * 2)
        assert costmodel.relative_error(c.flops, rec["flops"]) < 0.01
        # bytes: XLA counts actual accesses; the model is the minimal
        # floor — same order of magnitude
        assert 0.25 < c.bytes / rec["bytes_accessed"] < 4.0

    def test_conv2d_flops(self):
        x = jnp.ones((2, 3, 16, 16), jnp.float32)
        w = jnp.ones((8, 3, 3, 3), jnp.float32)

        def conv(x, w):
            return jax.lax.conv_general_dilated(x, w, (1, 1), "SAME")

        rec = self._xla(conv, x, w)
        c = costmodel.cost_of("conv2d", [(2, 3, 16, 16), (8, 3, 3, 3)],
                              [np.float32] * 2, {}, [(2, 8, 16, 16)])
        # SAME padding: XLA skips multiplies at the borders the
        # analytical formula counts
        assert costmodel.relative_error(c.flops, rec["flops"]) < 0.15

    def test_attention_flops(self):
        b, s, h, d = 2, 32, 4, 16
        q = jnp.ones((b, s, h, d), jnp.float32)

        def sdpa(q, k, v):
            logits = jnp.einsum("bshd,bthd->bhst", q, k) / (d ** 0.5)
            p = jax.nn.softmax(logits, axis=-1)
            return jnp.einsum("bhst,bthd->bshd", p, v)

        rec = self._xla(sdpa, q, q, q)
        c = costmodel.cost_of("flash_attention", [(b, s, h, d)] * 3,
                              [np.float32] * 3, {}, [(b, s, h, d)])
        assert costmodel.relative_error(c.flops, rec["flops"]) < 0.10

    def test_layer_norm_flops(self):
        x = jnp.ones((4, 128), jnp.float32)
        g = jnp.ones((128,), jnp.float32)
        b = jnp.zeros((128,), jnp.float32)

        def ln(x, g, b):
            m = x.mean(-1, keepdims=True)
            v = ((x - m) ** 2).mean(-1, keepdims=True)
            return (x - m) / jnp.sqrt(v + 1e-5) * g + b

        rec = self._xla(ln, x, g, b)
        c = costmodel.cost_of("layer_norm", [(4, 128)], [np.float32])
        assert costmodel.relative_error(c.flops, rec["flops"]) < 0.10

    def test_xla_cost_sums_partitions(self):
        fake = type("C", (), {"cost_analysis": lambda self: [
            {"flops": 10.0, "bytes accessed": 5.0},
            {"flops": 7.0, "bytes accessed": 2.0}]})()
        out = costmodel.xla_cost(fake)
        assert out == {"flops": 17.0, "bytes_accessed": 7.0,
                       "transcendentals": 0.0}


# =========================================================================
# Device profiler — attribution
# =========================================================================
class TestAttribution:
    def test_interval_resolution_priorities(self):
        # hand-built timeline: one 1.0s step; 0.4s device, 0.2s
        # collective INSIDE the device wait, 0.1s host outside both
        spans = [
            ("step", "step", 0.0, 1.0, 0, None),
            ("wait", "device", 0.1, 0.5, 0, None),
            ("ar", "collective", 0.2, 0.4, 0, None),
            ("op", "dispatch", 0.6, 0.7, 0, None),
        ]
        out = device.attribute(spans)
        tot = out["total"]
        assert tot["n_steps"] == 1
        assert abs(tot["collective_s"] - 0.2) < 1e-9
        assert abs(tot["compute_s"] - 0.2) < 1e-9     # device minus coll
        assert abs(tot["host_s"] - 0.1) < 1e-9
        assert abs(tot["idle_s"] - 0.5) < 1e-9
        s = (tot["compute_s"] + tot["collective_s"] + tot["host_s"]
             + tot["idle_s"])
        assert abs(s - tot["step_s"]) < 1e-9          # exact sum

    def test_sums_to_step_time_on_train_loop(self):
        """ISSUE acceptance: attribution of a real small train loop sums
        to measured step time within 10% (exact by construction here),
        with nonzero compute from the jitted step's device wait."""
        paddle.seed(0)
        w = jnp.asarray(np.random.randn(64, 64).astype(np.float32))
        x = jnp.asarray(np.random.randn(128, 64).astype(np.float32))
        y = jnp.asarray(np.random.randn(128, 64).astype(np.float32))

        @jax.jit
        def train_step(w):
            def loss(w):
                return jnp.mean((jnp.tanh(x @ w) - y) ** 2)
            g = jax.grad(loss)(w)
            return w - 0.1 * g

        state = {"w": w}

        def step():
            state["w"] = train_step(state["w"])
            return state["w"]

        out = perf.step_attribution(step, iters=3, warmup=1)
        tot = out["total"]
        assert tot["n_steps"] == 3
        parts = (tot["compute_s"] + tot["collective_s"] + tot["host_s"]
                 + tot["idle_s"])
        assert abs(parts - tot["step_s"]) <= 0.1 * tot["step_s"] + 1e-9
        assert tot["compute_s"] > 0          # the block wait is real
        for st in out["steps"]:
            p = (st["compute_s"] + st["collective_s"] + st["host_s"]
                 + st["idle_s"])
            assert abs(p - st["step_s"]) <= 0.1 * st["step_s"] + 1e-9

    def test_measure_blocks(self):
        x = jnp.ones((256, 256), jnp.float32)
        dt = device.measure(lambda a: a @ a, x, warmup=1, iters=2)
        assert dt > 0

    def test_timed_section_emits_spans(self):
        from paddle_tpu.observability import trace
        trace.clear()
        trace.activate()
        try:
            with device.timed_section("s1") as ts:
                ts.track(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        finally:
            trace.deactivate()
        spans = trace.drain()
        cats = {cat for _n, cat, *_ in spans}
        assert "device" in cats and "step" in cats


# =========================================================================
# HBM memory census
# =========================================================================
class TestMemoryCensus:
    def test_param_grad_optimizer_attribution(self):
        from paddle_tpu import nn

        paddle.seed(0)
        lin = nn.Linear(32, 32)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=lin.parameters())
        x = paddle.to_tensor(np.random.randn(4, 32).astype(np.float32))
        loss = paddle.ops.mean(lin(x) ** 2)
        loss.backward()
        opt.step()                        # materializes moments
        c = memory.census()
        assert c["params"] >= 32 * 32 * 4
        assert c["grads"] >= 32 * 32 * 4
        assert c["optimizer_state"] >= 2 * 32 * 32 * 4
        assert c["total"] >= (c["params"] + c["grads"]
                              + c["optimizer_state"])

    def test_dedup_one_tag_per_buffer(self):
        a = jnp.ones((16,), jnp.float32)
        before = memory.census(include_unclaimed=False)
        p1 = memory.register_provider("params", lambda: [a])
        p2 = memory.register_provider("optimizer_state", lambda: [a])
        try:
            c = memory.census(include_unclaimed=False)
            assert c["params"] == before["params"] + a.nbytes
            # second provider must not double-count the same buffer
            assert c["optimizer_state"] == before["optimizer_state"]
        finally:
            memory.unregister_provider(p1)
            memory.unregister_provider(p2)

    def test_provider_dies_with_object(self):
        class Holder:
            def __init__(self):
                self.buf = jnp.ones((1024,), jnp.float32)

        h = Holder()
        memory.register_object("kv_cache", h, lambda o: [o.buf])
        assert memory.census(include_unclaimed=False)["kv_cache"] >= 4096
        del h
        import gc
        gc.collect()
        assert memory.census(include_unclaimed=False)["kv_cache"] == 0.0

    def test_high_water_per_phase(self):
        memory.reset_high_water()
        big = jnp.ones((4096,), jnp.float32)
        pid = memory.register_provider("kv_cache", lambda: [big])
        try:
            memory.update_high_water("phase_a")
        finally:
            memory.unregister_provider(pid)
        memory.update_high_water("phase_b")
        hw = memory.high_water()
        assert hw["phase_a"] >= big.nbytes
        assert hw["phase_a"] > hw["phase_b"] - 1  # a saw the big buffer
        assert memory.high_water("phase_a")["kv_cache"] >= big.nbytes

    def test_hbm_metrics_exported(self):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        REGISTRY.reset()
        memory.refresh_metrics()
        snap = REGISTRY.snapshot()
        assert "paddle_tpu_hbm_live_bytes" in snap
        tags = {s["labels"][0]
                for s in snap["paddle_tpu_hbm_live_bytes"]["series"]}
        assert {"params", "grads", "optimizer_state", "kv_cache",
                "activations"} <= tags


# =========================================================================
# Compiled-program capture (to_static / SOT) + dispatch op-cost metrics
# =========================================================================
class TestCaptureAndDispatchCost:
    def test_to_static_capture(self):
        from paddle_tpu import nn
        from paddle_tpu.jit.api import to_static

        device.clear_compiled()
        paddle.set_flags({"FLAGS_perf_capture": True})
        paddle.seed(0)
        lin = nn.Linear(16, 16)

        @to_static
        def f(x):
            return paddle.ops.tanh(lin(x))

        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        f(x)
        progs = device.compiled_programs("to_static")
        assert progs and progs[0]["flops"] > 0
        assert progs[0]["peak_bytes"] > 0

    def test_sot_capture_on_graph_break(self):
        from paddle_tpu import nn
        from paddle_tpu.jit.api import to_static

        device.clear_compiled()
        paddle.set_flags({"FLAGS_perf_capture": True})
        paddle.seed(0)
        lin = nn.Linear(16, 16)

        @to_static
        def g(x):
            y = lin(x)
            if float(y.sum()) > -1e9:      # host sync → SOT fallback
                y = y + 1.0
            return y

        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        with pytest.warns(UserWarning):
            g(x)
        assert device.compiled_programs("sot")

    def test_capture_off_records_nothing(self):
        device.clear_compiled()
        assert not perf.capture_enabled()
        compiled = jax.jit(lambda a: a + 1).lower(jnp.ones((4,))).compile()
        # record_compiled is explicit; the gate is at call sites — verify
        # the to_static site respects the flag
        from paddle_tpu.jit.api import to_static

        @to_static
        def f(x):
            return x + 1

        f(paddle.to_tensor(np.ones((4,), np.float32)))
        assert device.compiled_programs("to_static") == []
        del compiled

    def test_dispatch_accumulates_modeled_cost(self):
        perf.attach_cost_models()
        paddle.set_flags({"FLAGS_enable_metrics": True,
                          "FLAGS_perf_op_cost": True})
        REGISTRY.reset()
        a = paddle.to_tensor(np.random.randn(32, 64).astype(np.float32))
        b = paddle.to_tensor(np.random.randn(64, 16).astype(np.float32))
        paddle.ops.matmul(a, b)
        paddle.ops.matmul(a, b)
        m = REGISTRY.get("paddle_tpu_perf_op_flops_total")
        assert m.value(op="matmul") == 2 * (2 * 32 * 64 * 16)
        mb = REGISTRY.get("paddle_tpu_perf_op_bytes_total")
        assert mb.value(op="matmul") > 0

    def test_dispatch_cost_off_by_default(self):
        paddle.set_flags({"FLAGS_enable_metrics": True})
        REGISTRY.reset()
        a = paddle.to_tensor(np.ones((8, 8), np.float32))
        paddle.ops.matmul(a, a)
        m = REGISTRY.get("paddle_tpu_perf_op_flops_total")
        assert m is None or m.value(op="matmul") == 0


# =========================================================================
# perf_report
# =========================================================================
class TestPerfReport:
    def _sample_report(self):
        op_time = {"matmul": {"calls": 4, "total_s": 0.01},
                   "layer_norm": {"calls": 4, "total_s": 0.002}}
        op_cost = {"matmul": {"flops": 4e9, "bytes": 1e8},
                   "layer_norm": {"flops": 1e7, "bytes": 2e7}}
        attribution = device.attribute([
            ("step", "step", 0.0, 0.012, 0, None),
            ("wait", "device", 0.0, 0.01, 0, None),
        ])
        return perf_report.build_report(op_time, op_cost,
                                        attribution=attribution,
                                        hbm={"params": 1000, "total": 2000})

    def test_build_report_structure(self):
        r = self._sample_report()
        assert r["ops"][0]["op"] == "matmul"     # sorted by host time
        row = r["ops"][0]
        assert row["achieved_gflops_per_s"] == pytest.approx(400.0)
        assert row["bound"] in ("compute", "bandwidth")
        assert 0 <= row["pct_of_roofline"]
        assert "whole_step" in r and r["whole_step"]["mfu"] >= 0
        assert r["device"]["peak_gflops_per_s"] > 0

    def test_markdown_contains_tables(self):
        md = perf_report.render_markdown(self._sample_report())
        assert "Per-op roofline" in md
        assert "Step-time attribution" in md
        assert "matmul" in md and "% roof" in md
        assert "HBM census" in md

    def test_snapshot_roundtrip(self):
        perf.attach_cost_models()
        paddle.set_flags({"FLAGS_enable_metrics": True,
                          "FLAGS_perf_op_cost": True})
        REGISTRY.reset()
        a = paddle.to_tensor(np.random.randn(16, 16).astype(np.float32))
        paddle.ops.matmul(a, a)
        snap = REGISTRY.snapshot()
        r = perf_report.build_report_from_snapshot(snap)
        ops = {row["op"] for row in r["ops"]}
        assert "matmul" in ops


# =========================================================================
# perf_gate — the CI teeth (tier-1 smoke per ISSUE: schema/structure on
# CPU, no timing assertions)
# =========================================================================
class TestPerfGate:
    LINES = "\n".join([
        json.dumps({"metric": "gpt2", "value": 100.0, "unit": "tokens/s",
                    "vs_baseline": 1.0, "extra": {"mfu": 0.5}}),
        json.dumps({"metric": "disp", "value": 10.0, "unit": "us/op",
                    "vs_baseline": 1.0}),
    ])

    def test_parse_json_lines_and_wrapper(self):
        direct = perf_gate.parse_bench_output(self.LINES)
        assert set(direct) == {"gpt2", "disp"}
        wrapped = perf_gate.parse_bench_output(
            json.dumps({"n": 1, "tail": "noise\n" + self.LINES}))
        assert set(wrapped) == {"gpt2", "disp"}
        aslist = perf_gate.parse_bench_output(
            json.dumps(list(direct.values())))
        assert set(aslist) == {"gpt2", "disp"}

    def test_schema_validation(self):
        ok = perf_gate.parse_bench_output(self.LINES)
        assert perf_gate.validate_schema(ok) == []
        bad = {"x": {"metric": "x", "unit": "error",
                     "vs_baseline": 0.0, "value": 0.0}}
        assert perf_gate.validate_schema(bad)
        assert perf_gate.validate_schema({}) == [
            "no bench rungs found in input"]

    def test_freeze_then_pass(self):
        cand = perf_gate.parse_bench_output(self.LINES)
        base = perf_gate.freeze(cand, min_ratio=0.9)
        assert set(base["rungs"]) == {"gpt2", "disp"}
        r = perf_gate.gate(cand, base)
        assert r["pass"] and all(c["status"] == "pass"
                                 for c in r["checks"])

    def test_gate_fails_on_slowed_rung(self):
        cand = perf_gate.parse_bench_output(self.LINES)
        base = perf_gate.freeze(cand, min_ratio=0.9)
        slow = {k: dict(v) for k, v in cand.items()}
        slow["gpt2"]["value"] = 80.0          # −20% > 10% tolerance
        r = perf_gate.gate(slow, base)
        assert not r["pass"]
        assert [c["metric"] for c in r["checks"]
                if c["status"] == "fail"] == ["gpt2"]

    def test_lower_is_better_direction(self):
        cand = perf_gate.parse_bench_output(self.LINES)
        base = perf_gate.freeze(cand, min_ratio=0.9)
        worse = {k: dict(v) for k, v in cand.items()}
        worse["disp"]["value"] = 20.0         # dispatch 2x SLOWER
        r = perf_gate.gate(worse, base)
        assert not r["pass"]
        better = {k: dict(v) for k, v in cand.items()}
        better["disp"]["value"] = 5.0         # 2x faster passes
        assert perf_gate.gate(better, base)["pass"]

    def test_gate_fails_on_missing_and_errored_rung(self):
        cand = perf_gate.parse_bench_output(self.LINES)
        base = perf_gate.freeze(cand)
        partial = {"gpt2": cand["gpt2"]}
        assert not perf_gate.gate(partial, base)["pass"]
        assert perf_gate.gate(partial, base,
                              allow_missing=True)["pass"]
        errored = {k: dict(v) for k, v in cand.items()}
        errored["disp"]["unit"] = "error"
        assert not perf_gate.gate(errored, base)["pass"]

    def test_freeze_skips_errored_rungs(self):
        cand = perf_gate.parse_bench_output(self.LINES)
        cand["broken"] = {"metric": "broken", "value": 0.0,
                          "unit": "error", "vs_baseline": 0.0}
        base = perf_gate.freeze(cand)
        assert "broken" not in base["rungs"]

    def test_frozen_repo_baseline_is_valid(self):
        """tools/perf_baseline.json (checked in) parses and gates the
        run it was frozen from. Rungs added to the baseline AFTER the
        r05 freeze (fleet_observability round 14, fusion round 15,
        planner_vs_manual round 16, async_overlap + async_batch_sweep
        round 17, serving_router round 18, serving_reqtrace round 19,
        pipeline_bubble round 21) are absent from the archived run —
        they may be missing, but nothing may fail."""
        with open(os.path.join(REPO, "tools", "perf_baseline.json")) as f:
            base = json.load(f)
        assert base["format"] == "paddle_tpu.perf_baseline/1"
        assert base["rungs"]
        assert "fleet_observability_overhead_ratio" in base["rungs"]
        assert "fusion_fused_vs_unfused_step_ratio" in base["rungs"]
        # the fusion bar is the acceptance criterion itself: >= 1.10x
        fusion = base["rungs"]["fusion_fused_vs_unfused_step_ratio"]
        assert fusion["value"] * fusion["min_ratio"] >= 1.10
        # the planner bar likewise: planner placement >= best manual
        pv = base["rungs"]["planner_vs_manual_step_ratio"]
        assert pv["value"] * pv["min_ratio"] >= 1.0
        with open(os.path.join(REPO, "BENCH_r05.json")) as f:
            cand = perf_gate.parse_bench_output(f.read())
        res = perf_gate.gate(cand, base, allow_missing=True)
        assert res["pass"]
        # the async bars: overlap >= the frozen no-regression floor,
        # batch sweep within the ladder tolerance of parity
        ao = base["rungs"]["async_overlap_step_ratio"]
        assert ao["value"] * ao["min_ratio"] >= 0.85
        assert "async_batch_sweep_tokens_ratio" in base["rungs"]
        missing = {c["metric"] for c in res["checks"]
                   if c["status"] == "missing"}
        assert "serving_reqtrace_overhead_ratio" in base["rungs"]
        # the verifier bar encodes the <2% budget: value * min_ratio
        vo = base["rungs"]["verifier_overhead_ratio"]
        assert vo["value"] * vo["min_ratio"] >= 0.98
        # the static-analyzer bar encodes the same <2% compile budget
        sa = base["rungs"]["static_analysis_overhead_ratio"]
        assert sa["value"] * sa["min_ratio"] >= 0.98
        # the pipeline bar is the boolean acceptance gate itself
        pb = base["rungs"]["pipeline_bubble_measured_vs_analytical"]
        assert pb["value"] * pb["min_ratio"] >= 1.0
        # the goodput-ledger bar encodes the <2% step budget (round 23)
        go = base["rungs"]["goodput_overhead_ratio"]
        assert go["value"] * go["min_ratio"] >= 0.95
        # the fault-recovery bar: armed abort plane < 2% of disarmed
        # step time (round 24); MTTR rides ungated in extra
        fr = base["rungs"]["fault_recovery_overhead_ratio"]
        assert fr["value"] * fr["min_ratio"] >= 0.95
        # the giant-embedding bar: sharded DLRM step >= the frozen
        # no-regression floor vs the replicated baseline (round 25;
        # parity + pod capacity proof + dedup win gate the score)
        eb = base["rungs"]["embedding_sharded_vs_replicated_step_ratio"]
        assert eb["value"] * eb["min_ratio"] >= 0.8
        assert missing <= {"fleet_observability_overhead_ratio",
                           "embedding_sharded_vs_replicated_step_ratio",
                           "fault_recovery_overhead_ratio",
                           "fusion_fused_vs_unfused_step_ratio",
                           "planner_vs_manual_step_ratio",
                           "async_overlap_step_ratio",
                           "async_batch_sweep_tokens_ratio",
                           "serving_router_goodput_scaling",
                           "verifier_overhead_ratio",
                           "static_analysis_overhead_ratio",
                           "serving_reqtrace_overhead_ratio",
                           "pipeline_bubble_measured_vs_analytical",
                           "goodput_overhead_ratio"}

    def test_cli_schema_only(self, tmp_path):
        p = tmp_path / "cand.json"
        p.write_text(self.LINES)
        rc = perf_gate.main(["--schema-only", str(p)])
        assert rc == 0

    def test_cli_freeze_and_gate(self, tmp_path, capsys):
        cand = tmp_path / "cand.json"
        cand.write_text(self.LINES)
        basep = tmp_path / "base.json"
        assert perf_gate.main(["--freeze", str(cand),
                               "--baseline", str(basep)]) == 0
        assert perf_gate.main([str(cand),
                               "--baseline", str(basep)]) == 0
        slow = tmp_path / "slow.json"
        rec = json.loads(self.LINES.splitlines()[0])
        rec["value"] = 1.0
        slow.write_text("\n".join([json.dumps(rec),
                                   self.LINES.splitlines()[1]]))
        capsys.readouterr()
        assert perf_gate.main([str(slow),
                               "--baseline", str(basep)]) == 1


# =========================================================================
# Metrics-dump process-unique suffix
# =========================================================================
class TestMetricsDumpSuffix:
    def test_dump_path_rank_env(self, monkeypatch):
        from paddle_tpu import observability as obs

        monkeypatch.delenv("PADDLE_TPU_METRICS_SUFFIX", raising=False)
        monkeypatch.setenv(obs._PRIMARY_PID_ENV, str(os.getpid()))
        monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
        assert obs._dump_path("/tmp/m.json") == "/tmp/m.json.rank3"
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        assert obs._dump_path("/tmp/m.json") == "/tmp/m.json"

    def test_dump_path_rank_worker_gets_both_suffixes(self, monkeypatch):
        """A fork/spawn worker OF rank N must not clobber rank N's own
        file — the pid rides along with the rank suffix."""
        from paddle_tpu import observability as obs

        monkeypatch.delenv("PADDLE_TPU_METRICS_SUFFIX", raising=False)
        monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
        monkeypatch.setenv(obs._PRIMARY_PID_ENV, str(os.getpid() + 1))
        assert (obs._dump_path("/tmp/m.json")
                == f"/tmp/m.json.rank2.pid{os.getpid()}")

    def test_dump_path_explicit_suffix_wins(self, monkeypatch):
        from paddle_tpu import observability as obs

        monkeypatch.setenv("PADDLE_TPU_METRICS_SUFFIX", "worker7")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
        assert obs._dump_path("/tmp/m.json") == "/tmp/m.json.worker7"

    def test_dump_path_child_process_gets_pid(self, monkeypatch):
        from paddle_tpu import observability as obs

        monkeypatch.delenv("PADDLE_TPU_METRICS_SUFFIX", raising=False)
        monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
        monkeypatch.delenv("RANK", raising=False)
        # the primary pid travels via the ENVIRONMENT so fork AND spawn
        # children both see they are not the owner of the bare path
        monkeypatch.setenv(obs._PRIMARY_PID_ENV, str(os.getpid() + 1))
        assert obs._dump_path("/tmp/m.json") == f"/tmp/m.json.pid{os.getpid()}"
        monkeypatch.setenv(obs._PRIMARY_PID_ENV, str(os.getpid()))
        assert obs._dump_path("/tmp/m.json") == "/tmp/m.json"

    @pytest.mark.slow
    def test_rank_worker_writes_suffixed_file(self, tmp_path):
        from paddle_tpu import observability as obs

        dump = tmp_path / "metrics.json"
        env = dict(os.environ)
        # an independently-launched rank (fresh env, no inherited
        # primary pid) owns its .rankN file
        env.pop(obs._PRIMARY_PID_ENV, None)
        env.update(JAX_PLATFORMS="cpu", FLAGS_enable_metrics="1",
                   PADDLE_TPU_METRICS_DUMP=str(dump),
                   PADDLE_TRAINER_ID="2")
        subprocess.run(
            [sys.executable, "-c",
             "import paddle_tpu, numpy as np; "
             "a = paddle_tpu.to_tensor(np.ones((4,4), np.float32)); "
             "paddle_tpu.ops.matmul(a, a)"],
            env=env, cwd=REPO, check=True, timeout=240)
        assert not dump.exists()
        assert (tmp_path / "metrics.json.rank2").exists()


# =========================================================================
# Serving / loadgen per-tick attribution (satellite)
# =========================================================================
class TestServingAttribution:
    def test_loadgen_reports_prefill_decode_split(self):
        from tools.loadgen import _tiny_engine, run_load

        eng = _tiny_engine()
        eng.warmup()
        rep = run_load(eng, offered_rps=100.0, n_requests=6,
                       max_new_tokens=4)
        eng.drain()
        att = rep["device_attribution"]
        assert att is not None
        assert att["ticks"] > 0
        assert att["prefill_compute_s"] > 0
        assert att["decode_compute_s"] > 0
        share = att["prefill_compute_share"] + att["decode_compute_share"]
        assert share == pytest.approx(1.0, abs=1e-3)
        # kv census: the engine's pages are attributed while it lives
        assert memory.census(include_unclaimed=False)["kv_cache"] > 0
