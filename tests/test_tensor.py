"""Tensor surface tests (reference: test/legacy_test/test_eager_tensor.py area)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_dtypes():
    t = paddle.to_tensor([1.0, 2.0, 3.0])
    assert t.dtype == np.float32  # python floats land as fp32
    # TPU-first design decision: integer data lands as int32 (the MXU/VPU
    # native index width; jax x64 mode stays off). The reference defaults
    # to int64 on CUDA.
    ti = paddle.to_tensor(np.arange(4))
    assert ti.dtype == np.int32
    tb = paddle.to_tensor([True, False])
    assert tb.dtype == np.bool_


def test_shape_meta():
    t = paddle.zeros([2, 3, 4])
    assert t.shape == [2, 3, 4]
    assert t.ndim == 3
    assert t.size == 24
    assert t.numel() == 24
    assert len(t) == 2


def test_item_and_numpy():
    t = paddle.to_tensor(3.5)
    assert t.item() == pytest.approx(3.5)
    assert float(t) == pytest.approx(3.5)
    a = paddle.to_tensor([[1, 2], [3, 4]])
    np.testing.assert_array_equal(a.numpy(), [[1, 2], [3, 4]])


def test_arithmetic_operators():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((2 + a).numpy(), [3, 4])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])


def test_comparison_and_indexing():
    a = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    m = a > 2.0
    assert m.dtype == np.bool_
    np.testing.assert_array_equal(a[0].numpy(), [1, 2])
    np.testing.assert_array_equal(a[:, 1].numpy(), [2, 4])
    np.testing.assert_array_equal(a[m].numpy(), [3, 4])


def test_setitem():
    a = paddle.zeros([3, 3])
    a[1] = 5.0
    np.testing.assert_allclose(a.numpy()[1], [5, 5, 5])
    a[0, 0] = 7.0
    assert a.numpy()[0, 0] == 7


def test_set_value_and_inplace():
    a = paddle.ones([2, 2])
    a.set_value(np.full((2, 2), 3.0, np.float32))
    np.testing.assert_allclose(a.numpy(), 3.0)
    a.add_(paddle.ones([2, 2]))
    np.testing.assert_allclose(a.numpy(), 4.0)
    a.zero_()
    np.testing.assert_allclose(a.numpy(), 0.0)


def test_astype_cast():
    a = paddle.to_tensor([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.astype(paddle.bfloat16)
    assert c.dtype == paddle.bfloat16


def test_detach_and_clone():
    a = paddle.to_tensor([1.0], stop_gradient=False)
    b = (a * 2).detach()
    assert b.stop_gradient
    c = a.clone()
    assert not c.stop_gradient  # clone is differentiable


def test_dist_placement_api():
    # Tensor.to_dist is the DistTensor entry (SURVEY §2.3 dygraph auto-parallel)
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = np.array(jax.devices("cpu")[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("dp", "mp"))
    t = paddle.ones([8, 4])
    d = t.to_dist(NamedSharding(mesh, P("dp", None)))
    assert d.is_dist()
    np.testing.assert_allclose(d.numpy(), 1.0)
